"""Scenario-vector fleet: per-cluster config lanes over ONE compiled engine.

The cluster-batch axis C steps hundreds of clusters in lockstep, but until
this module every autoscaler parameter was a per-run scalar folded into the
`AutoscaleStatics` / `StepConstants` leaves at engine build — a parameter
sweep or what-if query paid a fresh engine, a full XLA compile and warm-up
per scenario. Here the scenario-bearing control-law parameters ride as
per-cluster (C,)-shaped TRACED arrays instead (ROADMAP #4: "per-cluster
config vectors instead of Python scalars"), so ONE compiled window /
superspan program serves any scenario mix, and this module supplies:

- `Scenario`: the per-lane config delta a what-if query carries. The
  vectorizable set is exactly the parameters that (a) do not shape
  programs and (b) enter ONLY the autoscaler chains, so a lane with
  overrides stays lane-by-lane equivalent to a scalar run with the same
  scalars (tests/test_fleet.py pins it):
    * HPA scan interval, target-threshold tolerance, per-lane enable
    * CA scan interval, scale-down utilization threshold, node quota
    * as_to_ca_network_delay (the one config delay that feeds ONLY the
      autoscaler chains: d_hpa_up/down, d_ca_up/down, ca_period, ca_snap)
    * the pod-fault PRNG seed (`fault_injection` already keys draws
      per-cluster; the fleet generalizes that to per-lane seeds keyed on
      cluster 0, making a lane's fault stream a pure function of its
      scenario — see StepConstants.fault_seed)
  Slot counts, reserve sizes, the scheduling interval and everything else
  shape- or program-bearing stays a build-time static.
- `scenario_leaves`: the ONE owner of the scalar->per-lane composition
  rules (the delay-chain formulas previously inlined in
  engine.build_autoscale_statics). Both the engine build and the fleet's
  between-query updates go through it, so the two can never drift.
- `ScenarioFleet`: a resident front-end that packs incoming what-if
  queries (config delta + horizon) into cluster lanes, resets the lanes'
  state columns in place (donation-friendly select re-init against the
  pristine build snapshot — no recompile, no re-warm), runs the resident
  composed engine, and reads per-lane results back at the horizon
  boundaries where the host already blocks (the telemetry-ring drain
  points — zero NEW syncs inside the dispatch loop). Compile and warm-up
  amortize across the whole query stream.

Lane reset protocol — two modes:

- WAVE-aligned (the default): the engine's window clock is fleet-global,
  so queries pack into C-lane waves — all lanes reset together at a wave
  boundary, then the wave runs to its queries' horizons (lanes whose
  horizon came early keep simulating idle until the wave drains).
- LANE-ASYNCHRONOUS (`lane_async=True`, DESIGN §13): the engine carries
  per-lane window clocks (StepConstants.lane_clock / lane_horizon —
  traced (C,) data), each lane steps its own virtual span inside the
  shared window programs, and a finished lane is reset + re-seeded IN
  PLACE while neighbors keep stepping. Queries flow through a continuous
  `submit()` / `pump()` / `poll()` engine (`run_async()` drains the
  queue); per-query results are bit-identical to the wave-aligned path
  on the same (scenario, horizon) mix (tests/test_fleet_async.py's A/B
  gate), per-lane completion is pure host arithmetic over the clock
  mirrors (zero new syncs), and the telemetry ring's lane_active column
  feeds the observatory's lane-occupancy gauge + idle-lane verdict.

Query observatory (PR 17, DESIGN §14): every query carries a host-side
lifecycle record (submitted → admitted-to-lane → first-dispatch →
horizon-drained → polled, all perf_counter_ns stamps — no device reads),
the tracer gets a queue-wait and a service span per query linked by a
submit→drain Chrome flow plus a per-lane swimlane event, and the latency
statistics live in bounded log-bucketed streaming histograms
(telemetry/histogram.py: O(buckets) forever, never O(queries)) with the
queue-wait (submit→admit) vs service (admit→drain) split.

Fault domains (PR 19, DESIGN §15): the unit of failure is a QUERY or a
LANE, never the fleet. Terminal failures are TYPED results
(batched/faults.py QueryError taxonomy) streamed through `poll()` under
the same stream-once contract as `FleetResult`s — every submitted qid
streams exactly one terminal outcome, so a client never hangs on a dead
query. A failing dispatch fails only the occupying lane's query
(`LaneFaultError`), the lane is crash-reset from the pristine snapshot
(the PR 13 donated-select machinery reused as recovery — pure data ops,
zero recompiles), and a lane faulting repeatedly inside a window is
QUARANTINED out of the admission rotation with exponential-backoff probe
re-admission (observatory `lane_state` gauge + `lane_quarantine`
verdict). `submit()` gains a bounded queue with reject/block
backpressure and per-query deadlines enforced at host boundaries the
pump already crosses; `close()` is a graceful drain (stop admitting,
finish in-flight, fail queued with `ShutdownError`). With
`KTPU_HOST_CHAOS` unset and no injector armed, every new path is gated
on `self._chaos is None` / empty fault ledgers — the layer is provably
free when quiet (per-query A/B bit-identity + dispatch_stats equality,
pinned in tests/test_fleet_async.py and bench.py --host-chaos).
"""

from __future__ import annotations

import time
from collections import deque
from collections.abc import Mapping
from dataclasses import dataclass, fields
from functools import partial
from typing import Dict, List, Optional, Sequence, Union

import numpy as np

from kubernetriks_tpu.config import (
    KubeClusterAutoscalerConfig,
    KubeHorizontalPodAutoscalerConfig,
    SimulationConfig,
)
from kubernetriks_tpu.batched.faults import (
    DeadlineExceededError,
    HostChaos,
    InjectedFault,
    LaneFaultError,
    QueryError,
    RejectedError,
    ShutdownError,
)
from kubernetriks_tpu.telemetry.histogram import LatencyHistogram
from kubernetriks_tpu.telemetry.tracer import (
    PH_LANE_QUARANTINE,
    PH_QUERY_FAIL,
    PH_QUERY_QUEUE,
    PH_QUERY_SERVICE,
)

# Lifecycle records retired at poll() survive in a bounded trail (the
# most recent polled queries stay inspectable via query_lifecycle()).
_POLLED_LIFECYCLES_KEPT = 128
# Exact-sample cross-check window: the open-loop bench compares the
# histogram-derived p99 against the exact sorted-array p99 over this many
# most-recent latencies while both exist (bounded — the histogram is the
# statistic of record once the stream outgrows it).
_EXACT_LATENCY_WINDOW = 1024

# Scenario keys accepted as per-lane overrides (the vectorizable set).
SCENARIO_KEYS = (
    "hpa_scan_interval",
    "hpa_tolerance",
    "hpa_enabled",
    "ca_scan_interval",
    "ca_threshold",
    "ca_max_node_count",
    "as_to_ca_network_delay",
    "fault_seed",
)


@dataclass(frozen=True)
class Scenario:
    """One what-if query's config delta: every field is an override of the
    base SimulationConfig's value for ONE cluster lane (None = keep the
    base). `ca_max_node_count: 0` disables CA scale-up for the lane (quota
    0 plans nothing and counts no starvation); `hpa_enabled: False` parks
    the lane's pod groups (pg_active_from = +inf), matching a scalar run
    with the HPA off while the initial replicas still run."""

    hpa_scan_interval: Optional[float] = None
    hpa_tolerance: Optional[float] = None
    hpa_enabled: Optional[bool] = None
    ca_scan_interval: Optional[float] = None
    ca_threshold: Optional[float] = None
    ca_max_node_count: Optional[int] = None
    as_to_ca_network_delay: Optional[float] = None
    fault_seed: Optional[int] = None

    def overrides(self) -> Dict[str, object]:
        return {
            f.name: getattr(self, f.name)
            for f in fields(self)
            if getattr(self, f.name) is not None
        }


def _base_values(config: SimulationConfig) -> Dict[str, float]:
    """The base config's value for every scenario key — the scalar the
    per-lane vector is filled with where a lane has no override."""
    hpa = config.horizontal_pod_autoscaler
    ca = config.cluster_autoscaler
    hpa_tol = (
        hpa.kube_horizontal_pod_autoscaler_config
        or KubeHorizontalPodAutoscalerConfig()
    ).target_threshold_tolerance
    ca_thresh = (
        ca.kube_cluster_autoscaler or KubeClusterAutoscalerConfig()
    ).scale_down_utilization_threshold
    return {
        "hpa_scan_interval": float(hpa.scan_interval),
        "hpa_tolerance": float(hpa_tol),
        "hpa_enabled": bool(hpa.enabled),
        "ca_scan_interval": float(ca.scan_interval),
        "ca_threshold": float(ca_thresh),
        "ca_max_node_count": int(ca.max_node_count if ca.enabled else 0),
        "as_to_ca_network_delay": float(config.as_to_ca_network_delay),
        "fault_seed": int(
            config.fault_injection.seed
            if getattr(config, "fault_injection", None) is not None
            and config.fault_injection.seed is not None
            else config.seed
        ),
    }


def scenario_vectors(
    config: SimulationConfig,
    n_lanes: int,
    scenarios: Optional[Sequence[Optional[Scenario]]] = None,
    base_vectors: Optional[Dict[str, np.ndarray]] = None,
) -> Dict[str, np.ndarray]:
    """Materialize the per-lane (C,) scenario vectors: the base config's
    value everywhere (or a copy of `base_vectors` when given — the
    fleet's per-wave composition starts from its BUILD vectors, so a
    lane with no override keeps its build-time config, node-fault seeds
    included), each lane's Scenario overrides applied on top.
    scenarios: at most n_lanes entries (None entries keep the base)."""
    base = _base_values(config)
    out: Dict[str, np.ndarray] = {}
    for key in SCENARIO_KEYS:
        if base_vectors is not None and key in base_vectors:
            out[key] = base_vectors[key].copy()
        elif key == "hpa_enabled":
            out[key] = np.full((n_lanes,), bool(base[key]), bool)
        elif key in ("ca_max_node_count", "fault_seed"):
            out[key] = np.full((n_lanes,), int(base[key]), np.int64)
        else:
            out[key] = np.full((n_lanes,), float(base[key]), np.float64)
    if scenarios is not None:
        if len(scenarios) > n_lanes:
            raise ValueError(
                f"{len(scenarios)} scenarios do not fit {n_lanes} lanes"
            )
        for lane, scen in enumerate(scenarios):
            if scen is None:
                continue
            for key, val in scen.overrides().items():
                if key not in out:
                    raise KeyError(f"unknown scenario key {key!r}")
                out[key][lane] = val
    return out


def normalize_scenario(
    scenario: Optional[Dict[str, object]], n_lanes: int
) -> Optional[Dict[str, np.ndarray]]:
    """Validate a scenario-vector mapping: known keys only, every value
    broadcastable to (n_lanes,). Returns owned (C,) numpy arrays."""
    if scenario is None:
        return None
    out: Dict[str, np.ndarray] = {}
    for key, val in scenario.items():
        if key not in SCENARIO_KEYS:
            raise KeyError(
                f"unknown scenario key {key!r}; supported: {SCENARIO_KEYS}"
            )
        arr = np.asarray(val)
        if arr.ndim == 0:
            arr = np.full((n_lanes,), arr[()])
        if arr.shape != (n_lanes,):
            raise ValueError(
                f"scenario[{key!r}] must be scalar or shape ({n_lanes},), "
                f"got {arr.shape}"
            )
        out[key] = arr.copy()
    return out


def scenario_leaves(
    config: SimulationConfig,
    n_lanes: int,
    scenario: Optional[Dict[str, np.ndarray]] = None,
) -> Dict[str, np.ndarray]:
    """Compose the per-lane (C,)-shaped autoscaler-parameter leaves from
    the base config plus optional per-lane overrides — THE owner of the
    delay-chain composition rules (mirroring the scalar event chains;
    reference cluster_autoscaler.rs:256-262, SURVEY.md §3.2/3.4). Used by
    engine.build_autoscale_statics at build AND by
    engine.update_scenario between fleet queries, so the two sites can
    never drift. All values are float64 seconds (converted to device
    TPairs by the caller) except the bool/int control vectors."""
    scenario = dict(scenario or {})
    base = _base_values(config)
    C = n_lanes

    def vec(key, dtype=np.float64):
        val = scenario.get(key)
        out = np.full((C,), base[key], dtype)
        if val is not None:
            out[:] = np.asarray(val)
        return out

    hpa_scan = vec("hpa_scan_interval")
    hpa_tol = vec("hpa_tolerance")
    hpa_en = vec("hpa_enabled", bool) & bool(
        config.horizontal_pod_autoscaler.enabled
    )
    ca_scan = vec("ca_scan_interval")
    ca_thresh = vec("ca_threshold")
    ca_max = vec("ca_max_node_count", np.int64)
    if not config.cluster_autoscaler.enabled:
        ca_max[:] = 0
    as_to_ca = vec("as_to_ca_network_delay")
    fault_seed = vec("fault_seed", np.int64)

    as_to_ps = float(config.as_to_ps_network_delay)
    ps_to_sched = float(config.ps_to_sched_network_delay)
    sched_to_as = float(config.sched_to_as_network_delay)
    as_to_node = float(config.as_to_node_network_delay)
    d_pod_enqueue = as_to_ps + ps_to_sched

    # The CA's true cadence drifts: the scalar proxy re-arms scan_interval
    # AFTER the info round-trip returns (delay 0 on overrun), so the
    # period is round_trip + scan_interval (or just round_trip on
    # overrun) — composed per lane.
    ca_roundtrip = 2.0 * (as_to_ca + as_to_ps)
    ca_period_s = ca_roundtrip + np.where(
        ca_roundtrip <= ca_scan, ca_scan, 0.0
    )

    return {
        "hpa_interval_s": hpa_scan,
        "hpa_tolerance": hpa_tol,
        "hpa_enabled": hpa_en,
        "ca_threshold": ca_thresh,
        "ca_max_nodes": ca_max,
        "fault_seed": fault_seed,
        "d_hpa_up_s": as_to_ca + d_pod_enqueue,
        "d_hpa_down_s": as_to_ca + as_to_ps,
        "d_ca_up_s": 3.0 * as_to_ca + 5.0 * as_to_ps + ps_to_sched,
        "d_ca_down_s": 3.0 * as_to_ca + 4.0 * as_to_ps + as_to_node,
        "ca_period_s": ca_period_s,
        "ca_snap_s": as_to_ca + as_to_ps,
        "ca_finish_vis_s": np.full((C,), as_to_node + as_to_ps),
        "ca_commit_vis_s": np.full((C,), sched_to_as + as_to_ps),
    }


# --- fleet ------------------------------------------------------------------


@dataclass
class FleetResult:
    """One drained what-if query. Shares the `.ok` / `.kind`
    discrimination protocol with the `QueryError` taxonomy
    (batched/faults.py): a poll loop filters terminal outcomes with
    `outcome.ok` instead of isinstance ladders."""

    ok = True
    kind = "result"

    query: int
    wave: int
    lane: int
    horizon: float
    scenario: Scenario
    counters: Dict[str, float]
    hpa_replicas: Optional[Dict[str, int]]
    ca_nodes: Optional[List[int]]
    # Per-lane divergence counters (the loud-readout bounds of
    # engine.check_autoscaler_bounds, read per lane here): nonzero means
    # the lane's trajectory diverged from the scalar semantics.
    hpa_reserve_clamped: int = 0
    ca_reserve_starved: int = 0


# The per-lane counter rows a query reads back (MetricArrays fields).
_RESULT_COUNTERS = (
    "pods_succeeded",
    "pods_removed",
    "terminated_pods",
    "scheduling_decisions",
    "scaled_up_pods",
    "scaled_down_pods",
    "scaled_up_nodes",
    "scaled_down_nodes",
    "node_crashes",
    "node_recoveries",
    "pod_interruptions",
    "pod_restarts",
    "pods_failed",
)


def jit_cache_sizes() -> Dict[str, int]:
    """Compiled-variant counts of every jit entry the dispatch loop can
    touch — the zero-recompile observable: capture after warm-up, compare
    after the query stream (bench.py --sweep asserts equality; a scenario
    update that silently became a jit-static shows up here loudly)."""
    from kubernetriks_tpu.batched import autoscale, engine, state, step

    entries = {
        "window_step": step.window_step,
        "run_windows": step.run_windows,
        "run_windows_donated": step.run_windows_donated,
        "run_windows_skip": step.run_windows_skip,
        "run_windows_skip_donated": step.run_windows_skip_donated,
        "run_superspan": step.run_superspan,
        "run_superspan_donated": step.run_superspan_donated,
        "fused_chunk_slide": engine._fused_chunk_slide,
        "fused_chunk_slide_donated": engine._fused_chunk_slide_donated,
        "hpa_pass_donated": autoscale.hpa_pass_donated,
        "ca_pass_donated": autoscale.ca_pass_donated,
        "tree_copy": state.tree_copy,
        "reset_lanes": _reset_lanes,
    }
    out = {}
    for name, fn in entries.items():
        try:
            out[name] = int(fn._cache_size())
        except AttributeError:  # pragma: no cover - jax version drift
            out[name] = -1
    return out


def _make_reset_lanes():
    import jax
    import jax.numpy as jnp

    @partial(jax.jit, donate_argnums=(0,))
    def reset(state, pristine, mask):
        """Per-lane state re-init: lanes with mask True take the pristine
        build state's rows, everything else keeps the current buffers —
        donation reuses the live state's device buffers in place (no fresh
        full-state allocation per wave). Every state leaf leads with the
        cluster axis, so one broadcasted select covers the whole pytree."""

        def leaf(cur, ini):
            m = mask.reshape((-1,) + (1,) * (cur.ndim - 1))
            return jnp.where(m, ini, cur)

        return jax.tree.map(leaf, state, pristine)

    return reset


_reset_lanes = _make_reset_lanes()


class ScenarioFleet:
    """Resident what-if service over one compiled batched engine.

    Build once (compile + warm-up paid once), then `submit()` scenarios
    and `run()`: queries pack into C-lane waves; each wave resets the
    lanes in place, installs the wave's per-lane config vectors (traced
    data — zero recompiles), steps the resident engine to the wave's
    horizons and drains per-lane results at those existing host-block
    boundaries.
    """

    def __init__(
        self,
        config: SimulationConfig,
        cluster_events,
        workload_events,
        n_lanes: int,
        horizon: float,
        strict_divergence: bool = True,
        build_scenarios: Optional[Sequence[Optional[Scenario]]] = None,
        lane_async: bool = False,
        span_windows: Optional[int] = None,
        max_queue: Optional[int] = None,
        queue_policy: Optional[str] = None,
        quarantine_faults: int = 3,
        quarantine_window: int = 64,
        quarantine_backoff: int = 8,
        host_chaos: Optional[HostChaos] = None,
        tuned_profile=None,
        **engine_kwargs,
    ) -> None:
        from kubernetriks_tpu.batched.engine import build_batched_from_traces
        from kubernetriks_tpu.flags import flag_int, flag_str

        if n_lanes < 1:
            raise ValueError("a fleet needs at least one lane")
        self.config = config
        self.n_lanes = int(n_lanes)
        self.default_horizon = float(horizon)
        self.strict_divergence = bool(strict_divergence)
        self.lane_async = bool(lane_async)
        if self.lane_async:
            engine_kwargs.setdefault("lane_async", True)
        if span_windows is None:
            span_windows = flag_int("KTPU_LANE_SPAN")
        self.span_windows = max(1, int(span_windows)) if span_windows else 8
        # Build WITH the scenario vectors so every scenario-bearing leaf
        # is (C,)-shaped traced data from the start (later updates are
        # pure data; in particular consts.fault_seed's pytree presence is
        # fixed at build — see engine.update_scenario). build_scenarios:
        # per-lane BUILD config (the wave default a query's overrides
        # apply on top of) — the one channel that reaches the host-
        # compiled node-fault crash chains, which live in the trace slab
        # and are fixed per lane at build (pod-fault seeds stay pure
        # traced data and re-seed per wave).
        self._vectors = scenario_vectors(config, self.n_lanes, build_scenarios)
        self.engine = build_batched_from_traces(
            config,
            cluster_events,
            workload_events,
            n_clusters=self.n_lanes,
            scenario=dict(self._vectors),
            tuned_profile=tuned_profile,
            **engine_kwargs,
        )
        # The profile the engine build resolved (explicit arg >
        # KTPU_TUNED_PROFILE > none) — surfaced here so fleet callers and
        # the bench record can disclose which statics source served.
        self.tuned_profile = self.engine.tuned_profile
        self._queue: deque = deque()
        self._next_query = 0
        # Terminal outcome per qid: FleetResult (ok=True) or a typed
        # QueryError (ok=False) — both stream through poll() once.
        self.results: Dict[int, Union[FleetResult, QueryError]] = {}
        self.waves_run = 0
        # Wave 0 runs on the build-fresh engine; later waves reset first.
        self._dirty = False
        # Warm the lane-reset program now (an empty lane list is the same
        # compiled program — the mask is traced data), so the first REAL
        # reset at the wave-2 boundary is a cache hit and the sweep's
        # zero-recompiles-after-warm-up capture covers every program the
        # steady query stream can touch.
        self.engine.fleet_reset(lanes=[])
        # KTPU_EXPLAIN_RECOMPILES=1: guard every post-warm-up wave with
        # the recompile sentinel — the runtime cross-check of the
        # scenariotrace lint pass's static compile-once guarantee. Wave 1
        # is warm-up (the window/superspan programs legitimately compile
        # there); any compilation inside a later wave raises, naming the
        # jit entry.
        from kubernetriks_tpu.recompile import maybe_sentinel

        self._sentinel = maybe_sentinel()
        # Lane-async bookkeeping (pump/poll, DESIGN §13). _live_vectors is
        # the CURRENT per-lane config row set: assignments rewrite only
        # the re-seeded lanes' rows, so update_scenario hands in-flight
        # lanes bit-identical values and their trajectories are untouched.
        self._live_vectors = {k: v.copy() for k, v in self._vectors.items()}
        self._active: Dict[int, tuple] = {}  # lane -> (qid, scen, horizon)
        self._trace_rows: Dict[int, tuple] = {}  # qid -> (lo, hi)
        self._completed: deque = deque()
        # Query-observatory state (PR 17). _lifecycle holds one mutable
        # record per LIVE query (queued / in-flight / completed-unpolled):
        # perf_counter_ns stamps for submitted -> admitted ->
        # first_dispatch -> drained (-> polled at retirement), the
        # assigned lane, and the Chrome flow id linking submit to drain.
        # poll() retires records into the bounded _polled_lifecycles
        # trail, so the map's size tracks live queries, never the stream.
        self._lifecycle: Dict[int, Dict[str, int]] = {}
        self._polled_lifecycles: deque = deque(maxlen=_POLLED_LIFECYCLES_KEPT)
        # Latency statistics: bounded log-bucket histograms (O(buckets),
        # exact count/sum — the replacement for the PR 16-era unbounded
        # query_latency_s dict) + the bounded exact-sample window the
        # bench's histogram-vs-exact assert reads.
        self.latency_hist = LatencyHistogram()
        self.queue_wait_hist = LatencyHistogram()
        self.service_hist = LatencyHistogram()
        self.latency_exact_window: deque = deque(maxlen=_EXACT_LATENCY_WINDOW)
        self.pump_rounds = 0
        # True once a pump round has exercised the full program set
        # (assign + step + drain) — the sentinel guards rounds after that.
        self._async_warm_done = False
        # Span values whose window-program variants were AOT-warmed
        # (engine.precompile_lane_spans) — first drain alone cannot
        # prove the drain tail's freezing program compiled, because a
        # burst-submitted stream runs boundary-aligned (no-freeze)
        # chunks exclusively until the queue dries.
        self._warm_spans: set = set()
        self.lane_busy_windows = np.zeros((self.n_lanes,), np.int64)
        self.lane_total_windows = np.zeros((self.n_lanes,), np.int64)
        # Fault-domain state (PR 19, DESIGN §15). Bounded admission:
        # queue depth + backpressure policy, flag defaults
        # (KTPU_FLEET_QUEUE / KTPU_FLEET_QUEUE_POLICY), unset = the
        # pre-fault-domain unbounded queue.
        if max_queue is None:
            max_queue = flag_int("KTPU_FLEET_QUEUE")
        self.max_queue = int(max_queue) if max_queue is not None else None
        if self.max_queue is not None and self.max_queue < 1:
            raise ValueError(
                "max_queue must be >= 1 (or None for unbounded), "
                f"got {self.max_queue}"
            )
        policy = queue_policy or flag_str("KTPU_FLEET_QUEUE_POLICY") or "reject"
        if policy not in ("reject", "block"):
            raise ValueError(
                f"queue_policy must be 'reject' or 'block', got {policy!r}"
            )
        self.queue_policy = policy
        # Host-chaos injector: explicit arg wins, else the registered
        # flag. None = injection OFF — every chaos branch below is gated
        # on it, so an unset flag takes the exact pre-chaos code path.
        if host_chaos is None:
            host_chaos = HostChaos.from_flag(flag_str("KTPU_HOST_CHAOS"))
        self._chaos = host_chaos
        # Quarantine policy: a lane faulting `quarantine_faults` times
        # within `quarantine_window` pump rounds leaves the admission
        # rotation for `quarantine_backoff` rounds, then re-admits ONE
        # probe query; a faulting probe doubles the backoff, a completing
        # probe restores the lane and clears its fault history.
        self.quarantine_faults = max(1, int(quarantine_faults))
        self.quarantine_window = max(1, int(quarantine_window))
        self.quarantine_backoff = max(1, int(quarantine_backoff))
        self._lane_fault_rounds: Dict[int, deque] = {}
        self._quarantine: Dict[int, Dict] = {}
        self.quarantine_events = 0
        self.readmissions = 0
        self.failed_queries: Dict[str, int] = {}
        # True once any queued entry ever carried a deadline — the pump's
        # deadline sweep is skipped entirely (zero added host work) for
        # deadline-free streams.
        self._deadlines_ever = False
        self._closing = False
        self._closed = False

    # -- query intake --------------------------------------------------------

    # Scenario fields that must be finite and non-negative (seconds /
    # ratios); the remaining keys are bool/int control values.
    _NONNEG_KEYS = (
        "hpa_scan_interval",
        "hpa_tolerance",
        "ca_scan_interval",
        "ca_threshold",
        "as_to_ca_network_delay",
    )

    def _validate_scenario(self, scenario) -> Scenario:
        """Loud pre-admission validation: unknown keys and wrong axis
        shapes raise HERE (naming the field and the legal set) instead of
        becoming in-flight poison at a lane-reseed boundary."""
        if scenario is None:
            return Scenario()
        if isinstance(scenario, Scenario):
            overrides = scenario.overrides()
        elif isinstance(scenario, Mapping):
            overrides = dict(scenario)
            unknown = [k for k in overrides if k not in SCENARIO_KEYS]
            if unknown:
                raise ValueError(
                    f"submit(): unknown scenario key(s) {sorted(unknown)} "
                    f"— legal keys: {list(SCENARIO_KEYS)}"
                )
        else:
            raise ValueError(
                "submit(): scenario must be a Scenario or a mapping of "
                f"scenario keys, got {type(scenario).__name__}"
            )
        for key, val in overrides.items():
            arr = np.asarray(val)
            if arr.ndim != 0:
                raise ValueError(
                    f"submit(): scenario[{key!r}] must be a per-query "
                    f"SCALAR override (axis shape ()), got shape "
                    f"{arr.shape} — per-lane (C,) vectors belong to "
                    "build_scenarios / engine.update_scenario"
                )
            if key in self._NONNEG_KEYS:
                v = float(arr)
                if not np.isfinite(v) or v < 0:
                    raise ValueError(
                        f"submit(): scenario[{key!r}] must be a finite "
                        f"value >= 0, got {val!r}"
                    )
        if isinstance(scenario, Scenario):
            return scenario
        return Scenario(**overrides)

    @staticmethod
    def _validate_positive(name: str, value, unit: str) -> float:
        try:
            out = float(value)
        except (TypeError, ValueError):
            out = float("nan")
        if not np.isfinite(out) or out <= 0:
            raise ValueError(
                f"submit(): {name} must be a finite number > 0 "
                f"({unit}), got {value!r}"
            )
        return out

    def _retry_after_hint(self) -> Optional[float]:
        """Backpressure hint for RejectedError: the observed median
        service wall scaled by the queue depth ahead, None before any
        query completed."""
        if self.service_hist.count == 0:
            return None
        p50_s = self.service_hist.percentile(50.0)
        waves_ahead = (len(self._queue) + 1) / max(1, self.n_lanes)
        return round(p50_s * waves_ahead, 6)

    def submit(
        self,
        scenario: Optional[Union[Scenario, Mapping]] = None,
        horizon: Optional[float] = None,
        trace_rows: Optional[tuple] = None,
        deadline_s: Optional[float] = None,
    ) -> int:
        """Queue one what-if query; returns its id (the key into
        `results` after `run()` / the pump's drains). trace_rows:
        optional (lo, hi) workload row-range for the query's lane
        (lane-async builds only — engine.set_lane_trace installs it at
        the lane's reseed boundary). deadline_s: optional relative
        deadline (host seconds from now); a query still QUEUED past its
        deadline fails with DeadlineExceededError without ever occupying
        a lane (checked at pump boundaries — an admitted query always
        runs to its horizon).

        Validation happens BEFORE admission (loud ValueError naming the
        field); a full bounded queue applies the configured backpressure
        (reject: the query's qid streams a RejectedError through poll();
        block: pump inline until a slot frees). After close(), raises
        ShutdownError."""
        if self._closing:
            raise ShutdownError(
                -1,
                "submit() after close(): the fleet is draining/closed "
                "and admits no new queries",
            )
        scen = self._validate_scenario(scenario)
        h = (
            self._validate_positive("horizon", horizon, "simulated seconds")
            if horizon is not None
            else self.default_horizon
        )
        if deadline_s is not None:
            deadline_s = self._validate_positive(
                "deadline_s", deadline_s, "host seconds from submit"
            )
        if trace_rows is not None:
            if not self.lane_async:
                raise ValueError(
                    "trace_rows needs lane_async=True (the per-lane "
                    "trace multiplexer)"
                )
            lo, hi = trace_rows
            lo = int(lo)
            hi = None if hi is None else int(hi)
            if lo < 0 or (hi is not None and hi <= lo):
                raise ValueError(
                    "submit(): trace_rows must satisfy 0 <= lo < hi "
                    f"(hi=None = end of trace), got {trace_rows!r}"
                )
            trace_rows = (lo, hi)
        # Bounded admission: the queue depth check runs after validation
        # (a malformed query is a caller bug, not backpressure).
        if (
            self.max_queue is not None
            and len(self._queue) >= self.max_queue
            and self.queue_policy == "block"
        ):
            # Inline pump/run until a slot frees — the fleet is
            # single-threaded, so blocking IS making progress.
            while len(self._queue) >= self.max_queue:
                if self.lane_async:
                    self.pump()
                else:
                    self.run()
        qid = self._next_query
        self._next_query += 1
        t_submit = time.perf_counter_ns()
        # Lifecycle birth: host stamp + the submit->drain flow arrow's id
        # (NULL_TRACER returns 0 = no flow; all pure host, zero syncs).
        self._lifecycle[qid] = {
            "submitted_ns": t_submit,
            "flow_id": self.engine.tracer.flow_start(PH_QUERY_QUEUE),
            "lane": -1,
        }
        if self.max_queue is not None and len(self._queue) >= self.max_queue:
            # policy == "reject": the qid still streams exactly one
            # terminal outcome (a RejectedError via poll), preserving the
            # stream-once contract for refused work too.
            self._fail_query(
                qid,
                RejectedError(
                    qid,
                    f"query {qid} rejected at admission: queue full "
                    f"({len(self._queue)}/{self.max_queue} queued; "
                    "policy 'reject')",
                    retry_after_s=self._retry_after_hint(),
                    scenario=scen,
                    horizon=h,
                ),
            )
            return qid
        if trace_rows is not None:
            self._trace_rows[qid] = trace_rows
        deadline_ns = None
        if deadline_s is not None:
            deadline_ns = t_submit + int(deadline_s * 1e9)
            self._deadlines_ever = True
        self._queue.append((qid, scen, h, deadline_ns))
        return qid

    @property
    def pending(self) -> int:
        return len(self._queue)

    # -- fault delivery ------------------------------------------------------

    def _fail_query(self, qid: int, err: QueryError) -> None:
        """Deliver one terminal TYPED failure through the completion
        stream: same `results` + `_completed` path as a drained result,
        so poll() streams it exactly once and every counter/lifecycle
        readout stays coherent."""
        rec = self._lifecycle.get(qid)
        t_fail = time.perf_counter_ns()
        if rec is not None:
            rec["failed_ns"] = t_fail
            if err.lane >= 0:
                rec["lane"] = err.lane
            tracer = self.engine.tracer
            tracer.end(
                PH_QUERY_FAIL,
                rec["submitted_ns"],
                dur=t_fail - rec["submitted_ns"],
            )
            if rec["flow_id"]:
                tracer.flow_end(PH_QUERY_QUEUE, rec["flow_id"])
        self._trace_rows.pop(qid, None)
        self.results[qid] = err
        self._completed.append(qid)
        self.failed_queries[err.kind] = (
            self.failed_queries.get(err.kind, 0) + 1
        )

    def _expire_deadlines(self) -> None:
        """Fail queued-past-deadline queries WITHOUT occupying a lane —
        runs at pump/wave boundaries the host already crosses (pure
        queue arithmetic, zero new syncs), and only when a deadline was
        ever submitted."""
        if not self._deadlines_ever or not self._queue:
            return
        now = time.perf_counter_ns()
        keep: deque = deque()
        while self._queue:
            entry = self._queue.popleft()
            qid, scen, horizon, deadline_ns = entry
            if deadline_ns is not None and now >= deadline_ns:
                late_s = (now - deadline_ns) / 1e9
                self._fail_query(
                    qid,
                    DeadlineExceededError(
                        qid,
                        f"query {qid} deadline exceeded while queued "
                        f"({late_s:.3f}s late) — failed without "
                        "occupying a lane",
                        late_s=round(late_s, 6),
                        scenario=scen,
                        horizon=horizon,
                    ),
                )
            else:
                keep.append(entry)
        self._queue = keep

    # -- wave machinery ------------------------------------------------------

    def _lane_rows(self, lanes: Sequence[int]) -> Dict[int, Dict[str, float]]:
        """Per-lane counter rows, fetched in ONE host block per metric
        leaf at a horizon boundary (the engine just blocked there for the
        step's own sync; this is the readout ride-along, not a new
        steady-state sync)."""
        m = self.engine.state.metrics
        host = {
            name: np.asarray(getattr(m, name)) for name in _RESULT_COUNTERS
        }
        host["hpa_reserve_clamped"] = np.asarray(m.hpa_reserve_clamped)
        host["ca_reserve_starved"] = np.asarray(m.ca_reserve_starved)
        return {
            lane: {name: arr[lane].item() for name, arr in host.items()}
            for lane in lanes
        }

    def _drain_lane(
        self,
        qid: int,
        lane: int,
        horizon: float,
        scen: Scenario,
        rows: Dict,
        wave: Optional[int] = None,
    ) -> None:
        row = rows[lane]
        clamped = int(row.pop("hpa_reserve_clamped"))
        starved = int(row.pop("ca_reserve_starved"))
        if self.strict_divergence and (clamped > 0 or starved > 0):
            raise RuntimeError(
                f"fleet query {qid} (lane {lane}): autoscaler reserve "
                f"bound crossed (hpa_reserve_clamped={clamped}, "
                f"ca_reserve_starved={starved}) — the lane's trajectory "
                "diverged from the scalar semantics; widen the reserves "
                "or pass strict_divergence=False to read it anyway"
            )
        eng = self.engine
        hpa = None
        ca = None
        if eng.state.auto is not None:
            hpa = eng.hpa_replicas(lane)
            ca = [int(v) for v in eng.ca_node_counts(lane)]
        self.results[qid] = FleetResult(
            query=qid,
            wave=self.waves_run if wave is None else wave,
            lane=lane,
            horizon=horizon,
            scenario=scen,
            counters={k: int(v) for k, v in row.items()},
            hpa_replicas=hpa,
            ca_nodes=ca,
            hpa_reserve_clamped=clamped,
            ca_reserve_starved=starved,
        )

    def _run_wave(self, wave) -> None:
        if self._sentinel is not None and self.waves_run >= 1:
            with self._sentinel.expect_none(
                f"fleet wave {self.waves_run + 1} (post-warm-up)"
            ):
                self._run_wave_inner(wave)
        else:
            self._run_wave_inner(wave)

    def _run_wave_inner(self, wave) -> None:
        eng = self.engine
        # Install the wave's per-lane config rows: base values everywhere,
        # each assigned lane's overrides on top. Idle lanes run the base
        # scenario (their work is discarded).
        vectors = scenario_vectors(
            self.config,
            self.n_lanes,
            [scen for _, scen, _, _ in wave],
            base_vectors=self._vectors,
        )
        eng.update_scenario(vectors)
        if self._dirty:
            eng.fleet_reset()
        self._dirty = True
        # Wave admission: every lane of the wave starts together, so the
        # whole wave shares one admission stamp (queue-wait on this path
        # is wave-packing delay, not lane contention).
        t_admit = time.perf_counter_ns()
        for lane, (qid, _, _, _) in enumerate(wave):
            rec = self._lifecycle.get(qid)
            if rec is not None:
                rec["admitted_ns"] = t_admit
                rec["lane"] = lane
        # Step to each distinct horizon once; lanes finishing there are
        # read back while the host is already blocked at the step exit.
        by_horizon: Dict[float, list] = {}
        for lane, (qid, scen, horizon, _) in enumerate(wave):
            by_horizon.setdefault(horizon, []).append((qid, lane, scen))
        tracer = eng.tracer
        for horizon in sorted(by_horizon):
            eng.step_until_time(horizon)
            lanes = [lane for _, lane, _ in by_horizon[horizon]]
            rows = self._lane_rows(lanes)
            t_drain = time.perf_counter_ns()
            for qid, lane, scen in by_horizon[horizon]:
                self._drain_lane(qid, lane, horizon, scen, rows)
                # Retire the lifecycle record here (wave fleets read
                # results from `results`, not poll()) so the map stays
                # bounded by live queries on this path too.
                rec = self._lifecycle.pop(qid, None)
                if rec is not None:
                    rec["drained_ns"] = t_drain
                    if rec["flow_id"]:
                        tracer.flow_end(PH_QUERY_QUEUE, rec["flow_id"])
                    self._polled_lifecycles.append((qid, rec))
        self.waves_run += 1

    def run(self) -> Dict[int, FleetResult]:
        """Drain the queue: pack pending queries into C-lane waves and run
        each on the resident engine. Returns {query id: FleetResult} for
        everything drained (also accumulated in `self.results`)."""
        self._expire_deadlines()
        while self._queue:
            wave = [
                self._queue.popleft()
                for _ in range(min(self.n_lanes, len(self._queue)))
            ]
            self._run_wave(wave)
            self._expire_deadlines()
        return self.results

    # -- lane-async pump (continuous submit/poll, DESIGN §13) ----------------

    def pump(self, span_windows: Optional[int] = None) -> int:
        """One lane-async scheduling round: seed idle lanes from the
        queue, step up to `span_windows` global windows in power-of-two
        chunks clamped to the nearest lane-plan boundary (each chunk
        shape compiles once; boundary-aligned chunks run the no-freeze
        window program and never overshoot a horizon), then drain the
        lanes whose per-lane clock says their plan completed — pure host
        arithmetic over the clock mirrors, zero new device syncs. Returns
        the number of queries completed this round."""
        if not self.lane_async:
            raise ValueError(
                "pump() needs lane_async=True (wave-aligned fleets run())"
            )
        span = int(span_windows) if span_windows else self.span_windows
        if span not in self._warm_spans:
            self.engine.precompile_lane_spans(span)
            self._warm_spans.add(span)
        if self._sentinel is not None and self._async_warm_done:
            with self._sentinel.expect_none(
                f"fleet pump round {self.pump_rounds + 1} (post-warm-up)"
            ):
                drained = self._pump_inner(span)
        else:
            drained = self._pump_inner(span)
        self.pump_rounds += 1
        if drained and self.pump_rounds >= 1:
            # Assign + step + drain have all run at least once: every
            # program class the steady query stream touches is warm.
            self._async_warm_done = True
        return drained

    def _pump_inner(self, span: int) -> int:
        eng = self.engine
        # 0. Host-boundary deadline sweep: queued-past-deadline queries
        # fail here, before they can occupy a lane. No-op (one attribute
        # read) unless a deadline was ever submitted.
        self._expire_deadlines()
        # 1. Seed idle lanes: rewrite ONLY their _live_vectors rows (base
        # row + this query's overrides), reset their state in place, and
        # start their clocks at the engine's current global window.
        # Quarantined lanes sit out the rotation until their backoff
        # expires, then take ONE probe query; a closing fleet admits
        # nothing (graceful drain).
        assigned = []
        for lane in range(self.n_lanes):
            if lane in self._active or not self._queue or self._closing:
                continue
            q = self._quarantine.get(lane)
            if q is not None:
                if q["probing"] or self.pump_rounds < q["until_round"]:
                    continue
                q["probing"] = True
                self._push_lane_states()
            # Admission drops the deadline: an admitted query always
            # runs to its horizon (deadlines bound QUEUE time only —
            # enforcing them mid-flight would need new device syncs).
            assigned.append((lane, *self._queue.popleft()[:3]))
        if assigned:
            for lane, qid, scen, horizon in assigned:
                for key in SCENARIO_KEYS:
                    self._live_vectors[key][lane] = self._vectors[key][lane]
                for key, val in scen.overrides().items():
                    self._live_vectors[key][lane] = val
            eng.update_scenario(
                {k: v.copy() for k, v in self._live_vectors.items()}
            )
            lanes = [lane for lane, _, _, _ in assigned]
            eng.lane_reset(lanes)
            for lane, qid, _, _ in assigned:
                # Always (re)install the lane's workload range at the
                # reseed boundary: a previous query's mask must not leak
                # into this one (full range when the query carries none;
                # the mux skips the device write when nothing changed).
                lo, hi = self._trace_rows.pop(qid, (0, None))
                eng.set_lane_trace(lane, lo, hi)
            eng.set_lane_plan(
                lanes,
                eng.next_window_idx,
                [eng.horizon_windows(h) for _, _, _, h in assigned],
            )
            # Lifecycle: admitted-to-lane — close the queue-wait span
            # (submit -> here) on the tracer with an explicit duration.
            t_admit = time.perf_counter_ns()
            tracer = eng.tracer
            for lane, qid, scen, horizon in assigned:
                self._active[lane] = (qid, scen, horizon)
                rec = self._lifecycle.get(qid)
                if rec is not None:
                    rec["admitted_ns"] = t_admit
                    rec["lane"] = lane
                    tracer.end(
                        PH_QUERY_QUEUE,
                        rec["submitted_ns"],
                        dur=t_admit - rec["submitted_ns"],
                    )
        if not self._active:
            return 0
        # Lifecycle: first-dispatch — the step block below is the first
        # device dispatch that can carry a freshly admitted lane's plan.
        t_dispatch = time.perf_counter_ns()
        for lane, (qid, _, _) in self._active.items():
            rec = self._lifecycle.get(qid)
            if rec is not None and "first_dispatch_ns" not in rec:
                rec["first_dispatch_ns"] = t_dispatch
        # 2. Dispatch, boundary-aligned: while every lane is mid-plan,
        # step power-of-two sub-spans clamped to the NEAREST lane
        # completion (ladder {span, span/2, ..., 1} — each shape compiles
        # once). Chunks then never cross a plan boundary, so (a) no lane
        # overshoots its horizon (zero occupancy waste while the queue
        # feeds) and (b) the engine's host-mirror proof selects the
        # no-freeze window program for every chunk — the lane-async
        # executor's per-window cost collapses to the wave-aligned
        # program's. Only the drain tail (queue dry, parked lanes riding
        # along) falls back to the fixed span + freezing program.
        remaining0 = eng.lane_windows_remaining()
        queue_fed = bool(self._queue)
        stepped = 0
        try:
            if len(self._active) == self.n_lanes:
                left = span
                remaining = remaining0.copy()
                while left > 0:
                    m = int(min(left, remaining.min()))
                    sub = 1 << (m.bit_length() - 1)
                    self._dispatch(sub)
                    stepped += sub
                    left -= sub
                    remaining = remaining - sub
                    if (remaining <= 0).any():
                        # A plan completed exactly at the chunk edge:
                        # stop the round so the drain/reseed below runs
                        # promptly.
                        break
            else:
                self._dispatch(span)
                stepped = span
        except Exception as exc:
            # FAULT DOMAIN: a failing dispatch kills the occupying
            # lane's query (or, unattributable, every active query) —
            # never the fleet. The lane is crash-reset below; neighbors
            # keep their trajectories (lanes are independent pure
            # functions of scenario + horizon). Recompile-sentinel and
            # strict-divergence errors are NOT lane faults and must stay
            # loud — they indicate a fleet-level contract break.
            from kubernetriks_tpu.recompile import RecompileError

            if isinstance(exc, RecompileError):
                raise
            self._on_dispatch_fault(exc)
            return 0
        # 3. Occupancy ledger (host ints): a lane is busy for
        # min(stepped, windows left on its plan). Idle lanes count as
        # wasted dispatch only while queries were WAITING (queue fed) —
        # parked lanes riding out the drain tail of a dried-up stream are
        # not the async executor's waste (an open-loop feed never dries).
        for lane in range(self.n_lanes):
            if lane in self._active:
                self.lane_busy_windows[lane] += min(
                    stepped, int(remaining0[lane])
                )
                self.lane_total_windows[lane] += stepped
            elif queue_fed:
                self.lane_total_windows[lane] += stepped
        # 4. Drain completed plans.
        done = eng.lane_windows_done()
        finished = [lane for lane in sorted(self._active) if done[lane]]
        if not finished:
            return 0
        rows = self._lane_rows(finished)
        t_drain = time.perf_counter_ns()
        obs = getattr(eng, "observatory", None)
        tracer = eng.tracer
        for lane in finished:
            qid, scen, horizon = self._active.pop(lane)
            self._drain_lane(
                qid, lane, horizon, scen, rows, wave=self.pump_rounds
            )
            q = self._quarantine.get(lane)
            if q is not None and q["probing"]:
                # Probe query COMPLETED: full re-admission — clear the
                # quarantine and the lane's fault history, close the
                # quarantine span (fire -> re-admission).
                del self._quarantine[lane]
                self._lane_fault_rounds.pop(lane, None)
                self.readmissions += 1
                tracer.end(
                    PH_LANE_QUARANTINE,
                    q["since_ns"],
                    dur=t_drain - q["since_ns"],
                )
                if obs is not None:
                    obs.note_lane_readmitted(lane, probes=q["probes"] + 1)
                self._push_lane_states()
            # Lifecycle: horizon-drained — close the service span
            # (admit -> here), land the flow arrow, and draw the lane
            # swimlane interval; then fold the total / queue-wait /
            # service walls into the bounded histograms. All host
            # timestamps: telemetry armed or not, zero device reads.
            rec = self._lifecycle.get(qid)
            if rec is not None:
                rec["drained_ns"] = t_drain
                t_sub = rec["submitted_ns"]
                t_adm = rec.get("admitted_ns", t_sub)
                tracer.end(
                    PH_QUERY_SERVICE, t_adm, dur=t_drain - t_adm
                )
                if rec["flow_id"]:
                    tracer.flow_end(PH_QUERY_QUEUE, rec["flow_id"])
                tracer.lane_event(lane, qid, t_adm, t_drain - t_adm)
                lat = (t_drain - t_sub) / 1e9
                queue_wait = (t_adm - t_sub) / 1e9
                service = (t_drain - t_adm) / 1e9
            else:  # pragma: no cover - records exist for every submit
                lat = queue_wait = service = 0.0
            self.latency_hist.record(lat)
            self.queue_wait_hist.record(queue_wait)
            self.service_hist.record(service)
            self.latency_exact_window.append(lat)
            self._completed.append(qid)
            if obs is not None:
                obs.note_query(lat, queue_wait, service)
        return len(finished)

    # -- fault isolation + quarantine (lane-async) ---------------------------

    def _dispatch(self, n_windows: int) -> None:
        """One engine dispatch, with the host-chaos injection point: a
        stall sleeps before the dispatch (slow-lane latency, no failure),
        a dispatch fault raises InjectedFault in PLACE of the dispatch
        (the engine state is untouched — exactly like an XLA error
        surfacing before results land). Chaos off = straight call."""
        chaos = self._chaos
        if chaos is not None:
            stall = chaos.stall_s()
            if stall > 0.0:
                time.sleep(stall)
            victim = chaos.dispatch_fault(self._active)
            if victim is not None:
                raise InjectedFault(
                    f"host-chaos: injected dispatch fault on lane "
                    f"{victim} (seed {chaos.seed})",
                    lane=victim,
                )
        self.engine.step_windows(n_windows)

    def _on_dispatch_fault(self, exc: Exception) -> None:
        """Poison isolation: fail the victim lane's query (typed, via
        the completion stream), crash-reset the lane from the pristine
        snapshot, and zero its plan so the clock mirrors stay coherent.
        An exception that names no lane (no `.lane` attribute) is
        unattributable and fails every active query — still never the
        fleet."""
        eng = self.engine
        victim = getattr(exc, "lane", None)
        if victim is not None and victim in self._active:
            lanes = [int(victim)]
        else:
            lanes = sorted(self._active)
        for lane in lanes:
            qid, scen, horizon = self._active.pop(lane)
            self._fail_query(
                qid,
                LaneFaultError(
                    qid,
                    f"query {qid}: lane {lane} dispatch failed "
                    f"({type(exc).__name__}: {exc}) — lane crash-reset, "
                    "neighbors unaffected",
                    lane=lane,
                    cause=exc,
                    scenario=scen,
                    horizon=horizon,
                ),
            )
            self._note_lane_fault(lane)
        # Crash recovery = the donated-select lane reset (pure data ops,
        # no structure swap, no recompile) + a zero-window plan so the
        # lane reads as "done" to the host mirrors until re-seeded.
        eng.lane_reset(lanes)
        eng.set_lane_plan(lanes, eng.next_window_idx, [0] * len(lanes))

    def _note_lane_fault(self, lane: int) -> None:
        """Quarantine bookkeeping for one lane fault. A faulting PROBE
        doubles the backoff; `quarantine_faults` faults within
        `quarantine_window` pump rounds fire a fresh quarantine."""
        obs = getattr(self.engine, "observatory", None)
        q = self._quarantine.get(lane)
        if q is not None:
            q["backoff"] = min(q["backoff"] * 2, 1 << 16)
            q["until_round"] = self.pump_rounds + q["backoff"]
            q["probing"] = False
            q["probes"] += 1
            if obs is not None:
                obs.note_lane_quarantined(
                    lane, backoff_rounds=q["backoff"], probed=True
                )
            self._push_lane_states()
            return
        rounds = self._lane_fault_rounds.setdefault(
            lane, deque(maxlen=self.quarantine_faults)
        )
        rounds.append(self.pump_rounds)
        if (
            len(rounds) >= self.quarantine_faults
            and self.pump_rounds - rounds[0] <= self.quarantine_window
        ):
            self._quarantine[lane] = {
                "backoff": self.quarantine_backoff,
                "until_round": self.pump_rounds + self.quarantine_backoff,
                "probing": False,
                "probes": 0,
                "since_ns": time.perf_counter_ns(),
            }
            rounds.clear()
            self.quarantine_events += 1
            if obs is not None:
                obs.note_lane_quarantined(
                    lane,
                    backoff_rounds=self.quarantine_backoff,
                    probed=False,
                )
            self._push_lane_states()

    def lane_states(self) -> List[str]:
        """Per-lane admission state: 'active' (query in flight), 'idle'
        (admissible), 'quarantined' (out of rotation, backoff pending),
        'probe' (backoff expired — next admission is a probe, or the
        probe is in flight)."""
        out = []
        for lane in range(self.n_lanes):
            q = self._quarantine.get(lane)
            if q is not None:
                if q["probing"] or self.pump_rounds >= q["until_round"]:
                    out.append("probe")
                else:
                    out.append("quarantined")
            elif lane in self._active:
                out.append("active")
            else:
                out.append("idle")
        return out

    def _push_lane_states(self) -> None:
        obs = getattr(self.engine, "observatory", None)
        if obs is not None:
            obs.note_lane_states(self.lane_states())

    def arm_host_chaos(self, chaos: Optional[HostChaos]) -> None:
        """Attach (or detach, with None) the host-fault injector —
        bench.py arms chaos AFTER warm-up so the zero-post-warm-up
        recompile assert runs under injection."""
        self._chaos = chaos

    def fault_report(self) -> Dict:
        """Availability + fault-domain counters (the bench's host-chaos
        record): completed/failed split by kind, quarantine activity,
        current lane states, injector event counts."""
        completed_ok = sum(
            1 for r in self.results.values() if getattr(r, "ok", True)
        )
        submitted = self._next_query
        return {
            "submitted": submitted,
            "completed": completed_ok,
            "failed": dict(self.failed_queries),
            "availability": (
                completed_ok / submitted if submitted else 1.0
            ),
            "quarantine_events": self.quarantine_events,
            "readmissions": self.readmissions,
            "lane_states": self.lane_states(),
            "chaos": (
                self._chaos.report() if self._chaos is not None else None
            ),
        }

    def _qid_inventory(self) -> str:
        """The known-qid inventory for loud lookup errors: what this
        fleet has seen, where everything currently is."""
        if self._next_query == 0:
            return "no queries have been submitted to this fleet yet"
        in_flight = sorted(q for q, _, _ in self._active.values())
        return (
            f"{self._next_query} submitted "
            f"(qids 0..{self._next_query - 1}), "
            f"{len(self.results)} completed "
            f"({len(self._completed)} unpolled), "
            f"in-flight qids {in_flight}, {len(self._queue)} queued"
        )

    def _retire_lifecycle(self, qid: int, t_poll_ns: int) -> None:
        rec = self._lifecycle.pop(qid, None)
        if rec is not None:
            rec["polled_ns"] = t_poll_ns
            self._polled_lifecycles.append((qid, rec))

    def poll(
        self, qid: Optional[int] = None
    ) -> List[Union[FleetResult, QueryError]]:
        """Terminal outcomes delivered since the last poll, in
        completion order — the read side of the continuous
        submit/pump/poll engine. Outcomes are FleetResults (ok=True) OR
        typed QueryErrors (ok=False: rejected / deadline_exceeded /
        lane_fault / feeder / shutdown) under ONE stream-once contract:
        every submitted qid streams exactly one terminal outcome, so a
        client never hangs on a dead query.

        ``poll(qid)`` narrows to one query: its outcome (as a
        one-element list) exactly once after it lands, ``[]`` while it
        is still queued/in-flight (or after its outcome was already
        streamed), and a loud ``KeyError`` carrying the known-qid
        inventory when the qid was never submitted here — silence is
        reserved for not-ready, never for a caller bug."""
        t_poll = time.perf_counter_ns()
        if qid is None:
            out = [self.results[q] for q in self._completed]
            for q in self._completed:
                self._retire_lifecycle(q, t_poll)
            self._completed.clear()
            return out
        qid = int(qid)
        if qid < 0 or qid >= self._next_query:
            raise KeyError(
                f"poll({qid}): query {qid} was never submitted to this "
                f"fleet — {self._qid_inventory()}"
            )
        if qid in self._completed:
            self._completed.remove(qid)
            self._retire_lifecycle(qid, t_poll)
            return [self.results[qid]]
        return []

    def query_lifecycle(self, qid: int) -> Dict[str, int]:
        """The host-side lifecycle record for one query: perf_counter_ns
        stamps (submitted_ns, admitted_ns, first_dispatch_ns, drained_ns,
        polled_ns — present once the stage happened), the assigned lane,
        and the trace flow id. Live queries read from the live map;
        recently polled ones from the bounded retirement trail. Raises
        the same loud KeyError as poll() for unknown qids (and for
        records that aged out of the bounded trail)."""
        qid = int(qid)
        if 0 <= qid < self._next_query:
            rec = self._lifecycle.get(qid)
            if rec is None:
                for old_qid, old_rec in reversed(self._polled_lifecycles):
                    if old_qid == qid:
                        rec = old_rec
                        break
            if rec is not None:
                return dict(rec)
        raise KeyError(
            f"query_lifecycle({qid}): no lifecycle record (never "
            f"submitted, or retired past the last "
            f"{_POLLED_LIFECYCLES_KEPT} polled queries) — "
            f"{self._qid_inventory()}"
        )

    def run_async(
        self, span_windows: Optional[int] = None
    ) -> Dict[int, FleetResult]:
        """Pump until the queue and every in-flight lane drain. The async
        counterpart of run(): same {query id: FleetResult} map, same
        per-query numbers (the A/B gate in tests/test_fleet_async.py),
        but a finished lane re-seeds immediately instead of idling to the
        wave boundary."""
        if not self.lane_async:
            raise ValueError(
                "run_async() needs lane_async=True (wave-aligned fleets run())"
            )
        while self._queue or self._active:
            self.pump(span_windows)
        return self.results

    def lane_occupancy(self) -> Dict[str, float]:
        """Busy fraction of dispatched lane-windows (the open-loop bench
        gate): per-lane busy/total from the pump ledger, reported as the
        across-lane mean and min. 1.0 before any pump round."""
        total = np.maximum(self.lane_total_windows, 1)
        frac = self.lane_busy_windows / total
        if not self.lane_total_windows.any():
            frac = np.ones_like(frac)
        return {
            "mean": float(frac.mean()),
            "min": float(frac.min()),
            "lane_windows_busy": int(self.lane_busy_windows.sum()),
            "lane_windows_total": int(self.lane_total_windows.sum()),
        }

    def reset_query_stats(self) -> None:
        """Forget the latency histograms and the occupancy ledger (bench
        warm-up boundary: the reported percentiles/occupancy then
        reflect the resident steady state, not compile time). ATOMIC
        across both sides: the fleet's histograms and the engine
        observatory's query histograms/SLO window reset together, so the
        two can never report different streams."""
        self.latency_hist.reset()
        self.queue_wait_hist.reset()
        self.service_hist.reset()
        self.latency_exact_window.clear()
        self.lane_busy_windows[:] = 0
        self.lane_total_windows[:] = 0
        obs = getattr(self.engine, "observatory", None)
        if obs is not None:
            obs.reset_query_stats()

    def query_latency_percentiles(self) -> Dict[str, float]:
        """Submit-to-drain wall latency percentiles (ms) over every
        completed query — derived from the bounded histogram (exact
        count, percentiles within one bucket width of exact) — exported
        next to queries/s in the open-loop bench record and the
        observatory report."""
        h = self.latency_hist
        if h.count == 0:
            return {"count": 0}
        out: Dict[str, float] = {"count": h.count}
        out.update(h.percentiles_ms())
        return out

    def query_latency_breakdown(self) -> Dict[str, object]:
        """The queue-wait (submit→admit) vs service (admit→drain) split
        plus the raw histogram dump: the open-loop bench embeds this in
        the SWEEP JSON and the Prometheus exporter renders the histogram
        natively (`_bucket`/`_sum`/`_count`)."""
        return {
            "queue_wait_ms": self.queue_wait_hist.percentiles_ms(),
            "service_ms": self.service_hist.percentiles_ms(),
            "histogram": self.latency_hist.to_dict(),
        }

    def sweep(
        self, scenarios: Sequence[Scenario], horizon: Optional[float] = None
    ) -> List[FleetResult]:
        """Convenience: submit + run a whole scenario list, results in
        submission order."""
        qids = [self.submit(s, horizon) for s in scenarios]
        self.run()
        return [self.results[q] for q in qids]

    def close(self, drain: bool = True) -> None:
        """Graceful shutdown: stop admitting (submit() now raises
        ShutdownError), finish in-flight queries (drain=True pumps the
        lane-async fleet until every active lane completes), then fail
        everything still queued with a typed ShutdownError through the
        completion stream — every submitted qid still streams exactly
        one terminal outcome, and poll() keeps working after close (the
        results are host state). drain=False fails in-flight queries
        too, without stepping the engine further."""
        if self._closed:
            return
        self._closing = True
        if self.lane_async and self._active:
            if drain:
                while self._active:
                    self.pump()
            else:
                for lane in sorted(self._active):
                    qid, scen, horizon = self._active.pop(lane)
                    self._fail_query(
                        qid,
                        ShutdownError(
                            qid,
                            f"query {qid} was in flight at "
                            "close(drain=False)",
                            lane=lane,
                            scenario=scen,
                            horizon=horizon,
                        ),
                    )
        while self._queue:
            qid, scen, horizon, _deadline = self._queue.popleft()
            self._fail_query(
                qid,
                ShutdownError(
                    qid,
                    f"query {qid} was still queued at close() — the "
                    "graceful drain finishes in-flight queries and "
                    "fails queued ones",
                    scenario=scen,
                    horizon=horizon,
                ),
            )
        self._closed = True
        if self._sentinel is not None:
            self._sentinel.uninstall()
            self._sentinel = None
        self.engine.close()
