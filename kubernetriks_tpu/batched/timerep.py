"""Window-indexed time representation for the batched path.

Simulation time on device is a pair (win: int32, off: float32) with
``t = win * interval + off`` and ``off ∈ [0, interval)`` — the TPU-native
answer to the precision problem that float64 solves on CPU:

- The reference composes sub-0.1 s control-plane delays onto absolute
  timestamps up to ~7e5 s (Alibaba traces; delays: src/config.yaml:73-78).
  float32 absolute seconds lose the delays (ulp ≈ 0.06 s at 7e5); float64 is
  emulated on TPU and makes every scatter/gather/sort in the hot loop pay a
  64-bit tax (measured ~2x whole-step cost on v5e).
- The pair splits time into an EXACT integer scheduling-window index (the
  only discrete decision the simulation makes: which window an event lands
  in) and a bounded offset carried to within one float32 ulp at `interval`
  (interval * 2^-23 ≈ 1e-6 s at the default 10 s interval) — three orders of
  magnitude below the smallest modeled delay, and independent of absolute
  simulation time.

All pair ops are elementwise 32-bit; comparisons are lexicographic. Offsets
never store +inf: infinity ("no pending effect") is win >= INF_WIN with
off = 0, so arithmetic never produces NaN.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
import numpy as np

# "+infinity" window index. Small enough that INF_WIN + INF_WIN + slack fits
# int32 (adds of two times never both exceed one INF), large enough
# (~5e9 simulated seconds at interval=10) to exceed any real trace horizon.
INF_WIN = 1 << 29


class TPair(NamedTuple):
    """A batch of simulation times: (win * interval + off) seconds."""

    win: jnp.ndarray  # int32 window index; >= INF_WIN means +inf
    off: jnp.ndarray  # float32 offset in [0, interval); 0 where +inf


def t_full(shape, win: int, off: float = 0.0) -> TPair:
    return TPair(
        win=jnp.full(shape, win, jnp.int32),
        off=jnp.full(shape, off, jnp.float32),
    )


def t_inf(shape) -> TPair:
    return t_full(shape, INF_WIN, 0.0)


def t_zeros(shape) -> TPair:
    return t_full(shape, 0, 0.0)


def is_inf(a: TPair) -> jnp.ndarray:
    return a.win >= INF_WIN


def t_lt(a: TPair, b: TPair) -> jnp.ndarray:
    return (a.win < b.win) | ((a.win == b.win) & (a.off < b.off))


def t_le(a: TPair, b: TPair) -> jnp.ndarray:
    return (a.win < b.win) | ((a.win == b.win) & (a.off <= b.off))


def t_min(a: TPair, b: TPair) -> TPair:
    take_b = t_lt(b, a)
    return TPair(
        win=jnp.where(take_b, b.win, a.win),
        off=jnp.where(take_b, b.off, a.off),
    )


def t_where(mask: jnp.ndarray, a: TPair, b: TPair) -> TPair:
    return TPair(
        win=jnp.where(mask, a.win, b.win), off=jnp.where(mask, a.off, b.off)
    )


def t_norm(win: jnp.ndarray, off: jnp.ndarray, interval: jnp.ndarray) -> TPair:
    """Renormalize an unnormalized pair (off may be >= interval, any finite
    value >= 0) back to off ∈ [0, interval). Infinite pairs (win >= INF_WIN)
    pass through — their off stays 0 by construction."""
    off = off.astype(jnp.float32)
    q = jnp.floor(off / interval)
    return TPair(
        win=(win + q.astype(jnp.int32)).astype(jnp.int32),
        off=(off - q * interval).astype(jnp.float32),
    )


def t_add(a: TPair, b: TPair, interval: jnp.ndarray) -> TPair:
    """a + b. Offsets sum to < 2*interval, so one carry normalizes."""
    return t_norm(a.win + b.win, a.off + b.off, interval)


def to_f64(a: TPair, interval: float) -> np.ndarray:
    """Host-side absolute seconds (numpy float64); +inf where infinite."""
    win = np.asarray(a.win, np.int64)
    off = np.asarray(a.off, np.float64)
    t = win * float(interval) + off
    return np.where(win >= INF_WIN, np.inf, t)


def from_f64_np(t: np.ndarray, interval: float):
    """Host-side split of absolute float64 seconds into (win, off) numpy
    arrays. +inf maps to (INF_WIN, 0). The split is computed in float64, so
    win is exact and off carries only the final float32 rounding plus the
    boundary clamp below (≤ one float32 ulp at `interval`, interval * 2^-23)."""
    t = np.asarray(t, np.float64)
    finite = np.isfinite(t)
    win = np.where(finite, np.floor(t / interval), INF_WIN).astype(np.int64)
    off = np.where(finite, t - win * float(interval), 0.0)
    # Guard the floor against f64 division rounding at exact multiples.
    over = finite & (off >= interval)
    win = np.where(over, win + 1, win)
    off = np.where(over, off - interval, off)
    off32 = off.astype(np.float32)
    # The float32 cast can round an offset just below the boundary UP to
    # exactly `interval`. Clamp to the largest float32 below it rather than
    # carrying: a carry would move the time into the next window, and window
    # classification must stay exact (it decides which step applies the
    # event, matching the scalar oracle); the clamp error is at most one
    # float32 ulp at `interval` (interval * 2^-23, the docstring's bound).
    off32 = np.minimum(
        off32, np.nextafter(np.float32(interval), np.float32(0.0))
    ).astype(np.float32)
    return win.astype(np.int32), off32
