"""Compiled scheduler-profile pipeline: the device-plugin subsystem that
lowers a KubeScheduler profile (ordered filter refs + weighted score refs)
into the batched hot path.

The scalar path interprets profiles per pod through the plugin registry
(core/scheduler/plugins.py, kube_scheduler.py). The batched path cannot —
its decision core runs inside jit-compiled programs and Mosaic/Pallas
kernels — so a profile is COMPILED here, once, at engine construction:

- `compile_profile` validates every plugin ref against the device registry
  below and produces a `CompiledProfile`: a small, hashable NamedTuple of
  plugin names and weights. A profile referencing a plugin the device
  registry cannot lower raises `UnsupportedProfileError` naming the plugin
  and the supported set — the batched engine REFUSES profiles it cannot
  honor instead of silently running the hard-coded default (the
  silent-wrong-profile failure mode this subsystem kills).
- The `CompiledProfile` threads through `_STEP_STATICS` exactly like
  `fault_params` (batched/step.py): it is a jit static, so each profile
  compiles its own window programs, and the expressions below are inlined
  into both the lax.scan oracle path and the Pallas kernels
  (`ops/scheduler_kernel._fit_score_place`) as kernel statics.
- `profile_fit_mask` / `profile_score` are the ONE definition of the
  filter-mask and weighted-score expressions. They are pure elementwise
  jnp programs over broadcast-compatible arrays, which is precisely what
  makes them lowerable in BOTH worlds: the scan body calls them on
  (C, N) node arrays with (C, 1) requests, the kernels on (Np, LANE) node
  tiles with (1, LANE) requests. All literals are explicitly typed
  (Mosaic cannot lower weak f64/i64 constants under jax_enable_x64).

Semantics (pinned bit-for-bit against the pre-profile hard-fused core for
the default profile, and against the scalar oracle for every profile by
tests/test_random_equivalence.py):

- Filters AND into the alive mask (scalar: list comprehension chain).
- Scores are float32, summed over scorers after weighting; a weight of
  exactly 1.0 skips the multiply so the default profile's expression tree
  is textually identical to the historical hard-fused one.
- Zero-allocatable nodes score NaN on the scalar path (plugins.py) and
  -inf here: neither can win the last-max-wins `>=` argmax, so decisions
  agree; -inf keeps the kernels free of NaN-propagation hazards.
- Tie-breaks: last max in node-slot order == the reference's `>=` sweep
  over name-sorted nodes (kube_scheduler.rs:140-150).
"""

from __future__ import annotations

from typing import Callable, Dict, NamedTuple, Tuple

import jax.numpy as jnp
import numpy as np

from kubernetriks_tpu.core.scheduler.kube_scheduler import (
    DEFAULT_SCHEDULER_NAME,
    KubeSchedulerConfig,
    kube_scheduler_config_from_spec,
)
from kubernetriks_tpu.core.scheduler.plugins import (
    BALANCED,
    FIT,
    LEAST_ALLOCATED,
    MOST_ALLOCATED,
)

_NEG_INF = float(np.float32(-np.inf))


class UnsupportedProfileError(ValueError):
    """A configured profile references a plugin the device pipeline cannot
    lower (or an un-lowerable weight). Raised at engine construction —
    loudly, naming the offender and the supported set — never silently
    replaced by the default pipeline."""


class CompiledProfile(NamedTuple):
    """A profile lowered to kernel statics: hashable (it keys the jit
    cache through _STEP_STATICS) and tiny (names + weights only; the
    expressions are regenerated from the registry at trace time)."""

    name: str  # display name ("default", "best_fit", or "custom")
    filters: Tuple[str, ...]  # ordered filter plugin names
    scores: Tuple[Tuple[str, float], ...]  # (scorer name, weight) pairs


def _zero(x):
    """A typed zero matching x's dtype — Mosaic rejects weak Python-scalar
    constants inside kernel bodies under jax_enable_x64."""
    return x.dtype.type(0)


# --- device plugin registry ---------------------------------------------------
# Filters: fn(cpu, ram, rc, rr) -> bool mask (AND-composed onto `alive`).
# Scorers: fn(cpu, ram, rc, rr) -> float32 score (summed after weighting).
# cpu/ram are the nodes' current allocatable, rc/rr the candidate's requests;
# any broadcast-compatible shapes (the scan path and the kernels differ).


def _filter_fit(cpu, ram, rc, rr):
    return (rc <= cpu) & (rr <= ram)


def _score_least_allocated(cpu, ram, rc, rr):
    neg_inf = jnp.float32(_NEG_INF)
    hundred = jnp.float32(100.0)
    half = jnp.float32(0.5)
    cpu_f = cpu.astype(jnp.float32)
    ram_f = ram.astype(jnp.float32)
    cpu_score = jnp.where(
        cpu > _zero(cpu),
        (cpu_f - rc.astype(jnp.float32)) * hundred / cpu_f,
        neg_inf,
    )
    ram_score = jnp.where(
        ram > _zero(ram),
        (ram_f - rr.astype(jnp.float32)) * hundred / ram_f,
        neg_inf,
    )
    return (cpu_score + ram_score) * half


def _score_most_allocated(cpu, ram, rc, rr):
    neg_inf = jnp.float32(_NEG_INF)
    hundred = jnp.float32(100.0)
    half = jnp.float32(0.5)
    cpu_f = cpu.astype(jnp.float32)
    ram_f = ram.astype(jnp.float32)
    cpu_score = jnp.where(
        cpu > _zero(cpu),
        (rc.astype(jnp.float32) - cpu_f) * hundred / cpu_f,
        neg_inf,
    )
    ram_score = jnp.where(
        ram > _zero(ram),
        (rr.astype(jnp.float32) - ram_f) * hundred / ram_f,
        neg_inf,
    )
    return (cpu_score + ram_score) * half


def _score_balanced(cpu, ram, rc, rr):
    neg_inf = jnp.float32(_NEG_INF)
    hundred = jnp.float32(100.0)
    cpu_f = cpu.astype(jnp.float32)
    ram_f = ram.astype(jnp.float32)
    ok = (cpu > _zero(cpu)) & (ram > _zero(ram))
    # Guard the divisors so the masked-out lanes never divide by zero
    # (where() evaluates both branches).
    one = jnp.float32(1.0)
    cpu_frac = rc.astype(jnp.float32) / jnp.where(ok, cpu_f, one)
    ram_frac = rr.astype(jnp.float32) / jnp.where(ok, ram_f, one)
    return jnp.where(
        ok, hundred - jnp.abs(cpu_frac - ram_frac) * hundred, neg_inf
    )


DEVICE_FILTER_PLUGINS: Dict[str, Callable] = {
    FIT: _filter_fit,
}

DEVICE_SCORE_PLUGINS: Dict[str, Callable] = {
    LEAST_ALLOCATED: _score_least_allocated,
    MOST_ALLOCATED: _score_most_allocated,
    BALANCED: _score_balanced,
}


# The reference default, hard-fused into the batched path since its first
# version — now just the profile every other one is compiled like.
DEFAULT_PROFILE = CompiledProfile(
    name="default",
    filters=(FIT,),
    scores=((LEAST_ALLOCATED, 1.0),),
)


def compile_profile(spec=None) -> CompiledProfile:
    """Lower one profile spec to a CompiledProfile.

    Accepts everything kube_scheduler_config_from_spec does (None, a named
    profile string, an explicit {filters, score} mapping, a
    KubeSchedulerConfig) plus an already-compiled CompiledProfile (validated
    again — a hand-built one may still name unknown plugins).

    Raises UnsupportedProfileError naming the offending plugin and the
    supported set when the batched path cannot lower the profile; the
    scalar interpreter may still run such a profile, but the engine must
    never silently substitute the default for it."""
    if isinstance(spec, CompiledProfile):
        prof = spec
    else:
        if spec is None:
            spec = "default"
        name = spec if isinstance(spec, str) else None
        config = kube_scheduler_config_from_spec(spec)
        kprof = config.profiles[DEFAULT_SCHEDULER_NAME]
        prof = CompiledProfile(
            name=name or "custom",
            filters=tuple(p.name for p in kprof.plugins.filter),
            scores=tuple(
                (p.name, float(1.0 if p.weight is None else p.weight))
                for p in kprof.plugins.score
            ),
        )
    for fname in prof.filters:
        if fname not in DEVICE_FILTER_PLUGINS:
            raise UnsupportedProfileError(
                f"scheduler profile {prof.name!r}: filter plugin {fname!r} "
                f"has no device lowering — the batched path supports "
                f"filters {sorted(DEVICE_FILTER_PLUGINS)} and scorers "
                f"{sorted(DEVICE_SCORE_PLUGINS)} "
                f"(kubernetriks_tpu/batched/pipeline.py); run the scalar "
                f"backend for scalar-only plugins"
            )
    for sname, weight in prof.scores:
        if sname not in DEVICE_SCORE_PLUGINS:
            raise UnsupportedProfileError(
                f"scheduler profile {prof.name!r}: score plugin {sname!r} "
                f"has no device lowering — the batched path supports "
                f"filters {sorted(DEVICE_FILTER_PLUGINS)} and scorers "
                f"{sorted(DEVICE_SCORE_PLUGINS)} "
                f"(kubernetriks_tpu/batched/pipeline.py); run the scalar "
                f"backend for scalar-only plugins"
            )
        if not (weight > 0.0) or not np.isfinite(weight):
            # Scalar NaN-score semantics survive any positive weight; a
            # zero/negative/non-finite weight would flip the -inf lowering
            # of zero-allocatable nodes into a winning score.
            raise UnsupportedProfileError(
                f"scheduler profile {prof.name!r}: score plugin {sname!r} "
                f"has weight {weight!r}; the device lowering requires a "
                f"finite weight > 0"
            )
    return prof


def to_kube_scheduler_config(profile: CompiledProfile) -> KubeSchedulerConfig:
    """CompiledProfile -> the KubeSchedulerConfig that makes the scalar
    KubeScheduler run the SAME profile — the oracle side of the per-profile
    equivalence sweeps."""
    return kube_scheduler_config_from_spec(
        {
            "filters": list(profile.filters),
            "score": [
                {"name": n, "weight": w} for n, w in profile.scores
            ],
        }
    )


# --- compiled expressions -----------------------------------------------------


def profile_fit_mask(profile: CompiledProfile, alive, cpu, ram, rc, rr):
    """The profile's filter chain ANDed onto the alive mask. Elementwise;
    usable in the scan body and inside Mosaic kernels."""
    fit = alive
    for fname in profile.filters:
        fit = fit & DEVICE_FILTER_PLUGINS[fname](cpu, ram, rc, rr)
    return fit


def profile_score(profile: CompiledProfile, fit, cpu, ram, rc, rr):
    """The profile's weighted score sum, masked to -inf off the fit set.
    weight == 1.0 skips the multiply, so the default profile generates the
    exact historical expression tree (bit-identical programs)."""
    neg_inf = jnp.float32(_NEG_INF)
    total = None
    for sname, weight in profile.scores:
        s = DEVICE_SCORE_PLUGINS[sname](cpu, ram, rc, rr)
        if weight != 1.0:
            s = s * jnp.float32(weight)
        total = s if total is None else total + s
    if total is None:
        # Scoreless profile: every fitting node scores 0.0; the last-max
        # argmax then picks the last fitting slot, matching the scalar
        # `>=` sweep over all-zero node_scores.
        return jnp.where(fit, jnp.float32(0.0), neg_inf)
    return jnp.where(fit, total, neg_inf)


def profile_fit_score(profile: CompiledProfile, alive, cpu, ram, rc, rr):
    """(fit mask, masked score) in one call — the decision core both the
    lax.scan path (batched/step.py) and the Pallas kernels
    (ops/scheduler_kernel._fit_score_place) build on."""
    fit = profile_fit_mask(profile, alive, cpu, ram, rc, rr)
    return fit, profile_score(profile, fit, cpu, ram, rc, rr)


def bestfit_logits_from_obs(obs):
    """The MostAllocatedResources scorer evaluated on the RL environment's
    observation channels (rl/env.featurize: alloc and request fractions of
    node capacity). The scorer is scale-invariant per resource —
    (rc - cpu)/cpu is unchanged by dividing both by capacity — so the
    capacity-normalized channels rank nodes exactly like the raw
    allocatables. This is the ONE best-fit definition shared by the
    learning proof's heuristic baseline (rl/evaluate.bestfit_policy_apply)
    and the scheduler's "best_fit" device profile."""
    return DEVICE_SCORE_PLUGINS[MOST_ALLOCATED](
        obs[..., 2], obs[..., 3], obs[..., 4], obs[..., 5]
    )
