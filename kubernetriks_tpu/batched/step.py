"""The vectorized window step: trace-event application + pod finishes + one
scheduling cycle, over a whole batch of clusters at once.

This replaces the scalar event loop (reference: src/simulator.rs:355-372 pops
one event at a time) with array programs:

- Each control-plane hop of the reference becomes a time-shifted effect
  (SURVEY.md §5.8); the compiler pre-shifts event times to their effect times.
- Pod completions are precomputed finish times invalidated by masks (replacing
  DSLab cancel_event, reference: src/core/node_component.rs:102-104).
- Event application is BULK: the window's slab segment is gathered once per
  cluster, node/pod removal times become scatter-min arrays, and the
  finish-vs-removal interleaving is resolved elementwise per pod by comparing
  finish_time against min(window_end, node_removal_time, pod_removal_time) —
  ordering fidelity without a per-event loop.
- The kube-scheduler cycle has three equivalent formulations (see
  _run_scheduling_cycle): a sorted top-K compaction + lax.scan (the oracle;
  queue order (queue_ts, queue_seq) == the scalar ActiveQueue's (timestamp,
  insertion seq) min-heap; Fit mask + LeastAllocatedResources score +
  last-wins argmax, reference semantics:
  src/core/scheduler/kube_scheduler.rs:63-152, plugin.rs:33-63), the same
  sort feeding a Pallas candidate kernel with a data-dependent early exit,
  and — on dense cluster batches — a fully fused Pallas selection kernel
  with no sort at all (ops/scheduler_kernel.py). Dense batches also route
  the freed-resource, event-application and decision-commit scatters
  through one-hot Pallas kernels (TPU scatter cost is per-index).
- run_windows_skip fast-forwards over provably no-op windows (bit-exact;
  the engine auto-enables it on sparse traces).

Time is the 32-bit (win, off) pair of timerep.py. Each step runs at window
index W (cycle time T = W * interval); all event/effect times applied in the
window are carried as float32 seconds RELATIVE to the previous window's start
((W-1) * interval) — bounded values whose scatter/gather/sort stay on the
TPU's fast 32-bit paths — and are renormalized to pairs only when written
back to persistent state.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from kubernetriks_tpu.batched.state import (
    ClusterBatchState,
    EstArrays,
    EV_CREATE_NODE,
    EV_CREATE_POD,
    EV_NODE_CRASH,
    EV_NODE_RECOVER,
    EV_REMOVE_NODE,
    EV_REMOVE_POD,
    PHASE_EMPTY,
    PHASE_FAILED,
    PHASE_QUEUED,
    PHASE_REMOVED,
    PHASE_RUNNING,
    PHASE_SUCCEEDED,
    PHASE_UNSCHEDULABLE,
    NODE_HOT_LEAVES,
    StepConstants,
    TraceSlab,
    swap_node_layout,
)
from kubernetriks_tpu.batched.timerep import (
    TPair,
    t_add,
    t_inf,
    t_le,
    t_lt,
    t_norm,
    t_where,
)

INF = jnp.inf


def t_seconds_f32(a: TPair, interval) -> jnp.ndarray:
    """Pair -> float32 seconds (for metric values and bounded spans)."""
    return a.win.astype(jnp.float32) * jnp.float32(interval) + a.off


def lexsort_time_i32(t: TPair, seq: jnp.ndarray) -> jnp.ndarray:
    """Row-wise stable argsort by (time pair, seq) -> int32 indices: the
    batched ActiveQueue ordering ((timestamp, insertion seq) min-heap,
    reference: src/core/scheduler/queue.rs:13-75)."""
    C, P = seq.shape
    iota = jnp.broadcast_to(jnp.arange(P, dtype=jnp.int32)[None, :], (C, P))
    _, _, _, order = jax.lax.sort(
        (t.win, t.off, seq, iota), dimension=1, num_keys=3, is_stable=True
    )
    return order


def _est_add_reduced(est: EstArrays, values: jnp.ndarray, mask: jnp.ndarray) -> EstArrays:
    """Fold a (C, P) masked batch of samples into (C,) estimator accumulators."""
    values = values.astype(jnp.float32)
    maskf = mask.astype(jnp.float32)
    return EstArrays(
        count=est.count + mask.sum(axis=1, dtype=jnp.int32),
        total=est.total + (values * maskf).sum(axis=1),
        total_sq=est.total_sq + (values * values * maskf).sum(axis=1),
        minimum=jnp.minimum(est.minimum, jnp.where(mask, values, INF).min(axis=1)),
        maximum=jnp.maximum(est.maximum, jnp.where(mask, values, -INF).max(axis=1)),
    )


def _rel_seconds(t: TPair, base_win: jnp.ndarray, interval) -> jnp.ndarray:
    """Pair -> float32 seconds relative to base_win * interval. Exact (zero
    multiplier) for times inside the base window — the common case for
    this window's events/effects — and correctly ordered for earlier ones."""
    return (t.win - base_win).astype(jnp.float32) * jnp.float32(interval) + t.off


def _stable_queue_rank(keys) -> jnp.ndarray:
    """Dense queue ranks from lexicographic (C, P) sort keys: the
    scatter-inverse of a stable sort over the pod axis, slot order breaking
    exact key ties. Shared by the reschedule and CrashLoopBackOff retry
    dispositions so the scalar-parity ordering rules live in ONE place."""
    C, P = keys[0].shape
    iota_pp = jnp.broadcast_to(jnp.arange(P, dtype=jnp.int32)[None, :], (C, P))
    out = jax.lax.sort(
        (*keys, iota_pp), dimension=1, num_keys=len(keys), is_stable=True
    )
    return (
        jnp.zeros((C, P), jnp.int32)
        .at[jnp.arange(C, dtype=jnp.int32)[:, None], out[-1]]
        .set(iota_pp)
    )



def _shard_rowwise(core, n_in: int, n_out: int, mesh, axis: str):
    """shard_map a kernel wrapper over the cluster axis: every input/output
    is a (C, ...) array sharded on axis 0 (pallas_call has no GSPMD
    partitioning rule, so each device runs the kernel on its own shard; the
    wrappers pad per-shard, and clusters are independent so no collectives
    are needed)."""
    from jax.sharding import PartitionSpec

    from kubernetriks_tpu.parallel.multihost import shard_map

    row = PartitionSpec(axis, None)
    return shard_map(
        core,
        mesh=mesh,
        in_specs=(row,) * n_in,
        # A kernel returning one bare array (not a 1-tuple) needs a bare spec.
        out_specs=(row,) * n_out if n_out > 1 else row,
        check_vma=False,
    )


def _window_work_due(
    state: ClusterBatchState, slab: TraceSlab, W: jnp.ndarray
) -> jnp.ndarray:
    """Scalar bool: could _apply_window_events_work change ANY state leaf at
    window W? The window-cost razor's due-ness predicate — a handful of
    cheap compares + reductions against the ~35 masked elementwise passes
    of the resolution soup. CONSERVATIVE by construction (true whenever any
    trigger below could fire; running the soup needlessly is always exact):

    - a due trace event (the chunk loop's own entry condition);
    - a pending autoscaler/chaos effect due: CA node create/remove, HPA pod
      removal (win < W exactly, the soup's own due tests minus the ~alive /
      phase refinements — supersets, so never missed);
    - a running pod's finish due by the window end. With none of the other
      triggers firing, every interrupt source is +inf, so the soup's cutoff
      is exactly the window-end pair this predicate compares against.

    When false, the soup is the identity on everything except
    time = max(time, W) (metric folds add masked zeros, estimator min/max
    merge against +/-inf identities, requeue_signal ors False) — the skip
    branch replicates exactly that. Layout-agnostic: only row-major leaves
    (pending pairs, pod arrays) and the slab are read."""
    C = state.time.shape[0]
    E_total = slab.packed.shape[1]
    rows1 = jnp.arange(C, dtype=jnp.int32)
    cursor = jnp.clip(state.event_cursor, 0, E_total - 1)
    ev_due = (
        (state.event_cursor < E_total) & (slab.packed[rows1, cursor, 0] < W)
    ).any()
    pend_due = (
        (state.nodes.create_time.win < W[:, None]).any()
        | (state.nodes.remove_time.win < W[:, None]).any()
        | (state.pods.removal_time.win < W[:, None]).any()
    )
    P = state.pods.phase.shape[1]
    window_end = TPair(
        win=jnp.broadcast_to(W[:, None], (C, P)),
        off=jnp.zeros((C, P), jnp.float32),
    )
    fin_due = (
        (state.pods.phase == PHASE_RUNNING)
        & t_le(state.pods.finish_time, window_end)
    ).any()
    return ev_due | pend_due | fin_due


def _apply_window_events(
    state: ClusterBatchState,
    slab: TraceSlab,
    W: jnp.ndarray,
    consts: StepConstants,
    max_events_per_window: int,
    conditional_move: bool = False,
    use_pallas: bool = False,
    pallas_interpret: bool = False,
    pallas_mesh=None,
    pallas_axis: str = "clusters",
    use_pallas_select: bool = False,
    node_name_rank=None,
    pod_name_rank=None,
    fault_params=None,
    lane_major: bool = False,
    window_razor: bool = True,
    node_key_fn=None,
):
    """Event application + finish resolution, behind the window-cost razor
    (KTPU_WINDOW_RAZOR): when the due-ness predicate proves the window has
    no resolution work, the whole soup is skipped via lax.cond — empty and
    near-empty windows in dense traces stop paying the ~35 masked
    elementwise passes (fast-forward only helps when WHOLE spans are empty;
    this gates per window inside dense spans). Bit-exact: the skip branch
    fires only when the soup is provably the identity (see
    _window_work_due). window_razor=False keeps the always-run path for
    A/B measurement."""
    args = (
        consts,
        max_events_per_window,
        conditional_move,
        use_pallas,
        pallas_interpret,
        pallas_mesh,
        pallas_axis,
        use_pallas_select,
        node_name_rank,
        pod_name_rank,
        fault_params,
        lane_major,
        node_key_fn,
    )
    if not window_razor:
        return _apply_window_events_work(state, slab, W, *args)

    def run(st):
        return _apply_window_events_work(st, slab, W, *args)

    def skip(st):
        if conditional_move:
            C, P = st.pods.phase.shape
            N = (
                st.nodes.cap_cpu.shape[0]
                if lane_major
                else st.nodes.cap_cpu.shape[1]
            )
            f32inf = jnp.float32(INF)
            wake = WakeEvents(
                node_mask=jnp.zeros((C, N), bool),
                node_rel=jnp.full((C, N), f32inf, jnp.float32),
                freed_mask=jnp.zeros((C, P), bool),
                freed_rel=jnp.full((C, P), f32inf, jnp.float32),
            )
        else:
            wake = None
        return st._replace(time=jnp.maximum(st.time, W)), wake

    return jax.lax.cond(_window_work_due(state, slab, W), run, skip, state)


def _apply_window_events_work(
    state: ClusterBatchState,
    slab: TraceSlab,
    W: jnp.ndarray,
    consts: StepConstants,
    max_events_per_window: int,
    conditional_move: bool = False,
    use_pallas: bool = False,
    pallas_interpret: bool = False,
    pallas_mesh=None,
    pallas_axis: str = "clusters",
    use_pallas_select: bool = False,
    node_name_rank=None,
    pod_name_rank=None,
    fault_params=None,
    lane_major: bool = False,
    node_key_fn=None,
) -> ClusterBatchState:
    """Apply every trace event with effect time STRICTLY before the cycle time
    W * interval, and resolve all pod finishes due in the window.

    lane_major (KTPU_LANE_MAJOR): the hot node leaves
    (state.NODE_HOT_LEAVES) and every node-shaped accumulator in this
    function are carried TRANSPOSED (N, C) — the Pallas kernels' layout —
    so the event/free kernel boundaries stop materializing transposed
    copies. Pod arrays, the pending-effect pairs and WakeEvents keep the
    row-major convention (their producers/consumers are row-major-shaped
    sorts/gathers); the handful of row-major pending-effect masks that
    merge into lane-major accumulators transpose exactly once below.

    fault_params (chaos.FaultParams, static): with node_faults, the slab may
    carry EV_NODE_CRASH (remove semantics + crash/downtime accounting; a
    separate scatter keeps crash attribution for the interruption counter)
    and EV_NODE_RECOVER (create semantics on a fresh slot + recovery count);
    with pod_faults, running pods whose will_fail flag is set FAIL at their
    finish_time instead of succeeding — retry via CrashLoopBackOff requeue
    or terminate as PHASE_FAILED past the restart limit.

    Strictness: an effect landing exactly at cycle time T is processed after
    the cycle in the scalar kernel (older-event-id-first FIFO), so it belongs
    to the next window. With pair times that check is exact: effect applied
    iff its window index < W.

    Dtype note (applies to this whole module): jax_enable_x64 is on (see
    state.py), so every index/count op must pin an explicit 32-bit dtype —
    untyped arange/argmax/bool-sum default to i64 under x64, and stray i64
    lanes measurably slow the TPU hot loop (emulated 64-bit).
    """
    pods, nodes, metrics = state.pods, state.nodes, state.metrics
    C, P = pods.phase.shape
    N = nodes.alive.shape[0] if lane_major else nodes.alive.shape[1]
    # Node-shaped accumulators follow the hot leaves' layout: (N, C) lane
    # major, (C, N) row major. n_sum_ax reduces them to (C,).
    n_shape = (N, C) if lane_major else (C, N)
    n_sum_ax = 0 if lane_major else 1
    E_total = slab.packed.shape[1]
    E = max_events_per_window
    interval = jnp.float32(consts.scheduling_interval)
    rows1 = jnp.arange(C, dtype=jnp.int32)
    rows = rows1[:, None]
    base = W - 1  # (C,) the window the applied events fall in
    f32inf = jnp.float32(INF)

    from kubernetriks_tpu.ops.scheduler_kernel import (
        event_kernel_fits,
        fused_event_scatter,
    )

    node_faults = fault_params is not None and fault_params.node_faults
    pod_faults = fault_params is not None and fault_params.fail_prob > 0

    # The one-hot scatter kernels sweep whole (P, 128-lane) tiles per event,
    # so like the selection kernel they only pay when the cluster lanes are
    # dense — use_pallas_select carries exactly that gate (measured: the
    # C=1 replay regressed 229 s -> 350 s with them always-on). The kernel
    # predates the chaos event kinds, so fault-bearing slabs take the plain
    # scatter path (bit-identical fallback).
    use_event_kernel = (
        use_pallas
        and use_pallas_select
        and event_kernel_fits(N, P, E)
        and not node_faults
    )
    if use_event_kernel:
        event_core = partial(
            fused_event_scatter,
            interpret=pallas_interpret,
            nodes_lane_major=lane_major,
        )
        if pallas_mesh is not None:
            event_core = _shard_rowwise(event_core, 10, 5, pallas_mesh, pallas_axis)

    # --- bulk-apply the window's slab events, E at a time -------------------
    # E is a CHUNK size, not a worst-case bound: chunks apply inside a
    # while_loop until no cluster has a due event left. A trace with a burst
    # window (e.g. 1000 CreateNodes at t=0) takes a few extra iterations in
    # that one window instead of taxing every window with a burst-sized
    # gather/scatter. Due events are a sorted prefix of the slab, so a chunk
    # boundary never skips one.
    def chunk_due(cursor):
        nxt = slab.packed[rows1, jnp.clip(cursor, 0, E_total - 1), 0]
        return (cursor < E_total) & (nxt < W)

    def chunk_cond(carry):
        return jnp.any(chunk_due(carry[0]))

    def chunk_body(carry):
        (cursor, created, node_removal, pod_create, pod_create_seq,
         pod_removal, n_creates) = carry[:7]
        tail = 7
        if conditional_move:
            node_create_rel = carry[tail]
            tail += 1
        if node_faults:
            crash_rm, n_recover = carry[tail], carry[tail + 1]
        offs = cursor[:, None] + jnp.arange(E, dtype=jnp.int32)[None, :]
        offs_c = jnp.clip(offs, 0, E_total - 1)
        # One packed gather instead of four (gather cost is per-index on TPU).
        pk = slab.packed[rows, offs_c]  # (C, E, 4) int32
        ev_win = pk[..., 0]
        ev_off = jax.lax.bitcast_convert_type(pk[..., 1], jnp.float32)
        ev_k = pk[..., 2]
        ev_s_raw = pk[..., 3]
        valid = (offs < E_total) & (ev_win < W[:, None])
        # Pod event slots are GLOBAL; the device pod arrays are segmented into
        # a sliding window over plain trace pods (global slot <
        # consts.trace_pod_bound, device slot = global - pod_base) and a
        # RESIDENT tail of pod-group ring slots (device slot = global -
        # consts.resident_shift; pod groups are long-running services, which
        # would block the window's terminal-prefix shift forever). Both
        # subtractions are the identity on full-resident runs. Out-of-window
        # slots (already-shifted-out, necessarily terminal pods — e.g. a
        # RemovePod after its pod finished and scrolled away) drop at the
        # scatters.
        is_pod_ev = (ev_k == EV_CREATE_POD) | (ev_k == EV_REMOVE_POD)
        seg_shift = jnp.where(
            ev_s_raw < consts.trace_pod_bound,
            state.pod_base[:, None],
            consts.resident_shift,
        )
        ev_s = jnp.where(is_pod_ev, ev_s_raw - seg_shift, ev_s_raw)
        ev_s = jnp.where(is_pod_ev & (ev_s < 0), jnp.int32(1 << 29), ev_s)
        # Event time in f32 seconds relative to base (== ev_off when the
        # event is in this window, which consecutive stepping guarantees).
        ev_rel = (ev_win - base[:, None]).astype(jnp.float32) * interval + ev_off

        is_cn = valid & (ev_k == EV_CREATE_NODE)
        is_rn = valid & (ev_k == EV_REMOVE_NODE)
        is_cp = valid & (ev_k == EV_CREATE_POD)
        is_rp = valid & (ev_k == EV_REMOVE_POD)
        if node_faults:
            # Recoveries ARE creations (fresh slot, fresh capacity) — fold
            # into is_cn so every create-side effect (alive/alloc, wake
            # events, pending-create interplay) applies identically; crashes
            # scatter into their own removal array so crash attribution
            # survives for the interruption/downtime metrics, and merge into
            # node_removal after the loop.
            is_crash = valid & (ev_k == EV_NODE_CRASH)
            is_recover = valid & (ev_k == EV_NODE_RECOVER)
            is_cn = is_cn | is_recover
        # Queue sequence numbers follow slab (== emission) order, continuing
        # across chunks via the running n_creates.
        create_rank = jnp.cumsum(is_cp, axis=1, dtype=jnp.int32) - 1
        ev_seq = state.queue_seq_counter[:, None] + n_creates[:, None] + create_rank

        if use_event_kernel:
            # One Pallas call replaces the five (C, E)-indexed scatters
            # below (~5 ms/window at dense shapes; scatter cost is
            # per-index on TPU).
            created, node_removal, pod_create, pod_create_seq, pod_removal = (
                event_core(
                    ev_k, ev_s, ev_rel, ev_seq, valid,
                    created, node_removal, pod_create, pod_create_seq,
                    pod_removal,
                )
            )
        else:
            # Scatter helpers: out-of-range slot drops the write. Node
            # accumulators are lane-major under lane_major — the scatter
            # indices swap axes ((slot, cluster) pairs), same index count.
            def drop_slot(mask, width):
                return jnp.where(mask, ev_s, width)

            def n_scatter(acc, mask, op, values=None):
                idx = (
                    (drop_slot(mask, N), rows)
                    if lane_major
                    else (rows, drop_slot(mask, N))
                )
                ref = acc.at[idx[0], idx[1]]
                if values is None:
                    return ref.set(True, mode="drop")
                return getattr(ref, op)(values, mode="drop")

            created = n_scatter(created, is_cn, "set")
            node_removal = n_scatter(
                node_removal, is_rn, "min",
                jnp.where(is_rn, ev_rel, f32inf),
            )
            pod_create = pod_create.at[rows, drop_slot(is_cp, P)].min(
                jnp.where(is_cp, ev_rel, f32inf), mode="drop"
            )
            pod_create_seq = pod_create_seq.at[rows, drop_slot(is_cp, P)].max(
                jnp.where(is_cp, ev_seq, 0), mode="drop"
            )
            pod_removal = pod_removal.at[rows, drop_slot(is_rp, P)].min(
                jnp.where(is_rp, ev_rel, f32inf), mode="drop"
            )
        out = (
            cursor + valid.sum(axis=1, dtype=jnp.int32),
            created,
            node_removal,
            pod_create,
            pod_create_seq,
            pod_removal,
            n_creates + is_cp.sum(axis=1, dtype=jnp.int32),
        )
        if conditional_move:
            # Node-add times feed the per-event wake scans (scalar
            # on_add_node_to_cache runs once PER node at its visibility
            # time; _conditional_wake_exact). Only built on the
            # conditional-move path — an extra (C, N) scatter otherwise.
            node_create_rel = n_scatter_min(
                node_create_rel, is_cn, ev_s,
                jnp.where(is_cn, ev_rel, f32inf),
            )
            out = out + (node_create_rel,)
        if node_faults:
            crash_rm = n_scatter_min(
                crash_rm, is_crash, ev_s,
                jnp.where(is_crash, ev_rel, f32inf),
            )
            out = out + (
                crash_rm,
                n_recover + is_recover.sum(axis=1, dtype=jnp.int32),
            )
        return out

    def n_scatter_min(acc, mask, ev_s, values):
        tgt = jnp.where(mask, ev_s, N)
        if lane_major:
            return acc.at[tgt, rows].min(values, mode="drop")
        return acc.at[rows, tgt].min(values, mode="drop")

    carry0 = (
        state.event_cursor,
        jnp.zeros(n_shape, bool),
        jnp.full(n_shape, INF, jnp.float32),
        jnp.full((C, P), INF, jnp.float32),
        jnp.zeros((C, P), jnp.int32),
        jnp.full((C, P), INF, jnp.float32),
        jnp.zeros((C,), jnp.int32),
    )
    if conditional_move:
        carry0 = carry0 + (jnp.full(n_shape, INF, jnp.float32),)
    if node_faults:
        carry0 = carry0 + (
            jnp.full(n_shape, INF, jnp.float32),
            jnp.zeros((C,), jnp.int32),
        )
    carry_out = jax.lax.while_loop(chunk_cond, chunk_body, carry0)
    (event_cursor, created, node_removal, pod_create, pod_create_seq,
     pod_removal, n_creates) = carry_out[:7]
    tail = 7
    node_create_rel = None
    if conditional_move:
        node_create_rel = carry_out[tail]
        tail += 1
    if node_faults:
        crash_rm, n_recover = carry_out[tail], carry_out[tail + 1]
        crashed_now = crash_rm < f32inf
        metrics = metrics._replace(
            node_crashes=metrics.node_crashes
            + crashed_now.sum(axis=n_sum_ax, dtype=jnp.int32),
            node_recoveries=metrics.node_recoveries + n_recover,
            # Downtime = the crash's pre-sampled repair span (each slot
            # crashes at most once; recovery opens a fresh slot).
            # crash_downtime is a hot leaf, so it shares crashed_now's
            # layout either way.
            node_downtime_s=metrics.node_downtime_s
            + jnp.where(crashed_now, nodes.crash_downtime, 0.0).sum(
                axis=n_sum_ax
            ),
        )
        node_removal = jnp.minimum(node_removal, crash_rm)

    def to_nmaj(x):
        """Row-major (C, N) mask/value -> the node accumulators' layout."""
        return x.T if lane_major else x

    # Pending autoscaler creations due this window (CA scale-up effects).
    # The pending pairs stay row-major (see state.NODE_HOT_LEAVES): their
    # masks/values compute row-major — where the t_where writebacks need
    # them — and transpose once to merge with the lane-major accumulators.
    alive_row = nodes.alive.T if lane_major else nodes.alive
    pend_create_row = (nodes.create_time.win < W[:, None]) & ~alive_row
    created = created | to_nmaj(pend_create_row)
    if conditional_move:
        node_create_rel = jnp.minimum(
            node_create_rel,
            to_nmaj(
                jnp.where(
                    pend_create_row,
                    _rel_seconds(nodes.create_time, base[:, None], interval),
                    f32inf,
                )
            ),
        )
    node_create_time = t_where(
        pend_create_row, t_inf((C, N)), nodes.create_time
    )
    # Pending autoscaler removals due this window (CA scale-down effects).
    pend_rm_due = nodes.remove_time.win < W[:, None]
    pend_remove = jnp.where(
        pend_rm_due, _rel_seconds(nodes.remove_time, base[:, None], interval), f32inf
    )
    node_removal = jnp.minimum(node_removal, to_nmaj(pend_remove))
    node_remove_time = t_where(pend_rm_due, t_inf((C, N)), nodes.remove_time)
    # Pending HPA scale-down removals due this window.
    pend_prm_due = pods.removal_time.win < W[:, None]
    pend_pod_removal = jnp.where(
        pend_prm_due, _rel_seconds(pods.removal_time, base[:, None], interval), f32inf
    )
    pod_removal = jnp.minimum(pod_removal, pend_pod_removal)
    pod_removal_time = t_where(pend_prm_due, t_inf((C, P)), pods.removal_time)

    # --- apply creations ----------------------------------------------------
    alive = nodes.alive | created
    alloc_cpu = jnp.where(created, nodes.cap_cpu, nodes.alloc_cpu)
    alloc_ram = jnp.where(created, nodes.cap_ram, nodes.alloc_ram)

    was_empty_created = (pods.phase == 0) & (pod_create < f32inf)
    enqueue_ts = t_norm(
        jnp.broadcast_to(base[:, None], (C, P)),
        jnp.where(was_empty_created, pod_create, 0.0)
        + jnp.float32(consts.delta_pod_enqueue),
        interval,
    )
    phase = jnp.where(was_empty_created, PHASE_QUEUED, pods.phase)
    queue_ts = t_where(was_empty_created, enqueue_ts, pods.queue_ts)
    queue_seq = jnp.where(was_empty_created, pod_create_seq, pods.queue_seq)
    initial_attempt_ts = t_where(
        was_empty_created, enqueue_ts, pods.initial_attempt_ts
    )
    attempts = jnp.where(was_empty_created, 1, pods.attempts)

    # --- resolve running pods: finish vs node removal vs pod removal --------
    running = phase == PHASE_RUNNING
    node_idx = jnp.clip(pods.node, 0, None)

    def n_gather(acc):
        """(C, P) per-pod gather from a node-layout accumulator: result
        [c, p] = acc[node_idx[c, p]] of cluster c — index pairs swap axes
        under lane-major, same index count."""
        if lane_major:
            return acc[node_idx, rows]
        return acc[rows, node_idx]

    # The per-pod node-removal gather is a (C, P)-indexed op — one of the two
    # most expensive ops in the step — and most windows remove no node at
    # all; branch around it (the predicate reduction is replicated, so the
    # cond also holds under a C-sharded mesh).
    pod_node_removal = jax.lax.cond(
        (node_removal < f32inf).any(),
        lambda: jnp.where(pods.node >= 0, n_gather(node_removal), f32inf),
        lambda: jnp.full((C, P), INF, jnp.float32),
    )
    # Earliest interruption of this pod in rel-seconds; +inf = none.
    interrupt = jnp.minimum(pod_node_removal, pod_removal)
    has_interrupt = interrupt < f32inf
    # cutoff = min(window_end, interruption): window_end is the pair (W, 0),
    # an interruption the pair (base, interrupt); compare the pod's finish
    # pair against whichever applies.
    cut = t_norm(
        jnp.where(has_interrupt, base[:, None], W[:, None]),
        jnp.where(has_interrupt, interrupt, 0.0),
        interval,
    )
    finishes = running & t_le(pods.finish_time, cut)
    interrupted = running & ~finishes & has_interrupt
    rescheds = interrupted & (pod_node_removal < pod_removal)
    removed_running = interrupted & (pod_removal <= pod_node_removal)

    # Chaos: split completions into real finishes and failing attempts
    # (will_fail drawn at commit; finish_time IS the fail time). Both free
    # their resources through the shared `freed` path below; only real
    # finishes count succeeded/duration stats.
    if pod_faults:
        fails = finishes & pods.will_fail
        real_fin = finishes & ~pods.will_fail
    else:
        fails = None
        real_fin = finishes

    if node_faults:
        # Crash-caused reschedules (the interruption metric): the pod's
        # earliest node removal came from a crash (ties attribute to the
        # crash, matching the scalar chain where the crash IS the removal).
        pod_crash_rm = jax.lax.cond(
            crashed_now.any(),
            lambda: jnp.where(pods.node >= 0, n_gather(crash_rm), f32inf),
            lambda: jnp.full((C, P), INF, jnp.float32),
        )
        crash_caused = rescheds & (pod_crash_rm <= pod_node_removal)
        metrics = metrics._replace(
            pod_interruptions=metrics.pod_interruptions
            + crash_caused.sum(axis=1, dtype=jnp.int32)
        )

    # Free resources of finished and removed-while-running pods (a dead node's
    # allocatable is irrelevant; slots are never reused). A straight
    # (C, P)-indexed scatter is the single most expensive op in the step
    # (measured 27 ms/window at 1024x256), and only a handful of pods free
    # per window. Preferred: the Pallas free kernel (per-lane iterated
    # extraction + node one-hot adds, early exit at the deepest lane's freed
    # count — integer adds commute, so it is bit-identical). Fallback:
    # compact up to F freed pods per round with top_k and scatter F-sized
    # chunks — correct everywhere, but each round's lax.top_k lowers to a
    # full (C, P) sort on TPU (~4 ms/window at dense shapes).
    freed = finishes | removed_running
    from kubernetriks_tpu.ops.scheduler_kernel import (
        free_kernel_fits,
        fused_free_resources,
    )

    duration_s = t_seconds_f32(pods.duration, interval)
    dur_stats = None
    if use_pallas and use_pallas_select and free_kernel_fits(N, P):
        core = partial(
            fused_free_resources,
            interpret=pallas_interpret,
            nodes_lane_major=lane_major,
        )
        if pallas_mesh is not None:
            core = _shard_rowwise(core, 8, 3, pallas_mesh, pallas_axis)
        # The kernel also folds the finished pods' duration-estimator
        # samples (count/total/total_sq/min/max), replacing the five
        # (C, P) masked reductions below.
        alloc_cpu, alloc_ram, dur_stats = core(
            freed, pods.node, pods.req_cpu, pods.req_ram,
            real_fin, duration_s, alloc_cpu, alloc_ram,
        )
    else:
        F = min(P, 32)  # freed-compaction chunk width (independent of E)

        def free_cond(carry):
            return carry[0].any()

        def free_body(carry):
            pending, acpu, aram = carry
            _, idx = jax.lax.top_k(pending.astype(jnp.int32), F)
            fv = pending[rows, idx]
            tgt = jnp.where(fv, node_idx[rows, idx], N)
            add_cpu = jnp.where(fv, pods.req_cpu[rows, idx], 0)
            add_ram = jnp.where(fv, pods.req_ram[rows, idx], 0)
            if lane_major:
                acpu = acpu.at[tgt, rows].add(add_cpu, mode="drop")
                aram = aram.at[tgt, rows].add(add_ram, mode="drop")
            else:
                acpu = acpu.at[rows, tgt].add(add_cpu, mode="drop")
                aram = aram.at[rows, tgt].add(add_ram, mode="drop")
            pending = pending.at[rows, jnp.where(fv, idx, P)].set(False, mode="drop")
            return (pending, acpu, aram)

        _, alloc_cpu, alloc_ram = jax.lax.while_loop(
            free_cond, free_body, (freed, alloc_cpu, alloc_ram)
        )

    # Finished pods.
    if dur_stats is not None:
        n_done = dur_stats[:, 0].astype(jnp.int32)
        est = metrics.pod_duration
        pod_duration_est = EstArrays(
            count=est.count + n_done,
            total=est.total + dur_stats[:, 1],
            total_sq=est.total_sq + dur_stats[:, 2],
            minimum=jnp.minimum(est.minimum, dur_stats[:, 3]),
            maximum=jnp.maximum(est.maximum, dur_stats[:, 4]),
        )
    else:
        n_done = real_fin.sum(axis=1, dtype=jnp.int32)
        pod_duration_est = _est_add_reduced(
            metrics.pod_duration, duration_s, real_fin
        )
    metrics = metrics._replace(
        pods_succeeded=metrics.pods_succeeded + n_done,
        terminated_pods=metrics.terminated_pods + n_done,
        pod_duration=pod_duration_est,
        processed_nodes=metrics.processed_nodes
        + created.sum(axis=n_sum_ax, dtype=jnp.int32),
    )
    phase = jnp.where(real_fin, PHASE_SUCCEEDED, phase)
    finish_time = t_where(finishes, t_inf((C, P)), pods.finish_time)

    # Reschedule pods of removed nodes (reference: scheduler.rs:336-364).
    # Queue order among same-window rescheds must match the scalar's event
    # order: removal visibility time first, then — for same-time removals —
    # the order the removal requests were EMITTED (the CA walks scale-down
    # candidates in node-name order), then sorted pod names within a node.
    # Name ranks come from the autoscale statics when available; slot order
    # is the fallback (equal keys keep slot order under the stable sort).
    def _resched_rank_exact():
        big = jnp.int32(1 << 30)
        node_c2 = jnp.clip(pods.node, 0, N - 1)
        if node_key_fn is not None:
            # Slot reclaim: removed CA nodes order by their occupants'
            # CURRENT names (allocation-index keys, autoscale.ca_name_order)
            # — the static table describes the slots' first occupants.
            nr = node_key_fn()[jnp.arange(C, dtype=jnp.int32)[:, None], node_c2]
        elif node_name_rank is not None:
            nr = node_name_rank[jnp.arange(C, dtype=jnp.int32)[:, None], node_c2]
        else:
            nr = node_c2
        k1 = jnp.where(rescheds, pod_node_removal, f32inf)
        k2 = jnp.where(rescheds, nr, big)
        if pod_name_rank is not None:
            k3 = jnp.where(rescheds, pod_name_rank, big)
        else:
            k3 = jnp.zeros((C, P), jnp.int32)
        return _stable_queue_rank((k1, k2, k3))

    resched_rank = jax.lax.cond(
        rescheds.any(),
        _resched_rank_exact,
        lambda: jnp.cumsum(rescheds, axis=1, dtype=jnp.int32) - 1,
    )
    resched_ts = t_norm(
        jnp.broadcast_to(base[:, None], (C, P)),
        jnp.where(rescheds, pod_node_removal, 0.0)
        + jnp.float32(consts.delta_reschedule),
        interval,
    )
    phase = jnp.where(rescheds, PHASE_QUEUED, phase)
    queue_ts = t_where(rescheds, resched_ts, queue_ts)
    queue_seq = jnp.where(
        rescheds, state.queue_seq_counter[:, None] + n_creates[:, None] + resched_rank,
        queue_seq,
    )
    initial_attempt_ts = t_where(rescheds, resched_ts, initial_attempt_ts)
    attempts = jnp.where(rescheds, 1, attempts)
    finish_time = t_where(rescheds, t_inf((C, P)), finish_time)
    pod_node = jnp.where(rescheds, -1, pods.node)
    n_rescheds = rescheds.sum(axis=1, dtype=jnp.int32)

    # Chaos: dispose of failing attempts — CrashLoopBackOff retry (requeue
    # at fail + min(base * 2^k, cap), fresh initial-attempt timestamp,
    # mirroring the scalar RequeuePodAfterBackoff delivery) or permanent
    # failure past the restart limit (terminal PHASE_FAILED).
    restarts_arr = pods.restarts
    will_fail_arr = pods.will_fail
    n_fail_retries = jnp.zeros_like(n_rescheds)
    if pod_faults:
        new_restarts = pods.restarts + 1
        retry = fails & (new_restarts <= jnp.int32(fault_params.restart_limit))
        perma = fails & ~retry
        fail_rel = _rel_seconds(pods.finish_time, base[:, None], interval)
        backoff = jnp.minimum(
            jnp.float32(fault_params.backoff_base)
            * jnp.exp2(pods.restarts.astype(jnp.float32)),
            jnp.float32(fault_params.backoff_cap),
        )
        # The retry cannot enter the queue before the failure itself reaches
        # the scheduler (node -> api server -> storage -> scheduler — the
        # same chain as a node-removal reschedule), so a backoff shorter
        # than that delay is floored at it, like the scalar delivery.
        retry_ts = t_norm(
            jnp.broadcast_to(base[:, None], (C, P)),
            jnp.where(
                retry,
                fail_rel
                + jnp.maximum(backoff, jnp.float32(consts.delta_reschedule)),
                0.0,
            ),
            interval,
        )

        def _fail_rank_exact():
            # Seq ranks among this window's retries follow the scalar's
            # failure-event order: fail time, then pod name (slot order as
            # the rank-less fallback, kept by the stable sort).
            big = jnp.int32(1 << 30)
            k1 = jnp.where(retry, fail_rel, f32inf)
            if pod_name_rank is not None:
                k2 = jnp.where(retry, pod_name_rank, big)
            else:
                k2 = jnp.zeros((C, P), jnp.int32)
            return _stable_queue_rank((k1, k2))

        fail_rank = jax.lax.cond(
            retry.any(),
            _fail_rank_exact,
            lambda: jnp.cumsum(retry, axis=1, dtype=jnp.int32) - 1,
        )
        phase = jnp.where(
            retry,
            PHASE_QUEUED,
            jnp.where(perma, PHASE_FAILED, phase),
        )
        queue_ts = t_where(retry, retry_ts, queue_ts)
        queue_seq = jnp.where(
            retry,
            state.queue_seq_counter[:, None]
            + n_creates[:, None]
            + n_rescheds[:, None]
            + fail_rank,
            queue_seq,
        )
        initial_attempt_ts = t_where(retry, retry_ts, initial_attempt_ts)
        attempts = jnp.where(retry, 1, attempts)
        pod_node = jnp.where(fails, -1, pod_node)
        restarts_arr = jnp.where(fails, new_restarts, pods.restarts)
        will_fail_arr = jnp.where(fails, False, pods.will_fail)
        n_fail_retries = retry.sum(axis=1, dtype=jnp.int32)
        n_perma = perma.sum(axis=1, dtype=jnp.int32)
        metrics = metrics._replace(
            pod_restarts=metrics.pod_restarts + n_fail_retries,
            pods_failed=metrics.pods_failed + n_perma,
            terminated_pods=metrics.terminated_pods + n_perma,
        )

    # Removed-while-running pods terminate as removed
    # (reference: api_server.rs PodRemovedFromNode removed=true accounting).
    n_removed_running = removed_running.sum(axis=1, dtype=jnp.int32)
    metrics = metrics._replace(
        pods_removed=metrics.pods_removed + n_removed_running,
        terminated_pods=metrics.terminated_pods + n_removed_running,
    )
    phase = jnp.where(removed_running, PHASE_REMOVED, phase)
    finish_time = t_where(removed_running, t_inf((C, P)), finish_time)

    # Removal of queued/unschedulable (or just-created) pods: dropped from the
    # queues with NO removed/terminated metrics (scalar parity: only
    # PodRemovedFromNode(removed=true) counts, reference: api_server.rs:345-368).
    removed_queued = (
        ((phase == PHASE_QUEUED) | (phase == PHASE_UNSCHEDULABLE))
        & (pod_removal < f32inf)
        & ~removed_running
    )
    phase = jnp.where(removed_queued, PHASE_REMOVED, phase)

    # Kill removed nodes AFTER pod resolution (resolution reads pre-window
    # alive only via pods.node indices, which is removal-independent).
    alive = alive & ~(node_removal < f32inf)

    any_created_node = created.any(axis=n_sum_ax)
    any_freed = (n_done > 0) | (n_removed_running > 0)
    if pod_faults:
        # Failing attempts free their resources too (scalar: the failure
        # handler wakes the unschedulable queue like a finish).
        any_freed = any_freed | fails.any(axis=1)

    # Conditional-move wake events (consumed by prepare_cycle's per-event
    # wake scans when enable_unscheduled_pods_conditional_move is on;
    # _conditional_wake_exact replays the scalar's one-scan-per-event
    # semantics): a new node contributes its full allocatable (= capacity at
    # creation, scheduler.rs:393), a finished/removed pod its freed requests
    # (scheduler.rs:366-380). Only built on the conditional-move path.
    if conditional_move:
        node_rel = jnp.where(created, node_create_rel, f32inf)
        wake_events = WakeEvents(
            # WakeEvents is row-major by contract (its consumer concatenates
            # the node and pod axes); transpose the lane-major accumulators
            # once here — conditional-move runs only.
            node_mask=created.T if lane_major else created,
            node_rel=node_rel.T if lane_major else node_rel,
            freed_mask=freed,
            freed_rel=jnp.where(
                finishes,
                _rel_seconds(pods.finish_time, base[:, None], interval),
                jnp.where(removed_running, pod_removal, f32inf),
            ),
        )
    else:
        wake_events = None

    new_state = state._replace(
        nodes=nodes._replace(
            alive=alive,
            alloc_cpu=alloc_cpu,
            alloc_ram=alloc_ram,
            create_time=node_create_time,
            remove_time=node_remove_time,
        ),
        pods=pods._replace(
            phase=phase,
            queue_ts=queue_ts,
            queue_seq=queue_seq,
            initial_attempt_ts=initial_attempt_ts,
            attempts=attempts,
            node=pod_node,
            finish_time=finish_time,
            removal_time=pod_removal_time,
            restarts=restarts_arr,
            will_fail=will_fail_arr,
        ),
        metrics=metrics,
        event_cursor=event_cursor,
        queue_seq_counter=state.queue_seq_counter
        + n_creates
        + n_rescheds
        + n_fail_retries,
        # Events of interest wake the unschedulable queue (flush-all policy,
        # reference: scheduler.rs:391-410,435-440,445-473).
        requeue_signal=state.requeue_signal | any_created_node | any_freed,
        time=jnp.maximum(state.time, W),
    )
    return new_state, wake_events


class WakeEvents(NamedTuple):
    """This window's conditional-move wake events (intra-window lifetime:
    built by _apply_window_events, consumed by the same window's
    prepare_cycle). Rel times are float32 seconds from the window base."""

    node_mask: jnp.ndarray  # (C, N) nodes created this window
    node_rel: jnp.ndarray  # (C, N) creation effect rel seconds; +inf pad
    freed_mask: jnp.ndarray  # (C, P) pods freed (finish/removal)
    freed_rel: jnp.ndarray  # (C, P) free effect rel seconds; +inf pad


def _conditional_wake_exact(
    state: ClusterBatchState,
    pods,
    stale: jnp.ndarray,
    wake: "WakeEvents",
    lane_major: bool = False,
) -> jnp.ndarray:
    """Resource-aware unschedulable wakes for
    enable_unscheduled_pods_conditional_move, replicating the reference's
    one-greedy-scan-PER-EVENT semantics exactly: each node-add / freed event
    runs its own budget scan over the unschedulable queue in (insert_ts,
    name) order — here (queue_ts, queue_seq; park timestamps are distinct
    within a cycle, so seq ties cannot occur) — at the event's effect time,
    with pods moved by earlier events absent from later scans and pods
    parked after an event's time invisible to it:

    - Node added (reference: src/core/scheduler/scheduler.rs:391-409):
      budget = the new node's allocatable (= capacity); a pod that FITS
      consumes the budget and STAYS parked; a pod that does not fit moves to
      the active queue. (That inverted sense is the reference's actual
      behavior; preserved as-is.)
    - Resources freed by pod finish/removal (scheduler.rs:366-380,435-439,
      462-468): budget = that pod's freed requests; greedy first-fit — a pod
      that fits consumes the budget and MOVES.

    Cost: one P-length scan per wake event, gated to windows that have
    events and parked pods (rare outside contended conditional-move runs).
    """
    C, P = pods.phase.shape
    N = wake.node_mask.shape[1]
    rows = jnp.arange(C, dtype=jnp.int32)[:, None]
    rows1 = jnp.arange(C, dtype=jnp.int32)
    unsched = (pods.phase == PHASE_UNSCHEDULABLE) & ~stale

    u_t = t_where(unsched, pods.queue_ts, t_inf((C, P)))
    u_seq = jnp.where(unsched, pods.queue_seq, jnp.iinfo(jnp.int32).max)
    order = lexsort_time_i32(u_t, u_seq)  # (C, P) unschedulable first
    o_valid = unsched[rows, order]
    o_req_cpu = pods.req_cpu[rows, order]
    o_req_ram = pods.req_ram[rows, order]
    # (No park-time-vs-event-time gate: every parked pod present at this
    # window's prepare was parked microseconds after a PREVIOUS window
    # boundary, so it predates all of this window's events except
    # sub-microsecond pathologies.)

    # Combined event axis (N node slots + P pod slots), sorted by effect
    # time (stable; same-time events keep node-before-freed slab order —
    # same-timestamp interleavings are FIFO in the scalar queue and the
    # trace compiler emits creates before the finishes they enable).
    f32inf = jnp.float32(INF)
    ev_rel = jnp.concatenate([wake.node_rel, wake.freed_rel], axis=1)
    ev_valid = jnp.concatenate([wake.node_mask, wake.freed_mask], axis=1)
    ev_is_node = jnp.concatenate(
        [jnp.ones((C, N), bool), jnp.zeros((C, P), bool)], axis=1
    )
    cap_cpu = state.nodes.cap_cpu.T if lane_major else state.nodes.cap_cpu
    cap_ram = state.nodes.cap_ram.T if lane_major else state.nodes.cap_ram
    ev_cpu = jnp.concatenate([cap_cpu, pods.req_cpu], axis=1)
    ev_ram = jnp.concatenate([cap_ram, pods.req_ram], axis=1)
    key = jnp.where(ev_valid, ev_rel, f32inf)
    _, s_valid, s_is_node, s_cpu, s_ram = jax.lax.sort(
        (key, ev_valid, ev_is_node, ev_cpu, ev_ram),
        dimension=1, num_keys=1, is_stable=True,
    )
    n_ev = jnp.max(ev_valid.sum(axis=1, dtype=jnp.int32))

    def ev_body(carry):
        e, moved = carry
        v_valid = jax.lax.dynamic_index_in_dim(s_valid, e, 1, keepdims=False)
        v_is_node = jax.lax.dynamic_index_in_dim(s_is_node, e, 1, keepdims=False)
        v_cpu = jax.lax.dynamic_index_in_dim(s_cpu, e, 1, keepdims=False)
        v_ram = jax.lax.dynamic_index_in_dim(s_ram, e, 1, keepdims=False)

        def pod_scan(c2, xs):
            bud_cpu, bud_ram = c2
            p_valid, rcpu, rram, m = xs
            considered = p_valid & ~m & v_valid
            fits = considered & (rcpu <= bud_cpu) & (rram <= bud_ram)
            bud_cpu = bud_cpu - jnp.where(fits, rcpu, 0)
            bud_ram = bud_ram - jnp.where(fits, rram, 0)
            mv = jnp.where(v_is_node, considered & ~fits, fits)
            return (bud_cpu, bud_ram), mv

        (_, _), mv_sorted = jax.lax.scan(
            pod_scan,
            (v_cpu, v_ram),
            (o_valid.T, o_req_cpu.T, o_req_ram.T, moved.T),
        )
        return e + jnp.int32(1), moved | mv_sorted.T

    _, moved_sorted = jax.lax.while_loop(
        lambda carry: carry[0] < n_ev,
        ev_body,
        (jnp.int32(0), jnp.zeros((C, P), bool)),
    )
    # Scatter sorted-order decisions back to slot positions.
    return jnp.zeros((C, P), bool).at[rows, order].set(moved_sorted)


class CycleCandidates(NamedTuple):
    """Compacted per-cycle scheduling candidates (top-K of the sorted queue);
    a pytree, so it composes with jit/scan like the rest of the state."""

    pods: "object"  # PodArrays with wake/flush moves applied
    last_flush_win: jnp.ndarray
    cand: jnp.ndarray  # (C, K) pod slots in queue order
    valid: jnp.ndarray  # (C, K)
    req_cpu: jnp.ndarray
    req_ram: jnp.ndarray
    # (C, K) float32 queue wait at cycle start: T - initial_attempt_ts.
    waited: jnp.ndarray


def cycle_timing(valid, waited, pod_sched_time, consts: StepConstants):
    """(C, K) per-candidate timing mechanics, computed in one vectorized
    shot: the simulated cycle duration is a prefix sum over the (static per
    cycle) candidate mask — pod k's assignment effect time includes the
    algorithm latency of pods 0..k (reference: scheduler.rs:270-320) — so no
    sequential scan is needed. Shared by the lax.scan, Pallas and RL paths;
    a single source is what keeps them bit-for-bit aligned.

    Returns (pod_queue_time (C,K), start_s (C,K), park_s (C,K)) — the
    latter two as float32 second offsets relative to the cycle time T."""
    step_dur = jnp.where(valid, pod_sched_time[:, None], 0.0)
    cd_post = jnp.cumsum(step_dur, axis=1)
    pod_queue_time = waited + (cd_post - step_dur)
    start_s = cd_post + jnp.float32(consts.delta_bind_start)
    # Unschedulable park: new insert timestamp = T + cycle duration
    # (reference: scheduler.rs:282-306).
    park_s = cd_post
    return pod_queue_time, start_s, park_s


def decision_metrics(metrics, assign_k, pod_queue_time_k, pod_sched_time):
    """Fold one cycle's decisions into the (C,) metric accumulators
    (reference counters/estimators: scheduler.rs:322-329)."""
    C, K = assign_k.shape
    return metrics._replace(
        scheduling_decisions=metrics.scheduling_decisions
        + assign_k.sum(axis=1, dtype=jnp.int32),
        queue_time=_est_add_reduced(metrics.queue_time, pod_queue_time_k, assign_k),
        algo_latency=_est_add_reduced(
            metrics.algo_latency,
            jnp.broadcast_to(pod_sched_time[:, None], (C, K)),
            assign_k,
        ),
    )


def prepare_queue(
    state: ClusterBatchState,
    W: jnp.ndarray,
    consts: StepConstants,
    conditional_move: bool = False,
    wake=None,
    lane_major: bool = False,
):
    """Queue preamble shared by every cycle path (sorted-scan, Pallas
    candidate kernel, Pallas selection kernel, RL): unschedulable wake/flush
    moves and the eligibility mask. Returns (pods with moves applied,
    last_flush_win, eligible (C, P))."""
    C, P = state.pods.phase.shape
    pods = state.pods
    interval = jnp.float32(consts.scheduling_interval)
    Tpair = TPair(
        win=jnp.broadcast_to(W[:, None], (C, P)),
        off=jnp.zeros((C, P), jnp.float32),
    )

    # Unschedulable-leftover flush at the 30 s cadence
    # (reference: scheduler.rs:188-203).
    flush_now = (W - state.last_flush_win).astype(jnp.float32) * interval >= jnp.float32(
        consts.flush_interval
    )

    def wake_block():
        # Stale: T - queue_ts > max_stay, i.e. queue_ts + max_stay < T.
        stay_cut = t_norm(
            pods.queue_ts.win,
            pods.queue_ts.off + jnp.float32(consts.max_unschedulable_stay),
            interval,
        )
        stale = (
            (pods.phase == PHASE_UNSCHEDULABLE)
            & t_lt(stay_cut, Tpair)
            & flush_now[:, None]
        )
        if conditional_move:
            assert wake is not None, (
                "conditional_move prepare needs this window's WakeEvents"
            )
            moves = _conditional_wake_exact(
                state, pods, stale, wake, lane_major=lane_major
            )
        else:
            moves = state.requeue_signal[:, None] & (
                pods.phase == PHASE_UNSCHEDULABLE
            )
        to_move = stale | moves
        return (
            jnp.where(to_move, PHASE_QUEUED, pods.phase),
            pods.attempts + to_move.astype(jnp.int32),
        )

    # No parked pod anywhere -> nothing to wake or flush; skip the whole
    # (C, P) block (common case on uncontended batches).
    phase2, attempts2 = jax.lax.cond(
        (pods.phase == PHASE_UNSCHEDULABLE).any(),
        wake_block,
        lambda: (pods.phase, pods.attempts),
    )
    pods = pods._replace(phase=phase2, attempts=attempts2)
    last_flush_win = jnp.where(flush_now, W, state.last_flush_win)

    # Eligible = queued strictly before T — with pair times that is exactly
    # queue_ts.win < W.
    eligible = (pods.phase == PHASE_QUEUED) & (pods.queue_ts.win < W[:, None])
    return pods, last_flush_win, eligible


def candidates_from_slots(
    pods,
    last_flush_win: jnp.ndarray,
    cand: jnp.ndarray,
    valid: jnp.ndarray,
    W: jnp.ndarray,
    consts: StepConstants,
) -> CycleCandidates:
    """Assemble CycleCandidates from chosen candidate slots — the gathers
    and the `waited` formula shared by the sorted path and the in-kernel
    selection path (ONE definition, so the paths cannot drift)."""
    C = cand.shape[0]
    rows = jnp.arange(C, dtype=jnp.int32)[:, None]
    interval = jnp.float32(consts.scheduling_interval)
    init_win = pods.initial_attempt_ts.win[rows, cand]
    init_off = pods.initial_attempt_ts.off[rows, cand]
    waited = (W[:, None] - init_win).astype(jnp.float32) * interval - init_off
    return CycleCandidates(
        pods=pods,
        last_flush_win=last_flush_win,
        cand=cand,
        valid=valid,
        req_cpu=pods.req_cpu[rows, cand],
        req_ram=pods.req_ram[rows, cand],
        waited=waited,
    )


def prepare_cycle(
    state: ClusterBatchState,
    W: jnp.ndarray,
    consts: StepConstants,
    K: int,
    conditional_move: bool = False,
    wake=None,
    lane_major: bool = False,
) -> CycleCandidates:
    """prepare_queue + queue sort + top-K compaction. W: (C,) int32 window
    index (cycle time T = W * interval)."""
    C, P = state.pods.phase.shape
    rows = jnp.arange(C, dtype=jnp.int32)[:, None]
    pods, last_flush_win, eligible = prepare_queue(
        state, W, consts, conditional_move, wake, lane_major=lane_major
    )

    # Queue order: (queue_ts, queue_seq).
    sort_t = t_where(eligible, pods.queue_ts, t_inf((C, P)))
    sort_seq = jnp.where(eligible, pods.queue_seq, jnp.iinfo(jnp.int32).max)
    order = lexsort_time_i32(sort_t, sort_seq)  # (C, P)

    cand = order[:, :K]
    return candidates_from_slots(
        pods, last_flush_win, cand, eligible[rows, cand], W, consts
    )



def commit_scattered_tail(
    state: ClusterBatchState,
    pods,
    last_flush_win,
    W: jnp.ndarray,
    consts: StepConstants,
    alloc_cpu,
    alloc_ram,
    metrics,
    phase,
    node,
    start_tmp,
    park_tmp,
    fault_params=None,
) -> ClusterBatchState:
    """Shared bottom half of the decision commit: reconstruct absolute
    start/finish/park pairs from the scattered float32 second offsets
    (+inf = untouched) and write the post-cycle state. Used by commit_cycle
    and by the megakernel path (whose kernel already produced the scattered
    phase/node/start/park arrays).

    With pod faults on, this is ALSO where every new attempt's failure draw
    happens: a counter-PRNG threefry on (seed, cluster, global plain pod
    slot, restarts) — identical bits to the scalar oracle's draw at
    assignment commit — decides whether the attempt fails and at what
    fraction of its duration; a failing attempt's finish_time becomes its
    fail time and will_fail is set for the finish resolution to dispose."""
    C, P = pods.phase.shape
    interval = jnp.float32(consts.scheduling_interval)
    f32inf = jnp.float32(INF)

    started = start_tmp < f32inf
    start_pair = t_norm(
        jnp.broadcast_to(W[:, None], (C, P)),
        jnp.where(started, start_tmp, 0.0),
        interval,
    )
    service = pods.duration.win < 0
    finish_pair = t_add(start_pair, pods.duration, interval)
    start_time = t_where(started, start_pair, pods.start_time)
    finish_val = t_where(service, t_inf((C, P)), finish_pair)
    pods_fault_fields = {}
    if fault_params is not None and fault_params.fail_prob > 0:
        from kubernetriks_tpu import chaos

        idx = jnp.broadcast_to(
            jnp.arange(P, dtype=jnp.int32)[None, :], (C, P)
        )
        # Device layout: [window over plain slots | resident ring tail];
        # plain device slot -> global slot via pod_base, resident via the
        # fixed shift. Only plain trace pods with finite durations draw
        # (ring replicas' identities are runtime-assigned and path-specific).
        plain_width = consts.trace_pod_bound - consts.resident_shift
        in_plain = idx < plain_width
        gslot = idx + jnp.where(
            in_plain, state.pod_base[:, None], jnp.int32(consts.resident_shift)
        )
        if consts.fault_seed is not None:
            # Scenario-vector fleet: per-lane seeds ride as traced (C,)
            # data and the cluster key pins to 0, so a lane's draws are a
            # pure function of its scenario seed — the same keying the
            # scalar oracle uses (PodFaultOracle keys cluster 0), which
            # makes lane placement permutation-invariant (fleet.py).
            seed_key = jnp.asarray(consts.fault_seed, jnp.uint32)[:, None]
            cid = jnp.zeros((C, P), jnp.uint32)
        else:
            seed_key = fault_params.seed
            cid = jnp.broadcast_to(
                jnp.arange(C, dtype=jnp.int32)[:, None], (C, P)
            ).astype(jnp.uint32)
        u_fail, u_frac = chaos.pod_attempt_uniforms(
            seed_key,
            cid,
            gslot.astype(jnp.uint32),
            pods.restarts.astype(jnp.uint32),
            xp=jnp,
        )
        faultable = started & in_plain & (pods.duration.win >= 0)
        wf = faultable & (u_fail < jnp.float32(fault_params.fail_prob))
        dur_s = t_seconds_f32(pods.duration, interval)
        fail_fin = t_norm(
            jnp.broadcast_to(W[:, None], (C, P)),
            jnp.where(wf, start_tmp + u_frac * dur_s, 0.0),
            interval,
        )
        finish_val = t_where(wf, fail_fin, finish_val)
        pods_fault_fields["will_fail"] = jnp.where(
            started, wf, pods.will_fail
        )
    finish_time = t_where(started, finish_val, pods.finish_time)
    parked = park_tmp < f32inf
    park_pair = t_norm(
        jnp.broadcast_to(W[:, None], (C, P)),
        jnp.where(parked, park_tmp, 0.0),
        interval,
    )
    queue_ts = t_where(parked, park_pair, pods.queue_ts)

    return state._replace(
        nodes=state.nodes._replace(alloc_cpu=alloc_cpu, alloc_ram=alloc_ram),
        pods=pods._replace(
            phase=phase,
            queue_ts=queue_ts,
            node=node,
            start_time=start_time,
            finish_time=finish_time,
            **pods_fault_fields,
        ),
        metrics=metrics,
        requeue_signal=jnp.zeros_like(state.requeue_signal),
        last_flush_win=last_flush_win,
        time=jnp.maximum(state.time, W),
    )


def commit_cycle(
    state: ClusterBatchState,
    cc: CycleCandidates,
    W: jnp.ndarray,
    consts: StepConstants,
    alloc_cpu,
    alloc_ram,
    metrics,
    assign_k,
    park_k,
    best_k,
    start_s_k,
    park_s_k,
    use_pallas: bool = False,
    pallas_interpret: bool = False,
    pallas_mesh=None,
    pallas_axis: str = "clusters",
    fault_params=None,
) -> ClusterBatchState:
    """Scatter the K per-cluster decisions back into (C, P) state.

    start_s_k / park_s_k are float32 second offsets relative to the cycle
    time T = W * interval; the absolute start/finish/park pairs are
    reconstructed elementwise after two cheap float32 scatters (64-bit value
    scatters are the slow path on TPU). With use_pallas, the four
    (C, K)-indexed scatters run as one Pallas one-hot kernel instead
    (ops/scheduler_kernel.fused_commit_scatter, bit-identical)."""
    C, P = cc.pods.phase.shape
    rows = jnp.arange(C, dtype=jnp.int32)[:, None]
    pods = cc.pods
    cand = cc.cand
    interval = jnp.float32(consts.scheduling_interval)
    f32inf = jnp.float32(INF)

    from kubernetriks_tpu.ops.scheduler_kernel import (
        commit_kernel_fits,
        fused_commit_scatter,
    )

    if use_pallas and commit_kernel_fits(P, cand.shape[1]):
        core = partial(fused_commit_scatter, interpret=pallas_interpret)
        if pallas_mesh is not None:
            core = _shard_rowwise(core, 8, 4, pallas_mesh, pallas_axis)
        phase, node, start_tmp, park_tmp = core(
            cand, assign_k, park_k, best_k, start_s_k, park_s_k,
            pods.phase, pods.node,
        )
        phase = phase.astype(pods.phase.dtype)
        node = node.astype(pods.node.dtype)
    else:
        new_phase = jnp.where(
            assign_k,
            jnp.int32(PHASE_RUNNING),
            jnp.where(park_k, jnp.int32(PHASE_UNSCHEDULABLE), jnp.int32(-1)),
        ).astype(pods.phase.dtype)
        touched = assign_k | park_k
        phase = pods.phase.at[rows, jnp.where(touched, cand, P)].set(
            jnp.where(touched, new_phase, 0), mode="drop"
        )
        node = pods.node.at[rows, jnp.where(assign_k, cand, P)].set(
            jnp.where(assign_k, best_k, 0), mode="drop"
        )
        start_tmp = (
            jnp.full((C, P), INF, jnp.float32)
            .at[rows, jnp.where(assign_k, cand, P)]
            .set(jnp.where(assign_k, start_s_k, f32inf), mode="drop")
        )
        park_tmp = (
            jnp.full((C, P), INF, jnp.float32)
            .at[rows, jnp.where(park_k, cand, P)]
            .set(jnp.where(park_k, park_s_k, f32inf), mode="drop")
        )

    return commit_scattered_tail(
        state, pods, cc.last_flush_win, W, consts, alloc_cpu, alloc_ram,
        metrics, phase, node, start_tmp, park_tmp,
        fault_params=fault_params,
    )


def _run_scheduling_cycle(
    state: ClusterBatchState,
    W: jnp.ndarray,
    consts: StepConstants,
    max_pods_per_cycle: int,
    use_pallas: bool = False,
    pallas_interpret: bool = False,
    conditional_move: bool = False,
    pallas_mesh=None,
    pallas_axis: str = "clusters",
    use_pallas_select: bool = False,
    wake=None,
    use_megakernel: bool = True,
    fault_params=None,
    lane_major: bool = False,
    profile=None,
) -> ClusterBatchState:
    """One vectorized kube-scheduler cycle at window W for every cluster
    (scalar equivalent: reference scheduler.rs:246-333).

    profile (pipeline.CompiledProfile, static; None = the reference
    default): the compiled scheduler profile whose filter-mask and
    weighted-score expressions the decision core runs — threaded to the
    lax.scan body and every Pallas kernel below, so all four formulations
    of the cycle execute the SAME configured profile (the scalar path's
    composable Filter/Score plugins, lowered; batched/pipeline.py).

    NOTE on a rejected optimization: skipping empty cycles behind a scalar
    lax.cond (predicate: no eligible/parked pod, no wake signal) is exact,
    but measured SLOWER end-to-end — on TPU the cond materializes the full
    state carry through both branches, costing more than the skipped sort.

    lane_major: the hot node leaves are (N, C) — the Pallas wrappers
    consume/return them without transposes (nodes_lane_major); the lax.scan
    fallback converts at its branch boundary (CPU-parity path only).
    """
    from kubernetriks_tpu.batched.pipeline import DEFAULT_PROFILE

    if profile is None:
        profile = DEFAULT_PROFILE
    C, P = state.pods.phase.shape
    N = (
        state.nodes.alive.shape[0]
        if lane_major
        else state.nodes.alive.shape[1]
    )

    alive = state.nodes.alive
    alive_count = alive.sum(
        axis=0 if lane_major else 1, dtype=jnp.int32
    ).astype(jnp.float32)
    pod_sched_time = jnp.float32(consts.time_per_node) * alive_count  # (C,)

    if use_pallas and use_pallas_select and use_megakernel:
        # MEGAKERNEL path: queue selection (iterated 3-key argmin), the
        # fit/score/place cycle AND the decision commit run in ONE Pallas
        # launch; the queue-time estimator folds in-kernel. Timing inputs
        # are positional tables computed with cycle_timing's exact cumsum
        # arithmetic (valid decisions form a position prefix, and cumsum
        # outputs depend only on their input prefix, so the table values at
        # valid positions are bit-identical to the masked ones).
        from kubernetriks_tpu.ops.scheduler_kernel import (
            fused_select_cycle_commit,
        )

        pods, last_flush_win, eligible = prepare_queue(
            state, W, consts, conditional_move, wake, lane_major=lane_major
        )
        interval = jnp.float32(consts.scheduling_interval)
        K = max_pods_per_cycle
        waited_p = (
            W[:, None] - pods.initial_attempt_ts.win
        ).astype(jnp.float32) * interval - pods.initial_attempt_ts.off
        full_dur = jnp.broadcast_to(pod_sched_time[:, None], (C, K))
        cd_post = jnp.cumsum(full_dur, axis=1)
        qpre_t = cd_post - full_dur
        start_t = cd_post + jnp.float32(consts.delta_bind_start)
        park_t = cd_post

        core = partial(
            fused_select_cycle_commit,
            k_pods=K,
            interpret=pallas_interpret,
            nodes_lane_major=lane_major,
            profile=profile,
        )
        if pallas_mesh is not None:
            core = _shard_rowwise(core, 15, 7, pallas_mesh, pallas_axis)
        (alloc_cpu, alloc_ram, phase, node, start_tmp, park_tmp, qstats) = core(
            alive,
            state.nodes.alloc_cpu,
            state.nodes.alloc_ram,
            eligible,
            pods.queue_ts.win,
            pods.queue_ts.off,
            pods.queue_seq,
            pods.req_cpu,
            pods.req_ram,
            waited_p,
            pods.phase,
            pods.node,
            qpre_t,
            start_t,
            park_t,
        )
        # Metric merge from the in-kernel fold: queue_time estimator rows
        # (count, total, total_sq, min, max); algo_latency adds the constant
        # per-cluster pod_sched_time once per assignment.
        n_assign = qstats[:, 0].astype(jnp.int32)
        has = n_assign > 0
        nf = qstats[:, 0]
        m = state.metrics
        qt, al = m.queue_time, m.algo_latency
        metrics = m._replace(
            scheduling_decisions=m.scheduling_decisions + n_assign,
            queue_time=EstArrays(
                count=qt.count + n_assign,
                total=qt.total + qstats[:, 1],
                total_sq=qt.total_sq + qstats[:, 2],
                minimum=jnp.minimum(qt.minimum, qstats[:, 3]),
                maximum=jnp.maximum(qt.maximum, qstats[:, 4]),
            ),
            algo_latency=EstArrays(
                count=al.count + n_assign,
                total=al.total + nf * pod_sched_time,
                total_sq=al.total_sq + nf * pod_sched_time * pod_sched_time,
                minimum=jnp.where(
                    has, jnp.minimum(al.minimum, pod_sched_time), al.minimum
                ),
                maximum=jnp.where(
                    has, jnp.maximum(al.maximum, pod_sched_time), al.maximum
                ),
            ),
        )
        return commit_scattered_tail(
            state, pods, last_flush_win, W, consts, alloc_cpu, alloc_ram,
            metrics, phase, node, start_tmp, park_tmp,
            fault_params=fault_params,
        )
    elif use_pallas and use_pallas_select:
        # Two-kernel fallback (KTPU_MEGAKERNEL=0): in-kernel selection+cycle,
        # commit as a second one-hot kernel — kept for A/B measurement.
        from kubernetriks_tpu.ops.scheduler_kernel import (
            fused_select_schedule_cycle,
        )

        pods, last_flush_win, eligible = prepare_queue(
            state, W, consts, conditional_move, wake, lane_major=lane_major
        )
        core = partial(
            fused_select_schedule_cycle,
            k_pods=max_pods_per_cycle,
            interpret=pallas_interpret,
            nodes_lane_major=lane_major,
            profile=profile,
        )
        if pallas_mesh is not None:
            core = _shard_rowwise(core, 9, 7, pallas_mesh, pallas_axis)
        cand, cand_valid, assign_k, fitany_k, best_k, alloc_cpu, alloc_ram = core(
            alive,
            state.nodes.alloc_cpu,
            state.nodes.alloc_ram,
            eligible,
            pods.queue_ts.win,
            pods.queue_ts.off,
            pods.queue_seq,
            pods.req_cpu,
            pods.req_ram,
        )
        cc = candidates_from_slots(
            pods, last_flush_win, cand, cand_valid, W, consts
        )
        park_k = cand_valid & ~fitany_k
    elif use_pallas:
        cc = prepare_cycle(
            state, W, consts, max_pods_per_cycle, conditional_move, wake,
            lane_major=lane_major,
        )
        cand_valid, cand_req_cpu, cand_req_ram = cc.valid, cc.req_cpu, cc.req_ram
        # The (C, N)-heavy core runs as a fused VMEM kernel; the (C,)-shaped
        # timing/metric mechanics below replicate the scan path's float-op
        # ordering exactly (see ops/scheduler_kernel.py).
        from kubernetriks_tpu.ops.scheduler_kernel import fused_schedule_cycle

        core = partial(
            fused_schedule_cycle,
            interpret=pallas_interpret,
            nodes_lane_major=lane_major,
            profile=profile,
        )
        if pallas_mesh is not None:
            core = _shard_rowwise(core, 6, 5, pallas_mesh, pallas_axis)
        assign_k, fitany_k, best_k, alloc_cpu, alloc_ram = core(
            alive,
            state.nodes.alloc_cpu,
            state.nodes.alloc_ram,
            cand_valid,
            cand_req_cpu,
            cand_req_ram,
        )
        park_k = cand_valid & ~fitany_k
    else:
        cc = prepare_cycle(
            state, W, consts, max_pods_per_cycle, conditional_move, wake,
            lane_major=lane_major,
        )
        cand_valid, cand_req_cpu, cand_req_ram = cc.valid, cc.req_cpu, cc.req_ram
        # The scan fallback's body is (C, N)-row-major-shaped (per-row
        # scatter-adds, axis-1 argmax); under lane-major state it converts
        # at this branch boundary — the CPU-parity path, where XLA pays
        # layout copies either way.
        alive_x = alive.T if lane_major else alive
        acpu0 = state.nodes.alloc_cpu.T if lane_major else state.nodes.alloc_cpu
        aram0 = state.nodes.alloc_ram.T if lane_major else state.nodes.alloc_ram

        from kubernetriks_tpu.batched.pipeline import profile_fit_score

        def body(carry, xs):
            alloc_cpu, alloc_ram = carry
            valid, req_cpu, req_ram = xs

            # The compiled profile's filter mask + weighted score
            # (pipeline.py; default = Fit + LeastAllocatedResources,
            # reference: plugin.rs:33-63) — the SAME expressions the Pallas
            # kernels inline, so the scan oracle and the kernels cannot
            # drift per profile. Scores are float32 on BOTH batched paths;
            # the precision only affects argmax tie-breaks between
            # near-equal node scores, which the cross-path equivalence
            # tests cover.
            fit, score = profile_fit_score(
                profile,
                alive_x,
                alloc_cpu,
                alloc_ram,
                req_cpu[:, None],
                req_ram[:, None],
            )
            # Last-max-wins argmax, matching the reference's `>=` sweep over
            # name-sorted nodes (kube_scheduler.rs:140-150).
            best = jnp.int32(N - 1) - jax.lax.argmax(score[:, ::-1], 1, jnp.int32)
            any_fit = fit.any(axis=1)

            assign = valid & any_fit
            park = valid & ~any_fit
            rows1 = jnp.arange(C, dtype=jnp.int32)
            best_c = jnp.clip(best, 0, None)
            alloc_cpu = alloc_cpu.at[rows1, best_c].add(jnp.where(assign, -req_cpu, 0))
            alloc_ram = alloc_ram.at[rows1, best_c].add(jnp.where(assign, -req_ram, 0))
            return (alloc_cpu, alloc_ram), (assign, park, best)

        xs = (cand_valid.T, cand_req_cpu.T, cand_req_ram.T)
        (alloc_cpu, alloc_ram), outs = jax.lax.scan(body, (acpu0, aram0), xs)
        assign_k, park_k, best_k = (o.T for o in outs)
        if lane_major:
            alloc_cpu, alloc_ram = alloc_cpu.T, alloc_ram.T

    # Timing/metric mechanics: vectorized and shared by ALL THREE paths above
    # (and the RL path), so the decision cores stay the only divergence.
    pod_queue_time_k, start_s_k, park_s_k = cycle_timing(
        cand_valid, cc.waited, pod_sched_time, consts
    )
    metrics = decision_metrics(
        state.metrics, assign_k, pod_queue_time_k, pod_sched_time
    )
    return commit_cycle(
        state, cc, W, consts, alloc_cpu, alloc_ram, metrics,
        assign_k, park_k, best_k, start_s_k, park_s_k,
        use_pallas=use_pallas and use_pallas_select,
        pallas_interpret=pallas_interpret,
        pallas_mesh=pallas_mesh,
        pallas_axis=pallas_axis,
        fault_params=fault_params,
    )


def _freeze_lanes(
    state: ClusterBatchState,
    state0: ClusterBatchState,
    active: jnp.ndarray,
    lane_major: bool = False,
) -> ClusterBatchState:
    """Lane-async clock protocol (DESIGN §13): revert every state leaf of
    INACTIVE lanes to its pre-window value, so a lane outside its
    [lane_clock, lane_clock + lane_horizon) span parks bit-exactly while
    neighbors keep stepping. `active` is the (C,) bool lane mask; the
    telemetry ring is excluded (inactive lanes still record their
    zero-delta row — the occupancy column needs it) and the hot node
    leaves mask along their own cluster axis (axis 1 inside lane-major
    programs — a bare leading-C broadcast would be the exact hazard the
    shapecontract pass patrols). Pure selects on values the body already
    holds: no reductions, no new syncs."""

    def keep(cur, prev, c_axis):
        shape = [1] * cur.ndim
        shape[c_axis] = active.shape[0]
        return jnp.where(active.reshape(shape), cur, prev)

    nodes = state.nodes
    frozen_nodes = nodes._replace(
        **{
            name: jax.tree.map(
                lambda cur, prev, ax=(
                    1 if (lane_major and name in NODE_HOT_LEAVES) else 0
                ): keep(cur, prev, ax),
                getattr(nodes, name),
                getattr(state0.nodes, name),
            )
            for name in nodes._fields
        }
    )
    rest = jax.tree.map(
        lambda cur, prev: keep(cur, prev, 0),
        state._replace(nodes=None, telemetry=None),
        state0._replace(nodes=None, telemetry=None),
    )
    return rest._replace(nodes=frozen_nodes, telemetry=state.telemetry)


def _telemetry_record(
    state: ClusterBatchState,
    m0,
    W: jnp.ndarray,
    consts: StepConstants,
    lane_major: bool = False,
    telem_window=None,
    lane_active=None,
):
    """Fold one per-window record row into the device telemetry ring:
    metric-counter deltas vs the window's incoming metrics `m0` plus queue
    depths / alive-node counts / reserve-occupancy gauges read straight
    off the post-window state. Pure bookkeeping — reads simulation state,
    writes only the ring — so telemetry-on runs are bit-identical to
    telemetry-off on every other leaf (tests/test_telemetry.py pins this).
    Cost: two (C, P) phase reductions, one (C, N) reduction, two tiny
    (C, G) occupancy sums and one (C, 1, K) scatter per window, only
    compiled in when the ring exists (state.telemetry is a structural
    static, like `auto`). The occupancy columns are derived from state
    the body already carries (auto counters, pod_base, static geometry) —
    no reductions over the slab or the pod axis beyond the record's own,
    and nothing here runs on the KTPU_WINDOW_RAZOR skip path (the record
    sits after the razor cond, once per executed window)."""
    from kubernetriks_tpu.batched.state import TelemetryRing

    ring = state.telemetry
    m1 = state.metrics
    pods, nodes = state.pods, state.nodes
    queued = (pods.phase == PHASE_QUEUED).sum(axis=1, dtype=jnp.int32)
    unsched = (pods.phase == PHASE_UNSCHEDULABLE).sum(axis=1, dtype=jnp.int32)
    alive = nodes.alive.sum(axis=0 if lane_major else 1, dtype=jnp.int32)
    # Reserve-occupancy gauges (capacity observatory): live HPA replicas
    # (tail - head over groups), consumed CA reserve slots (ca_cursor is
    # monotone — THE saturation driver of ROADMAP #2), and the remaining
    # plain-trace headroom of the sliding pod window. auto-off engines
    # record zeros (their programs never carry the auto pytree anyway).
    if state.auto is not None:
        hpa_used = (state.auto.hpa_tail - state.auto.hpa_head).sum(
            axis=1, dtype=jnp.int32
        )
        ca_used = state.auto.ca_cursor.sum(axis=1, dtype=jnp.int32)
    else:
        hpa_used = jnp.zeros_like(queued)
        ca_used = jnp.zeros_like(queued)
    # The device window covers plain_width plain-trace slots starting at
    # pod_base (plain_width = full device axis on non-segmented runs);
    # trace_pod_bound defaults to a huge sentinel there, so the headroom
    # column lands >= UNBOUNDED_SENTINEL and the observatory skips it.
    # Scalar int32 arithmetic on values the body already carries.
    plain_width = jnp.minimum(
        jnp.int32(pods.phase.shape[1]),
        consts.trace_pod_bound - consts.resident_shift,
    )
    headroom = jnp.maximum(
        consts.trace_pod_bound - state.pod_base - plain_width, 0
    )
    hpa = (m1.scaled_up_pods - m0.scaled_up_pods) + (
        m1.scaled_down_pods - m0.scaled_down_pods
    )
    ca = (m1.scaled_up_nodes - m0.scaled_up_nodes) + (
        m1.scaled_down_nodes - m0.scaled_down_nodes
    )
    faults = (
        (m1.node_crashes - m0.node_crashes)
        + (m1.node_recoveries - m0.node_recoveries)
        + (m1.pod_interruptions - m0.pod_interruptions)
        + (m1.pod_restarts - m0.pod_restarts)
        + (m1.pods_failed - m0.pods_failed)
    )
    # Lane-async mode: the window column records the GLOBAL window index
    # (telem_window) so it stays lane-uniform — ring.merge_snapshot keys
    # on buf[0, :, 0] — while every other column carries the lane's own
    # values; the lane_active bit is the occupancy observable. Outside
    # lane-async builds both default to the wave-aligned behavior
    # (window = W, active = 1 everywhere).
    row = jnp.stack(
        [
            telem_window if telem_window is not None else W,
            m1.scheduling_decisions - m0.scheduling_decisions,
            queued,
            unsched,
            hpa,
            ca,
            faults,
            alive,
            hpa_used,
            ca_used,
            headroom,
            (
                lane_active.astype(jnp.int32)
                if lane_active is not None
                else jnp.ones_like(W)
            ),
        ],
        axis=-1,
    ).astype(jnp.int32)
    C, R = ring.buf.shape[0], ring.buf.shape[1]
    rows = jnp.arange(C, dtype=jnp.int32)
    buf = ring.buf.at[rows, jnp.mod(ring.cursor, R)].set(row)
    return TelemetryRing(buf=buf, cursor=ring.cursor + 1)


def _window_body(
    state: ClusterBatchState,
    slab: TraceSlab,
    W: jnp.ndarray,
    consts: StepConstants,
    max_events_per_window: int,
    max_pods_per_cycle: int,
    autoscale_statics=None,
    max_ca_pods_per_cycle: int = 64,
    max_pods_per_scale_down: int = 8,
    use_pallas: bool = False,
    pallas_interpret: bool = False,
    conditional_move: bool = False,
    pallas_mesh=None,
    pallas_axis: str = "clusters",
    use_pallas_select: bool = False,
    use_megakernel: bool = True,
    hpa_seg=None,
    fault_params=None,
    name_ranks=None,
    lane_major: bool = False,
    window_razor: bool = True,
    ca_descatter: bool = True,
    reclaim: bool = False,
    reclaim_period: int = 1,
    profile=None,
    freeze_lanes: bool = True,
) -> ClusterBatchState:
    W = jnp.broadcast_to(jnp.asarray(W, jnp.int32), state.time.shape)
    # Lane-async clock protocol (engine lane_async=True, DESIGN §13): each
    # lane steps its VIRTUAL window W - lane_clock[c] — bit-identical to a
    # fresh run's window of that index — and is active only inside
    # [0, lane_horizon[c]). Inactive lanes still execute the body (the
    # clamp keeps the virtual index sane) and are reverted wholesale by
    # _freeze_lanes before the telemetry record, so a finished lane parks
    # at its exact final state until the host re-seeds it. lane_clock is
    # traced (C,) data: re-seeding never recompiles. freeze_lanes=False is
    # the ALL-ACTIVE fast path: the engine's host clock mirrors prove no
    # lane enters or leaves its span during the dispatched chunk, so the
    # state-wide revert selects (pure identities there) are compiled out —
    # bit-identical by construction, and the dominant per-window saving of
    # the lane-async executor (the freeze is O(state) every window).
    telem_W = W
    lane_active = None
    state0 = None
    if consts.lane_clock is not None:
        rel = W - consts.lane_clock
        lane_active = (rel >= 0) & (rel < consts.lane_horizon)
        state0 = state if freeze_lanes else None
        W = jnp.maximum(rel, 0)
    # Telemetry ring (flight recorder): the window's incoming metric
    # counters, diffed at the end of the body into one per-window record.
    m0 = state.metrics
    # CA slot reclaim (KTPU_RECLAIM): compaction runs FIRST — a clean
    # state boundary, and a scale-up later in this window then sees every
    # reclaimable slot (the loud starvation bound can only fire on true
    # live-demand exhaustion). See autoscale.ca_reclaim_pass.
    if reclaim and autoscale_statics is not None and state.auto is not None:
        from kubernetriks_tpu.batched.autoscale import ca_reclaim_pass

        state, auto_r = ca_reclaim_pass(
            state,
            state.auto,
            autoscale_statics,
            W,
            consts,
            period=reclaim_period,
            nodes_lane_major=lane_major,
        )
        state = state._replace(auto=auto_r)
    # Same-time reschedule/retry ordering needs lexicographic name ranks to
    # match the scalar's sorted-name walks; they come from the autoscale
    # statics when autoscalers are on, else from the engine's standalone
    # rank tables (built for fault-injection runs, where node crashes
    # produce large same-instant reschedule batches).
    if autoscale_statics is not None:
        node_name_rank = autoscale_statics.node_name_rank
        pod_name_rank = autoscale_statics.pod_name_rank
    elif name_ranks is not None:
        node_name_rank, pod_name_rank = name_ranks
    else:
        node_name_rank = pod_name_rank = None
    node_key_fn = None
    if (
        reclaim
        and autoscale_statics is not None
        and state.auto is not None
        and state.auto.ca_alloc is not None
    ):
        # Under reclaim the same-window reschedule batches order removed
        # CA nodes by their occupants' CURRENT names, not the slots'
        # static first-occupant names; the key derives from the
        # allocation indices and is only computed inside the (rare)
        # reschedule cond. auto is captured here — event application
        # never mutates it.
        from kubernetriks_tpu.batched.autoscale import ca_name_order

        auto0 = state.auto
        node_key_fn = lambda: ca_name_order(  # noqa: E731
            auto0, autoscale_statics
        )[1]

    state, wake = _apply_window_events(
        state,
        slab,
        W,
        consts,
        max_events_per_window,
        conditional_move,
        use_pallas,
        pallas_interpret,
        pallas_mesh,
        pallas_axis,
        use_pallas_select,
        node_name_rank=node_name_rank,
        pod_name_rank=pod_name_rank,
        fault_params=fault_params,
        lane_major=lane_major,
        window_razor=window_razor,
        node_key_fn=node_key_fn,
    )
    # Pre-cycle shadows for the CA's early-snapshot case (a CA storage
    # snapshot landing before this window's commit-visibility time must not
    # see this cycle's assignments/parks — ca_pass docstring).
    pre_cycle = (
        state.pods.phase,
        state.pods.attempts,
        state.nodes.alloc_cpu,
        state.nodes.alloc_ram,
    )
    state = _run_scheduling_cycle(
        state,
        W,
        consts,
        max_pods_per_cycle,
        use_pallas,
        pallas_interpret,
        conditional_move,
        pallas_mesh,
        pallas_axis,
        use_pallas_select,
        wake=wake,
        use_megakernel=use_megakernel,
        fault_params=fault_params,
        lane_major=lane_major,
        profile=profile,
    )
    if autoscale_statics is not None:
        # Autoscaler ticks due by this window run after the scheduling cycle
        # (the scalar snapshot lands between cycles; SURVEY.md §3.5); their
        # effects land at composed future times via the pending-effect arrays.
        from kubernetriks_tpu.batched.autoscale import ca_pass, hpa_pass

        auto = state.auto
        # hpa_seg: STATIC (lo, hi) group-slot bounds (engine._hpa_seg) so
        # the HPA body and its not-due cond carry only the group slice;
        # (0, 0) = no group slots anywhere, skip the pass entirely.
        if hpa_seg != (0, 0):
            state, auto = hpa_pass(
                state, auto, autoscale_statics, W, consts, seg=hpa_seg
            )
        state, auto = ca_pass(
            state,
            auto,
            autoscale_statics,
            W,
            consts,
            max_ca_pods_per_cycle,
            max_pods_per_scale_down,
            pre=pre_cycle,
            # Each CA kernel gates on its own VMEM fits-check inside.
            use_pallas=use_pallas,
            pallas_interpret=pallas_interpret,
            pallas_mesh=pallas_mesh,
            pallas_axis=pallas_axis,
            nodes_lane_major=lane_major,
            descatter=ca_descatter,
            reclaim=reclaim,
        )
        state = state._replace(auto=auto)
    if lane_active is not None and state0 is not None:
        # Freeze BEFORE the record: frozen lanes then diff m1 == m0 and
        # record zero-delta rows (their gauges re-read the parked state),
        # so the ring never carries phantom progress for an idle lane.
        state = _freeze_lanes(state, state0, lane_active, lane_major)
    if state.telemetry is not None:
        state = state._replace(
            telemetry=_telemetry_record(
                state,
                m0,
                W,
                consts,
                lane_major=lane_major,
                telem_window=telem_W,
                lane_active=lane_active,
            )
        )
    return state


def gauge_snapshot(
    state: ClusterBatchState, lane_major: bool = False
) -> jnp.ndarray:
    """(C, 7) on-device gauge readings after a window: current nodes/pods,
    scheduling-queue length, node-average and cluster-total cpu/ram
    utilization (scalar equivalents: GaugeMetrics fields fed from
    collect_utilizations, reference: src/metrics/collector.rs:166-192,
    352-390). Utilization = requests / capacity over alive nodes."""
    if lane_major:
        state = swap_node_layout(state)
    nodes, pods = state.nodes, state.pods
    alive = nodes.alive
    alive_f = alive.astype(jnp.float32)
    n_alive = alive.sum(axis=1, dtype=jnp.int32)
    n_alive_f = jnp.maximum(n_alive, 1).astype(jnp.float32)

    live_pod = (
        (pods.phase == PHASE_QUEUED)
        | (pods.phase == PHASE_UNSCHEDULABLE)
        | (pods.phase == PHASE_RUNNING)
    )
    queued = (pods.phase == PHASE_QUEUED) | (pods.phase == PHASE_UNSCHEDULABLE)

    cap_cpu = jnp.maximum(nodes.cap_cpu, 1).astype(jnp.float32)
    cap_ram = jnp.maximum(nodes.cap_ram, 1).astype(jnp.float32)
    used_cpu = (nodes.cap_cpu - nodes.alloc_cpu).astype(jnp.float32) * alive_f
    used_ram = (nodes.cap_ram - nodes.alloc_ram).astype(jnp.float32) * alive_f

    node_avg_cpu = (used_cpu / cap_cpu).sum(axis=1) / n_alive_f
    node_avg_ram = (used_ram / cap_ram).sum(axis=1) / n_alive_f
    total_cap_cpu = jnp.maximum((cap_cpu * alive_f).sum(axis=1), 1.0)
    total_cap_ram = jnp.maximum((cap_ram * alive_f).sum(axis=1), 1.0)

    return jnp.stack(
        [
            n_alive.astype(jnp.float32),
            live_pod.sum(axis=1, dtype=jnp.int32).astype(jnp.float32),
            queued.sum(axis=1, dtype=jnp.int32).astype(jnp.float32),
            node_avg_cpu,
            node_avg_ram,
            used_cpu.sum(axis=1) / total_cap_cpu,
            used_ram.sum(axis=1) / total_cap_ram,
        ],
        axis=-1,
    )


_STEP_STATICS = (
    "max_events_per_window",
    "max_pods_per_cycle",
    "max_ca_pods_per_cycle",
    "max_pods_per_scale_down",
    "use_pallas",
    "pallas_interpret",
    "conditional_move",
    "pallas_mesh",
    "pallas_axis",
    "use_pallas_select",
    "use_megakernel",
    "hpa_seg",
    # chaos.FaultParams (hashable NamedTuple of scalars) or None; None
    # compiles programs textually identical to the pre-chaos build.
    "fault_params",
    # PR 9 perf statics, each with a flags.py A/B switch: lane-major hot
    # node state (KTPU_LANE_MAJOR), the empty-window resolution razor
    # (KTPU_WINDOW_RAZOR), and the CA scale-down combined segment-sum
    # (KTPU_CA_DESCATTER). All three are bit-exact either way.
    "lane_major",
    "window_razor",
    "ca_descatter",
    # CA slot reclaim (KTPU_RECLAIM, r14): the compaction pass at the top
    # of the window body + allocation-index name orders in the CA passes.
    # Off compiles the pre-reclaim programs (the A/B bit-identity gate);
    # reclaim_period > 1 batches the compaction's (C, P) safety sweep.
    "reclaim",
    "reclaim_period",
    # pipeline.CompiledProfile (hashable NamedTuple of plugin names +
    # weights) or None; the compiled scheduler profile whose filter/score
    # expressions the decision core runs. None compiles programs identical
    # to the pre-profile build (the reference default). Co-travels with
    # fault_params through every window-program entry (the ktpu-lint
    # jit-static pass enforces the pairing).
    "profile",
)


@partial(jax.jit, static_argnames=_STEP_STATICS)
def window_step(
    state: ClusterBatchState,
    slab: TraceSlab,
    W: jnp.ndarray,
    consts: StepConstants,
    max_events_per_window: int,
    max_pods_per_cycle: int,
    autoscale_statics=None,
    max_ca_pods_per_cycle: int = 64,
    max_pods_per_scale_down: int = 8,
    use_pallas: bool = False,
    pallas_interpret: bool = False,
    conditional_move: bool = False,
    pallas_mesh=None,
    pallas_axis: str = "clusters",
    use_pallas_select: bool = False,
    use_megakernel: bool = True,
    hpa_seg=None,
    fault_params=None,
    name_ranks=None,
    lane_major: bool = False,
    window_razor: bool = True,
    ca_descatter: bool = True,
    reclaim: bool = False,
    reclaim_period: int = 1,
    profile=None,
) -> ClusterBatchState:
    """Advance every cluster through scheduling-cycle window index W.

    Lane-major conversion happens at the jit boundary (state at rest is
    ALWAYS row-major — see state.swap_node_layout): two transposes per
    dispatch instead of two per kernel boundary."""
    if lane_major:
        state = swap_node_layout(state)
    state = _window_body(
        state,
        slab,
        W,
        consts,
        max_events_per_window,
        max_pods_per_cycle,
        autoscale_statics,
        max_ca_pods_per_cycle,
        max_pods_per_scale_down,
        use_pallas,
        pallas_interpret,
        conditional_move,
        pallas_mesh,
        pallas_axis,
        use_pallas_select,
        use_megakernel=use_megakernel,
        hpa_seg=hpa_seg,
        fault_params=fault_params,
        name_ranks=name_ranks,
        lane_major=lane_major,
        window_razor=window_razor,
        ca_descatter=ca_descatter,
        reclaim=reclaim,
        reclaim_period=reclaim_period,
        profile=profile,
    )
    if lane_major:
        state = swap_node_layout(state)
    return state


def _next_interesting_window(
    state: ClusterBatchState,
    slab: TraceSlab,
    W: jnp.ndarray,
    consts: StepConstants,
    autoscale_statics,
    flush_windows: int,
) -> jnp.ndarray:
    """First window index > W whose body could change state (scalar, min
    over clusters). A window with none of the triggers below is PROVABLY the
    identity on all simulation state except the cadence bookkeeping that
    _catch_up_bookkeeping replays (last_flush_win, hpa_next/ca_next, time):
    no due trace events, no due pod finishes, no pending autoscaler effects,
    no eligible queued pod (an empty cycle assigns/parks/measures nothing
    and signals are already zeroed by the previous commit), no flush window
    while pods are parked, and no CA/HPA tick that could act.

    Every trigger is CONSERVATIVE (running a window early is always safe —
    window execution at any index is semantics-preserving); what is never
    allowed is skipping past a trigger."""
    from kubernetriks_tpu.batched.timerep import INF_WIN

    pods, nodes = state.pods, state.nodes
    C = state.time.shape[0]
    rows1 = jnp.arange(C, dtype=jnp.int32)
    big = jnp.int32(INF_WIN)
    E_total = slab.packed.shape[1]

    def amin(x):
        return jnp.min(x).astype(jnp.int32)

    # Next unapplied trace event (applied when stepping win+1).
    cursor = jnp.clip(state.event_cursor, 0, E_total - 1)
    ev_win = slab.packed[rows1, cursor, 0]
    ev_next = jnp.where(state.event_cursor < E_total, ev_win, big)
    cand = amin(ev_next) + 1

    # Pod finishes (resolved in the finish pair's window or the next; running
    # the earlier window is a harmless no-op when off > 0).
    running = pods.phase == PHASE_RUNNING
    cand = jnp.minimum(cand, amin(jnp.where(running, pods.finish_time.win, big)))

    # Pending effect times (applied when stepping win+1): CA node
    # creations/removals, HPA pod removals.
    cand = jnp.minimum(cand, amin(nodes.create_time.win) + 1)
    cand = jnp.minimum(cand, amin(nodes.remove_time.win) + 1)
    cand = jnp.minimum(cand, amin(pods.removal_time.win) + 1)

    # Queued pods become eligible at queue_ts.win + 1.
    queued = pods.phase == PHASE_QUEUED
    cand = jnp.minimum(cand, amin(jnp.where(queued, pods.queue_ts.win, big)) + 1)

    # Parked pods: the flush cadence can wake them, and a due CA tick can
    # scale up from the unscheduled cache.
    parked_any = (pods.phase == PHASE_UNSCHEDULABLE).any()
    flush_next = jnp.min(state.last_flush_win) + jnp.int32(flush_windows)
    cand = jnp.minimum(cand, jnp.where(parked_any, flush_next, big))

    if autoscale_statics is not None and state.auto is not None:
        auto = state.auto
        # The CA cycle runs in the window containing its storage snapshot
        # (drifting cadence; autoscale.ca_pass docstring).
        ca_snap_t = t_add(
            auto.ca_next, autoscale_statics.ca_snap,
            jnp.float32(consts.scheduling_interval),
        )
        ca_tick = amin(ca_snap_t.win)
        hpa_tick = amin(auto.hpa_next.win)
        ca_can_act = parked_any | (auto.ca_count.sum() > 0)
        cand = jnp.minimum(cand, jnp.where(ca_can_act, ca_tick, big))
        # HPA ticks are interesting whenever a group could be active (the
        # engine parks hpa_next at +inf otherwise, making this a no-op).
        cand = jnp.minimum(cand, hpa_tick)
        if auto.col_next is not None:
            # HPA collection latch (r14 staleness fix): the 60 s metrics
            # collection snapshots the load curve AT its window — a skipped
            # collection would latch a different utilization later, so its
            # tick is a trigger like the HPA's own.
            cand = jnp.minimum(cand, amin(auto.col_next.win))

    return jnp.maximum(W + jnp.int32(1), cand)


def _catch_up_bookkeeping(
    state: ClusterBatchState,
    from_w: jnp.ndarray,
    to_w: jnp.ndarray,
    consts: StepConstants,
    autoscale_statics,
) -> ClusterBatchState:
    """Replay the cadence bookkeeping of the skipped windows [from_w, to_w)
    with the SAME per-window arithmetic the window body uses, so a
    fast-forwarded run's state is bit-identical to continuous stepping:
    last_flush_win advances at the flush cadence, due autoscaler ticks
    advance hpa_next/ca_next once per window, and time tracks the last
    covered window. O(skipped windows) scalar work per cluster — ~10 tiny
    (C,)-shaped ops per window vs ~2k for a full body."""
    interval = jnp.float32(consts.scheduling_interval)
    has_auto = autoscale_statics is not None and state.auto is not None

    def body(carry):
        w, last_flush, hpa_next, ca_next = carry
        wc = jnp.broadcast_to(w, last_flush.shape)
        flush_now = (wc - last_flush).astype(jnp.float32) * interval >= jnp.float32(
            consts.flush_interval
        )
        last_flush = jnp.where(flush_now, wc, last_flush)
        if has_auto:
            T = TPair(win=wc, off=jnp.zeros_like(hpa_next.off))
            hpa_next = t_where(
                t_le(hpa_next, T),
                t_add(hpa_next, autoscale_statics.hpa_interval, interval),
                hpa_next,
            )
            # Same due/advance arithmetic as ca_pass: the cycle belongs to
            # the window containing its storage snapshot; the period is the
            # drifting round-trip + scan (autoscale.ca_pass docstring).
            T1 = TPair(win=wc + jnp.int32(1), off=jnp.zeros_like(ca_next.off))
            ca_due = t_lt(
                t_add(ca_next, autoscale_statics.ca_snap, interval), T1
            )
            ca_next = t_where(
                ca_due,
                t_add(ca_next, autoscale_statics.ca_period, interval),
                ca_next,
            )
        return (w + jnp.int32(1), last_flush, hpa_next, ca_next)

    if has_auto:
        hpa0, ca0 = state.auto.hpa_next, state.auto.ca_next
    else:
        dummy = TPair(
            win=jnp.zeros_like(state.last_flush_win),
            off=jnp.zeros(state.last_flush_win.shape, jnp.float32),
        )
        hpa0, ca0 = dummy, dummy
    _, last_flush, hpa_next, ca_next = jax.lax.while_loop(
        lambda carry: carry[0] < to_w,
        body,
        (jnp.asarray(from_w, jnp.int32), state.last_flush_win, hpa0, ca0),
    )
    state = state._replace(
        last_flush_win=last_flush,
        time=jnp.maximum(state.time, to_w - 1),
    )
    if has_auto:
        state = state._replace(
            auto=state.auto._replace(hpa_next=hpa_next, ca_next=ca_next)
        )
    return state


def _run_windows_skip_impl(
    state: ClusterBatchState,
    slab: TraceSlab,
    first: jnp.ndarray,
    last: jnp.ndarray,
    consts: StepConstants,
    max_events_per_window: int,
    max_pods_per_cycle: int,
    autoscale_statics=None,
    max_ca_pods_per_cycle: int = 64,
    max_pods_per_scale_down: int = 8,
    use_pallas: bool = False,
    pallas_interpret: bool = False,
    conditional_move: bool = False,
    pallas_mesh=None,
    pallas_axis: str = "clusters",
    use_pallas_select: bool = False,
    use_megakernel: bool = True,
    flush_windows: int = 3,
    hpa_seg=None,
    fault_params=None,
    name_ranks=None,
    lane_major: bool = False,
    window_razor: bool = True,
    ca_descatter: bool = True,
    reclaim: bool = False,
    reclaim_period: int = 1,
    profile=None,
):
    """run_windows with FAST-FORWARD over provably no-op windows: a dynamic
    while_loop executes only interesting windows (see
    _next_interesting_window) and replays the skipped windows' cadence
    bookkeeping exactly, so the final state is bit-identical to stepping
    every index in [first, last]. One compiled program serves any span
    (first/last are traced scalars). No per-window gauge collection — the
    engine falls back to run_windows when gauges are on."""
    if lane_major:
        # _next_interesting_window / _catch_up_bookkeeping read only
        # row-major leaves (pending pairs, pods), so the lane-major carry
        # flows through the whole skip loop untouched.
        state = swap_node_layout(state)

    def cond(carry):
        _, W = carry
        return W <= last

    def body(carry):
        state, W = carry
        state = _window_body(
            state,
            slab,
            W,
            consts,
            max_events_per_window,
            max_pods_per_cycle,
            autoscale_statics,
            max_ca_pods_per_cycle,
            max_pods_per_scale_down,
            use_pallas,
            pallas_interpret,
            conditional_move,
            pallas_mesh,
            pallas_axis,
            use_pallas_select,
            use_megakernel=use_megakernel,
            hpa_seg=hpa_seg,
            fault_params=fault_params,
            name_ranks=name_ranks,
            lane_major=lane_major,
            window_razor=window_razor,
            ca_descatter=ca_descatter,
            reclaim=reclaim,
            reclaim_period=reclaim_period,
            profile=profile,
        )
        W_next = jnp.minimum(
            _next_interesting_window(
                state, slab, W, consts, autoscale_statics, flush_windows
            ),
            last + jnp.int32(1),
        )
        state = _catch_up_bookkeeping(
            state, W + jnp.int32(1), W_next, consts, autoscale_statics
        )
        return state, W_next

    state, _ = jax.lax.while_loop(
        cond, body, (state, jnp.asarray(first, jnp.int32))
    )
    if lane_major:
        state = swap_node_layout(state)
    return state


# Undonated (pure) and donated jit entries share one traced body. The engine's
# steady-state loop uses the DONATED variants: the full (C,N)/(C,P) state is
# consumed and updated in place instead of being re-materialized into fresh
# device buffers on every dispatch (the composed path dispatches popcount(span)
# chunks per slide span, so the per-dispatch allocate+copy of the whole state
# was pure overhead). Donated and undonated programs are bit-identical —
# tests/test_window_donation_dispatch.py pins it — but a donated call INVALIDATES its
# input state; callers that keep the input (tests, warm-up against a scratch
# copy) use the undonated names.
run_windows_skip = partial(
    jax.jit, static_argnames=_STEP_STATICS + ("flush_windows",)
)(_run_windows_skip_impl)
run_windows_skip_donated = jax.jit(
    _run_windows_skip_impl,
    static_argnames=_STEP_STATICS + ("flush_windows",),
    donate_argnums=(0,),
)


def _run_windows_impl(
    state: ClusterBatchState,
    slab: TraceSlab,
    window_idxs: jnp.ndarray,
    consts: StepConstants,
    max_events_per_window: int,
    max_pods_per_cycle: int,
    autoscale_statics=None,
    max_ca_pods_per_cycle: int = 64,
    max_pods_per_scale_down: int = 8,
    use_pallas: bool = False,
    pallas_interpret: bool = False,
    conditional_move: bool = False,
    collect_gauges: bool = False,
    pallas_mesh=None,
    pallas_axis: str = "clusters",
    use_pallas_select: bool = False,
    use_megakernel: bool = True,
    hpa_seg=None,
    fault_params=None,
    name_ranks=None,
    lane_major: bool = False,
    window_razor: bool = True,
    ca_descatter: bool = True,
    reclaim: bool = False,
    reclaim_period: int = 1,
    profile=None,
    freeze_lanes: bool = True,
):
    """Scan a whole sequence of scheduling-cycle windows on-device (the hot
    benchmark loop: no host round-trips between cycles). window_idxs: (Wn,)
    int32 consecutive window indices.

    With collect_gauges, returns (state, (Wn, C, 7) gauge time-series) — the
    batched analog of the scalar 5 s gauge CSV cycle (one sample per window,
    since batched state only changes at window boundaries)."""
    if lane_major:
        state = swap_node_layout(state)

    def body(carry, w):
        new = _window_body(
            carry,
            slab,
            w,
            consts,
            max_events_per_window,
            max_pods_per_cycle,
            autoscale_statics,
            max_ca_pods_per_cycle,
            max_pods_per_scale_down,
            use_pallas,
            pallas_interpret,
            conditional_move,
            pallas_mesh,
            pallas_axis,
            use_pallas_select,
            use_megakernel=use_megakernel,
            hpa_seg=hpa_seg,
            fault_params=fault_params,
            name_ranks=name_ranks,
            lane_major=lane_major,
            window_razor=window_razor,
            ca_descatter=ca_descatter,
            reclaim=reclaim,
            reclaim_period=reclaim_period,
            profile=profile,
            freeze_lanes=freeze_lanes,
        )
        return new, (
            gauge_snapshot(new, lane_major=lane_major)
            if collect_gauges
            else None
        )

    state, gauges = jax.lax.scan(body, state, jnp.asarray(window_idxs, jnp.int32))
    if lane_major:
        state = swap_node_layout(state)
    if collect_gauges:
        return state, gauges
    return state


run_windows = partial(
    jax.jit, static_argnames=_STEP_STATICS + ("collect_gauges", "freeze_lanes")
)(_run_windows_impl)
run_windows_donated = jax.jit(
    _run_windows_impl,
    static_argnames=_STEP_STATICS + ("collect_gauges", "freeze_lanes"),
    donate_argnums=(0,),
)


# --- sliding-window slide primitives ----------------------------------------
# Shared by the engine's two-dispatch slide path, the fused chunk+slide
# megastep (engine._fused_chunk_slide) and the superspan executor below.


def _slide_shift_core(phase, create_win_pay, base):
    """The window-shift amount, computed ON DEVICE: the leading run of
    terminal-or-padding pod slots across every cluster (min over C of each
    row's first blocking slot). Bit-identical to the host formulation in
    engine._advance_pod_window (same terminal set, same padding rule); only
    a 4-byte scalar crosses the tunnel instead of the full (C, W) phase
    fetch. `base` indexes create_win_pay's columns — GLOBAL plain slots for
    the whole-trace payload, stage-relative under a bounded RefillStage."""
    C, W = phase.shape  # phase is pre-sliced to the plain window [0, W)
    no_create = jnp.int32(np.iinfo(np.int32).max)
    seg = jax.lax.dynamic_slice(create_win_pay, (jnp.int32(0), base), (C, W))
    terminal = (
        (phase == PHASE_SUCCEEDED)
        | (phase == PHASE_REMOVED)
        | (phase == PHASE_FAILED)
    )
    padding = (phase == PHASE_EMPTY) & (seg == no_create)
    blocking = ~(terminal | padding)
    first_live = jnp.where(
        blocking.any(axis=1),
        jnp.argmax(blocking, axis=1).astype(jnp.int32),
        jnp.int32(W),
    )
    return jnp.min(first_live).astype(jnp.int32)


def _quantize_shift_device(s0, W: int):
    """Device mirror of _advance_pod_window's host shift quantization (same
    small set of slide amounts, so fused and unfused runs follow identical
    slide trajectories). s0 == 0 maps to 0 — the fused program's "no slide
    possible" flag, read back by the engine to trigger window growth."""
    quantum = max(W // 8, 1)
    # Largest power of two <= s0 (bit-smear; 0 for s0 == 0), the host path's
    # 1 << (s.bit_length() - 1) fallback.
    v = s0
    for sh in (1, 2, 4, 8, 16):
        v = v | (v >> sh)
    s = jnp.where(s0 >= quantum, jnp.int32(quantum), v - (v >> 1))
    if W // 4 > 0:
        s = jnp.where(s0 >= W // 4, jnp.int32(W // 4), s)
    if W // 2 > 0:
        s = jnp.where(s0 >= W // 2, jnp.int32(W // 2), s)
    return s.astype(jnp.int32)


def _slide_apply_traced(pods, rank, pay, base, s, W: int):
    """Window slide with a TRACED shift amount (s == 0 is the identity): the
    gather formulation of engine._slide_apply_device, so ONE compiled
    program covers every quantized shift and the slide can fuse into the
    window-chunk program (engine._fused_chunk_slide) or the superspan loop
    (run_superspan). Bit-identical to the concat path: shifted window slots
    copy their source slot, refill slots combine the device payload with the
    SAME fresh-slot constructor init_state uses, and the resident pod-group
    tail (device slots >= W) is untouched. `base` is in the payload's own
    column coordinates (see _slide_shift_core)."""
    from kubernetriks_tpu.batched.state import fresh_pod_arrays

    C, P = pods.phase.shape
    idx = jnp.arange(P, dtype=jnp.int32)[None, :]  # (1, P)
    in_window = idx < W
    refill = in_window & (idx >= (jnp.int32(W) - s))
    # Window slots shift left by s; refill slots read idx (masked out below);
    # resident-tail slots are the identity. idx + s < W for every shifted
    # slot, so the gather never crosses into the resident tail.
    src_old = jnp.broadcast_to(
        jnp.where(in_window & ~refill, idx + s, idx), (C, P)
    )
    # Refill slot idx's payload column is (base + s) + idx; the whole-trace
    # payload is padded to T + W columns and a RefillStage's exhaustion exit
    # fires before any out-of-range refill, so every reachable refill column
    # is covered. Clip for the masked-out rest.
    pay_cols = pay["req_cpu"].shape[1]
    pay_col = jnp.broadcast_to(
        jnp.clip(base + s + idx, 0, pay_cols - 1), (C, P)
    )

    def pg(a):
        return jnp.take_along_axis(a, pay_col, axis=1)

    fresh = fresh_pod_arrays(
        C,
        P,
        pg(pay["req_cpu"]),
        pg(pay["req_ram"]),
        TPair(win=pg(pay["dur_win"]), off=pg(pay["dur_off"])),
    )
    new_pods = jax.tree.map(
        lambda old, fr: jnp.where(
            refill, fr, jnp.take_along_axis(old, src_old, axis=1)
        ),
        pods,
        fresh,
    )
    new_rank = None
    if rank is not None:
        new_rank = jnp.where(
            refill, pg(pay["rank"]), jnp.take_along_axis(rank, src_old, axis=1)
        )
    return new_pods, new_rank


# --- superspan executor ------------------------------------------------------

# Exit codes in the superspan progress vector (progress[3]):
SUPERSPAN_RUN = 0  # ran to the target / span budget; nothing blocked
SUPERSPAN_GROW = 1  # shift == 0: the live-pod span outgrew the window
SUPERSPAN_STAGE = 2  # next slide needs refill columns beyond the stage


def _run_superspan_impl(
    state: ClusterBatchState,
    rank,
    progress,
    slab: TraceSlab,
    consts: StepConstants,
    stage,
    stage_lo,
    last,
    max_events_per_window: int,
    max_pods_per_cycle: int,
    autoscale_statics=None,
    max_ca_pods_per_cycle: int = 64,
    max_pods_per_scale_down: int = 8,
    use_pallas: bool = False,
    pallas_interpret: bool = False,
    conditional_move: bool = False,
    pallas_mesh=None,
    pallas_axis: str = "clusters",
    use_pallas_select: bool = False,
    use_megakernel: bool = True,
    hpa_seg=None,
    fault_params=None,
    name_ranks=None,
    lane_major: bool = False,
    window_razor: bool = True,
    ca_descatter: bool = True,
    reclaim: bool = False,
    reclaim_period: int = 1,
    profile=None,
    W: int = 0,
    K: int = 16,
    chunk: int = 8,
):
    """Execute up to K consecutive slide-spans ENTIRELY on device: one
    while_loop whose body either advances a chunk of windows (while the
    next window's pod creations still fit the device window) or computes,
    quantizes and applies the pod-window slide — refill columns drawn from
    the device-resident RefillStage — carrying pod_base (in state) and the
    windowed pod-name ranks as traced loop state. The steady-state host
    boundary of the ladder path (one shift readback + refill bookkeeping
    per span) collapses to ONE progress readback per K spans.

    Arguments beyond the run_windows set:
    - rank: (C, P) windowed pod-name ranks carried through on-device slides
      (None without autoscale statics). The statics' own pod_name_rank leaf
      is ignored inside the loop (autoscale.statics_with_pod_rank rebinds
      the carried array for every window chunk).
    - progress: (4,) int32 [next_window, pod_base, spans, code]. The loop
      starts at progress[0] with progress[3] as the initial code — a
      non-RUN input code makes the whole call the identity, so callers can
      chain dispatches speculatively and resolve the codes later.
    - stage: state.RefillStage covering payload columns
      [stage_lo, stage_lo + L); the whole-trace payload is the L = T + W,
      stage_lo = 0 special case and never exhausts.
    - last: final window index (inclusive) this call may execute.
    - W/K/chunk (static): pod-window width, span budget, windows advanced
      per full-rate loop iteration.

    Exits (code in the returned progress vector): SUPERSPAN_RUN with
    next_window > last = target reached; SUPERSPAN_RUN with spans == K =
    span budget, redispatch; SUPERSPAN_GROW = no slide possible with the
    capacity column readable, the engine must grow the window;
    SUPERSPAN_STAGE = the pending slide's refill columns lie beyond the
    stage (or the slide is blocked with the capacity column itself beyond
    the stage, where growth cannot be trusted), the engine must install the
    next staging buffer. Blocking exits leave the slide UNAPPLIED (state as
    of the last completed window), so re-dispatching after the host fix is
    exact.

    Bit-identity with the ladder path: the same _window_body runs at the
    same window indices (chunking is associativity-free), slides trigger at
    exactly the capacity boundaries step_until_time uses (first overflow
    create across clusters), and shift/quantize/apply are the SAME traced
    formulations the fused megastep dispatches.
    """
    big = jnp.int32(np.iinfo(np.int32).max)
    from kubernetriks_tpu.batched.autoscale import statics_with_pod_rank

    if lane_major:
        # One conversion per superspan dispatch (covers up to K slide-spans
        # of windows); everything the loop touches outside _window_body —
        # pod_base, phases, the stage — is row-major / pod-side.
        state = swap_node_layout(state)

    L = stage.req_cpu.shape[1]
    stage_lo = jnp.asarray(stage_lo, jnp.int32)
    last = jnp.asarray(last, jnp.int32)
    pay = {
        "req_cpu": stage.req_cpu,
        "req_ram": stage.req_ram,
        "dur_win": stage.dur_win,
        "dur_off": stage.dur_off,
        "create_win": stage.create_win,
    }
    if stage.rank is not None:
        pay["rank"] = stage.rank

    def step_windows(state, rank, idxs):
        st = statics_with_pod_rank(autoscale_statics, rank)

        def body(carry, w):
            new = _window_body(
                carry,
                slab,
                w,
                consts,
                max_events_per_window,
                max_pods_per_cycle,
                st,
                max_ca_pods_per_cycle,
                max_pods_per_scale_down,
                use_pallas,
                pallas_interpret,
                conditional_move,
                pallas_mesh,
                pallas_axis,
                use_pallas_select,
                use_megakernel=use_megakernel,
                hpa_seg=hpa_seg,
                fault_params=fault_params,
                name_ranks=name_ranks,
                lane_major=lane_major,
                window_razor=window_razor,
                ca_descatter=ca_descatter,
                reclaim=reclaim,
                reclaim_period=reclaim_period,
                profile=profile,
            )
            return new, None

        state, _ = jax.lax.scan(body, state, idxs)
        return state

    def cond(carry):
        _, _, w, spans, code = carry
        return (w <= last) & (code == SUPERSPAN_RUN) & (spans < jnp.int32(K))

    def body(carry):
        state, rank, w, spans, code = carry
        # pod_base is uniform across clusters (slides shift every row
        # together); min() is the replicated-scalar read under a mesh.
        base = jnp.min(state.pod_base)
        # Capacity: the last window index dispatchable before a pod creation
        # would land beyond the device window — the create window of global
        # plain slot base + W (engine._pod_capacity_window's device twin).
        # Beyond the trace's plain segment capacity is unbounded; a stage
        # whose headroom is fully consumed reports capacity -1, forcing the
        # slide branch (which then exits SUPERSPAN_STAGE or GROW).
        gcol = base + jnp.int32(W)
        col = gcol - stage_lo
        cap_read = jnp.min(
            jax.lax.dynamic_slice_in_dim(
                stage.create_win, jnp.clip(col, 0, L - 1), 1, axis=1
            )
        ).astype(jnp.int32)
        cap = jnp.where(
            gcol >= consts.trace_pod_bound,
            big,
            jnp.where(col < jnp.int32(L), cap_read, jnp.int32(-1)),
        )
        bound = jnp.minimum(cap, last)

        def run_branch(op):
            state, rank, w, spans = op
            can_chunk = (w + jnp.int32(chunk - 1)) <= bound

            def run_k(op2):
                state, rank, w = op2
                idxs = w + jnp.arange(chunk, dtype=jnp.int32)
                return step_windows(state, rank, idxs), rank, w + jnp.int32(chunk)

            def run_1(op2):
                state, rank, w = op2
                idxs = w + jnp.arange(1, dtype=jnp.int32)
                return step_windows(state, rank, idxs), rank, w + jnp.int32(1)

            state, rank, w = jax.lax.cond(
                can_chunk, run_k, run_1, (state, rank, w)
            )
            return state, rank, w, spans, jnp.int32(SUPERSPAN_RUN)

        def slide_branch(op):
            state, rank, w, spans = op
            s0 = _slide_shift_core(
                state.pods.phase[:, :W], stage.create_win, base - stage_lo
            )
            s = _quantize_shift_device(s0, W)
            blocked = s <= jnp.int32(0)
            # A blocked slide whose capacity column lies beyond the stage
            # (col >= L forced cap to -1 above) is staging exhaustion, not
            # growth: the TRUE capacity may still admit the next window, so
            # the engine must restage — GROW is only trustworthy when the
            # capacity read was in range.
            cap_unread = (col >= jnp.int32(L)) & (
                gcol < consts.trace_pod_bound
            )
            grow = blocked & ~cap_unread
            exhausted = (blocked & cap_unread) | (
                (~blocked)
                & ((base - stage_lo + jnp.int32(W) + s) > jnp.int32(L))
            )

            def apply(op2):
                state, rank = op2
                new_pods, new_rank = _slide_apply_traced(
                    state.pods, rank, pay, base - stage_lo, s, W
                )
                return (
                    state._replace(
                        pods=new_pods, pod_base=state.pod_base + s
                    ),
                    new_rank,
                )

            def skip(op2):
                return op2

            state, rank = jax.lax.cond(
                grow | exhausted, skip, apply, (state, rank)
            )
            code = jnp.where(
                grow,
                jnp.int32(SUPERSPAN_GROW),
                jnp.where(
                    exhausted,
                    jnp.int32(SUPERSPAN_STAGE),
                    jnp.int32(SUPERSPAN_RUN),
                ),
            )
            spans = spans + (code == SUPERSPAN_RUN).astype(jnp.int32)
            return state, rank, w, spans, code

        return jax.lax.cond(
            w <= bound, run_branch, slide_branch, (state, rank, w, spans)
        )

    progress = jnp.asarray(progress, jnp.int32)
    state, rank, w, spans, code = jax.lax.while_loop(
        cond,
        body,
        (state, rank, progress[0], jnp.int32(0), progress[3]),
    )
    if lane_major:
        state = swap_node_layout(state)
    progress_out = jnp.stack(
        [w, jnp.min(state.pod_base), spans, code]
    ).astype(jnp.int32)
    return state, rank, progress_out


_SUPERSPAN_STATICS = _STEP_STATICS + ("W", "K", "chunk")
run_superspan = partial(jax.jit, static_argnames=_SUPERSPAN_STATICS)(
    _run_superspan_impl
)
run_superspan_donated = jax.jit(
    _run_superspan_impl,
    static_argnames=_SUPERSPAN_STATICS,
    donate_argnums=(0,),
)
