"""The vectorized window step: trace-event application + pod finishes + one
scheduling cycle, over a whole batch of clusters at once.

This replaces the scalar event loop (reference: src/simulator.rs:355-372 pops
one event at a time) with array programs:

- Each control-plane hop of the reference becomes a time-shifted effect
  (SURVEY.md §5.8); the compiler pre-shifts event times to their effect times.
- Pod completions are precomputed finish times invalidated by masks (replacing
  DSLab cancel_event, reference: src/core/node_component.rs:102-104).
- Event application is BULK: the window's slab segment is gathered once per
  cluster, node/pod removal times become scatter-min arrays, and the
  finish-vs-removal interleaving is resolved elementwise per pod by comparing
  finish_time against min(window_end, node_removal_time, pod_removal_time) —
  ordering fidelity without a per-event loop.
- The kube-scheduler cycle is a COMPACTED sequential scan: the queue is sorted
  by (queue_ts, queue_seq) — identical to the scalar ActiveQueue's
  (timestamp, insertion seq) min-heap — the top-K candidates are gathered to
  (C, K) arrays, the scan updates only (C, N) allocatables per step (Fit mask +
  LeastAllocatedResources score + last-wins argmax, reference semantics:
  src/core/scheduler/kube_scheduler.rs:63-152, plugin.rs:33-63), and results
  scatter back to (C, P) once.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from kubernetriks_tpu.batched.state import (
    ClusterBatchState,
    EstArrays,
    EV_CREATE_NODE,
    EV_CREATE_POD,
    EV_REMOVE_NODE,
    EV_REMOVE_POD,
    PHASE_QUEUED,
    PHASE_REMOVED,
    PHASE_RUNNING,
    PHASE_SUCCEEDED,
    PHASE_UNSCHEDULABLE,
    StepConstants,
    TraceSlab,
)

INF = jnp.inf


def lexsort_i32(primary: jnp.ndarray, secondary: jnp.ndarray) -> jnp.ndarray:
    """Row-wise stable argsort by (primary, secondary) returning int32 indices.

    Equivalent to jnp.lexsort((secondary, primary), axis=1), but carries an
    int32 iota payload — under jax_enable_x64, jnp.lexsort's internal index
    iota is i64, which drags an emulated 64-bit lane through every (C, P)
    queue sort in the hot loop."""
    C, P = primary.shape
    iota = jnp.broadcast_to(jnp.arange(P, dtype=jnp.int32)[None, :], (C, P))
    _, _, order = jax.lax.sort(
        (primary, secondary, iota), dimension=1, num_keys=2, is_stable=True
    )
    return order


def _est_add_reduced(est: EstArrays, values: jnp.ndarray, mask: jnp.ndarray) -> EstArrays:
    """Fold a (C, P) masked batch of samples into (C,) estimator accumulators."""
    values = values.astype(jnp.float32)
    maskf = mask.astype(jnp.float32)
    return EstArrays(
        count=est.count + mask.sum(axis=1, dtype=jnp.int32),
        total=est.total + (values * maskf).sum(axis=1),
        total_sq=est.total_sq + (values * values * maskf).sum(axis=1),
        minimum=jnp.minimum(est.minimum, jnp.where(mask, values, INF).min(axis=1)),
        maximum=jnp.maximum(est.maximum, jnp.where(mask, values, -INF).max(axis=1)),
    )


def _apply_window_events(
    state: ClusterBatchState,
    slab: TraceSlab,
    window_end: jnp.ndarray,
    consts: StepConstants,
    max_events_per_window: int,
    conditional_move: bool = False,
) -> ClusterBatchState:
    """Apply every trace event with effect time STRICTLY before window_end, and
    resolve all pod finishes due in the window.

    Strictness: an effect landing exactly at cycle time T is processed after
    the cycle in the scalar kernel (older-event-id-first FIFO), so it belongs
    to the next window.

    Dtype note (applies to this whole module): jax_enable_x64 is on for the
    f64 time arrays, so every index/count op must pin an explicit 32-bit dtype
    — untyped arange/argmax/bool-sum default to i64 under x64, and stray i64
    lanes measurably slow the TPU hot loop (emulated 64-bit).
    """
    pods, nodes, metrics = state.pods, state.nodes, state.metrics
    C, P = pods.phase.shape
    N = nodes.alive.shape[1]
    E_total = slab.time.shape[1]
    E = max_events_per_window
    rows1 = jnp.arange(C, dtype=jnp.int32)
    rows = rows1[:, None]

    # Gather this window's slab segment: (C, E) starting at each cursor.
    offs = state.event_cursor[:, None] + jnp.arange(E, dtype=jnp.int32)[None, :]
    offs_c = jnp.clip(offs, 0, E_total - 1)
    ev_t = slab.time[rows, offs_c]
    ev_k = slab.kind[rows, offs_c]
    ev_s = slab.slot[rows, offs_c]
    valid = (offs < E_total) & (ev_t < window_end[:, None])

    is_cn = valid & (ev_k == EV_CREATE_NODE)
    is_rn = valid & (ev_k == EV_REMOVE_NODE)
    is_cp = valid & (ev_k == EV_CREATE_POD)
    is_rp = valid & (ev_k == EV_REMOVE_POD)

    # Scatter helpers: out-of-range slot drops the write.
    def drop_slot(mask, width):
        return jnp.where(mask, ev_s, width)

    # --- node creations -----------------------------------------------------
    created = (
        jnp.zeros((C, N), bool).at[rows, drop_slot(is_cn, N)].set(True, mode="drop")
    )
    # Pending autoscaler creations due this window (CA scale-up effects).
    pend_create = (nodes.create_time < window_end[:, None]) & ~nodes.alive
    created = created | pend_create
    node_create_time = jnp.where(pend_create, INF, nodes.create_time)
    # --- node removal times (scatter-min; +inf = not removed this window) ---
    node_removal = (
        jnp.full((C, N), INF)
        .at[rows, drop_slot(is_rn, N)]
        .min(jnp.where(is_rn, ev_t, INF), mode="drop")
    )
    # Pending autoscaler removals due this window (CA scale-down effects).
    pend_remove = jnp.where(
        nodes.remove_time < window_end[:, None], nodes.remove_time, INF
    )
    node_removal = jnp.minimum(node_removal, pend_remove)
    node_remove_time = jnp.where(pend_remove < INF, INF, nodes.remove_time)
    # --- pod creations ------------------------------------------------------
    pod_create_ts = (
        jnp.full((C, P), INF)
        .at[rows, drop_slot(is_cp, P)]
        .min(jnp.where(is_cp, ev_t, INF), mode="drop")
    )
    # Queue sequence numbers follow slab (== emission) order.
    create_rank = jnp.cumsum(is_cp, axis=1, dtype=jnp.int32) - 1
    pod_create_seq = (
        jnp.zeros((C, P), jnp.int32)
        .at[rows, drop_slot(is_cp, P)]
        .max(
            jnp.where(is_cp, state.queue_seq_counter[:, None] + create_rank, 0),
            mode="drop",
        )
    )
    n_creates = is_cp.sum(axis=1, dtype=jnp.int32)
    # --- pod removal times --------------------------------------------------
    pod_removal = (
        jnp.full((C, P), INF)
        .at[rows, drop_slot(is_rp, P)]
        .min(jnp.where(is_rp, ev_t, INF), mode="drop")
    )
    # Pending HPA scale-down removals due this window.
    pend_pod_removal = jnp.where(
        pods.removal_time < window_end[:, None], pods.removal_time, INF
    )
    pod_removal = jnp.minimum(pod_removal, pend_pod_removal)
    pod_removal_time = jnp.where(pend_pod_removal < INF, INF, pods.removal_time)

    # --- apply creations ----------------------------------------------------
    alive = nodes.alive | created
    alloc_cpu = jnp.where(created, nodes.cap_cpu, nodes.alloc_cpu)
    alloc_ram = jnp.where(created, nodes.cap_ram, nodes.alloc_ram)

    was_empty_created = (pods.phase == 0) & (pod_create_ts < INF)
    enqueue_ts = pod_create_ts + consts.delta_pod_enqueue
    phase = jnp.where(was_empty_created, PHASE_QUEUED, pods.phase)
    queue_ts = jnp.where(was_empty_created, enqueue_ts, pods.queue_ts)
    queue_seq = jnp.where(was_empty_created, pod_create_seq, pods.queue_seq)
    initial_attempt_ts = jnp.where(
        was_empty_created, enqueue_ts, pods.initial_attempt_ts
    )
    attempts = jnp.where(was_empty_created, 1, pods.attempts)

    # --- resolve running pods: finish vs node removal vs pod removal --------
    running = phase == PHASE_RUNNING
    node_idx = jnp.clip(pods.node, 0, None)
    pod_node_removal = jnp.where(
        pods.node >= 0, node_removal[rows, node_idx], INF
    )
    cutoff = jnp.minimum(
        jnp.minimum(window_end[:, None], pod_node_removal), pod_removal
    )
    finishes = running & (pods.finish_time <= cutoff)
    interrupted = running & ~finishes
    rescheds = interrupted & (pod_node_removal < pod_removal)
    removed_running = interrupted & (pod_removal <= pod_node_removal) & (pod_removal < INF)

    # Free resources of finished and removed-while-running pods (a dead node's
    # allocatable is irrelevant; slots are never reused).
    freed = finishes | removed_running
    alloc_cpu = alloc_cpu.at[rows, node_idx].add(jnp.where(freed, pods.req_cpu, 0))
    alloc_ram = alloc_ram.at[rows, node_idx].add(jnp.where(freed, pods.req_ram, 0))

    # Finished pods.
    n_done = finishes.sum(axis=1, dtype=jnp.int32)
    metrics = metrics._replace(
        pods_succeeded=metrics.pods_succeeded + n_done,
        terminated_pods=metrics.terminated_pods + n_done,
        pod_duration=_est_add_reduced(metrics.pod_duration, pods.duration, finishes),
        processed_nodes=metrics.processed_nodes + created.sum(axis=1, dtype=jnp.int32),
    )
    phase = jnp.where(finishes, PHASE_SUCCEEDED, phase)
    finish_time = jnp.where(finishes, INF, pods.finish_time)

    # Reschedule pods of removed nodes (reference: scheduler.rs:336-364; slot
    # order stands in for the scalar sorted-name order).
    resched_rank = jnp.cumsum(rescheds, axis=1, dtype=jnp.int32) - 1
    resched_ts = pod_node_removal + consts.delta_reschedule
    phase = jnp.where(rescheds, PHASE_QUEUED, phase)
    queue_ts = jnp.where(rescheds, resched_ts, queue_ts)
    queue_seq = jnp.where(
        rescheds, state.queue_seq_counter[:, None] + n_creates[:, None] + resched_rank,
        queue_seq,
    )
    initial_attempt_ts = jnp.where(rescheds, resched_ts, initial_attempt_ts)
    attempts = jnp.where(rescheds, 1, attempts)
    finish_time = jnp.where(rescheds, INF, finish_time)
    pod_node = jnp.where(rescheds, -1, pods.node)
    n_rescheds = rescheds.sum(axis=1, dtype=jnp.int32)

    # Removed-while-running pods terminate as removed
    # (reference: api_server.rs PodRemovedFromNode removed=true accounting).
    n_removed_running = removed_running.sum(axis=1, dtype=jnp.int32)
    metrics = metrics._replace(
        pods_removed=metrics.pods_removed + n_removed_running,
        terminated_pods=metrics.terminated_pods + n_removed_running,
    )
    phase = jnp.where(removed_running, PHASE_REMOVED, phase)
    finish_time = jnp.where(removed_running, INF, finish_time)

    # Removal of queued/unschedulable (or just-created) pods: dropped from the
    # queues with NO removed/terminated metrics (scalar parity: only
    # PodRemovedFromNode(removed=true) counts, reference: api_server.rs:345-368).
    removed_queued = (
        ((phase == PHASE_QUEUED) | (phase == PHASE_UNSCHEDULABLE))
        & (pod_removal < INF)
        & ~removed_running
    )
    phase = jnp.where(removed_queued, PHASE_REMOVED, phase)

    # Kill removed nodes AFTER pod resolution (resolution reads pre-window
    # alive only via pods.node indices, which is removal-independent).
    alive = alive & ~(node_removal < INF)

    applied = valid.sum(axis=1, dtype=jnp.int32)
    any_created_node = created.any(axis=1)
    any_freed = (n_done > 0) | (n_removed_running > 0)

    # Conditional-move budgets (consumed by prepare_cycle's wake scans when
    # enable_unscheduled_pods_conditional_move is on; reference pools budgets
    # per event, the batched path pools them per window): a new node
    # contributes its full allocatable (= capacity at creation,
    # scheduler.rs:393), a finished/removed pod its freed requests
    # (scheduler.rs:366-380). int64: pooled sums over N/P slots can exceed
    # int32 (e.g. thousands of 128 GiB nodes in one window) and the scalar
    # oracle's budgets are unbounded Python ints. Only computed when the
    # feature is on — the i64 reductions are emulated on TPU and nothing else
    # reads these fields.
    if conditional_move:
        wake_node_cpu = (created * nodes.cap_cpu.astype(jnp.int64)).sum(axis=1)
        wake_node_ram = (created * nodes.cap_ram.astype(jnp.int64)).sum(axis=1)
        wake_freed_cpu = jnp.where(freed, pods.req_cpu.astype(jnp.int64), 0).sum(axis=1)
        wake_freed_ram = jnp.where(freed, pods.req_ram.astype(jnp.int64), 0).sum(axis=1)
    else:
        wake_node_cpu = jnp.zeros_like(state.wake_node_cpu)
        wake_node_ram = jnp.zeros_like(state.wake_node_ram)
        wake_freed_cpu = jnp.zeros_like(state.wake_freed_cpu)
        wake_freed_ram = jnp.zeros_like(state.wake_freed_ram)

    return state._replace(
        nodes=nodes._replace(
            alive=alive,
            alloc_cpu=alloc_cpu,
            alloc_ram=alloc_ram,
            create_time=node_create_time,
            remove_time=node_remove_time,
        ),
        pods=pods._replace(
            phase=phase,
            queue_ts=queue_ts,
            queue_seq=queue_seq,
            initial_attempt_ts=initial_attempt_ts,
            attempts=attempts,
            node=pod_node,
            finish_time=finish_time,
            removal_time=pod_removal_time,
        ),
        metrics=metrics,
        event_cursor=state.event_cursor + applied,
        queue_seq_counter=state.queue_seq_counter + n_creates + n_rescheds,
        # Events of interest wake the unschedulable queue (flush-all policy,
        # reference: scheduler.rs:391-410,435-440,445-473).
        requeue_signal=state.requeue_signal | any_created_node | any_freed,
        wake_node_signal=state.wake_node_signal | any_created_node,
        wake_node_cpu=state.wake_node_cpu + wake_node_cpu,
        wake_node_ram=state.wake_node_ram + wake_node_ram,
        wake_freed_signal=state.wake_freed_signal | any_freed,
        wake_freed_cpu=state.wake_freed_cpu + wake_freed_cpu,
        wake_freed_ram=state.wake_freed_ram + wake_freed_ram,
        time=jnp.maximum(state.time, window_end),
    )


def _conditional_wake(
    state: ClusterBatchState, pods, stale: jnp.ndarray
) -> jnp.ndarray:
    """Resource-aware unschedulable wakes for
    enable_unscheduled_pods_conditional_move, replicating the reference's two
    greedy budget scans over the unschedulable queue in (insert_ts, name)
    order — here (queue_ts, queue_seq) order:

    - Node added (reference: src/core/scheduler/scheduler.rs:391-409): a pod
      that FITS the new node's allocatable consumes the budget and STAYS
      parked; a pod that does not fit moves to the active queue. (That
      inverted sense is the reference's actual behavior; preserved as-is.)
    - Resources freed by pod finish/removal (scheduler.rs:366-380,435-439,
      462-468): greedy first-fit against the freed budget — a pod that fits
      consumes the budget and MOVES.

    Deviation (documented): the scalar path runs one scan per event at its
    effect time; the batched path pools the budgets of all same-window events
    into one scan pass of each kind.
    """
    C, P = pods.phase.shape
    rows = jnp.arange(C, dtype=jnp.int32)[:, None]
    unsched = (pods.phase == PHASE_UNSCHEDULABLE) & ~stale

    u_ts = jnp.where(unsched, pods.queue_ts, INF)
    u_seq = jnp.where(unsched, pods.queue_seq, jnp.iinfo(jnp.int32).max)
    order = lexsort_i32(u_ts, u_seq)  # (C, P) unschedulable first
    o_valid = unsched[rows, order]
    o_req_cpu = pods.req_cpu[rows, order]
    o_req_ram = pods.req_ram[rows, order]

    def scan_body(carry, xs):
        node_cpu, node_ram, freed_cpu, freed_ram = carry
        valid, req_cpu, req_ram = xs
        # Scan 1: new-node budget — fits => consume + stay, else move.
        node_scan = valid & state.wake_node_signal
        fits_node = node_scan & (req_cpu <= node_cpu) & (req_ram <= node_ram)
        node_cpu = node_cpu - jnp.where(fits_node, req_cpu, 0)
        node_ram = node_ram - jnp.where(fits_node, req_ram, 0)
        move_no_fit = node_scan & ~fits_node
        # Scan 2: freed budget — fits => consume + move.
        freed_scan = valid & state.wake_freed_signal
        fits_freed = freed_scan & (req_cpu <= freed_cpu) & (req_ram <= freed_ram)
        freed_cpu = freed_cpu - jnp.where(fits_freed, req_cpu, 0)
        freed_ram = freed_ram - jnp.where(fits_freed, req_ram, 0)
        return (node_cpu, node_ram, freed_cpu, freed_ram), move_no_fit | fits_freed

    _, move_sorted = jax.lax.scan(
        scan_body,
        (
            state.wake_node_cpu,
            state.wake_node_ram,
            state.wake_freed_cpu,
            state.wake_freed_ram,
        ),
        (o_valid.T, o_req_cpu.T, o_req_ram.T),
    )
    # Scatter sorted-order decisions back to slot positions.
    return jnp.zeros((C, P), bool).at[rows, order].set(move_sorted.T)


class CycleCandidates(NamedTuple):
    """Compacted per-cycle scheduling candidates (top-K of the sorted queue);
    a pytree, so it composes with jit/scan like the rest of the state."""

    pods: "object"  # PodArrays with wake/flush moves applied
    last_flush_time: jnp.ndarray
    cand: jnp.ndarray  # (C, K) pod slots in queue order
    valid: jnp.ndarray  # (C, K)
    req_cpu: jnp.ndarray
    req_ram: jnp.ndarray
    duration: jnp.ndarray
    initial_ts: jnp.ndarray


def decision_mechanics(
    metrics,
    valid,
    assign,
    duration,
    T,
    cycle_dur,
    pod_queue_time,
    pod_sched_time,
    consts: StepConstants,
):
    """The per-pod timing/metric mechanics shared BIT-FOR-BIT by the lax.scan
    path, the Pallas path's mech scan, and the RL path: cycle-duration
    accumulation, start/finish/park timestamps, decision metrics. Keeping this
    in exactly one place is what guarantees scan/Pallas float-op parity."""
    time_dtype = T.dtype
    cycle_dur_post = cycle_dur + jnp.where(valid, pod_sched_time, 0.0)
    start = (T + cycle_dur_post + consts.delta_bind_start).astype(time_dtype)
    finish = jnp.where(duration >= 0, start + duration, INF).astype(time_dtype)
    # Unschedulable park: new insert timestamp = T + cycle duration
    # (reference: scheduler.rs:282-306).
    park_ts = (T + cycle_dur_post).astype(time_dtype)
    metrics = metrics._replace(
        scheduling_decisions=metrics.scheduling_decisions + assign.astype(jnp.int32),
        queue_time=metrics.queue_time.add(pod_queue_time, assign),
        algo_latency=metrics.algo_latency.add(pod_sched_time, assign),
    )
    return metrics, start, finish, park_ts, cycle_dur_post


def apply_decision(
    alloc_cpu,
    alloc_ram,
    metrics,
    valid,
    any_fit,
    action,
    req_cpu,
    req_ram,
    duration,
    T,
    cycle_dur,
    pod_queue_time,
    pod_sched_time,
    consts: StepConstants,
):
    """Decision-independent cycle mechanics shared by the kube and RL paths:
    commit one chosen node per cluster (resource reservation, start/finish
    computation, park timestamps, metric accounting). `action` is the chosen
    node slot; `any_fit` gates assignment vs unschedulable park."""
    C = valid.shape[0]
    rows1 = jnp.arange(C, dtype=jnp.int32)

    assign = valid & any_fit
    park = valid & ~any_fit

    action_c = jnp.clip(action, 0, None)
    alloc_cpu = alloc_cpu.at[rows1, action_c].add(jnp.where(assign, -req_cpu, 0))
    alloc_ram = alloc_ram.at[rows1, action_c].add(jnp.where(assign, -req_ram, 0))

    metrics, start, finish, park_ts, cycle_dur_post = decision_mechanics(
        metrics, valid, assign, duration, T, cycle_dur,
        pod_queue_time, pod_sched_time, consts,
    )
    return alloc_cpu, alloc_ram, metrics, assign, park, start, finish, park_ts, cycle_dur_post


def prepare_cycle(
    state: ClusterBatchState,
    T: jnp.ndarray,
    consts: StepConstants,
    K: int,
    conditional_move: bool = False,
) -> CycleCandidates:
    """Cycle preamble shared by the kube-scheduler and RL-policy cycles:
    unschedulable wake/flush moves, queue sort, top-K compaction."""
    rows = jnp.arange(state.pods.phase.shape[0], dtype=jnp.int32)[:, None]
    pods = state.pods

    # Unschedulable-leftover flush at the 30 s cadence
    # (reference: scheduler.rs:188-203).
    flush_now = (T - state.last_flush_time) >= consts.flush_interval
    stale = (
        (pods.phase == PHASE_UNSCHEDULABLE)
        & (T[:, None] - pods.queue_ts > consts.max_unschedulable_stay)
        & flush_now[:, None]
    )
    if conditional_move:
        wake = _conditional_wake(state, pods, stale)
    else:
        wake = state.requeue_signal[:, None] & (pods.phase == PHASE_UNSCHEDULABLE)
    to_move = stale | wake
    pods = pods._replace(
        phase=jnp.where(to_move, PHASE_QUEUED, pods.phase),
        attempts=pods.attempts + to_move.astype(jnp.int32),
    )
    last_flush_time = jnp.where(flush_now, T, state.last_flush_time)

    # Queue order: (queue_ts, queue_seq); eligible = queued strictly before T.
    eligible = (pods.phase == PHASE_QUEUED) & (pods.queue_ts < T[:, None])
    sort_ts = jnp.where(eligible, pods.queue_ts, INF)
    sort_seq = jnp.where(eligible, pods.queue_seq, jnp.iinfo(jnp.int32).max)
    order = lexsort_i32(sort_ts, sort_seq)  # (C, P)

    cand = order[:, :K]
    return CycleCandidates(
        pods=pods,
        last_flush_time=last_flush_time,
        cand=cand,
        valid=eligible[rows, cand],
        req_cpu=pods.req_cpu[rows, cand],
        req_ram=pods.req_ram[rows, cand],
        duration=pods.duration[rows, cand],
        initial_ts=pods.initial_attempt_ts[rows, cand],
    )


def commit_cycle(
    state: ClusterBatchState,
    cc: CycleCandidates,
    T: jnp.ndarray,
    alloc_cpu,
    alloc_ram,
    metrics,
    assign_k,
    park_k,
    best_k,
    start_k,
    finish_k,
    park_ts_k,
) -> ClusterBatchState:
    """Scatter the K per-cluster decisions back into (C, P) state."""
    C, P = cc.pods.phase.shape
    rows = jnp.arange(C, dtype=jnp.int32)[:, None]
    pods = cc.pods
    cand = cc.cand

    new_phase = jnp.where(
        assign_k,
        jnp.int32(PHASE_RUNNING),
        jnp.where(park_k, jnp.int32(PHASE_UNSCHEDULABLE), jnp.int32(-1)),
    ).astype(pods.phase.dtype)
    touched = assign_k | park_k
    phase = pods.phase.at[rows, jnp.where(touched, cand, P)].set(
        jnp.where(touched, new_phase, 0), mode="drop"
    )
    node = pods.node.at[rows, jnp.where(assign_k, cand, P)].set(
        jnp.where(assign_k, best_k, 0), mode="drop"
    )
    start_time = pods.start_time.at[rows, jnp.where(assign_k, cand, P)].set(
        jnp.where(assign_k, start_k, 0.0), mode="drop"
    )
    finish_time = pods.finish_time.at[rows, jnp.where(assign_k, cand, P)].set(
        jnp.where(assign_k, finish_k, 0.0), mode="drop"
    )
    queue_ts = pods.queue_ts.at[rows, jnp.where(park_k, cand, P)].set(
        jnp.where(park_k, park_ts_k, 0.0), mode="drop"
    )

    return state._replace(
        nodes=state.nodes._replace(alloc_cpu=alloc_cpu, alloc_ram=alloc_ram),
        pods=pods._replace(
            phase=phase,
            queue_ts=queue_ts,
            node=node,
            start_time=start_time,
            finish_time=finish_time,
        ),
        metrics=metrics,
        requeue_signal=jnp.zeros_like(state.requeue_signal),
        wake_node_signal=jnp.zeros_like(state.wake_node_signal),
        wake_node_cpu=jnp.zeros_like(state.wake_node_cpu),
        wake_node_ram=jnp.zeros_like(state.wake_node_ram),
        wake_freed_signal=jnp.zeros_like(state.wake_freed_signal),
        wake_freed_cpu=jnp.zeros_like(state.wake_freed_cpu),
        wake_freed_ram=jnp.zeros_like(state.wake_freed_ram),
        last_flush_time=cc.last_flush_time,
        time=jnp.maximum(state.time, T),
    )


def _run_scheduling_cycle(
    state: ClusterBatchState,
    T: jnp.ndarray,
    consts: StepConstants,
    max_pods_per_cycle: int,
    use_pallas: bool = False,
    pallas_interpret: bool = False,
    conditional_move: bool = False,
) -> ClusterBatchState:
    """One vectorized kube-scheduler cycle at time T for every cluster
    (scalar equivalent: reference scheduler.rs:246-333)."""
    C, P = state.pods.phase.shape
    N = state.nodes.alive.shape[1]

    cc = prepare_cycle(state, T, consts, max_pods_per_cycle, conditional_move)
    cand_valid, cand_req_cpu, cand_req_ram = cc.valid, cc.req_cpu, cc.req_ram
    cand_duration, cand_initial_ts = cc.duration, cc.initial_ts

    alive = state.nodes.alive
    alive_count = alive.sum(axis=1, dtype=jnp.int32).astype(jnp.float32)
    time_dtype = cc.pods.queue_ts.dtype

    if use_pallas:
        # The (C, N)-heavy core runs as a fused VMEM kernel; the (C,)-shaped
        # timing/metric mechanics below replicate the scan path's float-op
        # ordering exactly (see ops/scheduler_kernel.py).
        from kubernetriks_tpu.ops.scheduler_kernel import fused_schedule_cycle

        assign_k, fitany_k, best_k, alloc_cpu, alloc_ram = fused_schedule_cycle(
            alive,
            state.nodes.alloc_cpu,
            state.nodes.alloc_ram,
            cand_valid,
            cand_req_cpu,
            cand_req_ram,
            interpret=pallas_interpret,
        )
        park_k = cand_valid & ~fitany_k
        pod_sched_time = consts.time_per_node * alive_count  # (C,)

        def mech_body(carry, xs):
            cycle_dur, metrics = carry
            valid, assign, initial_ts, duration = xs
            pod_queue_time = T - initial_ts + cycle_dur
            metrics, start, finish, park_ts, cycle_dur_post = decision_mechanics(
                metrics, valid, assign, duration, T, cycle_dur,
                pod_queue_time, pod_sched_time, consts,
            )
            return (cycle_dur_post, metrics), (start, finish, park_ts)

        (_, metrics), (start_k, finish_k, park_ts_k) = jax.lax.scan(
            mech_body,
            (jnp.zeros((C,), time_dtype), state.metrics),
            (cand_valid.T, assign_k.T, cand_initial_ts.T, cand_duration.T),
        )
        return commit_cycle(
            state, cc, T, alloc_cpu, alloc_ram, metrics,
            assign_k, park_k, best_k, start_k.T, finish_k.T, park_ts_k.T,
        )

    def body(carry, xs):
        alloc_cpu, alloc_ram, cycle_dur, metrics = carry
        valid, req_cpu, req_ram, duration, initial_ts = xs

        # Queue time uses the cycle duration accumulated BEFORE this pod; the
        # assignment effect time uses it AFTER (reference: scheduler.rs:270-320).
        pod_queue_time = T - initial_ts + cycle_dur
        pod_sched_time = consts.time_per_node * alive_count

        # Fit filter + LeastAllocatedResources score (reference: plugin.rs:33-63).
        # Scores are float32 on BOTH batched paths (this scan and the Pallas
        # kernel) — f64 is emulated on TPU; the precision only affects argmax
        # tie-breaks between near-equal node scores, which the cross-path
        # equivalence tests cover.
        fit = (
            alive
            & (req_cpu[:, None] <= alloc_cpu)
            & (req_ram[:, None] <= alloc_ram)
        )
        alloc_cpu_f = alloc_cpu.astype(jnp.float32)
        alloc_ram_f = alloc_ram.astype(jnp.float32)
        cpu_score = jnp.where(
            alloc_cpu > 0,
            (alloc_cpu_f - req_cpu[:, None].astype(jnp.float32)) * 100.0 / alloc_cpu_f,
            -INF,
        )
        ram_score = jnp.where(
            alloc_ram > 0,
            (alloc_ram_f - req_ram[:, None].astype(jnp.float32)) * 100.0 / alloc_ram_f,
            -INF,
        )
        score = jnp.where(fit, (cpu_score + ram_score) * jnp.float32(0.5), -INF)
        # Last-max-wins argmax, matching the reference's `>=` sweep over
        # name-sorted nodes (kube_scheduler.rs:140-150).
        best = jnp.int32(N - 1) - jax.lax.argmax(score[:, ::-1], 1, jnp.int32)
        any_fit = fit.any(axis=1)

        (alloc_cpu, alloc_ram, metrics, assign, park, start, finish, park_ts,
         cycle_dur_post) = apply_decision(
            alloc_cpu, alloc_ram, metrics, valid, any_fit, best,
            req_cpu, req_ram, duration, T, cycle_dur,
            pod_queue_time, pod_sched_time, consts,
        )
        outs = (assign, park, best, start, finish, park_ts)
        return (alloc_cpu, alloc_ram, cycle_dur_post, metrics), outs

    xs = (
        cand_valid.T,
        cand_req_cpu.T,
        cand_req_ram.T,
        cand_duration.T,
        cand_initial_ts.T,
    )
    (alloc_cpu, alloc_ram, _, metrics), outs = jax.lax.scan(
        body,
        (state.nodes.alloc_cpu, state.nodes.alloc_ram, jnp.zeros((C,), time_dtype),
         state.metrics),
        xs,
    )
    assign_k, park_k, best_k, start_k, finish_k, park_ts_k = (o.T for o in outs)
    return commit_cycle(
        state, cc, T, alloc_cpu, alloc_ram, metrics,
        assign_k, park_k, best_k, start_k, finish_k, park_ts_k,
    )


def _window_body(
    state: ClusterBatchState,
    slab: TraceSlab,
    window_end: jnp.ndarray,
    consts: StepConstants,
    max_events_per_window: int,
    max_pods_per_cycle: int,
    autoscale_statics=None,
    max_ca_pods_per_cycle: int = 64,
    max_pods_per_scale_down: int = 8,
    use_pallas: bool = False,
    pallas_interpret: bool = False,
    conditional_move: bool = False,
) -> ClusterBatchState:
    window_end = jnp.broadcast_to(window_end, state.time.shape)
    state = _apply_window_events(
        state, slab, window_end, consts, max_events_per_window, conditional_move
    )
    state = _run_scheduling_cycle(
        state,
        window_end,
        consts,
        max_pods_per_cycle,
        use_pallas,
        pallas_interpret,
        conditional_move,
    )
    if autoscale_statics is not None:
        # Autoscaler ticks due by this window run after the scheduling cycle
        # (the scalar snapshot lands between cycles; SURVEY.md §3.5); their
        # effects land at composed future times via the pending-effect arrays.
        from kubernetriks_tpu.batched.autoscale import ca_pass, hpa_pass

        auto = state.auto
        state, auto = hpa_pass(state, auto, autoscale_statics, window_end)
        state, auto = ca_pass(
            state,
            auto,
            autoscale_statics,
            window_end,
            max_ca_pods_per_cycle,
            max_pods_per_scale_down,
        )
        state = state._replace(auto=auto)
    return state


_STEP_STATICS = (
    "max_events_per_window",
    "max_pods_per_cycle",
    "max_ca_pods_per_cycle",
    "max_pods_per_scale_down",
    "use_pallas",
    "pallas_interpret",
    "conditional_move",
)


@partial(jax.jit, static_argnames=_STEP_STATICS)
def window_step(
    state: ClusterBatchState,
    slab: TraceSlab,
    window_end: jnp.ndarray,
    consts: StepConstants,
    max_events_per_window: int,
    max_pods_per_cycle: int,
    autoscale_statics=None,
    max_ca_pods_per_cycle: int = 64,
    max_pods_per_scale_down: int = 8,
    use_pallas: bool = False,
    pallas_interpret: bool = False,
    conditional_move: bool = False,
) -> ClusterBatchState:
    """Advance every cluster to `window_end` (the next scheduling-cycle time)."""
    return _window_body(
        state,
        slab,
        window_end,
        consts,
        max_events_per_window,
        max_pods_per_cycle,
        autoscale_statics,
        max_ca_pods_per_cycle,
        max_pods_per_scale_down,
        use_pallas,
        pallas_interpret,
        conditional_move,
    )


@partial(jax.jit, static_argnames=_STEP_STATICS)
def run_windows(
    state: ClusterBatchState,
    slab: TraceSlab,
    window_ends: jnp.ndarray,
    consts: StepConstants,
    max_events_per_window: int,
    max_pods_per_cycle: int,
    autoscale_statics=None,
    max_ca_pods_per_cycle: int = 64,
    max_pods_per_scale_down: int = 8,
    use_pallas: bool = False,
    pallas_interpret: bool = False,
    conditional_move: bool = False,
) -> ClusterBatchState:
    """Scan a whole sequence of scheduling-cycle windows on-device (the hot
    benchmark loop: no host round-trips between cycles)."""

    def body(carry, w):
        return (
            _window_body(
                carry,
                slab,
                w,
                consts,
                max_events_per_window,
                max_pods_per_cycle,
                autoscale_statics,
                max_ca_pods_per_cycle,
                max_pods_per_scale_down,
                use_pallas,
                pallas_interpret,
                conditional_move,
            ),
            None,
        )

    state, _ = jax.lax.scan(body, state, window_ends)
    return state
