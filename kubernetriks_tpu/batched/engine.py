"""BatchedSimulation: the user-facing driver for the vectorized path.

Compiles traces to slabs, builds the dense state, steps whole batches of
clusters through scheduling-cycle windows on-device, and reduces metrics to
the same summary shape the scalar MetricsCollector prints.

Sharding: all state arrays lead with the cluster axis C; `mesh` shards that
axis across devices (pure data parallelism over simulated clusters — each
cluster is independent, so the step needs no cross-device collectives; metric
reduction at readout is the only communication).
"""

from __future__ import annotations

import math
from typing import Dict, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from kubernetriks_tpu.batched.state import (
    DEFAULT_RAM_UNIT,
    PHASE_QUEUED,
    PHASE_RUNNING,
    PHASE_UNSCHEDULABLE,
    TraceSlab,
    init_state,
    make_step_constants,
)
from kubernetriks_tpu.batched.step import run_windows, window_step
from kubernetriks_tpu.batched.trace_compile import (
    CompiledClusterTrace,
    compile_cluster_trace,
    pad_and_batch,
)
from kubernetriks_tpu.config import SimulationConfig


class BatchedSimulation:
    def __init__(
        self,
        config: SimulationConfig,
        compiled_traces: Sequence[CompiledClusterTrace],
        ram_unit: int = DEFAULT_RAM_UNIT,
        max_events_per_window: Optional[int] = None,
        max_pods_per_cycle: Optional[int] = None,
        mesh: Optional[Mesh] = None,
        batch_axis: str = "clusters",
    ) -> None:
        self.config = config
        if config.enable_unscheduled_pods_conditional_move:
            raise NotImplementedError(
                "enable_unscheduled_pods_conditional_move is not yet supported "
                "on the batched path (it always applies the reference's "
                "default flush-all policy); use the scalar path for "
                "conditional-move configs"
            )
        self.consts = make_step_constants(config)
        self.ram_unit = ram_unit
        C = len(compiled_traces)

        (
            ev_time,
            ev_kind,
            ev_slot,
            node_cap_cpu,
            node_cap_ram,
            pod_req_cpu,
            pod_req_ram,
            pod_duration,
        ) = pad_and_batch(compiled_traces)

        self.n_clusters = C
        self.n_nodes = node_cap_cpu.shape[1]
        self.n_pods = pod_req_cpu.shape[1]
        self.n_events = ev_time.shape[1]

        # Cap per-window event work: worst-case events falling in one window.
        if max_events_per_window is None:
            max_events_per_window = self._max_events_in_any_window(ev_time)
        self.max_events_per_window = max(1, max_events_per_window)
        # Cap per-cycle scheduling work (the scalar path drains the queue
        # unboundedly, reference scheduler.rs:261; the batched path bounds each
        # cycle and catches up next cycle).
        self.max_pods_per_cycle = max(1, max_pods_per_cycle or self.n_pods)

        self.state = init_state(
            C,
            self.n_nodes,
            self.n_pods,
            node_cap_cpu,
            node_cap_ram,
            pod_req_cpu,
            pod_req_ram,
            pod_duration,
        )
        self.slab = TraceSlab(
            time=jnp.asarray(ev_time),
            kind=jnp.asarray(ev_kind),
            slot=jnp.asarray(ev_slot),
        )
        self.node_names = [c.node_names for c in compiled_traces]
        self.pod_names = [c.pod_names for c in compiled_traces]
        self.next_window = 0.0

        self.mesh = mesh
        if mesh is not None:
            sharding = NamedSharding(mesh, PartitionSpec(batch_axis))
            self.state = jax.device_put(self.state, self._state_shardings(sharding))
            self.slab = jax.device_put(
                self.slab, NamedSharding(mesh, PartitionSpec(batch_axis, None))
            )

    def _state_shardings(self, sharding):
        """Every leaf leads with the C axis; shard axis 0, replicate the rest."""

        def leaf_sharding(leaf):
            spec = PartitionSpec(
                *([sharding.spec[0]] + [None] * (leaf.ndim - 1))
            )
            return NamedSharding(sharding.mesh, spec)

        return jax.tree.map(leaf_sharding, self.state)

    def _max_events_in_any_window(self, ev_time: np.ndarray) -> int:
        """Worst-case events falling into one (cluster, scheduling-window)
        bucket — the static per-window event budget."""
        interval = self.config.scheduling_cycle_interval
        rows, cols = np.nonzero(np.isfinite(ev_time))
        if rows.size == 0:
            return 1
        win = np.floor_divide(ev_time[rows, cols], interval).astype(np.int64)
        keys = rows * (win.max() + 2) + win
        _, per_key = np.unique(keys, return_counts=True)
        return int(per_key.max())

    # --- stepping -----------------------------------------------------------

    def window_times(self, until_time: float) -> np.ndarray:
        """Scheduling-cycle times in (next_window, until_time], starting at 0
        like the scalar scheduler.start()."""
        interval = self.config.scheduling_cycle_interval
        first = self.next_window
        count = int(math.floor((until_time - first) / interval)) + 1
        return first + np.arange(max(count, 0)) * interval

    def step_until_time(self, until_time: float) -> None:
        windows = self.window_times(until_time)
        if len(windows) == 0:
            return
        self.state = run_windows(
            self.state,
            self.slab,
            jnp.asarray(windows, self.state.time.dtype),
            self.consts,
            self.max_events_per_window,
            self.max_pods_per_cycle,
        )
        self.next_window = float(windows[-1]) + self.config.scheduling_cycle_interval

    def step_window(self) -> None:
        """Advance a single scheduling cycle (useful for tests)."""
        self.state = window_step(
            self.state,
            self.slab,
            jnp.asarray(self.next_window, self.state.time.dtype),
            self.consts,
            self.max_events_per_window,
            self.max_pods_per_cycle,
        )
        self.next_window += self.config.scheduling_cycle_interval

    def run_to_completion(self, max_time: float = 1e7) -> None:
        """Step until every trace pod has terminated (scalar equivalent:
        RunUntilAllPodsAreFinishedCallbacks), bounded by max_time."""
        interval = self.config.scheduling_cycle_interval
        chunk = max(64, self.max_events_per_window)
        finite = self.slab.time[jnp.isfinite(self.slab.time)]
        last_event_time = float(finite.max()) if finite.size else 0.0
        while True:
            self.step_until_time(self.next_window + chunk * interval)
            # Never conclude before the trace is fully applied: EMPTY slots may
            # still be waiting on future CreatePod events.
            if self.next_window <= last_event_time:
                continue
            phases = np.asarray(self.state.pods.phase)
            durations = np.asarray(self.state.pods.duration)
            # Finite-duration pods not yet terminal?
            live = (
                ((phases == PHASE_QUEUED) | (phases == PHASE_UNSCHEDULABLE))
                | ((phases == PHASE_RUNNING) & (durations >= 0))
            )
            if not live.any():
                return
            if self.next_window > max_time:
                raise RuntimeError(
                    f"run_to_completion exceeded max_time={max_time}; "
                    f"{int(live.sum())} pods still live"
                )

    # --- readout ------------------------------------------------------------

    def metrics_summary(self) -> Dict:
        """Cross-cluster reduction into the scalar printer's shape."""
        m = self.state.metrics

        def est(e):
            count = np.asarray(e.count, np.int64)
            total = np.asarray(e.total, np.float64)
            total_sq = np.asarray(e.total_sq, np.float64)
            n = count.sum()
            if n == 0:
                return {"min": math.inf, "max": -math.inf, "mean": math.nan, "variance": math.nan}
            mean = total.sum() / n
            return {
                "min": float(np.asarray(e.minimum).min()),
                "max": float(np.asarray(e.maximum).max()),
                "mean": float(mean),
                "variance": float(total_sq.sum() / n - mean * mean),
            }

        return {
            "counters": {
                "pods_succeeded": int(np.asarray(m.pods_succeeded).sum()),
                "pods_removed": int(np.asarray(m.pods_removed).sum()),
                "terminated_pods": int(np.asarray(m.terminated_pods).sum()),
                "processed_nodes": int(np.asarray(m.processed_nodes).sum()),
                "scheduling_decisions": int(np.asarray(m.scheduling_decisions).sum()),
            },
            "timings": {
                "pod_duration": est(m.pod_duration),
                "pod_schedule_time": est(m.algo_latency),
                "pod_queue_time": est(m.queue_time),
            },
        }

    def cluster_metrics(self, cluster: int) -> Dict:
        m = self.state.metrics
        return {
            "pods_succeeded": int(m.pods_succeeded[cluster]),
            "pods_removed": int(m.pods_removed[cluster]),
            "terminated_pods": int(m.terminated_pods[cluster]),
            "scheduling_decisions": int(m.scheduling_decisions[cluster]),
        }

    def pod_view(self, cluster: int) -> Dict[str, Dict]:
        """Name-keyed pod states for equivalence tests against the scalar path."""
        phases = np.asarray(self.state.pods.phase[cluster])
        nodes = np.asarray(self.state.pods.node[cluster])
        starts = np.asarray(self.state.pods.start_time[cluster])
        names = self.pod_names[cluster]
        node_names = self.node_names[cluster]
        out = {}
        for slot, name in enumerate(names):
            out[name] = {
                "phase": int(phases[slot]),
                "node": node_names[nodes[slot]] if nodes[slot] >= 0 else None,
                "start_time": float(starts[slot]),
            }
        return out


def build_batched_from_traces(
    config: SimulationConfig,
    cluster_events,
    workload_events,
    n_clusters: int = 1,
    **kwargs,
) -> BatchedSimulation:
    """Replicate one (cluster trace, workload trace) pair across n_clusters —
    the homogeneous-batch benchmark shape."""
    compiled = compile_cluster_trace(
        cluster_events,
        workload_events,
        config,
        ram_unit=kwargs.pop("ram_unit", DEFAULT_RAM_UNIT),
    )
    return BatchedSimulation(config, [compiled] * n_clusters, **kwargs)
