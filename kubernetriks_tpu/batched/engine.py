"""BatchedSimulation: the user-facing driver for the vectorized path.

Compiles traces to slabs, builds the dense state, steps whole batches of
clusters through scheduling-cycle windows on-device, and reduces metrics to
the same summary shape the scalar MetricsCollector prints.

Sharding: all state arrays lead with the cluster axis C; `mesh` shards that
axis across devices (pure data parallelism over simulated clusters — each
cluster is independent, so the step needs no cross-device collectives; metric
reduction at readout is the only communication).
"""

from __future__ import annotations

import math
import os
from functools import partial
from typing import Dict, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from kubernetriks_tpu.batched.autoscale import (
    AutoscaleStatics,
    init_autoscale_state,
)
from kubernetriks_tpu.parallel.multihost import (
    is_cross_process,
    put_global,
    to_host,
)
from kubernetriks_tpu.batched.state import (
    DEFAULT_RAM_UNIT,
    PHASE_QUEUED,
    PHASE_RUNNING,
    PHASE_UNSCHEDULABLE,
    RefillStage,
    TraceSlab,
    init_state,
    make_step_constants,
    swap_node_layout,
    tree_copy,
)
from kubernetriks_tpu.batched.timerep import TPair, from_f64_np, to_f64
from kubernetriks_tpu.batched.step import (
    _STEP_STATICS,
    _quantize_shift_device,
    _slide_apply_traced,
    _slide_shift_core,
    SUPERSPAN_GROW,
    SUPERSPAN_RUN,
    SUPERSPAN_STAGE,
    run_superspan,
    run_superspan_donated,
    run_windows,
    window_step,
)
from kubernetriks_tpu.batched.trace_compile import (
    CompiledClusterTrace,
    compile_cluster_trace,
    pad_and_batch,
)
from kubernetriks_tpu.config import SimulationConfig
from kubernetriks_tpu import sanitize
from kubernetriks_tpu.flags import (
    flag_bool,
    flag_int,
    flag_set,
    flag_str,
    flag_tristate,
)
from kubernetriks_tpu.telemetry import (
    GaugeSeries,
    NULL_TRACER,
    SpanTracer,
    log_chunk_throughput,
)
from kubernetriks_tpu.telemetry.tracer import (
    PH_CKPT_RESTORE,
    PH_CKPT_SAVE,
    PH_FUSED_CHUNK_SLIDE,
    PH_PRECOMPILE,
    PH_PROGRESS_WAIT,
    PH_REFILL_PREFETCH,
    PH_SHIFT_WAIT,
    PH_SLIDE,
    PH_STAGE_ASSEMBLE,
    PH_STAGE_PREFETCH,
    PH_STAGE_PUT,
    PH_STAGE_WAIT_FEEDER,
    PH_STAGE_WAIT_UPLOAD,
    PH_SUPERSPAN,
    PH_WINDOW_CHUNK,
    PH_WINDOW_GROW,
)


# Device-resident slide payload budget: req/ram + duration pair +
# create-win (+ name ranks under autoscalers) at (C, T + W) int32 each.
# Above this, the engine keeps the host slide path (payloads stay in RAM).
_DEVICE_SLIDE_BUDGET_BYTES = 2 << 30

# Checkpoint-meta coverage of the STRUCTURAL state leaves (= None default:
# their presence is part of the compiled program's identity, so a restore
# into a template missing them dies deep inside orbax). The stateleaf
# lint pass proves every structural ClusterBatchState/AutoscaleState leaf
# has an entry here — the value is the coverage story save_checkpoint /
# load_checkpoint implement (see those methods' guards).
CKPT_COVERED_LEAVES = {
    "auto": "presence derived from config at build; the restoring engine's "
    "own state template supplies the structure (same-config contract)",
    "telemetry": "meta['telemetry_ring'] + the armed/unarmed ring-size "
    "guard in load_checkpoint (both directions, meta-absent included)",
    "ca_alloc": "meta['reclaim'] — the follow-or-raise reclaim guard "
    "rebuilds/drops the leaf to match the checkpoint",
    "ca_total": "meta['reclaim'] (see ca_alloc)",
    "ca_reclaimed": "meta['reclaim'] (see ca_alloc)",
    "col_next": "config-derived: the collection latch arms exactly when "
    "real pod groups exist, so a same-config restore template matches",
    "col_run": "config-derived (see col_next)",
    "col_util_cpu": "config-derived (see col_next)",
    "col_util_ram": "config-derived (see col_next)",
}

# Power-of-two dispatch chunk ladder for the sliding path: any span is its
# binary decomposition (popcount(span) dispatches), and at most this many
# program shapes ever compile (engine.step_until_time; precompile_chunks
# AOT-compiles them up front).
_CHUNK_LADDER = (128, 64, 32, 16, 8, 4, 2, 1)


# The slide primitives (_slide_shift_core, _quantize_shift_device,
# _slide_apply_traced) moved to batched/step.py with the superspan executor
# (run_superspan needs them and engine imports step, not vice versa); the
# engine-side jitted shift entry keeps living here for the two-dispatch path.
_slide_shift_device = jax.jit(_slide_shift_core)


def _fused_chunk_slide_impl(
    state,
    slab,
    window_idxs,
    consts,
    payload,
    base,
    max_events_per_window: int,
    max_pods_per_cycle: int,
    autoscale_statics=None,
    max_ca_pods_per_cycle: int = 64,
    max_pods_per_scale_down: int = 8,
    use_pallas: bool = False,
    pallas_interpret: bool = False,
    conditional_move: bool = False,
    pallas_mesh=None,
    pallas_axis: str = "clusters",
    use_pallas_select: bool = False,
    use_megakernel: bool = True,
    hpa_seg=None,
    fault_params=None,
    name_ranks=None,
    lane_major: bool = False,
    window_razor: bool = True,
    ca_descatter: bool = True,
    reclaim: bool = False,
    reclaim_period: int = 1,
    profile=None,
    W: int = 0,
):
    """The composed path's steady-state MEGASTEP: one device program runs a
    whole window chunk (scheduling cycles + the in-trace HPA/CA passes of
    _window_body) AND the following pod-window slide — shift computation,
    quantization, gather-apply — with a traced shift amount. The engine
    dispatches this for the LAST ladder chunk of every slide span, so a span
    costs exactly popcount(span) dispatches and its only host sync is the
    asynchronous 4-byte readback of the returned shift (0 = no slide was
    possible; grow the window). Returns (state, new_pod_name_rank | None,
    shift)."""
    from kubernetriks_tpu.batched.step import _window_body

    if lane_major:
        # Hot node leaves flip to the kernels' (N, C) layout for the whole
        # chunk+slide program; state at rest stays row-major
        # (state.swap_node_layout). The slide itself is pod-side only.
        state = swap_node_layout(state)

    def body(carry, w):
        new = _window_body(
            carry,
            slab,
            w,
            consts,
            max_events_per_window,
            max_pods_per_cycle,
            autoscale_statics,
            max_ca_pods_per_cycle,
            max_pods_per_scale_down,
            use_pallas,
            pallas_interpret,
            conditional_move,
            pallas_mesh,
            pallas_axis,
            use_pallas_select,
            use_megakernel=use_megakernel,
            hpa_seg=hpa_seg,
            fault_params=fault_params,
            name_ranks=name_ranks,
            lane_major=lane_major,
            window_razor=window_razor,
            ca_descatter=ca_descatter,
            reclaim=reclaim,
            reclaim_period=reclaim_period,
            profile=profile,
        )
        return new, None

    state, _ = jax.lax.scan(body, state, jnp.asarray(window_idxs, jnp.int32))
    if lane_major:
        state = swap_node_layout(state)
    base = jnp.asarray(base, jnp.int32)
    s0 = _slide_shift_core(state.pods.phase[:, :W], payload["create_win"], base)
    s = _quantize_shift_device(s0, W)
    rank = (
        autoscale_statics.pod_name_rank
        if (autoscale_statics is not None and "rank" in payload)
        else None
    )
    new_pods, new_rank = _slide_apply_traced(
        state.pods, rank, payload, base, s, W
    )
    state = state._replace(pods=new_pods, pod_base=state.pod_base + s)
    return state, new_rank, s


# The fused program shares every window-program static (drift between the
# fused and plain programs' static sets would make a new kwarg traced in one
# of them) plus the slide's window width.
_FUSED_STATICS = _STEP_STATICS + ("W",)
_fused_chunk_slide = jax.jit(
    _fused_chunk_slide_impl, static_argnames=_FUSED_STATICS
)
_fused_chunk_slide_donated = jax.jit(
    _fused_chunk_slide_impl, static_argnames=_FUSED_STATICS, donate_argnums=(0,)
)




@partial(jax.jit, static_argnames=("s", "W"))
def _slide_apply_device(pods, rank, pay, base, s: int, W: int):
    """Apply a quantized window slide of `s` slots entirely on device:
    slice the refill segment out of the device-resident payload at
    base + W, build pristine refill slots with the SAME constructor
    init_state uses, and concatenate — no host round-trips. Also slides
    the windowed pod-name ranks (autoscale statics) when `rank` is given.
    Mirrors the host path in _advance_pod_window leaf-for-leaf."""
    from kubernetriks_tpu.batched.state import fresh_pod_arrays

    C = pods.phase.shape[0]
    start = (jnp.int32(0), base + jnp.int32(W))

    def sl(a):
        return jax.lax.dynamic_slice(a, start, (C, s))

    refill = fresh_pod_arrays(
        C,
        s,
        sl(pay["req_cpu"]),
        sl(pay["req_ram"]),
        TPair(win=sl(pay["dur_win"]), off=sl(pay["dur_off"])),
    )
    new_pods = jax.tree.map(
        lambda a, b: jnp.concatenate([a[:, s:W], b, a[:, W:]], axis=1),
        pods,
        refill,
    )
    new_rank = None
    if rank is not None:
        new_rank = jnp.concatenate(
            [rank[:, s:W], sl(pay["rank"]), rank[:, W:]], axis=1
        )
    return new_pods, new_rank


def _lex_name_ranks(names) -> np.ndarray:  # ktpu: sync-ok(host-side name-rank table builder over python name lists, no device values)
    """Rank of each slot's name in the stable lexicographic sort of
    `names` — THE scalar-parity ordering primitive (the scalar storage
    walks name-sorted snapshots). Used by both the autoscale statics and
    the standalone fault-run rank tables; keep them on this one
    implementation so the rank rules can't drift apart."""
    order = np.argsort(np.asarray(names, dtype=object), kind="stable")
    out = np.empty(len(names), np.int32)
    out[order] = np.arange(len(names), dtype=np.int32)
    return out


def _reclaim_class_tables(
    compiled_traces,
    group_names,
    reserves,
    n_trace_nodes: int,
    S: int,
):
    """Static name-CLASS tables for the CA slot-reclaim orders
    (autoscale.ca_name_order): one class per trace node (a singleton
    name) and one per CA node group (the decimal name FAMILY
    "{group}_{d}", d >= 1 — the scalar's total_allocated naming, which
    occupies the lexicographic interval ["{group}_1", "{group}_:") since
    every suffix starts with a digit 1-9 and ':' is the character after
    '9'). The global name order then decomposes into a static cross-class
    order plus the dynamic decimal-suffix order within a group — but ONLY
    if no class interleaves another. This verifies exactly that, per
    cluster, and returns (ca_slot_class (C, S), ca_class_start (C, Gn),
    node_class_key (C, N_total), None) on success or (None, None, None,
    reason) when the name sets make reclaim's order decomposition
    unsound (the engine then refuses or falls back, loudly).
    """
    C = len(compiled_traces)
    Gn = len(group_names)
    fams = [(f"{name}_1", f"{name}_:") for name in group_names]
    for i in range(Gn):
        for j in range(i + 1, Gn):
            lo_i, hi_i = fams[i]
            lo_j, hi_j = fams[j]
            if lo_i < hi_j and lo_j < hi_i:
                return None, None, None, (
                    f"CA node-group name families {group_names[i]!r} and "
                    f"{group_names[j]!r} interleave lexicographically"
                )
    PAD_KEY = np.int32(1 << 30)
    ca_slot_class = np.zeros((C, S), np.int32)
    ca_class_start = np.zeros((C, Gn), np.int32)
    node_class_key = np.full((C, n_trace_nodes + S), PAD_KEY, np.int32)
    memo: dict = {}
    for ci, trace in enumerate(compiled_traces):
        names = list(trace.node_names[:n_trace_nodes])
        key = id(trace)
        got = memo.get(key)
        if got is None:
            for t in names:
                for gi, (lo, hi) in enumerate(fams):
                    if lo <= t < hi:
                        return None, None, None, (
                            f"trace node name {t!r} falls inside CA "
                            f"group {group_names[gi]!r}'s name family"
                        )
            # Total class order: singletons by their name, families by
            # their interval start (disjoint intervals make this the
            # global lexicographic order of every current & future name).
            entries = [(t, ("t", slot)) for slot, t in enumerate(names)]
            entries += [
                (fams[gi][0], ("f", gi)) for gi in range(Gn)
            ]
            entries.sort(key=lambda e: e[0])
            n_classes = len(entries)
            if n_classes * (S + 1) >= (1 << 31) - (S + 1):
                return None, None, None, (
                    f"{n_classes} name classes x (S + 1 = {S + 1}) "
                    "overflows the int32 name-key space"
                )
            trace_rank = np.full(n_trace_nodes, -1, np.int64)
            fam_rank = np.zeros(Gn, np.int64)
            for rank, (_, tag) in enumerate(entries):
                if tag[0] == "t":
                    trace_rank[tag[1]] = rank
                else:
                    fam_rank[tag[1]] = rank
            got = memo[key] = (trace_rank, fam_rank)
        trace_rank, fam_rank = got
        nk = node_class_key[ci]
        named = trace_rank >= 0
        nk[:n_trace_nodes][named] = (trace_rank[named] * (S + 1)).astype(
            np.int32
        )
        cursor = 0
        for gi, reserve in enumerate(reserves):
            ca_slot_class[ci, cursor : cursor + reserve] = np.int32(
                fam_rank[gi]
            )
            nk[n_trace_nodes + cursor : n_trace_nodes + cursor + reserve] = (
                np.int32(fam_rank[gi] * (S + 1))
            )
            cursor += reserve
        # First class-sorted slot position of each group's reserve: the
        # groups in family-class order, cumulative reserve widths.
        order = np.argsort(fam_rank, kind="stable")
        pos = 0
        for gi in order:
            ca_class_start[ci, gi] = pos
            pos += reserves[gi]
    return ca_slot_class, ca_class_start, node_class_key, None


def build_autoscale_statics(
    config: SimulationConfig,
    compiled_traces,
    n_pods: int,
    n_trace_nodes: int,
    ram_unit: int,
    ca_slot_multiplier: int = 2,
    pod_slot_offset: int = 0,
    sliding: bool = False,
    scenario=None,
):
    """Host-side compilation of pod-group (HPA) and node-group (CA) tables.
    pod_slot_offset: global-to-device pod-slot shift for the resident
    pod-group segment under a sliding pod window (0 = full-resident); the
    HPA tables live entirely in DEVICE coordinates.

    scenario: optional per-lane override vectors (fleet.SCENARIO_KEYS,
    each (C,)) — the scenario-bearing control-law parameters (scan
    intervals, thresholds, CA period, autoscaler-chain delays, per-lane
    enables/quotas) are ALWAYS composed per-cluster through
    fleet.scenario_leaves and land as (C,)-shaped traced leaves, so one
    compiled program serves any scenario mix; with scenario=None every
    lane carries the base config's values (value-identical to the
    pre-fleet scalar fold).

    Returns (statics, extra_node_cap_cpu (S,), extra_node_cap_ram (S,),
    extra_node_names, aux); the extra node slots are the CA's reserved slots,
    appended after the trace's node slots (the batched analog of pre-sizing the
    component pool with the autoscaler max, reference: src/simulator.rs:212-230;
    slots are never reused, hence the churn multiplier). aux carries the
    host-side tables engine.update_scenario needs to recompose leaves
    without rebuilding (pg_active_when_on: (C, Gp) f64 activation times
    as if the HPA were on everywhere; +inf on padding groups)."""
    from kubernetriks_tpu.batched.fleet import scenario_leaves

    C = len(compiled_traces)
    ca_on = config.cluster_autoscaler.enabled
    leaves = scenario_leaves(config, C, scenario)

    # --- HPA pod groups -----------------------------------------------------
    Gp = max((len(c.pod_groups) for c in compiled_traces), default=0) or 1
    U = 1
    for c in compiled_traces:
        for g in c.pod_groups:
            U = max(U, len(g.cpu_units), len(g.ram_units))

    pg_slot_start = np.zeros((C, Gp), np.int32)
    pg_slot_count = np.zeros((C, Gp), np.int32)
    pg_initial = np.zeros((C, Gp), np.int32)
    pg_max_pods = np.zeros((C, Gp), np.int32)
    pg_target_cpu = np.zeros((C, Gp), np.float32)
    pg_target_ram = np.zeros((C, Gp), np.float32)
    pg_active_from = np.full((C, Gp), np.inf, np.float64)
    pg_active_when_on = np.full((C, Gp), np.inf, np.float64)
    pg_creation_s = np.zeros((C, Gp), np.float64)
    pg_cpu_dur = np.zeros((C, Gp, U), np.float32)
    pg_cpu_load = np.zeros((C, Gp, U), np.float32)
    pg_cpu_const = np.zeros((C, Gp), bool)
    pg_ram_dur = np.zeros((C, Gp, U), np.float32)
    pg_ram_load = np.zeros((C, Gp, U), np.float32)
    pg_ram_const = np.zeros((C, Gp), bool)
    pod_group_id = np.full((C, n_pods), -1, np.int32)

    for ci, c in enumerate(compiled_traces):
        for gi, g in enumerate(c.pod_groups):
            pg_slot_start[ci, gi] = g.slot_start - pod_slot_offset
            pg_slot_count[ci, gi] = g.slot_count
            pg_initial[ci, gi] = g.initial
            pg_max_pods[ci, gi] = g.max_pods
            pg_target_cpu[ci, gi] = g.target_cpu
            pg_target_ram[ci, gi] = g.target_ram
            # With HPA disabled the group's initial pods still run (the
            # api-server expansion is unconditional) but no cycle ever acts.
            # active_from = creation + register delay (the first HPA tick that
            # sees the group, reference: horizontal_pod_autoscaler.rs:187-198).
            # Per-LANE enable (scenario vector): a disabled lane parks its
            # groups at +inf — the data encoding of "HPA off" the fleet's
            # lane configs use.
            pg_creation_s[ci, gi] = g.creation_time
            pg_active_when_on[ci, gi] = (
                g.creation_time + config.as_to_hpa_network_delay
            )
            pg_active_from[ci, gi] = (
                pg_active_when_on[ci, gi]
                if leaves["hpa_enabled"][ci]
                else np.inf
            )
            for ui, (dur, load) in enumerate(g.cpu_units):
                pg_cpu_dur[ci, gi, ui] = dur
                pg_cpu_load[ci, gi, ui] = load
            pg_cpu_const[ci, gi] = g.cpu_const
            for ui, (dur, load) in enumerate(g.ram_units):
                pg_ram_dur[ci, gi, ui] = dur
                pg_ram_load[ci, gi, ui] = load
            pg_ram_const[ci, gi] = g.ram_const
            dev_start = g.slot_start - pod_slot_offset
            pod_group_id[ci, dev_start : dev_start + g.slot_count] = gi

    # --- CA node groups -----------------------------------------------------
    ca_config = config.cluster_autoscaler
    groups = (
        sorted(
            ca_config.node_groups, key=lambda g: g.node_template.metadata.name
        )
        if ca_on
        else []
    )
    Gn = len(groups) or 1
    reserves = []
    for g in groups:
        per_group_cap = g.max_count if g.max_count is not None else ca_config.max_node_count
        reserves.append(min(per_group_cap, ca_config.max_node_count) * ca_slot_multiplier)
    S = sum(reserves) or 1

    ng_ca_start = np.zeros((C, Gn), np.int32)
    ng_slot_count = np.zeros((C, Gn), np.int32)
    ng_max_count = np.full((C, Gn), -1, np.int32)
    ng_tmpl_cpu = np.zeros((C, Gn), np.int32)
    ng_tmpl_ram = np.zeros((C, Gn), np.int32)
    ca_slots = np.full((C, S), -1, np.int32)
    ca_slot_group = np.full((C, S), -1, np.int32)
    extra_cap_cpu = np.zeros((S,), np.int32)
    extra_cap_ram = np.zeros((S,), np.int32)
    extra_node_names = []

    cursor = 0
    for gi, (g, reserve) in enumerate(zip(groups, reserves)):
        name = g.node_template.metadata.name
        assert name, "CA node templates must be named"
        cap = g.node_template.status.capacity
        ng_ca_start[:, gi] = cursor
        ng_slot_count[:, gi] = reserve
        ng_max_count[:, gi] = -1 if g.max_count is None else g.max_count
        ng_tmpl_cpu[:, gi] = int(cap.cpu)
        ng_tmpl_ram[:, gi] = int(cap.ram) // ram_unit
        for k in range(reserve):
            ca_slots[:, cursor + k] = n_trace_nodes + cursor + k
            ca_slot_group[:, cursor + k] = gi
            extra_cap_cpu[cursor + k] = int(cap.cpu)
            extra_cap_ram[cursor + k] = int(cap.ram) // ram_unit
            extra_node_names.append(f"{name}_{k + 1}")
        cursor += reserve

    interval = config.scheduling_cycle_interval

    def pair(x) -> TPair:
        """Scalar or array seconds -> device TPair (host-side f64 split)."""
        w, o = from_f64_np(np.asarray(x, np.float64), interval)
        return TPair(win=jnp.asarray(w), off=jnp.asarray(o))

    f64 = lambda x: jnp.asarray(x, jnp.float64)  # noqa: E731

    # Scenario-bearing control-law parameters (scan intervals, thresholds,
    # the drifting CA period, the autoscaler-chain delay compositions) are
    # composed per-LANE by fleet.scenario_leaves — the one owner of those
    # formulas (incl. the cluster_autoscaler.rs:256-262 overrun rule) —
    # and land below as (C,)-shaped traced leaves.

    # Lexicographic name ranks of the trace's pods (device slot coords):
    # the storage's unscheduled-cache snapshot is name-sorted
    # (persistent_storage.py scale_up_info; reference
    # persistent_storage.rs:137-146), and the CA bin-packs in that order.
    # Ranks are static only while device slots don't shift — under a
    # sliding pod window they stay BIG and the cache keeps insertion order
    # (count-exact, identity documented in docs/PARITY.md). HPA ring slots
    # beyond the trace's initial replicas get fresh names at runtime and
    # likewise stay BIG.
    BIG_RANK = np.int32(1 << 30)
    # Tiled batches repeat a handful of compiled traces across many
    # clusters; memoize the object-dtype argsorts per unique trace.
    _rank_cache: dict = {}

    def _ranks_for(names_key, names):
        got = _rank_cache.get(names_key)
        if got is None:
            got = _rank_cache[names_key] = _lex_name_ranks(names)
        return got

    pod_name_rank = np.full((C, n_pods), BIG_RANK, np.int32)
    if not sliding and pod_slot_offset == 0:
        for ci, trace in enumerate(compiled_traces):
            ranks = _ranks_for(("pod", id(trace)), trace.pod_names[:n_pods])
            pod_name_rank[ci, : len(ranks)] = ranks

    # Node-name ranks over trace nodes + CA slots (slot names are static:
    # slot k of group g is always "{g}_{k+1}", matching the scalar's
    # total_allocated naming). The CA scale-down walks candidates and
    # first-fits re-placements in NAME order (info.nodes is name-sorted,
    # persistent_storage.sorted_nodes) — slot order differs once a name set
    # straddles a digit boundary ("g_10" < "g_2") or trace names interleave.
    # The node axis only gains the S reserved CA slots when the engine
    # actually appends them (CA on with named groups) — the rank array must
    # match the axis exactly (a stale +S here broadcast-crashed HPA-only
    # configs with >1 node; N=1 configs masked it via size-1 broadcasting).
    N_total = n_trace_nodes + (S if extra_node_names else 0)
    node_name_rank = np.full((C, N_total), BIG_RANK, np.int32)
    ca_sd_order = np.tile(np.arange(S, dtype=np.int32), (C, 1))
    for ci, trace in enumerate(compiled_traces):
        names = list(trace.node_names[:n_trace_nodes]) + extra_node_names
        ranks = _ranks_for(("node", id(trace)), names)
        node_name_rank[ci, : len(ranks)] = ranks
        if extra_node_names:
            ca_ranks = node_name_rank[ci, n_trace_nodes:]
            ca_sd_order[ci] = np.argsort(ca_ranks, kind="stable").astype(
                np.int32
            )

    # Reclaim name-order tables (r14): built whenever a CA reserve exists
    # and the name classes verify non-interleaving; otherwise None with
    # the reason in aux — the engine falls back (or raises on an explicit
    # reclaim=True) instead of running an unsound order decomposition.
    rc_slot_class = rc_class_start = rc_node_key = None
    if ca_on and extra_node_names:
        rc_slot_class, rc_class_start, rc_node_key, reclaim_reason = (
            _reclaim_class_tables(
                compiled_traces,
                [g.node_template.metadata.name for g in groups],
                reserves,
                n_trace_nodes,
                S,
            )
        )
    elif ca_on:
        reclaim_reason = "the CA reserve is empty (no named node groups)"
    else:
        reclaim_reason = "the cluster autoscaler is disabled"

    # The scalar metrics collector's fixed pod-utilization pull cadence
    # (60 s), as device time for the HPA collection latch.
    from kubernetriks_tpu.metrics.collector import MetricsCollector

    statics = AutoscaleStatics(
        pg_slot_start=jnp.asarray(pg_slot_start),
        pg_slot_count=jnp.asarray(pg_slot_count),
        pg_initial=jnp.asarray(pg_initial),
        pg_max_pods=jnp.asarray(pg_max_pods),
        pg_target_cpu=jnp.asarray(pg_target_cpu),
        pg_target_ram=jnp.asarray(pg_target_ram),
        pg_active_from=pair(pg_active_from),
        pg_creation_s=jnp.asarray(pg_creation_s),
        pg_cpu_dur=jnp.asarray(pg_cpu_dur),
        pg_cpu_load=jnp.asarray(pg_cpu_load),
        pg_cpu_total=jnp.asarray(pg_cpu_dur.sum(axis=-1)),
        pg_cpu_const=jnp.asarray(pg_cpu_const),
        pg_ram_dur=jnp.asarray(pg_ram_dur),
        pg_ram_load=jnp.asarray(pg_ram_load),
        pg_ram_total=jnp.asarray(pg_ram_dur.sum(axis=-1)),
        pg_ram_const=jnp.asarray(pg_ram_const),
        pod_group_id=jnp.asarray(pod_group_id),
        ng_ca_start=jnp.asarray(ng_ca_start),
        ng_slot_count=jnp.asarray(ng_slot_count),
        ng_max_count=jnp.asarray(ng_max_count),
        ng_tmpl_cpu=jnp.asarray(ng_tmpl_cpu),
        ng_tmpl_ram=jnp.asarray(ng_tmpl_ram),
        ca_max_nodes=jnp.asarray(leaves["ca_max_nodes"], jnp.int32),
        ca_slots=jnp.asarray(ca_slots),
        ca_slot_group=jnp.asarray(ca_slot_group),
        hpa_interval=pair(leaves["hpa_interval_s"]),
        hpa_tolerance=f64(leaves["hpa_tolerance"]),
        ca_threshold=f64(leaves["ca_threshold"]),
        d_hpa_up=pair(leaves["d_hpa_up_s"]),
        d_hpa_down=pair(leaves["d_hpa_down_s"]),
        d_ca_up=pair(leaves["d_ca_up_s"]),
        d_ca_down=pair(leaves["d_ca_down_s"]),
        ca_period=pair(leaves["ca_period_s"]),
        ca_snap=pair(leaves["ca_snap_s"]),
        ca_finish_vis=pair(leaves["ca_finish_vis_s"]),
        ca_commit_vis=pair(leaves["ca_commit_vis_s"]),
        pod_name_rank=jnp.asarray(pod_name_rank),
        node_name_rank=jnp.asarray(node_name_rank),
        ca_sd_order=jnp.asarray(ca_sd_order),
        col_interval=pair(
            np.full((C,), MetricsCollector.COLLECTION_INTERVAL, np.float64)
        ),
        ca_slot_class=(
            None if rc_slot_class is None else jnp.asarray(rc_slot_class)
        ),
        ca_class_start=(
            None if rc_class_start is None else jnp.asarray(rc_class_start)
        ),
        node_class_key=(
            None if rc_node_key is None else jnp.asarray(rc_node_key)
        ),
    )
    aux = {
        "pg_active_when_on": pg_active_when_on,
        "reclaim_unsupported": reclaim_reason,
    }
    return statics, extra_cap_cpu, extra_cap_ram, extra_node_names, aux


class BatchedSimulation:
    def __init__(  # ktpu: sync-ok(engine build: cold-path host compilation of traces/tables, outside every timed region)
        self,
        config: SimulationConfig,
        compiled_traces: Sequence[CompiledClusterTrace],
        ram_unit: int = DEFAULT_RAM_UNIT,
        max_events_per_window: Optional[int] = None,
        max_pods_per_cycle: Optional[int] = None,
        mesh: Optional[Mesh] = None,
        batch_axis: str = "clusters",
        ca_slot_multiplier: int = 2,
        max_ca_pods_per_cycle: int = 64,
        max_pods_per_scale_down: int = 8,
        use_pallas: Optional[bool] = None,
        pallas_interpret: bool = False,
        pod_window: Optional[int] = None,
        fast_forward: Optional[bool] = None,
        donate: Optional[bool] = None,
        fuse_slide: Optional[bool] = None,
        superspan: Optional[bool] = None,
        superspan_k: Optional[int] = None,
        superspan_chunk: Optional[int] = None,
        superspan_stage_cols: Optional[int] = None,
        stream: Optional[bool] = None,
        stream_depth: Optional[int] = None,
        stream_segment: Optional[int] = None,
        sanitize_mode: Optional[bool] = None,
        telemetry: Optional[bool] = None,
        telemetry_ring: int = 1024,
        watchdog: Optional[bool] = None,
        lane_major: Optional[bool] = None,
        window_razor: Optional[bool] = None,
        ca_descatter: Optional[bool] = None,
        reclaim: Optional[bool] = None,
        reclaim_period: Optional[int] = None,
        scheduler_profile=None,
        scenario=None,
        lane_async: bool = False,
        tuned_profile=None,
    ) -> None:
        self.config = config
        # Tuned-statics profile seam (PR 20, tune/): resolution order for
        # the profile SOURCE is explicit arg > KTPU_TUNED_PROFILE (a
        # path, or 1/auto resolving artifacts/tuned/ then the bundled
        # tune/profiles/ dir by backend + geometry) > nothing; per KNOB
        # the order stays explicit kwarg > the knob's own env flag >
        # tuned-profile entry > hand-picked platform default, so a
        # profile never overrides a value someone pinned by hand. An
        # explicitly named profile raises on backend/geometry mismatch
        # (naming the field); the n_nodes half of the key is re-checked
        # after the statics build below, where N is finally known.
        from kubernetriks_tpu.tune.profile import resolve_build_profile

        self.tuned_profile = resolve_build_profile(
            tuned_profile,
            backend=jax.default_backend(),
            n_clusters=len(compiled_traces),
        )
        _tuned = (
            self.tuned_profile.statics if self.tuned_profile else {}
        )
        # Scenario-vector fleet (batched/fleet.py): optional per-lane
        # override vectors for the autoscaler control-law parameters.
        # Validated + normalized to (C,) numpy arrays here; the statics
        # build below composes them into the (C,)-shaped traced leaves
        # and the chaos block installs per-lane pod-fault seeds as
        # consts.fault_seed. None = every lane runs the base config
        # (value-identical leaves to the pre-fleet scalar fold).
        from kubernetriks_tpu.batched.fleet import normalize_scenario

        self._scenario = normalize_scenario(scenario, len(compiled_traces))
        # Compiled scheduler profile (batched/pipeline.py): the configured
        # Filter/Score plugin profile lowered to kernel statics. Resolution
        # order: explicit arg > config.scheduler_profile > KTPU_PROFILE env
        # (bench/CLI selection) > the reference default. compile_profile
        # RAISES (UnsupportedProfileError, naming the plugin and the
        # supported set) on anything the batched path cannot lower —
        # never a silent fallback to the hard-coded default.
        from kubernetriks_tpu.batched.pipeline import compile_profile

        if scheduler_profile is None:
            scheduler_profile = getattr(config, "scheduler_profile", None)
        if scheduler_profile is None:
            scheduler_profile = flag_str("KTPU_PROFILE")
        self.profile = compile_profile(scheduler_profile)
        # Flight recorder (KTPU_TRACE / telemetry arg): host-side span
        # tracer over every dispatch phase + the device-side per-window
        # metrics ring carried in ClusterBatchState (attached below, once
        # C is known). Off: NULL_TRACER no-ops and the state carries
        # telemetry=None, compiling programs identical to the
        # pre-telemetry build. telemetry_ring: ring capacity in windows
        # (the engine drains before wrap at existing sync boundaries).
        if telemetry is not None:
            self._telemetry = bool(telemetry)
        else:
            self._telemetry = flag_bool("KTPU_TRACE")
        self.tracer = SpanTracer() if self._telemetry else NULL_TRACER
        self._telemetry_ring_size = max(8, int(telemetry_ring))
        # Saturation watchdog (KTPU_WATCHDOG / watchdog arg): the capacity
        # observatory's trajectory checks over the ring's reserve-occupancy
        # columns (telemetry/observatory.py). Rides the flight recorder —
        # unset means "armed exactly when telemetry is"; an explicit
        # watchdog=True with telemetry off would silently watch nothing,
        # so it raises (the stream-without-superspan precedent).
        if watchdog is not None:
            self._watchdog = bool(watchdog)
        else:
            env = flag_tristate("KTPU_WATCHDOG")
            self._watchdog = self._telemetry if env is None else bool(env)
        if self._watchdog and not self._telemetry:
            raise ValueError(
                "watchdog=True requires the flight recorder (telemetry="
                "True / KTPU_TRACE=1): the saturation watchdog reads the "
                "device ring's reserve-occupancy columns"
            )
        # window-index -> (C, K) drained ring rows, deduped across
        # overlapping drains (telemetry/ring.py) and BOUNDED: the host
        # series keeps at most telemetry_series_windows distinct windows
        # (oldest pruned first, disclosed as ring.series_dropped_windows)
        # — without the cap the observatory's lossless mid-call drains
        # would re-grow an O(T) host term on exactly the endurance runs
        # they exist to watch. The default (64k windows ≈ 11 MB at the
        # composed shape) far exceeds any bench/test span; endurance
        # consumers stream the full series through the JSONL exporter
        # instead of holding it resident.
        self._ring_seen: dict = {}
        self.telemetry_series_windows = 1 << 16
        self._ring_series_dropped = 0
        self._ring_windows_recorded = 0  # device cursor high-water mark
        self._ring_drained_at = 0  # window cursor of the last ring drain
        self._pending_flow = 0  # tracer flow id of an in-flight readback
        # Runtime sanitizer (KTPU_SANITIZE / sanitize_mode arg): the
        # steady-state dispatch region runs under a device-to-host
        # transfer guard (waived syncs carry explicit allow scopes that
        # mirror the lint pass's sync-ok waivers), donated inputs are
        # force-deleted after donated calls so read-after-donate raises
        # even on CPU, and the KTPU_DEBUG_FINITE sweep runs at every
        # dispatch boundary. See kubernetriks_tpu/sanitize.py.
        self._sanitize = (
            bool(sanitize_mode)
            if sanitize_mode is not None
            else sanitize.sanitize_default()
        )
        # Buffer donation (KTPU_DONATE / donate arg): the steady-state
        # dispatch loop consumes its input state buffers in place instead of
        # re-materializing the full (C,N)/(C,P) state every dispatch.
        # Bit-identical either way (tests/test_window_donation_dispatch.py);
        # anything that must keep self.state valid across a dispatch
        # (precompile_chunks) runs against a scratch copy. Default: on for
        # accelerator backends — the win is device-buffer reuse behind the
        # tunnel; on CPU hosts it measures neutral-at-best and the donated
        # program variants would shadow-compile next to any undonated use,
        # so tests opt in explicitly.
        if donate is not None:
            self.donate = bool(donate)
        else:
            env = flag_tristate("KTPU_DONATE")
            if env is None:
                env = _tuned.get("donate")
            self.donate = (
                env if env is not None else jax.default_backend() != "cpu"
            )
        # Fused chunk+slide megastep (KTPU_FUSED_SLIDE / fuse_slide arg):
        # the last ladder chunk of a slide span also computes, quantizes and
        # applies the window slide on device (see _fused_chunk_slide); the
        # engine reads one 4-byte shift back asynchronously instead of
        # dispatching shift + apply separately. Default: on for accelerator
        # backends — the win is per-span dispatch+sync overhead that only
        # exists through the device tunnel; on CPU hosts the extra fused
        # program variants would only double compile time, so tests opt in
        # explicitly (tests/test_window_donation_dispatch.py).
        if fuse_slide is not None:
            self._fuse_slide = bool(fuse_slide)
        else:
            env = flag_tristate("KTPU_FUSED_SLIDE")
            if env is None:
                env = _tuned.get("fuse_slide")
            self._fuse_slide = (
                env if env is not None else jax.default_backend() != "cpu"
            )
        # Superspan executor (KTPU_SUPERSPAN / superspan arg): the
        # steady-state sliding loop dispatches ONE device program per up-to-K
        # slide-spans (step.run_superspan) — windows, shift computation,
        # quantization and slide application all inside one while_loop, refill
        # columns drawn from a device-resident staging slab — instead of
        # popcount(span) ladder chunks + a per-span shift readback. The only
        # host sync left in steady state is the (4,)-int32 progress readback,
        # one per superspan. Bit-identical to the ladder path
        # (tests/test_superspan.py); default on for accelerator backends —
        # on CPU hosts the extra program variant would only double compile
        # time, so tests opt in explicitly.
        if superspan is not None:
            self._superspan = bool(superspan)
        else:
            env = flag_tristate("KTPU_SUPERSPAN")
            if env is None:
                env = _tuned.get("superspan")
            self._superspan = bool(
                env if env is not None else jax.default_backend() != "cpu"
            )
        if superspan_k is None:
            superspan_k = _tuned.get("superspan_k", 16)
        if superspan_chunk is None:
            superspan_chunk = _tuned.get("superspan_chunk", 8)
        if superspan_stage_cols is None:
            superspan_stage_cols = _tuned.get("superspan_stage_cols")
        self._superspan_k = max(1, int(superspan_k))
        self._superspan_chunk = max(1, int(superspan_chunk))
        self._superspan_stage_cols = superspan_stage_cols
        # Streaming trace-ingestion pipeline (KTPU_STREAM / stream arg):
        # a feeder thread (batched/stream.py) compiles trace segments into
        # a bounded ring of K device-resident RefillStage slabs, running
        # AHEAD of the superspan dispatch loop — stage-exhaustion exits
        # find the next slab already uploaded, and the whole-trace device
        # slide payload is never materialized (host+device staging memory
        # is O(K x segment), not O(trace)). Rides the superspan executor:
        # tristate default mirrors KTPU_SUPERSPAN (accelerator on, CPU
        # off), and an explicit stream=True without the superspan executor
        # is a loud error rather than a silent whole-trace fallback.
        if stream is not None:
            self._stream = bool(stream)
            if self._stream and not self._superspan:
                raise ValueError(
                    "stream=True requires the superspan executor "
                    "(superspan=True / KTPU_SUPERSPAN): the streaming "
                    "feeder stages slabs for run_superspan's bounded "
                    "RefillStage path"
                )
        else:
            env = flag_tristate("KTPU_STREAM")
            if env is None:
                env = _tuned.get("stream")
            self._stream = (
                bool(env if env is not None else jax.default_backend() != "cpu")
                and self._superspan
            )
        if mesh is not None and is_cross_process(mesh):
            # Forced off on CROSS-PROCESS meshes (the lane_major
            # precedent): the feeder thread's uploads go through
            # put_global, whose collective ordering across hosts is only
            # coordinated on the engine thread — an uncoordinated
            # feeder-thread put could interleave with the engine's
            # collectives. Single-process meshes (incl. a whole v5e-8)
            # stream normally; cross-process runs keep the resident
            # device-slide payload path.
            self._stream = False
        if stream_depth is None:
            # KTPU_STREAM_DEPTH has a concrete registry default (3), so
            # "flag unset" is checked explicitly — otherwise a tuned
            # depth could never apply.
            if flag_set("KTPU_STREAM_DEPTH"):
                stream_depth = flag_int("KTPU_STREAM_DEPTH")
            else:
                stream_depth = _tuned.get(
                    "stream_depth", flag_int("KTPU_STREAM_DEPTH")
                )
        self._stream_depth = max(1, int(stream_depth))
        if stream_segment is None:
            stream_segment = flag_int("KTPU_STREAM_SEGMENT")
        if stream_segment is None:
            stream_segment = _tuned.get("stream_segment")
        self._stream_segment = (
            None if stream_segment is None else int(stream_segment)
        )
        # The live feeder (stream.StreamFeeder) — built lazily at the
        # first staged dispatch, closed + rebuilt (re-seek) on window
        # growth and checkpoint restore. _feeder_produced_total carries
        # the production counter across those re-seeks so
        # dispatch_stats["feeder_slabs_produced"] is cumulative.
        self._feeder = None
        self._feeder_produced_total = 0
        # Feeder supervisor (PR 19, DESIGN §15): producer death surfaces
        # as FeederProducerError at get_stage; the supervisor rebuilds
        # the feeder with exponential backoff, carrying the dead ring's
        # retired-slab high-water mark so never-re-offer spans restarts.
        # A chaos injector (KTPU_HOST_CHAOS, or set directly by tests)
        # rides into every feeder built so the kill channel draws inside
        # the producer thread.
        self._feeder_restarts = 0
        self._feeder_restart_cap = 5
        self._feeder_backoff_s = 0.005
        self._feeder_chaos = None
        if flag_str("KTPU_HOST_CHAOS") is not None:
            from kubernetriks_tpu.batched.faults import HostChaos

            self._feeder_chaos = HostChaos.from_flag(
                flag_str("KTPU_HOST_CHAOS")
            )
        # Lane-major hot node state (KTPU_LANE_MAJOR / lane_major arg): the
        # window programs carry state.NODE_HOT_LEAVES transposed (N, C) —
        # the Pallas kernels' layout — killing the per-kernel-boundary
        # transposes; state at rest stays row-major (conversion lives at
        # the jit entries). Bit-identical either way
        # (tests/test_layout_razor.py); default on for accelerator
        # backends — on CPU XLA pays the layout copies anyway and the
        # extra program variants would only double compile time, so tests
        # opt in explicitly. Under a mesh the shard_map in_specs pin the
        # row-major (C, ...) convention, so the mode is forced off.
        if lane_major is not None:
            self.lane_major = bool(lane_major)
        else:
            env = flag_tristate("KTPU_LANE_MAJOR")
            if env is None:
                env = _tuned.get("lane_major")
            self.lane_major = bool(
                env if env is not None else jax.default_backend() != "cpu"
            )
        if mesh is not None:
            self.lane_major = False
        # Window-cost razor (KTPU_WINDOW_RAZOR / window_razor arg): gate
        # the per-window resolution soup behind a cheap due-ness predicate
        # (step._window_work_due) so empty windows in dense traces stop
        # paying it. Tristate like lane_major: on for accelerator backends,
        # off on CPU hosts (the cond adds compile to every window program
        # there against a marginal measured win — BENCH_r07 A/B). CA
        # de-scatter round 3 (KTPU_CA_DESCATTER / ca_descatter arg):
        # combined segment-sum + grouping sort in the scale-down cond body
        # — same program size, so default-on everywhere. All bit-exact.
        if window_razor is not None:
            self.window_razor = bool(window_razor)
        else:
            env = flag_tristate("KTPU_WINDOW_RAZOR")
            if env is None:
                env = _tuned.get("window_razor")
            self.window_razor = bool(
                env if env is not None else jax.default_backend() != "cpu"
            )
        if ca_descatter is not None:
            self.ca_descatter = bool(ca_descatter)
        elif flag_set("KTPU_CA_DESCATTER"):
            self.ca_descatter = flag_bool("KTPU_CA_DESCATTER")
        else:
            self.ca_descatter = bool(
                _tuned.get("ca_descatter", flag_bool("KTPU_CA_DESCATTER"))
            )
        # CA slot reclaim (KTPU_RECLAIM / reclaim arg): a periodic
        # in-trace compaction returns fully-retired CA reserve slots, so
        # ca_cursor tracks LIVE occupancy and sustained churn never
        # exhausts the reserve (ROADMAP #2 — the endurance blocker).
        # Trajectories are scalar-exact: allocations carry the scalar's
        # total_allocated naming index and name-ordered walks derive
        # their order from it (autoscale.ca_name_order). Tristate like
        # the other perf statics: unset means on for accelerator
        # backends, off on CPU hosts (the compaction cond + dynamic
        # orders are extra program text on every window program; tests
        # and endurance runs opt in explicitly). An explicit reclaim=True
        # on a trace whose node-name classes interleave (the order
        # decomposition would be unsound) raises at build; the tristate
        # default falls back off with a warning. Finalized after the
        # autoscale statics are built below.
        self._reclaim_requested = (
            bool(reclaim) if reclaim is not None else None
        )
        if self._reclaim_requested is None:
            self._reclaim_requested = flag_tristate("KTPU_RECLAIM")
        if reclaim_period is None:
            if flag_set("KTPU_RECLAIM_PERIOD"):
                reclaim_period = flag_int("KTPU_RECLAIM_PERIOD")
            else:
                reclaim_period = _tuned.get(
                    "reclaim_period", flag_int("KTPU_RECLAIM_PERIOD")
                )
        self.reclaim_period = max(1, int(reclaim_period))
        self.reclaim = False
        # (lo, RefillStage) staging buffers for the superspan executor when
        # the whole-trace payload exceeds the device budget: the stage the
        # next dispatch reads, and the double-buffered successor assembled
        # while the current superspan runs on device (_prefetch_stage).
        self._stage_cur = None
        self._stage_next = None
        # (shift-array, new-name-rank-or-None) of a fused slide whose host
        # resolution is still pending (step_until_time resolves it at the
        # span boundary).
        self._pending_shift = None
        # (start, width, refill pytree) prefetched for the HOST slide path
        # while a span's chunks run on device (_prefetch_refill).
        self._refill_prefetch = None
        # Dispatch accounting for the steady-state loop, asserted by the
        # dispatch-count regression test: window_chunks counts device
        # dispatches that advance windows (fused_slides of them also slid),
        # slide_dispatches counts SEPARATE shift/apply dispatches (0 when
        # fused), slide_syncs counts blocking host readbacks that gate a
        # slide decision, refill_prefetches counts host-path payload
        # prefetches that overlapped device compute.
        # superspans counts run_superspan dispatches (each is one device
        # program covering up to K slide-spans and ONE blocking progress
        # readback, also counted in slide_syncs); superspan_spans counts the
        # slide-spans those dispatches completed on device; stage_refills
        # counts staging-buffer installs (whole-trace-payload engines never
        # restage).
        # ladder_fallbacks counts step_until_time calls where a
        # superspan-selected engine dispatched the ladder instead
        # (instrumented modes, gauge collection, fast-forward) — the
        # silent-fallback observable bench.py --smoke asserts on, now
        # visible in every telemetry_report.
        # feeder_slabs_produced mirrors the streaming feeder's production
        # counter (0 on non-streaming engines): stage_refills counts slabs
        # the dispatch loop INSTALLED, feeder_slabs_produced counts slabs
        # the producer BUILT — produced >> installed means wasted
        # production (stride too small), produced == installed with
        # feeder-not-ready stalls means a starved feeder (raise
        # stream_depth / widen segments). Both land in telemetry_report.
        self.dispatch_stats = {
            "window_chunks": 0,
            "fused_slides": 0,
            "slide_dispatches": 0,
            "slide_syncs": 0,
            "refill_prefetches": 0,
            "superspans": 0,
            "superspan_spans": 0,
            "stage_refills": 0,
            "feeder_slabs_produced": 0,
            "ladder_fallbacks": 0,
        }
        self._use_pallas_requested = use_pallas
        self.pallas_interpret = bool(pallas_interpret)
        self.use_pallas = bool(use_pallas)  # finalized after shapes are known
        self.conditional_move = bool(
            config.enable_unscheduled_pods_conditional_move
        )
        # Lane-asynchronous fleet mode (batched/fleet.py, DESIGN §13):
        # per-lane window clocks in StepConstants (lane_clock/lane_horizon)
        # let each lane run its own virtual span inside the shared window
        # programs — a finished lane is frozen by the window body and
        # re-seeded in place (set_lane_plan + lane_reset) while neighbors
        # keep stepping. Requires a SCENARIO build (the per-lane reset
        # pristine + scenario leaves are the substrate) and the
        # full-resident dispatch path: the sliding window, superspan
        # executor, streaming feeder and fast-forward skip all assume one
        # fleet-global clock, so composing them here would be a silent
        # correctness hazard — loud errors instead (the
        # stream-without-superspan precedent).
        self.lane_async = bool(lane_async)
        if self.lane_async:
            if self._scenario is None:
                raise ValueError(
                    "lane_async=True requires a scenario build (scenario="
                    "{...} / ScenarioFleet): per-lane resets re-seed from "
                    "the scenario pristine"
                )
            if pod_window is not None:
                raise ValueError(
                    "lane_async=True requires the full-resident pod path "
                    "(pod_window=None): the sliding window's refill cursor "
                    "is fleet-global"
                )
            if superspan or stream:
                raise ValueError(
                    "lane_async=True is incompatible with the superspan "
                    "executor / streaming feeder: their progress carries "
                    "assume one fleet-global window clock"
                )
            # Tristate-off the global-clock perf statics instead of
            # erroring on their accelerator defaults.
            self._superspan = False
            self._stream = False
            self._fuse_slide = False
            fast_forward = False
        self.consts = make_step_constants(config)
        self.ram_unit = ram_unit
        compiled_traces = list(compiled_traces)
        C = len(compiled_traces)

        # Fast-forward (run_windows_skip): the skip only pays when whole
        # spans are provably empty; on dense traces every window is
        # interesting and the per-window interesting-check + while_loop
        # structure COST ~14% (measured: 8-day replay at 1.55 events/window
        # 229 s -> 261 s). Default: auto-enable below 0.25 trace events per
        # window (set after the trace is compiled, below); exactness either
        # way is pinned by tests/test_fast_forward.py.
        self._fast_forward_requested = fast_forward
        self.fast_forward = bool(fast_forward)  # finalized once density is known
        # Windows per flush period in the SAME f32 arithmetic the step uses,
        # so the skip's flush-window prediction can never disagree.
        d = 1
        while (
            np.float32(d) * np.float32(config.scheduling_cycle_interval)
            < np.float32(self.consts.flush_interval)
        ):
            d += 1
        self._flush_windows = d

        # Sliding pod window (SURVEY §5.8 host/device streaming, pod axis):
        # the device pod arrays cover only [pod_base, pod_base + pod_window)
        # of the trace's PLAIN pod slots; as old pods terminate the window
        # shifts forward, refilled from the host payload. Per-window cost is
        # then bounded by max concurrency, not trace length, so arbitrarily
        # long traces stream through fixed-size device state. HPA pod groups
        # compose with the window via the segmented slot layout
        # (trace_compile.segment_pod_slots): their reserved ring slots are
        # renumbered past every plain pod and stay device-RESIDENT after the
        # window segment, because group pods are long-running services that
        # would block the window's terminal-prefix shift.
        # 0 / negative mirror the CLI's "disabled" sentinel: full-resident.
        if pod_window is not None and pod_window <= 0:
            pod_window = None
        trace_pod_bound = None
        if any(c.pod_groups for c in compiled_traces):
            # The segmented layout is CANONICAL whenever pod groups exist,
            # windowed or not: slot order feeds order-sensitive passes (CA
            # scale-down re-placement, same-window reschedule ranking), so
            # windowed and full-resident runs must share one layout to stay
            # equivalent.
            from kubernetriks_tpu.batched.trace_compile import segment_pod_slots

            compiled_traces, trace_pod_bound = segment_pod_slots(compiled_traces)
            if trace_pod_bound == 0:
                # Pure pod-group workload: nothing for the window to slide
                # over — every slot is ring-resident; run full-resident.
                pod_window = None
        self.pod_window = pod_window
        self._pod_base = 0
        self._full_pods = None
        self._payload_source = None
        self._resident_shift = 0

        # Full-resident runs 128-align the pod axis: the Pallas wrapper pads
        # (operand copies from jnp.pad before every kernel launch) become
        # no-ops when P is already a tile multiple. Padded slots are exactly
        # batch-padding slots (req 0, duration sentinel, no create event —
        # phase stays EMPTY forever). The sliding path keeps exact widths:
        # its segmented [window | resident] layout derives device offsets
        # from the plain-slot count, and the device window W is already the
        # caller's tile-friendly choice.
        n_pods_aligned = None
        if pod_window is None and flag_bool("KTPU_ALIGN_PODS"):
            p_max = max((c.n_pods for c in compiled_traces), default=0)
            n_pods_aligned = -(-max(p_max, 1) // 128) * 128

        (
            ev_time,
            ev_kind,
            ev_slot,
            node_cap_cpu,
            node_cap_ram,
            pod_req_cpu,
            pod_req_ram,
            pod_duration,
            node_crash_downtime,
        ) = pad_and_batch(compiled_traces, n_pods=n_pods_aligned)

        # Host-side node-event schedule for point-in-time readouts
        # (node_count_at): a slab event applies only when its WINDOW
        # executes, so a trace/chaos node transition earlier in the
        # current (unexecuted) window is visible in neither the alive
        # flags nor the pending effect pairs — the readout resolves it
        # from this table. Node events only: O(nodes + crash chains),
        # never O(T).
        from kubernetriks_tpu.batched.state import (
            EV_CREATE_NODE,
            EV_NODE_CRASH,
            EV_NODE_RECOVER,
            EV_REMOVE_NODE,
        )

        _node_kind = np.isin(
            ev_kind,
            (EV_CREATE_NODE, EV_REMOVE_NODE, EV_NODE_CRASH, EV_NODE_RECOVER),
        )
        _ev_win_all, _ = from_f64_np(ev_time, config.scheduling_cycle_interval)
        self._node_event_table = [
            (
                ev_time[ci][_node_kind[ci]],
                np.isin(
                    ev_kind[ci][_node_kind[ci]],
                    (EV_CREATE_NODE, EV_NODE_RECOVER),
                ),
                ev_slot[ci][_node_kind[ci]],
                _ev_win_all[ci][_node_kind[ci]],
            )
            for ci in range(C)
        ]

        # Chaos engine: static fault constants (None = off, identical
        # programs) and the KTPU_DEBUG_FINITE guard mode (host-side NaN/inf
        # sweep after every dispatched chunk; off by default so the donated
        # hot path is untouched).
        from kubernetriks_tpu.chaos import make_fault_params

        self.fault_params = make_fault_params(config)
        self._debug_finite = flag_bool("KTPU_DEBUG_FINITE")
        # Per-lane pod-fault seeds (scenario vector): traced (C,) data in
        # StepConstants — each lane's attempt draws key on (seed[c],
        # cluster 0), making its fault stream a pure function of the
        # scenario (lane-permutation invariance; fleet re-seeds are data,
        # not recompiles). Installed ONLY under a scenario build so
        # scenario-less engines keep the pre-fleet consts pytree (and the
        # per-cluster keying the chaos suite pins).
        if (
            self._scenario is not None
            and self.fault_params is not None
            and self.fault_params.fail_prob > 0
        ):
            from kubernetriks_tpu.batched.fleet import scenario_leaves

            seeds = scenario_leaves(config, C, self._scenario)["fault_seed"]
            self.consts = self.consts._replace(
                fault_seed=jnp.asarray(
                    seeds.astype(np.uint32), jnp.uint32
                )
            )
        # Lane-async clocks: traced (C,) data in StepConstants, plus the
        # host-side numpy mirrors the completion arithmetic reads (the
        # traced leaves themselves are never read on the host — the
        # scenariotrace pass's compile-once contract). All lanes start
        # INACTIVE (horizon 0): the fleet arms each lane with
        # set_lane_plan when it assigns a query.
        if self.lane_async:
            self._lane_clock_np = np.zeros((C,), np.int64)
            self._lane_horizon_np = np.zeros((C,), np.int64)
            self.consts = self.consts._replace(
                lane_clock=jnp.asarray(self._lane_clock_np, jnp.int32),
                lane_horizon=jnp.asarray(self._lane_horizon_np, jnp.int32),
            )

        if pod_window is not None:
            # Cross-process meshes are supported through the device-resident
            # slide path: the shift amount is a replicated scalar (readable
            # on every process), slices/concats run SPMD, and the payload is
            # placed with put_global. Only the HOST fallback path needs
            # every shard addressable — __init__ refuses cross-process
            # builds whose payload exceeds the device budget (below).
            P_full = pod_req_cpu.shape[1]
            # T: first resident (pod-group ring) slot; the window slides over
            # plain slots [0, T) only.
            T = trace_pod_bound if trace_pod_bound is not None else P_full
            pod_window = min(pod_window, T)
            self.pod_window = pod_window
            self._resident_shift = T - pod_window
            self.consts = self.consts._replace(
                trace_pod_bound=np.int32(T),
                resident_shift=np.int32(self._resident_shift),
            )
            # Window index of each plain pod slot's create event (slots are
            # assigned in event order, so this is per-row nondecreasing) —
            # the O(1) capacity lookup for the dispatch loop. Group-slot
            # creations (initial replicas) target the resident tail and never
            # constrain the window.
            ev_win_np, _ = from_f64_np(ev_time, config.scheduling_cycle_interval)
            create_win = np.full((C, T), np.iinfo(np.int32).max, np.int32)
            rows_np = np.arange(C)[:, None]
            is_cp = (ev_kind == 3) & (ev_slot < T)  # EV_CREATE_POD, plain
            create_win[
                np.broadcast_to(rows_np, ev_kind.shape)[is_cp],
                ev_slot[is_cp],
            ] = ev_win_np[is_cp]
            self._pod_create_win = create_win
            self._full_pods = {
                "req_cpu": pod_req_cpu[:, :T],
                "req_ram": pod_req_ram[:, :T],
                "duration": pod_duration[:, :T],
            }
            # Payload seam (ROADMAP #2 host-memory bound): every refill /
            # staging consumer reads request+duration columns through
            # this source. The build default wraps the resident arrays;
            # attach_payload_source swaps in a bounded segment reader and
            # RELEASES them, making steady-state host RSS O(stage width).
            from kubernetriks_tpu.batched.trace_compile import (
                ArrayPayloadSource,
            )

            self._payload_source = ArrayPayloadSource(self._full_pods)
            # Lexicographic pod-name ranks over the WHOLE trace (global pod
            # coords): the window's device slice is refreshed on every slide
            # (statics are traced arguments, so no recompile), keeping the
            # name-ordered semantics (CA cache order, reschedule queue
            # order) identical between sliding and full-resident runs.
            BIG_RANK = np.int32(1 << 30)
            self._pod_name_rank_full = np.full((C, P_full), BIG_RANK, np.int32)
            _rank_cache: dict = {}
            for ci, trace in enumerate(compiled_traces):
                ranks = _rank_cache.get(id(trace))
                if ranks is None:
                    order_np = np.argsort(
                        np.asarray(trace.pod_names, dtype=object), kind="stable"
                    )
                    ranks = np.empty(len(trace.pod_names), np.int32)
                    ranks[order_np] = np.arange(
                        len(trace.pod_names), dtype=np.int32
                    )
                    _rank_cache[id(trace)] = ranks
                self._pod_name_rank_full[ci, : len(ranks)] = ranks
            # Device pod arrays: [window over plain slots | resident rings].
            pod_req_cpu = np.concatenate(
                [pod_req_cpu[:, :pod_window], pod_req_cpu[:, T:]], axis=1
            )
            pod_req_ram = np.concatenate(
                [pod_req_ram[:, :pod_window], pod_req_ram[:, T:]], axis=1
            )
            pod_duration = np.concatenate(
                [pod_duration[:, :pod_window], pod_duration[:, T:]], axis=1
            )

        # Autoscaler tables (HPA pod groups from the trace, CA node groups from
        # the config); the CA's reserved node slots are appended after the
        # trace's slots.
        hpa_on = config.horizontal_pod_autoscaler.enabled
        ca_on = config.cluster_autoscaler.enabled
        self.autoscale_statics = None
        self.max_ca_pods_per_cycle = max_ca_pods_per_cycle
        self.max_pods_per_scale_down = max_pods_per_scale_down
        # Per-cluster reserve capacities for the capacity observatory's
        # occupancy gauges (telemetry/observatory.py): total HPA pod-group
        # slots and total CA node slots. Host python ints, fetched ONCE
        # here at build time (cold path, before mesh placement).
        self._reserve_capacities: dict = {}
        self.pod_group_names = [[g.name for g in c.pod_groups] for c in compiled_traces]
        self._autoscale_aux: dict = {}
        if hpa_on or ca_on:
            statics, extra_cpu, extra_ram, extra_names, aux = build_autoscale_statics(
                config,
                compiled_traces,
                n_pods=pod_req_cpu.shape[1],
                n_trace_nodes=node_cap_cpu.shape[1],
                ram_unit=ram_unit,
                ca_slot_multiplier=ca_slot_multiplier,
                pod_slot_offset=self._resident_shift,
                sliding=pod_window is not None,
                scenario=self._scenario,
            )
            self.autoscale_statics = statics
            self._autoscale_aux = aux
            # Finalize the reclaim decision now that the name-order
            # tables' verification outcome is known.
            want = self._reclaim_requested
            if want is None:
                want = jax.default_backend() != "cpu"
            supported = ca_on and statics.ca_slot_class is not None
            if want and not supported:
                reason = aux.get("reclaim_unsupported") or "unsupported"
                if self._reclaim_requested:
                    raise ValueError(
                        "reclaim=True (KTPU_RECLAIM) is unsupported for "
                        f"this build: {reason} — the allocation-name "
                        "order decomposition would be unsound; rename "
                        "the conflicting nodes/groups or run without "
                        "reclaim"
                    )
                if ca_on:
                    import warnings as _warnings

                    _warnings.warn(
                        "KTPU_RECLAIM default-on disabled: "
                        f"{reason}; the CA reserve stays monotone "
                        "(engine.check_autoscaler_bounds remains the "
                        "only backstop)",
                        RuntimeWarning,
                        stacklevel=2,
                    )
                want = False
            self.reclaim = bool(want and supported)
            self._reserve_capacities = {
                "hpa_reserve": [
                    int(v)
                    for v in np.asarray(statics.pg_slot_count).sum(axis=1)
                ],
                "ca_reserve": [
                    int(v)
                    for v in np.asarray(statics.ng_slot_count).sum(axis=1)
                ],
            }
            if ca_on and extra_names:
                node_cap_cpu = np.concatenate(
                    [node_cap_cpu, np.tile(extra_cpu, (C, 1))], axis=1
                )
                node_cap_ram = np.concatenate(
                    [node_cap_ram, np.tile(extra_ram, (C, 1))], axis=1
                )
        else:
            extra_names = []

        self.n_clusters = C
        self.n_nodes = node_cap_cpu.shape[1]
        self.n_pods = pod_req_cpu.shape[1]
        # N is only known here (derived from the traces + CA reserve
        # groups), so the tuned profile's node-axis key is re-checked
        # post-build: strict (explicit) profiles raise GeometryMismatch,
        # auto-resolved ones warn loudly and keep the applied statics.
        if self.tuned_profile is not None:
            self.tuned_profile.check_geometry(n_nodes=self.n_nodes)
        # Real (trace-defined) pod slots, before the 128-alignment padding
        # of the device axis — the count completion/terminal asserts want.
        self.n_real_pods = max((c.n_pods for c in compiled_traces), default=0)
        self.n_events = ev_time.shape[1]

        # Per-window event application runs in CHUNKS of this size inside a
        # while_loop until the window's due events are exhausted, so this is a
        # typical-case tile size, not a worst-case bound: a trace whose worst
        # window has thousands of events (e.g. the t=0 cluster creation burst)
        # pays a few extra loop iterations there instead of taxing every
        # window with a burst-sized gather/scatter.
        # 32: scatter cost scales with C x E, and typical windows carry far
        # fewer events than a burst; smaller chunks measurably beat 128 on
        # the TPU (burst windows just loop a few more times).
        if max_events_per_window is None:
            max_events_per_window = min(self._max_events_in_any_window(ev_time), 32)
        self.max_events_per_window = max(1, max_events_per_window)
        # Cap per-cycle scheduling work (the scalar path drains the queue
        # unboundedly, reference scheduler.rs:261; the batched path bounds each
        # cycle and catches up next cycle).
        self.max_pods_per_cycle = max(1, max_pods_per_cycle or self.n_pods)

        # Finalize the Pallas decision now that shapes are known. Default: on
        # for real-TPU runs whose blocks fit VMEM (overridable via the
        # use_pallas arg or KUBERNETRIKS_PALLAS=0/1). Under a mesh the kernel
        # runs per-shard through shard_map (step.py), so the gate is the
        # PER-SHARD cluster count, and C must divide the mesh evenly.
        from kubernetriks_tpu.ops.scheduler_kernel import (
            default_enabled,
            kernel_fits,
            select_kernel_fits,
        )

        n_shards = 1 if mesh is None else mesh.size
        if self.use_pallas and mesh is not None:
            assert self.n_clusters % n_shards == 0, (
                f"use_pallas under a mesh needs n_clusters ({self.n_clusters}) "
                f"divisible by the mesh size ({n_shards}) for shard_map"
            )
        if self._use_pallas_requested is None:
            # Default-on whenever the blocks fit: even at C=1 (the trace-replay
            # shape, where the 128-lane cluster tile is almost all padding) the
            # kernel's data-dependent early exit over candidates beats the
            # K-step lax.scan by ~5x on hardware — the scan pays all K
            # sequential iterations (~16 us each) while typical cycles have
            # far fewer pending pods (measured 2026-07-30: 0.90 ms vs 4.58 ms
            # per window at C=1, N=1313, P=4096, K=256).
            self.use_pallas = (
                default_enabled()
                and self.n_clusters % n_shards == 0
                and kernel_fits(self.n_nodes, self.max_pods_per_cycle)
            )
        # Prefer the fused selection kernel (in-kernel queue argmin instead
        # of the (C, P) lexsort) when its pod blocks fit VMEM AND the
        # 128-cluster lane tiles are mostly real: its per-candidate passes
        # sweep whole (P, 128) tiles, so at small C the padding waste loses
        # to the sort+candidate kernel (measured at C=1, P=4096: 5.3 ms vs
        # 0.9 ms per window), while dense batches win by dropping the sort.
        self.use_pallas_select = (
            self.use_pallas
            and self.n_clusters // n_shards >= 128
            and select_kernel_fits(
                self.n_nodes, self.n_pods, self.max_pods_per_cycle
            )
        )
        # The r4 megakernel (selection + cycle + commit in one launch) is the
        # default on the dense path when its larger VMEM footprint fits;
        # KTPU_MEGAKERNEL=0 selects the two-kernel path (A/B measurement).
        # Read at BUILD time and threaded as a jit-static, so toggling the
        # env between engine builds takes effect without cache collisions.
        from kubernetriks_tpu.ops.scheduler_kernel import (
            select_commit_kernel_fits,
        )

        self.use_megakernel = (
            self.use_pallas_select
            and flag_bool("KTPU_MEGAKERNEL")
            and select_commit_kernel_fits(
                self.n_nodes, self.n_pods, self.max_pods_per_cycle
            )
        )

        # The CA's reserved node slots (appended above) never crash — pad
        # the crash-downtime payload to the final node axis.
        if node_crash_downtime.shape[1] < self.n_nodes:
            node_crash_downtime = np.concatenate(
                [
                    node_crash_downtime,
                    np.zeros(
                        (C, self.n_nodes - node_crash_downtime.shape[1]),
                        np.float32,
                    ),
                ],
                axis=1,
            )
        self.state = init_state(
            C,
            self.n_nodes,
            self.n_pods,
            node_cap_cpu,
            node_cap_ram,
            pod_req_cpu,
            pod_req_ram,
            pod_duration,
            interval=config.scheduling_cycle_interval,
            node_crash_downtime=node_crash_downtime,
        )
        # Static (lo, hi) device-slot bounds covering every pod-group slot:
        # the HPA pass only touches group slots, so its body (victim sort
        # included) and its not-due cond carry run on this slice instead of
        # the full (C, P) pod axis (autoscale.hpa_pass). (0, 0) = the HPA
        # can never act (off, no groups, or empty reserves) — the step skips
        # the pass entirely and hpa_next parks at +inf below to match.
        self._hpa_seg = (0, 0)
        if self.autoscale_statics is not None and (
            hpa_on and any(c.pod_groups for c in compiled_traces)
        ):
            starts = np.asarray(self.autoscale_statics.pg_slot_start)
            counts = np.asarray(self.autoscale_statics.pg_slot_count)
            gmask = counts > 0
            if gmask.any():
                seg_lo = max(int(starts[gmask].min()), 0)
                seg_hi = min(int((starts + counts)[gmask].max()), self.n_pods)
                self._hpa_seg = (
                    (seg_lo, seg_hi) if seg_hi > seg_lo else (0, 0)
                )
        if self.autoscale_statics is not None:
            # collect: arm the HPA collection latch (the 60 s staleness
            # fix) whenever the HPA can actually act; reclaim: arm the CA
            # slot-reclaim leaves (allocation indices + counters).
            auto = init_autoscale_state(
                self.autoscale_statics,
                reclaim=self.reclaim,
                collect=self._hpa_seg != (0, 0),
            )
            # When the step skips hpa_pass (seg == (0, 0)), park its tick at
            # +inf so everything that reads hpa_next (fast-forward's
            # _next_interesting_window, _catch_up_bookkeeping) agrees the
            # HPA never fires.
            if self._hpa_seg == (0, 0):
                from kubernetriks_tpu.batched.timerep import t_inf

                auto = auto._replace(hpa_next=t_inf((C,)))
            self.state = self.state._replace(auto=auto)
            # Seed the replica indices of the trace's INITIAL group replicas
            # (created by slab events, which don't carry hpa_idx): the i-th
            # reserved slot's first occupant is "{group}_{i}".
            gid_np = np.asarray(self.autoscale_statics.pod_group_id)
            if (gid_np >= 0).any():
                start_np = np.asarray(self.autoscale_statics.pg_slot_start)
                init_np = np.asarray(self.autoscale_statics.pg_initial)
                P_dev = gid_np.shape[1]
                gidc = np.clip(gid_np, 0, None)
                off_np = (
                    np.arange(P_dev, dtype=np.int32)[None, :]
                    - np.take_along_axis(start_np, gidc, axis=1)
                )
                seeded = (gid_np >= 0) & (
                    off_np < np.take_along_axis(init_np, gidc, axis=1)
                )
                hpa_idx0 = np.where(seeded, off_np, -1).astype(np.int32)
                self.state = self.state._replace(
                    pods=self.state.pods._replace(
                        hpa_idx=jnp.asarray(hpa_idx0)
                    )
                )
        self.observatory = None
        if self._telemetry:
            # Attach the device metrics ring BEFORE mesh placement below,
            # so its leaves pick up the state sharding like every other
            # (C, ...) array. Presence is a structural static (like
            # `auto`): telemetry-off engines compile identical programs.
            from kubernetriks_tpu.telemetry.ring import init_ring

            self.state = self.state._replace(
                telemetry=init_ring(C, self._telemetry_ring_size)
            )
            # Capacity observatory (telemetry/observatory.py): occupancy
            # series + memory watermarks + the saturation watchdog, fed
            # strictly from drained host copies at the ring's existing
            # drain points (_maybe_drain_ring / drain_telemetry).
            from kubernetriks_tpu.telemetry.observatory import Observatory

            self.observatory = Observatory(
                interval=config.scheduling_cycle_interval,
                capacities=self._reserve_capacities,
                watchdog=self._watchdog,
            )
        ev_win, ev_off = from_f64_np(ev_time, config.scheduling_cycle_interval)
        self.slab = TraceSlab.build(ev_win, ev_off, ev_kind, ev_slot)
        self._ev_time_np = ev_time  # host copy (f64) for completion checks
        self._lane_mux = None
        if self.lane_async:
            # Per-lane trace multiplexer (DESIGN §13): host copy of the
            # just-built packed slab (build-time fetch of a host-sourced
            # array — the cold construction boundary, not a steady-state
            # sync), plus a warm pass of the data-only row install so the
            # first RANGED query re-seeds under the sentinel without
            # compiling anything.
            from kubernetriks_tpu.batched.stream import LaneTraceMux

            self._lane_mux = LaneTraceMux(np.asarray(self.slab.packed))  # ktpu: sync-ok(build-time host copy of the freshly built trace slab for the lane mux — construction boundary, no steady-state device read)
            rows = self._lane_mux.offer(0)
            self._lane_mux.retire([0])
            self._install_lane_rows(
                0, rows if rows is not None else self._lane_mux._base[0]
            )
        if self._fast_forward_requested is None:
            finite = ev_time[np.isfinite(ev_time)]
            span = (
                max(1.0, float(finite.max()) / config.scheduling_cycle_interval)
                if finite.size
                else 1.0
            )
            density = finite.size / (C * span)  # trace events per window
            self.fast_forward = density < 0.25
        self.node_names = [c.node_names + extra_names for c in compiled_traces]
        self.pod_names = [c.pod_names for c in compiled_traces]
        self.next_window_idx = 0
        # Per-window gauge collection (batched analog of the scalar 5 s gauge
        # cycle): enable with collect_gauges, read via gauge_series() or
        # write_gauge_csv(). The series buffer lives in the telemetry
        # package (telemetry/gauges.py owns concat/CSV/sidecar); the
        # engine only performs the (waived) device fetches.
        self.collect_gauges = False
        self._gauges = GaugeSeries()
        # Profiling hooks: set profile_dir to capture a jax.profiler trace of
        # every step_until_time dispatch; set log_throughput for a per-chunk
        # decisions/s + cluster-windows/s log line (TPU analog of the scalar
        # events/s log, reference: src/simulator.rs:363-368).
        self.profile_dir: Optional[str] = None
        self.log_throughput = False
        # Raise at readout when a documented autoscaler work bound was
        # crossed (HPA reserve clamp, CA slot-reserve exhaustion) instead of
        # silently reporting a diverged trajectory. Opt out for exploratory
        # runs with strict_autoscaler_bounds = False.
        self.strict_autoscaler_bounds = True

        self.mesh = mesh
        self._batch_axis = batch_axis
        self._sharding = None
        if mesh is not None:
            # Cross-process meshes (multi-host over DCN) can't device_put a
            # host-local array onto non-addressable devices; every process
            # holds the same compiled trace and contributes its shards.
            put = put_global if is_cross_process(mesh) else jax.device_put
            sharding = NamedSharding(mesh, PartitionSpec(batch_axis))
            self._sharding = sharding
            self.state = put(self.state, self._state_shardings(sharding, self.state))
            self.slab = put(
                self.slab,
                jax.tree.map(
                    lambda _: NamedSharding(mesh, PartitionSpec(batch_axis, None)),
                    self.slab,
                ),
            )
            if self.autoscale_statics is not None:
                self.autoscale_statics = put(
                    self.autoscale_statics,
                    self._state_shardings(sharding, self.autoscale_statics),
                )
        # Standalone name-rank tables for full-resident runs WITHOUT
        # autoscalers: same-instant reschedule batches (node crashes under
        # fault injection, but ALSO plain same-timestamp trace RemoveNode
        # events) need queue order following the scalar's sorted-name walk —
        # the slot-order fallback diverges there. Historically these tables
        # were built only for fault runs; the per-profile equivalence
        # sweeps surfaced a profile trajectory (balanced_packing, seed 101)
        # where two trace removals co-reschedule pods and slot order flips
        # the next cycle's queue, so the ranks are now built for EVERY
        # full-resident engine (two small int tables, memoized argsort).
        # With autoscalers on, the autoscale statics already carry the
        # ranks; under a sliding pod window without autoscalers the
        # slot-order stand-in remains (documented in docs/PARITY.md).
        self._fault_name_ranks = None
        if self.autoscale_statics is None and self.pod_window is None:
            BIG_RANK = np.int32(1 << 30)
            nnr = np.full((C, self.n_nodes), BIG_RANK, np.int32)
            pnr = np.full((C, self.n_pods), BIG_RANK, np.int32)
            # Workload traces are identical across clusters (only the node
            # fault schedules differ), so memoize the object-dtype argsort
            # by name tuple — the pod table is computed once for C clusters.
            memo: dict = {}

            def _ranks(names):
                key = tuple(names)
                got = memo.get(key)
                if got is None:
                    got = memo[key] = _lex_name_ranks(names)
                return got

            for ci in range(C):
                r = _ranks(self.node_names[ci])
                nnr[ci, : len(r)] = r
                r = _ranks(self.pod_names[ci])
                # The pod axis may be 128-aligned past the real names;
                # padding slots keep BIG_RANK.
                pnr[ci, : min(len(r), self.n_pods)] = r[: self.n_pods]
            ranks = (jnp.asarray(nnr), jnp.asarray(pnr))
            if self.mesh is not None:
                row = NamedSharding(
                    self.mesh, PartitionSpec(self._batch_axis, None)
                )
                put = (
                    put_global if is_cross_process(self.mesh) else jax.device_put
                )
                ranks = put(ranks, (row, row))
            self._fault_name_ranks = ranks

        # Sliding runs: install the initial windowed name-rank slice
        # (build_autoscale_statics leaves ranks BIG under sliding). Must run
        # AFTER self.mesh is assigned and the statics carry their final
        # sharding — _refresh_name_ranks re-puts with old.sharding.
        self._refresh_name_ranks()
        self._init_device_slide()
        if (
            self.pod_window is not None
            and self.mesh is not None
            and is_cross_process(self.mesh)
            and self._device_slide is None
            and not self._stream_on()
        ):
            raise ValueError(
                "pod_window on a cross-process mesh requires the "
                "device-resident slide payload, but this trace exceeds its "
                "memory budget — raise _DEVICE_SLIDE_BUDGET_BYTES, enlarge "
                "pod_window, or drop to a single-process mesh (the host "
                "slide path needs every shard addressable)"
            )

        # Scenario-vector fleets (batched/fleet.py) reset lanes against the
        # PRISTINE build state (fleet_reset's donation-friendly select
        # re-init). Snapshot it only for scenario builds — plain engines
        # must not pay a second full-state copy in device memory.
        self._pristine = None
        self._pristine_pod_window = self.pod_window
        if self._scenario is not None:
            self._pristine = tree_copy(self.state)

    def _slide_payload_fits(self, W: int) -> bool:
        """Whether the device-resident slide payload at window width W fits
        the memory budget — the ONE owner of the payload-size formula, used
        by _init_device_slide and by _grow_pod_window's pre-mutation check
        (req x2, dur pair x2, create window, + name ranks with statics)."""
        if self._full_pods is None:
            return False
        C, T = self._full_pods["req_cpu"].shape
        n_i32 = 5 + (1 if self.autoscale_statics is not None else 0)
        return C * (T + W) * 4 * n_i32 <= _DEVICE_SLIDE_BUDGET_BYTES

    def _init_device_slide(self) -> None:
        """Upload the slide payload (pod requests, durations, create
        windows, name ranks over the PLAIN trace segment) to the device so
        window slides run on-device. The host slide path's per-slide
        round-trips — the (C, W) phase fetch, the refill device_put, the
        name-rank device_put — measured 237-486 ms/slide through the
        tunneled TPU runtime; the device path fetches one 4-byte shift.
        Falls back to the host path (payload stays None) above the memory
        budget."""
        self._device_slide = None
        if self.pod_window is None or self._full_pods is None:
            return
        if self._stream_on():
            # Streaming ingestion: the whole-trace payload is exactly what
            # the feeder exists to NOT materialize — the superspan loop
            # stages bounded slabs through the ring instead, and device
            # staging memory stays O(stream_depth x segment) regardless of
            # trace length.
            return
        full = self._full_pods
        T = full["req_cpu"].shape[1]
        W = self.pod_window
        has_rank = self.autoscale_statics is not None
        if not self._slide_payload_fits(W):
            return
        from kubernetriks_tpu.batched.state import duration_pair_np
        from kubernetriks_tpu.batched.trace_compile import stage_segment

        # The whole-trace payload is the lo = 0, width = T + W staging
        # segment — stage_segment owns the padding rules, so this payload
        # and the bounded RefillStage slabs (_make_stage) cannot drift.
        seg = stage_segment(
            self._payload_source,
            self._pod_create_win,
            self._pod_name_rank_full[:, :T] if has_rank else None,
            0,
            T + W,
        )
        dur_pair = duration_pair_np(
            seg.pop("duration"), self.config.scheduling_cycle_interval
        )
        payload = {
            "req_cpu": jnp.asarray(seg["req_cpu"]),
            "req_ram": jnp.asarray(seg["req_ram"]),
            "dur_win": dur_pair.win,
            "dur_off": dur_pair.off,
            "create_win": jnp.asarray(seg["create_win"]),
        }
        if has_rank:
            payload["rank"] = jnp.asarray(seg["rank"])
        if self._sharding is not None:
            row = NamedSharding(
                self._sharding.mesh, PartitionSpec(self._batch_axis, None)
            )
            put = (
                put_global
                if is_cross_process(self._sharding.mesh)
                else jax.device_put
            )
            payload = put(payload, {k: row for k in payload})
        self._device_slide = payload

    def _state_shardings(self, sharding, tree):
        """Every non-scalar leaf leads with the C axis; shard axis 0,
        replicate the rest (scalars are replicated)."""

        def leaf_sharding(leaf):
            if leaf.ndim == 0:
                return NamedSharding(sharding.mesh, PartitionSpec())
            spec = PartitionSpec(
                *([sharding.spec[0]] + [None] * (leaf.ndim - 1))
            )
            return NamedSharding(sharding.mesh, spec)

        return jax.tree.map(leaf_sharding, tree)

    def _max_events_in_any_window(self, ev_time: np.ndarray) -> int:
        """Worst-case events falling into one (cluster, scheduling-window)
        bucket — the static per-window event budget."""
        interval = self.config.scheduling_cycle_interval
        rows, cols = np.nonzero(np.isfinite(ev_time))
        if rows.size == 0:
            return 1
        win = np.floor_divide(ev_time[rows, cols], interval).astype(np.int64)
        keys = rows * (win.max() + 2) + win
        _, per_key = np.unique(keys, return_counts=True)
        return int(per_key.max())

    # --- stepping -----------------------------------------------------------

    @property
    def next_window(self) -> float:
        """Next scheduling-cycle time in seconds (windows are indexed; this is
        the float view tests and callers use)."""
        return self.next_window_idx * self.config.scheduling_cycle_interval

    @next_window.setter
    def next_window(self, t: float) -> None:
        interval = self.config.scheduling_cycle_interval
        idx = int(round(t / interval))
        assert abs(idx * interval - t) < 1e-9 * max(1.0, abs(t)), (
            f"next_window must be a multiple of the {interval}s cycle interval"
        )
        self.next_window_idx = idx

    def window_times(self, until_time: float) -> np.ndarray:
        """Scheduling-cycle times in [next_window, until_time], starting at 0
        like the scalar scheduler.start()."""
        interval = self.config.scheduling_cycle_interval
        idxs = self.window_idxs(until_time)
        return idxs.astype(np.float64) * interval

    def window_idxs(self, until_time: float) -> np.ndarray:
        interval = self.config.scheduling_cycle_interval
        first = self.next_window_idx
        count = int(math.floor(until_time / interval)) - first + 1
        return first + np.arange(max(count, 0), dtype=np.int32)

    def _window_call_kwargs(self) -> dict:
        """The window-program config kwargs shared by every dispatch and
        warm-up site (run_windows, run_windows_skip, the fused chunk+slide
        megastep). ONE owner — a new engine static added here reaches the
        warmed AND dispatched programs together, so precompile_chunks can
        never warm a program the loop then doesn't use. Callers add their
        entry-specific extras (collect_gauges, flush_windows, W)."""
        return dict(
            max_events_per_window=self.max_events_per_window,
            max_pods_per_cycle=self.max_pods_per_cycle,
            autoscale_statics=self.autoscale_statics,
            max_ca_pods_per_cycle=self.max_ca_pods_per_cycle,
            max_pods_per_scale_down=self.max_pods_per_scale_down,
            use_pallas=self.use_pallas,
            pallas_interpret=self.pallas_interpret,
            conditional_move=self.conditional_move,
            pallas_mesh=self.mesh if self.use_pallas else None,
            pallas_axis=self._batch_axis,
            use_pallas_select=self.use_pallas_select,
            use_megakernel=self.use_megakernel,
            hpa_seg=self._hpa_seg,
            fault_params=self.fault_params,
            name_ranks=self._fault_name_ranks,
            lane_major=self.lane_major,
            window_razor=self.window_razor,
            ca_descatter=self.ca_descatter,
            reclaim=self.reclaim,
            reclaim_period=self.reclaim_period,
            profile=self.profile,
        )

    def _dispatch_windows(
        self,
        idxs: np.ndarray,
        fuse_slide: bool = False,
        freeze_lanes: bool = True,
    ) -> None:
        """Run one chunk of windows and fold the results into self.state
        (+ gauge accumulation). With fuse_slide, dispatch the chunk+slide
        megastep instead (_fused_chunk_slide): the returned shift's host
        readback starts immediately but is only consumed at the span
        boundary (_resolve_pending_slide), so no sync lands here."""
        self.dispatch_stats["window_chunks"] += 1
        tr = self.tracer
        tr.count(f"dispatch_chunk_{len(idxs)}")
        donated_in = self.state if (self.donate and self._sanitize) else None
        if fuse_slide:
            self.dispatch_stats["fused_slides"] += 1
            fn = _fused_chunk_slide_donated if self.donate else _fused_chunk_slide
            t0 = tr.begin()
            state, new_rank, s = fn(
                self.state,
                self.slab,
                jnp.asarray(idxs, jnp.int32),
                self.consts,
                self._device_slide,
                np.int32(self._pod_base),
                W=self.pod_window,
                **self._window_call_kwargs(),
            )
            tr.end(PH_FUSED_CHUNK_SLIDE, t0)
            self.state = state
            if donated_in is not None:
                sanitize.consume_donated(donated_in)
            if new_rank is not None:
                # Device-to-device swap, no sync; identical values when the
                # slide turns out to be a no-op (s == 0).
                self.autoscale_statics = self.autoscale_statics._replace(
                    pod_name_rank=new_rank
                )
            if hasattr(s, "copy_to_host_async"):
                with sanitize.allow_transfer(
                    self._sanitize, "async shift prefetch"
                ):
                    s.copy_to_host_async()  # ktpu: sync-ok(async initiation of the waived 4-byte shift readback — does not block)
            self._pending_flow = tr.flow_start(PH_SHIFT_WAIT)
            self._pending_shift = s
            self.next_window_idx = int(idxs[-1]) + 1
            return
        if self.fast_forward and not self.collect_gauges:
            # Fast-forward dispatch: execute only interesting windows of the
            # span (bit-identical end state; see run_windows_skip). Gauge
            # collection needs every window's sample, so it keeps the scan.
            from kubernetriks_tpu.batched.step import (
                run_windows_skip,
                run_windows_skip_donated,
            )

            skip_fn = run_windows_skip_donated if self.donate else run_windows_skip
            t0 = tr.begin()
            self.state = skip_fn(
                self.state,
                self.slab,
                np.int32(idxs[0]),
                np.int32(idxs[-1]),
                self.consts,
                flush_windows=self._flush_windows,
                **self._window_call_kwargs(),
            )
            tr.end(PH_WINDOW_CHUNK, t0)
            if donated_in is not None:
                sanitize.consume_donated(donated_in)
            self.next_window_idx = int(idxs[-1]) + 1
            return
        from kubernetriks_tpu.batched.step import run_windows_donated

        win_fn = run_windows_donated if self.donate else run_windows
        t0 = tr.begin()
        out = win_fn(
            self.state,
            self.slab,
            jnp.asarray(idxs, jnp.int32),
            self.consts,
            collect_gauges=self.collect_gauges,
            freeze_lanes=freeze_lanes,
            **self._window_call_kwargs(),
        )
        tr.end(PH_WINDOW_CHUNK, t0)
        if self.collect_gauges:
            self.state, gauges = out
            with sanitize.allow_transfer(
                self._sanitize, "gauge time-series readback"
            ):
                self._gauges.append(np.asarray(idxs), to_host(gauges))  # ktpu: sync-ok(gauge instrumentation: per-chunk time-series readback, gauge runs are not the steady-state path)
        else:
            self.state = out
        if donated_in is not None:
            sanitize.consume_donated(donated_in)
        self.next_window_idx = int(idxs[-1]) + 1

    def precompile_chunks(self, max_chunk: int = 128) -> int:
        """Warm the sliding path's dispatch-chunk program shapes (the
        power-of-two ladder, plus the fused chunk+slide variants when they
        are in play) so no compile lands inside a timed region — a novel
        chunk shape costs seconds through the tunneled TPU runtime.

        Each shape is dispatched once against a scratch COPY of the current
        state (so self.state survives buffer donation) with the CURRENT
        window index REPEATED chunk times: warm-up indices stay in range —
        never past the pod window's capacity — and a repeated window is
        quiet by construction (its due events, finishes and autoscaler
        ticks all resolve in the first scan iteration, leaving the rest of
        the chunk empty cycles). Per-shape warm-up compute is therefore
        bounded by ~one real window + (chunk - 1) empty cycles, instead of
        re-simulating chunk real windows per shape; idx VALUES are traced,
        so the compiled/warmed program is exactly the one the dispatch loop
        uses. Total cost: at most len(_CHUNK_LADDER) shapes (2x with the
        fused-slide variants), each one compile (seconds through the
        tunnel, cache hit when already warm) plus the bounded quiet
        execution. Returns the number of shapes dispatched. No-op on
        fast-forward or non-sliding engines (one program serves any span
        there). Superspan engines warm the ONE superspan program instead of
        the ladder — the steady-state loop never dispatches ladder chunks
        while the superspan path is selectable."""
        if self.pod_window is None or (
            self.fast_forward and not self.collect_gauges
        ):
            return 0
        if self._superspan_ok():
            # The superspan loop is the ONLY program the steady-state
            # dispatch will use (one shape serves every span/target), so
            # warm it instead of the ladder; a no-op progress code compiles
            # the whole while_loop without executing a window. Dispatched
            # against a scratch copy like the ladder shapes (donation).
            t_warm = self.tracer.begin()
            stage, lo = self._current_stage()
            rank = (
                self.autoscale_statics.pod_name_rank
                if self.autoscale_statics is not None
                else None
            )
            fn = run_superspan_donated if self.donate else run_superspan
            out = fn(
                tree_copy(self.state),
                rank,
                jnp.asarray(
                    [self.next_window_idx, self._pod_base, 0, SUPERSPAN_GROW],
                    jnp.int32,
                ),
                self.slab,
                self.consts,
                stage,
                jnp.int32(lo),
                jnp.int32(self.next_window_idx),
                W=self.pod_window,
                K=self._superspan_k,
                chunk=self._superspan_chunk,
                **self._window_call_kwargs(),
            )
            jax.block_until_ready(out)  # ktpu: sync-ok(warm-up: AOT compile of the superspan program, outside every timed region)
            self.tracer.end(PH_PRECOMPILE, t_warm)
            return 1
        from kubernetriks_tpu.batched.step import run_windows_donated

        win_fn = run_windows_donated if self.donate else run_windows
        n = 0
        t_warm = self.tracer.begin()
        warm_fused = self._fused_slide_ok()
        for chunk in _CHUNK_LADDER:
            if chunk > max_chunk:
                continue
            idxs = jnp.full((chunk,), self.next_window_idx, jnp.int32)
            out = win_fn(
                tree_copy(self.state),
                self.slab,
                idxs,
                self.consts,
                collect_gauges=self.collect_gauges,
                **self._window_call_kwargs(),
            )
            jax.block_until_ready(out)  # discarded: warm-up only  # ktpu: sync-ok(warm-up: AOT compile of the ladder shapes, outside every timed region)
            n += 1
            if warm_fused:
                fn = (
                    _fused_chunk_slide_donated
                    if self.donate
                    else _fused_chunk_slide
                )
                out = fn(
                    tree_copy(self.state),
                    self.slab,
                    idxs,
                    self.consts,
                    self._device_slide,
                    np.int32(self._pod_base),
                    W=self.pod_window,
                    **self._window_call_kwargs(),
                )
                jax.block_until_ready(out)  # ktpu: sync-ok(warm-up: AOT compile of the fused chunk+slide shapes, outside every timed region)
                n += 1
        self.tracer.end(PH_PRECOMPILE, t_warm)
        return n

    # --- scenario-vector fleet support (batched/fleet.py) -------------------

    def _pair_np(self, x) -> TPair:
        """Host f64 seconds (scalar or array) -> device TPair."""
        w, o = from_f64_np(
            np.asarray(x, np.float64), self.config.scheduling_cycle_interval  # ktpu: sync-ok(scenario update: host numpy over per-lane config vectors, no device values)
        )
        return TPair(win=jnp.asarray(w), off=jnp.asarray(o))

    def update_scenario(self, scenario) -> None:
        """Install new per-lane scenario vectors into the RESIDENT engine:
        the scenario-bearing statics leaves (scan intervals, thresholds,
        CA period/quota, autoscaler-chain delays, per-lane HPA enables)
        and the pod-fault seed vector are all traced (C,)-shaped DATA, so
        this is a handful of host->device puts — never a recompile
        (bench.py --sweep asserts exactly that via fleet.jit_cache_sizes).
        Only legal on an engine built with scenario= (the fleet build):
        a scenario-less build may carry a different consts pytree
        (no fault_seed leaf), where a late update would shadow-compile."""
        from kubernetriks_tpu.batched.fleet import (
            normalize_scenario,
            scenario_leaves,
        )

        if self._scenario is None:
            raise ValueError(
                "update_scenario requires an engine built with scenario= "
                "(the fleet build): scenario-less engines compile the "
                "pre-fleet consts pytree and a late scenario would "
                "shadow-compile next to it"
            )
        updates = normalize_scenario(scenario, self.n_clusters) or {}
        self._scenario.update(updates)
        leaves = scenario_leaves(self.config, self.n_clusters, self._scenario)
        if self.autoscale_statics is not None:
            active_when_on = self._autoscale_aux["pg_active_when_on"]
            pg_active_from = np.where(
                leaves["hpa_enabled"][:, None], active_when_on, np.inf
            )
            st = self.autoscale_statics._replace(
                hpa_interval=self._pair_np(leaves["hpa_interval_s"]),
                hpa_tolerance=jnp.asarray(
                    leaves["hpa_tolerance"], jnp.float64
                ),
                ca_threshold=jnp.asarray(leaves["ca_threshold"], jnp.float64),
                ca_max_nodes=jnp.asarray(leaves["ca_max_nodes"], jnp.int32),
                pg_active_from=self._pair_np(pg_active_from),
                d_hpa_up=self._pair_np(leaves["d_hpa_up_s"]),
                d_hpa_down=self._pair_np(leaves["d_hpa_down_s"]),
                d_ca_up=self._pair_np(leaves["d_ca_up_s"]),
                d_ca_down=self._pair_np(leaves["d_ca_down_s"]),
                ca_period=self._pair_np(leaves["ca_period_s"]),
                ca_snap=self._pair_np(leaves["ca_snap_s"]),
                ca_finish_vis=self._pair_np(leaves["ca_finish_vis_s"]),
                ca_commit_vis=self._pair_np(leaves["ca_commit_vis_s"]),
            )
            if self._sharding is not None:
                put = (
                    put_global
                    if is_cross_process(self._sharding.mesh)
                    else jax.device_put
                )
                st = put(st, self._state_shardings(self._sharding, st))
            self.autoscale_statics = st
        if self.consts.fault_seed is not None:
            self.consts = self.consts._replace(
                fault_seed=jnp.asarray(
                    leaves["fault_seed"].astype(np.uint32), jnp.uint32
                )
            )

    def fleet_reset(self, lanes=None) -> None:
        """Reset cluster lanes to the PRISTINE build state in place — the
        fleet's between-query re-init. One donated select per state leaf
        against the build snapshot (device-buffer reuse, no recompile, no
        re-warm; fleet._reset_lanes). lanes=None resets EVERY lane and
        also rewinds the host-side cursors (window clock, pod-window
        position, staging ring/feeder, telemetry bookkeeping) — the wave
        boundary. An explicit lane list resets only those state rows and
        leaves the clock alone (only meaningful while the clock is at a
        wave boundary; the window clock is fleet-global)."""
        from kubernetriks_tpu.batched.fleet import _reset_lanes

        if self._pristine is None:
            raise ValueError(
                "fleet_reset requires an engine built with scenario= "
                "(the fleet build keeps the pristine state snapshot)"
            )
        if self.pod_window != self._pristine_pod_window:
            raise RuntimeError(
                f"fleet_reset: the pod window grew ({self._pristine_pod_window}"
                f" -> {self.pod_window}) during a wave, so the pristine "
                "snapshot's shapes are stale — build the fleet with a "
                "larger pod_window so dense waves never grow it"
            )
        mask = np.zeros((self.n_clusters,), bool)
        if lanes is None:
            mask[:] = True
        else:
            mask[np.asarray(list(lanes), np.int64)] = True  # ktpu: sync-ok(fleet reset: host numpy over a python lane list, no device values)
        donated_in = self.state if self._sanitize else None
        self.state = _reset_lanes(
            self.state, self._pristine, jnp.asarray(mask)
        )
        if donated_in is not None:
            sanitize.consume_donated(donated_in)
        if lanes is not None:
            return
        # Wave boundary: rewind the host cursors to the build state.
        self.next_window_idx = 0
        self._pod_base = 0
        self._pending_shift = None
        self._refill_prefetch = None
        self._stage_cur = None
        self._stage_next = None
        self._close_feeder()
        self._refresh_name_ranks()
        if self.state.telemetry is not None:
            self._ring_seen.clear()
            self._ring_series_dropped = 0
            self._ring_windows_recorded = 0
            self._ring_drained_at = 0
            self._pending_flow = 0
        if self.observatory is not None:
            self.observatory.reset()

    # --- lane-async clock protocol (DESIGN §13) ---------------------------

    def horizon_windows(self, horizon: float) -> int:
        """Window count a fresh run of `horizon` sim-seconds executes —
        the lane_horizon a lane needs for per-query bit-identity with the
        wave-aligned path (window_idxs from cursor 0)."""
        interval = self.config.scheduling_cycle_interval
        return int(math.floor(horizon / interval)) + 1

    def set_lane_plan(self, lanes, start_window: int, horizons) -> None:
        """Arm per-lane clocks: lanes start their virtual window 0 at
        global window `start_window` and run `horizons[i]` windows. PURE
        DATA update — the (C,) consts leaves are traced, so re-seeding a
        lane never recompiles (the fleet's compile-once contract); the
        numpy mirrors keep host completion arithmetic sync-free."""
        if not self.lane_async:
            raise ValueError(
                "set_lane_plan requires an engine built with lane_async="
                "True (per-lane window clocks)"
            )
        lanes = np.asarray(list(lanes), np.int64)  # ktpu: sync-ok(python lane-index list, no device value)
        self._lane_clock_np[lanes] = int(start_window)
        self._lane_horizon_np[lanes] = np.asarray(horizons, np.int64)  # ktpu: sync-ok(python horizon list into the host mirror, no device value)
        self.consts = self.consts._replace(
            lane_clock=jnp.asarray(self._lane_clock_np, jnp.int32),
            lane_horizon=jnp.asarray(self._lane_horizon_np, jnp.int32),
        )

    def lane_windows_done(self) -> np.ndarray:
        """(C,) bool: lanes whose planned span is fully dispatched (global
        cursor past lane_clock + lane_horizon). Host arithmetic over the
        numpy clock mirrors — zero device syncs; counters for finished
        lanes are fetched by the caller at an existing host-block
        boundary (fleet._lane_rows)."""
        return (
            self._lane_clock_np + self._lane_horizon_np
            <= self.next_window_idx
        )

    def _install_lane_rows(self, lane: int, rows: np.ndarray) -> None:
        """Data-only device install of one lane's (E, 4) trace rows via
        dynamic_update_slice with TRACED start indices — one compiled
        program for every lane (a static `.at[lane].set` would compile
        per lane index and trip the post-warm-up sentinel)."""
        packed = jax.lax.dynamic_update_slice(
            self.slab.packed,
            jnp.asarray(rows, jnp.int32)[None],
            (
                jnp.asarray(lane, jnp.int32),
                jnp.asarray(0, jnp.int32),
                jnp.asarray(0, jnp.int32),
            ),
        )
        self.slab = TraceSlab(packed=packed)

    def set_lane_trace(self, lane: int, lo: int = 0, hi=None) -> None:
        """Install a per-lane workload row-range (stream.LaneTraceMux):
        the lane replays only slab rows [lo, hi) (pod creates outside the
        range and their removes masked to EV_NONE in place — host copy,
        sort order preserved). Reseed-boundary call: the mux's never-
        re-offer invariant refuses a lane whose previous range was not
        retired by lane_reset. Pure data install — zero recompiles, zero
        new steady-state syncs."""
        if not self.lane_async or self._lane_mux is None:
            raise ValueError(
                "set_lane_trace requires an engine built with "
                "lane_async=True (per-lane trace multiplexer)"
            )
        rows = self._lane_mux.offer(int(lane), lo, hi)
        if rows is not None:
            self._install_lane_rows(int(lane), rows)

    def lane_windows_remaining(self) -> np.ndarray:
        """(C,) host ints: windows left on each lane's plan from the
        global cursor (0 for idle/finished lanes) — the pump's occupancy
        ledger input. Same sync-free mirror arithmetic as
        lane_windows_done."""
        rem = (
            self._lane_clock_np + self._lane_horizon_np
            - self.next_window_idx
        )
        return np.clip(rem, 0, None)

    def step_windows(self, n_windows: int) -> None:
        """Dispatch exactly `n_windows` windows from the global cursor —
        the lane-async pump's fixed-span dispatch. The full-resident plain
        path compiles ONE program per distinct span length (program shape
        = idxs length), so a free-running fleet that always pumps the same
        span recompiles nothing after warm-up (the sweep's sentinel
        asserts it). Same guard/drain protocol as step_until_time."""
        n = int(n_windows)
        if n <= 0:
            return
        if self.pod_window is not None:
            raise ValueError(
                "step_windows requires the full-resident pod path "
                "(pod_window=None); sliding-window engines advance with "
                "step_until_time"
            )
        if self.state.telemetry is not None:
            pending = self.next_window_idx - self._ring_drained_at
            if pending > 0 and pending + n > self._telemetry_ring_size:
                self._maybe_drain_ring(force=True)
        # All-active fast path: the host clock mirrors prove every lane
        # stays inside its [clock, clock + horizon) span for the WHOLE
        # chunk, so the state-wide freeze selects (identities there) are
        # compiled out (step._window_body freeze_lanes=False). The mirrors
        # are host-authoritative (clocks only move via set_lane_plan /
        # lane_reset), so the proof costs no device read; spans touching a
        # lane boundary keep the freezing program. Two warmed variants
        # total — the pump's warm-up stream exercises both.
        start = self.next_window_idx
        freeze = True
        if self.lane_async:
            freeze = not (
                bool(np.all(self._lane_clock_np <= start))
                and bool(
                    np.all(
                        start + n
                        <= self._lane_clock_np + self._lane_horizon_np
                    )
                )
            )
        with sanitize.guard(self._sanitize):
            self._step_idxs(
                np.arange(start, start + n, dtype=np.int32),
                freeze_lanes=freeze,
            )
        self._maybe_drain_ring()

    def precompile_lane_spans(self, span: int) -> int:
        """Warm the lane-async pump's window-program variants: every
        power-of-two chunk of the pump ladder {span, span/2, ..., 1}
        plus the raw drain-tail span, each in BOTH freeze variants (the
        boundary-aligned no-freeze program and the freezing fallback).
        The pump's organic stream only exercises the variants its feed
        pattern happens to need — a burst-submitted stream runs
        boundary-aligned (no-freeze) chunks exclusively until the queue
        dries, so its first freezing dispatch would otherwise compile
        mid-stream, after the fleet declared itself warm (the armed
        sentinel in tests/test_fleet_async.py catches exactly that).
        Same scratch-copy protocol as precompile_chunks: the current
        window index repeats chunk times, so per-shape warm-up compute
        is ~one real window plus empty cycles. Returns the number of
        programs dispatched (cache hits included)."""
        if not self.lane_async or self.pod_window is not None:
            return 0
        from kubernetriks_tpu.batched.step import run_windows_donated

        win_fn = run_windows_donated if self.donate else run_windows
        sizes = []
        c = 1 << (max(int(span), 1).bit_length() - 1)
        while c >= 1:
            sizes.append(c)
            c //= 2
        if int(span) not in sizes:
            sizes.insert(0, int(span))
        n = 0
        t_warm = self.tracer.begin()
        for chunk in sizes:
            idxs = jnp.full((chunk,), self.next_window_idx, jnp.int32)
            for freeze in (False, True):
                out = win_fn(
                    tree_copy(self.state),
                    self.slab,
                    idxs,
                    self.consts,
                    collect_gauges=self.collect_gauges,
                    freeze_lanes=freeze,
                    **self._window_call_kwargs(),
                )
                jax.block_until_ready(out)  # discarded: warm-up only  # ktpu: sync-ok(warm-up: AOT compile of the lane-span variants, outside every timed region)
                n += 1
        self.tracer.end(PH_PRECOMPILE, t_warm)
        return n

    def lane_reset(self, lanes) -> None:
        """Per-lane pristine reset that PRESERVES the telemetry ring: the
        free-running engine re-seeds finished lanes mid-flight, and a
        plain fleet_reset(lanes) would tree-map the ring back to its
        pristine (cursor 0) snapshot — desynchronizing the per-lane
        cursors the uniform-window scatter relies on and dropping
        undrained rows. Strips the ring from both sides of the donated
        select (None = absent pytree node, one extra warmed program
        variant) and re-attaches the live ring after."""
        from kubernetriks_tpu.batched.fleet import _reset_lanes

        if not self.lane_async:
            raise ValueError(
                "lane_reset requires an engine built with lane_async=True"
            )
        if self._pristine is None:
            raise ValueError(
                "lane_reset requires an engine built with scenario= "
                "(the fleet build keeps the pristine state snapshot)"
            )
        mask = np.zeros((self.n_clusters,), bool)
        mask[np.asarray(list(lanes), np.int64)] = True  # ktpu: sync-ok(lane reset: host numpy over a python lane list, no device values)
        if self._lane_mux is not None:
            # The reset boundary retires the lanes' offered trace ranges:
            # the next set_lane_trace for them is legal again.
            self._lane_mux.retire(lanes)
        ring = self.state.telemetry
        state = self.state._replace(telemetry=None)
        pristine = self._pristine._replace(telemetry=None)
        donated_in = state if self._sanitize else None
        state = _reset_lanes(state, pristine, jnp.asarray(mask))
        if donated_in is not None:
            sanitize.consume_donated(donated_in)
        self.state = state._replace(telemetry=ring)

    def step_until_time(self, until_time: float) -> None:
        """Advance to `until_time`. THE steady-state dispatch region: under
        KTPU_SANITIZE it runs inside a device-to-host transfer guard — any
        sync not inside an explicit sanitize.allow_transfer scope (the
        runtime mirror of the lint pass's sync-ok waivers) raises."""
        if self.state.telemetry is not None:
            # Entry-side wrap guard (host arithmetic only): the incoming
            # span's window count is known here, so drain the undrained
            # rows NOW if this call would wrap past them — loss can then
            # only happen when ONE call spans more than the ring itself
            # (disclosed via windows_recorded > windows_kept).
            pending = self.next_window_idx - self._ring_drained_at
            interval = self.config.scheduling_cycle_interval
            n_new = max(
                0,
                int(math.floor(until_time / interval))
                - self.next_window_idx
                + 1,
            )
            if pending > 0 and pending + n_new > self._telemetry_ring_size:
                self._maybe_drain_ring(force=True)
        with sanitize.guard(self._sanitize):
            self._step_until_time(until_time)
        # Telemetry ring pressure check (host-side arithmetic only): drain
        # before records wrap out. Lands OUTSIDE the transfer-guard region
        # at a boundary where callers already block (bench span fetches),
        # so telemetry-on adds no sync inside the steady-state loop.
        self._maybe_drain_ring()

    def _step_until_time(self, until_time: float) -> None:
        idxs = self.window_idxs(until_time)
        if len(idxs) == 0:
            return
        if self.pod_window is None:
            self._step_idxs(idxs)
            return
        # Sliding-window dispatch: run sub-spans up to the last window whose
        # pod creations still fit the device window, shifting past terminal
        # pods between spans. Spans are cut greedily along a power-of-two
        # chunk ladder — the binary decomposition of any span length, so a
        # span costs popcount(span) dispatches (a 20-window span is 16+4 =
        # 2 dispatches; the old coarse (128,32,8,1) ladder cut it into
        # 8+8+1+1+1+1 = 6, and per-dispatch overhead is ~20 ms through the
        # tunneled TPU runtime — the dispatch tax WAS the composed path's
        # largest single cost). When a slide will follow the span, the LAST
        # chunk dispatches as the fused chunk+slide megastep
        # (_fused_chunk_slide): the slide itself costs no extra dispatch,
        # and the only host sync of the span is the asynchronous 4-byte
        # shift readback at the boundary (_resolve_pending_slide). Engines
        # on the host slide path instead prefetch the refill payload while
        # the span's chunks are still running on device. At most
        # len(LADDER) program shapes compile per variant;
        # precompile_chunks() AOT-compiles them so none lands mid-bench.
        target = int(idxs[-1])
        if self._superspan_ok():
            self._run_superspans(target)
            return
        if self._superspan:
            # Superspan selected but not dispatchable (instrumented mode,
            # gauges, fast-forward, debug-finite): count the silent ladder
            # fallback so it is observable outside bench.py --smoke.
            self.dispatch_stats["ladder_fallbacks"] += 1
        while self.next_window_idx <= target:
            sub = min(target, self._pod_capacity_window())
            will_slide = sub < target
            fuse = will_slide and self._fused_slide_ok()
            while self.next_window_idx <= sub:
                span = sub - self.next_window_idx + 1
                chunk = next(c for c in _CHUNK_LADDER if c <= span)
                # _step_idxs keeps the profiling/gauge instrumentation on
                # every dispatch size; chunk == span marks the span's final
                # chunk (the greedy binary decomposition ends exactly at sub).
                self._step_idxs(
                    np.arange(
                        self.next_window_idx,
                        self.next_window_idx + chunk,
                        dtype=np.int32,
                    ),
                    fuse_slide=fuse and chunk == span,
                )
            if sub >= target:
                return
            if will_slide and self._device_slide is None:
                # Host slide path: assemble the refill payload NOW, while
                # the span's dispatched chunks are still executing on device
                # (dispatches are asynchronous; the blocking phase fetch in
                # _advance_pod_window comes after).
                self._prefetch_refill()
            advanced = (
                self._resolve_pending_slide()
                if self._pending_shift is not None
                else self._advance_pod_window()
            )
            if not advanced:
                # The live-pod span outgrew the window (no leading pod is
                # terminal): grow the window in place instead of failing —
                # dense stretches of a long trace adapt automatically.
                if not self._grow_pod_window():
                    raise RuntimeError(
                        f"pod_window={self.pod_window} is too small: window "
                        f"{sub + 1} needs pod slots beyond the device window "
                        "and no leading pod is terminal yet, and the window "
                        "already covers the whole plain trace segment"
                    )
            # Ring pressure check riding the slide/grow sync that just
            # blocked (host arithmetic otherwise — no new syncs): ladder
            # spans longer than the ring stay lossless inside ONE call.
            self._maybe_drain_ring()

    def _fused_slide_ok(self) -> bool:
        """Whether spans can end in the fused chunk+slide megastep: needs
        the device-resident slide payload and the plain run_windows dispatch
        mode (fast-forward spans and gauge collection keep their own
        programs; both fall back to the two-dispatch slide)."""
        return (
            self._fuse_slide
            and self._device_slide is not None
            and not self.fast_forward
            and not self.collect_gauges
        )

    def _superspan_ok(self) -> bool:
        """Whether the steady-state loop can dispatch superspans: needs the
        sliding window and the plain run_windows dispatch mode (fast-forward
        and gauge collection keep their own programs), and steps aside for
        the per-chunk instrumentation paths — profiling and throughput logs
        want ladder-granular timings, and the ladder is bit-identical.
        KTPU_DEBUG_FINITE keeps the ladder too: its promise is per-chunk
        NaN/inf localization, and a superspan only surfaces state once per
        up-to-K spans."""
        return (
            self._superspan
            and self.pod_window is not None
            and not self.fast_forward
            and not self.collect_gauges
            and not self.profile_dir
            and not self.log_throughput
            and not self._debug_finite
        )

    def _stage_width(self) -> int:
        """Static column count of the superspan staging slab when the
        whole-trace payload is over budget (or streaming keeps it bounded
        unconditionally): W windows of shift headroom would starve a max
        (W/2) slide, so the default is 4W (3W of shift headroom per
        stage), clamped to the whole padded payload. A streaming engine's
        stream_segment (KTPU_STREAM_SEGMENT) overrides the default — the
        per-slab memory knob of the feeder ring."""
        W = self.pod_window
        T = int(self.consts.trace_pod_bound)
        if self._stream_on() and self._stream_segment is not None:
            want = self._stream_segment
        elif self._superspan_stage_cols is not None:
            want = self._superspan_stage_cols
        else:
            want = 4 * W
        return min(max(want, W + max(W // 2, 1)), T + W)

    def _stage_arrays(self, lo: int, width: int) -> dict:
        """Host half of staging-slab construction: the numpy segment
        payload for columns [lo, lo + width)
        (trace_compile.stage_segment owns the layout and padding rules).
        Pure host numpy — safe to call from the feeder thread."""
        from kubernetriks_tpu.batched.trace_compile import stage_segment

        return stage_segment(
            self._payload_source,
            self._pod_create_win,
            (
                self._pod_name_rank_full[:, : int(self.consts.trace_pod_bound)]
                if self.autoscale_statics is not None
                else None
            ),
            lo,
            width,
        )

    def _stage_upload(self, seg: dict) -> RefillStage:
        """Device half: pair conversion + upload + mesh placement of an
        assembled segment (mirrors _init_device_slide). Host-to-device
        only — safe from the feeder thread (the sanitizer's d2h transfer
        guard is engine-thread-local and never applies here)."""
        from kubernetriks_tpu.batched.state import duration_pair_np

        dur = duration_pair_np(
            seg.pop("duration"), self.config.scheduling_cycle_interval
        )
        stage = RefillStage(
            req_cpu=jnp.asarray(seg["req_cpu"]),
            req_ram=jnp.asarray(seg["req_ram"]),
            dur_win=dur.win,
            dur_off=dur.off,
            create_win=jnp.asarray(seg["create_win"]),
            rank=(
                jnp.asarray(seg["rank"]) if "rank" in seg else None
            ),
        )
        if self._sharding is not None:
            row = NamedSharding(
                self._sharding.mesh, PartitionSpec(self._batch_axis, None)
            )
            put = (
                put_global
                if is_cross_process(self._sharding.mesh)
                else jax.device_put
            )
            stage = put(
                stage,
                jax.tree.map(lambda _: row, stage),
            )
        return stage

    def _make_stage(self, lo: int, width: int) -> RefillStage:
        """Assemble + upload one staging slab covering payload columns
        [lo, lo + width) ON the engine thread (the non-streaming bounded
        path); the streaming feeder builds slabs through the same two
        halves off-thread."""
        t0 = self.tracer.begin()
        seg = self._stage_arrays(lo, width)
        self.tracer.end(PH_STAGE_ASSEMBLE, t0)
        t0 = self.tracer.begin()
        stage = self._stage_upload(seg)
        self.tracer.end(PH_STAGE_PUT, t0)
        return stage

    # --- streaming feeder lifecycle ----------------------------------------

    def attach_payload_source(self, source) -> None:
        """Swap the resident whole-trace payload arrays (req/ram/duration,
        ~16 B/pod host memory) for a bounded segment-at-a-time
        PayloadSource (trace_compile.FeederPayloadSource over the native
        feeder's WorkloadSegmentReader, or any source honoring the
        contract) and RELEASE them — the host-memory half of the
        endurance work (ROADMAP #2): steady-state host RSS then holds
        only O(stage width) payload plus the disclosed small per-pod
        int32 tables (create windows for the O(1) capacity lookup, name
        ranks when autoscalers are on — 4 B/pod each, reported by
        _slab_accounting as host_payload_bytes).

        Requires the streaming superspan pipeline (the device-resident
        slide payload and the host slide path both want the whole trace
        resident) and a pure plain-pod payload axis (pod groups renumber
        it). The feeder is re-seeked so its producer thread never reads a
        released array."""
        from kubernetriks_tpu.batched.trace_compile import (
            ArrayPayloadSource,
            PayloadSource,
        )

        if not isinstance(source, PayloadSource):
            raise TypeError(
                f"attach_payload_source wants a trace_compile."
                f"PayloadSource, got {type(source).__name__}"
            )
        if self.pod_window is None or not self._stream_on():
            raise ValueError(
                "attach_payload_source requires the streaming superspan "
                "pipeline (pod_window + stream=True/KTPU_STREAM + "
                "superspan): the non-streaming paths keep the whole "
                "payload resident by design"
            )
        T = int(self.consts.trace_pod_bound)
        if source.total_rows < T:
            raise ValueError(
                f"payload source covers {source.total_rows} plain pod "
                f"columns; this trace has {T}"
            )
        if any(len(names) for names in self.pod_group_names):
            raise ValueError(
                "attach_payload_source does not support pod-group "
                "workloads: the resident group ring renumbers the "
                "payload axis past the plain segment, so payload column "
                "i would no longer be workload row i"
            )
        # Fidelity gate BEFORE releasing anything: the new source must
        # reproduce the engine's compiled payload bit-exactly over the
        # whole trace (chunked, one cold-path host pass). This is what
        # makes the swap safe at all — it catches a single-workload
        # FeederPayloadSource broadcast onto a HETEROGENEOUS fleet
        # (per-cluster traces differ; the reader would silently serve
        # cluster 0's pods to every lane), mismatched unit conversions,
        # or plain wrong-trace attachment, all of which would otherwise
        # produce wrong trajectories with no error.
        reference = (
            ArrayPayloadSource(self._full_pods)
            if self._full_pods is not None
            else self._payload_source
        )
        if reference is not None:
            chunk = 1 << 16
            for lo_v in range(0, T, chunk):
                w = min(chunk, T - lo_v)
                want = reference.segment(lo_v, w)
                got = source.segment(lo_v, w)
                for k in ("req_cpu", "req_ram", "duration"):
                    if not np.array_equal(want[k], got[k]):
                        diff = np.argwhere(want[k] != got[k])
                        c_bad, j_bad = (int(v) for v in diff[0])
                        raise ValueError(
                            f"attach_payload_source: source disagrees "
                            f"with the compiled trace payload at {k}"
                            f"[cluster {c_bad}, column {lo_v + j_bad}] "
                            f"({want[k][c_bad, j_bad]} != "
                            f"{got[k][c_bad, j_bad]}) — a payload source "
                            "serves the workload of EVERY cluster lane; "
                            "heterogeneous per-cluster traces need a "
                            "per-cluster-aware source (or keep the "
                            "resident payload)"
                        )
        self._close_feeder()
        self._payload_source = source
        self._full_pods = None
        self._stage_cur = None
        self._stage_next = None
        self._refill_prefetch = None

    def _stream_on(self) -> bool:
        """Whether the streaming pipeline stages this engine's slabs: the
        sliding window exists and the superspan executor is selected (the
        feeder stages for run_superspan's bounded RefillStage path; the
        ladder/instrumented fallbacks keep their own slide machinery)."""
        return (
            self._stream and self._superspan and self.pod_window is not None
        )

    def _ensure_feeder(self, retired_lo: int = -1):
        """The live StreamFeeder, built lazily at the current base and
        geometry (stage width is a jit static, so the feeder is re-built —
        re-seeked — whenever geometry or base moves non-monotonically:
        window growth, checkpoint restore). `retired_lo` is the supervisor
        restart path's carry-over: the dead ring's retired-slab
        high-water mark, so never-re-offer spans restarts."""
        if self._feeder is None:
            from kubernetriks_tpu.batched.stream import StreamFeeder

            W = self.pod_window
            self._feeder = StreamFeeder(
                self._stage_arrays,
                self._stage_upload,
                base=self._pod_base,
                width=self._stage_width(),
                window=W,
                trace_cols=int(self.consts.trace_pod_bound) + W,
                depth=self._stream_depth,
                retired_lo=retired_lo,
                chaos=self._feeder_chaos,
            )
        return self._feeder

    def _restart_feeder(self, feeder, err):
        """Supervisor restart after a producer death (FeederProducerError
        from get_stage): close the dead feeder, back off exponentially,
        rebuild at the current base carrying the retired-slab high-water
        mark (never-re-offer survives the restart — slab content is a
        pure function of (lo, width), so the rebuilt ring cannot
        diverge). Past the restart cap the error propagates — a
        persistently dying producer is a real bug, not weather — and the
        lane-async fleet above converts it to per-lane FeederErrors."""
        import logging
        import time as _time

        self._feeder_restarts += 1
        if self._feeder_restarts > self._feeder_restart_cap:
            raise err
        retired = feeder.retired_watermark()
        self._feeder_produced_total += feeder.produced
        feeder.close(timeout=1.0)
        self._feeder = None
        delay = self._feeder_backoff_s * (2 ** (self._feeder_restarts - 1))
        logging.getLogger(__name__).warning(
            "stream feeder producer died (%s); supervisor restart "
            "%d/%d after %.0f ms backoff",
            err,
            self._feeder_restarts,
            self._feeder_restart_cap,
            delay * 1e3,
        )
        _time.sleep(delay)
        return self._ensure_feeder(retired_lo=retired)

    def _close_feeder(self) -> None:
        """Stop + drop the feeder (re-seek half 1): the next staged
        dispatch rebuilds it at the then-current base and geometry. Slab
        content is a pure function of (lo, width), so a rebuilt feeder
        can never diverge from the one it replaces."""
        if self._feeder is not None:
            self._feeder_produced_total += self._feeder.produced
            self._feeder.close()
            self._feeder = None

    def _stage_covers(self, lo: int, stage: RefillStage) -> bool:
        """A stage serves a dispatch at the current pod_base iff the base
        sits inside it with the full window readable (the superspan's own
        exhaustion exit handles running out of headroom mid-flight)."""
        L = stage.req_cpu.shape[1]
        return (
            L == self._stage_width()
            and lo <= self._pod_base
            and self._pod_base - lo + self.pod_window <= L
        )

    def _current_stage(self):
        """(stage, lo) for the next superspan dispatch. Streaming engines
        draw from the feeder ring (the producer runs ahead; a not-ready
        slab blocks here with the stall split recorded); whole-trace
        payload engines wrap it directly (lo = 0, zero-copy, never
        restages); over-budget engines install the double-buffered
        successor when it covers the current base, else rebuild at the
        base."""
        if self._stream_on():
            from kubernetriks_tpu.batched.faults import FeederProducerError

            feeder = self._ensure_feeder()
            while True:
                try:
                    stage, lo, fresh = feeder.get_stage(
                        self._pod_base, tracer=self.tracer
                    )
                    break
                except FeederProducerError as err:
                    feeder = self._restart_feeder(feeder, err)
            if fresh:
                self.dispatch_stats["stage_refills"] += 1
            self.dispatch_stats["feeder_slabs_produced"] = (
                self._feeder_produced_total + feeder.produced
            )
            return stage, lo
        if self._device_slide is not None:
            pay = self._device_slide
            return (
                RefillStage(
                    req_cpu=pay["req_cpu"],
                    req_ram=pay["req_ram"],
                    dur_win=pay["dur_win"],
                    dur_off=pay["dur_off"],
                    create_win=pay["create_win"],
                    rank=pay.get("rank"),
                ),
                0,
            )
        if self._stage_cur is not None and self._stage_covers(*self._stage_cur):
            lo, stage = self._stage_cur
            return stage, lo
        nxt, self._stage_next = self._stage_next, None
        if nxt is not None and self._stage_covers(*nxt):
            # Prefetch HIT: the double-buffered successor assembled while
            # the previous superspan ran covers the restage point.
            self.tracer.count("stage_prefetch_hit")
            self._stage_cur = nxt
        else:
            # Prefetch MISS: rebuild at the base on the span boundary's
            # critical path (the stall the tracer makes visible).
            self.tracer.count("stage_prefetch_miss")
            lo = self._pod_base
            self._stage_cur = (lo, self._make_stage(lo, self._stage_width()))
        self.dispatch_stats["stage_refills"] += 1
        lo, stage = self._stage_cur
        return stage, lo

    def _prefetch_stage(self, cur_lo: int) -> None:
        """Double-buffering: assemble + device_put the NEXT staging slab
        while the just-dispatched superspan runs on device. An
        exhaustion-exit superspan's final base b satisfies
        b > cur_lo + R - W/2 (the failed slide's shift is at most W/2 and
        its columns crossed cur_lo + L), so a successor at exactly that
        lower bound always covers the restage point — host assembly and the
        H2D transfer overlap device compute instead of serializing at the
        span boundary (the generalization of the ladder path's
        _prefetch_refill)."""
        if self._device_slide is not None or self._stream_on():
            # Streaming engines need no consumer-side prefetch nudge: the
            # feeder's producer thread runs the slab schedule ahead on its
            # own (the K-deep generalization of this 2-deep hook).
            return
        W = self.pod_window
        Lw = self._stage_width()
        lo_pred = cur_lo + (Lw - W) - W // 2
        if lo_pred <= cur_lo:
            return
        if self._stage_next is not None and self._stage_next[0] == lo_pred:
            return
        t0 = self.tracer.begin()
        self._stage_next = (lo_pred, self._make_stage(lo_pred, Lw))
        self.tracer.end(PH_STAGE_PREFETCH, t0)

    def _run_superspans(self, target: int) -> None:
        """The superspan dispatch loop: one device program per up-to-K
        slide-spans, one blocking (4,)-int32 progress readback per dispatch
        consumed AFTER the next stage's prefetch is in flight. Host work per
        superspan: the readback, the host-mirror updates (pod_base, window
        cursor, carried name ranks), and — over-budget engines only — the
        overlapped staging assembly."""
        fn = run_superspan_donated if self.donate else run_superspan
        tr = self.tracer
        while self.next_window_idx <= target:
            W = self.pod_window
            stage, lo = self._current_stage()
            rank = (
                self.autoscale_statics.pod_name_rank
                if self.autoscale_statics is not None
                else None
            )
            progress_in = jnp.asarray(
                [self.next_window_idx, self._pod_base, 0, SUPERSPAN_RUN],
                jnp.int32,
            )
            self.dispatch_stats["superspans"] += 1
            donated_in = (
                self.state if (self.donate and self._sanitize) else None
            )
            t0 = tr.begin()
            state, rank, progress = fn(
                self.state,
                rank,
                progress_in,
                self.slab,
                self.consts,
                stage,
                jnp.int32(lo),
                jnp.int32(target),
                W=W,
                K=self._superspan_k,
                chunk=self._superspan_chunk,
                **self._window_call_kwargs(),
            )
            tr.end(PH_SUPERSPAN, t0)
            self.state = state
            if donated_in is not None:
                sanitize.consume_donated(donated_in)
            if rank is not None:
                self.autoscale_statics = self.autoscale_statics._replace(
                    pod_name_rank=rank
                )
            if hasattr(progress, "copy_to_host_async"):
                with sanitize.allow_transfer(
                    self._sanitize, "async progress prefetch"
                ):
                    progress.copy_to_host_async()  # ktpu: sync-ok(async initiation of the waived progress readback — does not block)
            fid = tr.flow_start(PH_PROGRESS_WAIT)
            # Overlap the next stage's host assembly + H2D with the device
            # program still running, BEFORE the blocking readback.
            self._prefetch_stage(lo)
            t0 = tr.begin()
            with sanitize.allow_transfer(
                self._sanitize, "superspan progress readback"
            ):
                w, base, spans, code = (int(v) for v in to_host(progress))  # ktpu: sync-ok(THE steady-state sync: one async-prefetched (4,)-i32 progress readback per superspan dispatch)
            tr.end(PH_PROGRESS_WAIT, t0)
            tr.flow_end(PH_PROGRESS_WAIT, fid)
            self._check_finite()
            self.dispatch_stats["slide_syncs"] += 1
            self.dispatch_stats["superspan_spans"] += spans
            self.next_window_idx = w
            self._pod_base = base
            if code == SUPERSPAN_GROW:
                if not self._grow_pod_window():
                    raise RuntimeError(
                        f"pod_window={self.pod_window} is too small: window "
                        f"{w} needs pod slots beyond the device window "
                        "and no leading pod is terminal yet, and the window "
                        "already covers the whole plain trace segment"
                    )
            elif code == SUPERSPAN_STAGE:
                if self._device_slide is not None:
                    # Unreachable by construction (the whole-trace payload
                    # covers every refill column a slide can touch); a silent
                    # retry here would loop forever, so fail loudly instead.
                    raise RuntimeError(
                        "superspan reported staging exhaustion against the "
                        "whole-trace slide payload"
                    )
                # The stage ran out of slide headroom mid-flight. It may
                # still COVER the final base (exhaustion fires on the
                # pending slide's refill columns, not the window read), so
                # drop it — _current_stage then installs the prefetched
                # successor, or rebuilds at the new base (L - W >= W/2 of
                # fresh headroom, so the retried slide always lands and the
                # dispatch loop can't spin on an exhausted buffer). The
                # streaming ring RETIRES the slab instead: the feeder
                # asserts a retired slab is never re-offered, so the
                # spin-on-exhausted-buffer bug class is structurally
                # pinned rather than relying on this drop.
                if self._feeder is not None:
                    self._feeder.retire(lo)
                self._stage_cur = None
            # SUPERSPAN_RUN with w <= target: K-span budget hit; redispatch.
            # Telemetry ring pressure check (host arithmetic; the fetch, if
            # due, rides the progress readback that JUST blocked — still
            # zero new syncs): long single calls no longer wrap rows out
            # unless ONE dispatch retires more windows than the ring holds.
            self._maybe_drain_ring()

    def _resolve_pending_slide(self) -> bool:
        """Consume a fused slide's pending shift — the span's ONLY host
        sync, an async-prefetched 4-byte readback. The device state already
        slid (or provably could not, shift 0); this just moves the host
        mirrors. Returns False when no slide was possible (grow the window).
        """
        s_arr = self._pending_shift
        self._pending_shift = None
        self.dispatch_stats["slide_syncs"] += 1
        t0 = self.tracer.begin()
        with sanitize.allow_transfer(
            self._sanitize, "fused-slide shift readback"
        ):
            s = int(s_arr)  # ktpu: sync-ok(the fused span's only host sync: async-prefetched 4-byte shift readback, consumed at the span boundary)
        self.tracer.end(PH_SHIFT_WAIT, t0)
        self.tracer.flow_end(PH_SHIFT_WAIT, self._pending_flow)
        if s <= 0:
            # The fused slide was the identity (statics rank swap included);
            # nothing moved on device or host.
            return False
        self._pod_base += s
        self._refill_prefetch = None
        return True

    def _prefetch_refill(self) -> None:
        """Host slide path: build the next slide's refill payload at the
        MAXIMAL quantized width (every possible shift is a prefix of it)
        before the blocking phase fetch, overlapping the host assembly +
        device_put with the span's in-flight device chunks.
        _advance_pod_window slices it to the actual shift."""
        W = self.pod_window
        width = max(W // 2, 1)
        start = self._pod_base + W
        if (
            self._refill_prefetch is not None
            and self._refill_prefetch[:2] == (start, width)
        ):
            return
        self.dispatch_stats["refill_prefetches"] += 1
        t0 = self.tracer.begin()
        self._refill_prefetch = (start, width, self._make_refill(start, width))
        self.tracer.end(PH_REFILL_PREFETCH, t0)

    def _pod_capacity_window(self) -> int:
        """Largest window index dispatchable before a pod creation would land
        beyond the device window (slots are created in event order, so the
        first overflow create's window bounds every cluster)."""
        L = self._pod_base + self.pod_window
        if L >= self._pod_create_win.shape[1]:
            return 1 << 30
        return int(self._pod_create_win[:, L].min())

    def _refresh_name_ranks(self) -> None:
        """Re-slice the windowed pod-name ranks into the autoscale statics
        after a window slide (device layout: [window over plain slots |
        resident rings])."""
        if self.autoscale_statics is None or self._payload_source is None:
            return
        W = self.pod_window
        T = int(self.consts.trace_pod_bound)
        full = self._pod_name_rank_full
        C = full.shape[0]
        BIG_RANK = np.int32(1 << 30)
        seg = full[:, self._pod_base : self._pod_base + W]
        if seg.shape[1] < W:
            seg = np.concatenate(
                [seg, np.full((C, W - seg.shape[1]), BIG_RANK, np.int32)],
                axis=1,
            )
        dev = np.concatenate([seg, full[:, T:]], axis=1)
        old = self.autoscale_statics.pod_name_rank
        put = (
            put_global
            if (self.mesh is not None and is_cross_process(self.mesh))
            else jax.device_put
        )
        new = put(jnp.asarray(dev), old.sharding)
        self.autoscale_statics = self.autoscale_statics._replace(
            pod_name_rank=new
        )

    def _advance_pod_window(self) -> bool:
        t0 = self.tracer.begin()
        try:
            return self._advance_pod_window_impl()
        finally:
            self.tracer.end(PH_SLIDE, t0)

    def _advance_pod_window_impl(self) -> bool:
        """Shift the device pod window past the leading run of terminal pods
        (uniform shift across clusters), refilling the tail from the host
        payload. Only the window segment [0, pod_window) moves; the resident
        pod-group tail beyond it is untouched. Returns False if no shift is
        possible."""
        from kubernetriks_tpu.batched.state import (
            PHASE_EMPTY,
            PHASE_FAILED,
            PHASE_REMOVED,
            PHASE_SUCCEEDED,
        )

        def slice_pad(arr, start, width, fill):
            """arr[:, start:start+width], right-padded with fill past the
            trace's plain-pod segment."""
            seg = arr[:, start : start + width]
            if seg.shape[1] < width:
                pad = np.full(
                    (arr.shape[0], width - seg.shape[1]), fill, arr.dtype
                )
                seg = np.concatenate([seg, pad], axis=1)
            return seg

        W = self.pod_window
        win_lo = self._pod_base
        if self._device_slide is not None:
            # On-device shift computation: only the scalar crosses the
            # tunnel (the host fetch of the full (C, W) phase array was the
            # first of the per-slide round-trips this path eliminates).
            # (The steady-state loop fuses this dispatch pair into the
            # span's last chunk instead — _fused_chunk_slide; this
            # two-dispatch path serves fast-forward/gauge/fuse-disabled
            # engines.)
            self.dispatch_stats["slide_dispatches"] += 1
            self.dispatch_stats["slide_syncs"] += 1
            with sanitize.allow_transfer(
                self._sanitize, "two-dispatch slide shift readback"
            ):
                s = int(  # ktpu: sync-ok(blocking 4-byte shift readback gating the slide decision on the two-dispatch path; the steady-state loop fuses this away)
                    _slide_shift_device(
                        self.state.pods.phase[:, :W],
                        self._device_slide["create_win"],
                        jnp.asarray(win_lo, jnp.int32),
                    )
                )
        else:
            self.dispatch_stats["slide_syncs"] += 1
            with sanitize.allow_transfer(
                self._sanitize, "host slide path phase fetch"
            ):
                phases = to_host(self.state.pods.phase)[:, :W]  # ktpu: sync-ok(host slide path: blocking (C, W) phase fetch — the round-trip the device-resident payload eliminates)
            terminal = (
                (phases == PHASE_SUCCEEDED)
                | (phases == PHASE_REMOVED)
                | (phases == PHASE_FAILED)
            )
            # Padding slots — EMPTY with NO create event in the trace
            # (shorter clusters of a heterogeneous batch, or the padded
            # tail) — can never come alive, so they never block the shift.
            # EMPTY slots whose create event is still pending must stay.
            no_create = np.iinfo(np.int32).max
            create_win = slice_pad(self._pod_create_win, win_lo, W, no_create)
            padding = (phases == PHASE_EMPTY) & (create_win == no_create)
            blocking = ~(terminal | padding)
            first_live = np.where(
                blocking.any(axis=1), blocking.argmax(axis=1), phases.shape[1]
            )
            s = int(first_live.min())
        if s <= 0:
            return False
        # Quantize the shift to a SMALL set of values: every distinct s is a
        # distinct concatenate/refill shape, and each novel shape recompiles
        # the 17-leaf pytree concat (measured ~7 s per novel slide through
        # the tunnel — 400x the actual window step). Three main shapes (W/2,
        # W/4, W/8) plus small powers of two as the forced-minimal fallback;
        # sliding less than possible is harmless — the capacity check just
        # triggers another slide sooner.
        quantum = max(W // 8, 1)
        if s >= W // 2 > 0:
            s = W // 2
        elif s >= W // 4 > 0:
            s = W // 4
        elif s >= quantum:
            s = quantum
        else:
            s = 1 << (s.bit_length() - 1)

        if self._device_slide is not None:
            self.dispatch_stats["slide_dispatches"] += 1
            rank = (
                self.autoscale_statics.pod_name_rank
                if self.autoscale_statics is not None
                else None
            )
            new_pods, new_rank = _slide_apply_device(
                self.state.pods,
                rank,
                self._device_slide,
                jnp.asarray(win_lo, jnp.int32),
                s,
                W,
            )
            self.state = self.state._replace(
                pods=new_pods, pod_base=self.state.pod_base + jnp.int32(s)
            )
            self._pod_base += s
            if new_rank is not None:
                self.autoscale_statics = self.autoscale_statics._replace(
                    pod_name_rank=new_rank
                )
            return True

        pf = self._refill_prefetch
        self._refill_prefetch = None
        if pf is not None and pf[0] == win_lo + W and pf[1] >= s:
            # Prefetched while the span's chunks ran on device: every
            # quantized shift is a prefix of the maximal-width payload.
            refill = jax.tree.map(lambda a: a[:, :s], pf[2])
        else:
            refill = self._make_refill(win_lo + W, s)
        new_pods = jax.tree.map(
            lambda a, b: jnp.concatenate([a[:, s:W], b, a[:, W:]], axis=1),
            self.state.pods,
            refill,
        )
        self.state = self.state._replace(
            pods=new_pods, pod_base=self.state.pod_base + jnp.int32(s)
        )
        self._pod_base += s
        self._refresh_name_ranks()
        return True

    def _make_refill(self, start: int, width: int):
        """Pristine pod slots for global plain slots [start, start + width)
        — built by the SAME constructor init_state uses (windowed,
        full-resident and grown runs can never drift on fresh-slot
        defaults), sliced from the host payload with right-padding past the
        trace, and C-sharded under a mesh so downstream concatenations
        compose shard-local slices. Shared by the host slide path and
        _grow_pod_window."""
        from kubernetriks_tpu.batched.state import (
            duration_pair_np,
            fresh_pod_arrays,
        )

        C = self._pod_create_win.shape[0]
        cols = self._payload_source.segment(start, width)
        refill = fresh_pod_arrays(
            C,
            width,
            cols["req_cpu"],
            cols["req_ram"],
            duration_pair_np(
                cols["duration"],
                self.config.scheduling_cycle_interval,
            ),
        )
        if self.mesh is not None:
            put = put_global if is_cross_process(self.mesh) else jax.device_put
            refill = put(refill, self._state_shardings(self._sharding, refill))
        return refill

    def _grow_pod_window(self) -> bool:
        t0 = self.tracer.begin()
        try:
            return self._grow_pod_window_impl()
        finally:
            self.tracer.end(PH_WINDOW_GROW, t0)

    def _grow_pod_window_impl(self) -> bool:
        """Double the sliding window IN PLACE when a dense stretch of the
        trace outgrows it (peak live-pod span > pod_window, so no slide is
        possible): insert fresh plain-pod slots between the window segment
        and the resident ring tail, re-point the segment mapping
        (consts.resident_shift moves right), and rebuild the windowed
        name-rank/group statics and the device slide payload. Bit-exact:
        window slots [0, new_W) cover global plain slots
        [pod_base, pod_base + new_W) with the SAME fresh-slot constructor
        the initial build uses, and the inserted slots' create events are
        still pending (the capacity check never dispatched a window needing
        them). Shapes change, so the step recompiles once per growth.
        Returns False when the window already spans the whole plain
        segment."""
        W = self.pod_window
        T = int(self.consts.trace_pod_bound)
        if W is None or W >= T:
            return False
        new_W = min(2 * W, T)
        insert = new_W - W
        # Re-seek half of the streaming pipeline: the stage width is keyed
        # to W, so the feeder's slabs are stale after growth — close it
        # BEFORE mutating the payload tables its assemble callback reads
        # (close joins the producer thread; the next staged dispatch
        # rebuilds at the grown geometry).
        self._close_feeder()
        # Cross-process meshes REQUIRE the device-resident slide payload
        # (the host path calls to_host on non-addressable shards) unless
        # the streaming feeder stages slabs instead; check the grown
        # payload against the budget BEFORE mutating anything, so the
        # raise leaves the engine consistent (same predicate as
        # _init_device_slide).
        if (
            self.mesh is not None
            and is_cross_process(self.mesh)
            and self._full_pods is not None
            and not self._stream_on()
            and not self._slide_payload_fits(new_W)
        ):
            raise ValueError(
                "pod_window growth on a cross-process mesh would push "
                "the device-resident slide payload past its memory "
                "budget — raise _DEVICE_SLIDE_BUDGET_BYTES, start with "
                "a larger pod_window, or drop to a single-process mesh "
                "(the host slide path needs every shard addressable)"
            )
        base = self._pod_base
        C = self._pod_create_win.shape[0]
        refill = self._make_refill(base + W, insert)
        new_pods = jax.tree.map(
            lambda a, b: jnp.concatenate([a[:, :W], b, a[:, W:]], axis=1),
            self.state.pods,
            refill,
        )
        self.state = self.state._replace(pods=new_pods)
        self.pod_window = new_W
        self._resident_shift = T - new_W
        self.consts = self.consts._replace(
            resident_shift=np.int32(self._resident_shift)
        )
        if self.autoscale_statics is not None:
            st = self.autoscale_statics
            # The resident ring tail moved right by `insert` device slots:
            # group ids gain `insert` no-group window slots before the tail,
            # ring start indices shift right (padding groups have
            # slot_count 0; their start is only read through real gids).
            pgi = st.pod_group_id
            gap = jnp.full((C, insert), -1, jnp.int32)
            if self.mesh is not None:
                put = (
                    put_global
                    if is_cross_process(self.mesh)
                    else jax.device_put
                )
                gap = put(gap, self._state_shardings(self._sharding, gap))
            self.autoscale_statics = st._replace(
                pod_group_id=jnp.concatenate(
                    [pgi[:, :W], gap, pgi[:, W:]], axis=1
                ),
                pg_slot_start=st.pg_slot_start + jnp.int32(insert),
            )
            if self._hpa_seg not in (None, (0, 0)):
                lo, hi = self._hpa_seg
                self._hpa_seg = (lo + insert, hi + insert)
            self._refresh_name_ranks()  # rebuilds windowed ranks at new_W
        self._init_device_slide()  # re-pad the payload to T + new_W
        # A prefetched refill payload (host slide path) is sized/positioned
        # for the OLD window width — drop it. Superspan staging slabs are
        # width-keyed too (_stage_covers rejects them anyway; free the HBM).
        self._refill_prefetch = None
        self._stage_cur = None
        self._stage_next = None
        if (
            self.mesh is not None
            and is_cross_process(self.mesh)
            and self._device_slide is None
            and not self._stream_on()
        ):
            # Not an assert: this consistency check must survive python -O —
            # silently continuing on a cross-process mesh without the
            # device payload would hit to_host on non-addressable shards
            # much later, as an opaque error.
            raise RuntimeError(
                "pre-mutation budget check above must match "
                "_init_device_slide"
            )
        # Kernel VMEM fits-gates depend on the device pod-axis width.
        self.n_pods += insert
        from kubernetriks_tpu.ops.scheduler_kernel import (
            select_commit_kernel_fits,
            select_kernel_fits,
        )

        self.use_pallas_select = (
            self.use_pallas_select
            and select_kernel_fits(
                self.n_nodes, self.n_pods, self.max_pods_per_cycle
            )
        )
        self.use_megakernel = (
            self.use_megakernel
            and self.use_pallas_select
            and select_commit_kernel_fits(
                self.n_nodes, self.n_pods, self.max_pods_per_cycle
            )
        )
        import logging

        logging.getLogger(__name__).info(
            "pod_window grew %d -> %d at window base %d (live span outgrew "
            "the window)", W, new_W, base,
        )
        return True

    # Float state fields whose +/-inf values are documented sentinels ("no
    # pending effect" pairs, estimator min/max identities) — everything else
    # must be finite after every chunk under KTPU_DEBUG_FINITE=1.
    _FINITE_EXEMPT = (
        "finish_time",
        "removal_time",
        "remove_time",
        "create_time",
        "hpa_next",
        "ca_next",
        "minimum",
        "maximum",
    )

    def _check_finite(self) -> None:
        """KTPU_DEBUG_FINITE=1 guard mode: sweep every float leaf of the
        state after a dispatched chunk — NaN anywhere, or inf outside the
        documented sentinel fields, raises with the offending field name.
        Host-side readback, so the donated hot path is untouched when off.
        KTPU_SANITIZE folds this sweep in at every dispatch boundary (on
        the superspan path: once per superspan, where the progress
        readback already syncs)."""
        if not (self._debug_finite or self._sanitize):
            return
        with sanitize.allow_transfer(self._sanitize, "finite-guard sweep"):
            self._check_finite_now()

    def _check_finite_now(self) -> None:  # ktpu: sync-ok(guard-mode state sweep body: full host readback is the point)
        flat, _ = jax.tree_util.tree_flatten_with_path(self.state)
        for path, leaf in flat:
            arr = np.asarray(to_host(leaf))
            if not np.issubdtype(arr.dtype, np.floating):
                continue
            key = jax.tree_util.keystr(path)
            if np.isnan(arr).any():
                raise FloatingPointError(
                    f"KTPU_DEBUG_FINITE: NaN in state field {key} after "
                    f"window {self.next_window_idx - 1}"
                )
            if not any(tok in key for tok in self._FINITE_EXEMPT) and not (
                np.isfinite(arr).all()
            ):
                raise FloatingPointError(
                    f"KTPU_DEBUG_FINITE: non-finite value in state field "
                    f"{key} after window {self.next_window_idx - 1}"
                )

    def _decisions_total(self) -> int:  # ktpu: sync-ok(log_throughput instrumentation: per-chunk decisions counter fetch, instrumented runs only)
        """Blocking fetch of the summed decisions counter — the ONE owner
        of the instrumented path's throughput probe (PR 8 deduped the
        before/after fetch sites onto it)."""
        with sanitize.allow_transfer(
            self._sanitize, "log_throughput decisions fetch"
        ):
            return int(to_host(self.state.metrics.scheduling_decisions).sum())

    def _step_idxs(
        self,
        idxs: np.ndarray,
        fuse_slide: bool = False,
        freeze_lanes: bool = True,
    ) -> None:
        if not (self.profile_dir or self.log_throughput):
            self._dispatch_windows(
                idxs, fuse_slide=fuse_slide, freeze_lanes=freeze_lanes
            )
            self._check_finite()
            return

        # Instrumented path: optional jax.profiler capture + a per-chunk
        # decisions/s log line (TPU analog of the scalar events/s log,
        # reference: src/simulator.rs:363-368). The per-chunk timing and
        # log formatting live on the tracer (telemetry/tracer.py); while a
        # profiler capture is active, tracer spans also enter
        # jax.profiler.TraceAnnotations so host phases land in the xplane
        # next to the device ops they dispatched
        # (scripts/profile_composed_xplane.py correlates them).
        import contextlib
        import logging
        import time

        ctx = (
            jax.profiler.trace(self.profile_dir)
            if self.profile_dir
            else contextlib.nullcontext()
        )
        from kubernetriks_tpu.telemetry.tracer import PH_CHUNK_FENCED

        self.tracer.annotate = bool(self.profile_dir)
        before = self._decisions_total() if self.log_throughput else 0
        t0 = time.perf_counter()
        with ctx, self.tracer.span(PH_CHUNK_FENCED):
            self._dispatch_windows(
                idxs, fuse_slide=fuse_slide, freeze_lanes=freeze_lanes
            )
            jax.block_until_ready(self.state.time)  # ktpu: sync-ok(instrumented path: fence so the per-chunk clock measures device work, not dispatch)
        elapsed = time.perf_counter() - t0
        self.tracer.annotate = False
        self._check_finite()
        if self.log_throughput:
            log_chunk_throughput(
                logging.getLogger(__name__),
                len(idxs),
                self.n_clusters,
                self._decisions_total() - before,
                elapsed,
            )

    def step_window(self) -> None:
        """Advance a single scheduling cycle (useful for tests)."""
        if self.pod_window is not None:
            assert self.next_window_idx <= self._pod_capacity_window(), (
                "step_window would apply a pod creation beyond the sliding "
                "pod window; use step_until_time (which shifts the window) "
                "or a larger pod_window"
            )
        self.state = window_step(
            self.state,
            self.slab,
            jnp.asarray(self.next_window_idx, jnp.int32),
            self.consts,
            self.max_events_per_window,
            self.max_pods_per_cycle,
            self.autoscale_statics,
            self.max_ca_pods_per_cycle,
            self.max_pods_per_scale_down,
            self.use_pallas,
            self.pallas_interpret,
            self.conditional_move,
            pallas_mesh=self.mesh if self.use_pallas else None,
            pallas_axis=self._batch_axis,
            use_pallas_select=self.use_pallas_select,
            use_megakernel=self.use_megakernel,
            hpa_seg=self._hpa_seg,
            fault_params=self.fault_params,
            name_ranks=self._fault_name_ranks,
            lane_major=self.lane_major,
            window_razor=self.window_razor,
            ca_descatter=self.ca_descatter,
            reclaim=self.reclaim,
            reclaim_period=self.reclaim_period,
            profile=self.profile,
        )
        if self.collect_gauges:
            from kubernetriks_tpu.batched.step import gauge_snapshot

            self._gauges.append(
                np.asarray([self.next_window_idx], np.int32),  # ktpu: sync-ok(single-window test helper: host-side window index, no device value)
                to_host(gauge_snapshot(self.state))[None],  # ktpu: sync-ok(gauge instrumentation in the single-window test helper)
            )
        self.next_window_idx += 1

    def run_to_completion(self, max_time: float = 1e7) -> None:
        """Step until every trace pod has terminated (scalar equivalent:
        RunUntilAllPodsAreFinishedCallbacks), bounded by max_time."""
        interval = self.config.scheduling_cycle_interval
        chunk = max(64, self.max_events_per_window)
        finite = self._ev_time_np[np.isfinite(self._ev_time_np)]
        last_event_time = float(finite.max()) if finite.size else 0.0
        while True:
            self.step_until_time(self.next_window + chunk * interval)
            # Never conclude before the trace is fully applied: EMPTY slots
            # may still be waiting on future CreatePod events. An event in
            # window w is only applied when window w+1 steps, so the run must
            # have advanced strictly past last_event_time + interval.
            if self.next_window <= last_event_time + interval:
                continue
            phases = to_host(self.state.pods.phase)  # ktpu: sync-ok(completion poll at chunk boundary — the batched analog of the scalar run-until-finished callback)
            service = to_host(self.state.pods.duration.win) < 0  # ktpu: sync-ok(completion poll at chunk boundary)
            # Finite-duration pods not yet terminal?
            live = (
                ((phases == PHASE_QUEUED) | (phases == PHASE_UNSCHEDULABLE))
                | ((phases == PHASE_RUNNING) & ~service)
            )
            if not live.any():
                return
            if self.next_window > max_time:
                raise RuntimeError(
                    f"run_to_completion exceeded max_time={max_time}; "
                    f"{int(live.sum())} pods still live"
                )

    # --- readout ------------------------------------------------------------

    def check_autoscaler_bounds(self) -> None:  # ktpu: sync-ok(readout: divergence counters fetched once at summary time)
        """Raise loudly when a documented autoscaler work bound was crossed
        and the trajectory has (or is about to) diverge from the scalar
        semantics (autoscale.py "Remaining bounded deviations"):

        - HPA reserve clamp: an HPA cycle wanted more replicas than the
          group's reserve had reusable slots for. The scalar
          (kube_horizontal_pod_autoscaler.rs:157-181) would have created
          them — counts are already wrong.
        - CA reserve starvation: a scale-up cycle wanted to open a node for
          a cache pod — quota headroom and a fitting template existed — but
          the group's ca_slot_multiplier x max_count slot reserve was
          consumed (slots are never reclaimed, the batched analog of the
          reference's pre-sized component pool, src/simulator.rs:212-230 —
          but the reference RECLAIMS components on scale-down,
          node_component_pool.rs:60-77, so long churn never exhausts it
          there). The pod silently stays unscheduled where the scalar would
          have provisioned a node.

        Both are EXACT observed-divergence counters folded inside the
        passes (autoscale.py), not state heuristics: a run that merely
        consumed its reserve without unmet demand does not raise.
        """
        if self.autoscale_statics is None or not self.strict_autoscaler_bounds:
            return
        from kubernetriks_tpu.parallel.multihost import to_host

        clamped = np.asarray(to_host(self.state.metrics.hpa_reserve_clamped))
        if clamped.sum() > 0:
            worst = int(clamped.argmax())
            raise RuntimeError(
                f"HPA slot reserve exhausted: {int(clamped.sum())} wanted "
                f"replica(s) across {int((clamped > 0).sum())} cluster(s) "
                f"(worst: cluster {worst}, {int(clamped[worst])}) could not "
                "be activated because no reusable slot remained in the pod "
                "group's reserve — the scalar path would have created them, "
                "so reported replica counts have diverged. Enlarge the "
                "group's slot reserve (trace compile pg_slot_count) or "
                "lower max_pods churn; set strict_autoscaler_bounds=False "
                "to read the diverged metrics anyway."
            )
        starved = np.asarray(to_host(self.state.metrics.ca_reserve_starved))
        if starved.sum() > 0:
            worst = int(starved.argmax())
            if self.reclaim:
                hint = (
                    "slot reclaim is ON, so every fully-retired slot was "
                    "already returned — the reserve is exhausted by LIVE "
                    "demand (plus removals still inside their visibility "
                    "horizon). Raise ca_slot_multiplier (build arg) to "
                    "widen the reserve"
                )
            else:
                hint = (
                    "scaled-up slots are never reclaimed on this build — "
                    "raise ca_slot_multiplier (build arg) to widen the "
                    "reserve, or enable slot reclaim (reclaim=True / "
                    "KTPU_RECLAIM=1) so retired slots return to it"
                )
            raise RuntimeError(
                f"CA slot reserve exhausted: {int(starved.sum())} "
                f"scale-up attempt(s) across {int((starved > 0).sum())} "
                f"cluster(s) (worst: cluster {worst}, "
                f"{int(starved[worst])}) found quota headroom and a "
                "fitting node-group template but no reserved slot left — "
                "the demand silently starved where the scalar path would "
                f"have provisioned a node. {hint}; or set "
                "strict_autoscaler_bounds=False to accept the starved "
                "trajectory."
            )
        # Decimal-suffix name keys (autoscale.decimal_string_key) order
        # "{prefix}_{idx}" names exactly for idx < 10^8; past that the
        # int32 key saturates its digit bands and name-ordered walks
        # would silently drift. Endurance runs approach this only after
        # ~10^8 allocations per group — raise loudly instead of drifting.
        auto = self.state.auto
        if auto is not None:
            tail_max = int(np.asarray(to_host(auto.hpa_tail)).max())
            total_max = 0
            if auto.ca_total is not None:
                total_max = int(np.asarray(to_host(auto.ca_total)).max())
            if max(tail_max, total_max) >= 10**8:
                raise RuntimeError(
                    f"allocation-name counter overflow: hpa_tail max "
                    f"{tail_max}, ca_total max {total_max} reached the "
                    "10^8 bound of the decimal-suffix name keys "
                    "(autoscale.decimal_string_key) — name-ordered "
                    "victim/walk selection is no longer exact past it"
                )

    def tuning_statics(self) -> Dict[str, object]:
        """The RESOLVED values of every closed-domain tuning knob
        (tune/knobs.py) this build compiled in — after the full per-knob
        precedence (explicit kwarg > env flag > tuned profile > platform
        default) played out. The autotuner's profile-roundtrip gates
        compare this table across builds: a profile that 'loads back
        build-identical' means equal tables here."""
        # Every field below is a plain Python jit-static the constructor
        # already normalised to bool/int — no array readout happens here.
        return {
            "superspan": self._superspan,
            "fuse_slide": self._fuse_slide,
            "superspan_k": int(self._superspan_k),
            "superspan_chunk": int(self._superspan_chunk),
            "lane_major": self.lane_major,
            "window_razor": self.window_razor,
            "ca_descatter": self.ca_descatter,
            "donate": self.donate,
            "stream": self._stream,
            "stream_depth": int(self._stream_depth),
        }

    def metrics_summary(self) -> Dict:  # ktpu: sync-ok(readout: one-shot cross-cluster metric reduction after the run)
        """Cross-cluster reduction into the scalar printer's shape. On a
        cross-process mesh the metric arrays allgather over DCN first.
        Raises via check_autoscaler_bounds when a documented autoscaler
        work bound was crossed (divergence would otherwise be silent)."""
        from kubernetriks_tpu.parallel.multihost import to_host

        self.check_autoscaler_bounds()
        m = jax.tree.map(to_host, self.state.metrics)

        def est(e):
            count = np.asarray(e.count, np.int64)
            total = np.asarray(e.total, np.float64)
            total_sq = np.asarray(e.total_sq, np.float64)
            n = count.sum()
            if n == 0:
                return {"min": math.inf, "max": -math.inf, "mean": math.nan, "variance": math.nan}
            mean = total.sum() / n
            return {
                "min": float(np.asarray(e.minimum).min()),
                "max": float(np.asarray(e.maximum).max()),
                "mean": float(mean),
                "variance": float(total_sq.sum() / n - mean * mean),
            }

        return {
            "counters": {
                "pods_succeeded": int(np.asarray(m.pods_succeeded).sum()),
                "pods_removed": int(np.asarray(m.pods_removed).sum()),
                "terminated_pods": int(np.asarray(m.terminated_pods).sum()),
                "processed_nodes": int(np.asarray(m.processed_nodes).sum()),
                "scheduling_decisions": int(np.asarray(m.scheduling_decisions).sum()),
                "total_scaled_up_pods": int(np.asarray(m.scaled_up_pods).sum()),
                "total_scaled_down_pods": int(np.asarray(m.scaled_down_pods).sum()),
                "total_scaled_up_nodes": int(np.asarray(m.scaled_up_nodes).sum()),
                "total_scaled_down_nodes": int(np.asarray(m.scaled_down_nodes).sum()),
                # Chaos-engine fault counters (zero when faults are off).
                "node_crashes": int(np.asarray(m.node_crashes).sum()),
                "node_recoveries": int(np.asarray(m.node_recoveries).sum()),
                "node_downtime_s": float(
                    np.asarray(m.node_downtime_s, np.float64).sum()
                ),
                "pod_interruptions": int(np.asarray(m.pod_interruptions).sum()),
                "pod_restarts": int(np.asarray(m.pod_restarts).sum()),
                "pods_failed": int(np.asarray(m.pods_failed).sum()),
            },
            "timings": {
                "pod_duration": est(m.pod_duration),
                "pod_schedule_time": est(m.algo_latency),
                "pod_queue_time": est(m.queue_time),
            },
        }

    def cluster_metrics(self, cluster: int) -> Dict:  # ktpu: sync-ok(readout: per-cluster counters after the run)
        m = self.state.metrics
        return {
            "pods_succeeded": int(m.pods_succeeded[cluster]),
            "pods_removed": int(m.pods_removed[cluster]),
            "terminated_pods": int(m.terminated_pods[cluster]),
            "scheduling_decisions": int(m.scheduling_decisions[cluster]),
        }

    def hpa_replicas(self, cluster: int) -> Dict[str, int]:  # ktpu: sync-ok(readout: replica counts after the run)
        """Per-pod-group created replica counts (scalar equivalent:
        len(PodGroupInfo.created_pods))."""
        auto = self.state.auto
        assert auto is not None, "autoscaling is not enabled"
        head = to_host(auto.hpa_head)[cluster]
        tail = to_host(auto.hpa_tail)[cluster]
        names = self.pod_group_names[cluster]
        return {name: int(tail[i] - head[i]) for i, name in enumerate(names)}

    def ca_slots_reclaimed(self) -> np.ndarray:  # ktpu: sync-ok(readout: reclaim counter after the run)
        """(C,) CA reserve slots returned by the reclaim compaction
        (zeros when reclaim is off) — the 'reclaim actually fired'
        observable the endurance gates assert on."""
        auto = self.state.auto
        if auto is None or auto.ca_reclaimed is None:
            return np.zeros(self.n_clusters, np.int32)
        return np.asarray(to_host(auto.ca_reclaimed))

    def ca_node_counts(self, cluster: int) -> np.ndarray:  # ktpu: sync-ok(readout: node counts after the run)
        """Current cluster-autoscaler node count per node group."""
        auto = self.state.auto
        assert auto is not None, "autoscaling is not enabled"
        return to_host(auto.ca_count)[cluster]

    def node_count_at(self, t: float, cluster: int = 0) -> int:  # ktpu: sync-ok(readout: point-in-time node count query)
        """Alive node count at absolute time t, resolving pending
        create/remove effects with effect time <= t. The step applies an
        effect when it next runs a window PAST the effect's time — an
        implementation detail of the lazy window application — so a faithful
        'how many nodes exist at t' read must resolve the scheduled effects
        the state already carries (the batched equivalent of the scalar
        api_server.node_count() sampled mid-window).

        CA-slot effects carry a readout correction (r14, surfaced by the
        endurance gates at drift phases no short run reaches): the device
        pairs are the SCHEDULER/NODE-side visibility times the simulation
        semantics need (create d_ca_up = fire + 3*as_to_ca + 5*as_to_ps +
        ps_to_sched, the PS->scheduler notification; remove d_ca_down =
        fire + 3*as_to_ca + 4*as_to_ps + as_to_node, the node component
        going down), while the scalar oracle `api_server.node_count()`
        flips at the AS bookkeeping instants — `_handle_create_node` runs
        one (as_to_ps + ps_to_sched) BEFORE the scheduler hears, and
        `on_node_removed_from_cluster` one as_to_node AFTER the component
        died. Chaos never targets CA slots (their crash payload is zero
        padding), so every pending CA-slot effect is a CA-cycle effect
        and the constant shifts are exact. Effects a window already
        resolved can no longer be shifted, so boundary-exact samples keep
        a sub-delay edge — sample mid-window (the suite's boundary+5
        convention) for exact trajectories.

        Trace/chaos node events carry the complementary correction: a
        slab event earlier in the CURRENT (unexecuted) window is visible
        in neither the alive flags nor the pending pairs, so the readout
        replays the host-side node-event schedule
        (self._node_event_table) over the unapplied suffix with the same
        AS-bookkeeping shifts — a mid-window sample right after a chaos
        crash agrees with the scalar count (the r14 endurance gates
        sample exactly there)."""
        interval = self.config.scheduling_cycle_interval
        win = int(t // interval)
        off = t - win * interval
        cfg = self.config
        # The same AS-bookkeeping shifts for CA-slot pending pairs and
        # unapplied slab node events: the device times are scheduler/
        # node-side visibility, the scalar count flips at the AS
        # bookkeeping instants.
        up_shift = float(
            cfg.as_to_ps_network_delay + cfg.ps_to_sched_network_delay
        )
        down_shift = float(cfg.as_to_node_network_delay)
        nodes = self.state.nodes
        alive = to_host(nodes.alive)[cluster]
        cw = to_host(nodes.create_time.win)[cluster]
        co = to_host(nodes.create_time.off)[cluster]
        rw = to_host(nodes.remove_time.win)[cluster]
        ro = to_host(nodes.remove_time.off)[cluster]
        due_create = (cw < win) | ((cw == win) & (co <= off))
        due_remove = (rw < win) | ((rw == win) & (ro <= off))
        st = self.autoscale_statics
        if st is not None and st.ca_slots.shape[1] > 0:
            slots = np.asarray(st.ca_slots)[cluster]
            slots = slots[slots >= 0]
            if slots.size:
                ca = np.zeros(alive.shape[0], bool)
                ca[slots] = True
                abs_c = cw.astype(np.float64) * interval + co - up_shift
                abs_r = rw.astype(np.float64) * interval + ro + down_shift
                due_create = np.where(ca, abs_c <= t, due_create)
                due_remove = np.where(ca, abs_r <= t, due_remove)
        count = (alive | due_create) & ~due_remove
        # Trace/chaos slab node events the step has not APPLIED yet (their
        # window never executed — the r14 endurance gates sample mid-window
        # while a crash sits earlier in the same window): resolve them from
        # the host-side schedule, last transition at or before t wins.
        # Events in executed windows already live in the flags/pairs above.
        applied_win = int(to_host(self.state.time)[cluster])
        et, is_create, es, ew = self._node_event_table[cluster]
        eff = np.where(is_create, et - up_shift, et + down_shift)
        sel = (ew >= applied_win) & (eff <= t)
        # "Last transition wins" is defined on the EFFECTIVE (shifted)
        # times, not the slab order: a short-downtime crash/recover pair
        # inverts under the shifts (recover's -up_shift lands before
        # crash's +down_shift when downtime < up_shift + down_shift), and
        # the scalar's AS bookkeeping then processed the removal last.
        # Stable sort keeps slab FIFO order at equal effective instants.
        idx = np.nonzero(sel)[0]
        for i in idx[np.argsort(eff[idx], kind="stable")]:
            count[es[i]] = bool(is_create[i])
        return int(count.sum())

    # --- telemetry readout --------------------------------------------------

    def _maybe_drain_ring(self, force: bool = False):
        """Drain the device telemetry ring before records wrap out. The
        pressure check is pure host arithmetic (window cursor vs ring
        capacity); the blocking fetch itself lives in telemetry/ring.py
        and only ever runs at boundaries where the host already blocks —
        step_until_time entry/exit, readout, and (since the capacity
        observatory) the steady-state loop's OWN sync points, immediately
        after the superspan progress readback / slide-shift readback
        blocked anyway — never a new sync (the no-new-syncs half of the
        telemetry contract; dispatch_stats stay equal on/off). Returns the
        observatory's drain record when a drain happened, else None."""
        if self.state.telemetry is None:
            return None
        pending = self.next_window_idx - self._ring_drained_at
        if not force and pending * 2 < self._telemetry_ring_size:
            return None
        from kubernetriks_tpu.telemetry import ring as dring

        with sanitize.allow_transfer(
            self._sanitize,
            "telemetry ring drain riding an existing host-block boundary",
        ):
            buf, cursor = dring.snapshot(self.state.telemetry)
        dring.merge_snapshot(self._ring_seen, buf)
        cap = self.telemetry_series_windows
        if cap and len(self._ring_seen) > cap:
            # Prune the OLDEST windows past the series bound (disclosed
            # in telemetry_report as ring.series_dropped_windows).
            drop = sorted(self._ring_seen)[: len(self._ring_seen) - cap]
            for w in drop:
                del self._ring_seen[w]
            self._ring_series_dropped += len(drop)
        self._ring_windows_recorded = max(
            self._ring_windows_recorded, cursor
        )
        self._ring_drained_at = self.next_window_idx
        return self._observe_drain(buf)

    def _observe_drain(self, buf) -> Optional[Dict]:
        """Feed one drained ring buffer (an OWNED host copy — see
        drain_telemetry's aliasing note) to the capacity observatory:
        occupancy ingest, memory-watermark sample, watchdog pass, and the
        export hooks. Pure host work on drained copies."""
        if self.observatory is None:
            return None
        fresh = self.observatory.ingest(buf)
        feeder_rep = None
        if self._feeder is not None:
            feeder_rep = self._feeder.report()
            feeder_rep["restarts"] = self._feeder_restarts
            self.dispatch_stats["feeder_slabs_produced"] = (
                self._feeder_produced_total + feeder_rep["slabs_produced"]
            )
        stats = dict(self.dispatch_stats)
        return self.observatory.observe(
            resources=self._sample_resources(),
            dispatch_stats=stats,
            sync_budget={
                "steady_state_expected": stats["superspans"]
                + stats["fused_slides"],
                "observed_slide_syncs": stats["slide_syncs"],
            },
            feeder=feeder_rep,
            fresh=fresh,
        )

    def drain_telemetry(self) -> Dict:
        """Force a telemetry-ring drain + observatory observation NOW and
        return the drain record ({} when telemetry is off). THE explicit
        seam the watchdog/export path uses between step_until_time calls
        (PR 8 left mid-run drains riding step_until_time exits only; the
        steady-state loop now also drains under pressure at its own sync
        points, so a long single call can no longer silently exceed the
        windows_recorded > windows_kept disclosure unless ONE dispatch
        retires more than the ring holds).

        Owned-copy rule (the donated-dispatch aliasing hazard): on the
        CPU backend the drain's device fetch may ALIAS the live ring
        buffer, and the next donated dispatch mutates that buffer in
        place — telemetry/ring.snapshot therefore forces an owned
        np.array copy before anything downstream sees the rows. Rows
        returned here stay valid across later dispatches
        (tests/test_telemetry.py pins this against a donated engine)."""
        return self._maybe_drain_ring(force=True) or {}

    def attach_metrics_exporter(self, exporter) -> None:
        """Register a time-series export hook — an object with
        .emit(record: dict), e.g. telemetry/export.JsonlExporter — called
        once per ring drain with the observatory's pure-python record.
        Exports run strictly from drained host copies (the export seam
        carries the hot-path lint pragma with zero sync waivers)."""
        if self.observatory is None:
            raise ValueError(
                "telemetry is off — build with telemetry=True or "
                "KTPU_TRACE=1 to attach metrics exporters"
            )
        self.observatory.exporters.append(exporter)

    def _sample_resources(self) -> Dict:  # ktpu: sync-ok(drain-point resource sampling: backend allocator stats + host RSS + slab byte accounting — host-side reads, no simulation-state sync)
        """Host/device memory sample for the observatory's watermarks:
        host RSS (procfs), backend allocator stats where the platform
        exposes them (TPU/GPU; CPU usually returns nothing), and EXACT
        slab/ring byte accounting from the staging machinery. Runs only
        at drain points (ring pressure / explicit drain_telemetry), never
        inside a dispatch."""
        from kubernetriks_tpu.telemetry.observatory import sample_host_memory

        res: Dict = dict(sample_host_memory())
        dev_in_use = dev_peak = 0
        have_dev = False
        for d in jax.local_devices():
            try:
                ms = d.memory_stats()
            except Exception:
                ms = None
            if not ms:
                continue
            have_dev = True
            dev_in_use += int(ms.get("bytes_in_use", 0))
            dev_peak += int(ms.get("peak_bytes_in_use", 0))
        if have_dev:
            res["device_bytes_in_use"] = dev_in_use
            res["device_peak_bytes_in_use"] = dev_peak
        res["slabs"] = self._slab_accounting()
        return res

    def _slab_accounting(self) -> Dict:
        """Exact staging-memory accounting (host arithmetic over known
        geometry + buffer sizes): the device slide payload, live staging
        slabs, the feeder ring's capacity bound, and the telemetry ring
        itself. Flat numbers here across superspans ARE the bounded-memory
        claim of the streaming pipeline (tests/test_soak.py pins it)."""

        def nbytes(tree) -> int:
            total = 0
            for leaf in jax.tree_util.tree_leaves(tree):
                total += int(getattr(leaf, "nbytes", 0) or 0)
            return total

        host_payload = 0
        if self._full_pods is not None:
            host_payload += sum(
                int(a.nbytes) for a in self._full_pods.values()
            )
        for small in (
            getattr(self, "_pod_create_win", None),
            getattr(self, "_pod_name_rank_full", None),
        ):
            if small is not None:
                host_payload += int(small.nbytes)
        acct = {
            "device_slide_bytes": (
                nbytes(self._device_slide)
                if self._device_slide is not None
                else 0
            ),
            # Resident host payload: the whole-trace request/duration
            # arrays (released by attach_payload_source) plus the small
            # per-pod int32 tables the engine keeps for O(1) lookups —
            # the observable behind the bounded-host-memory claim.
            "host_payload_bytes": host_payload,
            "stage_bytes": nbytes(
                [s for s in (self._stage_cur, self._stage_next) if s is not None]
            ),
            "telemetry_ring_bytes": (
                nbytes(self.state.telemetry)
                if self.state.telemetry is not None
                else 0
            ),
        }
        if self._feeder is not None:
            n_arrays = 5 + (1 if self.autoscale_statics is not None else 0)
            per_slab = (
                self.n_clusters * self._feeder.width * 4 * n_arrays
            )
            acct["feeder_slab_bytes"] = per_slab
            acct["feeder_ring_capacity_bytes"] = per_slab * self._feeder.depth
        return acct

    def telemetry_window_series(self):
        """(windows (Wn,), records (Wn, C, K)) device-ring per-window
        series; columns follow telemetry.ring.RING_COLUMNS. Empty arrays
        when telemetry is off."""
        from kubernetriks_tpu.telemetry import ring as dring

        self._maybe_drain_ring(force=True)
        return dring.series(self._ring_seen, self.n_clusters)

    def telemetry_report(self) -> Dict:
        """Aggregated flight-recorder readout: per-phase host wall time
        (exact even when the span ring wrapped), dispatch stats incl.
        ladder_fallbacks, the observed sync count vs the documented
        steady-state budget (1 progress readback per superspan + 1 shift
        readback per fused slide — the lint pass's sync-ok waiver set),
        stage-prefetch hit/miss counts, the dispatch-chunk histogram, and
        the device ring's totals. Callable with telemetry off (dispatch
        stats only, enabled: False)."""
        feeder_rep = None
        if self._feeder is not None:
            # ONE snapshot under the feeder's lock: syncing dispatch_stats
            # from the same report keeps the cumulative counter a superset
            # of the section even while the producer is mid-publish.
            feeder_rep = self._feeder.report()
            feeder_rep["restarts"] = self._feeder_restarts
            self.dispatch_stats["feeder_slabs_produced"] = (
                self._feeder_produced_total + feeder_rep["slabs_produced"]
            )
        stats = dict(self.dispatch_stats)
        rep = {"enabled": self._telemetry, "dispatch_stats": stats}
        rep.update(self.tracer.report())
        if feeder_rep is not None:
            # Streaming-feeder section: production counters, the
            # ring-depth gauge, and the stall split (feeder-not-ready vs
            # upload-wait — the same two numbers the stage_wait_* tracer
            # spans carry, kept here so untraced runs still expose them).
            rep["feeder"] = feeder_rep
        rep["sync_budget"] = {
            "steady_state_expected": stats["superspans"]
            + stats["fused_slides"],
            "observed_slide_syncs": stats["slide_syncs"],
        }
        hits = rep["counters"].get("stage_prefetch_hit", 0)
        misses = rep["counters"].get("stage_prefetch_miss", 0)
        if hits + misses:
            rep["stage_prefetch_hit_rate"] = hits / (hits + misses)
        # Per-window cost line: the window-program DISPATCH phases plus the
        # blocking readback WAITS (progress_wait / shift_wait), divided by
        # the windows the device ring recorded. Dispatch is asynchronous,
        # so execution time surfaces in the waits — dispatch + wait
        # together bound compile + device time per window (on a warm jit
        # cache the wait share IS the device-execution proxy). THE
        # observable the lane-major / razor / de-scatter A/Bs are sized
        # with — bench.py --smoke --trace asserts it, so a layout
        # regression moves a number CPU CI sees.
        from kubernetriks_tpu.telemetry.tracer import PHASE_NAMES as _PN

        window_phases = (
            _PN[PH_WINDOW_CHUNK],
            _PN[PH_FUSED_CHUNK_SLIDE],
            _PN[PH_SUPERSPAN],
            _PN[PH_PROGRESS_WAIT],
            _PN[PH_SHIFT_WAIT],
            # Streaming-feeder stalls block the dispatch loop exactly like
            # the readback waits, so they belong to the per-window cost
            # (zero on non-streaming runs — continuity with r7-r9 numbers).
            _PN[PH_STAGE_WAIT_FEEDER],
            _PN[PH_STAGE_WAIT_UPLOAD],
            "chunk_fenced",
        )
        win_ms = sum(
            rep["spans"][p]["total_ms"]
            for p in window_phases
            if p in rep.get("spans", {})
        )
        if self.state.telemetry is not None:
            from kubernetriks_tpu.telemetry import ring as dring

            wins, data = self.telemetry_window_series()
            rep["ring"] = {
                "columns": list(dring.RING_COLUMNS),
                "windows_recorded": self._ring_windows_recorded,
                "windows_kept": int(len(wins)),
                "series_dropped_windows": self._ring_series_dropped,
                # Sums only make sense for the per-window ACTION deltas;
                # point-in-time gauges (queue depths, alive nodes, the
                # observatory's reserve-occupancy columns) report their
                # high-water mark instead.
                "totals": {
                    name: int(data[:, :, col].sum()) if len(wins) else 0
                    for col, name in enumerate(dring.RING_COLUMNS)
                    if col > 0 and name not in dring.GAUGE_COLUMNS
                },
                "high_water": {
                    name: int(data[:, :, col].max()) if len(wins) else 0
                    for col, name in enumerate(dring.RING_COLUMNS)
                    if name in dring.GAUGE_COLUMNS
                },
            }
        if self.observatory is not None:
            # Capacity-observatory section: occupancy (current +
            # high-water vs reserve capacity), host/device memory
            # watermarks, slab/ring accounting, watchdog verdicts. The
            # memory sample is refreshed so the report reflects NOW.
            self.observatory.update_memory(self._sample_resources())
            rep["resources"] = self.observatory.report()
            windows = int(self._ring_windows_recorded)
            if windows > 0:
                rep["per_window"] = {
                    "windows": windows,
                    "window_program_ms_total": win_ms,
                    "ms_per_window": win_ms / windows,
                }
        return rep

    def write_chrome_trace(self, path: str) -> str:
        """Write the Chrome trace-event JSON (Perfetto-loadable): host
        spans, async-readback flow arrows, and the device ring as
        sim-time counter tracks. Requires telemetry on."""
        if not self._telemetry:
            raise ValueError(
                "telemetry is off — build with telemetry=True or KTPU_TRACE=1"
            )
        extra = None
        if self.state.telemetry is not None:
            from kubernetriks_tpu.telemetry import ring as dring

            wins, data = self.telemetry_window_series()
            extra = dring.counter_events(
                wins, data, self.config.scheduling_cycle_interval
            )
        return self.tracer.write_chrome_trace(path, extra)

    def close(self) -> None:
        """Release background resources — currently the streaming
        feeder's producer thread. Idempotent and optional: the producer
        is a daemon that exits with the process (and on its own once the
        final slab is published), but long-lived hosts building many
        engines should close the ones they abandon."""
        self._close_feeder()

    # --- checkpoint / resume ------------------------------------------------
    # The whole simulation state is one pytree of arrays, so checkpointing is
    # a direct orbax save (SURVEY §5.4: absent in the reference — runs are
    # seed+config+trace — but cheap here and useful for long RL training).

    def _ckpt_payload(self):
        return {
            "state": self.state,
            "next_window_idx": jnp.asarray(self.next_window_idx, jnp.int32),
        }

    def save_checkpoint(self, path: str) -> None:
        """Persist the device state + window cursor to an orbax checkpoint
        directory (overwrites), and the accumulated gauge series — whose
        length is run-dependent, unlike the fixed-shape state pytree — to a
        numpy sidecar next to it."""
        from kubernetriks_tpu.checkpoint import ckpt_save

        with self.tracer.span(PH_CKPT_SAVE):
            ckpt_save(path, self._ckpt_payload())
            # The window can GROW mid-run (_grow_pod_window), changing the
            # pod arrays' shapes — record it so load_checkpoint can grow a
            # freshly built engine to match before restoring.
            meta_path = os.path.abspath(path) + ".meta.json"
            meta = {}
            if self.pod_window is not None:
                meta["pod_window"] = int(self.pod_window)
            if self.state.telemetry is not None:
                # The telemetry ring is part of the state pytree; a
                # restore template must carry a matching ring, so record
                # its capacity for load_checkpoint's loud guard.
                meta["telemetry_ring"] = int(self._telemetry_ring_size)
            if self.reclaim:
                # Slot-reclaim leaves (ca_alloc/ca_total/...) ride the
                # state pytree; record the mode so a mismatched restore
                # raises the actionable message instead of an opaque
                # manifest diff. Reclaim-off saves write nothing,
                # keeping older checkpoints loadable.
                meta["reclaim"] = True
            from kubernetriks_tpu.batched.pipeline import DEFAULT_PROFILE

            if self.profile != DEFAULT_PROFILE:
                # The compiled scheduler profile is an engine-build static:
                # restoring this state into an engine compiled with a
                # different profile would silently continue the run under
                # different scheduling semantics — record it for
                # load_checkpoint's loud guard (default-profile saves write
                # nothing, keeping old checkpoints loadable).
                meta["scheduler_profile"] = {
                    "name": self.profile.name,
                    "filters": list(self.profile.filters),
                    "scores": [list(s) for s in self.profile.scores],
                }
            if meta:
                import json

                with open(meta_path, "w") as fh:
                    json.dump(meta, fh)
            elif os.path.exists(meta_path):
                # A plain save over a previously windowed/telemetry
                # checkpoint must not leave the stale meta to mislead a
                # later load (same shadowing rule as the gauges sidecar
                # below).
                os.remove(meta_path)
            # Gauge series sidecar (run-length-dependent shape, unlike the
            # state pytree); an empty series removes a stale file so a
            # previous save's gauges never shadow this run's on restore.
            self._gauges.save_sidecar(os.path.abspath(path) + ".gauges.npz")

    def load_checkpoint(self, path: str) -> None:  # ktpu: sync-ok(checkpoint restore: cold path)
        """Restore state saved by save_checkpoint into this simulation (which
        must have been built from the same config/traces — the current state
        pytree provides the restore structure). Restored arrays land
        unsharded; re-apply device placement for mesh runs if needed."""
        from kubernetriks_tpu.checkpoint import ckpt_restore

        meta_path = os.path.abspath(path) + ".meta.json"
        meta = {}
        if os.path.exists(meta_path):
            import json

            with open(meta_path) as fh:
                meta = json.load(fh)
        # Telemetry mismatch guard: the ring is part of the state pytree,
        # so a template without a matching ring would fail deep inside
        # ckpt_restore as an opaque structure error — raise the
        # actionable message here instead (the same treatment pod_window
        # gets below). Runs with meta absent too: a plain save writes no
        # meta at all, and restoring it into a telemetry-armed engine is
        # exactly the mismatch.
        saved_reclaim = bool(meta.get("reclaim", False))
        if saved_reclaim != self.reclaim:
            # Tristate-defaulted engines FOLLOW the checkpoint instead of
            # raising: KTPU_RECLAIM defaults on for accelerator backends,
            # so every pre-reclaim checkpoint would otherwise refuse to
            # restore on TPU/GPU until the user dug up KTPU_RECLAIM=0.
            # The swap is a cold-path mode flip: reclaim is a per-call
            # jit static (next dispatch compiles the other program) and
            # the slot-reclaim leaves are presence-only in the auto
            # pytree, so matching the TEMPLATE to the saved structure is
            # all the restore needs. Explicit reclaim=/KTPU_RECLAIM
            # requests still raise — the user asked for a specific mode.
            followable = self._reclaim_requested is None and (
                not saved_reclaim
                or (
                    self.autoscale_statics is not None
                    and self.autoscale_statics.ca_slot_class is not None
                )
            )
            if followable:
                import warnings as _warnings

                _warnings.warn(
                    f"checkpoint saved with reclaim={saved_reclaim} but "
                    f"this engine defaulted to {self.reclaim} "
                    f"(KTPU_RECLAIM tristate): following the checkpoint "
                    f"— continuing with reclaim={saved_reclaim}",
                    RuntimeWarning,
                    stacklevel=2,
                )
                self.reclaim = saved_reclaim
                auto_t = self.state.auto
                if saved_reclaim:
                    fresh = init_autoscale_state(
                        self.autoscale_statics,
                        reclaim=True,
                        collect=auto_t.col_next is not None,
                    )
                    auto_t = auto_t._replace(
                        ca_alloc=fresh.ca_alloc,
                        ca_total=fresh.ca_total,
                        ca_reclaimed=fresh.ca_reclaimed,
                    )
                else:
                    auto_t = auto_t._replace(
                        ca_alloc=None, ca_total=None, ca_reclaimed=None
                    )
                self.state = self.state._replace(auto=auto_t)
            else:
                raise ValueError(
                    f"checkpoint reclaim mismatch: saved with reclaim="
                    f"{saved_reclaim}, this engine built with "
                    f"{self.reclaim} — the slot-reclaim leaves are part "
                    "of the state pytree; build the restoring engine "
                    f"with reclaim={saved_reclaim} (KTPU_RECLAIM) to "
                    "continue the run"
                )
        saved_ring = meta.get("telemetry_ring")
        have_ring = (
            self._telemetry_ring_size
            if self.state.telemetry is not None
            else None
        )
        if saved_ring != have_ring:
            raise ValueError(
                f"checkpoint telemetry ring mismatch: saved "
                f"telemetry_ring={saved_ring}, this engine has "
                f"{have_ring} — build with telemetry="
                f"{saved_ring is not None} and telemetry_ring="
                f"{saved_ring} (or KTPU_TRACE) to restore it"
            )
        # Scheduler-profile mismatch guard: the compiled profile is a
        # build-time static, so a restore into a differently-profiled
        # engine would silently continue the run under different
        # scheduling semantics (the silent-wrong-profile failure mode).
        # Saves under the default profile write no key; absence == default.
        from kubernetriks_tpu.batched.pipeline import (
            CompiledProfile,
            DEFAULT_PROFILE,
        )

        saved_prof = meta.get("scheduler_profile")
        if saved_prof is not None:
            saved_prof = CompiledProfile(
                name=saved_prof["name"],
                filters=tuple(saved_prof["filters"]),
                scores=tuple(
                    (str(n), float(w)) for n, w in saved_prof["scores"]
                ),
            )
        if (saved_prof or DEFAULT_PROFILE) != self.profile:
            raise ValueError(
                f"checkpoint scheduler-profile mismatch: saved "
                f"{(saved_prof or DEFAULT_PROFILE).name!r} "
                f"{(saved_prof or DEFAULT_PROFILE).scores}, this engine "
                f"compiled {self.profile.name!r} {self.profile.scores} — "
                "build the restoring engine with the same "
                "scheduler_profile to continue the run"
            )
        saved_window = meta.get("pod_window")
        if saved_window is not None and self.pod_window is not None:
            while self.pod_window < saved_window:
                if not self._grow_pod_window():
                    break
            if self.pod_window != saved_window:
                # Not an assert: under python -O the mismatch would
                # surface later as an opaque ckpt_restore shape error.
                raise ValueError(
                    f"checkpoint was saved at pod_window={saved_window}; "
                    f"this engine is at {self.pod_window} and cannot match"
                )
        with self.tracer.span(PH_CKPT_RESTORE):
            restored = ckpt_restore(path, self._ckpt_payload())
            self.state = restored["state"]
            self.next_window_idx = int(restored["next_window_idx"])
            self._pod_base = int(np.asarray(self.state.pod_base)[0])
            # Re-seek the streaming feeder (and drop engine-held staging
            # slabs): the restored base may precede everything staged so
            # far, and the ring's never-re-offer invariant makes serving
            # an earlier base an assertion — the rebuilt feeder restarts
            # its slab schedule at the restored base instead of replaying
            # (slab content is position-keyed, so no replay divergence is
            # possible either way).
            self._close_feeder()
            self._stage_cur = None
            self._stage_next = None
            self._refresh_name_ranks()
            self._gauges = GaugeSeries.load_sidecar(
                os.path.abspath(path) + ".gauges.npz"
            )
            # Ring rows drained before the restore described the
            # pre-restore trajectory; the restored ring carries its own.
            self._ring_seen = {}
            self._ring_series_dropped = 0
            self._ring_windows_recorded = 0
            self._ring_drained_at = 0
            if self.observatory is not None:
                # The occupancy trajectory restarts at the restored state;
                # mixing pre-restore points would corrupt the watchdog fit.
                self.observatory.reset()

    def gauge_series(self):
        """(times (W,), samples (W, C, 7)) accumulated gauge time-series;
        columns follow the scalar GAUGE_CSV_COLUMNS after the timestamp
        (series buffer: telemetry/gauges.py)."""
        return self._gauges.series(
            self.n_clusters, self.config.scheduling_cycle_interval
        )

    def write_gauge_csv(self, path: str, cluster: int = 0) -> None:
        """Dump one cluster's gauge series in the scalar collector's 8-column
        schema (reference: src/metrics/collector.rs:216-228), so the offline
        plotting tooling consumes either backend's output unchanged."""
        self._gauges.write_csv(
            path,
            cluster,
            self.n_clusters,
            self.config.scheduling_cycle_interval,
        )

    def pod_view(self, cluster: int) -> Dict[str, Dict]:  # ktpu: sync-ok(readout: name-keyed pod states for equivalence tests)
        """Name-keyed pod states for equivalence tests against the scalar
        path. With a sliding pod window, only the currently-resident slots
        appear (shifted-out pods are terminal and already counted)."""
        phases = to_host(self.state.pods.phase)[cluster]
        nodes = to_host(self.state.pods.node)[cluster]
        start_pair = self.state.pods.start_time
        starts = to_f64(
            type(start_pair)(
                win=to_host(start_pair.win)[cluster],
                off=to_host(start_pair.off)[cluster],
            ),
            self.config.scheduling_cycle_interval,
        )
        names = self.pod_names[cluster]
        node_names = self.node_names[cluster]
        W = self.pod_window
        out = {}
        for slot in range(phases.shape[0]):
            # Device slot -> global slot: window segment shifts by pod_base,
            # the resident pod-group tail by the fixed resident_shift.
            if W is not None and slot >= W:
                g = self._resident_shift + slot
            else:
                g = self._pod_base + slot
            if g >= len(names) or not names[g]:
                continue  # batch padding (or segmented-layout filler) slot
            out[names[g]] = {
                "phase": int(phases[slot]),
                "node": node_names[nodes[slot]] if nodes[slot] >= 0 else None,
                "start_time": float(starts[slot]),
            }
        return out


def build_batched_from_traces(
    config: SimulationConfig,
    cluster_events,
    workload_events,
    n_clusters: int = 1,
    **kwargs,
) -> BatchedSimulation:
    """Replicate one (cluster trace, workload trace) pair across n_clusters —
    the homogeneous-batch benchmark shape.

    With fault injection enabled and node faults configured, each cluster
    gets its OWN crash/recover schedule (the counter PRNG keys on the
    cluster index — cluster 0 matches the scalar path), so the trace is
    compiled per cluster instead of tiled."""
    ram_unit = kwargs.pop("ram_unit", DEFAULT_RAM_UNIT)
    slot_mult = kwargs.pop("pod_group_slot_multiplier", 2)

    from kubernetriks_tpu import chaos

    fault_cfg = getattr(config, "fault_injection", None)
    if chaos.has_node_faults(fault_cfg):
        fault_seed = (
            fault_cfg.seed if fault_cfg.seed is not None else config.seed
        )
        # Scenario-vector fleet: per-LANE crash-chain seeds. The chain
        # compiler then keys every lane on cluster 0 with its own seed —
        # a lane's crash schedule becomes a pure function of its scenario
        # seed (same-seed lanes share one schedule; lane c with seed s
        # matches the scalar oracle run with seed s), instead of the
        # replicated-batch default where every lane derives a distinct
        # schedule from (shared seed, lane index). NOTE: chain events are
        # compiled into the trace slab, so node-fault seeds are fixed at
        # BUILD (per wave of lanes they are config data the fleet sets
        # once); the pod-fault seed channel stays pure traced data.
        scenario = kwargs.get("scenario")
        lane_seeds = None
        if scenario is not None:
            # ANY scenario build keys scenario-pure: the engine installs
            # consts.fault_seed for the pod channel whenever a scenario
            # is present (defaulting every lane to the config seed), so
            # the node chains must follow the same rule or the two fault
            # channels would mix per-lane and per-index keying.
            seeds = scenario.get("fault_seed")
            lane_seeds = np.broadcast_to(
                np.asarray(  # ktpu: sync-ok(engine build: host numpy over the scenario seed vector, no device values)
                    seeds if seeds is not None else fault_seed, np.int64
                ),
                (n_clusters,),
            )
        horizon = chaos.fault_horizon(
            fault_cfg, cluster_events, workload_events
        )
        # Same (seed, cluster-key) -> same chain: memoize the compile so
        # a fleet of repeated scenarios pays one chain per unique seed.
        _chain_cache: dict = {}

        def _compiled_for(c: int):
            seed = fault_seed if lane_seeds is None else int(lane_seeds[c])
            ckey = c if lane_seeds is None else 0
            got = _chain_cache.get((seed, ckey))
            if got is None:
                got = _chain_cache[(seed, ckey)] = compile_cluster_trace(
                    chaos.inject_node_faults(
                        cluster_events,
                        fault_cfg,
                        seed,
                        ckey,
                        horizon,
                        config.scheduling_cycle_interval,
                    ),
                    workload_events,
                    config,
                    ram_unit=ram_unit,
                    pod_group_slot_multiplier=slot_mult,
                )
            return got

        compiled_list = [_compiled_for(c) for c in range(n_clusters)]
        return BatchedSimulation(config, compiled_list, **kwargs)

    compiled = compile_cluster_trace(
        cluster_events,
        workload_events,
        config,
        ram_unit=ram_unit,
        pod_group_slot_multiplier=slot_mult,
    )
    return BatchedSimulation(config, [compiled] * n_clusters, **kwargs)
