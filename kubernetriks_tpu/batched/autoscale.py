"""Vectorized autoscaler passes for the batched backend.

The scalar HPA / cluster-autoscaler control loops (reference:
src/autoscalers/horizontal_pod_autoscaler/*.rs, cluster_autoscaler/*.rs)
become masked array passes over the dense cluster-batch state, run at their
scan cadence inside the window step:

- HPA: per-(cluster, pod-group) closed-form utilization from the compiled
  load curves, the k8s desired-replicas formula with tolerance band
  (reference: kube_horizontal_pod_autoscaler.rs:54-155), and head/tail
  activation windows over the group's reserved pod slots.
- CA: bounded-K first-fit bin-packing scale-up over the unscheduled-pod cache
  and a nested-scan scale-down with simulated re-placement over shared virtual
  allocatables (reference: kube_cluster_autoscaler.rs:55-307).

Times are the 32-bit (win, off) pairs of timerep.py; the only 64-bit math is
the load-curve elapsed-time evaluation (float64 on tiny (C, G) shapes — the
curves cycle over arbitrary-length periods, where float32 elapsed time at
Alibaba-scale timestamps would blur the curve position).

Round-4 exact-CA semantics (the old "one-window visibility shift" and
"fixed cadence" approximations are retired; tests/test_random_ca_equivalence
pins sample-for-sample trajectory equality, incl. conditional-move churn):
- ca_next carries the TRUE cycle fire time: the scalar re-arms
  scan_interval after the info round-trip returns
  (cluster_autoscaler.py on_response; reference
  cluster_autoscaler.rs:256-262 with delay 0 on overrun), so the period is
  round_trip + scan_interval and cycles DRIFT across windows. Cycle k runs
  in the window containing its storage-snapshot time s_k = fire + as_to_ca
  + as_to_ps; effects compose from the fire time.
- The decision reads the storage's view at s_k exactly: pre-cycle shadows
  when s_k precedes this window's commit visibility (ca_pass `pre`), and
  finish-visibility reconstruction on both sides of the window boundary
  (_ca_scale_down vis_gone/vis_back).
- Scale-down walks candidates and first-fits re-placements in NODE-NAME
  order (info.nodes is name-sorted); scale-up bin-packs the cache in
  POD-NAME order (scale_up_info sorts names) via the static name ranks.

Remaining bounded deviations:
- Scale-up considers at most K_up cache pods and scale-down at most K_sd pods
  per candidate node per cycle; overflow is deferred to the next cycle
  (scale-up) or conservatively skipped (scale-down).
- CA slot reserve: each group reserves slots ~ multiplier x max_count,
  mirroring the reference's pre-sized component pool
  (src/simulator.rs:212-230). Under reclaim (KTPU_RECLAIM, the r14
  endurance work) fully-retired slots are RETURNED to the reserve by a
  periodic in-trace compaction (ca_reclaim_pass) the way the reference's
  node_component_pool reuses components (node_component_pool.rs:60-77),
  so `ca_cursor` tracks LIVE reserve occupancy instead of cumulative
  allocations and sustained churn never exhausts the reserve; names stay
  scalar-exact because each allocation carries the scalar's monotone
  total_allocated index (auto.ca_alloc / ca_total — "{group}_{idx+1}")
  and every name-ordered walk derives its order from that index
  (ca_name_order). Without reclaim the cursor is monotone and the loud
  bound (engine.check_autoscaler_bounds) remains the only backstop.
- CA-cache name ORDER for HPA replicas whose slot has been ring-reused uses
  the slot's first occupant's static name rank (pod_name_rank); HPA
  scale-down victim IDENTITY is exact regardless (pods.hpa_idx stores the
  live occupant's replica index).
- Sub-scan-interval CA cadences (scan_interval < the window interval)
  degrade to one cycle per window.

Round-4 HPA identity semantics: scale-down pops the lexicographically
SMALLEST replica name from the group's live set exactly like the scalar's
BTreeSet (kube_horizontal_pod_autoscaler.rs:197-205) — victims are
scattered, so scale-up activates the first free slots of the reserve in
slot order and stores each occupant's replica index in pods.hpa_idx
("{group}_{idx}" naming, idx = total-created counter); hpa_head counts
total removals, keeping current = tail - head.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from kubernetriks_tpu.batched.state import (
    ClusterBatchState,
    PHASE_EMPTY,
    PHASE_FAILED,
    PHASE_QUEUED,
    PHASE_REMOVED,
    PHASE_RUNNING,
    PHASE_SUCCEEDED,
    PHASE_UNSCHEDULABLE,
    StepConstants,
    swap_node_layout,
)
from kubernetriks_tpu.batched.timerep import (
    TPair,
    is_inf,
    t_add,
    t_inf,
    t_le,
    t_lt,
    t_min,
    t_where,
    t_zeros,
)
from kubernetriks_tpu.batched.pipeline import DEVICE_FILTER_PLUGINS
from kubernetriks_tpu.core.scheduler.plugins import FIT

INF = jnp.inf
_BIG_I32 = jnp.iinfo(jnp.int32).max

# The Fit feasibility predicate, shared with the scheduler pipeline's
# device-plugin registry: CA placement simulation stays first-fit by
# reference semantics, but "fits" means the same thing everywhere.
_fit_filter = DEVICE_FILTER_PLUGINS[FIT]


class AutoscaleStatics(NamedTuple):
    """Compile-time autoscaler tables (pytree of arrays; C-leading)."""

    # --- HPA pod groups: (C, Gp) ---
    pg_slot_start: jnp.ndarray  # int32 first reserved pod slot
    pg_slot_count: jnp.ndarray  # int32 reserved slots (cumulative creations cap)
    pg_initial: jnp.ndarray  # int32 initial replicas (created by the trace)
    pg_max_pods: jnp.ndarray  # int32 max simultaneous replicas
    pg_target_cpu: jnp.ndarray  # float32; <=0 means metric unset
    pg_target_ram: jnp.ndarray  # float32; <=0 means metric unset
    # First HPA tick that sees the group: creation + register delay (pair);
    # win=INF_WIN = padding / HPA disabled.
    pg_active_from: TPair
    # Absolute creation time in float64 seconds for load-curve elapsed math.
    pg_creation_s: jnp.ndarray
    # Piecewise-cyclic load curves, (C, Gp, U); duration 0 = padding unit.
    pg_cpu_dur: jnp.ndarray
    pg_cpu_load: jnp.ndarray
    pg_cpu_total: jnp.ndarray  # (C, Gp) cycle length; 0 = no model (util 0)
    pg_cpu_const: jnp.ndarray  # bool: constant model (load IS the utilization)
    pg_ram_dur: jnp.ndarray
    pg_ram_load: jnp.ndarray
    pg_ram_total: jnp.ndarray
    pg_ram_const: jnp.ndarray
    pod_group_id: jnp.ndarray  # (C, P) int32 group of pod slot; -1 = none
    # --- CA node groups: (C, Gn) ---
    ng_ca_start: jnp.ndarray  # int32 first CA-slot (in the compact CA axis)
    ng_slot_count: jnp.ndarray  # int32 reserved CA slots
    ng_max_count: jnp.ndarray  # int32; <0 = unbounded
    ng_tmpl_cpu: jnp.ndarray  # int32 template capacity
    ng_tmpl_ram: jnp.ndarray  # int32 (ram units)
    ca_max_nodes: jnp.ndarray  # (C,) int32 global CA node quota
    ca_slots: jnp.ndarray  # (C, S) int32 global node slot of CA slot; -1 pad
    ca_slot_group: jnp.ndarray  # (C, S) int32 owning group; -1 pad
    # --- per-lane control-law parameters: (C,) pairs / arrays -----------
    # Scenario-vector fleet (batched/fleet.py): every leaf below is
    # per-CLUSTER traced data composed by fleet.scenario_leaves — a fleet
    # of heterogeneous autoscaler configs runs under ONE compiled program
    # (scalar-config builds carry the base value replicated across C).
    hpa_interval: TPair  # (C,) per-lane HPA scan interval
    hpa_tolerance: jnp.ndarray  # (C,) f64 per-lane target tolerance
    ca_threshold: jnp.ndarray  # (C,) f64 per-lane scale-down threshold
    d_hpa_up: TPair  # (C,) HPA tick -> scaled-up pod enters scheduler queue
    d_hpa_down: TPair  # (C,) HPA tick -> pod removal effect at storage
    d_ca_up: TPair  # (C,) CA tick -> scaled-up node schedulable
    d_ca_down: TPair  # (C,) CA tick -> node removal effect at node
    # --- exact-CA cadence/visibility (r4; see ca_pass docstring) ---
    ca_period: TPair  # (C,) true cycle period: round-trip + scan (or just rt)
    ca_snap: TPair  # (C,) cycle fire -> storage snapshot (as_to_ca + as_to_ps)
    ca_finish_vis: TPair  # (C,) node finish -> storage visibility
    ca_commit_vis: TPair  # (C,) scheduler commit -> storage visibility
    pod_name_rank: jnp.ndarray  # (C, P) int32 lexicographic name rank; BIG = n/a
    node_name_rank: jnp.ndarray  # (C, N) int32 node-name rank (trace + CA slots)
    ca_sd_order: jnp.ndarray  # (C, S) CA slot indices in name order
    # --- HPA metrics-collection cadence (staleness fix, r14) -----------
    # The scalar HPA reads whatever the metrics collector's fixed 60 s
    # collection cycle last pulled (metrics/collector.py
    # COLLECTION_INTERVAL); this pair is that cadence as device time, so
    # hpa_pass can latch collection-window snapshots (AutoscaleState
    # col_*) instead of sampling the load curve at its own tick.
    col_interval: Optional[TPair] = None  # (C,) the 60 s collection cadence
    # --- reclaim name-order tables (r14; None = reclaim unsupported) ---
    # The scalar names every allocation "{group}_{total_allocated}"; with
    # slot reuse the name no longer equals the slot, so name-ordered
    # walks (scale-down candidates, re-placement first-fit, same-window
    # reschedule batches) derive their order from the occupant's
    # allocation index. Cross-CLASS order (trace node vs group name
    # family, family vs family) is static — verified non-interleaving at
    # build (engine._reclaim_class_tables) — and only the within-group
    # decimal-suffix order is dynamic.
    ca_slot_class: Optional[jnp.ndarray] = None  # (C, S) int32 class rank of slot's group
    ca_class_start: Optional[jnp.ndarray] = None  # (C, Gn) int32 first class-sorted slot pos
    node_class_key: Optional[jnp.ndarray] = None  # (C, N) int32 class_rank * (S + 1)


def statics_with_pod_rank(
    statics: Optional[AutoscaleStatics], rank
) -> Optional[AutoscaleStatics]:
    """Rebind the windowed pod-name ranks into the statics. The superspan
    executor (step.run_superspan) slides the pod window ON DEVICE, so the
    ranks become loop-carried state rather than a per-dispatch constant;
    every window chunk inside the loop reads its statics through this ONE
    rebinding point (the statics argument's own pod_name_rank leaf is never
    read there — it merely pins shape/sharding)."""
    if statics is None or rank is None:
        return statics
    return statics._replace(pod_name_rank=rank)


class AutoscaleState(NamedTuple):
    """Dynamic autoscaler state (lives inside ClusterBatchState.auto).

    The Optional leaves are structural statics in the `auto`/`telemetry`
    tradition: None compiles programs without the corresponding machinery
    (reclaim off / collection latch off), arrays arm it. ca_cursor under
    reclaim tracks LIVE reserve occupancy (compaction pulls it back);
    without reclaim it is the classic monotone next-slot cursor."""

    hpa_head: jnp.ndarray  # (C, Gp) int32 first live created offset
    hpa_tail: jnp.ndarray  # (C, Gp) int32 next creation offset (== total_created)
    ca_count: jnp.ndarray  # (C, Gn) int32 current CA nodes per group
    ca_cursor: jnp.ndarray  # (C, Gn) int32 next reserved slot offset
    hpa_next: TPair  # (C,) next HPA tick
    ca_next: TPair  # (C,) next CA tick
    # --- CA slot reclaim (r14; None = reclaim off) ---------------------
    ca_alloc: Optional[jnp.ndarray] = None  # (C, S) int32 occupant's allocation
    # index (the scalar's total_allocated - 1 at open time); -1 = free slot.
    # INVARIANT: occupied slots are exactly the per-group prefix
    # [ng_ca_start, ng_ca_start + ca_cursor) — allocation appends at the
    # cursor and compaction re-packs keepers stably, so slot order among
    # live CA nodes always equals allocation order (which keeps the
    # scheduler's slot-order tie-break identical to the no-reclaim path).
    ca_total: Optional[jnp.ndarray] = None  # (C, Gn) int32 monotone allocation
    # counter (the scalar's group.total_allocated; names are "{g}_{total}").
    ca_reclaimed: Optional[jnp.ndarray] = None  # (C,) int32 slots returned to
    # the reserve by compaction (the "reclaim actually fired" observable).
    # --- HPA collection latch (r14 staleness fix; None = legacy inline) ---
    col_next: Optional[TPair] = None  # (C,) next 60 s collection tick
    col_run: Optional[jnp.ndarray] = None  # (C, Gp) int32 running count at the
    # last collection (0 = group absent from the sample, like the scalar's
    # metrics dict missing the group).
    col_util_cpu: Optional[jnp.ndarray] = None  # (C, Gp) f32 latched utilization
    col_util_ram: Optional[jnp.ndarray] = None  # (C, Gp) f32


# --- contract-prover registries (ktpu-lint; see state.py's checklist) --------
# Leaf manifest of AutoscaleState — must equal the fields exactly
# (stateleaf pass); structural ca_* leaves additionally need a DESIGN §12
# entry and a CKPT_COVERED_LEAVES story (engine.py).
AUTOSCALE_STATE_LEAVES = (
    "hpa_head",
    "hpa_tail",
    "ca_count",
    "ca_cursor",
    "hpa_next",
    "ca_next",
    "ca_alloc",
    "ca_total",
    "ca_reclaimed",
    "col_next",
    "col_run",
    "col_util_cpu",
    "col_util_ram",
)

# AutoscaleStatics leaves that are per-lane TRACED scenario data — the
# fleet.scenario_leaves composition targets. The scenariotrace lint pass
# forbids them from flowing into Python control flow, host casts, jit
# statics or shape expressions: a what-if config must never shape a
# program (the fleet's compile-once guarantee, statically).
SCENARIO_TRACED_LEAVES = (
    "hpa_interval",
    "hpa_tolerance",
    "ca_threshold",
    "ca_max_nodes",
    "pg_active_from",
    "d_hpa_up",
    "d_hpa_down",
    "d_ca_up",
    "d_ca_down",
    "ca_period",
    "ca_snap",
    "ca_finish_vis",
    "ca_commit_vis",
)

# Declared axis signatures (shapecontract pass): the per-cluster "C" lane
# vectors are exactly the leaves whose broadcasts against per-object
# (C, G)/(C, P)/(C, S) planes MUST be explicit ([:, None]) — the PR 13
# tolerance/finish_vis bug class. "C,G,*" = the (C, G, U) curve tables.
AXIS_SIGNATURES = {
    # AutoscaleState
    "hpa_head": "C,G",
    "hpa_tail": "C,G",
    "ca_count": "C,G",
    "ca_cursor": "C,G",
    "ca_total": "C,G",
    "ca_alloc": "C,S",
    "ca_reclaimed": "C",
    "hpa_next": "C",
    "ca_next": "C",
    "col_next": "C",
    "col_run": "C,G",
    "col_util_cpu": "C,G",
    "col_util_ram": "C,G",
    # AutoscaleStatics per-lane control-law leaves
    "hpa_interval": "C",
    "hpa_tolerance": "C",
    "ca_threshold": "C",
    "ca_max_nodes": "C",
    "d_hpa_up": "C",
    "d_hpa_down": "C",
    "d_ca_up": "C",
    "d_ca_down": "C",
    "ca_period": "C",
    "ca_snap": "C",
    "ca_finish_vis": "C",
    "ca_commit_vis": "C",
    "col_interval": "C",
    # AutoscaleStatics tables
    "pg_slot_start": "C,G",
    "pg_slot_count": "C,G",
    "pg_initial": "C,G",
    "pg_max_pods": "C,G",
    "pg_target_cpu": "C,G",
    "pg_target_ram": "C,G",
    "pg_active_from": "C,G",
    "pg_creation_s": "C,G",
    "pg_cpu_dur": "C,G,*",
    "pg_cpu_load": "C,G,*",
    "pg_cpu_total": "C,G",
    "pg_cpu_const": "C,G",
    "pg_ram_dur": "C,G,*",
    "pg_ram_load": "C,G,*",
    "pg_ram_total": "C,G",
    "pg_ram_const": "C,G",
    "pod_group_id": "C,P",
    "ng_ca_start": "C,G",
    "ng_slot_count": "C,G",
    "ng_max_count": "C,G",
    "ng_tmpl_cpu": "C,G",
    "ng_tmpl_ram": "C,G",
    "ca_slots": "C,S",
    "ca_slot_group": "C,S",
    "ca_sd_order": "C,S",
    "ca_slot_class": "C,S",
    "ca_class_start": "C,G",
    "pod_name_rank": "C,P",
    "node_name_rank": "C,N",
    "node_class_key": "C,N",
}


def init_autoscale_state(
    statics: AutoscaleStatics,
    reclaim: bool = False,
    collect: bool = False,
) -> AutoscaleState:
    """reclaim arms the CA slot-reclaim leaves (requires the statics'
    name-order tables); collect arms the HPA collection latch (the engine
    sets it whenever real pod groups exist)."""
    C, Gp = statics.pg_slot_start.shape
    Gn = statics.ng_ca_start.shape[1]
    S = statics.ca_slots.shape[1]
    if reclaim and statics.ca_slot_class is None:
        raise ValueError(
            "init_autoscale_state(reclaim=True) needs the statics' reclaim "
            "name-order tables (ca_slot_class/ca_class_start/node_class_key) "
            "— built by engine.build_autoscale_statics when the name "
            "classes verify non-interleaving"
        )
    return AutoscaleState(
        hpa_head=jnp.zeros((C, Gp), jnp.int32),
        # The trace's initial pods count as created (the api-server expansion
        # seeds created_pods/total_created, reference: api_server.rs:405-455).
        hpa_tail=statics.pg_initial.astype(jnp.int32),
        ca_count=jnp.zeros((C, Gn), jnp.int32),
        ca_cursor=jnp.zeros((C, Gn), jnp.int32),
        hpa_next=t_zeros((C,)),
        ca_next=t_zeros((C,)),
        ca_alloc=jnp.full((C, S), -1, jnp.int32) if reclaim else None,
        ca_total=jnp.zeros((C, Gn), jnp.int32) if reclaim else None,
        ca_reclaimed=jnp.zeros((C,), jnp.int32) if reclaim else None,
        col_next=t_zeros((C,)) if collect else None,
        col_run=jnp.zeros((C, Gp), jnp.int32) if collect else None,
        col_util_cpu=jnp.zeros((C, Gp), jnp.float32) if collect else None,
        col_util_ram=jnp.zeros((C, Gp), jnp.float32) if collect else None,
    )


def _curve_load(dur, load, total, elapsed):
    """Piecewise-constant cyclic curve lookup (reference semantics:
    src/core/resource_usage/pod_group.rs:71-99). dur/load: (C, G, U);
    total/elapsed: (C, G). elapsed is float64 (see module docstring); the
    returned load is float32."""
    safe_total = jnp.maximum(total.astype(jnp.float64), 1e-9)
    pos = jnp.where(total > 0, jnp.mod(elapsed, safe_total), 0.0)
    ecs = jnp.cumsum(dur, axis=-1) - dur  # exclusive start of each unit
    in_unit = (ecs <= pos[..., None]) & (pos[..., None] < ecs + dur)
    return jnp.where(in_unit, load, 0.0).sum(axis=-1).astype(jnp.float32)


def _broadcast_pair(p: TPair, shape) -> TPair:
    return TPair(
        win=jnp.broadcast_to(p.win[..., None], shape),
        off=jnp.broadcast_to(p.off[..., None], shape),
    )


def decimal_string_key(idx: jnp.ndarray) -> jnp.ndarray:
    """int32 key whose order equals the LEXICOGRAPHIC order of str(idx)
    for 0 <= idx < 10^8 ("g_10" < "g_2"): left-align the value to 8
    digits, tie-break shorter-first. Max key < 16 * 10^8 < 2^31. THE
    decimal-suffix ordering primitive shared by the HPA victim selection
    and the CA reclaim name orders — one implementation so the suffix
    rule can't drift."""
    idx = jnp.maximum(idx, 0)
    digits = (
        1
        + (idx >= 10).astype(jnp.int32)
        + (idx >= 100).astype(jnp.int32)
        + (idx >= 1_000).astype(jnp.int32)
        + (idx >= 10_000).astype(jnp.int32)
        + (idx >= 100_000).astype(jnp.int32)
        + (idx >= 1_000_000).astype(jnp.int32)
        + (idx >= 10_000_000).astype(jnp.int32)
    )
    pow10 = jnp.asarray(
        [0, 10_000_000, 1_000_000, 100_000, 10_000, 1_000, 100, 10, 1],
        jnp.int32,
    )
    return idx * pow10[digits] * jnp.int32(16) + digits


def ca_name_order(
    auto: AutoscaleState, st: AutoscaleStatics
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Dynamic name orderings of the LIVE CA fleet under slot reclaim:
    (sd_order (C, S) — CA slot indices in current node-name order, the
    drop-in for the static st.ca_sd_order — and node_key (C, N) — an
    int32 key whose order over alive nodes equals node-name order, the
    drop-in for st.node_name_rank in re-placement first-fit and
    same-window reschedule ranking).

    An occupant's name is "{group}_{alloc+1}" (the scalar's
    total_allocated naming). Cross-class order (trace singleton vs group
    family, family vs family) is static — the build verified the classes
    non-interleaving — so the key decomposes as class_rank * (S + 1) +
    within-group rank, where the within-group rank comes from ONE stable
    (C, S) 2-key sort by (class, decimal-suffix key). Free slots sort
    after their group's occupants (suffix key BIG) and keep the class
    base key — they are dead, so every consumer masks them by liveness
    first. When no slot has ever been reused (alloc == slot offset) both
    orders coincide with the static tables exactly."""
    C, S = auto.ca_alloc.shape
    Gn = st.ca_class_start.shape[1]
    rows = jnp.arange(C, dtype=jnp.int32)[:, None]
    iota_s = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32)[None, :], (C, S))
    occupied = auto.ca_alloc >= 0
    suffix = jnp.where(
        occupied, decimal_string_key(auto.ca_alloc + 1), _BIG_I32
    )
    _, _, sd_order = jax.lax.sort(
        (st.ca_slot_class, suffix, iota_s), dimension=1, num_keys=2,
        is_stable=True,
    )
    # Sorted position of each slot -> within-group rank (each group's
    # slots are contiguous in class order; ca_class_start is the static
    # first position of the group's segment).
    pos = jnp.zeros((C, S), jnp.int32).at[rows, sd_order].set(iota_s)
    gidc = jnp.clip(st.ca_slot_group, 0, Gn - 1)
    within = jnp.where(
        occupied, pos - st.ca_class_start[rows, gidc], 0
    )
    N = st.node_class_key.shape[1]
    tgt = jnp.where(occupied & (st.ca_slots >= 0), st.ca_slots, N)
    node_key = st.node_class_key.at[rows, tgt].add(within, mode="drop")
    return sd_order, node_key


def hpa_pass(
    state: ClusterBatchState,
    auto: AutoscaleState,
    st: AutoscaleStatics,
    W: jnp.ndarray,
    consts: StepConstants,
    seg=None,
) -> Tuple[ClusterBatchState, AutoscaleState]:
    """One masked HPA cycle at window W for every due cluster
    (scalar equivalent: horizontal_pod_autoscaler.py run cycle +
    kube_horizontal_pod_autoscaler.py formula).

    seg: optional STATIC (lo, hi) device-slot bounds covering every pod-group
    slot (engine._hpa_seg). The pass only ever touches group slots, so the
    body — including its (C, P) victim sort — runs on the [lo, hi) slice,
    and the not-due `lax.cond` identity branch carries (C, hi-lo) slices
    instead of the full pod arrays (the cond materializes its carry through
    both branches; with the full state that copy cost more than the
    amortized body — the §3 "empty-cycle skip" lesson, docs/DESIGN.md)."""
    pods = state.pods
    C, P = pods.phase.shape
    lo, hi = (0, P) if seg is None else seg
    sliced = (lo, hi) != (0, P)
    sub = (
        jax.tree.map(lambda a: a[:, lo:hi], pods) if sliced else pods
    )
    T0 = TPair(win=W, off=jnp.zeros_like(auto.hpa_next.off))
    due_cycle = t_le(auto.hpa_next, T0).any()
    due_any = due_cycle
    if auto.col_next is not None:
        # The 60 s metrics collection (the latch) is part of the same
        # cond: a collection-only window updates the col_* leaves and
        # leaves everything else untouched (delta = 0 on every lane).
        due_any = due_any | t_le(auto.col_next, T0).any()

    zeros = jnp.zeros((C,), jnp.int32)
    if auto.col_next is None:
        body = lambda: _hpa_pass_body(
            sub, state.queue_seq_counter, auto, st, W, consts, lo
        )
    else:
        # Collection-only windows (scan_interval > 60: the 60 s tick fires
        # between HPA cycles) latch the sample WITHOUT paying the cycle
        # body — desired-replica math, the (C, P) victim sort and the
        # activation scatters all have delta 0 when no lane's cycle is
        # due, so the light branch is trajectory-exact by construction
        # (same sample expressions, same col_* writes).
        body = lambda: jax.lax.cond(
            due_cycle,
            lambda: _hpa_pass_body(
                sub, state.queue_seq_counter, auto, st, W, consts, lo
            ),
            lambda: (
                sub,
                _hpa_collect_only(sub, auto, st, W, consts, lo),
                zeros,
                zeros,
                zeros,
                zeros,
            ),
        )
    sub2, auto2, up_s, down_s, clamp_s, n_up = jax.lax.cond(
        due_any,
        body,
        lambda: (sub, auto, zeros, zeros, zeros, zeros),
    )
    if sliced:
        pods2 = jax.tree.map(
            lambda full, s: full.at[:, lo:hi].set(s), pods, sub2
        )
    else:
        pods2 = sub2
    metrics = state.metrics
    metrics = metrics._replace(
        scaled_up_pods=metrics.scaled_up_pods + up_s,
        scaled_down_pods=metrics.scaled_down_pods + down_s,
        hpa_reserve_clamped=metrics.hpa_reserve_clamped + clamp_s,
    )
    state = state._replace(
        pods=pods2,
        metrics=metrics,
        queue_seq_counter=state.queue_seq_counter + n_up,
    )
    return state, auto2


def _hpa_metrics_sample(pods, st: AutoscaleStatics, W, consts, lo):
    """The metrics collector's per-group sample at window W over the pod
    slice [lo, lo+P): (run_per_group (C,Gp) int32, util_cpu, util_ram
    (C,Gp) float32). ONE expression source for the cycle body and the
    collection-only latch branch, so the latched values can never depend
    on which branch took the sample."""
    C, P = pods.phase.shape
    Gp = st.pg_slot_start.shape[1]
    rows = jnp.arange(C, dtype=jnp.int32)[:, None]
    # Group membership and running counts (running = bound AND started by T,
    # mirroring node_component.running_pods at collection time).
    gid = st.pod_group_id[:, lo : lo + P]
    gid_c = jnp.where(gid >= 0, gid, Gp)
    started = t_le(
        pods.start_time,
        TPair(
            win=jnp.broadcast_to(W[:, None], (C, P)),
            off=jnp.zeros((C, P), jnp.float32),
        ),
    )
    running = (pods.phase == PHASE_RUNNING) & started
    run_per_group = (
        jnp.zeros((C, Gp + 1), jnp.int32)
        .at[rows, gid_c]
        .add(running.astype(jnp.int32))[:, :Gp]
    )
    runf = jnp.maximum(run_per_group, 1).astype(jnp.float32)

    # Elapsed time since group creation, float64 (curves cycle over arbitrary
    # periods; f32 elapsed at large absolute t would blur the curve position).
    T_s = W.astype(jnp.float64) * jnp.float64(consts.scheduling_interval)
    elapsed = T_s[:, None] - st.pg_creation_s
    cpu_load = _curve_load(st.pg_cpu_dur, st.pg_cpu_load, st.pg_cpu_total, elapsed)
    ram_load = _curve_load(st.pg_ram_dur, st.pg_ram_load, st.pg_ram_total, elapsed)
    util_cpu = jnp.where(
        st.pg_cpu_total > 0,
        jnp.where(st.pg_cpu_const, cpu_load, jnp.minimum(1.0, cpu_load / runf)),
        0.0,
    )
    util_ram = jnp.where(
        st.pg_ram_total > 0,
        jnp.where(st.pg_ram_const, ram_load, jnp.minimum(1.0, ram_load / runf)),
        0.0,
    )
    return run_per_group, util_cpu, util_ram


def _latch_collection(
    auto: AutoscaleState, st: AutoscaleStatics, W, interval,
    run_per_group, util_cpu, util_ram,
):
    """The collection-window latch writes — col_next advance + sample
    snapshot, gated on the collection being due — as (col_due, (col_next',
    col_run', col_util_cpu', col_util_ram')). ONE implementation consumed
    by both the cycle body and the collection-only branch, so the latched
    values cannot depend on which branch took the sample."""
    col_due = t_le(
        auto.col_next, TPair(win=W, off=jnp.zeros(W.shape, jnp.float32))
    )
    return col_due, (
        t_where(
            col_due,
            t_add(auto.col_next, st.col_interval, interval),
            auto.col_next,
        ),
        jnp.where(col_due[:, None], run_per_group, auto.col_run),
        jnp.where(col_due[:, None], util_cpu, auto.col_util_cpu),
        jnp.where(col_due[:, None], util_ram, auto.col_util_ram),
    )


def _hpa_collect_only(
    pods,
    auto: AutoscaleState,
    st: AutoscaleStatics,
    W: jnp.ndarray,
    consts: StepConstants,
    lo: int = 0,
) -> AutoscaleState:
    """The 60 s collection tick WITHOUT a due HPA cycle on any lane: latch
    the sample into the col_* leaves and advance col_next — exactly the
    col_state writes _hpa_pass_body would make (shared _hpa_metrics_sample
    + _latch_collection), skipping the cycle machinery (desired-replica
    math, the (C, P) victim sort, activation scatters) that is all
    delta-0 when no cycle is due."""
    interval = jnp.float32(consts.scheduling_interval)
    run_per_group, util_cpu, util_ram = _hpa_metrics_sample(
        pods, st, W, consts, lo
    )
    _, (col_next2, col_run2, col_ucpu2, col_uram2) = _latch_collection(
        auto, st, W, interval, run_per_group, util_cpu, util_ram
    )
    return auto._replace(
        col_next=col_next2,
        col_run=col_run2,
        col_util_cpu=col_ucpu2,
        col_util_ram=col_uram2,
    )


def _hpa_pass_body(
    pods,
    queue_seq_counter: jnp.ndarray,
    auto: AutoscaleState,
    st: AutoscaleStatics,
    W: jnp.ndarray,
    consts: StepConstants,
    lo: int = 0,
):
    """HPA cycle body over the pod-slot slice [lo, lo+P) of the device pod
    axis (P here = slice width; pod_group_id indexes align via lo). Returns
    (pods', auto', scaled_up (C,), scaled_down (C,), reserve_clamped (C,),
    n_activated (C,)) — the caller owns the metrics fold and writeback."""
    C, P = pods.phase.shape
    Gp = st.pg_slot_start.shape[1]
    interval = jnp.float32(consts.scheduling_interval)
    rows = jnp.arange(C, dtype=jnp.int32)[:, None]
    T = TPair(win=W, off=jnp.zeros((C,), jnp.float32))  # (C,)
    Tg = TPair(
        win=jnp.broadcast_to(W[:, None], (C, Gp)),
        off=jnp.zeros((C, Gp), jnp.float32),
    )

    due = t_le(auto.hpa_next, T)
    active = due[:, None] & t_le(st.pg_active_from, Tg)

    gid = st.pod_group_id[:, lo : lo + P]
    gid_c = jnp.where(gid >= 0, gid, Gp)
    run_per_group, util_cpu, util_ram = _hpa_metrics_sample(
        pods, st, W, consts, lo
    )
    present = run_per_group > 0  # group absent from metrics when nothing runs

    # HPA metrics-staleness fix (r14): the scalar HPA reads the metrics
    # collector's LAST 60 s collection sample, not a fresh evaluation at
    # its own tick (metrics/collector.py COLLECTION_INTERVAL; the
    # collection event precedes a same-instant HPA cycle, so a cycle at a
    # collection instant sees the fresh sample). With the latch armed
    # (col_* leaves present), a due collection snapshots (running count,
    # utilization) at this window, and the cycle consumes the latched
    # sample — the NEW one only when the collection time does not exceed
    # the cycle's fire time (both due in one window with the collection
    # later: the cycle still reads the previous sample, like the scalar).
    # At the default scan_interval 60 both cadences tick at the same
    # windows and the latched values equal the inline evaluation — the
    # pre-latch trajectories bit-exactly. Sub-window collection cadences
    # (interval > 60 s) degrade to one collection per window, mirroring
    # the documented CA cadence bound.
    col_state = None
    if auto.col_next is not None:
        col_due, col_state = _latch_collection(
            auto, st, W, interval, run_per_group, util_cpu, util_ram
        )
        # A cycle and a collection at the SAME instant order by the event
        # kernel's FIFO ids — i.e. by EMISSION time: the collection was
        # emitted 60 s before, the cycle scan_interval before, so the
        # collection fires first iff scan_interval <= 60 (at exactly 60
        # the tie breaks to the collection: its handler ran first at the
        # shared emission instant, all the way back to t = 0 where the
        # collector starts before the HPA).
        same_t = t_le(auto.col_next, auto.hpa_next) & t_le(
            auto.hpa_next, auto.col_next
        )
        col_first = t_le(st.hpa_interval, st.col_interval)
        use_new = col_due & (
            t_lt(auto.col_next, auto.hpa_next) | (same_t & col_first)
        )
        run_eff = jnp.where(use_new[:, None], run_per_group, auto.col_run)
        util_cpu = jnp.where(use_new[:, None], util_cpu, auto.col_util_cpu)
        util_ram = jnp.where(use_new[:, None], util_ram, auto.col_util_ram)
        present = run_eff > 0

    current = auto.hpa_tail - auto.hpa_head

    def desired_by(util, target):
        ratio = util / jnp.maximum(target, 1e-9)
        # (C,) per-lane tolerance against the (C, Gp) ratio.
        in_band = jnp.abs(ratio - 1.0) <= st.hpa_tolerance[:, None]
        # -1e-4 guards float32 products landing epsilon above an integer
        # (the scalar path computes the formula in f64).
        d = jnp.ceil(current.astype(jnp.float32) * ratio - 1e-4).astype(jnp.int32)
        return jnp.where(in_band, current, d)

    has_cpu = st.pg_target_cpu > 0
    has_ram = st.pg_target_ram > 0
    d_cpu = desired_by(util_cpu, st.pg_target_cpu)
    d_ram = desired_by(util_ram, st.pg_target_ram)
    desired = jnp.where(
        has_cpu & has_ram,
        jnp.maximum(d_cpu, d_ram),
        jnp.where(has_cpu, d_cpu, jnp.where(has_ram, d_ram, current)),
    )
    desired = jnp.minimum(desired, st.pg_max_pods)

    act = active & present
    delta = jnp.where(act, desired - current, 0)
    # head/tail are monotonic counters: tail = total replicas ever created
    # (the scalar's total_created naming counter), head = total removed, so
    # current = tail - head. Slots are REUSED: name-exact scale-down pops
    # scattered victims, so churn (repeated by the cyclic load curves) frees
    # arbitrary slots, and scale-up activates the first `up` reusable slots
    # of the reserve in slot-offset order; `up` is clamped to the reusable
    # count so the reserve can never be exceeded.
    count_g = jnp.maximum(st.pg_slot_count, 1)
    up0 = jnp.minimum(jnp.maximum(delta, 0), count_g - current)
    down = jnp.minimum(jnp.maximum(-delta, 0), current)

    # Group slot starts in SLICE coords ((C, P); garbage where gid<0).
    slot_start_p = st.pg_slot_start[rows, gid_c] - jnp.int32(lo)
    in_group = gid >= 0
    tail_p = auto.hpa_tail[rows, gid_c]

    # Scale-up activates the FIRST `up` reusable slots of the group's
    # reserve in slot-offset order (name-exact scale-down pops scattered
    # victims, so free slots are not ring-contiguous); the new occupant's
    # replica index idx = tail + rank is STORED in pods.hpa_idx — names are
    # "{group}_{idx}" exactly like the scalar's total_created naming.
    reusable = (
        (pods.phase == PHASE_EMPTY)
        | (pods.phase == PHASE_SUCCEEDED)
        | (pods.phase == PHASE_REMOVED)
        | (pods.phase == PHASE_FAILED)
    )
    reuse_in_g = in_group & reusable
    n_reusable = (
        jnp.zeros((C, Gp + 1), jnp.int32)
        .at[rows, gid_c]
        .add(reuse_in_g.astype(jnp.int32))[:, :Gp]
    )
    up = jnp.minimum(up0, n_reusable)
    up_p = up[rows, gid_c]
    down_p = down[rows, gid_c]

    # Rank among the group's reusable slots, slot-offset order (exclusive
    # running count minus its value at the group's first slot).
    cs_excl = (
        jnp.cumsum(reuse_in_g, axis=1, dtype=jnp.int32)
        - reuse_in_g.astype(jnp.int32)
    )
    start_cs = cs_excl[rows, jnp.clip(slot_start_p, 0, P - 1)]
    reuse_rank = cs_excl - start_cs
    activate = reuse_in_g & (reuse_rank < up_p)
    # Global activation rank for unique queue sequence numbers.
    rank = jnp.cumsum(activate, axis=1, dtype=jnp.int32) - 1
    n_up = activate.sum(axis=1, dtype=jnp.int32)
    enq = t_add(T, st.d_hpa_up, interval)  # (C,) pair
    enq_p = _broadcast_pair(enq, (C, P))
    phase = jnp.where(activate, PHASE_QUEUED, pods.phase)
    queue_ts = t_where(activate, enq_p, pods.queue_ts)
    queue_seq = jnp.where(
        activate, queue_seq_counter[:, None] + rank, pods.queue_seq
    )
    initial_attempt_ts = t_where(activate, enq_p, pods.initial_attempt_ts)
    attempts = jnp.where(activate, 1, pods.attempts)
    hpa_idx = jnp.where(activate, tail_p + reuse_rank, pods.hpa_idx)
    # Reset state left over from a previous occupant of a reused slot.
    node = jnp.where(activate, -1, pods.node)
    start_time = t_where(activate, t_zeros((C, P)), pods.start_time)
    finish_time = t_where(activate, t_inf((C, P)), pods.finish_time)

    # --- scale down: remove the lexicographically-smallest replica names --
    # The scalar pops the string-smallest name from the group's live set
    # (kube_horizontal_pod_autoscaler.rs:197-205, a BTreeSet of
    # "{group}_{idx}" names) — NOT FIFO: "g_10" < "g_2". The occupant index
    # lives in pods.hpa_idx (stored at activation); its decimal-string
    # order is a numeric key (left-aligned value, then digit count), and
    # the `down` smallest keys among live group members are the victims.
    # hpa_head stays the total-removed counter, so current = tail - head.
    live = (
        in_group
        & (
            (pods.phase == PHASE_QUEUED)
            | (pods.phase == PHASE_UNSCHEDULABLE)
            | (pods.phase == PHASE_RUNNING)
        )
        & is_inf(pods.removal_time)
        & ~activate
    )
    # Decimal-string order of "{group}_{idx}" names (shared primitive;
    # loud i32 bound at idx >= 10^8 via engine.check_autoscaler_bounds).
    name_key = decimal_string_key(pods.hpa_idx)
    big = jnp.int32(1 << 30)
    sort_gid = jnp.where(live, gid_c, Gp)
    sort_key = jnp.where(live, name_key, big)
    iota_p2 = jnp.broadcast_to(jnp.arange(P, dtype=jnp.int32)[None, :], (C, P))
    s_gid, _, s_slot = jax.lax.sort(
        (sort_gid, sort_key, iota_p2), dimension=1, num_keys=2, is_stable=True
    )
    # Rank within group = sorted position - group's first sorted position.
    gseg_start = (
        jnp.full((C, Gp + 1), P, jnp.int32)
        .at[rows, s_gid]
        .min(iota_p2, mode="drop")
    )
    rank_sorted = iota_p2 - gseg_start[rows, s_gid]
    vrank = (
        jnp.zeros((C, P), jnp.int32)
        .at[rows, s_slot]
        .set(rank_sorted)
    )
    deactivate = live & (vrank < down_p)
    removal_time = t_where(activate, t_inf((C, P)), pods.removal_time)
    rem = t_add(T, st.d_hpa_down, interval)  # (C,) pair
    rem_p = _broadcast_pair(rem, (C, P))
    removal_time = t_where(
        deactivate, t_min(removal_time, rem_p), removal_time
    )

    auto = auto._replace(
        hpa_head=auto.hpa_head + down,
        hpa_tail=auto.hpa_tail + up,
        hpa_next=t_where(
            due, t_add(auto.hpa_next, st.hpa_interval, interval), auto.hpa_next
        ),
    )
    if col_state is not None:
        col_next2, col_run2, col_ucpu2, col_uram2 = col_state
        auto = auto._replace(
            col_next=col_next2,
            col_run=col_run2,
            col_util_cpu=col_ucpu2,
            col_util_ram=col_uram2,
        )
    pods = pods._replace(
        phase=phase,
        queue_ts=queue_ts,
        queue_seq=queue_seq,
        initial_attempt_ts=initial_attempt_ts,
        attempts=attempts,
        removal_time=removal_time,
        node=node,
        start_time=start_time,
        finish_time=finish_time,
        hpa_idx=hpa_idx,
    )
    return (
        pods,
        auto,
        up.sum(axis=1, dtype=jnp.int32),
        down.sum(axis=1, dtype=jnp.int32),
        # Replicas the formula wanted (delta, already clamped to the exact
        # scalar max_pod_count bound) but the reserve could not seat —
        # either up0's slot_count-current clamp or the no-reusable-slot
        # clamp. The scalar would have created them: nonzero = divergence,
        # surfaced loudly by engine.check_autoscaler_bounds().
        (jnp.maximum(delta, 0) - up).sum(axis=1, dtype=jnp.int32),
        n_up,
    )


def _ca_scale_up(
    state: ClusterBatchState,
    auto: AutoscaleState,
    st: AutoscaleStatics,
    branch: jnp.ndarray,
    K_up: int,
    phase_v: jnp.ndarray,
    attempts_v: jnp.ndarray,
    use_pallas: bool = False,
    pallas_interpret: bool = False,
    pallas_mesh=None,
    pallas_axis: str = "clusters",
):
    """Bin-packing scale-up over the unscheduled-pod cache
    (reference: kube_cluster_autoscaler.rs:190-240). Returns
    (planned (C,S) bool, planned_per_group (C,Gn), reserve_starved (C,) —
    open attempts blocked ONLY by the consumed slot reserve, the
    silent-divergence case engine.check_autoscaler_bounds raises on).
    phase_v/attempts_v are the storage-visible views supplied by ca_pass."""
    pods = state.pods
    C, P = pods.phase.shape
    S = st.ca_slots.shape[1]
    Gn = st.ng_ca_start.shape[1]
    rows1 = jnp.arange(C, dtype=jnp.int32)
    rows = rows1[:, None]

    # The storage unscheduled-pods cache: parked pods plus woken-but-unscheduled
    # pods (attempts>=2 after a wake, reference: persistent_storage.rs cache
    # removal only on assignment).
    in_cache = (phase_v == PHASE_UNSCHEDULABLE) | (
        (phase_v == PHASE_QUEUED) & (attempts_v >= 2)
    )

    from kubernetriks_tpu.ops.autoscale_kernel import (
        ca_up_kernel_fits,
        fused_ca_scale_up,
    )

    # NOTE (r5, measured dead end): moving the candidate ordering in-kernel
    # (an iterated 4-key argmin over (P, 128) VMEM pod tiles, mirroring the
    # scheduler's selection kernel) REGRESSED the composed bench 182k ->
    # 176k decisions/s: with a deep cache the loop runs all K_up=64 serial
    # sweeps of 7 (P, 128) tiles (~9.6 ms/window in the xplane profile)
    # while the XLA 4-key sort below costs ~0.06 ms — the scheduler kernel's
    # early-exit win does not transfer because CA backlogs keep k_bound
    # pegged at K_up. See docs/DESIGN.md §3.

    # The storage snapshot is NAME-sorted (scale_up_info, reference
    # persistent_storage.rs:137-146) and bin-packing consumes it in that
    # order. pod_name_rank carries the static lexicographic ranks (BIG for
    # slots whose names are runtime-assigned or shifted — those fall back
    # to queue order after every ranked pod, count-exact).
    name_key = jnp.where(in_cache, st.pod_name_rank, _BIG_I32)
    tie_win = jnp.where(in_cache, pods.queue_ts.win, _BIG_I32)
    tie_off = jnp.where(in_cache, pods.queue_ts.off, jnp.float32(jnp.inf))
    tie_seq = jnp.where(in_cache, pods.queue_seq, _BIG_I32)
    iota_p = jnp.broadcast_to(jnp.arange(P, dtype=jnp.int32)[None, :], (C, P))
    _, _, _, _, order_full = jax.lax.sort(
        (name_key, tie_win, tie_off, tie_seq, iota_p), dimension=1,
        num_keys=4, is_stable=True,
    )
    order = order_full[:, :K_up]
    cvalid = in_cache[rows, order] & branch[:, None]
    creq_cpu = pods.req_cpu[rows, order]
    creq_ram = pods.req_ram[rows, order]

    if use_pallas and ca_up_kernel_fits(S, Gn, K_up):
        core = partial(
            fused_ca_scale_up, n_slots=S, interpret=pallas_interpret
        )
        if pallas_mesh is not None:
            from kubernetriks_tpu.batched.step import _shard_rowwise

            core = _shard_rowwise(core, 11, 3, pallas_mesh, pallas_axis)
        planned_k, g_planned_k, starved_k = core(
            st.ca_max_nodes[:, None],
            auto.ca_count,
            auto.ca_cursor,
            st.ng_max_count,
            st.ng_slot_count,
            st.ng_tmpl_cpu,
            st.ng_tmpl_ram,
            st.ng_ca_start,
            cvalid,
            creq_cpu,
            creq_ram,
        )
        return planned_k, g_planned_k, starved_k[:, 0]

    planned0 = jnp.zeros((C, S), bool)
    plan_seq0 = jnp.full((C, S), _BIG_I32, jnp.int32)
    palloc_cpu0 = jnp.zeros((C, S), jnp.int32)
    palloc_ram0 = jnp.zeros((C, S), jnp.int32)
    g_planned0 = jnp.zeros((C, Gn), jnp.int32)
    total0 = auto.ca_count.sum(axis=1)  # CA counts only (reference quirk:
    # max_node_count bounds CA-owned nodes, kube_cluster_autoscaler.rs:62-80)
    counter0 = jnp.zeros((C,), jnp.int32)
    starved0 = jnp.zeros((C,), jnp.int32)

    def body(carry, xs):
        (
            planned, plan_seq, palloc_cpu, palloc_ram, g_planned, total,
            counter, starved,
        ) = carry
        valid, rcpu, rram = xs

        # First-fit into already-planned nodes, in plan order; fitting pods
        # deduct from the virtual allocatable (reference :81-87). The
        # feasibility mask is the Fit device plugin — CA placement is
        # first-fit BY REFERENCE SEMANTICS regardless of the scheduler
        # profile, but the fit predicate itself is the one registry
        # definition (batched/pipeline.py).
        fit = planned & _fit_filter(
            palloc_cpu, palloc_ram, rcpu[:, None], rram[:, None]
        )
        any_fit = fit.any(axis=1)
        first = jax.lax.argmin(jnp.where(fit, plan_seq, _BIG_I32), 1, jnp.int32)
        use = valid & any_fit
        palloc_cpu = palloc_cpu.at[rows1, jnp.where(use, first, S)].add(
            -rcpu, mode="drop"
        )
        palloc_ram = palloc_ram.at[rows1, jnp.where(use, first, S)].add(
            -rram, mode="drop"
        )

        # Else open a node from the first fitting group (name-sorted at build).
        can_open = valid & ~any_fit & (total < st.ca_max_nodes)
        gcount = auto.ca_count + g_planned
        # Base eligibility (quota headroom + template fit); g_ok adds the
        # slot-reserve cursor bound. Deriving g_ok from the base keeps the
        # starvation counter's "blocked ONLY by the reserve" invariant in
        # lockstep with the actual open decision.
        g_ok_nc = (
            ((st.ng_max_count < 0) | (gcount < st.ng_max_count))
            & (rcpu[:, None] <= st.ng_tmpl_cpu)
            & (rram[:, None] <= st.ng_tmpl_ram)
        )
        g_ok = g_ok_nc & (auto.ca_cursor + g_planned < st.ng_slot_count)
        g_found = g_ok.any(axis=1)
        g = jax.lax.argmax(g_ok, 1, jnp.int32)
        open_ = can_open & g_found
        # Reserve starvation: a group would accept this pod (quota headroom
        # + template fit, with a real reserve) but its never-reclaimed slot
        # reserve is consumed (autoscale.py "Remaining bounded deviations")
        # — counted so the engine raises loudly instead of silently
        # diverging.
        starved = starved + (
            can_open
            & ~g_found
            & (g_ok_nc & (st.ng_slot_count > 0)).any(axis=1)
        ).astype(jnp.int32)
        s_new = (
            st.ng_ca_start[rows1, g]
            + auto.ca_cursor[rows1, g]
            + g_planned[rows1, g]
        )
        s_tgt = jnp.where(open_, s_new, S)
        planned = planned.at[rows1, s_tgt].set(True, mode="drop")
        plan_seq = plan_seq.at[rows1, s_tgt].set(counter, mode="drop")
        # The new node joins at FULL template allocatable: the triggering pod
        # is NOT packed into it (reference quirk, kube_cluster_autoscaler.rs:210-218).
        palloc_cpu = palloc_cpu.at[rows1, s_tgt].set(
            st.ng_tmpl_cpu[rows1, g], mode="drop"
        )
        palloc_ram = palloc_ram.at[rows1, s_tgt].set(
            st.ng_tmpl_ram[rows1, g], mode="drop"
        )
        g_planned = g_planned.at[rows1, jnp.where(open_, g, Gn)].add(1, mode="drop")
        total = total + open_.astype(jnp.int32)
        counter = counter + open_.astype(jnp.int32)
        return (
            planned, plan_seq, palloc_cpu, palloc_ram, g_planned, total,
            counter, starved,
        ), None

    carry0 = (
        planned0, plan_seq0, palloc_cpu0, palloc_ram0, g_planned0, total0,
        counter0, starved0,
    )
    # Early exit at the deepest lane's cache count: the bin-pack is
    # sequential over K_up candidate positions, but typical caches hold a
    # handful of pods — iterating all K_up steps cost ~K_up sequential
    # (C, S) passes per due window.
    k_bound = jnp.minimum(
        jnp.max(cvalid.sum(axis=1, dtype=jnp.int32)), jnp.int32(K_up)
    )

    def loop_body(lcarry):
        k, carry = lcarry
        xs_k = (cvalid[:, k], creq_cpu[:, k], creq_ram[:, k])
        carry, _ = body(carry, xs_k)
        return (k + jnp.int32(1), carry)

    _, (planned, _, _, _, g_planned, _, _, starved) = jax.lax.while_loop(
        lambda lc: lc[0] < k_bound, loop_body, (jnp.int32(0), carry0)
    )
    return planned, g_planned, starved


def _ca_scale_down(
    state: ClusterBatchState,
    auto: AutoscaleState,
    st: AutoscaleStatics,
    branch: jnp.ndarray,
    K_sd: int,
    phase_v: jnp.ndarray,
    alloc_cpu_v: jnp.ndarray,
    alloc_ram_v: jnp.ndarray,
    snap: TPair,
    interval,
    use_pallas: bool = False,
    pallas_interpret: bool = False,
    pallas_mesh=None,
    pallas_axis: str = "clusters",
    descatter: bool = True,
    sd_order=None,
    node_rank=None,
):
    """Threshold + simulated-re-placement scale-down
    (reference: kube_cluster_autoscaler.rs:242-290). Returns
    (removed (C,S) bool, removed_per_group (C,Gn)).

    phase_v/alloc_*_v are the storage-visible views from ca_pass; on top of
    them the finish-visibility correction reconstructs what the storage
    knows at the snapshot time `snap`: a running pod whose finish became
    visible by snap counts as gone (its resources freed), and a
    just-succeeded pod whose finish is NOT yet visible still counts as
    running (its resources held, and it still needs re-placement).

    descatter (KTPU_CA_DESCATTER, r9 — round 3 of the de-scatter
    campaign): the correction segment-sum and the node-grouping sort above
    were the down-cond's two remaining expensive blocks after r5 (each a
    (C, P) sort + a pair of (C, P, N) rank-count reductions — DESIGN.md
    names them as the ~2.5 ms residue). They share a node key, so ONE
    combined 2-key sort (node, on_any-last... see below) and ONE pair of
    boundary reductions now serve both: the secondary key puts each node's
    storage-RUNNING pods first in its segment (so the grouping tables
    slice the same prefix the old single-key sort produced), the
    correction deltas ride the same sort as values (untouched rows carry
    0, so the full-segment integer sums equal the old touched-only sums
    exactly), and the per-node running count folds from a sorted
    indicator cumsum. Bit-exact by integer-additivity + stable-sort
    prefix order; descatter=False keeps the r5 two-sort path for A/B."""
    pods, nodes = state.pods, state.nodes
    C, P = pods.phase.shape
    N = nodes.alive.shape[1]
    S = st.ca_slots.shape[1]
    Gn = st.ng_ca_start.shape[1]
    rows1 = jnp.arange(C, dtype=jnp.int32)
    rows = rows1[:, None]
    col_n = jnp.arange(N, dtype=jnp.int32)[None, :]
    # Name orderings: the static build tables, or — under slot reclaim —
    # the dynamic orders derived from the occupants' allocation indices
    # (ca_name_order; bit-identical orders while no slot was ever reused).
    if sd_order is None:
        sd_order = st.ca_sd_order
    if node_rank is None:
        node_rank = st.node_name_rank

    snap_p = _broadcast_pair(snap, (C, P))
    # (C,) per-lane finish-visibility delay as a (C, 1) column against the
    # (C, P) pod pairs.
    finish_vis = TPair(
        win=st.ca_finish_vis.win[:, None], off=st.ca_finish_vis.off[:, None]
    )
    # Running pod whose finish notification reached storage by snap: gone.
    vis_gone = (phase_v == PHASE_RUNNING) & t_le(
        t_add(pods.finish_time, finish_vis, interval), snap_p
    )
    # Succeeded pod the storage hasn't seen finish yet: still running there.
    # (finish = start + duration; service pods never reach SUCCEEDED.)
    succ_finish = t_add(
        t_add(pods.start_time, pods.duration, interval),
        finish_vis,
        interval,
    )
    vis_back = (phase_v == PHASE_SUCCEEDED) & ~t_le(succ_finish, snap_p)
    # HPA removals whose storage effect landed by snap: gone (removal_time
    # is already a storage-effect time, d_hpa_down).
    vis_removed = (phase_v == PHASE_RUNNING) & t_le(pods.removal_time, snap_p)
    vis_gone = vis_gone | vis_removed

    # Virtual allocatables as the storage sees them. The per-node
    # correction sums are SEGMENT SUMS over a node-sorted copy of the
    # deltas (sort + cumsum + boundary gathers) instead of a (C, P)
    # scatter-add: XLA's TPU scatter lowering costs per-index
    # (xplane-measured ~1.1 ms/window at the composed shape; this
    # formulation is ~0.3). Integer adds, so any summation order is exact.
    node_c = jnp.clip(pods.node, 0, N - 1)
    d_cpu = jnp.where(vis_gone, pods.req_cpu, 0) - jnp.where(
        vis_back, pods.req_cpu, 0
    )
    d_ram = jnp.where(vis_gone, pods.req_ram, 0) - jnp.where(
        vis_back, pods.req_ram, 0
    )
    touched = vis_gone | vis_back
    on_any = ((phase_v == PHASE_RUNNING) & ~vis_gone) | vis_back
    zero_col = jnp.zeros((C, 1), jnp.int32)
    if descatter:
        # Combined de-scatter (see docstring): one 2-key sort — node slot,
        # then storage-running FIRST — serves the correction AND the
        # grouping. on_any pods have node >= 0 and touched pods are
        # RUNNING-phase, so node_c == the old sorts' key values.
        in_seg = touched | on_any
        key_node = jnp.where(in_seg, node_c, jnp.int32(N))
        key2 = jnp.where(on_any, 0, 1).astype(jnp.int32)
        key_s, _, dc_s, dr_s, ind_s, rc_sorted, rr_sorted = jax.lax.sort(
            (
                key_node,
                key2,
                d_cpu,
                d_ram,
                on_any.astype(jnp.int32),
                pods.req_cpu,
                pods.req_ram,
            ),
            dimension=1,
            num_keys=2,
            is_stable=True,
        )
        # ONE pair of (C, P, N) rank-count boundary reductions shared by
        # the correction and the grouping (was two pairs).
        tstart = (key_s[:, :, None] < col_n[:, None, :]).sum(
            axis=1, dtype=jnp.int32
        )
        tend = tstart + (key_s[:, :, None] == col_n[:, None, :]).sum(
            axis=1, dtype=jnp.int32
        )
        ecs_c = jnp.concatenate([zero_col, jnp.cumsum(dc_s, axis=1)], axis=1)
        ecs_r = jnp.concatenate([zero_col, jnp.cumsum(dr_s, axis=1)], axis=1)
        ecs_n = jnp.concatenate([zero_col, jnp.cumsum(ind_s, axis=1)], axis=1)
        alloc_cpu_v = alloc_cpu_v + ecs_c[rows, tend] - ecs_c[rows, tstart]
        alloc_ram_v = alloc_ram_v + ecs_r[rows, tend] - ecs_r[rows, tstart]
        # Node n's segment LEADS with its on_any pods in slot order (stable
        # sort, key2), so the grouping tables slice the same prefix the old
        # single-key sort produced; the running count folds from the
        # indicator cumsum over the same boundaries.
        seg_start = tstart
        seg_count = ecs_n[rows, tend] - ecs_n[rows, tstart]
    else:
        # r5 two-sort path, kept for A/B (KTPU_CA_DESCATTER=0).
        tkey = jnp.where(touched, node_c, jnp.int32(N))
        tkey_s, dc_s, dr_s = jax.lax.sort(
            (tkey, d_cpu, d_ram), dimension=1, num_keys=1, is_stable=True
        )
        ecs_c = jnp.concatenate([zero_col, jnp.cumsum(dc_s, axis=1)], axis=1)
        ecs_r = jnp.concatenate([zero_col, jnp.cumsum(dr_s, axis=1)], axis=1)
        tstart = (tkey_s[:, :, None] < col_n[:, None, :]).sum(
            axis=1, dtype=jnp.int32
        )
        tend = tstart + (tkey_s[:, :, None] == col_n[:, None, :]).sum(
            axis=1, dtype=jnp.int32
        )
        alloc_cpu_v = alloc_cpu_v + ecs_c[rows, tend] - ecs_c[rows, tstart]
        alloc_ram_v = alloc_ram_v + ecs_r[rows, tend] - ecs_r[rows, tstart]

        # Group storage-visible running pods by assigned node ONCE (a
        # per-slot (C, P) mask + argsort made the pass O(S * P log P) per
        # window — fatal at trace scale); each node's pods become a
        # contiguous segment of `porder`. The pod requests ride the sort
        # as VALUES, so the per-candidate tables below slice sorted arrays
        # instead of gathering through pod_order (one fewer (C, S*K_sd)
        # gather). Segment starts and counts come from rank-count
        # reductions over the sorted keys — a fused (C, P, N) compare+sum
        # — instead of the serial per-index scatter-min/scatter-add pair
        # (~2.3 ms/window at the composed shape).
        key_node = jnp.where(on_any, pods.node, jnp.int32(N))
        key_sorted, rc_sorted, rr_sorted = jax.lax.sort(
            (key_node, pods.req_cpu, pods.req_ram),
            dimension=1,
            num_keys=1,
            is_stable=True,
        )
        # seg_start[n] = #pods on nodes < n = first sorted position of node
        # n's segment (for a pod-less node this lands on the next segment
        # instead of the old scatter-min's P sentinel — all consumers mask
        # by seg_count == 0 first, so the value is never read).
        seg_start = (key_sorted[:, :, None] < col_n[:, None, :]).sum(
            axis=1, dtype=jnp.int32
        )
        seg_count = (key_sorted[:, :, None] == col_n[:, None, :]).sum(
            axis=1, dtype=jnp.int32
        )
    col_k = jnp.arange(K_sd, dtype=jnp.int32)[None, :]

    # Candidate walk order and liveness, shared by both paths: CA slots in
    # node-name order, alive where allocated (the kernel derives its walk
    # bound from cand_alive; the XLA path bounds its while_loop the same way).
    slot_perm = jnp.take_along_axis(st.ca_slots, sd_order, axis=1)
    slotc_perm = jnp.clip(slot_perm, 0, N - 1)
    cand_alive = (slot_perm >= 0) & nodes.alive[rows, slotc_perm]

    from kubernetriks_tpu.ops.autoscale_kernel import (
        ca_down_kernel_fits,
        fused_ca_scale_down,
    )

    if use_pallas and ca_down_kernel_fits(N, S, K_sd):
        # Per-candidate pod tables in name order, via ONE stacked gather
        # from the sort-carried request values (gather cost is per-index on
        # TPU; the old porder->req double indirection paid three (C, S*K)
        # gathers — xplane-measured ~4 ms/window at the composed shape).
        cnt_perm = jnp.where(
            slot_perm >= 0, seg_count[rows, slotc_perm], 0
        )
        seg_pos = jnp.clip(seg_start[rows, slotc_perm], 0, P - 1)  # (C, S)
        take = jnp.clip(
            seg_pos[:, :, None] + jnp.arange(K_sd, dtype=jnp.int32)[None, None, :],
            0,
            P - 1,
        ).reshape(C, S * K_sd)
        pr = jnp.take_along_axis(
            jnp.stack([rc_sorted, rr_sorted], axis=-1),
            take[:, :, None],
            axis=1,
        )
        pr_cpu = pr[..., 0]
        pr_ram = pr[..., 1]
        pv0 = (
            jnp.arange(K_sd, dtype=jnp.int32)[None, None, :]
            < cnt_perm[:, :, None]
        ).reshape(C, S * K_sd)
        not_pending = is_inf(nodes.remove_time)
        thresh = jnp.broadcast_to(
            st.ca_threshold.astype(jnp.float32), (C,)
        )[:, None]

        core = partial(fused_ca_scale_down, k_sd=K_sd, interpret=pallas_interpret)
        if pallas_mesh is not None:
            from kubernetriks_tpu.batched.step import _shard_rowwise

            core = _shard_rowwise(core, 15, 1, pallas_mesh, pallas_axis)
        removed_perm = core(
            branch[:, None],
            thresh,
            nodes.alive,
            not_pending,
            nodes.cap_cpu,
            nodes.cap_ram,
            alloc_cpu_v,
            alloc_ram_v,
            node_rank,
            slot_perm,
            cand_alive,
            cnt_perm,
            pr_cpu,
            pr_ram,
            pv0,
        )
        # Back from name-order positions to CA-slot indices (ca_sd_order is
        # a permutation, so .set() touches each slot exactly once).
        removed = (
            jnp.zeros((C, S), bool).at[rows, sd_order].set(removed_perm)
        )
        return _per_group(removed, st, rows, Gn)

    def outer(carry, s):
        valloc_cpu, valloc_ram = carry
        # The scalar walks candidates in NODE-NAME order (info.nodes is
        # name-sorted) and earlier candidates' committed re-placements are
        # visible to later ones — iterate CA slots through the name-order
        # permutation, (C,) per cluster.
        sidx = jax.lax.dynamic_index_in_dim(sd_order, s, 1, keepdims=False)
        # (C,) global node slot of this candidate.
        slot = st.ca_slots[rows1, sidx]
        slot_ok = (slot >= 0) & branch
        slotc = jnp.clip(slot, 0, N - 1)
        alive_here = nodes.alive[rows1, slotc] & slot_ok

        cap_cpu = jnp.maximum(nodes.cap_cpu[rows1, slotc], 1).astype(jnp.float32)
        cap_ram = jnp.maximum(nodes.cap_ram[rows1, slotc], 1).astype(jnp.float32)
        used_cpu = (nodes.cap_cpu[rows1, slotc] - valloc_cpu[rows1, slotc]).astype(
            jnp.float32
        )
        used_ram = (nodes.cap_ram[rows1, slotc] - valloc_ram[rows1, slotc]).astype(
            jnp.float32
        )
        util = jnp.maximum(used_cpu / cap_cpu, used_ram / cap_ram)
        # A node already pending removal (effect time beyond this window) must
        # not be re-selected: it would double-decrement ca_count.
        not_pending = is_inf(
            TPair(
                win=nodes.remove_time.win[rows1, slotc],
                off=nodes.remove_time.off[rows1, slotc],
            )
        )
        # f32 compare on both sides: the Mosaic kernel path has no f64, so
        # the XLA path casts the threshold down too — bit-identical paths.
        eligible = alive_here & not_pending & (
            util < st.ca_threshold.astype(jnp.float32)
        )

        # Pods assigned to this node (storage assignments include in-flight
        # bindings, matching PHASE_RUNNING): the K_sd-slice of this node's
        # segment in pod-slot order.
        cnt = seg_count[rows1, slotc] * slot_ok.astype(jnp.int32)
        attempt = eligible & (cnt <= K_sd)  # overflow: conservatively skip

        seg_pos = jnp.clip(seg_start[rows1, slotc], 0, P - 1)
        take = jnp.clip(seg_pos[:, None] + col_k, 0, P - 1)
        pvalid = (col_k < cnt[:, None]) & attempt[:, None]
        prcpu = rc_sorted[rows, take]
        prram = rr_sorted[rows, take]

        save_cpu, save_ram = valloc_cpu, valloc_ram

        def inner(icarry, ixs):
            vcpu, vram, ok = icarry
            pv, rcpu, rram = ixs
            fit = (
                nodes.alive
                & (col_n != slot[:, None])
                & _fit_filter(vcpu, vram, rcpu[:, None], rram[:, None])
            )
            any_fit = fit.any(axis=1)
            # First-fit in NODE-NAME order (the scalar iterates the
            # name-sorted info.nodes list; _node_fits_pod first match).
            tgt = jax.lax.argmin(
                jnp.where(fit, node_rank, _BIG_I32), 1, jnp.int32
            )
            place = pv & any_fit
            vcpu = vcpu.at[rows1, jnp.where(place, tgt, N)].add(-rcpu, mode="drop")
            vram = vram.at[rows1, jnp.where(place, tgt, N)].add(-rram, mode="drop")
            ok = ok & (~pv | any_fit)
            return (vcpu, vram, ok), None

        (vcpu, vram, all_ok), _ = jax.lax.scan(
            inner,
            (valloc_cpu, valloc_ram, jnp.ones((C,), bool)),
            (pvalid.T, prcpu.T, prram.T),
        )
        success = attempt & all_ok
        # Commit the re-placement on success, roll back otherwise
        # (reference :141-156); commits persist across later candidates.
        valloc_cpu = jnp.where(success[:, None], vcpu, save_cpu)
        valloc_ram = jnp.where(success[:, None], vram, save_ram)
        return valloc_cpu, valloc_ram, success

    def loop_body(carry):
        s, valloc_cpu, valloc_ram, removed = carry
        valloc_cpu, valloc_ram, success = outer((valloc_cpu, valloc_ram), s)
        sidx = jax.lax.dynamic_index_in_dim(sd_order, s, 1, keepdims=False)
        removed = removed.at[rows1, sidx].max(success)
        return (s + jnp.int32(1), valloc_cpu, valloc_ram, removed)

    # Name-order iteration: allocated slots are not a prefix of the name
    # permutation, so bound the walk by the LAST alive candidate's position
    # in permuted order (zero iterations before the first scale-up; dead /
    # unallocated slots inside the bound no-op through the alive_here gate).
    iota_s = jnp.arange(S, dtype=jnp.int32)[None, :]
    s_bound = jnp.max(jnp.where(cand_alive, iota_s + 1, 0)).astype(jnp.int32)
    _, _, _, removed = jax.lax.while_loop(
        lambda carry: carry[0] < s_bound,
        loop_body,
        (
            jnp.int32(0),
            alloc_cpu_v,
            alloc_ram_v,
            jnp.zeros((C, S), bool),
        ),
    )
    return _per_group(removed, st, rows, Gn)


def _per_group(removed, st, rows, Gn):
    """(removed (C, S) bool, per-group removal counts (C, Gn)) — the
    shared aggregation tail of both scale-down paths."""
    group_c = jnp.where(removed, st.ca_slot_group, Gn)
    removed_per_group = (
        jnp.zeros(group_c.shape[:1] + (Gn + 1,), jnp.int32)
        .at[rows, group_c]
        .add(removed.astype(jnp.int32))[:, :Gn]
    )
    return removed, removed_per_group


def ca_pass(
    state: ClusterBatchState,
    auto: AutoscaleState,
    st: AutoscaleStatics,
    W: jnp.ndarray,
    consts: StepConstants,
    K_up: int,
    K_sd: int,
    pre=None,
    use_pallas: bool = False,
    pallas_interpret: bool = False,
    pallas_mesh=None,
    pallas_axis: str = "clusters",
    nodes_lane_major: bool = False,
    descatter: bool = True,
    reclaim: bool = False,
) -> Tuple[ClusterBatchState, AutoscaleState]:
    """One masked cluster-autoscaler cycle (scalar equivalent:
    cluster_autoscaler.py cycle; AUTO info policy: scale up iff the
    unscheduled cache is non-empty, reference: persistent_storage.rs:381-412).

    nodes_lane_major (KTPU_LANE_MAJOR): the hot node leaves arrive (N, C);
    the CA glue is (C, N)-oriented (name-order gathers, grouping sorts), so
    it normalizes to row-major VIEWS here — a handful of transposes per
    window against the ~20 kernel-boundary transposes the mode removes in
    the base window (docs/DESIGN.md §"window-cost anatomy"). The pass only
    WRITES the pending pairs (create_time / remove_time — row-major
    always), so nothing converts back. descatter (KTPU_CA_DESCATTER):
    see _ca_scale_down.

    Exact cadence + snapshot semantics (r4): `auto.ca_next` is the TRUE
    cycle-fire time c_k (the scalar re-arms scan_interval after the info
    round-trip returns, so the period drifts relative to windows); the
    storage snapshot the decision reads lands at s_k = c_k + ca_snap. Cycle
    k runs in the window W with W*iv <= s_k < (W+1)*iv, whose post-cycle
    state matches the snapshot up to two sub-window corrections:

    - pre-cycle shadows: if s_k precedes this window's commit-visibility
      time T + ca_commit_vis, the storage has not yet seen THIS cycle's
      assignments/parks — `pre` = (phase, attempts, alloc_cpu, alloc_ram)
      captured before the cycle supplies the storage's view.
    - finish visibility (handled inside _ca_scale_down): the storage learns
      a pod finish at F + ca_finish_vis, which can be on either side of s_k
      relative to the window boundary the arrays reflect.
    """
    pods, nodes, metrics = state.pods, state.nodes, state.metrics
    # ONE owner of the hot-leaf transpose set (state.swap_node_layout);
    # the pass reads through the row-major view and writes the pending
    # pairs back through the ORIGINAL `nodes`, so the hot leaves keep
    # their incoming layout.
    state_row = swap_node_layout(state) if nodes_lane_major else state
    nodes_row = state_row.nodes
    C = pods.phase.shape[0]
    interval = jnp.float32(consts.scheduling_interval)
    T = TPair(win=W, off=jnp.zeros((C,), jnp.float32))
    T_next = TPair(win=W + 1, off=jnp.zeros((C,), jnp.float32))

    c_k = auto.ca_next
    snap = t_add(c_k, st.ca_snap, interval)
    due = t_lt(snap, T_next)

    commit_vis = t_add(T, st.ca_commit_vis, interval)
    early_snap = due & t_lt(snap, commit_vis)
    if pre is not None:
        pre_phase, pre_attempts, pre_alloc_cpu, pre_alloc_ram = pre
        if nodes_lane_major:
            pre_alloc_cpu = pre_alloc_cpu.T
            pre_alloc_ram = pre_alloc_ram.T
        phase_v = jnp.where(early_snap[:, None], pre_phase, pods.phase)
        attempts_v = jnp.where(early_snap[:, None], pre_attempts, pods.attempts)
        alloc_cpu_v = jnp.where(
            early_snap[:, None], pre_alloc_cpu, nodes_row.alloc_cpu
        )
        alloc_ram_v = jnp.where(
            early_snap[:, None], pre_alloc_ram, nodes_row.alloc_ram
        )
    else:
        phase_v, attempts_v = pods.phase, pods.attempts
        alloc_cpu_v, alloc_ram_v = nodes_row.alloc_cpu, nodes_row.alloc_ram

    in_cache = (phase_v == PHASE_UNSCHEDULABLE) | (
        (phase_v == PHASE_QUEUED) & (attempts_v >= 2)
    )
    any_unsched = in_cache.any(axis=1)
    up_branch = due & any_unsched
    down_branch = due & ~any_unsched

    # Branch around the whole pass bodies: most windows have an empty
    # unscheduled cache (no scale-up work) and scale-down's pod grouping
    # ((C, P) sort) only matters once CA nodes exist. The predicates reduce
    # to replicated scalars, so the conds hold under a C-sharded mesh.
    S = st.ca_slots.shape[1]
    Gn = st.ng_ca_start.shape[1]
    planned, planned_per_group, up_starved = jax.lax.cond(
        up_branch.any(),
        lambda: _ca_scale_up(
            state_row, auto, st, up_branch, K_up, phase_v, attempts_v,
            use_pallas=use_pallas,
            pallas_interpret=pallas_interpret,
            pallas_mesh=pallas_mesh,
            pallas_axis=pallas_axis,
        ),
        lambda: (
            jnp.zeros((C, S), bool),
            jnp.zeros((C, Gn), jnp.int32),
            jnp.zeros((C,), jnp.int32),
        ),
    )
    def _down_branch():
        # Under reclaim the candidate-walk and re-placement orders are
        # derived from the live occupants' allocation indices (the static
        # tables describe slot-index names, stale once a slot is reused);
        # computed inside the cond so quiet windows never pay the sort.
        sd_order = node_rank = None
        if reclaim and auto.ca_alloc is not None:
            sd_order, node_rank = ca_name_order(auto, st)
        return _ca_scale_down(
            state_row, auto, st, down_branch, K_sd,
            phase_v, alloc_cpu_v, alloc_ram_v, snap, interval,
            use_pallas=use_pallas,
            pallas_interpret=pallas_interpret,
            pallas_mesh=pallas_mesh,
            pallas_axis=pallas_axis,
            descatter=descatter,
            sd_order=sd_order,
            node_rank=node_rank,
        )

    removed, removed_per_group = jax.lax.cond(
        # ca_count (live CA nodes) rather than ca_cursor (ever allocated):
        # once everything scaled back down there is nothing to remove.
        down_branch.any() & (auto.ca_count.sum() > 0),
        _down_branch,
        lambda: (jnp.zeros((C, S), bool), jnp.zeros((C, Gn), jnp.int32)),
    )

    # Planned slots come alive at their effect time; removals likewise. The
    # effect-time value is one (C,) pair — scatter a boolean touch mask (fast
    # 32-bit path) and merge the pair elementwise.
    _, S = planned.shape
    N = nodes_row.alive.shape[1]
    rows = jnp.arange(C, dtype=jnp.int32)[:, None]
    tgt_create = jnp.where(planned, st.ca_slots, N)
    touch_create = (
        jnp.zeros((C, N), bool).at[rows, tgt_create].set(True, mode="drop")
    )
    eff_up = _broadcast_pair(t_add(c_k, st.d_ca_up, interval), (C, N))
    create_time = t_where(
        touch_create, t_min(nodes.create_time, eff_up), nodes.create_time
    )
    tgt_remove = jnp.where(removed, st.ca_slots, N)
    touch_remove = (
        jnp.zeros((C, N), bool).at[rows, tgt_remove].set(True, mode="drop")
    )
    eff_down = _broadcast_pair(t_add(c_k, st.d_ca_down, interval), (C, N))
    remove_time = t_where(
        touch_remove, t_min(nodes.remove_time, eff_down), nodes.remove_time
    )

    metrics = metrics._replace(
        scaled_up_nodes=metrics.scaled_up_nodes + planned.sum(axis=1, dtype=jnp.int32),
        scaled_down_nodes=metrics.scaled_down_nodes + removed.sum(axis=1, dtype=jnp.int32),
        ca_reserve_starved=metrics.ca_reserve_starved + up_starved,
    )
    new_auto = auto._replace(
        ca_count=auto.ca_count + planned_per_group - removed_per_group,
        ca_cursor=auto.ca_cursor + planned_per_group,
        ca_next=t_where(
            due, t_add(c_k, st.ca_period, interval), c_k
        ),
    )
    if reclaim and auto.ca_alloc is not None:
        # Stamp each opened slot's allocation index (the scalar's
        # total_allocated at open time; names are "{group}_{alloc+1}").
        # Scale-up opens the offsets [cursor, cursor + planned) of each
        # group's reserve in slot order, which is also allocation order,
        # so the index is cursor-relative arithmetic — no carry needed
        # through the bin-pack loop or the Pallas kernel.
        gidc = jnp.clip(st.ca_slot_group, 0, st.ng_ca_start.shape[1] - 1)
        iota_s = jnp.broadcast_to(
            jnp.arange(S, dtype=jnp.int32)[None, :], planned.shape
        )
        off_in_g = iota_s - st.ng_ca_start[rows, gidc]
        alloc_new = (
            auto.ca_total[rows, gidc]
            + off_in_g
            - auto.ca_cursor[rows, gidc]
        )
        new_auto = new_auto._replace(
            ca_alloc=jnp.where(planned, alloc_new, auto.ca_alloc),
            ca_total=auto.ca_total + planned_per_group,
        )
    auto = new_auto
    state = state._replace(
        nodes=nodes._replace(create_time=create_time, remove_time=remove_time),
        metrics=metrics,
    )
    return state, auto


def ca_reclaim_pass(
    state: ClusterBatchState,
    auto: AutoscaleState,
    st: AutoscaleStatics,
    W: jnp.ndarray,
    consts: StepConstants,
    period: int = 1,
    nodes_lane_major: bool = False,
) -> Tuple[ClusterBatchState, AutoscaleState]:
    """CA slot reclaim: return fully-RETIRED reserve slots to their group
    by a stable in-trace compaction, so ca_cursor tracks live occupancy
    and sustained churn never exhausts the reserve (the batched analog of
    the reference's node_component_pool reuse, node_component_pool.rs:60-77).

    Runs at the START of the window body — a clean state boundary, and it
    guarantees a scale-up later in the same window sees every slot that
    was reclaimable, so the loud starvation bound can only fire when the
    reserve is truly exhausted by LIVE demand.

    A slot is retired when its node's removal has fully drained:
    - the node is dead with no pending create/remove effect, and
    - no pod still binds it as RUNNING, and no SUCCEEDED pod's finish
      visibility is still in flight (a future CA cycle's storage snapshot
      lands at or after this window's start, so a finish visible by
      (W, 0) can never be resurrected by the scale-down's vis_back
      reconstruction; terminal pods past that horizon contribute nothing
      to any later pass and their stale slot pointers are remapped along
      with the move).

    Compaction is STABLE per group (keepers pack to the group's reserve
    prefix in slot order), which preserves the two orderings exactness
    rests on: slot order among live CA nodes stays allocation order (the
    scheduler's slot-order tie-break is untouched), and names ride the
    occupants' allocation indices (ca_alloc), so every name-ordered walk
    (ca_name_order) is invariant under the move. When nothing retires the
    permutation is the identity and the pass is a bit-exact no-op; the
    whole body sits behind a cond on the cheap (C, S) dead-slot predicate
    so quiet windows pay only the predicate.

    period > 1 additionally gates compaction to windows with
    (W + 1) % period == 0 (batching the (C, P) safety sweep); retired
    slots then wait, which is semantically invisible but can starve a
    scale-up the immediate cadence would have served — the default is the
    immediate cadence.
    """
    if auto is None or auto.ca_alloc is None:
        return state, auto
    nodes, pods = state.nodes, state.pods
    C, P = pods.phase.shape
    S = auto.ca_alloc.shape[1]
    Gn = st.ng_ca_start.shape[1]
    rows1 = jnp.arange(C, dtype=jnp.int32)
    rows = rows1[:, None]
    alive_row = nodes.alive.T if nodes_lane_major else nodes.alive
    N = alive_row.shape[1]
    n_trace = N - S
    interval = jnp.float32(consts.scheduling_interval)
    slots = st.ca_slots
    slotc = jnp.clip(slots, 0, N - 1)
    occupied = auto.ca_alloc >= 0

    # Cheap per-window predicate: an occupied slot whose node is dead
    # with no pending effects ((C, S) gathers only).
    dead = (
        occupied
        & (slots >= 0)
        & ~alive_row[rows, slotc]
        & is_inf(
            TPair(
                win=nodes.create_time.win[rows, slotc],
                off=nodes.create_time.off[rows, slotc],
            )
        )
        & is_inf(
            TPair(
                win=nodes.remove_time.win[rows, slotc],
                off=nodes.remove_time.off[rows, slotc],
            )
        )
    )
    do = dead.any()
    if period > 1:
        do = do & ((W + jnp.int32(1)) % jnp.int32(period) == 0).all()

    iota_s = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32)[None, :], (C, S))
    grp = jnp.where(st.ca_slot_group >= 0, st.ca_slot_group, Gn)

    def _compact():
        # Row-major views of the hot node leaves (transposes only inside
        # this rare branch; the pending pairs are row-major by contract).
        alive_r = nodes.alive.T if nodes_lane_major else nodes.alive
        acpu_r = nodes.alloc_cpu.T if nodes_lane_major else nodes.alloc_cpu
        aram_r = nodes.alloc_ram.T if nodes_lane_major else nodes.alloc_ram
        capc_r = nodes.cap_cpu.T if nodes_lane_major else nodes.cap_cpu
        capr_r = nodes.cap_ram.T if nodes_lane_major else nodes.cap_ram

        # Retirement safety: pods still binding the node. RUNNING blocks
        # outright; a SUCCEEDED pod blocks until its finish visibility
        # (finish + ca_finish_vis) reaches this window's start — after
        # that no future storage snapshot can reconstruct it (vis_back).
        Tp = TPair(
            win=jnp.broadcast_to(W[:, None], (C, P)),
            off=jnp.zeros((C, P), jnp.float32),
        )
        finish_vis = TPair(
            win=st.ca_finish_vis.win[:, None],
            off=st.ca_finish_vis.off[:, None],
        )
        succ_vis = t_add(
            t_add(pods.start_time, pods.duration, interval),
            finish_vis,
            interval,
        )
        blocking = (
            (pods.phase == PHASE_RUNNING)
            | ((pods.phase == PHASE_SUCCEEDED) & ~t_le(succ_vis, Tp))
        ) & (pods.node >= 0)
        tgt_b = jnp.where(blocking, pods.node, N)
        node_blocked = (
            jnp.zeros((C, N), bool).at[rows, tgt_b].set(True, mode="drop")
        )
        retired = dead & ~node_blocked[rows, slotc]
        keep = occupied & ~retired

        # Stable per-group partition: keepers first in slot order (slot
        # ranges per group are contiguous by construction).
        _, _, order = jax.lax.sort(
            (grp, jnp.where(keep, 0, 1).astype(jnp.int32), iota_s),
            dimension=1,
            num_keys=2,
            is_stable=True,
        )
        inv = jnp.zeros((C, S), jnp.int32).at[rows, order].set(iota_s)
        take = lambda a: jnp.take_along_axis(a, order, axis=1)  # noqa: E731

        # Permute the CA node segment (caps and crash payload are uniform
        # within a group / zero on CA slots — permutation-invariant, not
        # rewritten). Retired slots reset to pristine allocatable.
        seg = lambda a: a[:, n_trace:]  # noqa: E731
        retired_n = take(retired)
        alive_seg = take(seg(alive_r))
        acpu_seg = jnp.where(
            retired_n, seg(capc_r), take(seg(acpu_r))
        )
        aram_seg = jnp.where(
            retired_n, seg(capr_r), take(seg(aram_r))
        )
        ctw_seg = take(seg(nodes.create_time.win))
        cto_seg = take(seg(nodes.create_time.off))
        rtw_seg = take(seg(nodes.remove_time.win))
        rto_seg = take(seg(nodes.remove_time.off))

        cat = lambda full, s_: jnp.concatenate(  # noqa: E731
            [full[:, :n_trace], s_], axis=1
        )
        alive2 = cat(alive_r, alive_seg)
        acpu2 = cat(acpu_r, acpu_seg)
        aram2 = cat(aram_r, aram_seg)
        if nodes_lane_major:
            alive2, acpu2, aram2 = alive2.T, acpu2.T, aram2.T

        # Stale or live slot pointers follow the move (terminal pods past
        # the visibility horizon keep pointing at their retired slot's
        # new position; nothing ever reads them again).
        pn = pods.node
        ca_ptr = pn >= n_trace
        pn2 = jnp.where(
            ca_ptr,
            n_trace + inv[rows, jnp.clip(pn - n_trace, 0, S - 1)],
            pn,
        )

        keep_cnt = (
            jnp.zeros((C, Gn + 1), jnp.int32)
            .at[rows, grp]
            .add(keep.astype(jnp.int32))[:, :Gn]
        )
        return (
            alive2,
            acpu2,
            aram2,
            cat(nodes.create_time.win, ctw_seg),
            cat(nodes.create_time.off, cto_seg),
            cat(nodes.remove_time.win, rtw_seg),
            cat(nodes.remove_time.off, rto_seg),
            pn2,
            jnp.where(retired_n, -1, take(auto.ca_alloc)),
            keep_cnt,
            auto.ca_reclaimed + retired.sum(axis=1, dtype=jnp.int32),
        )

    def _identity():
        return (
            nodes.alive,
            nodes.alloc_cpu,
            nodes.alloc_ram,
            nodes.create_time.win,
            nodes.create_time.off,
            nodes.remove_time.win,
            nodes.remove_time.off,
            pods.node,
            auto.ca_alloc,
            auto.ca_cursor,
            auto.ca_reclaimed,
        )

    (
        alive2, acpu2, aram2, ctw2, cto2, rtw2, rto2, pn2,
        alloc2, cursor2, reclaimed2,
    ) = jax.lax.cond(do, _compact, _identity)
    state = state._replace(
        nodes=nodes._replace(
            alive=alive2,
            alloc_cpu=acpu2,
            alloc_ram=aram2,
            create_time=TPair(win=ctw2, off=cto2),
            remove_time=TPair(win=rtw2, off=rto2),
        ),
        pods=pods._replace(node=pn2),
    )
    auto = auto._replace(
        ca_alloc=alloc2, ca_cursor=cursor2, ca_reclaimed=reclaimed2
    )
    return state, auto


# Donated standalone entry points. Inside the window step the passes are
# already FUSED into the chunk program (step._window_body calls them in-trace,
# so there is no separate HPA/CA dispatch in the steady-state loop); these
# wrappers serve callers that drive a pass by itself (tests, exploratory
# tools) with the same in-place buffer reuse the donated window entries get.
# They take the full state ONLY — state.auto carries the AutoscaleState — so
# donation never sees the same buffer through two arguments (state and a
# separately-passed auto alias). Bit-identical to the plain calls
# (tests/test_window_donation_dispatch.py).
@partial(jax.jit, static_argnames=("seg",), donate_argnums=(0,))
def hpa_pass_donated(
    state: ClusterBatchState,
    st: AutoscaleStatics,
    W: jnp.ndarray,
    consts: StepConstants,
    seg=None,
) -> ClusterBatchState:
    state2, auto2 = hpa_pass(state, state.auto, st, W, consts, seg=seg)
    return state2._replace(auto=auto2)


@partial(
    jax.jit,
    static_argnames=(
        "K_up", "K_sd", "use_pallas", "pallas_interpret", "pallas_mesh",
        "pallas_axis", "descatter", "reclaim",
    ),
    donate_argnums=(0,),
)
def ca_pass_donated(
    state: ClusterBatchState,
    st: AutoscaleStatics,
    W: jnp.ndarray,
    consts: StepConstants,
    K_up: int,
    K_sd: int,
    pre=None,
    use_pallas: bool = False,
    pallas_interpret: bool = False,
    pallas_mesh=None,
    pallas_axis: str = "clusters",
    descatter: bool = True,
    reclaim: bool = False,
) -> ClusterBatchState:
    state2, auto2 = ca_pass(
        state, state.auto, st, W, consts, K_up, K_sd, pre=pre,
        use_pallas=use_pallas, pallas_interpret=pallas_interpret,
        pallas_mesh=pallas_mesh, pallas_axis=pallas_axis,
        descatter=descatter, reclaim=reclaim,
    )
    return state2._replace(auto=auto2)
