"""Vectorized autoscaler passes for the batched backend.

The scalar HPA / cluster-autoscaler control loops (reference:
src/autoscalers/horizontal_pod_autoscaler/*.rs, cluster_autoscaler/*.rs)
become masked array passes over the dense cluster-batch state, run at their
scan cadence inside the window step:

- HPA: per-(cluster, pod-group) closed-form utilization from the compiled
  load curves, the k8s desired-replicas formula with tolerance band
  (reference: kube_horizontal_pod_autoscaler.rs:54-155), and head/tail
  activation windows over the group's reserved pod slots.
- CA: bounded-K first-fit bin-packing scale-up over the unscheduled-pod cache
  and a nested-scan scale-down with simulated re-placement over shared virtual
  allocatables (reference: kube_cluster_autoscaler.rs:55-307).

Times are the 32-bit (win, off) pairs of timerep.py; the only 64-bit math is
the load-curve elapsed-time evaluation (float64 on tiny (C, G) shapes — the
curves cycle over arbitrary-length periods, where float32 elapsed time at
Alibaba-scale timestamps would blur the curve position).

Documented deviations from the scalar path (replica/node COUNTS match; exact
identity of scaled-down members may differ):
- HPA scale-down removes pods in FIFO creation order; the scalar path pops the
  lexicographically-smallest name, which deviates once indices reach 10+
  (kube_horizontal_pod_autoscaler.rs:197-205 pops a BTreeSet). Utilization is
  count-based, so trajectories are unaffected.
- CA decisions read state at the window boundary instead of at the simulated
  storage-snapshot time (a sub-window skew), and re-arm on a fixed cadence.
  The scalar path re-arms with delay 0 when the info round-trip
  (2 x as_to_ca + processing) exceeds scan_interval
  (cluster_autoscaler.rs:256-262), i.e. it degrades to back-to-back cycles;
  the batched path ticks at every due window, which IS the back-to-back
  cadence at window granularity (a cycle can never run more than once per
  window on either path, since decisions only change at window boundaries
  here). With the default delays (round-trip 1.34 s << 10 s scan interval)
  the branch never triggers, so the fixed cadence is exact; under overrun
  configs both paths converge to one cycle per window and differ only in
  sub-window effect timing, which the pending-effect arrays already carry.
- Scale-up considers at most K_up cache pods and scale-down at most K_sd pods
  per candidate node per cycle; overflow is deferred to the next cycle
  (scale-up) or conservatively skipped (scale-down).
- Scaled-up slots are never reused: each group reserves
  slots ~ multiplier x max_count, mirroring the reference's pre-sized
  component pool (src/simulator.rs:212-230) without reclaim.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from kubernetriks_tpu.batched.step import lexsort_time_i32
from kubernetriks_tpu.batched.state import (
    ClusterBatchState,
    PHASE_EMPTY,
    PHASE_FAILED,
    PHASE_QUEUED,
    PHASE_REMOVED,
    PHASE_RUNNING,
    PHASE_SUCCEEDED,
    PHASE_UNSCHEDULABLE,
    StepConstants,
)
from kubernetriks_tpu.batched.timerep import (
    TPair,
    is_inf,
    t_add,
    t_inf,
    t_le,
    t_min,
    t_where,
    t_zeros,
)

INF = jnp.inf
_BIG_I32 = jnp.iinfo(jnp.int32).max


class AutoscaleStatics(NamedTuple):
    """Compile-time autoscaler tables (pytree of arrays; C-leading)."""

    # --- HPA pod groups: (C, Gp) ---
    pg_slot_start: jnp.ndarray  # int32 first reserved pod slot
    pg_slot_count: jnp.ndarray  # int32 reserved slots (cumulative creations cap)
    pg_initial: jnp.ndarray  # int32 initial replicas (created by the trace)
    pg_max_pods: jnp.ndarray  # int32 max simultaneous replicas
    pg_target_cpu: jnp.ndarray  # float32; <=0 means metric unset
    pg_target_ram: jnp.ndarray  # float32; <=0 means metric unset
    # First HPA tick that sees the group: creation + register delay (pair);
    # win=INF_WIN = padding / HPA disabled.
    pg_active_from: TPair
    # Absolute creation time in float64 seconds for load-curve elapsed math.
    pg_creation_s: jnp.ndarray
    # Piecewise-cyclic load curves, (C, Gp, U); duration 0 = padding unit.
    pg_cpu_dur: jnp.ndarray
    pg_cpu_load: jnp.ndarray
    pg_cpu_total: jnp.ndarray  # (C, Gp) cycle length; 0 = no model (util 0)
    pg_cpu_const: jnp.ndarray  # bool: constant model (load IS the utilization)
    pg_ram_dur: jnp.ndarray
    pg_ram_load: jnp.ndarray
    pg_ram_total: jnp.ndarray
    pg_ram_const: jnp.ndarray
    pod_group_id: jnp.ndarray  # (C, P) int32 group of pod slot; -1 = none
    # --- CA node groups: (C, Gn) ---
    ng_ca_start: jnp.ndarray  # int32 first CA-slot (in the compact CA axis)
    ng_slot_count: jnp.ndarray  # int32 reserved CA slots
    ng_max_count: jnp.ndarray  # int32; <0 = unbounded
    ng_tmpl_cpu: jnp.ndarray  # int32 template capacity
    ng_tmpl_ram: jnp.ndarray  # int32 (ram units)
    ca_max_nodes: jnp.ndarray  # (C,) int32 global CA node quota
    ca_slots: jnp.ndarray  # (C, S) int32 global node slot of CA slot; -1 pad
    ca_slot_group: jnp.ndarray  # (C, S) int32 owning group; -1 pad
    # --- scalar time constants (pairs) ---
    hpa_interval: TPair
    ca_interval: TPair
    hpa_tolerance: jnp.ndarray  # f64 scalar
    ca_threshold: jnp.ndarray  # f64 scalar
    d_hpa_up: TPair  # HPA tick -> scaled-up pod enters scheduler queue
    d_hpa_down: TPair  # HPA tick -> pod removal effect at storage
    d_ca_up: TPair  # CA tick -> scaled-up node schedulable
    d_ca_down: TPair  # CA tick -> node removal effect at node


class AutoscaleState(NamedTuple):
    """Dynamic autoscaler state (lives inside ClusterBatchState.auto)."""

    hpa_head: jnp.ndarray  # (C, Gp) int32 first live created offset
    hpa_tail: jnp.ndarray  # (C, Gp) int32 next creation offset (== total_created)
    ca_count: jnp.ndarray  # (C, Gn) int32 current CA nodes per group
    ca_cursor: jnp.ndarray  # (C, Gn) int32 next reserved slot offset
    hpa_next: TPair  # (C,) next HPA tick
    ca_next: TPair  # (C,) next CA tick


def init_autoscale_state(statics: AutoscaleStatics) -> AutoscaleState:
    C, Gp = statics.pg_slot_start.shape
    Gn = statics.ng_ca_start.shape[1]
    return AutoscaleState(
        hpa_head=jnp.zeros((C, Gp), jnp.int32),
        # The trace's initial pods count as created (the api-server expansion
        # seeds created_pods/total_created, reference: api_server.rs:405-455).
        hpa_tail=statics.pg_initial.astype(jnp.int32),
        ca_count=jnp.zeros((C, Gn), jnp.int32),
        ca_cursor=jnp.zeros((C, Gn), jnp.int32),
        hpa_next=t_zeros((C,)),
        ca_next=t_zeros((C,)),
    )


def _curve_load(dur, load, total, elapsed):
    """Piecewise-constant cyclic curve lookup (reference semantics:
    src/core/resource_usage/pod_group.rs:71-99). dur/load: (C, G, U);
    total/elapsed: (C, G). elapsed is float64 (see module docstring); the
    returned load is float32."""
    safe_total = jnp.maximum(total.astype(jnp.float64), 1e-9)
    pos = jnp.where(total > 0, jnp.mod(elapsed, safe_total), 0.0)
    ecs = jnp.cumsum(dur, axis=-1) - dur  # exclusive start of each unit
    in_unit = (ecs <= pos[..., None]) & (pos[..., None] < ecs + dur)
    return jnp.where(in_unit, load, 0.0).sum(axis=-1).astype(jnp.float32)


def _broadcast_pair(p: TPair, shape) -> TPair:
    return TPair(
        win=jnp.broadcast_to(p.win[..., None], shape),
        off=jnp.broadcast_to(p.off[..., None], shape),
    )


def hpa_pass(
    state: ClusterBatchState,
    auto: AutoscaleState,
    st: AutoscaleStatics,
    W: jnp.ndarray,
    consts: StepConstants,
) -> Tuple[ClusterBatchState, AutoscaleState]:
    """One masked HPA cycle at window W for every due cluster
    (scalar equivalent: horizontal_pod_autoscaler.py run cycle +
    kube_horizontal_pod_autoscaler.py formula)."""
    due_any = t_le(
        auto.hpa_next, TPair(win=W, off=jnp.zeros_like(auto.hpa_next.off))
    ).any()
    return jax.lax.cond(
        due_any,
        lambda: _hpa_pass_body(state, auto, st, W, consts),
        lambda: (state, auto),
    )


def _hpa_pass_body(
    state: ClusterBatchState,
    auto: AutoscaleState,
    st: AutoscaleStatics,
    W: jnp.ndarray,
    consts: StepConstants,
) -> Tuple[ClusterBatchState, AutoscaleState]:
    pods, metrics = state.pods, state.metrics
    C, P = pods.phase.shape
    Gp = st.pg_slot_start.shape[1]
    interval = jnp.float32(consts.scheduling_interval)
    rows = jnp.arange(C, dtype=jnp.int32)[:, None]
    T = TPair(win=W, off=jnp.zeros((C,), jnp.float32))  # (C,)
    Tg = TPair(
        win=jnp.broadcast_to(W[:, None], (C, Gp)),
        off=jnp.zeros((C, Gp), jnp.float32),
    )

    due = t_le(auto.hpa_next, T)
    active = due[:, None] & t_le(st.pg_active_from, Tg)

    # Group membership and running counts (running = bound AND started by T,
    # mirroring node_component.running_pods at collection time).
    gid = st.pod_group_id
    gid_c = jnp.where(gid >= 0, gid, Gp)
    started = t_le(
        pods.start_time,
        TPair(
            win=jnp.broadcast_to(W[:, None], (C, P)),
            off=jnp.zeros((C, P), jnp.float32),
        ),
    )
    running = (pods.phase == PHASE_RUNNING) & started
    run_per_group = (
        jnp.zeros((C, Gp + 1), jnp.int32)
        .at[rows, gid_c]
        .add(running.astype(jnp.int32))[:, :Gp]
    )
    present = run_per_group > 0  # group absent from metrics when nothing runs
    runf = jnp.maximum(run_per_group, 1).astype(jnp.float32)

    # Elapsed time since group creation, float64 (curves cycle over arbitrary
    # periods; f32 elapsed at large absolute t would blur the curve position).
    T_s = W.astype(jnp.float64) * jnp.float64(consts.scheduling_interval)
    elapsed = T_s[:, None] - st.pg_creation_s
    cpu_load = _curve_load(st.pg_cpu_dur, st.pg_cpu_load, st.pg_cpu_total, elapsed)
    ram_load = _curve_load(st.pg_ram_dur, st.pg_ram_load, st.pg_ram_total, elapsed)
    util_cpu = jnp.where(
        st.pg_cpu_total > 0,
        jnp.where(st.pg_cpu_const, cpu_load, jnp.minimum(1.0, cpu_load / runf)),
        0.0,
    )
    util_ram = jnp.where(
        st.pg_ram_total > 0,
        jnp.where(st.pg_ram_const, ram_load, jnp.minimum(1.0, ram_load / runf)),
        0.0,
    )

    current = auto.hpa_tail - auto.hpa_head

    def desired_by(util, target):
        ratio = util / jnp.maximum(target, 1e-9)
        in_band = jnp.abs(ratio - 1.0) <= st.hpa_tolerance
        # -1e-4 guards float32 products landing epsilon above an integer
        # (the scalar path computes the formula in f64).
        d = jnp.ceil(current.astype(jnp.float32) * ratio - 1e-4).astype(jnp.int32)
        return jnp.where(in_band, current, d)

    has_cpu = st.pg_target_cpu > 0
    has_ram = st.pg_target_ram > 0
    d_cpu = desired_by(util_cpu, st.pg_target_cpu)
    d_ram = desired_by(util_ram, st.pg_target_ram)
    desired = jnp.where(
        has_cpu & has_ram,
        jnp.maximum(d_cpu, d_ram),
        jnp.where(has_cpu, d_cpu, jnp.where(has_ram, d_ram, current)),
    )
    desired = jnp.minimum(desired, st.pg_max_pods)

    act = active & present
    delta = jnp.where(act, desired - current, 0)
    # Slots are a ring over the group's reserve: head/tail are monotonic
    # counters and the live window [head, tail) maps onto ring offsets
    # modulo slot_count, so churn (scale-down then scale-up, repeated by the
    # cyclic load curves) reuses freed slots instead of exhausting the
    # reserve. A slot is only reusable once its previous occupant reached a
    # terminal phase; `up` is clamped to the longest reusable prefix of the
    # candidate window (counters accumulate incrementally, so resetting a
    # terminal slot never corrupts metrics).
    count_g = jnp.maximum(st.pg_slot_count, 1)
    up0 = jnp.minimum(jnp.maximum(delta, 0), count_g - current)
    down = jnp.minimum(jnp.maximum(-delta, 0), current)

    slot_start_p = st.pg_slot_start[rows, gid_c]  # (C, P); garbage where gid<0
    off = jnp.arange(P, dtype=jnp.int32)[None, :] - slot_start_p
    in_group = gid >= 0
    count_p = count_g[rows, gid_c]
    tail_ring = jnp.mod(auto.hpa_tail, count_g)[rows, gid_c]
    head_ring = jnp.mod(auto.hpa_head, count_g)[rows, gid_c]
    rel_tail = jnp.mod(off - tail_ring, count_p)  # candidate rank if < up
    rel_head = jnp.mod(off - head_ring, count_p)

    reusable = (
        (pods.phase == PHASE_EMPTY)
        | (pods.phase == PHASE_SUCCEEDED)
        | (pods.phase == PHASE_REMOVED)
        | (pods.phase == PHASE_FAILED)
    )
    up0_p = up0[rows, gid_c]
    blocked = in_group & (rel_tail < up0_p) & ~reusable
    big = jnp.int32(1 << 30)
    min_blocked = (
        jnp.full((C, Gp + 1), big, jnp.int32)
        .at[rows, gid_c]
        .min(jnp.where(blocked, rel_tail, big))[:, :Gp]
    )
    up = jnp.minimum(up0, min_blocked)
    up_p = up[rows, gid_c]
    down_p = down[rows, gid_c]

    activate = in_group & (rel_tail < up_p) & reusable
    rank = jnp.cumsum(activate, axis=1, dtype=jnp.int32) - 1
    n_up = activate.sum(axis=1, dtype=jnp.int32)
    enq = t_add(T, st.d_hpa_up, interval)  # (C,) pair
    enq_p = _broadcast_pair(enq, (C, P))
    phase = jnp.where(activate, PHASE_QUEUED, pods.phase)
    queue_ts = t_where(activate, enq_p, pods.queue_ts)
    queue_seq = jnp.where(
        activate, state.queue_seq_counter[:, None] + rank, pods.queue_seq
    )
    initial_attempt_ts = t_where(activate, enq_p, pods.initial_attempt_ts)
    attempts = jnp.where(activate, 1, pods.attempts)
    # Reset state left over from a previous occupant of a reused slot.
    node = jnp.where(activate, -1, pods.node)
    start_time = t_where(activate, t_zeros((C, P)), pods.start_time)
    finish_time = t_where(activate, t_inf((C, P)), pods.finish_time)

    # --- scale down: mark ring offsets [head, head+down) for removal -------
    deactivate = in_group & (rel_head < down_p) & ~activate
    removal_time = t_where(activate, t_inf((C, P)), pods.removal_time)
    rem = t_add(T, st.d_hpa_down, interval)  # (C,) pair
    rem_p = _broadcast_pair(rem, (C, P))
    removal_time = t_where(
        deactivate, t_min(removal_time, rem_p), removal_time
    )

    metrics = metrics._replace(
        scaled_up_pods=metrics.scaled_up_pods + up.sum(axis=1, dtype=jnp.int32),
        scaled_down_pods=metrics.scaled_down_pods + down.sum(axis=1, dtype=jnp.int32),
    )
    auto = auto._replace(
        hpa_head=auto.hpa_head + down,
        hpa_tail=auto.hpa_tail + up,
        hpa_next=t_where(
            due, t_add(auto.hpa_next, st.hpa_interval, interval), auto.hpa_next
        ),
    )
    state = state._replace(
        pods=pods._replace(
            phase=phase,
            queue_ts=queue_ts,
            queue_seq=queue_seq,
            initial_attempt_ts=initial_attempt_ts,
            attempts=attempts,
            removal_time=removal_time,
            node=node,
            start_time=start_time,
            finish_time=finish_time,
        ),
        metrics=metrics,
        queue_seq_counter=state.queue_seq_counter + n_up,
    )
    return state, auto


def _ca_scale_up(
    state: ClusterBatchState,
    auto: AutoscaleState,
    st: AutoscaleStatics,
    branch: jnp.ndarray,
    K_up: int,
):
    """Bin-packing scale-up over the unscheduled-pod cache
    (reference: kube_cluster_autoscaler.rs:190-240). Returns
    (planned (C,S) bool, planned_per_group (C,Gn))."""
    pods = state.pods
    C, P = pods.phase.shape
    S = st.ca_slots.shape[1]
    Gn = st.ng_ca_start.shape[1]
    rows1 = jnp.arange(C, dtype=jnp.int32)
    rows = rows1[:, None]

    # The storage unscheduled-pods cache: parked pods plus woken-but-unscheduled
    # pods (attempts>=2 after a wake, reference: persistent_storage.rs cache
    # removal only on assignment).
    in_cache = (pods.phase == PHASE_UNSCHEDULABLE) | (
        (pods.phase == PHASE_QUEUED) & (pods.attempts >= 2)
    )
    key_t = t_where(in_cache, pods.queue_ts, t_inf((C, P)))
    key_seq = jnp.where(in_cache, pods.queue_seq, _BIG_I32)
    order = lexsort_time_i32(key_t, key_seq)[:, :K_up]
    cvalid = in_cache[rows, order] & branch[:, None]
    creq_cpu = pods.req_cpu[rows, order]
    creq_ram = pods.req_ram[rows, order]

    planned0 = jnp.zeros((C, S), bool)
    plan_seq0 = jnp.full((C, S), _BIG_I32, jnp.int32)
    palloc_cpu0 = jnp.zeros((C, S), jnp.int32)
    palloc_ram0 = jnp.zeros((C, S), jnp.int32)
    g_planned0 = jnp.zeros((C, Gn), jnp.int32)
    total0 = auto.ca_count.sum(axis=1)  # CA counts only (reference quirk:
    # max_node_count bounds CA-owned nodes, kube_cluster_autoscaler.rs:62-80)
    counter0 = jnp.zeros((C,), jnp.int32)

    def body(carry, xs):
        planned, plan_seq, palloc_cpu, palloc_ram, g_planned, total, counter = carry
        valid, rcpu, rram = xs

        # First-fit into already-planned nodes, in plan order; fitting pods
        # deduct from the virtual allocatable (reference :81-87).
        fit = planned & (rcpu[:, None] <= palloc_cpu) & (rram[:, None] <= palloc_ram)
        any_fit = fit.any(axis=1)
        first = jax.lax.argmin(jnp.where(fit, plan_seq, _BIG_I32), 1, jnp.int32)
        use = valid & any_fit
        palloc_cpu = palloc_cpu.at[rows1, jnp.where(use, first, S)].add(
            -rcpu, mode="drop"
        )
        palloc_ram = palloc_ram.at[rows1, jnp.where(use, first, S)].add(
            -rram, mode="drop"
        )

        # Else open a node from the first fitting group (name-sorted at build).
        can_open = valid & ~any_fit & (total < st.ca_max_nodes)
        gcount = auto.ca_count + g_planned
        g_ok = (
            ((st.ng_max_count < 0) | (gcount < st.ng_max_count))
            & (auto.ca_cursor + g_planned < st.ng_slot_count)
            & (rcpu[:, None] <= st.ng_tmpl_cpu)
            & (rram[:, None] <= st.ng_tmpl_ram)
        )
        g_found = g_ok.any(axis=1)
        g = jax.lax.argmax(g_ok, 1, jnp.int32)
        open_ = can_open & g_found
        s_new = (
            st.ng_ca_start[rows1, g]
            + auto.ca_cursor[rows1, g]
            + g_planned[rows1, g]
        )
        s_tgt = jnp.where(open_, s_new, S)
        planned = planned.at[rows1, s_tgt].set(True, mode="drop")
        plan_seq = plan_seq.at[rows1, s_tgt].set(counter, mode="drop")
        # The new node joins at FULL template allocatable: the triggering pod
        # is NOT packed into it (reference quirk, kube_cluster_autoscaler.rs:210-218).
        palloc_cpu = palloc_cpu.at[rows1, s_tgt].set(
            st.ng_tmpl_cpu[rows1, g], mode="drop"
        )
        palloc_ram = palloc_ram.at[rows1, s_tgt].set(
            st.ng_tmpl_ram[rows1, g], mode="drop"
        )
        g_planned = g_planned.at[rows1, jnp.where(open_, g, Gn)].add(1, mode="drop")
        total = total + open_.astype(jnp.int32)
        counter = counter + open_.astype(jnp.int32)
        return (planned, plan_seq, palloc_cpu, palloc_ram, g_planned, total, counter), None

    carry0 = (planned0, plan_seq0, palloc_cpu0, palloc_ram0, g_planned0, total0, counter0)
    (planned, _, _, _, g_planned, _, _), _ = jax.lax.scan(
        body, carry0, (cvalid.T, creq_cpu.T, creq_ram.T)
    )
    return planned, g_planned


def _ca_scale_down(
    state: ClusterBatchState,
    auto: AutoscaleState,
    st: AutoscaleStatics,
    branch: jnp.ndarray,
    K_sd: int,
):
    """Threshold + simulated-re-placement scale-down
    (reference: kube_cluster_autoscaler.rs:242-290). Returns
    (removed (C,S) bool, removed_per_group (C,Gn))."""
    pods, nodes = state.pods, state.nodes
    C, P = pods.phase.shape
    N = nodes.alive.shape[1]
    S = st.ca_slots.shape[1]
    Gn = st.ng_ca_start.shape[1]
    rows1 = jnp.arange(C, dtype=jnp.int32)
    rows = rows1[:, None]
    col_n = jnp.arange(N, dtype=jnp.int32)[None, :]
    iota_p = jnp.broadcast_to(jnp.arange(P, dtype=jnp.int32)[None, :], (C, P))

    # Group running pods by assigned node ONCE (a per-slot (C, P) mask +
    # argsort made the pass O(S * P log P) per window — fatal at trace scale);
    # each node's pods become a contiguous segment of `porder`, located by a
    # scatter-min first-index and scatter-add count.
    on_any = pods.phase == PHASE_RUNNING
    key_node = jnp.where(on_any, pods.node, jnp.int32(N))
    key_sorted, porder = jax.lax.sort(
        (key_node, iota_p), dimension=1, num_keys=1, is_stable=True
    )
    seg_start = (
        jnp.full((C, N), P, jnp.int32)
        .at[rows, jnp.where(key_sorted < N, key_sorted, N)]
        .min(iota_p, mode="drop")
    )
    seg_count = (
        jnp.zeros((C, N), jnp.int32)
        .at[rows, jnp.where(on_any, jnp.clip(key_node, 0, N - 1), N)]
        .add(on_any.astype(jnp.int32), mode="drop")
    )
    col_k = jnp.arange(K_sd, dtype=jnp.int32)[None, :]

    # Only CA slots that were ever allocated (cursor-bounded per group) can
    # hold a node; iterate just those. Before the first scale-up this loop
    # runs ZERO iterations — the common case on healthy clusters.
    s_used = jnp.max(
        jnp.where(auto.ca_cursor > 0, st.ng_ca_start + auto.ca_cursor, 0)
    ).astype(jnp.int32)
    s_used = jnp.minimum(s_used, jnp.int32(S))

    def outer(carry, s):
        valloc_cpu, valloc_ram = carry
        # (C,) global node slot of CA slot s.
        slot = jax.lax.dynamic_index_in_dim(st.ca_slots, s, 1, keepdims=False)
        slot_ok = (slot >= 0) & branch
        slotc = jnp.clip(slot, 0, N - 1)
        alive_here = nodes.alive[rows1, slotc] & slot_ok

        cap_cpu = jnp.maximum(nodes.cap_cpu[rows1, slotc], 1).astype(jnp.float32)
        cap_ram = jnp.maximum(nodes.cap_ram[rows1, slotc], 1).astype(jnp.float32)
        used_cpu = (nodes.cap_cpu[rows1, slotc] - valloc_cpu[rows1, slotc]).astype(
            jnp.float32
        )
        used_ram = (nodes.cap_ram[rows1, slotc] - valloc_ram[rows1, slotc]).astype(
            jnp.float32
        )
        util = jnp.maximum(used_cpu / cap_cpu, used_ram / cap_ram)
        # A node already pending removal (effect time beyond this window) must
        # not be re-selected: it would double-decrement ca_count.
        not_pending = is_inf(
            TPair(
                win=nodes.remove_time.win[rows1, slotc],
                off=nodes.remove_time.off[rows1, slotc],
            )
        )
        eligible = alive_here & not_pending & (util < st.ca_threshold)

        # Pods assigned to this node (storage assignments include in-flight
        # bindings, matching PHASE_RUNNING): the K_sd-slice of this node's
        # segment in pod-slot order.
        cnt = seg_count[rows1, slotc] * slot_ok.astype(jnp.int32)
        attempt = eligible & (cnt <= K_sd)  # overflow: conservatively skip

        seg_pos = jnp.clip(seg_start[rows1, slotc], 0, P - 1)
        take = jnp.clip(seg_pos[:, None] + col_k, 0, P - 1)
        pod_order = porder[rows1[:, None], take]
        pvalid = (col_k < cnt[:, None]) & attempt[:, None]
        prcpu = pods.req_cpu[rows, pod_order]
        prram = pods.req_ram[rows, pod_order]

        save_cpu, save_ram = valloc_cpu, valloc_ram

        def inner(icarry, ixs):
            vcpu, vram, ok = icarry
            pv, rcpu, rram = ixs
            fit = (
                nodes.alive
                & (col_n != slot[:, None])
                & (rcpu[:, None] <= vcpu)
                & (rram[:, None] <= vram)
            )
            any_fit = fit.any(axis=1)
            tgt = jax.lax.argmax(fit, 1, jnp.int32)  # first-fit in slot order
            place = pv & any_fit
            vcpu = vcpu.at[rows1, jnp.where(place, tgt, N)].add(-rcpu, mode="drop")
            vram = vram.at[rows1, jnp.where(place, tgt, N)].add(-rram, mode="drop")
            ok = ok & (~pv | any_fit)
            return (vcpu, vram, ok), None

        (vcpu, vram, all_ok), _ = jax.lax.scan(
            inner,
            (valloc_cpu, valloc_ram, jnp.ones((C,), bool)),
            (pvalid.T, prcpu.T, prram.T),
        )
        success = attempt & all_ok
        # Commit the re-placement on success, roll back otherwise
        # (reference :141-156); commits persist across later candidates.
        valloc_cpu = jnp.where(success[:, None], vcpu, save_cpu)
        valloc_ram = jnp.where(success[:, None], vram, save_ram)
        return valloc_cpu, valloc_ram, success

    def loop_body(carry):
        s, valloc_cpu, valloc_ram, removed = carry
        valloc_cpu, valloc_ram, success = outer((valloc_cpu, valloc_ram), s)
        removed = removed.at[:, s].set(success)
        return (s + jnp.int32(1), valloc_cpu, valloc_ram, removed)

    _, _, _, removed = jax.lax.while_loop(
        lambda carry: carry[0] < s_used,
        loop_body,
        (
            jnp.int32(0),
            nodes.alloc_cpu,
            nodes.alloc_ram,
            jnp.zeros((C, S), bool),
        ),
    )
    group_c = jnp.where(removed, st.ca_slot_group, Gn)
    removed_per_group = (
        jnp.zeros((C, Gn + 1), jnp.int32)
        .at[rows, group_c]
        .add(removed.astype(jnp.int32))[:, :Gn]
    )
    return removed, removed_per_group


def ca_pass(
    state: ClusterBatchState,
    auto: AutoscaleState,
    st: AutoscaleStatics,
    W: jnp.ndarray,
    consts: StepConstants,
    K_up: int,
    K_sd: int,
) -> Tuple[ClusterBatchState, AutoscaleState]:
    """One masked cluster-autoscaler cycle at window W (scalar equivalent:
    cluster_autoscaler.py cycle; AUTO info policy: scale up iff the
    unscheduled cache is non-empty, reference: persistent_storage.rs:381-412)."""
    pods, nodes, metrics = state.pods, state.nodes, state.metrics
    C = pods.phase.shape[0]
    interval = jnp.float32(consts.scheduling_interval)
    T = TPair(win=W, off=jnp.zeros((C,), jnp.float32))

    due = t_le(auto.ca_next, T)
    in_cache = (pods.phase == PHASE_UNSCHEDULABLE) | (
        (pods.phase == PHASE_QUEUED) & (pods.attempts >= 2)
    )
    any_unsched = in_cache.any(axis=1)
    up_branch = due & any_unsched
    down_branch = due & ~any_unsched

    # Branch around the whole pass bodies: most windows have an empty
    # unscheduled cache (no scale-up work) and scale-down's pod grouping
    # ((C, P) sort) only matters once CA nodes exist. The predicates reduce
    # to replicated scalars, so the conds hold under a C-sharded mesh.
    S = st.ca_slots.shape[1]
    Gn = st.ng_ca_start.shape[1]
    planned, planned_per_group = jax.lax.cond(
        up_branch.any(),
        lambda: _ca_scale_up(state, auto, st, up_branch, K_up),
        lambda: (jnp.zeros((C, S), bool), jnp.zeros((C, Gn), jnp.int32)),
    )
    removed, removed_per_group = jax.lax.cond(
        # ca_count (live CA nodes) rather than ca_cursor (ever allocated):
        # once everything scaled back down there is nothing to remove.
        down_branch.any() & (auto.ca_count.sum() > 0),
        lambda: _ca_scale_down(state, auto, st, down_branch, K_sd),
        lambda: (jnp.zeros((C, S), bool), jnp.zeros((C, Gn), jnp.int32)),
    )

    # Planned slots come alive at their effect time; removals likewise. The
    # effect-time value is one (C,) pair — scatter a boolean touch mask (fast
    # 32-bit path) and merge the pair elementwise.
    _, S = planned.shape
    N = nodes.alive.shape[1]
    rows = jnp.arange(C, dtype=jnp.int32)[:, None]
    tgt_create = jnp.where(planned, st.ca_slots, N)
    touch_create = (
        jnp.zeros((C, N), bool).at[rows, tgt_create].set(True, mode="drop")
    )
    eff_up = _broadcast_pair(t_add(T, st.d_ca_up, interval), (C, N))
    create_time = t_where(
        touch_create, t_min(nodes.create_time, eff_up), nodes.create_time
    )
    tgt_remove = jnp.where(removed, st.ca_slots, N)
    touch_remove = (
        jnp.zeros((C, N), bool).at[rows, tgt_remove].set(True, mode="drop")
    )
    eff_down = _broadcast_pair(t_add(T, st.d_ca_down, interval), (C, N))
    remove_time = t_where(
        touch_remove, t_min(nodes.remove_time, eff_down), nodes.remove_time
    )

    metrics = metrics._replace(
        scaled_up_nodes=metrics.scaled_up_nodes + planned.sum(axis=1, dtype=jnp.int32),
        scaled_down_nodes=metrics.scaled_down_nodes + removed.sum(axis=1, dtype=jnp.int32),
    )
    auto = auto._replace(
        ca_count=auto.ca_count + planned_per_group - removed_per_group,
        ca_cursor=auto.ca_cursor + planned_per_group,
        ca_next=t_where(
            due, t_add(auto.ca_next, st.ca_interval, interval), auto.ca_next
        ),
    )
    state = state._replace(
        nodes=nodes._replace(create_time=create_time, remove_time=remove_time),
        metrics=metrics,
    )
    return state, auto
