# ktpu: hot-path
# ktpu: threaded
"""Streaming trace-ingestion pipeline: a bounded-memory feeder for the
superspan executor's staging slabs.

PR 3's double-buffered staging (`engine._prefetch_stage`) is the 2-deep
special case of the general mechanism this module provides: a PRODUCER
thread assembles refill-payload segments (`trace_compile.stage_segment`
via the engine's assemble callback) and `device_put`s them into a bounded
ring of at most K device-resident `state.RefillStage` slabs, running AHEAD
of the consumer — the engine's superspan dispatch loop — so a
stage-exhaustion exit finds the next slab already uploaded instead of
paying `stage_assemble` + `stage_put` on the span boundary's critical
path. This is the classic accelerator input pipeline (keep the device fed
from a producer that runs ahead of consumption), applied to the compiled
trace instead of training examples.

Memory bound: the pipeline holds at most K slabs of C x L columns on
device plus ONE segment being assembled on the host — O(K * C * L), not
O(trace length). A streaming engine never materializes the whole-trace
device slide payload (`engine._init_device_slide` is skipped), so
arbitrarily long traces stream through fixed-size staging state; see
docs/DESIGN.md §"Streaming ingestion pipeline" for the full formula and
the remaining host-side O(T) terms (the compiled payload source the
segment callbacks read — the native feeder's segment iteration,
`trace.feeder.WorkloadSegmentReader`, is the seam for bounding those
next).

Slab schedule. Stage geometry is STATIC (the slab width L is compiled
into the superspan program), so the producer does not need feedback to
know what to build: successive slabs advance by the deterministic stride

    stride = (L - W) - W//2

— exactly the lower bound `engine._prefetch_stage` derives for the
restage base of an exhaustion exit (the failed slide's shift is at most
W/2 and its refill columns crossed lo + L), so the scheduled successor
always covers the next restage point. A consumer whose ring ran empty
floors the schedule at its observed base (the non-streaming path's
miss-rebuild point). Minimal-width stages (L == W + W/2, stride 0) have
no headroom to predict into: there the producer runs DEMAND-driven —
builds exactly the slab the consumer's base asks for, reproducing the
old rebuild-at-base slab schedule (and hence its dispatch/sync counts)
with the assembly moved off the engine thread.

Spent slabs. A slab whose coverage the base has passed
(lo + L - W < base) is popped at the next `get_stage`; a slab the engine
explicitly retires after a SUPERSPAN_STAGE exit is popped immediately and
its lo recorded — `get_stage` asserts every served slab sits strictly
past the retired high-water mark, so the ring can NEVER re-offer a spent
slab (re-offering would spin the dispatch loop on an exhausted buffer —
the PR 3 bug class this pins down structurally). Moving the base
BACKWARDS (checkpoint restore, window growth) requires a re-seek: the
engine closes the feeder and builds a fresh one at the new base/geometry
(`engine._close_feeder`), so a restored run's slabs are rebuilt at the
restored base rather than replayed — slab content is a pure function of
(lo, width), which is why re-seek cannot diverge.

Stall accounting. The consumer-side wait for a covering slab is split
into the two causes a tuner needs to tell apart: `stage_wait_feeder`
(the producer has not PUBLISHED the slab yet — assembly/backlog bound;
raise the ring depth K or widen segments) vs `stage_wait_upload` (the
slab is published but its H2D transfer has not settled — PCIe/DMA bound;
wider segments amortize, deeper rings don't help). Both land on the
ENGINE's tracer (the wait happens on the engine thread); the producer's
own assembly/upload wall time is kept as plain counters here (the feeder
thread never touches the engine's single-threaded span ring).

This module carries the `# ktpu: hot-path` pragma: the lint host-sync
pass patrols it. Its one blocking primitive on device values —
`block_until_ready` on a freshly uploaded slab, HOST-to-device settle,
run on the FEEDER thread — carries an explicit waiver below; the feeder
never reads a device value back to the host.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Optional

from kubernetriks_tpu.batched.faults import (
    FeederProducerError,
    InjectedFeederKill,
)
from kubernetriks_tpu.telemetry import NULL_TRACER
from kubernetriks_tpu.telemetry.tracer import (
    PH_STAGE_WAIT_FEEDER,
    PH_STAGE_WAIT_UPLOAD,
)


class _Slot:
    """One ring entry: a device slab covering payload columns
    [lo, lo + L), plus the H2D settle event the producer sets once the
    upload has landed (the upload-wait half of the stall split)."""

    __slots__ = ("lo", "stage", "ready")

    def __init__(self, lo: int, stage, ready: threading.Event):
        self.lo = lo
        self.stage = stage
        self.ready = ready


def _settle_default(stage) -> None:
    """Block until the slab's H2D transfers have landed (feeder-thread
    call; host-to-device settle, not a device readback)."""
    import jax

    jax.block_until_ready(stage)  # ktpu: sync-ok(feeder thread H2D settle of a freshly uploaded staging slab — marks the upload-wait boundary, never reads device values back)


class StreamFeeder:
    """Bounded-ring producer of device-resident staging slabs.

    Parameters:
    - assemble(lo, width) -> host segment payload (numpy; the engine binds
      `trace_compile.stage_segment` over its compiled payload source).
    - upload(segment) -> device RefillStage (jnp.asarray + mesh placement;
      the engine binds its sharding-aware upload half).
    - base: first pod base the consumer will request (slab 0 lands here).
    - width/window: stage width L and pod window W (static geometry).
    - trace_cols: total payload columns (T + W incl. right padding) — a
      slab reaching them is the FINAL slab and the producer exits.
    - depth: ring capacity K (the memory bound); K = 1 degenerates to
      synchronous-but-off-thread staging and stays exact.
    - settle: H2D settle hook (tests inject a no-op for numpy slabs).
    - retired_lo: retired-slab high-water mark carried over from a dead
      predecessor — a SUPERVISOR restart (engine._restart_feeder) builds
      the replacement feeder with the old feeder's mark so the
      never-re-offer invariant spans restarts: the new ring starts empty
      but still refuses every slab the old ring already served spent.
    - chaos: optional `faults.HostChaos`; when armed, each produced slab
      first draws the feeder-kill channel and a hit raises
      `InjectedFeederKill` inside the producer thread (exercising the
      whole death -> FeederProducerError -> supervisor path).
    """

    def __init__(
        self,
        assemble: Callable[[int, int], dict],
        upload: Callable[[dict], object],
        *,
        base: int,
        width: int,
        window: int,
        trace_cols: int,
        depth: int = 3,
        settle: Optional[Callable[[object], None]] = _settle_default,
        retired_lo: int = -1,
        chaos=None,
    ) -> None:
        self._assemble = assemble
        self._upload = upload
        self._settle = settle
        self._chaos = chaos
        self.width = int(width)
        self.window = int(window)
        self.depth = max(1, int(depth))
        self.trace_cols = int(trace_cols)
        self.stride = self.width - self.window - self.window // 2
        # Run-ahead only works when the stride is positive — a slab must
        # cover strictly more bases than its predecessor for the schedule
        # to make progress. Minimal-width stages (L == W + W/2) have zero
        # slide headroom to predict into: the producer then runs
        # DEMAND-driven — it builds exactly the slab the consumer's base
        # asks for, off the engine thread, reproducing the non-streaming
        # path's rebuild-at-base miss behavior (and its slab schedule,
        # hence its dispatch counts) with the assembly moved off-thread.
        self.ahead = self.stride > 0

        self._cond = threading.Condition()
        self._ring: deque = deque()  # _Slot entries, strictly increasing lo
        self._next_lo = int(base)
        self._demand_lo = int(base)
        self._last_lo = -1  # highest slab lo ever published
        self._retired_lo = int(retired_lo)  # highest explicitly-retired lo
        self._served_lo = -1  # last slab lo handed to the consumer
        self._building_lo = -1  # slab the producer is currently building
        self._done = False  # producer published the final slab
        self._stop = False
        self._error: Optional[BaseException] = None

        # Stats (host ints; read under the lock or after close()).
        self.produced = 0
        self.spent_dropped = 0
        self.demand_fastforwards = 0
        self.ring_high_water = 0
        self._depth_sum = 0
        self._depth_samples = 0
        self.assemble_ns = 0
        self.upload_ns = 0
        self.settle_ns = 0
        self.stall_not_ready = 0
        self.stall_not_ready_ns = 0
        self.stall_upload = 0
        self.stall_upload_ns = 0

        self._thread = threading.Thread(
            target=self._produce, name="ktpu-stream-feeder", daemon=True
        )
        self._thread.start()

    # -- producer (feeder thread) -----------------------------------------

    def _produce(self) -> None:
        try:
            while True:
                with self._cond:
                    while not self._stop and (
                        len(self._ring) >= self.depth
                        or (
                            not self.ahead
                            and (
                                len(self._ring) > 0
                                or self._demand_lo <= self._last_lo
                            )
                        )
                    ):
                        self._cond.wait()
                    if self._stop:
                        return
                    if not self.ahead:
                        # Demand mode: build exactly the slab the
                        # consumer's base asks for (the ring is empty and
                        # the demand sits past everything already built —
                        # a retired slab's lo is never re-demanded, see
                        # get_stage's never-re-offer assert).
                        lo = self._demand_lo
                    else:
                        lo = self._next_lo
                        if not self._ring and self._demand_lo > lo:
                            # Starvation floor: with the ring empty and
                            # the consumer's base past the schedule, a
                            # scheduled slab would be dominated on arrival
                            # — fast-forward to the demanded base (the
                            # non-streaming path's miss-rebuild point).
                            lo = self._demand_lo
                            self.demand_fastforwards += 1
                    # Record what we are about to build so a death
                    # mid-build surfaces with its slab context
                    # (FeederProducerError.slab_lo).
                    self._building_lo = lo
                if self._chaos is not None and self._chaos.feeder_kill():
                    raise InjectedFeederKill(
                        f"host chaos: injected stream-feeder kill while "
                        f"building slab lo={lo}"
                    )
                # Build OUTSIDE the lock: assembly + upload are the slow
                # halves and must overlap the consumer's dispatches.
                t0 = time.perf_counter_ns()
                seg = self._assemble(lo, self.width)
                t1 = time.perf_counter_ns()
                stage = self._upload(seg)
                t2 = time.perf_counter_ns()
                slot = _Slot(lo, stage, threading.Event())
                with self._cond:
                    if self._stop:
                        return
                    self.assemble_ns += t1 - t0
                    self.upload_ns += t2 - t1
                    self._ring.append(slot)
                    self.produced += 1
                    self._last_lo = lo
                    if len(self._ring) > self.ring_high_water:
                        self.ring_high_water = len(self._ring)
                    self._next_lo = lo + max(self.stride, 1)
                    self._done = lo + self.width >= self.trace_cols
                    done = self._done
                    self._cond.notify_all()
                # Settle the H2D transfer before marking the slot ready:
                # a consumer that grabbed it meanwhile waits on the event
                # (the upload-wait half of the stall split).
                if self._settle is not None:
                    self._settle(slot.stage)
                    settle_ns = time.perf_counter_ns() - t2
                    with self._cond:
                        self.settle_ns += settle_ns
                slot.ready.set()
                if done:
                    return
        except BaseException as exc:  # propagate into the consumer
            with self._cond:
                self._error = exc
                # A consumer may already hold a published slab and be
                # blocked on its settle event (upload wait) — wake it so
                # the failure surfaces instead of hanging; get_stage
                # re-raises via _error on its next lock acquisition.
                for slot in self._ring:
                    slot.ready.set()
                self._cond.notify_all()

    # -- consumer (engine thread) ------------------------------------------

    def _producer_error(self) -> FeederProducerError:
        """Build the consumer-facing producer-death error with the slab
        context carried across the thread boundary (call under the
        lock): the slab index `lo` and payload span the producer was
        building when it died."""
        lo = self._building_lo  # ktpu: lock-ok(only called from get_stage while holding self._cond)
        span = (
            f"slab lo={lo} span=[{lo}, {lo + self.width})"
            if lo >= 0
            else "before the first slab"
        )
        return FeederProducerError(
            f"stream feeder producer failed ({span}): {self._error!r}",  # ktpu: lock-ok(only called from get_stage while holding self._cond)
            slab_lo=lo if lo >= 0 else None,
            width=self.width,
        )

    def retired_watermark(self) -> int:
        """Highest retired slab lo — the supervisor passes this as the
        replacement feeder's `retired_lo` so never-re-offer survives a
        restart."""
        with self._cond:
            return self._retired_lo

    def get_stage(self, base: int, tracer=NULL_TRACER):
        """Return (stage, lo, fresh) for the LARGEST-lo ring slab covering
        `base` (lo <= base and base - lo + W <= L; dominated predecessors
        pop as spent — the max-headroom rule), blocking until the
        producer publishes it; `fresh` is True the first time a slab is
        served (the engine's stage_refills accounting). Raises
        AssertionError if the ring would have to re-offer a spent/retired
        slab — the never-re-offer invariant — or if `base` moved backwards
        without a re-seek."""
        waited = False
        with self._cond:
            # Tell the producer where the consumer is: the next scheduled
            # slab never needs to start below the latest observed base (a
            # restage always lands at or past it).
            if base > self._demand_lo:
                self._demand_lo = base
                self._cond.notify_all()
            while True:
                if self._error is not None:
                    raise self._producer_error() from self._error
                # Drop slabs that can no longer cover any base >= `base`,
                # and DOMINATED slabs — a head whose successor also sits
                # at or below the base serves strictly less headroom than
                # that successor (the max-lo rule that mirrors the
                # non-streaming path's rebuild-at-base).
                while (
                    self._ring
                    and self._ring[0].lo + self.width - self.window < base
                ) or (len(self._ring) >= 2 and self._ring[1].lo <= base):
                    self._ring.popleft()
                    self.spent_dropped += 1
                    self._cond.notify_all()  # ring space freed
                if self._ring and self._ring[0].lo <= base:
                    slot = self._ring[0]
                    break
                if self._ring:  # head.lo > base: base moved backwards
                    raise AssertionError(
                        f"stream ring would re-offer below its head: "
                        f"requested base {base} precedes slab lo="
                        f"{self._ring[0].lo} — spent slabs are never "
                        "re-offered; re-seek the feeder (close + rebuild) "
                        "after moving the base backwards"
                    )
                if self._done:
                    raise AssertionError(
                        f"stream feeder exhausted the trace "
                        f"(trace_cols={self.trace_cols}) with base {base} "
                        "uncovered — stride/coverage invariant broken"
                    )
                # Slab not published yet: the feeder-not-ready stall.
                if not waited:
                    waited = True
                    t_wait = time.perf_counter_ns()
                self._cond.wait()
            if waited:
                dur = time.perf_counter_ns() - t_wait
                self.stall_not_ready += 1
                self.stall_not_ready_ns += dur
                tracer.end(PH_STAGE_WAIT_FEEDER, t_wait, dur=dur)
            assert slot.lo > self._retired_lo, (
                f"stream ring re-offered a retired slab (lo={slot.lo} <= "
                f"retired {self._retired_lo})"
            )
            fresh = slot.lo != self._served_lo
            self._served_lo = slot.lo
            self._depth_sum += len(self._ring)
            self._depth_samples += 1
        if not slot.ready.is_set():
            # Published but the H2D transfer has not settled: upload wait.
            t_wait = time.perf_counter_ns()
            slot.ready.wait()
            dur = time.perf_counter_ns() - t_wait
            with self._cond:
                self.stall_upload += 1
                self.stall_upload_ns += dur
                if self._error is not None:
                    # The settle failed — the event was set only so this
                    # wait could observe the failure, not a usable slab.
                    raise self._producer_error() from self._error
            tracer.end(PH_STAGE_WAIT_UPLOAD, t_wait, dur=dur)
        return slot.stage, slot.lo, fresh

    def retire(self, lo: int) -> None:
        """Drop the slab at `lo` after a SUPERSPAN_STAGE exhaustion exit
        and record it as spent — `get_stage` will assert rather than ever
        hand it out again (the exhausted slab may still COVER the final
        base; serving it again would spin the dispatch loop)."""
        with self._cond:
            if self._ring and self._ring[0].lo == lo:
                self._ring.popleft()
            if lo > self._retired_lo:
                self._retired_lo = lo
            self._cond.notify_all()

    def close(self, timeout: float = 30.0) -> bool:
        """Stop the producer and join it. Idempotent; the engine's re-seek
        (checkpoint restore, window growth) is close + rebuild. Returns
        False (with a warning) if the producer outlived the join timeout —
        it is mid-build on a huge segment; it will discard its slab at the
        stop check before publishing and exit on its own, but the caller
        should know the overlap happened."""
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        self._thread.join(timeout)
        if self._thread.is_alive():
            import logging

            logging.getLogger(__name__).warning(
                "stream feeder producer did not exit within %.0fs of "
                "close() (mid-build on a %d-column segment); it will "
                "discard the slab and exit at the next stop check",
                timeout,
                self.width,
            )
            return False
        return True

    # -- readout ------------------------------------------------------------

    def report(self) -> dict:
        """Feeder-side stats for engine.telemetry_report()['feeder']:
        production counters, the ring-depth gauge (mean + high-water vs
        capacity), producer wall time, and the stall split the consumer
        recorded."""
        with self._cond:
            depth_mean = (
                self._depth_sum / self._depth_samples
                if self._depth_samples
                else 0.0
            )
            return {
                "slabs_produced": self.produced,
                "spent_dropped": self.spent_dropped,
                "demand_fastforwards": self.demand_fastforwards,
                "ring_capacity": self.depth,
                "ring_depth_high_water": self.ring_high_water,
                "ring_depth_mean": round(depth_mean, 3),
                "segment_cols": self.width,
                "stride_cols": self.stride,
                "trace_cols": self.trace_cols,
                "assemble_ms": round(self.assemble_ns / 1e6, 3),
                "upload_ms": round(self.upload_ns / 1e6, 3),
                "settle_ms": round(self.settle_ns / 1e6, 3),
                "stalls": {
                    "feeder_not_ready": {
                        "count": self.stall_not_ready,
                        "ms": round(self.stall_not_ready_ns / 1e6, 3),
                    },
                    "upload_wait": {
                        "count": self.stall_upload,
                        "ms": round(self.stall_upload_ns / 1e6, 3),
                    },
                },
            }


class LaneTraceMux:
    """Per-lane workload multiplexer over the compiled trace slab — the
    full-resident analog of `trace.feeder.WorkloadSegmentReader`'s
    row-range contract (the PayloadSource seam), turned 90 degrees: where
    the streaming feeder offers every lane the SAME row window of an
    unbounded trace, the mux offers each lane its OWN row-range of the
    resident slab, so a lane-async fleet can replay a workload subset per
    query without recompiling anything (the masked rows are pure data).

    Semantics (`offer(lane, lo, hi)`): slab rows [lo, hi) of the lane are
    the kept range. Plain-pod CREATE events outside it are masked to
    EV_NONE IN PLACE — `win` stays untouched, so the per-lane time sort
    the event loop's searchsorted gathers rely on is preserved — and pod
    REMOVE events are masked by SLOT membership: a remove whose slot's
    create was masked is masked too (never a remove without its create),
    while a remove of a slot the slab never creates (pre-existing pods)
    is always kept. Node and chaos events are never masked: cluster shape
    and fault streams belong to the scenario vectors, not the workload
    range.

    Never-re-offer (per lane): `offer` REFUSES a lane whose previous
    range is still flying — the engine retires a lane's range at its
    reset boundary (`engine.lane_reset` -> `retire`), exactly like the
    feeder ring's retired-slab high-water mark refuses to re-serve a
    spent slab. Mutating an in-flight lane's rows would change history
    the scan carry already consumed.

    Host-only: the mux owns a host copy of the packed slab and returns
    host row blocks; the ENGINE owns the device install
    (`engine.set_lane_trace`, a data-only dynamic_update_slice at the
    reseed host-block boundary — zero new steady-state syncs).
    """

    def __init__(self, packed) -> None:
        import numpy as np

        base = np.array(packed, np.int32)  # ktpu: sync-ok(mux construction: one owned host copy of the freshly built slab, never on the steady-state path)
        if base.ndim != 3 or base.shape[-1] != 4:
            raise ValueError(
                f"LaneTraceMux wants a (C, E, 4) packed slab, got {base.shape}"
            )
        self._base = base
        C = base.shape[0]
        self._flying = [False] * C  # offer outstanding (not yet retired)
        self._installed = [None] * C  # last (lo, hi) served per lane
        self.offers = 0

    @property
    def n_rows(self) -> int:
        return self._base.shape[1]

    def offer(self, lane: int, lo: int = 0, hi: Optional[int] = None):
        """Masked host row block (E, 4) for `lane`, or None when the lane
        already has exactly this range installed (the caller skips the
        device update). Raises on a re-offer to a lane whose previous
        range was never retired."""
        import numpy as np

        from kubernetriks_tpu.batched.state import (
            EV_CREATE_POD,
            EV_NONE,
            EV_REMOVE_POD,
        )

        E = self._base.shape[1]
        hi = E if hi is None else int(hi)
        lo = int(lo)
        if not (0 <= lo <= hi <= E):
            raise ValueError(
                f"lane {lane}: trace row-range [{lo}, {hi}) outside [0, {E})"
            )
        if self._flying[lane]:
            raise RuntimeError(
                f"lane {lane}: trace rows re-offered while its previous "
                "range is still flying — retire the lane (lane_reset) "
                "before re-seeding (never-re-offer invariant)"
            )
        self._flying[lane] = True
        self.offers += 1
        if self._installed[lane] == (lo, hi):
            return None
        self._installed[lane] = (lo, hi)
        rows = self._base[lane].copy()
        kind = rows[:, 2]
        slot = rows[:, 3]
        is_create = kind == EV_CREATE_POD
        is_remove = kind == EV_REMOVE_POD
        if not bool(is_create.any()):
            return rows
        in_range = np.zeros((E,), bool)
        in_range[lo:hi] = True
        n_slots = int(slot[is_create | is_remove].max()) + 1
        created = np.zeros((n_slots,), bool)
        created[slot[is_create]] = True
        kept = np.zeros((n_slots,), bool)
        kept[slot[is_create & in_range]] = True
        drop = (is_create & ~in_range) | (
            is_remove & created[slot] & ~kept[slot]
        )
        rows[drop, 2] = EV_NONE
        return rows

    def retire(self, lanes) -> None:
        """Mark lanes' offered ranges as consumed (reset boundary): the
        next offer for them is legal again."""
        for lane in lanes:
            self._flying[int(lane)] = False

    def report(self) -> dict:
        return {
            "offers": self.offers,
            "installed": {
                lane: rng
                for lane, rng in enumerate(self._installed)
                if rng is not None
            },
        }
