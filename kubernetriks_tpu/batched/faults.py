# ktpu: threaded
"""Fault domain for the serving fleet: typed query outcomes + host chaos.

The lane-async fleet (fleet.py) turns the batched engine into a serving
host, and a serving host needs failure SEMANTICS, not just failure
propagation: the unit of failure must be a query or a lane, never the
fleet. This module owns the two halves of that contract:

- **The `QueryError` taxonomy** — terminal typed outcomes delivered
  *through* `ScenarioFleet.poll()` exactly like `FleetResult`s (the
  stream-once contract is preserved: every submitted qid streams exactly
  one terminal outcome, result or error). Clients discriminate with the
  shared `.ok` / `.kind` protocol — `FleetResult.ok is True`, every
  error's `.ok is False` — so a poll loop never needs isinstance
  ladders. Errors are real `Exception` subclasses: the same class is
  *raised* where no query exists to carry it (e.g. `submit()` after
  `close()` raises `ShutdownError`) and *streamed* where one does.

- **`HostChaos`** — a deterministic host-fault injector built on the
  same counter-based threefry derivation as the in-simulation chaos
  engine (`chaos.object_uniforms`): every decision is a pure function of
  (seed, stream, counter), so a pinned seed replays the exact same fault
  schedule on every run and platform. It claims host-side stream ids
  disjoint from the device chaos streams (1-3). Dispatch-fault victims
  are the LEAST-FAULTED active lane (ties to the lowest index), so a
  run long enough to hit N faults provably faults min(N, n_lanes)
  distinct lanes even while the active set churns — lane coverage by
  construction, not by luck.

Thread story (`# ktpu: threaded`): `HostChaos` is called from the fleet
pump loop AND from the stream-feeder producer thread (feeder kills), so
all mutable state (`_counters`, `_victim_counts`, `events`) lives under
`self._lock`; the feederlock lint pass patrols exactly that. The
derivation call itself happens outside the lock — nothing blocking is
ever held under it.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional, Sequence

from .. import chaos as _chaos

# Host-side chaos streams — disjoint from the device chaos streams
# (STREAM_NODE=1, STREAM_GROUP=2, STREAM_POD=3 in chaos.py).
STREAM_HOST_DISPATCH = 11
STREAM_HOST_FEEDER = 12
STREAM_HOST_STALL = 13


# --- typed query outcomes ----------------------------------------------------


class QueryError(Exception):
    """Terminal typed outcome for one query, streamed via `poll()`.

    Mirrors the `FleetResult` readout protocol: `.query`, `.lane`,
    `.horizon`, `.scenario` where known, plus `.ok is False` and a
    stable `.kind` string for JSON-friendly counting.
    """

    kind = "query_error"
    ok = False

    def __init__(
        self,
        query: int,
        message: str,
        *,
        lane: int = -1,
        scenario=None,
        horizon=None,
    ) -> None:
        super().__init__(message)
        self.query = int(query)
        self.message = message
        self.lane = int(lane)
        self.scenario = scenario
        self.horizon = horizon


class RejectedError(QueryError):
    """Refused at admission (bounded queue full, policy='reject').

    Carries a `retry_after_s` hint derived from the observed service
    rate, so an open-loop client can back off intelligently.
    """

    kind = "rejected"

    def __init__(self, query, message, *, retry_after_s=None, **kw) -> None:
        super().__init__(query, message, **kw)
        self.retry_after_s = retry_after_s


class DeadlineExceededError(QueryError):
    """Deadline passed while queued — failed WITHOUT occupying a lane."""

    kind = "deadline_exceeded"

    def __init__(self, query, message, *, deadline_s=None, late_s=None, **kw):
        super().__init__(query, message, **kw)
        self.deadline_s = deadline_s
        self.late_s = late_s


class LaneFaultError(QueryError):
    """The occupying lane's dispatch failed; the lane was crash-reset
    from the pristine snapshot and only THIS query died."""

    kind = "lane_fault"

    def __init__(self, query, message, *, cause=None, **kw) -> None:
        super().__init__(query, message, **kw)
        # repr, not the exception object: errors outlive the engine and
        # must stay picklable / JSON-summarizable.
        self.cause = cause if isinstance(cause, str) else repr(cause)


class FeederError(QueryError):
    """The stream-feeder producer died under this query's lanes; carries
    the originating slab context from `FeederProducerError`."""

    kind = "feeder"

    def __init__(self, query, message, *, slab_lo=None, restarts=None, **kw):
        super().__init__(query, message, **kw)
        self.slab_lo = slab_lo
        self.restarts = restarts


class ShutdownError(QueryError):
    """Queued at `close()` — the graceful drain finishes in-flight work
    but fails what never reached a lane. Also RAISED by `submit()` after
    close (no qid exists to stream it under)."""

    kind = "shutdown"


# --- low-level fault carriers (not query outcomes) ---------------------------


class FeederProducerError(RuntimeError):
    """Stream-feeder producer death with slab context preserved across
    the thread boundary: the slab index (`slab_lo`) and payload span
    (`[slab_lo, slab_lo + width)`) the producer was building when it
    died. `stream.StreamFeeder.get_stage` raises this; the engine's
    feeder supervisor catches it and decides restart vs `FeederError`."""

    def __init__(self, message, *, slab_lo=None, width=None) -> None:
        super().__init__(message)
        self.slab_lo = slab_lo
        self.width = width


class InjectedFault(RuntimeError):
    """Raised by `HostChaos` at a dispatch boundary in place of the real
    dispatch; `.lane` names the victim so isolation stays per-lane."""

    def __init__(self, message, *, lane=None) -> None:
        super().__init__(message)
        self.lane = lane


class InjectedFeederKill(RuntimeError):
    """Raised inside the stream-feeder producer thread by `HostChaos`."""


# --- deterministic host-fault injector ---------------------------------------

_CHAOS_DEFAULTS = dict(
    seed=7, dispatch=0.04, feeder=0.05, stall=0.03, stall_ms=2.0
)


class HostChaos:
    """Counter-seeded host-fault injector (threefry, like chaos.py).

    Each channel draws from its own (stream, counter) sequence, so the
    fault schedule is a pure function of the seed and the deterministic
    call sequence — independent of wall clock, thread timing (each draw
    atomically claims its counter under the lock) and platform.
    """

    def __init__(
        self,
        seed: int = 7,
        *,
        dispatch_rate: float = 0.0,
        feeder_rate: float = 0.0,
        stall_rate: float = 0.0,
        stall_ms: float = 2.0,
    ) -> None:
        self.seed = int(seed)
        self.dispatch_rate = float(dispatch_rate)
        self.feeder_rate = float(feeder_rate)
        self.stall_rate = float(stall_rate)
        self.stall_ms = float(stall_ms)
        self._lock = threading.Lock()
        self._counters: Dict[int, int] = {}
        self._victim_counts: Dict[int, int] = {}
        self.events: Dict[str, int] = {
            "draws": 0,
            "dispatch_faults": 0,
            "feeder_kills": 0,
            "stalls": 0,
        }

    # -- flag parsing --------------------------------------------------------

    @classmethod
    def from_flag(cls, spec: Optional[str]) -> Optional["HostChaos"]:
        """Build from a `KTPU_HOST_CHAOS` value. None/falsy -> None
        (injection OFF — the fleet takes the exact pre-chaos code path).
        '1'/'true'/'on' -> documented defaults; otherwise a 'k=v,k=v'
        spec with keys seed, dispatch, feeder, stall, stall_ms."""
        if spec is None:
            return None
        text = str(spec).strip()
        if text.lower() in ("", "0", "false", "no", "off"):
            return None
        params = dict(_CHAOS_DEFAULTS)
        if text.lower() not in ("1", "true", "yes", "on"):
            for item in text.split(","):
                item = item.strip()
                if not item:
                    continue
                if "=" not in item:
                    raise ValueError(
                        f"KTPU_HOST_CHAOS: bad item {item!r} (expected "
                        "'key=value' with keys "
                        f"{sorted(_CHAOS_DEFAULTS)}, or '1' for defaults)"
                    )
                key, _, value = item.partition("=")
                key = key.strip()
                if key not in _CHAOS_DEFAULTS:
                    raise ValueError(
                        f"KTPU_HOST_CHAOS: unknown key {key!r} (expected "
                        f"one of {sorted(_CHAOS_DEFAULTS)})"
                    )
                params[key] = float(value)
        return cls(
            seed=int(params["seed"]),
            dispatch_rate=params["dispatch"],
            feeder_rate=params["feeder"],
            stall_rate=params["stall"],
            stall_ms=params["stall_ms"],
        )

    # -- channels ------------------------------------------------------------

    def _draw(self, stream: int) -> float:
        with self._lock:
            counter = self._counters.get(stream, 0)
            self._counters[stream] = counter + 1
            self.events["draws"] += 1
        u, _ = _chaos.object_uniforms(self.seed, stream, 0, 0, counter)
        return float(u)

    def dispatch_fault(self, active_lanes: Sequence[int]) -> Optional[int]:
        """One draw per dispatch attempt; on a hit, the victim is the
        LEAST-faulted active lane (ties break to the lowest index) — a
        plain round-robin over the momentary active list would re-fault
        the same lanes whenever the set shrinks mid-run. Returns the
        victim lane or None."""
        lanes = sorted(int(v) for v in active_lanes)
        if not lanes or self.dispatch_rate <= 0.0:
            return None
        if self._draw(STREAM_HOST_DISPATCH) >= self.dispatch_rate:
            return None
        with self._lock:
            victim = min(
                lanes, key=lambda v: (self._victim_counts.get(v, 0), v)
            )
            self._victim_counts[victim] = (
                self._victim_counts.get(victim, 0) + 1
            )
            self.events["dispatch_faults"] += 1
        return victim

    def feeder_kill(self) -> bool:
        """One draw per produced slab (called from the producer thread)."""
        if self.feeder_rate <= 0.0:
            return False
        hit = self._draw(STREAM_HOST_FEEDER) < self.feeder_rate
        if hit:
            with self._lock:
                self.events["feeder_kills"] += 1
        return hit

    def stall_s(self) -> float:
        """Slow-lane stall: seconds to sleep before this dispatch (0.0
        almost always). Exercises the latency/SLO paths, not failures."""
        if self.stall_rate <= 0.0:
            return 0.0
        if self._draw(STREAM_HOST_STALL) >= self.stall_rate:
            return 0.0
        with self._lock:
            self.events["stalls"] += 1
        return self.stall_ms / 1e3

    def report(self) -> Dict:
        with self._lock:
            events = dict(self.events)
        return {
            "seed": self.seed,
            "rates": {
                "dispatch": self.dispatch_rate,
                "feeder": self.feeder_rate,
                "stall": self.stall_rate,
            },
            "events": events,
        }
