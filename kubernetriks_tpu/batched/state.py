"""Dense array state for the batched (vectorized) simulation path.

This is the TPU-native reformulation of the reference's actor state
(reference: src/core/{api_server,persistent_storage,scheduler,node_component}.rs
hold overlapping per-object maps; here the consistent merged view lives in
arrays of shape (clusters, nodes) / (clusters, pods)).

Design rules:
- Static shapes: N_max node slots and P_max pod slots per cluster, pre-sized
  from the trace like the reference's node pool (reference: src/simulator.rs:51-65).
- All payloads (capacities, requests, durations) are pre-staged per slot at
  trace-compile time; on-device events only flip phases/masks. Strings never
  reach the device.
- cpu is int32 millicores; ram is quantized to RAM_UNIT-byte units (ceil for
  requests, floor for capacity) so int32 never overflows and the batched path
  never overcommits relative to the byte-exact scalar path.
- Simulation time is the (win:int32, off:float32) window-indexed pair of
  batched/timerep.py: exact integer window classification plus a bounded
  float32 offset (ulp ≈ 1e-6 s at the default 10 s interval, three orders of
  magnitude under the smallest modeled delay) — full fidelity at
  Alibaba-scale timestamps without any 64-bit array in the hot loop.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax

# NOTE: importing this module enables jax_enable_x64 PROCESS-WIDE (a hard
# requirement of the batched subsystem, not an accident). The hot loop is
# all-32-bit by design (timerep.py pairs), but two cold spots still want
# 64-bit types: the HPA load-curve lookup evaluates elapsed time in f64
# (tiny (C, G)-shaped elementwise math), and the conditional-move wake
# budgets accumulate in i64 (unbounded in the scalar oracle). Tests also
# compare device output against the float64 scalar oracle.
jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from kubernetriks_tpu.batched.timerep import (  # noqa: E402
    TPair,
    from_f64_np,
    t_inf,
    t_zeros,
)

# Pod phases.
PHASE_EMPTY = 0  # slot not yet created
PHASE_QUEUED = 1  # in the scheduler's active queue
PHASE_UNSCHEDULABLE = 2  # parked in the unschedulable queue
PHASE_RUNNING = 3  # bound to a node (incl. binding in flight)
PHASE_SUCCEEDED = 4
PHASE_REMOVED = 5
PHASE_FAILED = 6

# Event kinds in the compiled trace slab.
EV_NONE = 0
EV_CREATE_NODE = 1
EV_REMOVE_NODE = 2
EV_CREATE_POD = 3
EV_REMOVE_POD = 4
# Chaos engine (chaos.py): a crash is EV_REMOVE_NODE semantics plus fault
# accounting (the slot's pre-staged crash_downtime folds into the downtime
# metric); a recovery is EV_CREATE_NODE semantics on a FRESH slot (slots are
# never reused) plus the recovery counter.
EV_NODE_CRASH = 5
EV_NODE_RECOVER = 6

DEFAULT_RAM_UNIT = 1024 * 1024  # 1 MiB

INF = jnp.inf


class NodeArrays(NamedTuple):
    """(C, N) per-node-slot arrays."""

    alive: jnp.ndarray  # bool
    cap_cpu: jnp.ndarray  # int32 millicores
    cap_ram: jnp.ndarray  # int32 ram units
    alloc_cpu: jnp.ndarray  # int32
    alloc_ram: jnp.ndarray  # int32
    # Pending on-device effects (cluster-autoscaler actions); +inf = none.
    create_time: TPair
    remove_time: TPair
    # Pre-staged chaos payload: the sampled repair span of the slot's crash
    # event (each slot crashes at most once — recovery opens a fresh slot);
    # 0 on slots that never crash. Folded into node_downtime_s when
    # EV_NODE_CRASH applies.
    crash_downtime: jnp.ndarray  # float32 seconds


class PodArrays(NamedTuple):
    """(C, P) per-pod-slot arrays."""

    phase: jnp.ndarray  # int32
    req_cpu: jnp.ndarray  # int32 millicores
    req_ram: jnp.ndarray  # int32 ram units
    # Static running duration as a time pair; win < 0 marks a long-running
    # service (the scalar path's running_duration=None).
    duration: TPair
    queue_ts: TPair  # queue-priority / eligibility timestamp
    queue_seq: jnp.ndarray  # int32: FIFO tie-break within equal timestamps
    initial_attempt_ts: TPair
    attempts: jnp.ndarray  # int32
    node: jnp.ndarray  # int32 node slot, -1 = none
    start_time: TPair
    finish_time: TPair  # +inf = no pending finish
    removal_time: TPair  # pending HPA scale-down effect; +inf = none
    # HPA replica index of the slot's CURRENT occupant ("{group}_{idx}"
    # names; -1 = not an HPA replica). Set at activation; the scale-down
    # victim selection pops the lexicographically-smallest name from it
    # (kube_horizontal_pod_autoscaler.rs:197-205).
    hpa_idx: jnp.ndarray  # int32
    # Chaos engine (CrashLoopBackOff): completed failure count, and whether
    # the CURRENT running attempt fails at finish_time (drawn at commit from
    # the counter PRNG on (cluster, global slot, restarts)). Inert zeros
    # when fault injection is off.
    restarts: jnp.ndarray  # int32
    will_fail: jnp.ndarray  # bool


class EstArrays(NamedTuple):
    """(C,) streaming estimator accumulators -> min/max/mean/variance at readout
    (mirrors the scalar Estimator, kubernetriks_tpu/metrics/collector.py)."""

    count: jnp.ndarray  # int32
    total: jnp.ndarray  # float32 sum
    total_sq: jnp.ndarray  # float32 sum of squares
    minimum: jnp.ndarray  # float32
    maximum: jnp.ndarray  # float32

    @staticmethod
    def zeros(shape) -> "EstArrays":
        return EstArrays(
            count=jnp.zeros(shape, jnp.int32),
            total=jnp.zeros(shape, jnp.float32),
            total_sq=jnp.zeros(shape, jnp.float32),
            minimum=jnp.full(shape, INF, jnp.float32),
            maximum=jnp.full(shape, -INF, jnp.float32),
        )

    def add(self, value: jnp.ndarray, mask: jnp.ndarray) -> "EstArrays":
        value = value.astype(jnp.float32)
        return EstArrays(
            count=self.count + mask.astype(jnp.int32),
            total=self.total + jnp.where(mask, value, 0.0),
            total_sq=self.total_sq + jnp.where(mask, value * value, 0.0),
            minimum=jnp.where(mask, jnp.minimum(self.minimum, value), self.minimum),
            maximum=jnp.where(mask, jnp.maximum(self.maximum, value), self.maximum),
        )


class MetricArrays(NamedTuple):
    """(C,) per-cluster counters (mirrors AccumulatedMetrics)."""

    pods_succeeded: jnp.ndarray  # int32
    pods_removed: jnp.ndarray  # int32
    terminated_pods: jnp.ndarray  # int32
    processed_nodes: jnp.ndarray  # int32
    scheduling_decisions: jnp.ndarray  # int32: successful assignments (bench metric)
    scaled_up_pods: jnp.ndarray  # int32 (HPA)
    scaled_down_pods: jnp.ndarray  # int32 (HPA)
    scaled_up_nodes: jnp.ndarray  # int32 (CA)
    scaled_down_nodes: jnp.ndarray  # int32 (CA)
    # Replicas an HPA cycle wanted but could not activate because the
    # group's slot reserve had no reusable slot (autoscale.py "Remaining
    # bounded deviations"); nonzero means the run diverged from the scalar
    # trajectory and the engine raises loudly at readout
    # (engine.check_autoscaler_bounds) instead of reporting wrong counts.
    hpa_reserve_clamped: jnp.ndarray  # int32
    # CA scale-up open attempts blocked ONLY by the consumed (never
    # reclaimed) slot reserve while the group had quota headroom and a
    # fitting template — the CA-side silent divergence, same loud-readout
    # treatment.
    ca_reserve_starved: jnp.ndarray  # int32
    # Chaos-engine fault counters (mirroring the scalar AccumulatedMetrics
    # additions): crashes/recoveries applied, summed sampled repair spans,
    # crash-caused pod reschedules, CrashLoopBackOff requeues, and pods
    # permanently failed past the restart limit.
    node_crashes: jnp.ndarray  # int32
    node_recoveries: jnp.ndarray  # int32
    node_downtime_s: jnp.ndarray  # float32
    pod_interruptions: jnp.ndarray  # int32
    pod_restarts: jnp.ndarray  # int32
    pods_failed: jnp.ndarray  # int32
    queue_time: EstArrays
    algo_latency: EstArrays
    pod_duration: EstArrays


class ClusterBatchState(NamedTuple):
    """Complete batched simulation state; a pytree of arrays with leading
    cluster axis C, shardable across a device mesh on that axis."""

    time: jnp.ndarray  # (C,) int32 last completed window index
    queue_seq_counter: jnp.ndarray  # (C,) int32 next queue sequence number
    event_cursor: jnp.ndarray  # (C,) int32 next unapplied trace event
    # First GLOBAL pod slot covered by the device pod arrays (sliding pod
    # window; 0 and never advanced when the window is the whole trace).
    pod_base: jnp.ndarray  # (C,) int32
    last_flush_win: jnp.ndarray  # (C,) int32 last unschedulable-leftover flush window
    requeue_signal: jnp.ndarray  # (C,) bool: node-add/pod-finish since last cycle
    # (Conditional-move wake budgets are NOT state: they are intra-window
    # WakeEvents threaded from event application to the same window's
    # prepare_cycle — step._conditional_wake_exact.)
    nodes: NodeArrays
    pods: PodArrays
    metrics: MetricArrays
    # Dynamic autoscaler state (AutoscaleState) or None when autoscaling is off.
    auto: Optional[NamedTuple] = None
    # Device-side per-window telemetry ring (TelemetryRing) or None when
    # telemetry is off — None compiles programs identical to the
    # pre-telemetry build, the same structural-static trick `auto` and
    # `fault_params` use.
    telemetry: Optional[TelemetryRing] = None


# Column layout of the device-side telemetry ring (TelemetryRing.buf).
# All int32: per-window aggregates cheap to fold from state the window body
# already holds — no new reductions over the trace slab, no float state.
TELEM_WINDOW = 0  # window index this record describes
TELEM_DECISIONS = 1  # scheduling decisions committed this window
TELEM_QUEUED = 2  # active-queue depth after the cycle
TELEM_UNSCHED = 3  # unschedulable-queue depth (failed fits parked)
TELEM_HPA_PODS = 4  # HPA pod actions this window (scale-ups + scale-downs)
TELEM_CA_NODES = 5  # CA node actions this window (scale-ups + scale-downs)
TELEM_FAULTS = 6  # chaos events this window (crashes/recoveries/retries/fails)
TELEM_ALIVE_NODES = 7  # alive node count after the window
# Capacity-observatory occupancy gauges (telemetry/observatory.py): the
# reserve consumptions whose exhaustion kills a long run (ROADMAP #2),
# folded from tiny (C, G)/(C,) state the window body already holds — no
# reductions over the trace slab or pod axis beyond what the record
# already pays, zeros when autoscaling is off.
TELEM_HPA_RESERVE = 8  # live HPA replicas across groups (hpa_tail - hpa_head)
TELEM_CA_RESERVE = 9  # CA reserve slots consumed across groups (ca_cursor:
# monotone without reclaim; LIVE occupancy under KTPU_RECLAIM, where the
# compaction pulls the cursor back — the watchdog fits the NET slope)
# Plain-trace refill columns the device pod window has NOT yet covered
# (trace_pod_bound - pod_base - plain window width). Values at or above
# telemetry/observatory.UNBOUNDED_SENTINEL mean "no sliding window /
# whole trace resident" (the trace_pod_bound default is a huge sentinel).
TELEM_POD_HEADROOM = 10
# Lane-asynchronous fleet (batched/fleet.py lane_async mode): 1 when this
# lane was ACTIVE for the window (its per-lane clock placed the global
# window inside [lane_clock, lane_clock + lane_horizon)), else 0. Always 1
# outside lane-async builds. The observatory folds the column into the
# lane-occupancy gauge and the idle-lane-waste verdict; in lane-async mode
# the TELEM_WINDOW column records the GLOBAL window index (uniform across
# lanes — ring.merge_snapshot keys on it), while every other column is the
# lane's own (virtual-clock) value.
TELEM_LANE_ACTIVE = 11
TELEMETRY_COLS = 12


class TelemetryRing(NamedTuple):
    """(C, R, TELEMETRY_COLS) device-side per-window metrics ring.

    Carried inside ClusterBatchState like `auto`: None (telemetry off)
    compiles programs identical to the pre-telemetry build; when present,
    every executed window scatters ONE record row per cluster at
    `cursor % R` and bumps the cursor — the ring accumulates on device and
    is drained host-side only at boundaries where the host already blocks
    (engine step_until_time exit / readout), never inside the dispatch
    loop, so telemetry-on adds zero new host syncs (the dispatch-count
    regression gate in tests/test_telemetry.py pins this).

    Unwritten rows carry window = -1 (the drain filters on it); a cursor
    past R means early windows wrapped out — the engine's pressure-based
    drain keeps long runs lossless by snapshotting before the wrap."""

    buf: jnp.ndarray  # (C, R, TELEMETRY_COLS) int32
    cursor: jnp.ndarray  # (C,) int32 total windows recorded (slot = cursor % R)


def strip_telemetry(state: "ClusterBatchState") -> "ClusterBatchState":
    """The state minus its telemetry ring — the comparison view for the
    telemetry-on vs telemetry-off bit-identity gate (the ring is the ONE
    leaf allowed to differ: it only exists on one side)."""
    return state._replace(telemetry=None)


class RefillStage(NamedTuple):
    """Device-resident staging slab for the superspan executor
    (step.run_superspan): refill payload columns [lo, lo + L) of the trace's
    PLAIN pod segment — requests, duration pairs, create windows and (under
    autoscalers) name ranks — pre-assembled host-side
    (trace_compile.stage_segment) and consumed by on-device window slides.
    Columns past the trace's plain segment carry the fresh-slot padding the
    host refill path produces (req 0, service-sentinel duration, no-create
    window), so a stage sliced anywhere near the trace end is still exact.
    `rank` is None when no autoscale statics exist (the pytree structure is
    part of the compiled program's identity, like every other None static).

    The engine keeps at most two stages alive: the one the in-flight
    superspan reads and the double-buffered successor assembled while the
    device runs (engine._prefetch_stage). An engine whose full slide payload
    fits the device budget wraps it as one whole-trace stage (lo = 0) and
    never restages."""

    req_cpu: jnp.ndarray  # (C, L) int32 millicores
    req_ram: jnp.ndarray  # (C, L) int32 ram units
    dur_win: jnp.ndarray  # (C, L) int32 duration pair (win < 0 = service)
    dur_off: jnp.ndarray  # (C, L) float32 duration pair offset
    create_win: jnp.ndarray  # (C, L) int32 create-event window; INT32_MAX = none
    rank: Optional[jnp.ndarray] = None  # (C, L) int32 lexicographic name ranks


class TraceSlab(NamedTuple):
    """(C, E) compiled trace events, time-sorted per cluster, padded with
    EV_NONE/time=+inf (win=INF_WIN).

    Columns are stored PACKED — (C, E, 4) int32 [win, off-bits, kind, slot] —
    and ONLY packed: the hot event loop gathers one (C, chunk, 4) slice
    instead of four separate (C, chunk) gathers (gather cost is per-index,
    not per-byte, on TPU), and the slab — the one component that still
    scales with trace length — carries no duplicate device memory."""

    packed: jnp.ndarray  # (C, E, 4) int32 [win, off-bits, kind, slot]

    @staticmethod
    def build(win, off, kind, slot) -> "TraceSlab":
        win = jnp.asarray(win, jnp.int32)
        off = jnp.asarray(off, jnp.float32)
        kind = jnp.asarray(kind, jnp.int32)
        slot = jnp.asarray(slot, jnp.int32)
        packed = jnp.stack(
            [win, jax.lax.bitcast_convert_type(off, jnp.int32), kind, slot],
            axis=-1,
        )
        return TraceSlab(packed=packed)


class StepConstants(NamedTuple):
    """Static per-run scalars derived from SimulationConfig; the control-plane
    hop delays of the scalar path composed into effective offsets
    (reference chains: SURVEY.md §3.2/3.4)."""

    scheduling_interval: float
    time_per_node: float  # scheduler latency model (reference: model.rs 1us)
    delta_pod_enqueue: float  # create -> pod in scheduler queue
    delta_bind_start: float  # assignment (incl. cycle duration) -> pod starts
    delta_reschedule: float  # node removal -> its pods re-enqueued
    flush_interval: float  # 30 s (reference: queue.rs:11)
    max_unschedulable_stay: float  # 300 s (reference: queue.rs:8)
    # Segmented pod layout (sliding window + resident pod-group tail): global
    # pod slots < trace_pod_bound are plain trace pods, mapped to device slots
    # by subtracting the per-cluster pod_base; slots >= trace_pod_bound are
    # resident pod-group ring slots, mapped by subtracting resident_shift.
    # Defaults (bound = huge, shift = 0) make the mapping the identity for
    # full-resident runs. np.int32 so the traced scalars stay 32-bit under
    # jax_enable_x64.
    trace_pod_bound: np.int32 = np.int32(1 << 30)
    resident_shift: np.int32 = np.int32(0)
    # Scenario-vector fleet (batched/fleet.py): per-cluster pod-fault PRNG
    # seeds, (C,) uint32, or None (the default — programs identical to the
    # pre-fleet build; the chaos draw then keys on the jit-static
    # FaultParams.seed plus the cluster index). When set, each lane's
    # draws key on (seed[c], cluster=0, slot, attempt): a lane's fault
    # stream is then a pure function of its SCENARIO, not its lane index,
    # which is what makes lane placement permutation-invariant and lane c
    # bit-identical to a standalone run with that seed. Traced data — a
    # fleet can re-seed lanes between queries without recompiling.
    fault_seed: Optional[jnp.ndarray] = None
    # Lane-asynchronous fleet (engine lane_async=True): per-lane window
    # clocks. A lane's VIRTUAL window for global window W is W -
    # lane_clock[c]; the lane is active while 0 <= W - lane_clock[c] <
    # lane_horizon[c], and the window body freezes (reverts) every state
    # leaf of inactive lanes so a finished lane parks bit-exactly at its
    # final state until the host re-seeds it in place (engine
    # set_lane_plan — traced data, so a reseed never recompiles). None
    # (the default) keeps programs identical to the wave-aligned build.
    lane_clock: Optional[jnp.ndarray] = None  # (C,) int32 global start window
    lane_horizon: Optional[jnp.ndarray] = None  # (C,) int32 windows to run


def make_step_constants(config) -> StepConstants:
    """Compose effective delays from the six config delays, mirroring the event
    chains of the scalar path (SURVEY.md §3.2: eleven hops pod lifecycle)."""
    return StepConstants(
        scheduling_interval=config.scheduling_cycle_interval,
        time_per_node=1e-6,
        delta_pod_enqueue=config.as_to_ps_network_delay
        + config.ps_to_sched_network_delay,
        delta_bind_start=config.sched_to_as_network_delay
        + 2.0 * config.as_to_ps_network_delay
        + config.as_to_node_network_delay,
        # Relative to the (already-shifted) node-removal effect time: the
        # NodeRemovedFromCluster -> api server -> storage -> scheduler chain.
        delta_reschedule=config.as_to_node_network_delay
        + config.as_to_ps_network_delay
        + config.ps_to_sched_network_delay,
        flush_interval=30.0,
        max_unschedulable_stay=300.0,
    )


def duration_pair_np(pod_duration: np.ndarray, interval: float) -> TPair:
    """Host float64 durations -> device TPair; <0 marks a long-running
    service (win = -1 sentinel)."""
    dur = np.asarray(pod_duration, np.float64)
    service = dur < 0
    dwin, doff = from_f64_np(np.where(service, 0.0, dur), interval)
    return TPair(
        win=jnp.asarray(np.where(service, -1, dwin), jnp.int32),
        off=jnp.asarray(np.where(service, 0.0, doff), jnp.float32),
    )


def fresh_pod_arrays(
    C: int,
    P: int,
    req_cpu,
    req_ram,
    duration: TPair,
) -> PodArrays:
    """Pod-slot arrays in their pristine (EMPTY, never-created) state — the
    single source of fresh-slot defaults, shared by init_state and the
    sliding pod window's refill."""
    return PodArrays(
        phase=jnp.zeros((C, P), jnp.int32),
        req_cpu=jnp.asarray(req_cpu, jnp.int32),
        req_ram=jnp.asarray(req_ram, jnp.int32),
        duration=duration,
        queue_ts=t_zeros((C, P)),
        queue_seq=jnp.zeros((C, P), jnp.int32),
        initial_attempt_ts=t_zeros((C, P)),
        attempts=jnp.zeros((C, P), jnp.int32),
        node=jnp.full((C, P), -1, jnp.int32),
        start_time=t_zeros((C, P)),
        finish_time=t_inf((C, P)),
        removal_time=t_inf((C, P)),
        hpa_idx=jnp.full((C, P), -1, jnp.int32),
        restarts=jnp.zeros((C, P), jnp.int32),
        will_fail=jnp.zeros((C, P), bool),
    )


def init_state(
    n_clusters: int,
    n_nodes: int,
    n_pods: int,
    node_cap_cpu: np.ndarray,
    node_cap_ram: np.ndarray,
    pod_req_cpu: np.ndarray,
    pod_req_ram: np.ndarray,
    pod_duration: np.ndarray,
    interval: float,
    node_crash_downtime: Optional[np.ndarray] = None,
) -> ClusterBatchState:
    """Build the initial state with pre-staged payloads (all slots start
    EMPTY/dead; trace events bring them to life). pod_duration: float64
    seconds, <0 marks a long-running service. node_crash_downtime: (C, N)
    sampled repair spans of the chaos engine's crash events (None = no
    faults, zeros)."""
    C, N, P = n_clusters, n_nodes, n_pods
    duration = duration_pair_np(pod_duration, interval)
    nodes = NodeArrays(
        alive=jnp.zeros((C, N), bool),
        cap_cpu=jnp.asarray(node_cap_cpu, jnp.int32),
        cap_ram=jnp.asarray(node_cap_ram, jnp.int32),
        alloc_cpu=jnp.asarray(node_cap_cpu, jnp.int32),
        alloc_ram=jnp.asarray(node_cap_ram, jnp.int32),
        create_time=t_inf((C, N)),
        remove_time=t_inf((C, N)),
        crash_downtime=(
            jnp.zeros((C, N), jnp.float32)
            if node_crash_downtime is None
            else jnp.asarray(node_crash_downtime, jnp.float32)
        ),
    )
    pods = fresh_pod_arrays(C, P, pod_req_cpu, pod_req_ram, duration)
    metrics = MetricArrays(
        pods_succeeded=jnp.zeros((C,), jnp.int32),
        pods_removed=jnp.zeros((C,), jnp.int32),
        terminated_pods=jnp.zeros((C,), jnp.int32),
        processed_nodes=jnp.zeros((C,), jnp.int32),
        scheduling_decisions=jnp.zeros((C,), jnp.int32),
        scaled_up_pods=jnp.zeros((C,), jnp.int32),
        scaled_down_pods=jnp.zeros((C,), jnp.int32),
        scaled_up_nodes=jnp.zeros((C,), jnp.int32),
        scaled_down_nodes=jnp.zeros((C,), jnp.int32),
        hpa_reserve_clamped=jnp.zeros((C,), jnp.int32),
        ca_reserve_starved=jnp.zeros((C,), jnp.int32),
        node_crashes=jnp.zeros((C,), jnp.int32),
        node_recoveries=jnp.zeros((C,), jnp.int32),
        node_downtime_s=jnp.zeros((C,), jnp.float32),
        pod_interruptions=jnp.zeros((C,), jnp.int32),
        pod_restarts=jnp.zeros((C,), jnp.int32),
        pods_failed=jnp.zeros((C,), jnp.int32),
        queue_time=EstArrays.zeros((C,)),
        algo_latency=EstArrays.zeros((C,)),
        pod_duration=EstArrays.zeros((C,)),
    )
    return ClusterBatchState(
        time=jnp.zeros((C,), jnp.int32),
        queue_seq_counter=jnp.zeros((C,), jnp.int32),
        event_cursor=jnp.zeros((C,), jnp.int32),
        pod_base=jnp.zeros((C,), jnp.int32),
        last_flush_win=jnp.zeros((C,), jnp.int32),
        requeue_signal=jnp.zeros((C,), bool),
        nodes=nodes,
        pods=pods,
        metrics=metrics,
    )


# --- lane-major hot node state -----------------------------------------------
# The Pallas kernels all consume node-shaped operands TRANSPOSED (clusters on
# the 128-wide lane axis, node slots on sublanes — ops/scheduler_kernel.py's
# one-layout rule), while the XLA glue historically worked row-major (C, N):
# every kernel boundary then materializes a transposed copy (pallas_call pins
# default layouts on operands — measured ~1.2 ms/window of marshalling at the
# composed shape, docs/DESIGN.md window-cost anatomy). Lane-major mode
# (KTPU_LANE_MAJOR / engine lane_major=) carries the HOT node leaves below
# transposed (N, C) across the whole window program: the wrappers skip their
# node-side transposes, the elementwise soup runs layout-agnostic on the
# kernel layout, and conversion happens ONCE per dispatch at the jit entry /
# exit (step.run_windows & friends), not per kernel boundary.
#
# Scope: exactly these NodeArrays leaves. The pending-effect pairs
# (create_time / remove_time) stay row-major — they are written by the CA
# pass's (C, N)-oriented scatters and read a handful of times per window —
# and the pod axis stays row-major everywhere (its sorts / rank builders /
# candidate gathers are row-major-shaped throughout step.py; see ROADMAP).
# At rest (engine.state between dispatches, checkpoints, readout) state is
# ALWAYS row-major; lane-major layout exists only inside compiled programs.
NODE_HOT_LEAVES = (
    "alive",
    "cap_cpu",
    "cap_ram",
    "alloc_cpu",
    "alloc_ram",
    "crash_downtime",
)


def swap_node_layout(state: "ClusterBatchState") -> "ClusterBatchState":
    """Transpose the hot node leaves between row-major (C, N) and lane-major
    (N, C). Self-inverse; everything else (pods, metrics, pending-effect
    pairs, auto, telemetry) is untouched. Exact — a transpose moves bits."""
    nodes = state.nodes
    return state._replace(
        nodes=nodes._replace(
            **{name: getattr(nodes, name).T for name in NODE_HOT_LEAVES}
        )
    )


# --- state-leaf & axis registries (ktpu-lint contract-prover passes) ---------
# THE "how to add a state leaf" anchor (DESIGN §7.7): the stateleaf lint
# pass proves these manifests equal the NamedTuple fields exactly, so a
# new leaf that skips the checklist fails at commit time, naming the
# registry it missed. Checklist for a new ClusterBatchState/AutoscaleState
# leaf: (1) it rides the pytree (fleet lane resets, checkpoints,
# compare_states and the sanitizer then cover it automatically — the
# PR 14 reclaim-counter lesson); (2) structural (= None default) leaves
# record their coverage story in engine.CKPT_COVERED_LEAVES; (3)
# allocation-index leaves are documented in DESIGN §12; (4) add the name
# here (and its axis signature below if it is per-cluster-shaped).
CLUSTER_STATE_LEAVES = (
    "time",
    "queue_seq_counter",
    "event_cursor",
    "pod_base",
    "last_flush_win",
    "requeue_signal",
    "nodes",
    "pods",
    "metrics",
    "auto",
    "telemetry",
)
TELEMETRY_RING_LEAVES = ("buf", "cursor")

# StepConstants leaves that are per-lane TRACED scenario data (the
# scenariotrace lint pass forbids them from flowing into Python control
# flow, host casts, jit statics or shape expressions — the fleet's
# compile-once guarantee; `is None` presence checks stay legal). The
# lane-async clock leaves are traced for the same reason: re-seeding a
# finished lane (engine.set_lane_plan) is a data update, never a
# recompile. Host-side mirrors live under different names
# (engine._lane_clock_np / _lane_horizon_np) so host arithmetic never
# reads the traced leaves.
SCENARIO_TRACED_CONSTS = ("fault_seed", "lane_clock", "lane_horizon")

# StepConstants manifest for the stateleaf lint pass: like
# CLUSTER_STATE_LEAVES, a new consts leaf must be added here (and to
# AXIS_SIGNATURES below if per-lane-shaped) or the pass fails naming it —
# the lane-async clock leaves are the template.
STEP_CONSTANTS_LEAVES = (
    "scheduling_interval",
    "time_per_node",
    "delta_pod_enqueue",
    "delta_bind_start",
    "delta_reschedule",
    "flush_interval",
    "max_unschedulable_stay",
    "trace_pod_bound",
    "resident_shift",
    "fault_seed",
    "lane_clock",
    "lane_horizon",
)

# Declared axis signatures of state leaves (the shapecontract lint pass):
# "C" = per-cluster lane vector, "C,P"/"C,N" = per-object planes, "C,*" =
# leading-C with an unspecified second axis (PodArrays (C, P) vs
# RefillStage (C, L) share these names), "@node" = the lane-major hot
# node leaves (NODE_HOT_LEAVES below: (C, N) at rest, (N, C) inside
# lane-major programs — mixes with (C,) lane vectors must go through the
# axis-parameterized helpers, never a bare broadcast).
AXIS_SIGNATURES = {
    "time": "C",
    # StepConstants lane-async clock leaves (per-lane vectors)
    "lane_clock": "C",
    "lane_horizon": "C",
    "queue_seq_counter": "C",
    "event_cursor": "C",
    "pod_base": "C",
    "last_flush_win": "C",
    "requeue_signal": "C",
    # PodArrays
    "phase": "C,P",
    "req_cpu": "C,*",
    "req_ram": "C,*",
    "duration": "C,P",
    "queue_ts": "C,P",
    "queue_seq": "C,P",
    "initial_attempt_ts": "C,P",
    "attempts": "C,P",
    "hpa_idx": "C,P",
    "restarts": "C,P",
    "will_fail": "C,P",
    "start_time": "C,P",
    "finish_time": "C,P",
    "removal_time": "C,P",
    # NodeArrays: pending-effect pairs stay row-major by contract; the
    # hot leaves are lane-major-ambiguous inside window programs.
    "create_time": "C,N",
    "remove_time": "C,N",
    "alive": "@node",
    "cap_cpu": "@node",
    "cap_ram": "@node",
    "alloc_cpu": "@node",
    "alloc_ram": "@node",
    "crash_downtime": "@node",
    # MetricArrays per-cluster counters
    "pods_succeeded": "C",
    "pods_removed": "C",
    "terminated_pods": "C",
    "processed_nodes": "C",
    "scheduling_decisions": "C",
    "scaled_up_pods": "C",
    "scaled_down_pods": "C",
    "scaled_up_nodes": "C",
    "scaled_down_nodes": "C",
    "hpa_reserve_clamped": "C",
    "ca_reserve_starved": "C",
    "node_crashes": "C",
    "node_recoveries": "C",
    "node_downtime_s": "C",
    "pod_interruptions": "C",
    "pod_restarts": "C",
    "pods_failed": "C",
}


@jax.jit
def tree_copy(tree):
    """Fresh device buffers carrying the inputs' shardings (jit outputs
    never alias undonated inputs). The buffer-donation-era state copier:
    a state pytree passed to a donated entry point (step.run_windows_donated
    and friends, engine._fused_chunk_slide) is CONSUMED — callers that must
    keep their state across such a dispatch (warm-up, A/B experiments,
    equivalence tests) dispatch a copy instead."""
    return jax.tree.map(jnp.copy, tree)


def compare_states(a: ClusterBatchState, b: ClusterBatchState) -> list:
    """Compare two final state pytrees under the documented parity policy:
    all simulation state exactly equal; float32 metric estimator accumulators
    to rtol 1e-6 (their masked (C, K) cycle folds are tiled per program by
    XLA, so differently-fused programs — scan vs Pallas, resident vs sliding
    window — can differ by an ulp; see docs/PARITY.md). Returns the keystr
    paths of mismatching leaves (empty list = parity).

    The single comparison predicate shared by the suite's interpret-mode
    Pallas tests and scripts/check_tpu_parity.py's on-hardware check.
    """
    flat_a, tdef_a = jax.tree_util.tree_flatten_with_path(a)
    flat_b, tdef_b = jax.tree_util.tree_flatten_with_path(b)
    if tdef_a != tdef_b:
        # Structurally different states (e.g. autoscaling enabled in only
        # one) must report as a mismatch, not silently zip-truncate.
        return [f"<tree structure: {tdef_a} != {tdef_b}>"]
    bad = []
    for (path, x), (_, y) in zip(flat_a, flat_b):
        key = jax.tree_util.keystr(path)
        xa, ya = np.asarray(x), np.asarray(y)
        if xa.shape != ya.shape:
            ok = False
        elif ".metrics." in key and xa.dtype == np.float32:
            # atol=0: a should-be-zero accumulator must BE zero.
            ok = bool(np.allclose(xa, ya, rtol=1e-6, atol=0.0))
        else:
            ok = bool((xa == ya).all())
        if not ok:
            bad.append(key)
    return bad
