"""Host-side trace compiler: scalar trace events -> dense device slabs.

The batched path's replacement for the reference's trace-to-event emission
(reference: src/simulator.rs:234-253): names are interned to slots once on the
host; payloads (capacities, requests, durations) are pre-staged into per-slot
arrays; the device sees only (time, kind, slot) triples.

Node re-creations of the same name get fresh slots (the scalar path allocates a
fresh pool component the same way, reference: src/core/node_component_pool.rs).
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from kubernetriks_tpu.batched.state import (
    DEFAULT_RAM_UNIT,
    EV_CREATE_NODE,
    EV_CREATE_POD,
    EV_NODE_CRASH,
    EV_NODE_RECOVER,
    EV_REMOVE_NODE,
    EV_REMOVE_POD,
)
from kubernetriks_tpu.core.events import (
    CreateNodeRequest,
    CreatePodGroupRequest,
    CreatePodRequest,
    RemoveNodeRequest,
    RemovePodRequest,
)
from kubernetriks_tpu.trace.interface import TraceEvents


@dataclass
class CompiledPodGroup:
    """Host-side pod-group table for the batched HPA: reserved slot range,
    targets, and the load curve compiled out of the nested YAML usage-model
    config (reference: src/core/resource_usage/interface.rs:13-18)."""

    name: str
    slot_start: int
    slot_count: int  # reserved slots = initial + multiplier x max_pod_count (ring-reused)
    max_pods: int
    initial: int
    creation_time: float
    target_cpu: float  # <=0 means unset
    target_ram: float
    cpu_units: List[Tuple[float, float]]  # (duration, load); [] = no model
    cpu_const: bool
    ram_units: List[Tuple[float, float]]
    ram_const: bool


def _compile_usage_model(model_config) -> Tuple[List[Tuple[float, float]], bool]:
    """ResourceUsageModelConfig -> (units, is_constant). A constant model's
    load IS the utilization; a pod_group model's load is divided by the live
    pod count (reference: src/core/resource_usage/{constant,pod_group}.rs)."""
    import yaml

    if model_config is None:
        return [], False
    parsed = yaml.safe_load(model_config.config)
    if model_config.model_name == "constant":
        return [(1.0, float(parsed["usage"]))], True
    if model_config.model_name == "pod_group":
        return [
            (float(u["duration"]), float(u["total_load"])) for u in parsed
        ], False
    raise ValueError(f"unknown usage model {model_config.model_name!r}")


@dataclass
class CompiledClusterTrace:
    """One cluster's compiled trace + payload tables (numpy, host-side)."""

    ev_time: np.ndarray  # (E,) float64
    ev_kind: np.ndarray  # (E,) int32
    ev_slot: np.ndarray  # (E,) int32
    node_cap_cpu: np.ndarray  # (N,) int32
    node_cap_ram: np.ndarray  # (N,) int32 (ram units)
    pod_req_cpu: np.ndarray  # (P,) int32
    pod_req_ram: np.ndarray  # (P,) int32 (ram units)
    pod_duration: np.ndarray  # (P,) float64 (-1 for long-running)
    node_names: List[str] = field(default_factory=list)
    pod_names: List[str] = field(default_factory=list)
    pod_groups: List[CompiledPodGroup] = field(default_factory=list)
    # (N,) sampled repair span of each slot's chaos-engine crash event
    # (0 where the slot never crashes); None when no faults were injected.
    node_crash_downtime: Optional[np.ndarray] = None

    @property
    def n_events(self) -> int:
        return len(self.ev_time)

    @property
    def n_nodes(self) -> int:
        return len(self.node_cap_cpu)

    @property
    def n_pods(self) -> int:
        return len(self.pod_req_cpu)


def _event_time_shifts(config) -> Tuple[float, float, float]:
    """Per-kind event-time shifts composing the scalar path's control-plane
    hop chains (SURVEY.md §3.2/3.4): (create_node, remove_node, remove_pod)."""
    if config is None:
        return 0.0, 0.0, 0.0
    return (
        3.0 * config.as_to_ps_network_delay + config.ps_to_sched_network_delay,
        2.0 * config.as_to_ps_network_delay + config.as_to_node_network_delay,
        config.as_to_ps_network_delay,
    )


def compile_cluster_trace(
    cluster_events: TraceEvents,
    workload_events: TraceEvents,
    config=None,
    ram_unit: int = DEFAULT_RAM_UNIT,
    pod_group_slot_multiplier: int = 2,
) -> CompiledClusterTrace:
    """Merge + time-sort both traces (stable: cluster events first at equal
    times, matching the scalar initialize() emission order, reference:
    src/simulator.rs:234-253) and intern names to slots.

    Event times are shifted to their *effect* times, composing the scalar
    path's control-plane hop chains (SURVEY.md §3.2/3.4):
    - CreateNode at t becomes schedulable when the scheduler caches it:
      t + 3*as_to_ps + ps_to_sched
    - RemoveNode at t takes effect when the node component cancels its pods:
      t + 2*as_to_ps + as_to_node
    - RemovePod at t takes effect when storage drops it: t + as_to_ps
    - CreatePod stays at t; its queue-entry time is shifted on-device by
      delta_pod_enqueue.
    """
    shift_create_node, shift_remove_node, shift_remove_pod = _event_time_shifts(config)

    # A node's remove effect can never precede its create effect: when the
    # per-kind shifts are asymmetric (shift_create > shift_remove) a same-tick
    # create+remove pair would otherwise reorder after shifting. Clamp the
    # remove to the create's effect time; the stable (time, order) sort then
    # keeps create first (trace file order at equal times).
    node_create_effect: Dict[str, float] = {}
    merged: List[Tuple[float, int, object]] = []
    for order, events in ((0, cluster_events), (1, workload_events)):
        for ts, event in events:
            shifted = float(ts)
            if isinstance(event, CreateNodeRequest):
                shifted += shift_create_node
                # Latest create wins: re-creations of a name clamp their own
                # subsequent remove (cluster events arrive in trace order).
                node_create_effect[event.node.metadata.name] = shifted
            elif isinstance(event, RemoveNodeRequest):
                shifted = max(
                    shifted + shift_remove_node,
                    node_create_effect.get(event.node_name, -np.inf),
                )
            elif isinstance(event, RemovePodRequest):
                shifted += shift_remove_pod
            merged.append((shifted, order, event))
    merged.sort(key=lambda item: (item[0], item[1]))

    ev_time: List[float] = []
    ev_kind: List[int] = []
    ev_slot: List[int] = []
    node_cap_cpu: List[int] = []
    node_cap_ram: List[int] = []
    node_names: List[str] = []
    live_node_slot: Dict[str, int] = {}
    pod_req_cpu: List[int] = []
    pod_req_ram: List[int] = []
    pod_duration: List[float] = []
    pod_names: List[str] = []
    pod_slot: Dict[str, int] = {}
    pod_groups: List[CompiledPodGroup] = []
    node_crash_downtime: Dict[int, float] = {}

    for ts, _, event in merged:
        if isinstance(event, CreateNodeRequest):
            # Chaos recoveries are fresh-slot creations (slots are never
            # reused); only the event kind differs, for fault accounting.
            node = event.node
            slot = len(node_cap_cpu)
            node_cap_cpu.append(int(node.status.capacity.cpu))
            node_cap_ram.append(int(node.status.capacity.ram) // ram_unit)
            node_names.append(node.metadata.name)
            live_node_slot[node.metadata.name] = slot
            ev_time.append(ts)
            ev_kind.append(EV_NODE_RECOVER if event.recovered else EV_CREATE_NODE)
            ev_slot.append(slot)
        elif isinstance(event, RemoveNodeRequest):
            slot = live_node_slot.pop(event.node_name)
            ev_time.append(ts)
            if event.crashed:
                ev_kind.append(EV_NODE_CRASH)
                node_crash_downtime[slot] = float(event.downtime_s)
            else:
                ev_kind.append(EV_REMOVE_NODE)
            ev_slot.append(slot)
        elif isinstance(event, CreatePodRequest):
            pod = event.pod
            slot = len(pod_req_cpu)
            requests = pod.spec.resources.requests
            pod_req_cpu.append(int(requests.cpu))
            pod_req_ram.append(-(-int(requests.ram) // ram_unit))  # ceil
            duration = pod.spec.running_duration
            pod_duration.append(-1.0 if duration is None else float(duration))
            pod_names.append(pod.metadata.name)
            pod_slot[pod.metadata.name] = slot
            ev_time.append(ts)
            ev_kind.append(EV_CREATE_POD)
            ev_slot.append(slot)
        elif isinstance(event, RemovePodRequest):
            ev_time.append(ts)
            ev_kind.append(EV_REMOVE_POD)
            ev_slot.append(pod_slot[event.pod_name])
        elif isinstance(event, CreatePodGroupRequest):
            group = event.pod_group
            template = group.pod_template
            assert template.spec.running_duration is None, (
                "Pod groups with specified duration are not supported. "
                "Only long running services."
            )
            umc = group.resources_usage_model_config
            cpu_units, cpu_const = _compile_usage_model(
                umc.cpu_config if umc else None
            )
            ram_units, ram_const = _compile_usage_model(
                umc.ram_config if umc else None
            )
            slot_start = len(pod_req_cpu)
            # The group's slots form a ring (autoscale.py hpa_pass): head/tail
            # wrap modulo slot_count, so churn reuses freed slots. The reserve
            # needs initial + multiplier*max so that (a) all initial pods fit
            # alongside a full scale-up window and (b) a slot is never
            # rewrapped while its previous occupant is still terminating.
            slot_count = group.initial_pod_count + (
                pod_group_slot_multiplier * group.max_pod_count
            )
            requests = template.spec.resources.requests
            for i in range(slot_count):
                pod_req_cpu.append(int(requests.cpu))
                pod_req_ram.append(-(-int(requests.ram) // ram_unit))
                pod_duration.append(-1.0)
                name = f"{group.name}_{i}"
                pod_slot[name] = len(pod_names)
                pod_names.append(name)
            # Initial pods hit the api server at the group's trace time
            # (reference expansion: src/core/api_server.rs:405-455).
            for i in range(group.initial_pod_count):
                ev_time.append(ts)
                ev_kind.append(EV_CREATE_POD)
                ev_slot.append(slot_start + i)
            targets = group.target_resources_usage
            pod_groups.append(
                CompiledPodGroup(
                    name=group.name,
                    slot_start=slot_start,
                    slot_count=slot_count,
                    max_pods=group.max_pod_count,
                    initial=group.initial_pod_count,
                    creation_time=float(ts),
                    target_cpu=float(targets.cpu_utilization or 0.0),
                    target_ram=float(targets.ram_utilization or 0.0),
                    cpu_units=cpu_units,
                    cpu_const=cpu_const,
                    ram_units=ram_units,
                    ram_const=ram_const,
                )
            )
        else:
            raise ValueError(
                f"batched path does not support trace event {type(event).__name__}"
            )

    crash_downtime_arr = None
    if node_crash_downtime:
        crash_downtime_arr = np.zeros(len(node_cap_cpu), np.float32)
        for slot, ttr in node_crash_downtime.items():
            crash_downtime_arr[slot] = ttr

    return CompiledClusterTrace(
        ev_time=np.asarray(ev_time, np.float64),
        ev_kind=np.asarray(ev_kind, np.int32),
        ev_slot=np.asarray(ev_slot, np.int32),
        node_cap_cpu=np.asarray(node_cap_cpu, np.int32).reshape(-1),
        node_cap_ram=np.asarray(node_cap_ram, np.int32).reshape(-1),
        pod_req_cpu=np.asarray(pod_req_cpu, np.int32).reshape(-1),
        pod_req_ram=np.asarray(pod_req_ram, np.int32).reshape(-1),
        pod_duration=np.asarray(pod_duration, np.float64).reshape(-1),
        node_names=node_names,
        pod_names=pod_names,
        pod_groups=pod_groups,
        node_crash_downtime=crash_downtime_arr,
    )


def segment_pod_slots(
    compiled: Sequence[CompiledClusterTrace],
) -> Tuple[List[CompiledClusterTrace], int]:
    """Renumber pod slots into the segmented layout the sliding pod window
    needs to coexist with HPA pod groups: plain (non-group) pods occupy
    global slots [0, T) in their original event order, pod-group reserved
    ring slots occupy [T, ...), where T is the batch-wide max plain-pod
    count. Group pods are long-running services — they would block the
    window's terminal-prefix shift forever — so the window slides only over
    the plain segment while the ring slots stay device-resident.

    Padding slots inside [plain_count, T) get empty names, zero requests and
    service duration; they are never targeted by any event. Event ORDER (and
    hence queue_seq assignment) is unchanged — only slot numbering moves, so
    the only behavioral deviation is the slot-order stand-in used for
    same-window reschedule ranking (docs/PARITY.md).

    Returns (renumbered traces, T). Identity (same objects) when no trace
    has pod groups.
    """
    if not any(c.pod_groups for c in compiled):
        return list(compiled), max((c.n_pods for c in compiled), default=0)

    group_masks = []
    for c in compiled:
        is_group = np.zeros(c.n_pods, bool)
        for g in c.pod_groups:
            is_group[g.slot_start : g.slot_start + g.slot_count] = True
        group_masks.append(is_group)
    T = max(int((~m).sum()) for m in group_masks)

    out: List[CompiledClusterTrace] = []
    for c, is_group in zip(compiled, group_masks):
        if c.n_pods == 0:
            # Nothing to renumber (and new_slot would be empty while node
            # events still populate ev_slot); pad_and_batch aligns widths.
            out.append(c)
            continue
        R = int(is_group.sum())
        L = T + R
        plain_ord = np.cumsum(~is_group) - 1
        group_ord = np.cumsum(is_group) - 1
        new_slot = np.where(is_group, T + group_ord, plain_ord).astype(np.int32)

        req_cpu = np.zeros(L, np.int32)
        req_ram = np.zeros(L, np.int32)
        duration = np.full(L, -1.0, np.float64)
        names = [""] * L
        req_cpu[new_slot] = c.pod_req_cpu
        req_ram[new_slot] = c.pod_req_ram
        duration[new_slot] = c.pod_duration
        for old, new in enumerate(new_slot):
            names[new] = c.pod_names[old]

        is_pod_ev = (c.ev_kind == EV_CREATE_POD) | (c.ev_kind == EV_REMOVE_POD)
        ev_slot = np.where(
            is_pod_ev, new_slot[np.clip(c.ev_slot, 0, c.n_pods - 1)], c.ev_slot
        ).astype(np.int32)

        groups = [
            dataclasses.replace(g, slot_start=T + int(group_ord[g.slot_start]))
            for g in c.pod_groups
        ]
        out.append(
            CompiledClusterTrace(
                ev_time=c.ev_time,
                ev_kind=c.ev_kind,
                ev_slot=ev_slot,
                node_cap_cpu=c.node_cap_cpu,
                node_cap_ram=c.node_cap_ram,
                pod_req_cpu=req_cpu,
                pod_req_ram=req_ram,
                pod_duration=duration,
                node_names=c.node_names,
                pod_names=names,
                pod_groups=groups,
                node_crash_downtime=c.node_crash_downtime,
            )
        )
    return out, T


def pad_and_batch(
    compiled: Sequence[CompiledClusterTrace],
    n_nodes: Optional[int] = None,
    n_pods: Optional[int] = None,
    n_events: Optional[int] = None,
) -> Tuple[np.ndarray, ...]:
    """Stack per-cluster compilations into (C, ...) arrays, padding slots and
    events (pad events: kind=EV_NONE, time=+inf)."""
    C = len(compiled)
    N = n_nodes if n_nodes is not None else max((c.n_nodes for c in compiled), default=0)
    P = n_pods if n_pods is not None else max((c.n_pods for c in compiled), default=0)
    E = n_events if n_events is not None else max((c.n_events for c in compiled), default=0)
    # +1: always keep a (time=+inf, EV_NONE) sentinel after the last real event.
    N, P, E = max(N, 1), max(P, 1), max(E, 0) + 1

    ev_time = np.full((C, E), np.inf, np.float64)
    ev_kind = np.zeros((C, E), np.int32)
    ev_slot = np.zeros((C, E), np.int32)
    node_cap_cpu = np.zeros((C, N), np.int32)
    node_cap_ram = np.zeros((C, N), np.int32)
    pod_req_cpu = np.zeros((C, P), np.int32)
    pod_req_ram = np.zeros((C, P), np.int32)
    pod_duration = np.full((C, P), -1.0, np.float64)
    node_crash_downtime = np.zeros((C, N), np.float32)

    for i, c in enumerate(compiled):
        ev_time[i, : c.n_events] = c.ev_time
        ev_kind[i, : c.n_events] = c.ev_kind
        ev_slot[i, : c.n_events] = c.ev_slot
        node_cap_cpu[i, : c.n_nodes] = c.node_cap_cpu
        node_cap_ram[i, : c.n_nodes] = c.node_cap_ram
        pod_req_cpu[i, : c.n_pods] = c.pod_req_cpu
        pod_req_ram[i, : c.n_pods] = c.pod_req_ram
        pod_duration[i, : c.n_pods] = c.pod_duration
        if c.node_crash_downtime is not None:
            node_crash_downtime[i, : c.n_nodes] = c.node_crash_downtime

    return (
        ev_time,
        ev_kind,
        ev_slot,
        node_cap_cpu,
        node_cap_ram,
        pod_req_cpu,
        pod_req_ram,
        pod_duration,
        node_crash_downtime,
    )


def compile_from_arrays(
    cluster_arrays,
    workload_arrays,
    config=None,
    ram_unit: int = DEFAULT_RAM_UNIT,
) -> CompiledClusterTrace:
    """Dense-array fast path: native-feeder output -> CompiledClusterTrace
    without materializing per-event Python objects.

    Semantically identical to compile_cluster_trace() over
    {cluster,workload}_events_from_arrays(...) — the equality is asserted in
    tests/test_native_feeder.py. Node events (small) run through a Python
    loop; pod events (the multi-million-row axis on Alibaba traces) are
    vectorized numpy end to end.

    cluster_arrays: kubernetriks_tpu.trace.feeder.ClusterArrays or None.
    workload_arrays: kubernetriks_tpu.trace.feeder.WorkloadArrays.
    """
    shift_create_node, shift_remove_node, _ = _event_time_shifts(config)

    # --- node events (loop; N is small) ------------------------------------
    node_cap_cpu: List[int] = []
    node_cap_ram: List[int] = []
    node_names: List[str] = []
    live_node_slot: Dict[int, int] = {}
    c_time: List[float] = []
    c_kind: List[int] = []
    c_slot: List[int] = []
    node_create_effect: Dict[int, float] = {}
    if cluster_arrays is not None:
        for i in range(len(cluster_arrays.ts)):
            mid = int(cluster_arrays.machine_id[i])
            if int(cluster_arrays.kind[i]) == 0:
                slot = len(node_cap_cpu)
                node_cap_cpu.append(int(cluster_arrays.cpu_millicores[i]))
                node_cap_ram.append(int(cluster_arrays.ram_bytes[i]) // ram_unit)
                node_names.append(cluster_arrays.node_name(i))
                live_node_slot[mid] = slot
                shifted = float(cluster_arrays.ts[i]) + shift_create_node
                node_create_effect[mid] = shifted
                c_time.append(shifted)
                c_kind.append(EV_CREATE_NODE)
                c_slot.append(slot)
            else:
                # Clamp like compile_cluster_trace: a remove's effect never
                # precedes its node's create effect under asymmetric shifts.
                c_time.append(
                    max(
                        float(cluster_arrays.ts[i]) + shift_remove_node,
                        node_create_effect.get(mid, -np.inf),
                    )
                )
                c_kind.append(EV_REMOVE_NODE)
                c_slot.append(live_node_slot.pop(mid))

    # --- pod events (vectorized) -------------------------------------------
    P = len(workload_arrays.start_ts)
    w_time = workload_arrays.start_ts.astype(np.float64)
    pod_req_cpu = workload_arrays.cpu_millicores.astype(np.int32)
    pod_req_ram = (-(-workload_arrays.ram_bytes // ram_unit)).astype(np.int32)
    pod_duration = workload_arrays.duration.astype(np.float64)
    pod_names = [workload_arrays.pod_name(i) for i in range(P)]

    # --- stable merge: primary time, cluster events before workload at ties
    times = np.concatenate([np.asarray(c_time, np.float64), w_time])
    kinds = np.concatenate(
        [np.asarray(c_kind, np.int32), np.full(P, EV_CREATE_POD, np.int32)]
    )
    slots = np.concatenate(
        [np.asarray(c_slot, np.int32), np.arange(P, dtype=np.int32)]
    )
    source = np.concatenate(
        [np.zeros(len(c_time), np.int8), np.ones(P, np.int8)]
    )
    order = np.lexsort((source, times))  # stable within each source stream

    return CompiledClusterTrace(
        ev_time=times[order],
        ev_kind=kinds[order],
        ev_slot=slots[order],
        node_cap_cpu=np.asarray(node_cap_cpu, np.int32).reshape(-1),
        node_cap_ram=np.asarray(node_cap_ram, np.int32).reshape(-1),
        pod_req_cpu=pod_req_cpu.reshape(-1),
        pod_req_ram=pod_req_ram.reshape(-1),
        pod_duration=pod_duration.reshape(-1),
        node_names=node_names,
        pod_names=pod_names,
        pod_groups=[],
    )


def _pad_cols(arr: np.ndarray, lo: int, width: int, fill, dtype) -> np.ndarray:
    """arr[:, lo:lo+width], right-padded with `fill` — the one padding
    rule of the staging column layout (see stage_segment)."""
    C = arr.shape[0]
    out = np.full((C, width), fill, dtype)
    src = arr[:, lo : lo + width]
    out[:, : src.shape[1]] = src
    return out


class PayloadSource:
    """Provider of the slide/staging PAYLOAD columns (pod requests +
    durations) for global plain-pod columns [lo, lo + width) — the seam
    that bounds the engine's steady-state host memory (ROADMAP #2):
    `segment` returns {"req_cpu", "req_ram", "duration"} (C, width)
    numpy arrays with the fresh-slot padding past the trace end (request
    0, duration -1.0 — the long-running-service sentinel the pair
    conversion encodes). ArrayPayloadSource wraps the resident
    whole-trace arrays (the build default, O(T) host); FeederPayloadSource
    materializes only the requested rows from a segment reader
    (trace.feeder.WorkloadSegmentReader), so after
    engine.attach_payload_source the resident payload drops to
    O(stage width) regardless of trace length. Thread-safety contract:
    `segment` is called from the streaming feeder's producer thread —
    implementations must be safe for one concurrent reader."""

    total_rows: int  # plain pod columns the source covers

    def segment(self, lo: int, width: int) -> Dict[str, np.ndarray]:
        raise NotImplementedError


class ArrayPayloadSource(PayloadSource):
    """Whole-trace arrays ({"req_cpu","req_ram","duration"} of shape
    (C, T)) — the resident default."""

    def __init__(self, full_pods: Dict[str, np.ndarray]) -> None:
        self.full_pods = full_pods
        self.total_rows = int(full_pods["req_cpu"].shape[1])

    def segment(self, lo: int, width: int) -> Dict[str, np.ndarray]:
        full = self.full_pods
        return {
            "req_cpu": _pad_cols(full["req_cpu"], lo, width, 0, np.int32),
            "req_ram": _pad_cols(full["req_ram"], lo, width, 0, np.int32),
            "duration": _pad_cols(
                full["duration"], lo, width, -1.0, np.float64
            ),
        }


class FeederPayloadSource(PayloadSource):
    """Bounded host payload over a row-range workload reader (native
    trace.feeder.WorkloadSegmentReader or the python-oracle
    WorkloadArraysReader): pod slots of a pure-workload trace are
    assigned in row order, so payload column i IS sorted workload row i,
    and a segment materializes exactly the requested rows. Conversions
    mirror compile_from_arrays (int32 millicores, ceil-div RAM
    quantization, float64 seconds) so a feeder-sourced slab is
    bit-identical to the resident arrays' slice. The compiled trace must
    carry no pod groups (group ring slots renumber the payload axis);
    the engine validates that at attach time."""

    def __init__(self, reader, n_clusters: int, ram_unit: int) -> None:
        self.reader = reader
        self.n_clusters = int(n_clusters)
        self.ram_unit = int(ram_unit)
        self.total_rows = len(reader)

    def segment(self, lo: int, width: int) -> Dict[str, np.ndarray]:
        C = self.n_clusters
        out = {
            "req_cpu": np.zeros((C, width), np.int32),
            "req_ram": np.zeros((C, width), np.int32),
            "duration": np.full((C, width), -1.0, np.float64),
        }
        n = max(0, min(width, self.total_rows - lo))
        if n:
            wa = self.reader.read(lo, n)
            out["req_cpu"][:, :n] = wa.cpu_millicores.astype(np.int32)[None, :]
            out["req_ram"][:, :n] = (
                -(-wa.ram_bytes // self.ram_unit)
            ).astype(np.int32)[None, :]
            out["duration"][:, :n] = wa.duration.astype(np.float64)[None, :]
        return out


def stage_segment(
    payload,
    create_win: np.ndarray,
    rank_full: Optional[np.ndarray],
    lo: int,
    width: int,
) -> Dict[str, np.ndarray]:
    """Staging-segment extraction for the superspan executor: numpy refill
    payload columns [lo, lo + width) of the trace's PLAIN pod segment, ready
    to become a device RefillStage (batched/state.py).

    Columns past the trace end get the SAME fresh-slot padding the host
    refill path produces — request 0, duration -1.0 (the long-running
    service sentinel the pair conversion encodes), INT32_MAX create window
    (never comes alive), BIG name rank — so a stage straddling the trace
    boundary slides bit-identically to the full-resident payload. The ONE
    owner of the staging column layout: the engine's whole-trace slide
    payload (_init_device_slide) and its bounded stage buffers (_make_stage)
    both assemble through here, so padding rules can never drift apart.
    Duration stays float64 SECONDS here; the caller converts to the device
    pair (duration_pair_np) after padding, exactly like the initial build.

    `payload` is a PayloadSource (or a bare {"req_cpu","req_ram",
    "duration"} whole-trace dict, wrapped on the fly): the request/
    duration columns come from it, while the create-window and name-rank
    tables — small int32 per-pod arrays the engine keeps resident for
    O(1) capacity lookups — are sliced here.
    """
    no_create = np.iinfo(np.int32).max
    BIG_RANK = np.int32(1 << 30)

    if not isinstance(payload, PayloadSource):
        payload = ArrayPayloadSource(payload)
    out = payload.segment(lo, width)
    out["create_win"] = _pad_cols(create_win, lo, width, no_create, np.int32)
    if rank_full is not None:
        out["rank"] = _pad_cols(rank_full, lo, width, BIG_RANK, np.int32)
    return out
