# ktpu: hot-path
"""Capacity observatory: reserve-occupancy tracking, memory watermarks
and the saturation watchdog (the flight recorder's capacity half).

The flight recorder (PR 8) made per-window *cost* visible; this module
makes the two things that actually kill a long run visible *before* they
do:

- **Reserve occupancy.** The batched path consumes bounded reserves that
  churn can exhaust (ROADMAP #2): the CA node-slot reserve (`ca_cursor`:
  LIVE occupancy under slot reclaim (KTPU_RECLAIM), where compaction
  pulls it back and the watchdog fits the NET slope; monotone cumulative
  allocations without reclaim), the HPA pod-group slot reserve, and the
  sliding pod window's plain-trace headroom. The window
  body appends these as gauge columns of the device telemetry ring
  (batched/state.py TELEM_HPA_RESERVE / TELEM_CA_RESERVE /
  TELEM_POD_HEADROOM), so they ride the existing per-window record
  scatter — zero new reductions on the hot path, zero new host syncs
  (the ring drains only at existing host-block boundaries, PR 8's rule).
- **Memory watermarks.** At those same drain points the engine samples
  host RSS, backend device-memory stats and exact slab/ring accounting
  (`engine._sample_resources`); this module folds the samples into
  high-water marks, so an O(T) leak shows as a rising watermark instead
  of an OOM three weeks in.
- **Saturation watchdog.** At each drain the observatory fits the recent
  occupancy trajectory (closed-form least squares per cluster) and emits
  a `SaturationWarning` with the estimated time-to-exhaustion while the
  run is still healthy — BEFORE the loud reserve bound
  (`engine.check_autoscaler_bounds`) fires at readout. It also flags a
  starved/wasteful streaming feeder (production vs install drift, the
  feeder-not-ready stall counter) and steady-state sync-budget
  violations.

Everything here runs strictly on DRAINED HOST COPIES (owned numpy
arrays from `telemetry/ring.snapshot`, plain dicts from the engine):
this module carries the `# ktpu: hot-path` pragma ON PURPOSE and stays
golden-clean with ZERO sync-ok waivers — it must never touch a device
value. Export seams (JSONL, Prometheus textfile) live in
`telemetry/export.py` under the same contract.
"""

from __future__ import annotations

import math
import os
import time
import warnings
from collections import deque
from typing import Dict, List, Optional, Sequence

import numpy as np

from kubernetriks_tpu.batched.state import (
    TELEM_CA_RESERVE,
    TELEM_HPA_RESERVE,
    TELEM_LANE_ACTIVE,
    TELEM_POD_HEADROOM,
    TELEM_WINDOW,
)
from kubernetriks_tpu.flags import flag_int
from kubernetriks_tpu.telemetry.histogram import LatencyHistogram

# TELEM_POD_HEADROOM values at or above this mean "no sliding window /
# whole plain trace resident" (state.StepConstants.trace_pod_bound
# defaults to a 1 << 30 sentinel): the watchdog skips those clusters.
UNBOUNDED_SENTINEL = 1 << 28

# SLO burn-rate verdict constants (DESIGN §14): the objective is "99% of
# queries complete under KTPU_SLO_MS", i.e. a 1% error budget. Burn rate
# = (violating fraction over a window) / budget; the fast page fires at
# the classic 14.4x multiple over the fast window (KTPU_SLO_BURN_WINDOW),
# the slow ticket at 6x over 12x that window, and each clears with
# hysteresis at half its threshold (like the reserve verdicts' recover
# fraction).
SLO_ERROR_BUDGET = 0.01
SLO_FAST_BURN = 14.4
SLO_SLOW_BURN = 6.0
SLO_MIN_SAMPLES = 8
_SLO_SAMPLE_CAP = 8192  # bounded (wall-windowed) violation samples


class SaturationWarning(UserWarning):
    """A capacity reserve is trending toward exhaustion (or a pipeline
    health invariant drifted): actionable ahead of the loud bound."""


def sample_host_memory() -> Dict[str, int]:
    """Host memory sample: current RSS from /proc/self/statm (Linux;
    0 where unavailable) and the process peak RSS from getrusage.
    Pure host I/O — no jax, no device values."""
    rss = 0
    try:
        with open("/proc/self/statm") as fh:
            fields = fh.read().split()
        rss = int(fields[1]) * os.sysconf("SC_PAGE_SIZE")
    except (OSError, IndexError, ValueError):
        pass
    peak = 0
    try:
        import resource

        # ru_maxrss is KiB on Linux.
        peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
    except Exception:
        pass
    return {"rss_bytes": rss, "peak_rss_bytes": peak}


def fit_slope(x: Sequence[float], y: np.ndarray) -> np.ndarray:
    """Closed-form least-squares slope of y against x. x: (n,) times;
    y: (n,) or (n, C) values. Returns a scalar or (C,) slope (0 where x
    has no spread)."""
    xs = np.fromiter((float(v) for v in x), dtype=np.float64)
    ys = y.astype(np.float64)
    xm = xs.mean()
    dx = xs - xm
    denom = float((dx * dx).sum())
    if denom <= 0.0:
        return np.zeros(ys.shape[1:], np.float64) if ys.ndim > 1 else np.float64(0.0)
    dy = ys - ys.mean(axis=0)
    if ys.ndim > 1:
        return (dx[:, None] * dy).sum(axis=0) / denom
    return (dx * dy).sum() / denom


def time_to_exhaustion(
    now: float, slope: float, capacity: Optional[float], falling: bool = False
) -> float:
    """Estimated seconds until `now` reaches `capacity` at `slope`
    (rising gauges) or reaches zero (falling gauges). math.inf when the
    trajectory never gets there."""
    if falling:
        if slope >= 0.0:
            return math.inf
        return max(now, 0.0) / -slope
    if capacity is None or slope <= 0.0:
        return math.inf
    remaining = capacity - now
    if remaining <= 0.0:
        return 0.0
    return remaining / slope


class Observatory:
    """Folds drained ring buffers + resource samples into occupancy
    series, high-water marks and watchdog verdicts.

    Parameters:
    - interval: scheduling interval (seconds per window) — converts the
      window axis to sim-seconds for trajectory fits.
    - capacities: {"hpa_reserve": [per-cluster total], "ca_reserve":
      [per-cluster total]} — plain python ints, computed once at engine
      build from the autoscale statics (None entries = no such reserve).
    - watchdog: arm the saturation checks (off: ingest/report only).
    - warn_frac: occupancy fraction that fires immediately.
    - min_frac: floor below which trajectory (eta-based) warnings stay
      quiet — an early-transient slope extrapolated from a nearly-empty
      reserve is noise, not a verdict.
    - horizon_s: fire when estimated exhaustion lands within this many
      sim-seconds (default: 500 windows).
    - fit_window: trajectory points kept per gauge (bounded history —
      the observatory's memory is O(fit_window * C), never O(T)).
    - exporters: objects with .emit(record: dict) called once per
      observe() with the pure-python drain record (telemetry/export.py).
    """

    def __init__(
        self,
        *,
        interval: float,
        capacities: Optional[Dict[str, Sequence[int]]] = None,
        watchdog: bool = True,
        warn_frac: float = 0.8,
        min_frac: float = 0.3,
        recover_frac: Optional[float] = None,
        horizon_s: Optional[float] = None,
        min_points: int = 4,
        fit_window: int = 64,
        exporters: Optional[list] = None,
        max_events: int = 256,
        lane_idle_frac: float = 0.5,
        slo_ms: Optional[float] = None,
        slo_burn_window_s: Optional[float] = None,
    ) -> None:
        self.interval = float(interval)
        self.capacities = dict(capacities or {})
        self.watchdog = bool(watchdog)
        self.warn_frac = float(warn_frac)
        self.min_frac = float(min_frac)
        # Hysteresis floor for clearing a fired reserve verdict (reserve
        # occupancy is non-monotone under slot reclaim): recover when
        # every lane is at or below this fraction with no near-horizon
        # trajectory. Default: half the warning fraction.
        self.recover_frac = (
            float(recover_frac)
            if recover_frac is not None
            else self.warn_frac / 2.0
        )
        self.horizon_s = (
            float(horizon_s) if horizon_s is not None else 500.0 * self.interval
        )
        self.min_points = max(2, int(min_points))
        self.fit_window = max(self.min_points, int(fit_window))
        self.exporters = list(exporters or [])
        self.max_events = int(max_events)
        # Idle-lane verdict floor: a lane active for less than this
        # fraction of the recent windows (lane-async fleets only — the
        # lane_active ring column is constant 1 everywhere else) means
        # dispatched lane-windows are being thrown away.
        self.lane_idle_frac = float(lane_idle_frac)
        # Latency-SLO verdict config: explicit kwargs win; otherwise the
        # registered flags decide (KTPU_SLO_MS unset = disarmed).
        if slo_ms is None:
            slo_ms = flag_int("KTPU_SLO_MS")
        self.slo_ms = float(slo_ms) if slo_ms is not None else None
        if slo_burn_window_s is None:
            slo_burn_window_s = flag_int("KTPU_SLO_BURN_WINDOW")
        self.slo_burn_window_s = float(slo_burn_window_s or 60)
        self.reset()

    def reset(self) -> None:
        """Drop accumulated series/watermarks (checkpoint restore: the
        restored run is a fresh trajectory)."""
        # (window, hpa_used (C,), ca_used (C,), headroom (C,),
        # lane_active (C,)) — bounded.
        self._points: deque = deque(maxlen=self.fit_window)
        self._last_window = -1
        self._high_water: Dict[str, int] = {}
        self._mem_high: Dict[str, int] = {}
        self._last_resources: Dict = {}
        self._last_stall_not_ready = 0
        # Lane fault-domain gauge (PR 19): per-lane state strings pushed
        # by the fleet at every transition ("active"/"idle"/
        # "quarantined"/"probe"), plus cumulative quarantine counters —
        # O(C) host strings, never device values.
        self._lane_states: List[str] = []
        self._quarantine_total = 0
        self._readmit_total = 0
        self.events: List[Dict] = []
        self.fired: Dict[str, int] = {}
        self.samples = 0
        self.reset_query_stats()

    def reset_query_stats(self) -> None:
        """Reset the query-latency histograms + the SLO sample window
        atomically (the fleet's reset_query_stats() calls this so the
        fleet and observatory sides never disagree). Fired SLO verdicts
        clear too: the post-reset traffic is a fresh trajectory."""
        # Bounded per-query latency stats (PR 17): log-bucketed streaming
        # histograms — O(buckets) forever, never O(queries) — for the
        # total submit->drain wall plus the queue-wait / service split.
        self._lat_hist = LatencyHistogram()
        self._queue_hist = LatencyHistogram()
        self._service_hist = LatencyHistogram()
        # (t_wall, violated) pairs for the SLO burn-rate windows.
        self._slo_samples: deque = deque(maxlen=_SLO_SAMPLE_CAP)
        for kind in ("slo_fast_burn", "slo_slow_burn"):
            self.fired.pop(kind, None)

    # -- ingest -------------------------------------------------------------

    def ingest(self, buf: np.ndarray) -> int:
        """Fold one drained ring buffer ((C, R, K) OWNED numpy copy —
        telemetry/ring.snapshot's owned-copy rule: a view of the device
        buffer would be mutated in place by the next donated dispatch)
        into the bounded occupancy history. Overlapping drains re-observe
        rows bit-identically; only windows past the last ingested one are
        appended. Returns the number of FRESH windows ingested (0 when
        the drain re-observed only known rows)."""
        wins = buf[0, :, TELEM_WINDOW]
        fresh = np.nonzero(wins > self._last_window)[0]
        if fresh.size == 0:
            return 0
        order = fresh[np.argsort(wins[fresh], kind="stable")]
        for slot in order.tolist():
            w = int(wins[slot])
            hpa = buf[:, slot, TELEM_HPA_RESERVE].copy()
            ca = buf[:, slot, TELEM_CA_RESERVE].copy()
            head = buf[:, slot, TELEM_POD_HEADROOM].copy()
            active = buf[:, slot, TELEM_LANE_ACTIVE].copy()
            self._points.append((w, hpa, ca, head, active))
            self._last_window = w
        # High-water folds over EVERY fresh row, not just the last one:
        # hpa_reserve_used is non-monotone (scale-downs shrink it), so an
        # intra-drain peak would otherwise be lost.
        for name, col in (
            ("hpa_reserve_used", TELEM_HPA_RESERVE),
            ("ca_reserve_used", TELEM_CA_RESERVE),
        ):
            peak = int(buf[:, order, col].max())
            self._high_water[name] = max(self._high_water.get(name, 0), peak)
        return int(order.size)

    # -- watchdog -----------------------------------------------------------

    def _event(self, kind: str, message: str, **info) -> Dict:
        """Record a watchdog event (bounded trail) WITHOUT warning —
        recoveries are good news; verdicts go through _warn."""
        event = {"kind": kind, "window": self._last_window, "message": message}
        event.update(info)
        self.events.append(event)
        if len(self.events) > self.max_events:
            del self.events[: len(self.events) - self.max_events]
        return event

    def _warn(self, kind: str, message: str, **info) -> Dict:
        event = self._event(kind, message, **info)
        self.fired.setdefault(kind, self._last_window)
        warnings.warn(message, SaturationWarning, stacklevel=3)
        return event

    def _check_reserve(self, name: str, idx: int, warnings_out: list) -> None:
        caps = self.capacities.get(name.replace("_used", ""))
        if caps is None or len(self._points) < self.min_points:
            return
        xs = [p[0] * self.interval for p in self._points]
        ys = np.stack([p[idx] for p in self._points], axis=0)  # (n, C)
        slopes = fit_slope(xs, ys)  # (C,) per sim-second
        now = ys[-1]
        # Non-monotone-gauge semantics (r14): under slot reclaim the
        # occupancy oscillates 0 -> peak -> 0 per churn cycle, and a
        # least-squares fit over a partial cycle reads the up-ramp as a
        # trend with a finite eta. The eta branch therefore also requires
        # the window MINIMUM to sit above the firing floor — a reserve
        # that fully drained inside the fit window is being recycled, not
        # leaked, while a genuine leak ratchets the minimum up until the
        # branch re-arms. The frac >= warn_frac branch stays
        # unconditional: 80% occupancy NOW is worth a verdict regardless
        # of trajectory shape.
        mins = ys.min(axis=0)
        # Worst cluster = smallest ETA, higher occupancy fraction as the
        # tie-break: with several flat-trajectory lanes past warn_frac
        # (eta = inf for all of them), the verdict must name the MOST
        # saturated lane, not whichever lane index came first —
        # heterogeneous fleets are judged per lane (DESIGN §11.3).
        worst_key = None
        worst = None
        for c in range(now.shape[0]):
            cap = float(caps[c]) if c < len(caps) else 0.0
            if cap <= 0.0:
                continue
            frac = float(now[c]) / cap
            eta = time_to_exhaustion(float(now[c]), float(slopes[c]), cap)
            if frac >= self.warn_frac or (
                frac >= self.min_frac
                and float(mins[c]) / cap >= self.min_frac
                and eta <= self.horizon_s
            ):
                key = (eta, -frac)
                if worst_key is None or key < worst_key:
                    worst_key = key
                    worst = (c, frac, eta, cap)
        if worst is not None:
            c, frac, eta, cap = worst
            eta_txt = (
                f"~{eta:.0f} sim-seconds to exhaustion"
                if math.isfinite(eta)
                else "trajectory flat but already past the warning fraction"
            )
            warnings_out.append(
                self._warn(
                    name,
                    f"saturation watchdog: {name} at {frac:.0%} of its "
                    f"reserve on cluster {c} ({int(now[c])}/{int(cap)}), "
                    f"{eta_txt} — the loud reserve bound "
                    "(engine.check_autoscaler_bounds) fires when demand "
                    "outruns it; widen the reserve "
                    "(ca_slot_multiplier / pg_slot_count) or curb churn",
                    cluster=c,
                    used=int(now[c]),
                    capacity=int(cap),
                    eta_s=None if math.isinf(eta) else round(eta, 1),
                )
            )
        elif name in self.fired:
            # Recovery (reclaim-era semantics): reserve occupancy is
            # NON-monotone under slot reclaim, so a previously-fired
            # verdict must CLEAR once every lane drops below the
            # hysteresis fraction with no near-horizon trajectory — a
            # later saturation then re-fires (recover -> re-warn cycle)
            # instead of the first verdict shadowing the whole run.
            worst_frac = 0.0
            for c in range(now.shape[0]):
                cap = float(caps[c]) if c < len(caps) else 0.0
                if cap > 0.0:
                    worst_frac = max(worst_frac, float(now[c]) / cap)
            if worst_frac <= self.recover_frac:
                del self.fired[name]
                warnings_out.append(
                    self._event(
                        f"{name}_recovered",
                        f"saturation watchdog: {name} recovered — "
                        f"occupancy down to {worst_frac:.0%} of the "
                        "reserve on every lane (slot reclaim / churn "
                        "trough); the verdict re-arms",
                        frac=round(worst_frac, 4),
                    )
                )

    def _check_headroom(self, warnings_out: list) -> None:
        # One verdict per run: approaching the trace end is expected and
        # monotone — repeating it every drain would be noise (the reserve
        # verdicts DO repeat: their trajectories can keep worsening).
        if "pod_headroom" in self.fired:
            return
        if len(self._points) < self.min_points:
            return
        ys = np.stack([p[3] for p in self._points], axis=0)  # (n, C)
        now = ys[-1]
        bounded = now < UNBOUNDED_SENTINEL
        if not bool(bounded.any()):
            return
        xs = [p[0] * self.interval for p in self._points]
        slopes = fit_slope(xs, ys)
        for c in np.nonzero(bounded)[0].tolist():
            eta = time_to_exhaustion(
                float(now[c]), float(slopes[c]), None, falling=True
            )
            # Running out of plain-trace headroom is NORMAL at trace end;
            # only a projected exhaustion well inside the horizon with
            # headroom still nonzero is worth a line (feeder/window
            # tuning, not a failure).
            if 0.0 < eta <= self.horizon_s and now[c] > 0:
                warnings_out.append(
                    self._warn(
                        "pod_headroom",
                        f"saturation watchdog: sliding-window trace "
                        f"headroom on cluster {c} is {int(now[c])} columns "
                        f"and falling (~{eta:.0f} sim-seconds to trace "
                        "end) — expected near end of trace; if early, the "
                        "stream segment/pod window is undersized",
                        cluster=c,
                        headroom=int(now[c]),
                        eta_s=round(eta, 1),
                    )
                )
                break  # one headroom line per observe is plenty

    def _check_lanes(self, warnings_out: list) -> None:
        """Idle-lane-waste verdict (lane-async fleets): a lane whose
        lane_active bit was 0 for more than (1 - lane_idle_frac) of the
        recent windows is burning dispatched lane-windows without
        simulating anything — the open-loop client is underfeeding the
        queue or the pump span badly overshoots the horizon mix. One
        verdict per run (the idle fraction can only be cured by feeding
        the queue, and repeating it every drain would be noise). Vacuous
        outside lane-async builds: the column is constant 1 there."""
        if "lane_idle" in self.fired:
            return
        if len(self._points) < self.min_points:
            return
        ys = np.stack([p[4] for p in self._points], axis=0)  # (n, C)
        if not bool((ys == 0).any()):
            return
        fracs = (ys > 0).mean(axis=0)  # (C,) active fraction
        worst = int(np.argmin(fracs))
        if float(fracs[worst]) < self.lane_idle_frac:
            warnings_out.append(
                self._warn(
                    "lane_idle",
                    f"saturation watchdog: lane {worst} was active for "
                    f"only {float(fracs[worst]):.0%} of the last "
                    f"{ys.shape[0]} windows (floor "
                    f"{self.lane_idle_frac:.0%}) — dispatched lane-"
                    "windows are being discarded; feed the submit queue "
                    "or shrink the pump span (KTPU_LANE_SPAN)",
                    lane=worst,
                    active_frac=round(float(fracs[worst]), 4),
                    windows=int(ys.shape[0]),
                )
            )

    def _check_slo(self, warnings_out: list) -> None:
        """Latency-SLO burn-rate verdicts (armed by KTPU_SLO_MS): the
        violating fraction of recent queries against the 1% error budget,
        judged over two wall windows — fast (KTPU_SLO_BURN_WINDOW, 14.4x
        threshold: pager material) and slow (12x the window, 6x: a
        ticket). A latency regression burns the budget the moment slow
        queries land, so this fires while lane occupancy still looks
        perfect — strictly before the idle-lane or reserve verdicts see
        anything. Hysteresis like the reserve verdicts: a fired kind
        clears (and re-arms) once its burn rate drops to half the firing
        threshold."""
        if self.slo_ms is None or not self._slo_samples:
            return
        now = time.monotonic()
        for kind, window, threshold in (
            ("slo_fast_burn", self.slo_burn_window_s, SLO_FAST_BURN),
            ("slo_slow_burn", 12.0 * self.slo_burn_window_s, SLO_SLOW_BURN),
        ):
            total = 0
            bad = 0
            for t, violated in reversed(self._slo_samples):
                if now - t > window:
                    break
                total += 1
                bad += int(violated)
            if total < SLO_MIN_SAMPLES:
                continue
            burn = (bad / total) / SLO_ERROR_BUDGET
            if burn >= threshold:
                warnings_out.append(
                    self._warn(
                        kind,
                        f"saturation watchdog: {kind.replace('_', ' ')} — "
                        f"{bad}/{total} queries over the {self.slo_ms:g}ms "
                        f"SLO in the last {window:g}s wall window, burn "
                        f"rate {burn:.1f}x the {SLO_ERROR_BUDGET:.0%} "
                        f"error budget (threshold {threshold}x) — slow "
                        "lanes are eating the budget while occupancy "
                        "still looks healthy; shed load or add lanes",
                        burn_rate=round(burn, 2),
                        window_s=round(window, 1),
                        violations=bad,
                        samples=total,
                        slo_ms=self.slo_ms,
                    )
                )
            elif kind in self.fired and burn <= threshold / 2.0:
                del self.fired[kind]
                warnings_out.append(
                    self._event(
                        f"{kind}_recovered",
                        f"saturation watchdog: {kind.replace('_', ' ')} "
                        f"recovered — burn rate down to {burn:.1f}x "
                        f"(clear threshold {threshold / 2.0:g}x); the "
                        "verdict re-arms",
                        burn_rate=round(burn, 2),
                        window_s=round(window, 1),
                    )
                )

    def _check_pipeline(
        self, dispatch_stats: Optional[Dict], sync_budget: Optional[Dict],
        feeder: Optional[Dict], warnings_out: list,
    ) -> None:
        if sync_budget:
            expected = sync_budget.get("steady_state_expected", 0)
            observed = sync_budget.get("observed_slide_syncs", 0)
            # The budget is EXACT only in the pure superspan steady state
            # (tests/test_superspan.py's equality gate); mixed ladder
            # engines legitimately pay extra slide syncs on their unfused
            # advances, so a verdict there would be noise.
            exact_regime = bool(dispatch_stats) and (
                dispatch_stats.get("superspans", 0) > 0
                and dispatch_stats.get("window_chunks", 0) == 0
            )
            if exact_regime and expected > 0 and observed > expected:
                warnings_out.append(
                    self._warn(
                        "sync_budget",
                        f"saturation watchdog: {observed} blocking slide "
                        f"syncs observed vs the documented steady-state "
                        f"budget of {expected} (1 progress readback per "
                        "superspan + 1 shift readback per fused slide) — "
                        "a new host sync crept into the dispatch loop",
                        observed=observed,
                        expected=expected,
                    )
                )
        if feeder and dispatch_stats:
            produced = dispatch_stats.get("feeder_slabs_produced", 0)
            installed = dispatch_stats.get("stage_refills", 0)
            depth = feeder.get("ring_capacity", 1)
            if produced - installed > max(4, 2 * depth):
                warnings_out.append(
                    self._warn(
                        "feeder_waste",
                        f"saturation watchdog: feeder produced {produced} "
                        f"slabs but only {installed} were installed — "
                        "run-ahead production is being discarded (stride "
                        "too small for this geometry; widen the stream "
                        "segment)",
                        produced=produced,
                        installed=installed,
                    )
                )
            stalls = (
                feeder.get("stalls", {})
                .get("feeder_not_ready", {})
                .get("count", 0)
            )
            if stalls > self._last_stall_not_ready:
                warnings_out.append(
                    self._warn(
                        "feeder_starved",
                        f"saturation watchdog: the dispatch loop stalled "
                        f"{stalls - self._last_stall_not_ready} time(s) "
                        "waiting for an unpublished feeder slab since the "
                        "last drain — the producer is not keeping ahead "
                        "(raise KTPU_STREAM_DEPTH or widen segments)",
                        stalls=stalls,
                    )
                )
            self._last_stall_not_ready = stalls

    # -- observe / report ---------------------------------------------------

    def update_memory(self, resources: Dict) -> None:
        """Fold one resource sample into the watermarks without running
        the watchdog or the exporters (telemetry_report's refresh path)."""
        self._last_resources = dict(resources)
        for key in ("rss_bytes", "device_bytes_in_use"):
            val = resources.get(key)
            if val:
                self._mem_high[key] = max(self._mem_high.get(key, 0), int(val))

    def observe(
        self,
        resources: Optional[Dict] = None,
        dispatch_stats: Optional[Dict] = None,
        sync_budget: Optional[Dict] = None,
        feeder: Optional[Dict] = None,
        fresh: Optional[int] = None,
    ) -> Dict:
        """One drain-point observation: fold the resource sample into the
        watermarks, run the watchdog over the ingested occupancy series,
        and emit the record to every exporter. Everything consumed here
        is a drained host copy — no device access.

        `fresh`: the corresponding ingest()'s fresh-window count. fresh=0
        means the drain re-observed only known rows (a readout call like
        telemetry_report forcing a drain right after one happened) — the
        watermarks still refresh, but the watchdog does not re-judge the
        same data and NOTHING goes to the exporters, so readout APIs stay
        side-effect-free on the JSONL stream (no phantom zero-interval
        records). None (callers without ingest bookkeeping) behaves like
        fresh data."""
        self.samples += 1
        if resources:
            self.update_memory(resources)
        is_fresh = fresh is None or fresh > 0
        fired: list = []
        if self.watchdog and is_fresh:
            self._check_reserve("hpa_reserve_used", 1, fired)
            self._check_reserve("ca_reserve_used", 2, fired)
            self._check_headroom(fired)
            self._check_lanes(fired)
            self._check_slo(fired)
            self._check_pipeline(dispatch_stats, sync_budget, feeder, fired)
        record = {
            "t_wall_s": round(time.time(), 3),
            "window": self._last_window,
            "sim_time_s": round(max(self._last_window, 0) * self.interval, 3),
            "fresh_windows": 0 if fresh is None else int(fresh),
            "occupancy": self.occupancy(),
            "resources": dict(self._last_resources),
            "watchdog": [dict(e) for e in fired],
        }
        if self._lat_hist.count:
            record["queries"] = self.query_stats()
        if fresh is None:
            record["fresh_windows"] = len(self._points)
        if is_fresh:
            for exporter in self.exporters:
                exporter.emit(record)
        return record

    def occupancy(self) -> Dict:
        """Current + high-water occupancy per gauge (cross-cluster worst),
        with capacity and fraction where a reserve exists."""
        out: Dict = {}
        # Lane fault-domain gauge (PR 19): counts per state plus the
        # cumulative quarantine counters. Numeric-only on purpose — the
        # Prometheus exporter's generic occupancy flattener renders each
        # entry as a gauge with zero export-side changes. Pushed by the
        # fleet, so it is current even before the first ring drain.
        if self._lane_states:
            states = self._lane_states
            out["lane_state"] = {
                "active": states.count("active"),
                "idle": states.count("idle"),
                "quarantined": states.count("quarantined"),
                "probe": states.count("probe"),
                "quarantine_events": self._quarantine_total,
                "readmissions": self._readmit_total,
            }
        if not self._points:
            return out
        last = self._points[-1]
        for name, idx in (
            ("hpa_reserve_used", 1),
            ("ca_reserve_used", 2),
        ):
            caps = self.capacities.get(name.replace("_used", ""))
            used = last[idx]
            entry = {
                "used_max": int(used.max()),
                "high_water": self._high_water.get(name, int(used.max())),
            }
            if caps is not None and len(caps) > 0:
                entry["capacity_min"] = int(min(caps))
                # Worst PER-CLUSTER fraction (used[c]/cap[c]) — dividing
                # the max-used cluster by the min-capacity cluster would
                # overstate heterogeneous fleets.
                fracs = [
                    float(used[c]) / float(caps[c])
                    for c in range(min(used.shape[0], len(caps)))
                    if caps[c] > 0
                ]
                if fracs:
                    entry["frac_max"] = round(max(fracs), 4)
            out[name] = entry
        head = last[3]
        bounded = head[head < UNBOUNDED_SENTINEL]
        out["pod_headroom"] = {
            "min": int(bounded.min()) if bounded.size else None,
            "unbounded_clusters": int((head >= UNBOUNDED_SENTINEL).sum()),
        }
        # Lane-occupancy gauge from the lane_active ring column: per-lane
        # active fraction over the bounded point window, reported as the
        # across-lane mean and min (1.0 outside lane-async builds — the
        # column is constant 1 there).
        active = np.stack([p[4] for p in self._points], axis=0)  # (n, C)
        fracs = (active > 0).mean(axis=0)
        out["lane_occupancy"] = {
            "mean": round(float(fracs.mean()), 4),
            "min": round(float(fracs.min()), 4),
        }
        return out

    # -- lane fault domain (lane-async fleet) -------------------------------

    def note_lane_states(self, states: Sequence[str]) -> None:
        """Record the fleet's per-lane state strings ("active"/"idle"/
        "quarantined"/"probe") — pushed at every quarantine/probe/
        re-admission transition so the `lane_state` occupancy gauge and
        the Prometheus export stay current between ring drains."""
        self._lane_states = [str(s) for s in states]

    def note_lane_quarantined(
        self, lane: int, *, backoff_rounds: int, probed: bool = False
    ) -> Dict:
        """Fire the `lane_quarantine` verdict: the fleet pulled a lane
        out of the admission rotation after repeated dispatch faults
        (`probed=True` = a probe dispatch failed and the backoff
        doubled). Clears with hysteresis at re-admission
        (note_lane_readmitted), like the reserve verdicts."""
        self._quarantine_total += 1
        verb = (
            "failed its re-admission probe and was re-quarantined"
            if probed
            else "was quarantined after repeated dispatch faults"
        )
        return self._warn(
            "lane_quarantine",
            f"saturation watchdog: lane {lane} {verb}; probe "
            f"re-admission in {backoff_rounds} pump rounds (exponential "
            "backoff) — queries route around it; a lane that never "
            "re-admits points at poisoned lane state, not weather",
            lane=int(lane),
            backoff_rounds=int(backoff_rounds),
            probed=bool(probed),
        )

    def note_lane_readmitted(self, lane: int, *, probes: int = 1) -> Dict:
        """Quarantine recovery: a probe dispatch drained cleanly and the
        lane rejoined the rotation — the fired verdict clears and
        re-arms (recover -> re-warn cycle, reserve-verdict semantics)."""
        self._readmit_total += 1
        self.fired.pop("lane_quarantine", None)
        return self._event(
            "lane_quarantine_recovered",
            f"saturation watchdog: lane {lane} re-admitted after "
            f"{probes} probe round(s) — quarantine cleared; the verdict "
            "re-arms",
            lane=int(lane),
            probes=int(probes),
        )

    # -- query latency (lane-async fleet) -----------------------------------

    def note_query(
        self,
        latency_s: float,
        queue_wait_s: Optional[float] = None,
        service_s: Optional[float] = None,
    ) -> None:
        """Record one completed query's submit-to-drain wall latency —
        called by the lane-async fleet's pump at the drain boundary (pure
        host floats, no device access). ``queue_wait_s`` / ``service_s``
        carry the submit→admit vs admit→drain split when the caller has
        lifecycle records (the PR 16-era single-number call keeps
        working)."""
        lat = float(latency_s)
        self._lat_hist.record(lat)
        if queue_wait_s is not None:
            self._queue_hist.record(float(queue_wait_s))
        if service_s is not None:
            self._service_hist.record(float(service_s))
        if self.slo_ms is not None:
            self._slo_samples.append(
                (time.monotonic(), lat * 1e3 > self.slo_ms)
            )

    def query_stats(self) -> Dict:
        """Latency percentiles (ms) over the recorded query completions,
        derived from the bounded histogram buckets (O(buckets) memory,
        exact count/sum, percentiles within one bucket width of exact) —
        plus the queue-wait/service split and the native-histogram dump
        the Prometheus exporter renders as ``_bucket``/``_sum``/
        ``_count``."""
        h = self._lat_hist
        if h.count == 0:
            return {"count": 0}
        out: Dict = {"count": h.count}
        out.update(h.percentiles_ms())
        if self._queue_hist.count:
            out["queue_wait"] = self._queue_hist.percentiles_ms()
        if self._service_hist.count:
            out["service"] = self._service_hist.percentiles_ms()
        out["histogram"] = h.to_dict()
        return out

    def report(self) -> Dict:
        """The `telemetry_report()["resources"]` section: occupancy,
        memory watermarks, and the watchdog's verdict trail."""
        return {
            "occupancy": self.occupancy(),
            "memory": {
                **self._last_resources,
                "high_water": dict(self._mem_high),
            },
            "queries": self.query_stats(),
            "lane_states": list(self._lane_states),
            "watchdog": {
                "enabled": self.watchdog,
                "fired": dict(self.fired),
                "events": [dict(e) for e in self.events[-16:]],
                "horizon_s": self.horizon_s,
                "warn_frac": self.warn_frac,
                "slo_ms": self.slo_ms,
                "slo_burn_window_s": self.slo_burn_window_s,
            },
            "samples": self.samples,
        }


# --- the autotuner's objective readout (PR 20, tune/) ------------------------

# Each fired stall/occupancy verdict scales the per-window cost by this
# much: a config that is 10% faster but starves the feeder or saturates
# a reserve should lose to a clean one. 0.25 is deliberately blunt —
# verdicts are rare binary events, not a second cost axis to tune.
VERDICT_PENALTY_FRAC = 0.25


def tuning_objective(report: Dict) -> Dict:
    """Fold one engine `telemetry_report()` into the autotuner's scalar
    objective: the per-window window-program cost line (dispatch + the
    blocking readback waits over ring windows — THE observable the
    hand A/Bs were sized with, BENCH_r07) scaled by a penalty per
    DISTINCT fired watchdog verdict kind. Pure host dict math on an
    already-drained report — no device values, per this module's
    contract. Returns {ms_per_window, verdicts_fired, penalty, score};
    lower score is better, and a report with no per-window line scores
    0.0 (callers that require windows assert ms_per_window > 0)."""
    per_window = report.get("per_window") or {}
    ms = float(per_window.get("ms_per_window", 0.0))
    watchdog = (report.get("resources") or {}).get("watchdog") or {}
    fired = {
        str(kind): int(count)
        for kind, count in (watchdog.get("fired") or {}).items()
        if count
    }
    penalty = 1.0 + VERDICT_PENALTY_FRAC * len(fired)
    return {
        "ms_per_window": ms,
        "verdicts_fired": fired,
        "penalty": penalty,
        "score": ms * penalty,
    }
