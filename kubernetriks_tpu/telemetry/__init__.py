"""kubernetriks_tpu.telemetry — the composed hot path's flight recorder.

Two synchronized halves (docs/DESIGN.md §"Telemetry"):

- **Host span tracer** (tracer.py): a preallocated ring of
  perf_counter_ns begin/end records over every engine phase — window
  chunks, the fused chunk+slide megastep, superspan dispatches, stage
  prefetch/assembly/upload, slides, window growth, checkpoint I/O — with
  the async shift/progress readbacks modeled as flow events, exported as
  Chrome trace-event JSON (Perfetto) and an aggregated per-phase report.
- **Device metrics ring** (ring.py): per-window scheduling/autoscaler/
  fault aggregates accumulated inside ClusterBatchState and drained only
  at existing host sync boundaries, so telemetry-on adds zero new host
  syncs and stays bit-identical to telemetry-off on every simulation
  leaf.

Plus the capacity half (docs/DESIGN.md §10):

- **Capacity observatory** (observatory.py): reserve-occupancy series
  (the ring's hpa/ca/headroom gauge columns), host/device memory
  watermarks sampled at ring drains, and the saturation watchdog
  (`KTPU_WATCHDOG`) whose time-to-exhaustion estimates fire BEFORE the
  loud reserve bound.
- **Time-series export** (export.py): bounded JSONL drain records + an
  atomic Prometheus-textfile writer, fed strictly from drained host
  copies.

And the query half (docs/DESIGN.md §14, PR 17):

- **Latency histogram** (histogram.py): the log-bucketed streaming
  histogram (O(buckets), exact count/sum, ~5% relative resolution)
  behind the lane-async fleet's per-query latency stats, the
  observatory's `query_stats()`, and the native Prometheus
  `_bucket`/`_sum`/`_count` series — replacing every O(queries) host
  structure on the serving path.

Enable with `KTPU_TRACE=1` (or `BatchedSimulation(telemetry=True)`);
`engine.telemetry_report()` / `engine.write_chrome_trace()` /
`engine.drain_telemetry()` read it out, and `bench.py --trace` embeds
the summary in the BENCH JSON.
"""

from kubernetriks_tpu.telemetry.gauges import GaugeSeries
from kubernetriks_tpu.telemetry.histogram import LatencyHistogram
from kubernetriks_tpu.telemetry.tracer import (
    NULL_TRACER,
    NullTracer,
    PHASE_NAMES,
    SpanTracer,
    log_chunk_throughput,
)

__all__ = [
    "GaugeSeries",
    "LatencyHistogram",
    "NULL_TRACER",
    "NullTracer",
    "PHASE_NAMES",
    "SpanTracer",
    "log_chunk_throughput",
]
