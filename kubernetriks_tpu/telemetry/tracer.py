# ktpu: hot-path
"""Host-side span tracer: the flight recorder's wall-clock half.

Zero-dependency, allocation-free on the hot path: `begin()` is one
`time.perf_counter_ns()` read, `end(phase, t0)` writes one row of a
preallocated int64 ring plus four scalar aggregate updates — measured
well under a microsecond per span, so instrumenting every engine dispatch
perturbs nothing (the <3% overhead gate in tests/test_telemetry.py pins
the end-to-end cost). Phases are small-int constants (no string interning
per record); flow events model the engine's ASYNC readbacks (the fused
slide's 4-byte shift, the superspan's (4,)-i32 progress vector) so the
prefetch/execute overlap — and any stall waiting on a stage — is visible
as an arrow in the rendered trace instead of an inference.

Two consumers:
- `chrome_trace()` — Chrome trace-event JSON (Perfetto-loadable): host
  spans as complete ("X") events, async readbacks as flow ("s"/"f")
  pairs, plus optional device-ring counter tracks on a sim-time process
  (telemetry/ring.py builds those).
- `report()` — the aggregated per-phase table (count / total / mean /
  max), exact even when the event ring wraps, because aggregates update
  on every `end()` rather than from the kept events.

This module carries the `# ktpu: hot-path` pragma ON PURPOSE: the lint
host-sync pass patrols it like the engine, and it stays golden-clean with
ZERO sync-ok waivers — the tracer must never touch a device value.
"""

from __future__ import annotations

import json
import time
from typing import Dict, Optional

import numpy as np

# Span phase ids. Names index PHASE_NAMES; keep both in lockstep.
PH_WINDOW_CHUNK = 0  # run_windows / run_windows_skip dispatch
PH_FUSED_CHUNK_SLIDE = 1  # fused chunk+slide megastep dispatch
PH_SUPERSPAN = 2  # run_superspan dispatch
PH_PROGRESS_WAIT = 3  # blocking superspan progress readback
PH_SHIFT_WAIT = 4  # blocking fused-slide shift readback
PH_STAGE_ASSEMBLE = 5  # host assembly of a staging slab segment
PH_STAGE_PUT = 6  # H2D upload of a staging slab
PH_STAGE_PREFETCH = 7  # double-buffered successor-stage prefetch
PH_REFILL_PREFETCH = 8  # host slide path refill payload prefetch
PH_SLIDE = 9  # pod-window advance (shift + refill apply)
PH_WINDOW_GROW = 10  # in-place pod-window growth
PH_CKPT_SAVE = 11  # checkpoint save I/O
PH_CKPT_RESTORE = 12  # checkpoint restore I/O
PH_PRECOMPILE = 13  # AOT warm-up of dispatch program shapes
PH_CHUNK_FENCED = 14  # instrumented dispatch + device fence (profiled runs)
# Streaming feeder stall split (batched/stream.py): the engine thread
# waited for a staging slab the producer had not PUBLISHED yet (assembly /
# ring backlog bound) vs a published slab whose H2D transfer had not
# SETTLED (transfer bound). Both recorded with explicit durations via
# end(phase, t0, dur=...) from the feeder's consumer side.
PH_STAGE_WAIT_FEEDER = 15
PH_STAGE_WAIT_UPLOAD = 16
# Query-observatory lifecycle stages (PR 17, batched/fleet.py): the
# queue-wait half (submit -> lane admission) and the service half
# (admission -> horizon drain) of every lane-async query, both recorded
# with explicit host durations via end(phase, t0, dur=...) and linked by
# a submit->drain Chrome flow arrow per query.
PH_QUERY_QUEUE = 17
PH_QUERY_SERVICE = 18
# Fault-domain phases (batched/fleet.py): a query's terminal failure
# (span covers submit -> failure delivery, dur from host stamps) and a
# lane's quarantine interval (span covers quarantine fire -> full
# re-admission). Both host-stamped via end(phase, t0, dur=...).
PH_QUERY_FAIL = 19
PH_LANE_QUARANTINE = 20

PHASE_NAMES = (
    "window_chunk",
    "fused_chunk_slide",
    "superspan",
    "progress_wait",
    "shift_wait",
    "stage_assemble",
    "stage_put",
    "stage_prefetch",
    "refill_prefetch",
    "slide",
    "window_grow",
    "ckpt_save",
    "ckpt_restore",
    "precompile",
    "chunk_fenced",
    "stage_wait_feeder",
    "stage_wait_upload",
    "query_queue",
    "query_service",
    "query_fail",
    "lane_quarantine",
)

_N_PHASES = len(PHASE_NAMES)
_FLOW_START = 0
_FLOW_END = 1

# Chrome-trace process ids: pid 0 = host spans, pid 1 = device-ring
# sim-time counter tracks (telemetry/ring.py), pid 2 = fleet lane
# swimlanes (one tid per lane, spans named by the occupying query id).
LANE_PID = 2


class _AnnotatedSpan:
    """Reusable context manager: one recorded span, optionally bridged
    into the active jax.profiler capture as a TraceAnnotation so host
    phases land in the xplane next to the device ops they caused
    (scripts/profile_composed_xplane.py correlates them)."""

    __slots__ = ("_tracer", "_phase", "_t0", "_ann")

    def __init__(self, tracer: "SpanTracer", phase: int):
        self._tracer = tracer
        self._phase = phase
        self._ann = None

    def __enter__(self):
        if self._tracer.annotate:
            try:
                from jax.profiler import TraceAnnotation

                self._ann = TraceAnnotation(PHASE_NAMES[self._phase])
                self._ann.__enter__()
            except Exception:
                self._ann = None
        self._t0 = self._tracer.begin()
        return self

    def __exit__(self, *exc):
        self._tracer.end(self._phase, self._t0)
        if self._ann is not None:
            self._ann.__exit__(*exc)
            self._ann = None
        return False


class SpanTracer:
    def __init__(
        self,
        capacity: int = 1 << 16,
        flow_capacity: int = 1 << 14,
        lane_capacity: int = 1 << 14,
    ):
        # Span event ring: [t0_ns, dur_ns, phase]; kept events wrap, the
        # per-phase aggregates below stay exact regardless.
        self._spans = np.zeros((capacity, 3), np.int64)
        self._n_spans = 0
        # Flow event ring: [t_ns, phase, flow_id, kind].
        self._flows = np.zeros((flow_capacity, 4), np.int64)
        self._n_flows = 0
        self._next_flow = 1
        # Lane-occupancy ring (query observatory): [t0_ns, dur_ns, lane,
        # qid] — rendered as one Perfetto swimlane per fleet lane with
        # the occupying query id as the span name.
        self._lane_spans = np.zeros((lane_capacity, 4), np.int64)
        self._n_lane_spans = 0
        # Exact per-phase aggregates (ns).
        self._agg_count = np.zeros(_N_PHASES, np.int64)
        self._agg_total = np.zeros(_N_PHASES, np.int64)
        self._agg_max = np.zeros(_N_PHASES, np.int64)
        # Freeform counters (stage prefetch hits/misses, dispatch
        # histogram buckets, ...). Host ints only.
        self.counters: Dict[str, int] = {}
        self.enabled = True
        # When True, span() context managers also enter a
        # jax.profiler.TraceAnnotation (set by the engine while a
        # profiler capture is active).
        self.annotate = False
        self._epoch = time.perf_counter_ns()

    # -- hot path ----------------------------------------------------------

    def begin(self) -> int:
        return time.perf_counter_ns()

    def end(self, phase: int, t0: int, dur: Optional[int] = None) -> None:
        dur = (time.perf_counter_ns() - t0) if dur is None else dur
        i = self._n_spans % self._spans.shape[0]
        buf = self._spans
        buf[i, 0] = t0
        buf[i, 1] = dur
        buf[i, 2] = phase
        self._n_spans += 1
        self._agg_count[phase] += 1
        self._agg_total[phase] += dur
        if dur > self._agg_max[phase]:
            self._agg_max[phase] = dur

    def count(self, name: str, n: int = 1) -> None:
        self.counters[name] = self.counters.get(name, 0) + n

    def flow_start(self, phase: int) -> int:
        fid = self._next_flow
        self._next_flow += 1
        self._flow_event(phase, fid, _FLOW_START)
        return fid

    def flow_end(self, phase: int, fid: int) -> None:
        self._flow_event(phase, fid, _FLOW_END)

    def _flow_event(self, phase: int, fid: int, kind: int) -> None:
        i = self._n_flows % self._flows.shape[0]
        buf = self._flows
        buf[i, 0] = time.perf_counter_ns()
        buf[i, 1] = phase
        buf[i, 2] = fid
        buf[i, 3] = kind
        self._n_flows += 1

    def lane_event(self, lane: int, qid: int, t0: int, dur: int) -> None:
        """One lane-occupancy interval: query ``qid`` held fleet lane
        ``lane`` for ``dur`` ns starting at ``t0`` (host clock). Ring
        write only — O(1), no allocation, no device touch."""
        i = self._n_lane_spans % self._lane_spans.shape[0]
        buf = self._lane_spans
        buf[i, 0] = t0
        buf[i, 1] = dur
        buf[i, 2] = lane
        buf[i, 3] = qid
        self._n_lane_spans += 1

    def span(self, phase: int) -> _AnnotatedSpan:
        """Context-manager span for cold paths (checkpoint I/O, the
        instrumented per-chunk loop); hot dispatch sites use begin/end
        directly to stay allocation-free."""
        return _AnnotatedSpan(self, phase)

    # -- export ------------------------------------------------------------

    def _kept(self, buf: np.ndarray, n: int) -> np.ndarray:
        cap = buf.shape[0]
        if n <= cap:
            return buf[:n]
        cut = n % cap
        return np.concatenate([buf[cut:], buf[:cut]], axis=0)

    def chrome_trace(self, extra_events: Optional[list] = None) -> dict:
        """Chrome trace-event JSON dict (load the written file straight
        into Perfetto / chrome://tracing). ts is microseconds relative to
        tracer construction; host spans live on pid 0, the device ring's
        sim-time counter tracks (extra_events, built by telemetry/ring.py)
        on pid 1."""
        ev = [
            {
                "ph": "M",
                "name": "process_name",
                "pid": 0,
                "tid": 0,
                "args": {"name": "ktpu-host"},
            },
            {
                "ph": "M",
                "name": "thread_name",
                "pid": 0,
                "tid": 0,
                "args": {"name": "engine dispatch loop"},
            },
        ]
        epoch = self._epoch
        for t0, dur, phase in self._kept(self._spans, self._n_spans).tolist():
            ev.append(
                {
                    "ph": "X",
                    "name": PHASE_NAMES[int(phase)],
                    "cat": "host",
                    "ts": (t0 - epoch) / 1e3,
                    "dur": dur / 1e3,
                    "pid": 0,
                    "tid": 0,
                }
            )
        for t, phase, fid, kind in self._kept(
            self._flows, self._n_flows
        ).tolist():
            ev.append(
                {
                    "ph": "s" if kind == _FLOW_START else "f",
                    "bp": "e",
                    "name": PHASE_NAMES[int(phase)] + "_readback",
                    "cat": "readback",
                    "id": int(fid),
                    "ts": (t - epoch) / 1e3,
                    "pid": 0,
                    "tid": 0,
                }
            )
        lane_rows = self._kept(self._lane_spans, self._n_lane_spans).tolist()
        if lane_rows:
            ev.append(
                {
                    "ph": "M",
                    "name": "process_name",
                    "pid": LANE_PID,
                    "tid": 0,
                    "args": {"name": "ktpu-lanes"},
                }
            )
            for lane in sorted({int(r[2]) for r in lane_rows}):
                ev.append(
                    {
                        "ph": "M",
                        "name": "thread_name",
                        "pid": LANE_PID,
                        "tid": lane,
                        "args": {"name": f"lane {lane}"},
                    }
                )
            for t0, dur, lane, qid in lane_rows:
                ev.append(
                    {
                        "ph": "X",
                        "name": f"q{int(qid)}",
                        "cat": "lane",
                        "ts": (t0 - epoch) / 1e3,
                        "dur": dur / 1e3,
                        "pid": LANE_PID,
                        "tid": int(lane),
                    }
                )
        if extra_events:
            ev.extend(extra_events)
        return {
            "traceEvents": ev,
            "displayTimeUnit": "ms",
            "otherData": {
                "spans_recorded": int(self._n_spans),
                "spans_kept": int(min(self._n_spans, self._spans.shape[0])),
            },
        }

    def write_chrome_trace(
        self, path: str, extra_events: Optional[list] = None
    ) -> str:
        with open(path, "w") as fh:
            json.dump(self.chrome_trace(extra_events), fh)
        return path

    def report(self) -> dict:
        """Aggregated per-phase wall time (ms totals, µs mean/max) plus
        the freeform counters — exact even when the span ring wrapped."""
        spans = {}
        for pid in range(_N_PHASES):
            n = int(self._agg_count[pid])
            if n == 0:
                continue
            total = int(self._agg_total[pid])
            spans[PHASE_NAMES[pid]] = {
                "count": n,
                "total_ms": total / 1e6,
                "mean_us": total / n / 1e3,
                "max_us": int(self._agg_max[pid]) / 1e3,
            }
        return {
            "spans": spans,
            "counters": dict(self.counters),
            "span_events": {
                "recorded": int(self._n_spans),
                "kept": int(min(self._n_spans, self._spans.shape[0])),
            },
            "lane_spans": {
                "recorded": int(self._n_lane_spans),
                "kept": int(
                    min(self._n_lane_spans, self._lane_spans.shape[0])
                ),
            },
        }


class _NullSpan:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class NullTracer:
    """API-compatible no-op stand-in so the engine's instrumentation sites
    stay branch-free; `begin()` skips the clock read entirely."""

    annotate = False
    enabled = False
    counters: Dict[str, int] = {}

    def begin(self) -> int:
        return 0

    def end(self, phase: int, t0: int, dur: Optional[int] = None) -> None:
        pass

    def count(self, name: str, n: int = 1) -> None:
        pass

    def flow_start(self, phase: int) -> int:
        return 0

    def flow_end(self, phase: int, fid: int) -> None:
        pass

    def lane_event(self, lane: int, qid: int, t0: int, dur: int) -> None:
        pass

    def span(self, phase: int) -> _NullSpan:
        return _NULL_SPAN

    def report(self) -> dict:
        return {
            "spans": {},
            "counters": {},
            "span_events": {"recorded": 0, "kept": 0},
            "lane_spans": {"recorded": 0, "kept": 0},
        }


NULL_TRACER = NullTracer()


def log_chunk_throughput(logger, n_windows, n_clusters, decisions, elapsed):
    """The per-chunk decisions/s + cluster-windows/s log line (TPU analog
    of the scalar events/s log, reference: src/simulator.rs:363-368) — ONE
    owner of the format, shared by the engine's log_throughput path."""
    logger.info(
        "chunk of %d windows in %.3fs: %.0f decisions/s, "
        "%.0f cluster-windows/s",
        n_windows,
        elapsed,
        decisions / max(elapsed, 1e-9),
        n_windows * n_clusters / max(elapsed, 1e-9),
    )
