"""Device-side telemetry ring: build, drain, merge (the flight recorder's
sim-time half).

The ring itself (state.TelemetryRing) is carried INSIDE ClusterBatchState
and written on-device by the window body (step._telemetry_record) — one
(C, TELEMETRY_COLS) int32 row per executed window, scattered at
cursor % R. This module owns everything host-side:

- `init_ring` builds the empty ring the engine attaches at construction;
- `snapshot` drains it to host arrays. The engine calls this ONLY at
  boundaries where the host already blocks — step_until_time exit (where
  bench span fetches land) and readout — NEVER inside the dispatch loop,
  so telemetry-on adds zero new host syncs there and the dispatch-count
  regression gate (tests/test_telemetry.py) holds. Unlike tracer.py,
  this module deliberately opts OUT of the lint pass's hot-path pragma:
  it is the cold drain side, and the one device fetch below is its whole
  purpose.
- `series` merges drained snapshots into one (windows, (Wn, C, K)) view,
  deduped by window index (overlapping snapshots of a wrapping ring
  re-observe the same rows bit-identically).
- `counter_events` renders the merged series as Chrome trace counter
  ("C") tracks on a sim-time process, so the Perfetto view shows queue
  depth / autoscaler actions / fault events against the host span
  timeline.
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp
import numpy as np

from kubernetriks_tpu.batched.state import TELEMETRY_COLS, TelemetryRing

# Column names, indexed by the TELEM_* constants in batched/state.py.
RING_COLUMNS = (
    "window",
    "decisions",
    "queued",
    "unschedulable",
    "hpa_pod_actions",
    "ca_node_actions",
    "fault_events",
    "alive_nodes",
    # Capacity-observatory occupancy gauges (telemetry/observatory.py):
    # live HPA replicas vs the pod-group slot reserve, consumed CA node
    # slots (monotone — the ROADMAP #2 saturation driver), and the
    # remaining plain-trace columns ahead of the sliding pod window.
    "hpa_reserve_used",
    "ca_reserve_used",
    "pod_headroom",
    # Lane-async fleet occupancy bit (state.TELEM_LANE_ACTIVE): 1 when the
    # lane's per-lane clock made it active for the window, constant 1
    # outside lane-async builds. The observatory's lane-occupancy gauge
    # and idle-lane-waste verdict fold this column.
    "lane_active",
)
assert len(RING_COLUMNS) == TELEMETRY_COLS

# Gauges are POINT-IN-TIME readings: summing them across windows (the way
# the per-window action deltas sum into ring totals) is meaningless, so
# report consumers track their high-water mark instead.
GAUGE_COLUMNS = frozenset(
    {
        "queued",
        "unschedulable",
        "alive_nodes",
        "hpa_reserve_used",
        "ca_reserve_used",
        "pod_headroom",
        "lane_active",
    }
)


def init_ring(n_clusters: int, capacity: int) -> TelemetryRing:
    """Empty ring: window column -1 marks unwritten rows (the drain
    filters on it), cursor 0."""
    return TelemetryRing(
        buf=jnp.full(
            (n_clusters, capacity, TELEMETRY_COLS), -1, jnp.int32
        ),
        cursor=jnp.zeros((n_clusters,), jnp.int32),
    )


def snapshot(telem: TelemetryRing) -> Tuple[np.ndarray, int]:
    """Drain the ring to host: ((C, R, K) buffer copy, total windows
    recorded). Blocking device fetch — callers sit at an existing host
    sync boundary (readout / step_until_time exit), outside the
    sanitizer's transfer-guard region. np.array (owned COPY, not a view):
    on the CPU backend device_get can alias the device buffer, and the
    next DONATED dispatch would mutate the buffer — and the snapshot —
    in place."""
    from kubernetriks_tpu.parallel.multihost import to_host

    buf = np.array(to_host(telem.buf))
    cursor = int(np.asarray(to_host(telem.cursor)).max())
    return buf, cursor


def merge_snapshot(seen: dict, buf: np.ndarray) -> None:
    """Fold one drained buffer into the window->row accumulator (keys:
    window index, values: (C, K) rows). Overlapping snapshots of a
    wrapping ring re-observe the same rows bit-identically, so last-write
    dedupe is exact; the dict keeps memory bounded by DISTINCT windows,
    not drain count."""
    wins = buf[0, :, 0]  # (R,) window column, uniform across clusters
    for slot in np.nonzero(wins >= 0)[0]:
        seen[int(wins[slot])] = buf[:, slot, :]


def series(seen: dict, n_clusters: int) -> Tuple[np.ndarray, np.ndarray]:
    """Accumulated records as (windows (Wn,), data (Wn, C, K)), sorted by
    window index."""
    if not seen:
        return (
            np.zeros((0,), np.int32),
            np.zeros((0, n_clusters, TELEMETRY_COLS), np.int32),
        )
    order = sorted(seen)
    wins = np.asarray(order, np.int32)
    data = np.stack([seen[w] for w in order], axis=0)  # (Wn, C, K)
    return wins, data


def counter_events(
    wins: np.ndarray, data: np.ndarray, interval: float, pid: int = 1
) -> list:
    """Chrome trace counter tracks from the merged ring series, on a
    sim-time process (ts = window * interval in sim-µs): cross-cluster
    sums per window for each ring column past the window index."""
    ev = [
        {
            "ph": "M",
            "name": "process_name",
            "pid": pid,
            "tid": 0,
            "args": {"name": "ktpu-device-ring (sim time)"},
        }
    ]
    if len(wins) == 0:
        return ev
    totals = data.sum(axis=1)  # (Wn, K) summed over clusters
    for i, w in enumerate(wins.tolist()):
        ts = w * interval * 1e6
        for col in range(1, TELEMETRY_COLS):
            ev.append(
                {
                    "ph": "C",
                    "name": RING_COLUMNS[col],
                    "pid": pid,
                    "ts": ts,
                    "args": {RING_COLUMNS[col]: int(totals[i, col])},
                }
            )
    return ev
