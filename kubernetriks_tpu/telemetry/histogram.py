# ktpu: hot-path
"""Log-bucketed streaming latency histogram (PR 17 query observatory).

The lane-async fleet used to remember every query latency in a host dict
(``query_latency_s: Dict[int, float]``) and the observatory mirrored the
tail in a deque — both O(queries), exactly the unbounded term the
bounded-memory discipline (PR 15) forbids.  This module replaces both
with a fixed-size geometric histogram:

* **Buckets** — upper boundaries ``LO * GROWTH**i`` with ``GROWTH =
  1.05`` (~5% relative resolution), ``LO = 1 µs``; bucket 0 is the
  underflow bucket (``v <= LO``) and the last bucket is the overflow
  bucket (``v > LO * GROWTH**(n-2)``, upper bound +Inf).  ~520 buckets
  cover 1 µs .. ~10⁵ s.
* **Exactness** — ``count`` and ``sum_s`` are exact (integer count,
  float accumulation); only the per-sample position is quantised.
* **Percentiles** — :meth:`percentile` reproduces the rank convention
  of ``numpy.percentile(..., method="higher")`` over the bucketed
  counts and returns the upper boundary of the rank's bucket, so the
  result is within one :meth:`bucket_width` of the exact same-convention
  percentile while both exist (pinned by tests/test_soak.py and the
  in-bench assert in ``bench.py run_open_loop``).

Pure host code: no jax, no device reads, O(buckets) memory forever —
safe under the hot-path pragma with zero sync waivers.
"""

from __future__ import annotations

import math
from typing import Dict, List, Tuple

import numpy as np

__all__ = ["LatencyHistogram", "GROWTH", "LO_SECONDS"]

GROWTH = 1.05  # geometric bucket ratio: ~5% relative bucket resolution
LO_SECONDS = 1e-6  # first upper boundary: 1 µs (underflow bucket below)
_HI_SECONDS = 1e5  # coverage target for the last finite boundary
_LOG_GROWTH = math.log(GROWTH)
# Finite boundaries LO*G^0 .. LO*G^(N_BUCKETS-2); last bucket is +Inf.
N_BUCKETS = 2 + int(math.ceil(math.log(_HI_SECONDS / LO_SECONDS) / _LOG_GROWTH))


class LatencyHistogram:
    """Bounded streaming histogram over positive latencies in seconds."""

    __slots__ = ("_counts", "count", "sum_s", "min_s", "max_s")

    def __init__(self) -> None:
        self._counts = np.zeros(N_BUCKETS, np.int64)
        self.count = 0
        self.sum_s = 0.0
        self.min_s = math.inf
        self.max_s = 0.0

    # ------------------------------------------------------------------
    # ingest
    # ------------------------------------------------------------------
    def reset(self) -> None:
        self._counts[:] = 0
        self.count = 0
        self.sum_s = 0.0
        self.min_s = math.inf
        self.max_s = 0.0

    def record(self, value_s: float) -> None:
        """O(1) insert; memory never grows (fixed bucket array)."""
        v = float(value_s)
        self._counts[self._index(v)] += 1
        self.count += 1
        self.sum_s += v
        if v < self.min_s:
            self.min_s = v
        if v > self.max_s:
            self.max_s = v

    @staticmethod
    def _index(v: float) -> int:
        if v <= LO_SECONDS:
            return 0
        # ceil with a small backlash so exact boundaries LO*G^k stay in
        # bucket k despite float log error.
        i = int(math.ceil(math.log(v / LO_SECONDS) / _LOG_GROWTH - 1e-9))
        if i < 1:
            return 1
        if i > N_BUCKETS - 1:
            return N_BUCKETS - 1
        return i

    # ------------------------------------------------------------------
    # boundaries
    # ------------------------------------------------------------------
    @staticmethod
    def upper_bound(i: int) -> float:
        """Upper boundary of bucket ``i`` (seconds; +Inf for the last)."""
        if i >= N_BUCKETS - 1:
            return math.inf
        return LO_SECONDS * GROWTH**i

    @classmethod
    def bucket_width(cls, value_s: float) -> float:
        """Width of the bucket containing ``value_s`` — the quantisation
        tolerance for the one-bucket-width percentile guarantee."""
        i = cls._index(float(value_s))
        if i >= N_BUCKETS - 1:
            return math.inf
        hi = cls.upper_bound(i)
        if i == 0:
            return hi  # underflow bucket spans (0, LO]
        return hi - hi / GROWTH

    @property
    def n_buckets(self) -> int:
        return N_BUCKETS

    def footprint_bytes(self) -> int:
        """Host bytes held by the bucket array — constant for life
        (pinned O(buckets), not O(queries), by the 100k soak)."""
        return int(self._counts.nbytes)

    # ------------------------------------------------------------------
    # stats
    # ------------------------------------------------------------------
    def percentile(self, q: float) -> float:
        """Bucket-derived percentile in seconds.

        Matches ``numpy.percentile(samples, q, method="higher")``: rank
        ``j = ceil(q/100 * (n-1))`` (0-based), then the upper boundary of
        the bucket holding the (j+1)-th sample.  The overflow bucket
        reports the exact observed maximum (its boundary is +Inf).
        """
        n = self.count
        if n == 0:
            return 0.0
        j = int(math.ceil(q / 100.0 * (n - 1) - 1e-12))
        if j < 0:
            j = 0
        if j > n - 1:
            j = n - 1
        cum = 0
        target = j + 1
        for i in range(N_BUCKETS):
            cum += int(self._counts[i])
            if cum >= target:
                if i >= N_BUCKETS - 1:
                    return self.max_s
                return self.upper_bound(i)
        return self.max_s  # unreachable: cum == count after the loop

    def percentiles_ms(self) -> Dict[str, float]:
        """p50/p95/p99 in milliseconds from the buckets (empty → {})."""
        if self.count == 0:
            return {}
        return {
            "p50_ms": round(self.percentile(50.0) * 1e3, 3),
            "p95_ms": round(self.percentile(95.0) * 1e3, 3),
            "p99_ms": round(self.percentile(99.0) * 1e3, 3),
        }

    def buckets(self) -> List[Tuple[float, int]]:
        """Sparse cumulative buckets: ``[(le_seconds, cumulative_count)]``
        for every bucket with a nonzero increment, ending with the
        ``(+Inf, count)`` catch-all — the native Prometheus histogram
        series (``_bucket{le=...}``)."""
        out: List[Tuple[float, int]] = []
        if self.count == 0:
            return out
        nz = np.nonzero(self._counts)[0]
        cum = np.cumsum(self._counts[nz])
        for k in range(len(nz)):
            i = int(nz[k])
            le = self.upper_bound(i)
            if not math.isinf(le):
                out.append((float(f"{le:.9g}"), int(cum[k])))
        out.append((math.inf, self.count))
        return out

    def to_dict(self) -> Dict[str, object]:
        """JSON-safe summary (``+Inf`` boundary rendered as a string)."""
        return {
            "count": self.count,
            "sum_s": round(self.sum_s, 9),
            "buckets": [
                ["+Inf" if math.isinf(le) else le, cum]
                for le, cum in self.buckets()
            ],
        }
