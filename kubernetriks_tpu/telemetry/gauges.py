"""Gauge time-series buffer: the per-window gauge instrumentation path,
ported out of the engine onto the telemetry package (PR 8 satellite).

The engine used to hold two parallel lists (`_gauge_windows` /
`_gauge_samples`) and repeat the concat/CSV/npz-sidecar logic across
four methods; this class is the one owner of that series. The engine
still performs the device fetches at its (waived, instrumented-path)
sync sites and hands HOST arrays in — this module never touches device
values."""

from __future__ import annotations

import csv
import os
from typing import List

import numpy as np


class GaugeSeries:
    """Accumulated (window-idx, (Wn, C, 7) sample) gauge chunks; columns
    follow the scalar GAUGE_CSV_COLUMNS after the timestamp."""

    def __init__(self) -> None:
        self._windows: List[np.ndarray] = []
        self._samples: List[np.ndarray] = []

    def __bool__(self) -> bool:
        return bool(self._windows)

    def append(self, windows: np.ndarray, samples: np.ndarray) -> None:
        """One chunk: windows (Wn,) int array, samples (Wn, C, 7) host
        array (already fetched by the caller)."""
        self._windows.append(np.asarray(windows))
        self._samples.append(np.asarray(samples))

    def series(self, n_clusters: int, interval: float):
        """(times (W,), samples (W, C, 7)); empty arrays when no gauges
        were collected."""
        if not self._samples:
            return np.zeros((0,)), np.zeros((0, n_clusters, 7))
        times = np.concatenate(self._windows).astype(np.float64) * interval
        return times, np.concatenate(self._samples, axis=0)

    def write_csv(
        self, path: str, cluster: int, n_clusters: int, interval: float
    ) -> None:
        """One cluster's series in the scalar collector's 8-column schema
        (reference: src/metrics/collector.rs:216-228), so offline tooling
        consumes either backend's output unchanged."""
        from kubernetriks_tpu.metrics.collector import GAUGE_CSV_COLUMNS

        times, samples = self.series(n_clusters, interval)
        with open(path, "w", newline="") as f:
            writer = csv.writer(f)
            writer.writerow(GAUGE_CSV_COLUMNS)
            for i, t in enumerate(times):
                row = samples[i, cluster]
                writer.writerow(
                    [t, int(row[0]), int(row[1]), int(row[2]),
                     float(row[3]), float(row[4]), float(row[5]),
                     float(row[6])]
                )

    def save_sidecar(self, path: str) -> None:
        """Persist next to a checkpoint; an empty series REMOVES a stale
        sidecar so a previous save's gauges never shadow this run's on
        restore."""
        if self._windows:
            np.savez(
                path,
                windows=np.concatenate(self._windows).astype(np.int32),
                samples=np.concatenate(self._samples, axis=0).astype(
                    np.float32
                ),
            )
        elif os.path.exists(path):
            os.remove(path)

    @classmethod
    def load_sidecar(cls, path: str) -> "GaugeSeries":
        out = cls()
        if os.path.exists(path):
            data = np.load(path)
            out.append(data["windows"], data["samples"])
        return out
