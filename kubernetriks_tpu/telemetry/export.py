# ktpu: hot-path
"""Time-series export seams for the capacity observatory: bounded JSONL
append + Prometheus-textfile writer.

Both exporters consume the PURE-PYTHON drain records / reports the
observatory builds from drained host copies — never a device value, never
a jax import. This module carries the `# ktpu: hot-path` pragma ON
PURPOSE (like tracer.py and observatory.py) and stays golden-clean with
ZERO sync-ok waivers: an export hook is exactly the place a careless
`np.asarray(state...)` would smuggle a host sync into the drain path, so
the lint host-sync pass patrols it (seeded fixture:
tests/lint_fixtures/hostsync_export_hook.py).

- `JsonlExporter` appends one JSON object per drain record, BOUNDED: when
  the file would exceed `max_bytes` it rotates to `<path>.1` (replacing
  the previous rotation), so an endurance run's metrics file is capped at
  ~2x max_bytes no matter how many weeks it simulates. Tail-friendly:
  `tail -f metrics.jsonl | jq .occupancy`.
- `write_prometheus_textfile` renders the latest telemetry report as
  Prometheus text exposition format via tmp+rename (atomic — the
  node_exporter textfile collector's contract), so standard scrape
  tooling can watch a resident fleet without any HTTP endpoint in the
  engine.
"""

from __future__ import annotations

import json
import math
import os
from typing import Dict, List, Optional


class JsonlExporter:
    """Bounded JSONL appender for observatory drain records."""

    def __init__(self, path: str, max_bytes: int = 8 << 20) -> None:
        self.path = path
        self.max_bytes = int(max_bytes)
        self.lines_written = 0
        directory = os.path.dirname(os.path.abspath(path))
        if directory:
            os.makedirs(directory, exist_ok=True)

    def emit(self, record: Dict) -> None:
        line = json.dumps(record, sort_keys=True) + "\n"
        try:
            size = os.path.getsize(self.path)
        except OSError:
            size = 0
        if size and size + len(line) > self.max_bytes:
            # Rotate: the previous window of history survives as .1, the
            # live file restarts — total footprint <= ~2x max_bytes.
            os.replace(self.path, self.path + ".1")
        with open(self.path, "a") as fh:
            fh.write(line)
        self.lines_written += 1


def _prom_escape(value: str) -> str:
    return value.replace("\\", "\\\\").replace('"', '\\"')


def _num(value) -> Optional[float]:
    if isinstance(value, bool):
        return 1.0 if value else 0.0
    if isinstance(value, (int, float)):
        return float(value)
    return None


def prometheus_lines(report: Dict, prefix: str = "ktpu_") -> List[str]:
    """Render a telemetry report (engine.telemetry_report()) as Prometheus
    text exposition lines: dispatch counters, the sync budget, the ring
    totals, and the capacity observatory's occupancy/memory gauges."""
    lines: List[str] = []

    def gauge(name: str, value, labels: Optional[Dict[str, str]] = None):
        num = _num(value)
        if num is None:
            return
        label_txt = ""
        if labels:
            inner = ",".join(
                f'{k}="{_prom_escape(str(v))}"' for k, v in sorted(labels.items())
            )
            label_txt = "{" + inner + "}"
        # Precision-preserving rendering: %g would round integers past 6
        # significant digits (an endurance run's window counters / byte
        # watermarks must stay exact; repr round-trips floats).
        txt = (
            str(int(num))
            if math.isfinite(num) and num == int(num)
            else repr(num)
        )
        lines.append(f"{prefix}{name}{label_txt} {txt}")

    for key, value in (report.get("dispatch_stats") or {}).items():
        gauge("dispatch_total", value, {"kind": key})
    budget = report.get("sync_budget") or {}
    gauge("sync_budget_expected", budget.get("steady_state_expected"))
    gauge("sync_budget_observed", budget.get("observed_slide_syncs"))
    ring = report.get("ring") or {}
    gauge("ring_windows_recorded", ring.get("windows_recorded"))
    gauge("ring_windows_kept", ring.get("windows_kept"))
    for key, value in (ring.get("totals") or {}).items():
        gauge("ring_total", value, {"column": key})
    resources = report.get("resources") or {}
    for name, entry in (resources.get("occupancy") or {}).items():
        if not isinstance(entry, dict):
            continue
        for field, value in entry.items():
            gauge("occupancy", value, {"gauge": name, "field": field})
    memory = resources.get("memory") or {}
    for key, value in memory.items():
        if key == "high_water":
            for hw_key, hw_val in value.items():
                gauge("memory_high_water_bytes", hw_val, {"kind": hw_key})
        elif isinstance(value, dict):
            for sub_key, sub_val in value.items():
                gauge("memory_bytes", sub_val, {"kind": f"{key}.{sub_key}"})
        else:
            gauge("memory_bytes", value, {"kind": key})
    queries = resources.get("queries") or {}
    for key, value in queries.items():
        # Lane-async per-query latency stats (observatory query_stats):
        # count + p50/p95/p99 in ms, with the queue_wait/service split
        # flattened into the stat label.
        if key == "histogram":
            continue
        if isinstance(value, dict):
            for sub_key, sub_val in value.items():
                gauge("query_latency", sub_val, {"stat": f"{key}_{sub_key}"})
        else:
            gauge("query_latency", value, {"stat": key})
    hist = queries.get("histogram") or {}
    if hist:
        # Native Prometheus histogram series from the bounded log-bucket
        # histogram: cumulative _bucket{le=...} samples (sparse — only
        # boundaries with nonzero increments, "+Inf" last), exact _sum
        # and _count, values under the same precision-preserving rule as
        # every other sample.
        for le, cum in hist.get("buckets") or []:
            le_num = _num(le)
            le_txt = (
                le
                if le_num is None
                else (
                    str(int(le_num))
                    if le_num == int(le_num)
                    else repr(le_num)
                )
            )
            gauge(
                "query_latency_seconds_bucket", cum, {"le": str(le_txt)}
            )
        gauge("query_latency_seconds_sum", hist.get("sum_s"))
        gauge("query_latency_seconds_count", hist.get("count"))
    watchdog = (resources.get("watchdog") or {})
    gauge("watchdog_enabled", watchdog.get("enabled"))
    for kind, window in (watchdog.get("fired") or {}).items():
        gauge("watchdog_fired_window", window, {"kind": kind})
    gauge("observatory_samples", resources.get("samples"))
    return lines


def write_prometheus_textfile(
    path: str, report: Dict, prefix: str = "ktpu_"
) -> str:
    """Atomically write the report as a Prometheus textfile (tmp+rename —
    a scraping node_exporter never sees a torn file)."""
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        fh.write("\n".join(prometheus_lines(report, prefix)) + "\n")
    os.replace(tmp, path)
    return path
