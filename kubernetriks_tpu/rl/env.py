"""RL environment: the batched simulator driven by a learned scheduler policy.

The policy replaces the KubeScheduler filter/score pass at the same seam the
scalar path exposes via PodSchedulingAlgorithm (reference:
src/core/scheduler/interface.rs:14-23): per pending pod, node logits over the
cluster's nodes, action-masked to Fit-feasible nodes. Everything else — trace
events, queues, finishes, delays, metrics — is the unmodified batched step, so
the policy trains against exactly the simulated control-plane dynamics.

A rollout scans scheduling windows on-device, recording per-decision
transitions (features, action, log-prob, value, reward) for PPO.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from kubernetriks_tpu.batched.state import ClusterBatchState, StepConstants, TraceSlab
from kubernetriks_tpu.batched.step import (
    _apply_window_events,
    commit_cycle,
    cycle_timing,
    decision_metrics,
    prepare_cycle,
)

INF = jnp.inf


class Transition(NamedTuple):
    """One scheduling decision per (cluster,) slice; stacked over (W, K)."""

    obs: jnp.ndarray  # (..., C, N, F) node features
    action: jnp.ndarray  # (..., C) chosen node (or argmax'd park)
    log_prob: jnp.ndarray  # (..., C)
    value: jnp.ndarray  # (..., C)
    reward: jnp.ndarray  # (..., C)
    valid: jnp.ndarray  # (..., C) decision actually happened


def featurize(
    alive, alloc_cpu, alloc_ram, cap_cpu, cap_ram, req_cpu, req_ram
) -> jnp.ndarray:
    """Per-node features for one pending pod: (C, N, F). The action mask's
    feasibility channel is the scheduler pipeline's Fit device plugin
    (batched/pipeline.py) — the policy's action space and the
    kube-scheduler's filter chain agree on what "fits" means."""
    from kubernetriks_tpu.batched.pipeline import profile_fit_mask, DEFAULT_PROFILE

    cap_cpu_f = jnp.maximum(cap_cpu.astype(jnp.float32), 1.0)
    cap_ram_f = jnp.maximum(cap_ram.astype(jnp.float32), 1.0)
    fits = profile_fit_mask(
        DEFAULT_PROFILE, alive, alloc_cpu, alloc_ram,
        req_cpu[:, None], req_ram[:, None],
    )
    return jnp.stack(
        [
            alive.astype(jnp.float32),
            fits.astype(jnp.float32),
            alloc_cpu.astype(jnp.float32) / cap_cpu_f,
            alloc_ram.astype(jnp.float32) / cap_ram_f,
            req_cpu.astype(jnp.float32)[:, None] / cap_cpu_f,
            req_ram.astype(jnp.float32)[:, None] / cap_ram_f,
        ],
        axis=-1,
    )


def policy_cycle(
    state: ClusterBatchState,
    W: jnp.ndarray,
    consts: StepConstants,
    K: int,
    policy_apply,
    params,
    rng: jnp.ndarray,
    greedy: bool = False,
    conditional_move: bool = False,
    reward_size_weighted: bool = False,
    shaping_coef: float = 0.0,
    shaping_gamma: float = 0.99,
    wake=None,
) -> Tuple[ClusterBatchState, Transition]:
    """One scheduling cycle (at window index W) where the policy picks nodes;
    returns the K per-cluster transitions. Action space = nodes, masked to
    Fit-feasible ones; no feasible node -> the pod parks unschedulable (like
    the Fit filter).

    Reward options (defaults preserve the plain +1/-1 reward):
    - reward_size_weighted: placements/parks pay req_cpu/node_cap instead of
      1 — capacity-weighted throughput, so stranding a full-node pod costs
      what a full node's worth of small pods earns.
    - shaping_coef (alpha): reward shaping F = gamma*phi(s') - phi(s) with
      phi = alpha * (count of whole-free alive nodes), applied per decision.
      Fragmenting a pristine node is charged AT the decision that fragments
      it instead of hundreds of decisions later when a large pod parks — the
      credit horizon collapses from O(rollout) to O(1). NOTE: this is
      potential-based (Ng/Harada/Russell 1999) only over the decision
      subsequence; phi changes caused by environment transitions between
      windows (pod finishes re-emptying nodes, CA scale-ups) carry no
      compensating term, so a small bias against fragmenting pristine nodes
      remains even where the trace would make it free. Measured on the
      bimodal proof scenario this bias points toward the true optimum
      (best-fit packing) and the trained greedy policy converges exactly to
      it (scripts/train_rl_proof.py, docs/RL_LEARNING.json)."""
    C, P = state.pods.phase.shape
    N = state.nodes.alive.shape[1]
    rows1 = jnp.arange(C, dtype=jnp.int32)

    cc = prepare_cycle(state, W, consts, K, conditional_move, wake)
    alive = state.nodes.alive

    alive_count = alive.sum(axis=1, dtype=jnp.int32).astype(jnp.float32)
    pod_sched_time = jnp.float32(consts.time_per_node) * alive_count
    # Timing mechanics shared with the kube paths (batched/step.py).
    pod_queue_time_k, start_s_k, park_s_k = cycle_timing(
        cc.valid, cc.waited, pod_sched_time, consts
    )

    def body(carry, xs):
        alloc_cpu, alloc_ram, rng = carry
        valid, req_cpu, req_ram, pod_queue_time = xs

        obs = featurize(
            alive, alloc_cpu, alloc_ram, state.nodes.cap_cpu, state.nodes.cap_ram,
            req_cpu, req_ram,
        )
        fit = obs[..., 1] > 0  # (C, N)
        any_fit = fit.any(axis=1)

        logits, value = policy_apply(params, obs)  # (C, N), (C,)
        # Finite mask value (not -inf): keeps softmax/log_softmax gradients
        # NaN-free while making masked nodes unselectable.
        masked_logits = jnp.where(fit, logits, -1e9)
        # Guard fully-infeasible rows (uniform over nodes; decision is a park).
        safe_logits = jnp.where(
            any_fit[:, None], masked_logits, jnp.zeros_like(masked_logits)
        )
        rng, sub = jax.random.split(rng)
        sampled = jax.random.categorical(sub, safe_logits, axis=-1)
        best = jax.lax.argmax(safe_logits, 1, jnp.int32)
        action = jnp.where(greedy, best, sampled).astype(jnp.int32)
        log_probs = jax.nn.log_softmax(safe_logits, axis=-1)
        log_prob = log_probs[rows1, action]

        assign = valid & any_fit
        park = valid & ~any_fit
        action_c = jnp.clip(action, 0, None)
        whole_free_before = (
            (alive & (alloc_cpu == state.nodes.cap_cpu))
            .sum(axis=1)
            .astype(jnp.float32)
        )
        alloc_cpu = alloc_cpu.at[rows1, action_c].add(jnp.where(assign, -req_cpu, 0))
        alloc_ram = alloc_ram.at[rows1, action_c].add(jnp.where(assign, -req_ram, 0))

        # Reward: placement pays +1 (or its capacity share), an unschedulable
        # park costs the same magnitude, minus a queue-time penalty so the
        # policy learns not to strand future pods.
        if reward_size_weighted:
            cap_at = jnp.maximum(
                state.nodes.cap_cpu[rows1, action_c].astype(jnp.float32), 1.0
            )
            unit = req_cpu.astype(jnp.float32) / cap_at
        else:
            unit = jnp.ones_like(req_cpu, jnp.float32)
        reward = jnp.where(
            assign,
            unit - 0.01 * jnp.minimum(pod_queue_time.astype(jnp.float32), 100.0),
            jnp.where(park, -unit, 0.0),
        )
        if shaping_coef:
            whole_free_after = (
                (alive & (alloc_cpu == state.nodes.cap_cpu))
                .sum(axis=1)
                .astype(jnp.float32)
            )
            # Only valid decisions carry shaping (invalid slots must stay
            # transparent to GAE's masked recursion).
            reward = reward + jnp.where(
                valid,
                shaping_coef
                * (jnp.float32(shaping_gamma) * whole_free_after - whole_free_before),
                0.0,
            )
        transition = Transition(
            obs=obs,
            action=action,
            log_prob=log_prob,
            value=value,
            reward=reward,
            valid=valid,
        )
        outs = (assign, park, action, transition)
        return (alloc_cpu, alloc_ram, rng), outs

    xs = (cc.valid.T, cc.req_cpu.T, cc.req_ram.T, pod_queue_time_k.T)
    (alloc_cpu, alloc_ram, _), outs = jax.lax.scan(
        body,
        (state.nodes.alloc_cpu, state.nodes.alloc_ram, rng),
        xs,
    )
    assign_k, park_k, action_k, transitions = outs
    metrics = decision_metrics(
        state.metrics, assign_k.T, pod_queue_time_k, pod_sched_time
    )
    state = commit_cycle(
        state, cc, W, consts, alloc_cpu, alloc_ram, metrics,
        assign_k.T, park_k.T, action_k.T, start_s_k, park_s_k,
    )
    return state, transitions  # transitions stacked over K on axis 0


@partial(
    jax.jit,
    static_argnames=(
        "policy_apply",
        "max_events_per_window",
        "max_pods_per_cycle",
        "greedy",
        "conditional_move",
        "max_ca_pods_per_cycle",
        "max_pods_per_scale_down",
        "reward_size_weighted",
        "shaping_coef",
        "shaping_gamma",
    ),
)
def rollout(
    state: ClusterBatchState,
    slab: TraceSlab,
    window_idxs: jnp.ndarray,
    consts: StepConstants,
    params,
    rng: jnp.ndarray,
    policy_apply,
    max_events_per_window: int,
    max_pods_per_cycle: int,
    greedy: bool = False,
    conditional_move: bool = False,
    autoscale_statics=None,
    max_ca_pods_per_cycle: int = 64,
    max_pods_per_scale_down: int = 8,
    reward_size_weighted: bool = False,
    shaping_coef: float = 0.0,
    shaping_gamma: float = 0.99,
) -> Tuple[ClusterBatchState, Transition]:
    """Scan scheduling windows (int32 indices) under the policy; transitions
    stacked (W, K, C, ...). With autoscale_statics, the HPA/CA passes run
    after each policy cycle exactly as on the kube-scheduler path, so the
    policy trains against autoscaler-driven dynamics."""

    def body(carry, w):
        st, rng = carry
        rng, sub = jax.random.split(rng)
        w_arr = jnp.broadcast_to(jnp.asarray(w, jnp.int32), st.time.shape)
        st, wake = _apply_window_events(
            st, slab, w_arr, consts, max_events_per_window, conditional_move,
            node_name_rank=(
                autoscale_statics.node_name_rank
                if autoscale_statics is not None else None
            ),
            pod_name_rank=(
                autoscale_statics.pod_name_rank
                if autoscale_statics is not None else None
            ),
        )
        pre_cycle = (
            st.pods.phase,
            st.pods.attempts,
            st.nodes.alloc_cpu,
            st.nodes.alloc_ram,
        )
        st, transition = policy_cycle(
            st, w_arr, consts, max_pods_per_cycle, policy_apply, params, sub,
            greedy=greedy, conditional_move=conditional_move,
            reward_size_weighted=reward_size_weighted,
            shaping_coef=shaping_coef, shaping_gamma=shaping_gamma,
            wake=wake,
        )
        if autoscale_statics is not None:
            from kubernetriks_tpu.batched.autoscale import ca_pass, hpa_pass

            auto = st.auto
            st, auto = hpa_pass(st, auto, autoscale_statics, w_arr, consts)
            st, auto = ca_pass(
                st, auto, autoscale_statics, w_arr, consts,
                max_ca_pods_per_cycle, max_pods_per_scale_down,
                pre=pre_cycle,
                # Reclaim-armed states (ca_alloc present — the accelerator
                # KTPU_RECLAIM default) must stamp allocation indices at
                # scale-up, or the cursor drifts past the ca_alloc>=0
                # prefix and a later compaction under-counts occupancy.
                reclaim=auto.ca_alloc is not None,
            )
            st = st._replace(auto=auto)
        return (st, rng), transition

    (state, _), transitions = jax.lax.scan(
        body, (state, rng), jnp.asarray(window_idxs, jnp.int32)
    )
    return state, transitions


def final_state_value(state: ClusterBatchState, policy_apply, params) -> jnp.ndarray:
    """Critic value of the post-rollout state (zero-request 'no pending pod'
    features), used to bootstrap truncated-rollout GAE."""
    zeros = jnp.zeros(state.nodes.alive.shape[0], jnp.int32)
    obs = featurize(
        state.nodes.alive,
        state.nodes.alloc_cpu,
        state.nodes.alloc_ram,
        state.nodes.cap_cpu,
        state.nodes.cap_ram,
        zeros,
        zeros,
    )
    _, value = policy_apply(params, obs)
    return value
