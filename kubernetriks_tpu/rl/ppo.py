"""PPO trainer for the scheduler policy over batches of simulated clusters.

Data parallelism follows the simulator's: the cluster axis C is the batch axis
(shardable over a mesh; policy params replicated, XLA inserts the gradient
all-reduce). Each PPO iteration: reset the cluster batch, roll W windows x K
decisions under the current policy, compute GAE over the flattened decision
sequence per cluster, and take clipped-objective gradient steps.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from kubernetriks_tpu.batched.engine import BatchedSimulation
from kubernetriks_tpu.rl.env import Transition, rollout
from kubernetriks_tpu.rl.policy import init_policy


class PPOConfig(NamedTuple):
    gamma: float = 0.99
    gae_lambda: float = 0.95
    clip_eps: float = 0.2
    value_coef: float = 0.5
    entropy_coef: float = 0.01
    learning_rate: float = 3e-4
    epochs_per_iteration: int = 4
    # Gradient accumulation over cluster chunks of this size (0 = whole
    # batch in one backward). The chunks ride a lax.scan, so the compiled
    # program carries ONE chunk-sized backward regardless of C — how the
    # attention policy's update (a much larger XLA program than the MLP's)
    # fits the 8192-cluster tracked config through the tunneled dev-TPU
    # compile helper. Chunk losses are combined with the FULL batch's
    # normalization (global advantage mean/std, global valid count), so the
    # accumulated gradient equals the monolithic one up to fp reduction
    # order.
    update_microbatch: int = 0
    # Rollout reward options (see rl/env.py policy_cycle): capacity-weighted
    # placement rewards and potential-based fragmentation shaping.
    reward_size_weighted: bool = False
    shaping_coef: float = 0.0


def compute_gae(
    rewards: jnp.ndarray,  # (T, C)
    values: jnp.ndarray,  # (T, C)
    valid: jnp.ndarray,  # (T, C)
    gamma: float,
    lam: float,
    bootstrap_value: Optional[jnp.ndarray] = None,  # (C,) V(s_final)
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Masked generalized advantage estimation over the decision sequence.

    Rollouts are horizon-truncated, not terminal: bootstrap_value (the critic's
    value of the post-rollout state) seeds the backward recursion so tail
    decisions are not biased as if the episode ended."""
    if bootstrap_value is None:
        bootstrap_value = jnp.zeros_like(values[-1])

    def body(carry, xs):
        next_adv, next_value = carry
        reward, value, is_valid = xs
        delta = reward + gamma * next_value - value
        adv = delta + gamma * lam * next_adv
        # Invalid steps are transparent: they pass the carry through unchanged.
        adv = jnp.where(is_valid, adv, next_adv)
        value_out = jnp.where(is_valid, value, next_value)
        return (adv, value_out), adv

    (_, _), advantages = jax.lax.scan(
        body,
        (jnp.zeros_like(values[-1]), bootstrap_value),
        (rewards, values, valid),
        reverse=True,
    )
    returns = advantages + values
    return advantages, returns


def ppo_loss(
    params,
    policy_apply,
    transition: Transition,  # flattened (T, C, ...)
    advantages: jnp.ndarray,
    returns: jnp.ndarray,
    config: PPOConfig,
    denom: Optional[jnp.ndarray] = None,
):
    """Clipped PPO objective. With denom=None (the monolithic path) the
    advantages are normalized and the loss averaged over this batch's valid
    decisions; a microbatch caller passes the FULL batch's valid count as
    denom and pre-normalized advantages, so summing chunk losses reproduces
    the monolithic objective."""
    logits, values = policy_apply(params, transition.obs)  # (T, C, N), (T, C)
    fit = transition.obs[..., 1] > 0
    # Finite mask value (not -inf): -inf produces NaN gradients through the
    # entropy term (d(p*log p) at log p = -inf is 0 * NaN).
    masked = jnp.where(fit, logits, -1e9)
    any_fit = fit.any(axis=-1, keepdims=True)
    safe = jnp.where(any_fit, masked, jnp.zeros_like(masked))
    log_probs = jax.nn.log_softmax(safe, axis=-1)
    action_log_prob = jnp.take_along_axis(
        log_probs, transition.action[..., None], axis=-1
    )[..., 0]

    mask = transition.valid.astype(jnp.float32)
    adv = advantages
    if denom is None:
        denom = jnp.maximum(mask.sum(), 1.0)
        adv_mean = (adv * mask).sum() / denom
        adv_std = jnp.sqrt(((adv - adv_mean) ** 2 * mask).sum() / denom + 1e-8)
        adv = (adv - adv_mean) / adv_std

    ratio = jnp.exp(action_log_prob - transition.log_prob)
    clipped = jnp.clip(ratio, 1.0 - config.clip_eps, 1.0 + config.clip_eps)
    policy_loss = -(jnp.minimum(ratio * adv, clipped * adv) * mask).sum() / denom

    value_loss = (((values - returns) ** 2) * mask).sum() / denom

    # Double-where: clamp BEFORE the product so backward never sees 0 * inf.
    lp_safe = jnp.where(fit, log_probs, 0.0)
    p_safe = jnp.where(fit, jnp.exp(log_probs), 0.0)
    entropy = -((p_safe * lp_safe).sum(axis=-1) * mask).sum() / denom

    total = (
        policy_loss
        + config.value_coef * value_loss
        - config.entropy_coef * entropy
    )
    return total, {
        "policy_loss": policy_loss,
        "value_loss": value_loss,
        "entropy": entropy,
    }


@partial(jax.jit, static_argnames=("policy_apply", "optimizer", "config"))
def ppo_update(
    params,
    opt_state,
    policy_apply,
    optimizer,
    transition: Transition,
    advantages,
    returns,
    config: PPOConfig,
):
    if config.update_microbatch:
        return _ppo_update_accum(
            params, opt_state, policy_apply, optimizer,
            transition, advantages, returns, config,
        )
    grad_fn = jax.value_and_grad(ppo_loss, has_aux=True)
    (loss, aux), grads = grad_fn(
        params, policy_apply, transition, advantages, returns, config
    )
    updates, opt_state = optimizer.update(grads, opt_state, params)
    params = optax.apply_updates(params, updates)
    return params, opt_state, loss, aux


def _ppo_update_accum(
    params,
    opt_state,
    policy_apply,
    optimizer,
    transition: Transition,
    advantages,
    returns,
    config: PPOConfig,
):
    """One optimizer step whose gradient accumulates over cluster chunks via
    lax.scan: the program holds a single chunk-sized backward, so arbitrary
    C fits a bounded compile budget (BASELINE config 5: attention-policy PPO
    at 8192 clusters)."""
    C = advantages.shape[1]
    Cc = min(config.update_microbatch, C)
    assert C % Cc == 0, (
        f"update_microbatch={Cc} must divide the cluster batch ({C})"
    )
    n_chunks = C // Cc

    # Global normalization BEFORE chunking, so chunk losses summed with the
    # global denom reproduce the monolithic objective.
    mask = transition.valid.astype(jnp.float32)
    denom = jnp.maximum(mask.sum(), 1.0)
    adv_mean = (advantages * mask).sum() / denom
    adv_std = jnp.sqrt(
        ((advantages - adv_mean) ** 2 * mask).sum() / denom + 1e-8
    )
    adv = (advantages - adv_mean) / adv_std

    def chunked(x):
        # (T, C, ...) -> (n_chunks, T, Cc, ...)
        return jnp.swapaxes(
            x.reshape(x.shape[0], n_chunks, Cc, *x.shape[2:]), 0, 1
        )

    xs = (jax.tree.map(chunked, transition), chunked(adv), chunked(returns))
    grad_fn = jax.value_and_grad(ppo_loss, has_aux=True)

    def body(acc, x):
        tr_c, adv_c, ret_c = x
        (loss_c, aux_c), grads_c = grad_fn(
            params, policy_apply, tr_c, adv_c, ret_c, config, denom
        )
        grads, loss, aux = acc
        return (
            jax.tree.map(jnp.add, grads, grads_c),
            loss + loss_c,
            jax.tree.map(jnp.add, aux, aux_c),
        ), None

    zero_grads = jax.tree.map(jnp.zeros_like, params)
    zero_aux = {
        "policy_loss": jnp.float32(0.0),
        "value_loss": jnp.float32(0.0),
        "entropy": jnp.float32(0.0),
    }
    (grads, loss, aux), _ = jax.lax.scan(
        body, (zero_grads, jnp.float32(0.0), zero_aux), xs
    )
    updates, opt_state = optimizer.update(grads, opt_state, params)
    params = optax.apply_updates(params, updates)
    return params, opt_state, loss, aux


class PPOTrainer:
    """Owns the policy/optimizer and iterates rollout -> GAE -> updates against
    a fresh copy of a BatchedSimulation's initial state each iteration."""

    def __init__(
        self,
        sim: BatchedSimulation,
        windows_per_rollout: int = 16,
        config: PPOConfig = PPOConfig(),
        hidden: int = 64,
        seed: int = 0,
        policy_kind: str = "mlp",
    ) -> None:
        self.sim = sim
        self.config = config
        self.windows = np.arange(windows_per_rollout, dtype=np.int32)
        rng = jax.random.PRNGKey(seed)
        self.rng, init_rng = jax.random.split(rng)
        n_nodes = sim.state.nodes.alive.shape[1]
        if policy_kind == "attention":
            from kubernetriks_tpu.rl.attention_policy import (
                attention_policy_apply,
                init_attention_policy,
            )

            self.policy = None
            self.params = init_attention_policy(init_rng, hidden=hidden)
            self.policy_apply = attention_policy_apply
        else:
            assert policy_kind == "mlp", policy_kind
            self.policy, self.params = init_policy(
                init_rng, n_nodes, hidden=hidden
            )
            self.policy_apply = self.policy.apply
        self.optimizer = optax.adam(config.learning_rate)
        self.opt_state = self.optimizer.init(self.params)
        self.initial_state = sim.state

    def save_checkpoint(self, path: str) -> None:
        """Persist policy params, optimizer state and the rollout RNG (the
        simulator side is re-derivable from config+traces; checkpoint it
        separately via BatchedSimulation.save_checkpoint if mid-rollout
        state matters)."""
        from kubernetriks_tpu.checkpoint import ckpt_save

        ckpt_save(
            path,
            {"params": self.params, "opt_state": self.opt_state, "rng": self.rng},
        )

    def load_checkpoint(self, path: str) -> None:
        from kubernetriks_tpu.checkpoint import ckpt_restore

        restored = ckpt_restore(
            path,
            {"params": self.params, "opt_state": self.opt_state, "rng": self.rng},
        )
        self.params = restored["params"]
        self.opt_state = restored["opt_state"]
        self.rng = restored["rng"]

    def collect(self, greedy: bool = False):
        self.rng, sub = jax.random.split(self.rng)
        final_state, transitions = rollout(
            self.initial_state,
            self.sim.slab,
            jnp.asarray(self.windows, jnp.int32),
            self.sim.consts,
            self.params,
            sub,
            self.policy_apply,
            self.sim.max_events_per_window,
            self.sim.max_pods_per_cycle,
            greedy=greedy,
            conditional_move=self.sim.conditional_move,
            autoscale_statics=self.sim.autoscale_statics,
            max_ca_pods_per_cycle=self.sim.max_ca_pods_per_cycle,
            max_pods_per_scale_down=self.sim.max_pods_per_scale_down,
            reward_size_weighted=self.config.reward_size_weighted,
            shaping_coef=self.config.shaping_coef,
            shaping_gamma=self.config.gamma,
        )
        # (W, K, C, ...) -> (W*K, C, ...) decision-ordered sequence.
        flat = jax.tree.map(
            lambda x: x.reshape((-1,) + x.shape[2:]), transitions
        )
        return final_state, flat

    def train_iteration(self) -> Dict[str, float]:
        from kubernetriks_tpu.rl.env import final_state_value

        final_state, flat = self.collect()
        bootstrap = final_state_value(final_state, self.policy_apply, self.params)
        advantages, returns = compute_gae(
            flat.reward, flat.value, flat.valid,
            self.config.gamma, self.config.gae_lambda,
            bootstrap_value=bootstrap,
        )
        aux = {}
        for _ in range(self.config.epochs_per_iteration):
            self.params, self.opt_state, loss, aux = ppo_update(
                self.params,
                self.opt_state,
                self.policy_apply,
                self.optimizer,
                flat,
                advantages,
                returns,
                self.config,
            )
        mask = np.asarray(flat.valid, np.float32)
        denom = max(mask.sum(), 1.0)
        result = {k: float(v) for k, v in aux.items()}
        result["mean_reward"] = float((np.asarray(flat.reward) * mask).sum() / denom)
        result["decisions"] = int(mask.sum())
        result["placements"] = int(
            np.asarray(final_state.metrics.scheduling_decisions).sum()
            - np.asarray(self.initial_state.metrics.scheduling_decisions).sum()
        )
        return result

    def train(self, iterations: int):
        history = []
        for _ in range(iterations):
            history.append(self.train_iteration())
        return history
