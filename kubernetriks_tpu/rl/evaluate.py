"""Policy evaluation: the learning-proof harness for the RL scheduler.

Compares, on the SAME trace and the SAME scheduling-window cadence:
  - the learned policy run greedily (argmax actions, no exploration noise),
  - the KubeScheduler batched path (Fit filter + LeastAllocatedResources
    score — the reference default, src/core/scheduler/kube_scheduler.rs),
against placement metrics read from the shared MetricArrays, so the
comparison is apples-to-apples: both paths use prepare_cycle/commit_cycle
and decision_metrics identically (rl/env.py vs batched/step.py).

The headline scenario (scripts/train_rl_proof.py, tests/test_rl_learning.py)
is a contended bimodal mix: a high-rate small-pod process plus a low-rate
large-pod process on a cluster sized so that SPREADING small pods (what
LeastAllocated does) fragments every node below the large-pod request,
while PACKING them leaves whole nodes free. Placement strategy — not
capacity — decides whether large pods ever place, which is exactly the
signal a learned scheduler must discover to beat the baseline.
"""

from __future__ import annotations

from typing import Dict

import jax.numpy as jnp
import numpy as np

from kubernetriks_tpu.batched.engine import BatchedSimulation
from kubernetriks_tpu.batched.pipeline import bestfit_logits_from_obs
from kubernetriks_tpu.batched.state import (
    PHASE_RUNNING,
    PHASE_SUCCEEDED,
    PHASE_UNSCHEDULABLE,
)
from kubernetriks_tpu.rl.env import rollout


def bestfit_policy_apply(params, obs):
    """The best-fit packing heuristic as a policy_apply — THE upper-bound
    reference of the learning proof, deduplicated onto the device-plugin
    registry: the logits are the MostAllocatedResources scorer of the
    scheduler's "best_fit" profile evaluated on the observation channels
    (batched/pipeline.bestfit_logits_from_obs), so the proof's baseline
    and the deployable scheduler profile share ONE scorer definition.
    `params` is unused (heuristic); the value head returns zeros."""
    return bestfit_logits_from_obs(obs), jnp.zeros(obs.shape[:-2])


def _summary(
    state, n_windows: int, large_cpu: int | None = None
) -> Dict[str, float]:
    """Placement metrics from a terminal ClusterBatchState (per-cluster means).

    With large_cpu set, also reports the placement fraction of "large" pods
    (req_cpu >= large_cpu) — the class whose fate depends on placement
    strategy in the bimodal fragmentation scenario."""
    m = state.metrics
    C = state.time.shape[0]
    placements = float(np.asarray(m.scheduling_decisions).sum()) / C
    succeeded = float(np.asarray(m.pods_succeeded).sum()) / C
    qt_count = np.asarray(m.queue_time.count, np.float64)
    qt_total = np.asarray(m.queue_time.total, np.float64)
    mean_queue_time = float(qt_total.sum() / np.maximum(qt_count.sum(), 1.0))
    phases = np.asarray(state.pods.phase)
    unschedulable = float((phases == PHASE_UNSCHEDULABLE).sum()) / C
    placed_mask = (phases == PHASE_RUNNING) | (phases == PHASE_SUCCEEDED)
    placed_now = float(placed_mask.sum()) / C
    out = {
        "placements_per_cluster": placements,
        "succeeded_per_cluster": succeeded,
        "mean_queue_time_s": mean_queue_time,
        "unschedulable_left_per_cluster": unschedulable,
        "placed_or_done_per_cluster": placed_now,
        "windows": float(n_windows),
    }
    if large_cpu is not None:
        req = np.asarray(state.pods.req_cpu)
        large = (req >= large_cpu) & (phases != 0)  # created large-pod slots
        n_large = max(int(large.sum()), 1)
        out["large_pods_per_cluster"] = float(large.sum()) / C
        out["large_placed_frac"] = float((large & placed_mask).sum()) / n_large
        out["large_unschedulable_frac"] = float(
            (large & (phases == PHASE_UNSCHEDULABLE)).sum()
        ) / n_large
    return out


def eval_policy(
    sim: BatchedSimulation,
    policy_apply,
    params,
    window_idxs: np.ndarray,
    rng,
    greedy: bool = True,
    large_cpu: int | None = None,
) -> Dict[str, float]:
    """Run the policy over the given windows from the sim's CURRENT state
    (do not reuse a stepped sim — build a fresh one per evaluation)."""
    final_state, flat = rollout(
        sim.state,
        sim.slab,
        jnp.asarray(window_idxs, jnp.int32),
        sim.consts,
        params,
        rng,
        policy_apply,
        sim.max_events_per_window,
        sim.max_pods_per_cycle,
        greedy=greedy,
        conditional_move=sim.conditional_move,
        autoscale_statics=sim.autoscale_statics,
        max_ca_pods_per_cycle=sim.max_ca_pods_per_cycle,
        max_pods_per_scale_down=sim.max_pods_per_scale_down,
    )
    out = _summary(final_state, len(window_idxs), large_cpu)
    valid = np.asarray(flat.valid)
    obs = np.asarray(flat.obs)
    parks = valid & ~(obs[..., 1] > 0).any(axis=-1)
    C = valid.shape[-1]
    out["park_decisions_per_cluster"] = float(parks.sum()) / C
    out["mean_reward"] = float(
        (np.asarray(flat.reward) * valid).sum() / max(valid.sum(), 1)
    )
    return out


def eval_kube(
    sim: BatchedSimulation,
    window_idxs: np.ndarray,
    large_cpu: int | None = None,
) -> Dict[str, float]:
    """Run the KubeScheduler batched path over the same windows (fresh sim)."""
    sim._dispatch_windows(np.asarray(window_idxs, np.int32))
    return _summary(sim.state, len(window_idxs), large_cpu)


# --- The bimodal learning-proof scenario ------------------------------------
# Probed across seeds (scripts/train_rl_proof.py header has the load math):
# long-lived small pods load ~59% of a 16-node cluster; spread by
# LeastAllocated they fragment every node below the full-node large-pod
# request, packed they fit in ~10 nodes. Placement strategy decides the
# large pods' fate: kube strands 4-7 pods/cluster, best-fit 0-2.
PROOF_N_NODES = 16
PROOF_NODE_CPU = 16_000
PROOF_NODE_RAM = 32 * 1024**3
PROOF_SMALL = dict(rate_per_second=0.25, cpu=2_000, ram=4 * 1024**3,
                   duration_range=(250.0, 350.0))
PROOF_LARGE = dict(rate_per_second=0.015, cpu=16_000, ram=32 * 1024**3,
                   duration_range=(250.0, 350.0))
PROOF_WINDOWS = 48        # x 10 s cycle interval = 480 s rollout
PROOF_HORIZON = 475.0
PROOF_MAX_PODS_PER_CYCLE = 16


def make_proof_sim(seed_base: int, n_clusters: int, n_seeds: int = 8):
    """Cluster batch for the learning proof, cycling over n_seeds distinct
    trace seeds so the training signal does not hinge on one Poisson draw."""
    from kubernetriks_tpu.batched.trace_compile import compile_cluster_trace
    from kubernetriks_tpu.config import SimulationConfig
    from kubernetriks_tpu.trace.generator import (
        MergedWorkloadTrace,
        PoissonWorkloadTrace,
        UniformClusterTrace,
    )

    config = SimulationConfig.from_yaml(
        "sim_name: rl_proof\nseed: 1\nscheduling_cycle_interval: 10.0"
    )
    cluster_events = UniformClusterTrace(
        PROOF_N_NODES, cpu=PROOF_NODE_CPU, ram=PROOF_NODE_RAM
    ).convert_to_simulator_events()
    compiled = []
    for k in range(min(n_seeds, n_clusters)):
        seed = seed_base + 100 * k
        workload = MergedWorkloadTrace(
            PoissonWorkloadTrace(
                horizon=PROOF_HORIZON, seed=seed, name_prefix="small",
                **PROOF_SMALL,
            ),
            PoissonWorkloadTrace(
                horizon=PROOF_HORIZON, seed=seed + 1, name_prefix="large",
                **PROOF_LARGE,
            ),
        )
        compiled.append(
            compile_cluster_trace(
                cluster_events, workload.convert_to_simulator_events(), config
            )
        )
    traces = [compiled[i % len(compiled)] for i in range(n_clusters)]
    return BatchedSimulation(
        config, traces, max_pods_per_cycle=PROOF_MAX_PODS_PER_CYCLE
    )
