"""Attention-based scheduler policy with explicit TP/SP sharding.

Same seam as rl/policy.py's MLP head (the PodSchedulingAlgorithm boundary,
reference: src/core/scheduler/interface.rs:14-23): per pending pod, node
logits over the cluster's nodes plus a pooled value. The difference is a
self-attention block over the node axis, so each node's logit can condition
on the whole cluster's occupancy (the MLP scores nodes independently) — and
that node axis is exactly the "sequence" this framework shards for
long-context clusters.

Two applies over the SAME parameter pytree:
- `attention_policy_apply(params, feats)` — plain single-device forward
  (usable anywhere `policy_apply` is, e.g. PPOTrainer(policy_kind=...)).
- `make_sharded_apply(mesh, ...)` — a shard_map'd forward over a
  (data, seq, model) mesh: clusters data-parallel, node axis
  sequence-parallel through ring attention (parallel/ring.py), and the FFN
  hidden dimension megatron-style tensor-parallel (column-split W1, row-split
  W2, psum over the model axis). Parity with the plain forward is asserted in
  tests/test_parallel.py.

Pure functions + an explicit param dict (no flax) so the sharded forward can
consume the pytree directly through shard_map in_specs.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from kubernetriks_tpu.parallel.ring import full_attention, ring_attention
from kubernetriks_tpu.rl.policy import NODE_FEATURES


def init_attention_policy(
    rng,
    hidden: int = 64,
    heads: int = 4,
    ffn_mult: int = 2,
    features: int = NODE_FEATURES,
) -> Dict[str, jnp.ndarray]:
    """He-initialized parameter pytree. hidden must divide by heads; the FFN
    hidden (ffn_mult*hidden) is the tensor-parallel dimension and must divide
    by the mesh's model-axis size when used with make_sharded_apply."""
    assert hidden % heads == 0
    ffn = ffn_mult * hidden

    def dense(key, fan_in, fan_out):
        w = jax.random.normal(key, (fan_in, fan_out), jnp.float32)
        return w * jnp.sqrt(2.0 / fan_in)

    ks = jax.random.split(rng, 10)
    return {
        "embed_w": dense(ks[0], features, hidden),
        "embed_b": jnp.zeros((hidden,), jnp.float32),
        "q_w": dense(ks[1], hidden, hidden),
        "k_w": dense(ks[2], hidden, hidden),
        "v_w": dense(ks[3], hidden, hidden),
        "proj_w": dense(ks[4], hidden, hidden),
        "proj_b": jnp.zeros((hidden,), jnp.float32),
        "ffn1_w": dense(ks[5], hidden, ffn),
        "ffn1_b": jnp.zeros((ffn,), jnp.float32),
        "ffn2_w": dense(ks[6], ffn, hidden),
        "ffn2_b": jnp.zeros((hidden,), jnp.float32),
        "logit_w": dense(ks[7], hidden, 1),
        "logit_b": jnp.zeros((1,), jnp.float32),
        "val1_w": dense(ks[8], hidden, hidden),
        "val1_b": jnp.zeros((hidden,), jnp.float32),
        "val2_w": dense(ks[9], hidden, 1),
        "val2_b": jnp.zeros((1,), jnp.float32),
    }


def _heads(x: jnp.ndarray, heads: int) -> jnp.ndarray:
    """(..., N, H*dh) -> (..., H, N, dh)."""
    *lead, n, d = x.shape
    x = x.reshape(*lead, n, heads, d // heads)
    return jnp.moveaxis(x, -2, -3)


def _unheads(x: jnp.ndarray) -> jnp.ndarray:
    """(..., H, N, dh) -> (..., N, H*dh)."""
    x = jnp.moveaxis(x, -3, -2)
    *lead, n, h, dh = x.shape
    return x.reshape(*lead, n, h * dh)


def _trunk_local(params, feats, attn_fn, heads: int):
    """Shared forward up to per-node embeddings; attn_fn supplies either the
    full or the ring attention over (..., H, N, dh) blocks."""
    alive = feats[..., 0] > 0  # (..., N)
    x = jax.nn.relu(feats @ params["embed_w"] + params["embed_b"])
    qh = _heads(x @ params["q_w"], heads)
    kh = _heads(x @ params["k_w"], heads)
    vh = _heads(x @ params["v_w"], heads)
    mask = alive[..., None, :]  # broadcast over heads then queries
    attn = _unheads(attn_fn(qh, kh, vh, mask))
    x = x + attn @ params["proj_w"] + params["proj_b"]
    return x, alive


def _head_outputs(params, x, alive):
    """Per-node logits + masked-mean pooled value from trunk embeddings."""
    x = jnp.where(alive[..., None], x, 0.0)
    logits = (x @ params["logit_w"] + params["logit_b"])[..., 0]
    count = jnp.maximum(alive.sum(axis=-1, keepdims=True), 1.0)
    pooled = x.sum(axis=-2) / count
    v = jax.nn.relu(pooled @ params["val1_w"] + params["val1_b"])
    value = (v @ params["val2_w"] + params["val2_b"])[..., 0]
    return logits, value


def attention_policy_apply(
    params, feats: jnp.ndarray, heads: int = 4
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(..., N, F) node features -> ((..., N) logits, (...,) value)."""
    x, alive = _trunk_local(params, feats, full_attention, heads)
    h = jax.nn.relu(x @ params["ffn1_w"] + params["ffn1_b"])
    x = x + h @ params["ffn2_w"] + params["ffn2_b"]
    return _head_outputs(params, x, alive)


def make_sharded_apply(
    mesh: Mesh,
    heads: int = 4,
    data_axis: str = "data",
    seq_axis: str = "seq",
    model_axis: str = "model",
):
    """Build apply(params, feats) for feats (C, N, F) with C sharded over
    data_axis, N over seq_axis (ring attention) and the FFN hidden dimension
    over model_axis (column/row-parallel matmuls + psum). Params enter
    replicated except the FFN weights, which shard_map slices per device.
    C, N and the FFN hidden must divide by the respective mesh axis sizes."""

    ffn_spec = {
        "ffn1_w": P(None, model_axis),
        "ffn1_b": P(model_axis),
        "ffn2_w": P(model_axis, None),
    }

    def spec_for(key):
        return ffn_spec.get(key, P())

    def fwd(params, feats):
        def ring(qh, kh, vh, mask):
            return ring_attention(qh, kh, vh, mask, seq_axis)

        x, alive = _trunk_local(params, feats, ring, heads)

        # Tensor-parallel FFN: column-split first matmul, row-split second,
        # one psum over the model axis restores the full activation.
        h = jax.nn.relu(x @ params["ffn1_w"] + params["ffn1_b"])
        y = jax.lax.psum(h @ params["ffn2_w"], model_axis)
        x = x + y + params["ffn2_b"]

        # Heads: logits stay node-sharded; the pooled value needs the masked
        # mean over ALL nodes -> psum the local sums over the sequence axis.
        x = jnp.where(alive[..., None], x, 0.0)
        logits = (x @ params["logit_w"] + params["logit_b"])[..., 0]
        count = jax.lax.psum(
            alive.sum(axis=-1, keepdims=True).astype(jnp.float32), seq_axis
        )
        pooled = jax.lax.psum(x.sum(axis=-2), seq_axis) / jnp.maximum(count, 1.0)
        v = jax.nn.relu(pooled @ params["val1_w"] + params["val1_b"])
        value = (v @ params["val2_w"] + params["val2_b"])[..., 0]
        return logits, value

    in_specs = (
        {k: spec_for(k) for k in (
            "embed_w", "embed_b", "q_w", "k_w", "v_w", "proj_w", "proj_b",
            "ffn1_w", "ffn1_b", "ffn2_w", "ffn2_b", "logit_w", "logit_b",
            "val1_w", "val1_b", "val2_w", "val2_b",
        )},
        P(data_axis, seq_axis, None),
    )
    out_specs = (P(data_axis, seq_axis), P(data_axis))

    from kubernetriks_tpu.parallel.multihost import shard_map

    return jax.jit(
        shard_map(
            fwd, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            # Full varying-axis checking on the new API; the 0.4.x line's
            # check_rep has a known replication-inference bug for
            # grad-of-scan (its own error text prescribes check_rep=False),
            # so checking is off exactly there. Forward/backward parity is
            # pinned numerically by tests/test_parallel.py either way.
            check_vma=hasattr(jax, "shard_map"),
        )
    )
