"""Scheduler policy network: per-pod node logits from masked node features.

The RL head replaces the KubeScheduler score pass (the north-star RL
configuration, BASELINE.json configs[4]): for each pending pod it scores every
node of its cluster. Architecture is permutation-equivariant over nodes — a
shared MLP maps each node's feature vector to a logit, plus a pooled value
head — so one set of weights serves any cluster size, and the whole batch of
(clusters x nodes) evaluations is a single bfloat16-friendly batched matmul
stack on the MXU.
"""

from __future__ import annotations

from typing import Tuple

import flax.linen as nn
import jax.numpy as jnp

# Per-node feature vector layout (see featurize() in rl/env.py):
# [alive, fits, alloc_cpu_frac, alloc_ram_frac, req_cpu_over_cap, req_ram_over_cap]
NODE_FEATURES = 6


class SchedulerPolicy(nn.Module):
    """Maps (..., N, F) node features -> ((..., N) logits, (...,) value)."""

    hidden: int = 64
    layers: int = 2

    @nn.compact
    def __call__(self, node_features: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
        x = node_features
        for _ in range(self.layers):
            x = nn.Dense(self.hidden)(x)
            x = nn.relu(x)
        logits = nn.Dense(1)(x)[..., 0]  # (..., N)

        # Value head over mean-pooled node embeddings.
        pooled = x.mean(axis=-2)  # (..., hidden)
        v = nn.relu(nn.Dense(self.hidden)(pooled))
        value = nn.Dense(1)(v)[..., 0]  # (...,)
        return logits, value


def init_policy(rng, n_nodes: int, hidden: int = 64, layers: int = 2):
    policy = SchedulerPolicy(hidden=hidden, layers=layers)
    params = policy.init(rng, jnp.zeros((1, n_nodes, NODE_FEATURES)))
    return policy, params
