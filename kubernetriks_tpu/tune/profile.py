"""Per-hardware tuned-statics profiles: persistence + the build seam.

A profile is a JSON table keyed by backend + geometry — the file name
IS the key: `artifacts/tuned/<backend>_<C>x<N>.json` — recorded like a
BENCH_*.json: the chosen statics, the objective they scored, the
hand-picked baseline they were searched from, and EVERY measured
candidate disclosed (so a profile is auditable and the search can
RESUME from it: already-measured candidates are cache hits).

Load seam (BatchedSimulation / ScenarioFleet build):

    profile source:  explicit `tuned_profile` arg
                   > KTPU_TUNED_PROFILE (a path, or 1/auto = resolve
                     artifacts/tuned/ then the bundled
                     kubernetriks_tpu/tune/profiles/ directory for the
                     build's backend + geometry)
                   > nothing (hand-picked statics, byte-for-byte the
                     pre-tuner build)
    per-knob value:  explicit build kwarg
                   > the knob's own env flag (KTPU_LANE_MAJOR, ...)
                   > the loaded profile's statics entry
                   > the hand-picked platform default

Mismatch policy: an EXPLICITLY loaded profile (arg, or a flag naming a
path) raises on backend/geometry mismatch, naming the field — you
asked for that exact file, silently ignoring it would be the
silent-fallback bug class this repo kills everywhere. Auto-resolved
profiles only ever match by construction (the file name is the key);
the engine re-checks n_nodes AFTER the statics build (N is derived
from the traces + CA groups) and warns LOUDLY on drift, leaving the
already-applied statics in place and disclosing them.
"""

from __future__ import annotations

import json
import os
import warnings
from typing import Dict, NamedTuple, Optional, Sequence

from kubernetriks_tpu.tune.knobs import validate_statics

SCHEMA_VERSION = 1
PROFILE_KIND = "ktpu-tuned-profile"

# Where `bench.py --tune` lands profiles (relative to the working
# directory) and where auto-resolution looks first.
ARTIFACT_DIR = os.path.join("artifacts", "tuned")

# Profiles bundled with the package (kubernetriks_tpu/tune/profiles/):
# the lowest-priority source in the auto-resolution chain.
BUNDLED_DIR = os.path.join(os.path.dirname(__file__), "profiles")

# KTPU_TUNED_PROFILE values that mean "resolve by geometry" rather than
# naming a file.
_AUTO_VALUES = frozenset({"1", "auto", "true", "on"})


class GeometryMismatch(ValueError):
    """An explicitly loaded profile does not match the build, naming
    the mismatched field."""


class TunedProfile(NamedTuple):
    backend: str
    n_clusters: int
    n_nodes: int
    statics: Dict[str, object]
    doc: Dict[str, object]  # the full JSON document (candidates etc.)
    source: str  # path it was loaded from, or "<dict>"
    explicit: bool  # explicitly requested (arg / flag path) -> strict

    def describe(self) -> str:
        return (
            f"{self.backend}_{self.n_clusters}x{self.n_nodes} "
            f"({self.source})"
        )

    def check_geometry(
        self,
        *,
        backend: Optional[str] = None,
        n_clusters: Optional[int] = None,
        n_nodes: Optional[int] = None,
    ) -> None:
        """Compare the profile key against the build, field by field.
        Explicit profiles RAISE GeometryMismatch naming the field;
        auto-resolved ones warn loudly and keep going (the statics are
        still bit-identity-safe — only their tuning provenance is for a
        different shape)."""
        checks = (
            ("backend", self.backend, backend),
            ("geometry.n_clusters", self.n_clusters, n_clusters),
            ("geometry.n_nodes", self.n_nodes, n_nodes),
        )
        for field, have, want in checks:
            if want is None or have == want:
                continue
            msg = (
                f"tuned profile {self.describe()}: {field} is {have!r} "
                f"but this build is {want!r} — the profile was tuned "
                "for different hardware/geometry"
            )
            if self.explicit:
                raise GeometryMismatch(msg)
            warnings.warn(
                msg + "; applying its statics anyway (bit-identity is "
                "guaranteed, the tuning provenance is not)",
                RuntimeWarning,
                stacklevel=3,
            )


def profile_path(
    backend: str, n_clusters: int, n_nodes: int, root: str = ARTIFACT_DIR
) -> str:
    """The canonical on-disk key: <root>/<backend>_<C>x<N>.json."""
    return os.path.join(root, f"{backend}_{n_clusters}x{n_nodes}.json")


def save_profile(doc: Dict[str, object], path: str) -> str:
    """Validate + write a profile document (creating directories);
    returns the path. The document must already carry the full record
    — this is persistence, not authoring (search.py authors)."""
    _validate_doc(doc, path)
    parent = os.path.dirname(path)
    if parent:
        os.makedirs(parent, exist_ok=True)
    with open(path, "w") as fh:
        json.dump(doc, fh, indent=2)
        fh.write("\n")
    return path


def _validate_doc(doc: Dict[str, object], source: str) -> None:
    if doc.get("kind") != PROFILE_KIND:
        raise ValueError(
            f"tuned profile {source}: 'kind' is {doc.get('kind')!r}, "
            f"expected {PROFILE_KIND!r}"
        )
    if doc.get("schema") != SCHEMA_VERSION:
        raise ValueError(
            f"tuned profile {source}: 'schema' is {doc.get('schema')!r}, "
            f"this build reads version {SCHEMA_VERSION}"
        )
    geo = doc.get("geometry")
    if not isinstance(geo, dict) or not {
        "n_clusters",
        "n_nodes",
    } <= set(geo):
        raise ValueError(
            f"tuned profile {source}: 'geometry' must carry n_clusters "
            f"and n_nodes, got {geo!r}"
        )
    if not isinstance(doc.get("backend"), str):
        raise ValueError(
            f"tuned profile {source}: 'backend' must be a string, got "
            f"{doc.get('backend')!r}"
        )
    statics = doc.get("statics")
    if not isinstance(statics, dict):
        raise ValueError(
            f"tuned profile {source}: 'statics' must be a table, got "
            f"{statics!r}"
        )
    # Unknown knobs and illegal values raise here, naming the field —
    # a stale profile from a renamed knob fails at load, not by
    # silently dropping the entry.
    validate_statics(statics)


def _from_doc(
    doc: Dict[str, object], source: str, explicit: bool
) -> TunedProfile:
    _validate_doc(doc, source)
    geo = doc["geometry"]
    return TunedProfile(
        backend=str(doc["backend"]),
        n_clusters=int(geo["n_clusters"]),
        n_nodes=int(geo["n_nodes"]),
        statics=dict(doc["statics"]),
        doc=doc,
        source=source,
        explicit=explicit,
    )


def load_profile(path: str, explicit: bool = True) -> TunedProfile:
    """Load + validate one profile file. Raises (naming the path and
    the offending field) on unknown knobs, illegal values, or a
    malformed document — never a silent partial load."""
    with open(path, encoding="utf-8") as fh:
        doc = json.load(fh)
    return _from_doc(doc, path, explicit)


def _auto_candidates(
    backend: str, n_clusters: int
) -> Sequence[str]:
    """Auto-resolution search list for KTPU_TUNED_PROFILE=1/auto: every
    <backend>_<C>x*.json under artifacts/tuned/ then the bundled dir
    (N is unknown until the statics build; a unique C-match loads and
    the post-build N check warns on drift)."""
    out = []
    prefix = f"{backend}_{n_clusters}x"
    for root in (ARTIFACT_DIR, BUNDLED_DIR):
        if not os.path.isdir(root):
            continue
        for name in sorted(os.listdir(root)):
            if name.startswith(prefix) and name.endswith(".json"):
                out.append(os.path.join(root, name))
    return out


def resolve_build_profile(
    tuned_profile,
    *,
    backend: str,
    n_clusters: int,
) -> Optional[TunedProfile]:
    """The engine-build seam (called from BatchedSimulation.__init__).

    `tuned_profile` — the explicit build arg: a TunedProfile, a profile
    dict, a path, False (= profile loading OFF even under the flag), or
    None (= consult KTPU_TUNED_PROFILE). Explicit sources are strict:
    load failures and backend/C mismatches raise, naming the field.
    Flag-auto sources are best-effort: no match resolves to None (the
    hand-picked statics) — quietly, because unset-flag builds must stay
    byte-for-byte the pre-tuner build and auto is the documented
    "use one if you have one" mode."""
    from kubernetriks_tpu.flags import flag_str

    if tuned_profile is False:
        return None
    explicit = tuned_profile is not None
    path: Optional[str] = None
    if isinstance(tuned_profile, TunedProfile):
        prof = tuned_profile
    elif isinstance(tuned_profile, dict):
        prof = _from_doc(tuned_profile, "<dict>", explicit=True)
    elif isinstance(tuned_profile, str):
        path = tuned_profile
        prof = None
    elif tuned_profile is None:
        raw = flag_str("KTPU_TUNED_PROFILE")
        if raw is None:
            return None
        if raw.strip().lower() in _AUTO_VALUES:
            candidates = _auto_candidates(backend, n_clusters)
            if not candidates:
                return None
            prof, path = None, candidates[0]
        else:
            # A flag naming a concrete path is as explicit as an arg:
            # a missing/stale file raises instead of silently running
            # the untuned statics the user thought they replaced.
            prof, path, explicit = None, raw, True
    else:
        raise TypeError(
            "tuned_profile must be a TunedProfile, a profile dict, a "
            f"path, False or None — got {type(tuned_profile).__name__}"
        )
    if prof is None:
        prof = load_profile(path, explicit=explicit)
    prof = prof._replace(explicit=explicit)
    prof.check_geometry(backend=backend, n_clusters=n_clusters)
    return prof
