"""Self-tuning statics: the measurement-driven autotuner that closes the
telemetry -> configuration loop (ROADMAP #5, DESIGN SS16).

Every performance-critical static the engine grew — the superspan
executor and its K/chunk shape, the streaming-feeder ring depth, the
lane-major / window-razor / CA-de-scatter program variants, buffer
donation, the fused chunk+slide megastep — was A/B'd by hand once
(BENCH_r07) and frozen into platform defaults. This package makes them
SEARCHABLE instead:

- `knobs.py`     — the declarative knob registry: name, legal values,
                   which engine kwarg (jit-static) each knob feeds,
                   whether changing it forces a recompile, and the
                   activation predicates (`stream` rides `superspan`).
- `measure.py`   — the pluggable measurement backend: the real bench
                   protocol (median of >= 5 valid spans, zero-decision
                   spans dropped, recompile sentinel armed per
                   candidate, bit-identity asserted across the grid)
                   and a pinned-measurements fake for tests and CI.
- `search.py`    — deterministic, resumable staged coordinate descent
                   over the registry, budgeted by KTPU_TUNE_BUDGET.
- `profile.py`   — the per-hardware tuned-statics profile: a JSON table
                   keyed by backend + geometry (artifacts/tuned/
                   <backend>_<C>x<N>.json) recording the chosen config
                   AND every measured candidate, loaded at engine/fleet
                   build via KTPU_TUNED_PROFILE.

Tuning changes statics only, never semantics: every candidate the
search measures must reproduce the reference final state bit for bit
(state.compare_states) with equal committed decisions — the same
parity contract the hand A/Bs enforced. The objective is the
observatory's readout (telemetry/observatory.tuning_objective): the
per-window window-program cost line scaled by a penalty for fired
stall/occupancy verdicts.

This is cold-path host code: no hot-path pragma, no jit, no device
work of its own (the measurement backend drives engines that do).
"""

from kubernetriks_tpu.tune.knobs import (  # noqa: F401
    KNOBS,
    Knob,
    active_knobs,
    knob_by_name,
    validate_statics,
)
from kubernetriks_tpu.tune.measure import (  # noqa: F401
    BenchMeasurementBackend,
    FakeMeasurementBackend,
    Measurement,
)
from kubernetriks_tpu.tune.profile import (  # noqa: F401
    GeometryMismatch,
    TunedProfile,
    load_profile,
    profile_path,
    resolve_build_profile,
    save_profile,
)
from kubernetriks_tpu.tune.search import (  # noqa: F401
    TuneResult,
    staged_coordinate_descent,
)
