"""Deterministic, resumable staged coordinate descent over the knob
registry.

The search walks the registry's stages in declaration order
(executor -> layout -> memory); within a stage it fixes one knob at a
time: measure every legal value of the knob with all other knobs held
at the current config, keep the best, move on. Dependent knobs
(`requires`) are skipped while inactive — flipping `superspan` on in
the executor stage activates `superspan_k`/`superspan_chunk` right
after it, in the same pass. No randomness, no wall-clock input: the
visit order is the registry order, ties break toward the earlier
candidate, and resumed runs replay cached measurements — same
measurements in, same chosen config out.

Resume + budget: every measurement is keyed by the canonical statics
JSON (measure.canonical_key). A prior profile's `candidates` list is
the resume cache — already-measured candidates are reused (disclosed
with `"reused": true`), and `budget` caps NEW measurements per run
(KTPU_TUNE_BUDGET): an exhausted budget stops the sweep, the partial
profile records `complete: false`, and the next run continues where
this one stopped.

The chosen config is the argmin over EVERYTHING measured — descent
path, seed configs (run_tune seeds the hand-picked BENCH_r07 all-on
config so "matches or beats the hand A/B" holds by construction) and
resumed candidates alike.
"""

from __future__ import annotations

from typing import Callable, Dict, List, NamedTuple, Optional, Sequence

from kubernetriks_tpu.tune.knobs import (
    KNOBS,
    STAGES,
    default_statics,
    is_active,
)
from kubernetriks_tpu.tune.measure import canonical_key


class TuneResult(NamedTuple):
    chosen: Dict[str, object]  # the winning statics table
    objective: float  # its measured objective score
    baseline: Dict[str, object]  # hand-picked defaults + their score
    candidates: List[Dict[str, object]]  # every candidate, visit order
    measured: int  # NEW measurements this run
    reused: int  # resume-cache hits this run
    complete: bool  # False = budget stopped the sweep early
    fingerprint: str  # the grid's (shared) semantic fingerprint


class BudgetExhausted(Exception):
    """Internal control flow: the measurement budget ran out."""


def staged_coordinate_descent(
    backend,
    *,
    budget: Optional[int] = None,
    resume_candidates: Optional[Sequence[Dict[str, object]]] = None,
    seed_configs: Sequence[Dict[str, object]] = (),
    log: Optional[Callable[[str], None]] = None,
) -> TuneResult:
    """Run the sweep. `backend` is any object with
    `measure(statics) -> Measurement`; `seed_configs` are partial
    statics tables (merged over the defaults) that are always measured
    before the descent — reference configurations the chosen config
    must match or beat."""
    resume_cache: Dict[str, Dict[str, object]] = {}
    for entry in resume_candidates or ():
        if isinstance(entry, dict) and "statics" in entry and "objective" in entry:
            resume_cache[canonical_key(entry["statics"])] = entry

    cache: Dict[str, Dict[str, object]] = {}
    candidates: List[Dict[str, object]] = []
    counts = {"measured": 0, "reused": 0}

    def note(msg: str) -> None:
        if log is not None:
            log(msg)

    def evaluate(config: Dict[str, object]) -> Dict[str, object]:
        key = canonical_key(config)
        if key in cache:
            return cache[key]
        if key in resume_cache:
            entry = dict(resume_cache[key])
            entry["reused"] = True
            counts["reused"] += 1
            note(f"tune: reused {key}")
        else:
            if budget is not None and counts["measured"] >= budget:
                raise BudgetExhausted(key)
            m = backend.measure(config)
            entry = {"statics": dict(config), "reused": False}
            entry.update(m.as_record())
            counts["measured"] += 1
            note(
                f"tune: measured {key} -> objective "
                f"{entry['objective']}"
            )
        cache[key] = entry
        candidates.append(entry)
        return entry

    config = default_statics()
    complete = True
    try:
        evaluate(config)  # the hand-picked baseline is always candidate 0
        for seed in seed_configs:
            merged = dict(config)
            merged.update(seed)
            evaluate(merged)
        for stage in STAGES:
            for knob in KNOBS:
                if knob.stage != stage or knob.values is None:
                    continue
                if not is_active(knob, config):
                    continue
                best_val = config[knob.name]
                best_obj = evaluate(config)["objective"]
                for value in knob.values:
                    cand = dict(config)
                    cand[knob.name] = value
                    obj = evaluate(cand)["objective"]
                    if obj < best_obj:
                        best_obj, best_val = obj, value
                config[knob.name] = best_val
    except BudgetExhausted as exc:
        complete = False
        note(
            f"tune: budget of {budget} new measurements exhausted at "
            f"{exc} — partial profile; rerun with it as resume input"
        )

    if not candidates:
        raise ValueError(
            "tune: the measurement budget did not cover even the "
            "baseline configuration — raise KTPU_TUNE_BUDGET"
        )
    # Argmin over everything measured; ties break toward the earliest
    # candidate (visit order is deterministic).
    chosen = min(
        enumerate(candidates), key=lambda t: (t[1]["objective"], t[0])
    )[1]
    baseline = candidates[0]
    return TuneResult(
        chosen=dict(chosen["statics"]),
        objective=float(chosen["objective"]),
        baseline={
            "statics": dict(baseline["statics"]),
            "objective": float(baseline["objective"]),
        },
        candidates=candidates,
        measured=counts["measured"],
        reused=counts["reused"],
        complete=complete,
        fingerprint=str(chosen.get("fingerprint", "")),
    )


def profile_doc(
    result: TuneResult,
    *,
    backend: str,
    n_clusters: int,
    n_nodes: int,
    budget: Optional[int] = None,
    protocol: str = "",
) -> Dict[str, object]:
    """Compose the persistable profile document (profile.save_profile
    validates and writes it): the chosen statics, the objective
    definition, the baseline, budget accounting and EVERY measured
    candidate — a BENCH_*.json-style full-disclosure record."""
    return {
        "kind": "ktpu-tuned-profile",
        "schema": 1,
        "backend": backend,
        "geometry": {
            "n_clusters": int(n_clusters),
            "n_nodes": int(n_nodes),
        },
        "statics": dict(result.chosen),
        "objective": {
            "score": result.objective,
            "definition": (
                "telemetry per-window window-program cost "
                "(ms_per_window) scaled by 1 + 0.25 per fired "
                "observatory stall/occupancy verdict "
                "(telemetry/observatory.tuning_objective); lower is "
                "better"
            ),
        },
        "baseline": result.baseline,
        "complete": result.complete,
        "budget": {
            "limit": budget,
            "measured": result.measured,
            "reused": result.reused,
        },
        "protocol": protocol,
        "fingerprint": result.fingerprint,
        "candidates": result.candidates,
        "knob_registry": {
            k.name: {
                "kind": k.kind,
                "values": list(k.values) if k.values is not None else None,
                "default": k.default,
                "stage": k.stage,
                "recompile": k.recompile,
                "requires": [list(r) for r in k.requires],
            }
            for k in KNOBS
        },
    }
