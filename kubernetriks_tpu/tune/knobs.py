"""The declarative knob registry: every tunable performance static.

A Knob names ONE engine build kwarg (a jit-static or host dispatch
parameter that is bit-identity-safe by the repo's own parity gates),
its legal candidate values, the stage the coordinate-descent sweep
visits it in, whether changing it forces a recompile (so the search
can disclose compile cost per candidate), and the activation
predicates (`requires`) that keep the sweep off configurations the
engine rejects (stream without the superspan executor) or where the
knob is inert (superspan_k on a ladder engine).

Closed-domain knobs (`values` is a tuple) are swept; open-domain knobs
(`values is None`) are registered — profiles may carry them, the
engine seam applies them, validation type-checks them — but the
default sweep skips them (their useful range is geometry-specific:
staging-slab widths scale with the pod window, not with a universal
candidate list).

Deliberately NOT knobs:
- `reclaim` (the tristate): an explicit reclaim=True RAISES on traces
  whose node-name classes interleave (engine build contract) — a
  tuner candidate must never turn a measurement into a build error.
  `reclaim_period` is registered open-domain for engines that already
  reclaim.
- fleet lane count / pod window: those are GEOMETRY — the profile is
  keyed by them (backend_<C>x<N>), they are not searched within one
  profile.

Adding a knob (DESIGN SS16): add the engine kwarg with a None default
and the explicit-arg > env-flag > tuned-profile > platform-default
resolution, register it here with its legal values and `requires`,
and the sweep, the profile schema, validation and the engine seam all
pick it up — no other edits.
"""

from __future__ import annotations

from typing import Dict, NamedTuple, Optional, Tuple


class Knob(NamedTuple):
    name: str  # == the BatchedSimulation build kwarg it feeds
    kind: str  # "bool" | "int" — value type in profiles
    values: Optional[Tuple]  # legal sweep candidates; None = open domain
    default: object  # the hand-picked value the sweep starts from
    stage: str  # coordinate-descent stage (visited in registry order)
    recompile: bool  # changing it forces an XLA recompile
    requires: Tuple  # ((knob, value), ...) — active only when all hold
    doc: str


KNOBS: Tuple[Knob, ...] = (
    # -- executor stage: which steady-state dispatch program runs --------
    Knob(
        "superspan",
        "bool",
        (False, True),
        False,
        "executor",
        True,
        (),
        "Scanned multi-slide executor (one while_loop program retires up "
        "to K slide-spans per dispatch) vs the ladder path.",
    ),
    Knob(
        "fuse_slide",
        "bool",
        (False, True),
        False,
        "executor",
        True,
        (("superspan", False),),
        "Fused chunk+slide megastep on the ladder path (inert under the "
        "superspan executor, which slides in-program).",
    ),
    Knob(
        "superspan_k",
        "int",
        (8, 16, 32),
        16,
        "executor",
        True,
        (("superspan", True),),
        "Max slide-spans retired per superspan dispatch (the while_loop "
        "trip bound; one progress readback amortizes over K spans).",
    ),
    Knob(
        "superspan_chunk",
        "int",
        (4, 8, 16),
        8,
        "executor",
        True,
        (("superspan", True),),
        "Window-chunk tile inside the superspan body (windows advanced "
        "per inner iteration).",
    ),
    # -- layout stage: the PR 9 window-cost program variants -------------
    Knob(
        "lane_major",
        "bool",
        (False, True),
        False,
        "layout",
        True,
        (),
        "Lane-major (N, C) hot node state inside window programs — kills "
        "the per-kernel-boundary transposes on accelerator backends.",
    ),
    Knob(
        "window_razor",
        "bool",
        (False, True),
        False,
        "layout",
        True,
        (),
        "Empty-window identity branch: gate the per-window resolution "
        "soup behind a cheap due-ness predicate.",
    ),
    Knob(
        "ca_descatter",
        "bool",
        (False, True),
        True,
        "layout",
        True,
        (),
        "CA scale-down shared 2-key sort (segment-sum + grouping in one "
        "pass) — the BENCH_r07 -13.3% ms/window front.",
    ),
    # -- memory stage: buffer and staging policy -------------------------
    Knob(
        "donate",
        "bool",
        (False, True),
        False,
        "memory",
        True,
        (),
        "Buffer donation for the steady-state dispatch loop (donated jit "
        "variants consume the input state in place).",
    ),
    Knob(
        "stream",
        "bool",
        (False, True),
        False,
        "memory",
        True,
        (("superspan", True),),
        "Streaming trace-ingestion feeder ring (requires the superspan "
        "executor; the engine raises otherwise, so the sweep never "
        "visits that combination).",
    ),
    Knob(
        "stream_depth",
        "int",
        (2, 3, 4),
        3,
        "memory",
        False,
        (("stream", True),),
        "Feeder ring depth K: at most K staging slabs live on device at "
        "once. Host-side staging policy — no recompile.",
    ),
    # -- open-domain knobs: registered, applied, validated, NOT swept ----
    Knob(
        "superspan_stage_cols",
        "int",
        None,
        None,
        "executor",
        True,
        (("superspan", True),),
        "Staging-slab width (payload columns) of the superspan refill "
        "stage. Geometry-specific; profiles may pin it, the default "
        "sweep leaves the engine's clamp rule in charge.",
    ),
    Knob(
        "stream_segment",
        "int",
        None,
        None,
        "memory",
        True,
        (("stream", True),),
        "Staging-segment width of the streaming feeder's slabs (a jit "
        "static). Geometry-specific, like superspan_stage_cols.",
    ),
    Knob(
        "reclaim_period",
        "int",
        None,
        1,
        "memory",
        True,
        (),
        "Reclaim compaction cadence in windows, for engines whose "
        "reclaim tristate is already on (the knob never TURNS reclaim "
        "on — see the module docstring).",
    ),
)

_BY_NAME: Dict[str, Knob] = {k.name: k for k in KNOBS}

STAGES: Tuple[str, ...] = tuple(dict.fromkeys(k.stage for k in KNOBS))


def knob_by_name(name: str) -> Knob:
    """The registered knob, or a ValueError NAMING the unknown field —
    the error profile validation surfaces for stale/typo'd JSON."""
    knob = _BY_NAME.get(name)
    if knob is None:
        raise ValueError(
            f"unknown tuning knob {name!r} — not in the tune.knobs "
            f"registry (known: {', '.join(sorted(_BY_NAME))})"
        )
    return knob


def default_statics() -> Dict[str, object]:
    """The hand-picked starting point of every sweep: each swept knob at
    its registered default (open-domain knobs stay unset — the engine's
    own clamp/flag rules keep deciding them)."""
    return {k.name: k.default for k in KNOBS if k.values is not None}


def validate_value(knob: Knob, value: object) -> None:
    """Legality check for one (knob, value) pair, naming the field."""
    if knob.values is not None:
        if value not in knob.values:
            raise ValueError(
                f"tuning knob {knob.name!r}: value {value!r} is not in "
                f"the registered legal set {knob.values!r}"
            )
        return
    # Open domain: type-check only. None is always legal (= engine rule).
    if value is None:
        return
    if knob.kind == "int" and not isinstance(value, bool) and isinstance(value, int):
        return
    if knob.kind == "bool" and isinstance(value, bool):
        return
    raise ValueError(
        f"tuning knob {knob.name!r}: value {value!r} is not a valid "
        f"{knob.kind} (open-domain knobs type-check against the "
        "registry kind)"
    )


def validate_statics(statics: Dict[str, object]) -> Dict[str, object]:
    """Validate a whole statics table (profile `statics`/candidate
    entries): every key must be a registered knob, every value legal.
    Returns the table unchanged so call sites can chain."""
    for name, value in statics.items():
        validate_value(knob_by_name(name), value)
    return statics


def is_active(knob: Knob, config: Dict[str, object]) -> bool:
    """Whether the knob is live under `config` (its `requires` hold —
    missing keys fall back to the required knob's registered default)."""
    for dep, want in knob.requires:
        have = config.get(dep, _BY_NAME[dep].default)
        if have != want:
            return False
    return True


def active_knobs(config: Dict[str, object]) -> Tuple[Knob, ...]:
    """The swept knobs live under `config`, in registry (stage) order."""
    return tuple(
        k for k in KNOBS if k.values is not None and is_active(k, config)
    )
