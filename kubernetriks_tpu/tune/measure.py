"""Pluggable measurement backends for the autotuner.

The search (search.py) is backend-agnostic: it hands a fully-pinned
statics table to `backend.measure(statics)` and gets a `Measurement`
back. Two backends exist:

- `BenchMeasurementBackend` — the real capture path: builds an engine
  with the candidate statics on the caller's composed traces, runs the
  bench protocol (warm-up + >= 5 valid timed spans, zero-decision
  spans dropped and disclosed, in-measure asserts instead of silent
  fallbacks), reads the observatory objective
  (telemetry/observatory.tuning_objective: per-window window-program
  cost scaled by fired stall/occupancy verdicts), and enforces the
  statics-only contract PER CANDIDATE: the recompile sentinel is armed
  across the measured spans (zero post-warm-up compiles), and every
  candidate's final state must be bit-identical to the first
  candidate's (state.compare_states) with equal committed decisions —
  the whole-grid bit-identity gate.

- `FakeMeasurementBackend` — pinned measurements for tests, smoke and
  the CI tune-smoke job: a deterministic additive cost model (base
  cost minus a per-knob/per-value bonus table), so tests can pin the
  expected winner, resume behavior and budget accounting without
  building engines.
"""

from __future__ import annotations

import hashlib
import json
import time
from typing import Dict, List, NamedTuple, Optional

from kubernetriks_tpu.tune.knobs import validate_statics


class Measurement(NamedTuple):
    objective: float  # the score the search minimizes (lower = better)
    ms_per_window: float  # the raw per-window telemetry cost line
    decisions_per_s: float  # median composed rate (disclosure)
    spans: Dict[str, object]  # {n, min, max, dropped, spread_frac}
    verdicts_fired: Dict[str, int]  # observatory watchdog verdicts
    fingerprint: str  # semantic digest: final state + decisions
    recompiles_after_warmup: int  # sentinel events past seal (must be 0)
    wall_s: float  # capture cost (disclosure only — never an input
    #               to the search, so resumed runs stay deterministic)

    def as_record(self) -> Dict[str, object]:
        return {
            "objective": round(self.objective, 4),
            "ms_per_window": round(self.ms_per_window, 4),
            "decisions_per_s": round(self.decisions_per_s, 3),
            "spans": self.spans,
            "verdicts_fired": self.verdicts_fired,
            "fingerprint": self.fingerprint,
            "recompiles_after_warmup": self.recompiles_after_warmup,
            "wall_s": round(self.wall_s, 3),
        }


def canonical_key(statics: Dict[str, object]) -> str:
    """THE candidate identity: sorted-key JSON of the full statics
    table. Resume caches, dedup and profile candidate matching all key
    on this, so a reordered dict is the same candidate."""
    return json.dumps(statics, sort_keys=True, default=str)


class FakeMeasurementBackend:
    """Deterministic pinned measurements: objective = base minus the
    bonus table's entry for each (knob, value) in the candidate. Knobs
    absent from the table contribute 0 — independent contributions, so
    coordinate descent provably reaches the global optimum and tests
    can pin the winner."""

    def __init__(
        self,
        bonuses: Optional[Dict[str, Dict[object, float]]] = None,
        base: float = 100.0,
    ):
        self.bonuses = bonuses or {}
        self.base = float(base)
        self.measure_calls: List[Dict[str, object]] = []

    def measure(self, statics: Dict[str, object]) -> Measurement:
        validate_statics(statics)
        self.measure_calls.append(dict(statics))
        cost = self.base
        for name, value in statics.items():
            table = self.bonuses.get(name)
            if table:
                cost -= float(table.get(value, 0.0))
        assert cost > 0, (
            f"fake measurement backend: bonus table drove the objective "
            f"to {cost} <= 0 for {statics!r} — raise base"
        )
        return Measurement(
            objective=cost,
            ms_per_window=cost,
            decisions_per_s=1e6 / cost,
            spans={"n": 5, "min": 1, "max": 1, "dropped": 0,
                   "spread_frac": 1.0},
            verdicts_fired={},
            # One constant fingerprint: the fake grid is trivially
            # bit-identical, mirroring the real backend's contract.
            fingerprint="fake:pinned",
            recompiles_after_warmup=0,
            wall_s=0.0,
        )


class BenchMeasurementBackend:
    """Real capture: one engine build + bench-protocol measurement per
    candidate on a fixed composed trace set.

    The traces, geometry and shared build kwargs are pinned at
    construction; `measure()` varies ONLY the candidate statics. The
    first measured candidate becomes the bit-identity reference: every
    later candidate must reproduce its final state exactly
    (compare_states — the documented parity policy) with equal
    committed decisions, or measure() raises. fast_forward is pinned
    off so executor candidates actually dispatch the program they name
    (the bench smoke lines' precedent)."""

    def __init__(
        self,
        config,
        cluster_events,
        workload_events,
        *,
        n_clusters: int,
        warm_until: float,
        t_end: float,
        step: float,
        build_kwargs: Optional[Dict[str, object]] = None,
        min_valid_spans: int = 5,
    ):
        self.config = config
        self.cluster_events = cluster_events
        self.workload_events = workload_events
        self.n_clusters = int(n_clusters)
        self.warm_until = float(warm_until)
        self.t_end = float(t_end)
        self.step = float(step)
        self.build_kwargs = dict(build_kwargs or {})
        self.min_valid_spans = int(min_valid_spans)
        self.n_nodes: Optional[int] = None  # known after first build
        self._reference = None  # (statics, final state, decisions)
        self.measure_calls: List[Dict[str, object]] = []

    def _decisions(self, sim) -> int:
        import numpy as np

        return int(
            np.asarray(sim.state.metrics.scheduling_decisions).sum()
        )

    def measure(self, statics: Dict[str, object]) -> Measurement:
        import numpy as np

        from kubernetriks_tpu.batched.engine import (
            build_batched_from_traces,
        )
        from kubernetriks_tpu.batched.state import compare_states
        from kubernetriks_tpu.recompile import (
            RecompileSentinel,
            sentinel_mode,
        )
        from kubernetriks_tpu.telemetry.observatory import (
            tuning_objective,
        )

        validate_statics(statics)
        self.measure_calls.append(dict(statics))
        wall_t0 = time.perf_counter()
        # Per-candidate sentinel: any compile after the seal (engine
        # build + warm-up + precompile) breaks the candidate — tuned
        # statics must keep the compile-once contract the flag defaults
        # keep. KTPU_EXPLAIN_RECOMPILES=0 force-disarms (the documented
        # escape hatch), matching the bench in-line asserts.
        sentinel = None
        if sentinel_mode() is not False:
            sentinel = RecompileSentinel("raise").install()
        sim = build_batched_from_traces(
            self.config,
            self.cluster_events,
            self.workload_events,
            n_clusters=self.n_clusters,
            telemetry=True,
            fast_forward=False,
            tuned_profile=False,  # candidates pin every knob explicitly
            **statics,
            **self.build_kwargs,
        )
        try:
            self.n_nodes = sim.n_nodes
            sim.step_until_time(self.warm_until)
            # The pod window must SLIDE inside the warm-up
            # (run_endurance's rule): the slide shift/apply programs
            # compile on first use, so a first slide inside a timed
            # span would land seconds of compile post-seal and trip
            # the armed sentinel. The slide time is a function of the
            # trace alone (semantics, identical across candidates), so
            # every candidate extends by the same amount and the
            # measured span sequence stays grid-uniform.
            warm_end = self.warm_until
            if sim.pod_window is not None:
                while sim._pod_base == 0 and warm_end < self.t_end:
                    warm_end += self.step
                    sim.step_until_time(warm_end)
                assert sim._pod_base != 0, (
                    f"tune candidate {statics!r}: the pod window never "
                    f"slid by t_end={self.t_end} — a later first slide "
                    "would compile inside a timed span; enlarge the "
                    "capture horizon or shrink pod_window"
                )
            sim.precompile_chunks()
            if sentinel is not None:
                sentinel.seal(f"tune candidate warm-up {statics!r}")
            # The bench span protocol: >= min_valid timed spans, each
            # decision fetch a real sync, zero-decision spans dropped
            # and disclosed, re-arm past t_end up to +5 steps before
            # failing loudly (bench.run_composed's r7 rule).
            rates, span_decisions = [], []
            end = warm_end + self.step
            max_end = self.t_end + 5 * self.step
            while end <= self.t_end or (
                sum(1 for d in span_decisions if d > 0)
                < self.min_valid_spans
                and end <= max_end
            ):
                before = self._decisions(sim)
                t0 = time.perf_counter()
                sim.step_until_time(end)
                decided = self._decisions(sim) - before
                span_decisions.append(decided)
                rates.append(decided / (time.perf_counter() - t0))
                end += self.step
            valid = [r for r, d in zip(rates, span_decisions) if d > 0]
            dropped = len(rates) - len(valid)
            assert len(valid) >= self.min_valid_spans, (
                f"tune candidate {statics!r}: only {len(valid)} valid "
                f"timed spans ({dropped} dropped as zero-decision) — "
                "extend the capture horizon"
            )
            rep = sim.telemetry_report()
            obj = tuning_objective(rep)
            assert obj["ms_per_window"] > 0, (
                f"tune candidate {statics!r}: telemetry report carries "
                "no per-window cost line (no windows recorded?)"
            )
            recompiles = 0
            if sentinel is not None:
                sentinel.check(f"tune candidate {statics!r}")
                recompiles = len(sentinel.post_seal_events())
            decisions_total = self._decisions(sim)
            # Whole-grid statics-only gate: bit-identical final state +
            # equal committed decisions vs the first candidate.
            if self._reference is None:
                self._reference = (
                    dict(statics),
                    sim.state,
                    decisions_total,
                )
            else:
                ref_statics, ref_state, ref_decisions = self._reference
                assert decisions_total == ref_decisions, (
                    f"tune candidate {statics!r} committed "
                    f"{decisions_total} decisions vs {ref_decisions} "
                    f"for the reference {ref_statics!r} — a tuning "
                    "knob changed SEMANTICS, not just statics"
                )
                bad = compare_states(ref_state, sim.state)
                assert not bad, (
                    f"tune candidate {statics!r} diverged from the "
                    f"reference {ref_statics!r} final state: {bad} — "
                    "a tuning knob changed SEMANTICS, not just statics"
                )
            digest = hashlib.sha1()
            digest.update(str(decisions_total).encode())
            for leaf in _state_leaves(sim.state):
                digest.update(np.asarray(leaf).tobytes())
            spread = (
                round(max(valid) / min(valid), 3) if min(valid) else 0.0
            )
            return Measurement(
                objective=float(obj["score"]),
                ms_per_window=float(obj["ms_per_window"]),
                decisions_per_s=float(np.median(valid)),
                spans={
                    "n": len(valid),
                    "min": round(min(valid)),
                    "max": round(max(valid)),
                    "dropped": dropped,
                    "spread_frac": spread,
                },
                verdicts_fired=dict(obj["verdicts_fired"]),
                fingerprint=digest.hexdigest(),
                recompiles_after_warmup=recompiles,
                wall_s=time.perf_counter() - wall_t0,
            )
        finally:
            if sentinel is not None:
                sentinel.uninstall()
            sim.close()


def _state_leaves(state):
    import jax

    return [
        leaf
        for leaf in jax.tree_util.tree_leaves(state)
        if hasattr(leaf, "dtype")
    ]
