"""One JSON/table rendering path for every end-of-run report (PR 8
satellite).

Before this module, the scalar printer owned a hand-rolled table with
hardcoded row labels, the batched engine printed raw json.dumps, and the
telemetry report had no renderer at all. Everything now renders through
`render_metrics` / `render_telemetry`: a report is a dict shaped
`{"counters": {...}, "timings": {name: {min,max,mean,variance}}}` (the
schema both `metrics/printer.metrics_as_dict` and
`BatchedSimulation.metrics_summary` already emit), and the format is a
CLI choice (`--report json|table`), not a backend property."""

from __future__ import annotations

import json
from typing import Any, Dict, List


def format_table(rows: List[list], header: List[str]) -> str:
    """Aligned ASCII table (the scalar printer's format, reference:
    src/metrics/printer.rs:20-164) — the one table formatter."""
    widths = [
        max(len(str(row[i])) for row in [header] + rows)
        for i in range(len(header))
    ]

    def fmt_row(row):
        return (
            "| "
            + " | ".join(str(v).ljust(w) for v, w in zip(row, widths))
            + " |"
        )

    sep = "+" + "+".join("-" * (w + 2) for w in widths) + "+"
    lines = [sep, fmt_row(header), sep]
    lines += [fmt_row(row) for row in rows]
    lines.append(sep)
    return "\n".join(lines)


# Keys whose generic snake_case -> label transform would drop meaning
# (units); pinned to the labels the scalar table always printed.
_LABELS = {
    "node_downtime_s": "Node downtime (s)",
}


def humanize(key: str) -> str:
    """snake_case metric key -> row label ("pod_queue_time" ->
    "Pod queue time"), matching the labels the scalar table always
    printed."""
    return _LABELS.get(key, key.replace("_", " ").capitalize())


def render_metrics(d: Dict[str, Any], fmt: str) -> str:
    """Render a {"counters", "timings"} report dict as "json" or "table".
    Scalar and batched runs share this path, so both backends emit the
    same schema in the same two shapes."""
    if fmt == "json":
        return json.dumps(d, indent=2, default=float)
    if fmt != "table":
        raise ValueError(f"unknown report format {fmt!r} (json|table)")
    parts = []
    counters = d.get("counters")
    if counters:
        parts.append(
            format_table(
                [[humanize(k), v] for k, v in counters.items()],
                ["Metric", "Count"],
            )
        )
    timings = d.get("timings")
    if timings:
        parts.append(
            format_table(
                [
                    [
                        humanize(name),
                        *(stats[k] for k in ("min", "max", "mean", "variance")),
                    ]
                    for name, stats in timings.items()
                ],
                ["Metric", "Min", "Max", "Mean", "Variance"],
            )
        )
    return "\n".join(parts)


def render_telemetry(rep: Dict[str, Any], fmt: str) -> str:
    """Render engine.telemetry_report() as "json" or "table": the
    per-phase span table, the dispatch stats, the sync budget, and the
    device-ring totals."""
    if fmt == "json":
        return json.dumps(rep, indent=2, default=float)
    if fmt != "table":
        raise ValueError(f"unknown report format {fmt!r} (json|table)")
    parts = []
    spans = rep.get("spans")
    if spans:
        parts.append(
            format_table(
                [
                    [
                        name,
                        s["count"],
                        round(s["total_ms"], 3),
                        round(s["mean_us"], 1),
                        round(s["max_us"], 1),
                    ]
                    for name, s in spans.items()
                ],
                ["Phase", "Count", "Total ms", "Mean µs", "Max µs"],
            )
        )
    rows = [[humanize(k), v] for k, v in rep.get("dispatch_stats", {}).items()]
    rows += [
        [humanize(k), v] for k, v in rep.get("sync_budget", {}).items()
    ]
    rows += [[humanize(k), v] for k, v in rep.get("counters", {}).items()]
    ring = rep.get("ring")
    if ring:
        rows += [
            ["Ring windows recorded", ring["windows_recorded"]],
            ["Ring windows kept", ring["windows_kept"]],
        ]
        rows += [
            [f"Ring total {humanize(k).lower()}", v]
            for k, v in ring.get("totals", {}).items()
        ]
        rows += [
            [f"Ring high-water {humanize(k).lower()}", v]
            for k, v in ring.get("high_water", {}).items()
        ]
    resources = rep.get("resources")
    if resources:
        # Capacity-observatory summary: occupancy vs reserve, memory
        # watermarks, watchdog verdicts (full detail stays in the JSON).
        for name, entry in resources.get("occupancy", {}).items():
            if isinstance(entry, dict) and "used_max" in entry:
                cap = entry.get("capacity_min")
                rows.append(
                    [
                        f"Occupancy {humanize(name).lower()}",
                        f"{entry['used_max']}/{cap}" if cap else entry["used_max"],
                    ]
                )
        mem = resources.get("memory", {})
        if mem.get("rss_bytes"):
            rows.append(["Host RSS (MB)", round(mem["rss_bytes"] / 1e6, 1)])
        fired = resources.get("watchdog", {}).get("fired", {})
        rows.append(["Watchdog verdicts fired", len(fired)])
    if rows:
        parts.append(format_table(rows, ["Metric", "Count"]))
    return "\n".join(parts)
