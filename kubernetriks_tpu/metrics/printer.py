"""End-of-run metric dump as JSON or aligned text table
(reference: src/metrics/printer.rs:20-164)."""

from __future__ import annotations

import json
import sys
from typing import Any, Dict, Optional, TextIO

from kubernetriks_tpu.config import MetricsPrinterConfig
from kubernetriks_tpu.metrics.collector import MetricsCollector


def metrics_as_dict(collector: MetricsCollector) -> Dict[str, Any]:
    """The JSON schema mirrors the reference's MetricsJSON
    (reference: src/metrics/printer.rs:83-109)."""
    metrics = collector.accumulated_metrics
    return {
        "counters": {
            "total_nodes_in_trace": metrics.total_nodes_in_trace,
            "total_pods_in_trace": metrics.total_pods_in_trace,
            "pods_succeeded": metrics.pods_succeeded,
            "pods_unschedulable": metrics.pods_unschedulable,
            "pods_failed": metrics.pods_failed,
            "pods_removed": metrics.pods_removed,
            "total_scaled_up_nodes": metrics.total_scaled_up_nodes,
            "total_scaled_down_nodes": metrics.total_scaled_down_nodes,
            "total_scaled_up_pods": metrics.total_scaled_up_pods,
            "total_scaled_down_pods": metrics.total_scaled_down_pods,
            # Chaos-engine fault counters (zero when fault injection is off).
            "node_crashes": metrics.node_crashes,
            "node_recoveries": metrics.node_recoveries,
            "node_downtime_s": metrics.node_downtime_s,
            "pod_interruptions": metrics.pod_interruptions,
            "pod_restarts": metrics.pod_restarts,
        },
        "timings": {
            "pod_duration": metrics.pod_duration_stats.as_dict(),
            "pod_schedule_time": metrics.pod_scheduling_algorithm_latency_stats.as_dict(),
            "pod_queue_time": metrics.pod_queue_time_stats.as_dict(),
        },
    }


def metrics_as_pretty_table(collector: MetricsCollector) -> str:
    """Aligned-table rendering, through the SAME generic path the batched
    engine's metrics_summary and the telemetry report use
    (metrics/render.py) — scalar and batched runs emit the same report
    schema in the same two formats."""
    from kubernetriks_tpu.metrics.render import render_metrics

    return render_metrics(metrics_as_dict(collector), "table")


def print_metrics(
    collector: MetricsCollector,
    config: Optional[MetricsPrinterConfig],
    stream: Optional[TextIO] = None,
) -> None:
    """Write metrics per config; without a config (or output_file), write JSON
    to ``stream`` (stdout by default)."""
    fmt = config.format if config else "JSON"
    if fmt == "PrettyTable":
        text = metrics_as_pretty_table(collector)
    else:
        text = json.dumps(metrics_as_dict(collector), indent=2)

    if config and config.output_file:
        with open(config.output_file, "w") as f:
            f.write(text)
    else:
        print(text, file=stream or sys.stdout)
