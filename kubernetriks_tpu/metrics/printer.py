"""End-of-run metric dump as JSON or aligned text table
(reference: src/metrics/printer.rs:20-164)."""

from __future__ import annotations

import json
import sys
from typing import Any, Dict, Optional, TextIO

from kubernetriks_tpu.config import MetricsPrinterConfig
from kubernetriks_tpu.metrics.collector import MetricsCollector


def metrics_as_dict(collector: MetricsCollector) -> Dict[str, Any]:
    """The JSON schema mirrors the reference's MetricsJSON
    (reference: src/metrics/printer.rs:83-109)."""
    metrics = collector.accumulated_metrics
    return {
        "counters": {
            "total_nodes_in_trace": metrics.total_nodes_in_trace,
            "total_pods_in_trace": metrics.total_pods_in_trace,
            "pods_succeeded": metrics.pods_succeeded,
            "pods_unschedulable": metrics.pods_unschedulable,
            "pods_failed": metrics.pods_failed,
            "pods_removed": metrics.pods_removed,
            "total_scaled_up_nodes": metrics.total_scaled_up_nodes,
            "total_scaled_down_nodes": metrics.total_scaled_down_nodes,
            "total_scaled_up_pods": metrics.total_scaled_up_pods,
            "total_scaled_down_pods": metrics.total_scaled_down_pods,
            # Chaos-engine fault counters (zero when fault injection is off).
            "node_crashes": metrics.node_crashes,
            "node_recoveries": metrics.node_recoveries,
            "node_downtime_s": metrics.node_downtime_s,
            "pod_interruptions": metrics.pod_interruptions,
            "pod_restarts": metrics.pod_restarts,
        },
        "timings": {
            "pod_duration": metrics.pod_duration_stats.as_dict(),
            "pod_schedule_time": metrics.pod_scheduling_algorithm_latency_stats.as_dict(),
            "pod_queue_time": metrics.pod_queue_time_stats.as_dict(),
        },
    }


def _format_table(rows: list, header: list) -> str:
    widths = [
        max(len(str(row[i])) for row in [header] + rows) for i in range(len(header))
    ]

    def fmt_row(row):
        return "| " + " | ".join(str(v).ljust(w) for v, w in zip(row, widths)) + " |"

    sep = "+" + "+".join("-" * (w + 2) for w in widths) + "+"
    lines = [sep, fmt_row(header), sep]
    lines += [fmt_row(row) for row in rows]
    lines.append(sep)
    return "\n".join(lines)


def metrics_as_pretty_table(collector: MetricsCollector) -> str:
    d = metrics_as_dict(collector)
    counter_rows = [
        ["Total nodes in trace", d["counters"]["total_nodes_in_trace"]],
        ["Total pods in trace", d["counters"]["total_pods_in_trace"]],
        ["Pods succeeded", d["counters"]["pods_succeeded"]],
        ["Pods unschedulable", d["counters"]["pods_unschedulable"]],
        ["Pods failed", d["counters"]["pods_failed"]],
        ["Pods removed", d["counters"]["pods_removed"]],
        ["Total scaled up nodes", d["counters"]["total_scaled_up_nodes"]],
        ["Total scaled down nodes", d["counters"]["total_scaled_down_nodes"]],
        ["Total scaled up pods", d["counters"]["total_scaled_up_pods"]],
        ["Total scaled down pods", d["counters"]["total_scaled_down_pods"]],
        ["Node crashes", d["counters"]["node_crashes"]],
        ["Node recoveries", d["counters"]["node_recoveries"]],
        ["Node downtime (s)", d["counters"]["node_downtime_s"]],
        ["Pod interruptions", d["counters"]["pod_interruptions"]],
        ["Pod restarts", d["counters"]["pod_restarts"]],
    ]
    timing_rows = [
        [name, *(stats[k] for k in ("min", "max", "mean", "variance"))]
        for name, stats in [
            ("Pod duration", d["timings"]["pod_duration"]),
            ("Pod schedule time", d["timings"]["pod_schedule_time"]),
            ("Pod queue time", d["timings"]["pod_queue_time"]),
        ]
    ]
    return (
        _format_table(counter_rows, ["Metric", "Count"])
        + "\n"
        + _format_table(timing_rows, ["Metric", "Min", "Max", "Mean", "Variance"])
    )


def print_metrics(
    collector: MetricsCollector,
    config: Optional[MetricsPrinterConfig],
    stream: Optional[TextIO] = None,
) -> None:
    """Write metrics per config; without a config (or output_file), write JSON
    to ``stream`` (stdout by default)."""
    fmt = config.format if config else "JSON"
    if fmt == "PrettyTable":
        text = metrics_as_pretty_table(collector)
    else:
        text = json.dumps(metrics_as_dict(collector), indent=2)

    if config and config.output_file:
        with open(config.output_file, "w") as f:
            f.write(text)
    else:
        print(text, file=stream or sys.stdout)
