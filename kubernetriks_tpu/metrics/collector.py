"""Centralized metric store + self-ticking collector.

Mirrors the reference's MetricsCollector (reference: src/metrics/collector.rs):
counters (AccumulatedMetrics), statistical estimators (min/max/mean/population
variance), gauges, a 60 s pod-utilization pull cycle, and a 5 s gauge recording
cycle. The gauge CSV path is configurable (the reference hardcodes
experiments/gauge_metrics.csv at collector.rs:216); None disables the file while
keeping the cycle (gauges still refresh for the HPA and tests).
"""

from __future__ import annotations

import csv
import math
from dataclasses import dataclass, field
from typing import Dict, Optional, TYPE_CHECKING, Tuple

from kubernetriks_tpu.core.events import (
    RecordGaugeMetricsCycle,
    RunPodMetricsCollectionCycle,
)
from kubernetriks_tpu.sim.kernel import EventHandler, SimulationContext

if TYPE_CHECKING:
    from kubernetriks_tpu.core.api_server import KubeApiServer


class Estimator:
    """Streaming min/max/mean/population-variance (Welford), matching the
    estimator bundle the reference builds from the `average` crate
    (reference: src/metrics/collector.rs:15-74)."""

    def __init__(self) -> None:
        self._count = 0
        self._min = math.inf
        self._max = -math.inf
        self._mean = 0.0
        self._m2 = 0.0

    def add(self, value: float) -> None:
        self._count += 1
        self._min = min(self._min, value)
        self._max = max(self._max, value)
        delta = value - self._mean
        self._mean += delta / self._count
        self._m2 += delta * (value - self._mean)

    def min(self) -> float:
        return self._min

    def max(self) -> float:
        return self._max

    def mean(self) -> float:
        return self._mean if self._count else math.nan

    def population_variance(self) -> float:
        return self._m2 / self._count if self._count else math.nan

    def count(self) -> int:
        return self._count

    def as_dict(self) -> Dict[str, float]:
        return {
            "min": self.min(),
            "max": self.max(),
            "mean": self.mean(),
            "variance": self.population_variance(),
        }

    def __eq__(self, other: object) -> bool:
        if not isinstance(other, Estimator):
            return NotImplemented
        return (
            self.min() == other.min()
            and self.max() == other.max()
            and self.mean() == other.mean()
            and (
                self.population_variance() == other.population_variance()
                or (
                    math.isnan(self.population_variance())
                    and math.isnan(other.population_variance())
                )
            )
        )


@dataclass
class InternalMetrics:
    """reference: src/metrics/collector.rs:77-87."""

    processed_nodes: int = 0
    terminated_pods: int = 0


@dataclass
class AccumulatedMetrics:
    """reference: src/metrics/collector.rs:89-192."""

    total_nodes_in_trace: int = 0
    total_pods_in_trace: int = 0
    pods_succeeded: int = 0
    pods_unschedulable: int = 0
    pods_failed: int = 0
    pods_removed: int = 0
    pod_duration_stats: Estimator = field(default_factory=Estimator)
    pod_scheduling_algorithm_latency_stats: Estimator = field(default_factory=Estimator)
    pod_queue_time_stats: Estimator = field(default_factory=Estimator)
    total_scaled_up_nodes: int = 0
    total_scaled_down_nodes: int = 0
    total_scaled_up_pods: int = 0
    total_scaled_down_pods: int = 0
    # Chaos-engine fault accounting (kubernetriks_tpu/chaos.py). pods_failed
    # above counts PERMANENTLY failed pods (restart limit exceeded);
    # pod_restarts counts CrashLoopBackOff requeues.
    node_crashes: int = 0
    node_recoveries: int = 0
    node_downtime_s: float = 0.0  # sum of sampled repair spans of applied crashes
    pod_interruptions: int = 0  # pods rescheduled because their node crashed
    pod_restarts: int = 0
    internal: InternalMetrics = field(default_factory=InternalMetrics)
    # pod group name -> (cpu estimator, ram estimator)
    pod_utilization_metrics: Dict[str, Tuple[Estimator, Estimator]] = field(
        default_factory=dict
    )

    def increment_pod_duration(self, value: float) -> None:
        self.pod_duration_stats.add(value)

    def increment_pod_scheduling_algorithm_latency(self, value: float) -> None:
        self.pod_scheduling_algorithm_latency_stats.add(value)

    def increment_pod_queue_time(self, value: float) -> None:
        self.pod_queue_time_stats.add(value)


@dataclass
class GaugeMetrics:
    """reference: src/metrics/collector.rs:166-192."""

    current_nodes: int = 0
    current_pods: int = 0
    pods_in_scheduling_queues: int = 0
    node_average_cpu_utilization: float = 0.0
    node_average_ram_utilization: float = 0.0
    cluster_total_cpu_utilization: float = 0.0
    cluster_total_ram_utilization: float = 0.0


GAUGE_CSV_COLUMNS = [
    "timestamp",
    "current_nodes",
    "current_pods",
    "pods_in_scheduling_queues",
    "node_average_cpu_utilization",
    "node_average_ram_utilization",
    "cluster_total_cpu_utilization",
    "cluster_total_ram_utilization",
]


class MetricsCollector(EventHandler):
    """reference: src/metrics/collector.rs:194-431."""

    RECORD_INTERVAL = 5.0
    COLLECTION_INTERVAL = 60.0

    def __init__(self, gauge_csv_path: Optional[str] = None) -> None:
        self.api_server_component: Optional["KubeApiServer"] = None
        self.ctx: Optional[SimulationContext] = None
        self.accumulated_metrics = AccumulatedMetrics()
        self.gauge_metrics = GaugeMetrics()
        self._gauge_file = None
        self._gauge_writer = None
        if gauge_csv_path:
            self._gauge_file = open(gauge_csv_path, "w", newline="")
            self._gauge_writer = csv.writer(self._gauge_file)
            self._gauge_writer.writerow(GAUGE_CSV_COLUMNS)

    def set_api_server_component(self, api_server: "KubeApiServer") -> None:
        self.api_server_component = api_server

    def set_context(self, ctx: SimulationContext) -> None:
        self.ctx = ctx

    def start_gauge_metrics_recording(self) -> None:
        self.ctx.emit_self_now(RecordGaugeMetricsCycle())

    def start_pod_metrics_collection(self) -> None:
        self.ctx.emit_self_now(RunPodMetricsCollectionCycle())

    # --- pod utilization pull (HPA input) ----------------------------------

    def collect_pod_metrics(self, event_time: float) -> None:
        """Pull per-pod-group cpu/ram utilization straight from node components
        (direct reads, not events — reference: src/metrics/collector.rs:263-337)."""
        self.accumulated_metrics.pod_utilization_metrics.clear()
        all_nodes = self.api_server_component.all_created_nodes()

        pod_count_in_pod_groups: Dict[str, int] = {}
        for node in all_nodes:
            for info in node.running_pods.values():
                if info.pod_group is not None:
                    pod_count_in_pod_groups[info.pod_group] = (
                        pod_count_in_pod_groups.get(info.pod_group, 0) + 1
                    )

        for node in all_nodes:
            for info in node.running_pods.values():
                if info.pod_group is None:
                    continue
                total = pod_count_in_pod_groups[info.pod_group]
                cpu_util = (
                    info.cpu_usage_model.current_usage(event_time, total)
                    if info.cpu_usage_model
                    else 0.0
                )
                ram_util = (
                    info.ram_usage_model.current_usage(event_time, total)
                    if info.ram_usage_model
                    else 0.0
                )
                utils = self.accumulated_metrics.pod_utilization_metrics.setdefault(
                    info.pod_group, (Estimator(), Estimator())
                )
                utils[0].add(cpu_util)
                utils[1].add(ram_util)

    def pod_metrics_mean_utilization(self) -> Dict[str, Tuple[float, float]]:
        return {
            group: (cpu.mean(), ram.mean())
            for group, (cpu, ram) in self.accumulated_metrics.pod_utilization_metrics.items()
        }

    # --- gauges -------------------------------------------------------------

    def collect_utilizations(self) -> None:
        """reference: src/metrics/collector.rs:352-390."""
        all_nodes = self.api_server_component.all_created_nodes()
        gauges = self.gauge_metrics
        gauges.node_average_cpu_utilization = 0.0
        gauges.node_average_ram_utilization = 0.0
        cluster_cpu_requests = cluster_ram_requests = 0
        cluster_cpu_capacity = cluster_ram_capacity = 0
        node_count = len(all_nodes)

        for node_component in all_nodes:
            status = node_component.runtime.node.status
            cpu_request = status.capacity.cpu - status.allocatable.cpu
            ram_request = status.capacity.ram - status.allocatable.ram
            gauges.node_average_cpu_utilization += cpu_request / status.capacity.cpu
            gauges.node_average_ram_utilization += ram_request / status.capacity.ram
            cluster_cpu_requests += cpu_request
            cluster_ram_requests += ram_request
            cluster_cpu_capacity += status.capacity.cpu
            cluster_ram_capacity += status.capacity.ram

        # Matches the reference's unguarded divisions: NaN when the cluster is
        # empty is avoided here by explicit guards (deviation: the reference
        # would produce NaN/inf; we clamp to 0.0 for clean CSV output).
        if node_count:
            gauges.node_average_cpu_utilization /= node_count
            gauges.node_average_ram_utilization /= node_count
        else:
            gauges.node_average_cpu_utilization = 0.0
            gauges.node_average_ram_utilization = 0.0
        gauges.cluster_total_cpu_utilization = (
            cluster_cpu_requests / cluster_cpu_capacity if cluster_cpu_capacity else 0.0
        )
        gauges.cluster_total_ram_utilization = (
            cluster_ram_requests / cluster_ram_capacity if cluster_ram_capacity else 0.0
        )

    def record_gauge_metrics(self, current_time: float) -> None:
        self.collect_utilizations()
        if self._gauge_writer is not None:
            gauges = self.gauge_metrics
            self._gauge_writer.writerow(
                [
                    current_time,
                    gauges.current_nodes,
                    gauges.current_pods,
                    gauges.pods_in_scheduling_queues,
                    gauges.node_average_cpu_utilization,
                    gauges.node_average_ram_utilization,
                    gauges.cluster_total_cpu_utilization,
                    gauges.cluster_total_ram_utilization,
                ]
            )

    def close(self) -> None:
        if self._gauge_file is not None:
            self._gauge_file.close()
            self._gauge_file = None
            self._gauge_writer = None

    # --- event handlers -----------------------------------------------------

    def on_run_pod_metrics_collection_cycle(
        self, data: RunPodMetricsCollectionCycle, time: float
    ) -> None:
        self.collect_pod_metrics(time)
        self.ctx.emit_self(RunPodMetricsCollectionCycle(), self.COLLECTION_INTERVAL)

    def on_record_gauge_metrics_cycle(
        self, data: RecordGaugeMetricsCycle, time: float
    ) -> None:
        self.record_gauge_metrics(time)
        self.ctx.emit_self(RecordGaugeMetricsCycle(), self.RECORD_INTERVAL)
