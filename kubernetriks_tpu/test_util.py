"""Shared test fixtures and cross-component consistency asserts
(reference: src/test_util/helpers.rs)."""

from __future__ import annotations

from kubernetriks_tpu.config import SimulationConfig
from kubernetriks_tpu.core.types import Node
from kubernetriks_tpu.sim.simulator import KubernetriksSimulation

DEFAULT_TEST_CONFIG_YAML = """
sim_name: "test_kubernetriks"
seed: 123
scheduling_cycle_interval: 10.0
as_to_ps_network_delay: 0.050
ps_to_sched_network_delay: 0.010
sched_to_as_network_delay: 0.020
as_to_node_network_delay: 0.150
as_to_ca_network_delay: 0.30
as_to_hpa_network_delay: 0.40
"""


def default_test_simulation_config(with_suffix: str = "") -> SimulationConfig:
    """reference: src/test_util/helpers.rs:60-80."""
    return SimulationConfig.from_yaml(DEFAULT_TEST_CONFIG_YAML + with_suffix)


def check_expected_node_is_equal_to_nodes_in_components(
    expected_node: Node, kube_sim: KubernetriksSimulation
) -> None:
    """State must agree in api server, storage and scheduler at once
    (reference: src/test_util/helpers.rs:7-33)."""
    name = expected_node.metadata.name
    assert expected_node == kube_sim.api_server.get_node_component(name).get_node()
    assert expected_node == kube_sim.persistent_storage.get_node(name)
    assert expected_node == kube_sim.scheduler.get_node(name)


def check_count_of_nodes_in_components_equals_to(
    count: int, kube_sim: KubernetriksSimulation
) -> None:
    assert count == kube_sim.api_server.node_count()
    assert count == kube_sim.persistent_storage.node_count()
    assert count == kube_sim.scheduler.node_count()


def check_expected_node_appeared_in_components(
    node_name: str, kube_sim: KubernetriksSimulation
) -> None:
    assert kube_sim.api_server.get_node_component(node_name) is not None
    assert kube_sim.persistent_storage.get_node(node_name) is not None
    kube_sim.scheduler.get_node(node_name)


# --- Alibaba CSV real-format quirk rendering (shared by the Python-oracle
# and native-feeder quirk suites, so both always test the SAME quirked
# input) --------------------------------------------------------------------

ALIBABA_INSTANCE_HEADER = (
    "start_ts,end_ts,job_id,task_id,machine_id,status,seq_no,total_seq_no"
)
ALIBABA_TASK_HEADER = (
    "create_ts,end_ts,job_id,task_id,inst_num,status,plan_cpu,plan_mem"
)
ALIBABA_MACHINE_HEADER = "ts,machine_id,event_type,event_detail,cap_cpu,cap_mem"


def quirkify_csv(text, crlf=False, quote=False, header=None):
    """Re-render a clean CSV body with real-format quirks: quote every other
    field (RFC4180 — including empty fields, which stay empty), prepend an
    optional header row, and optionally join with CRLF endings."""
    lines = text.strip("\n").split("\n")
    if quote:
        lines = [
            ",".join(
                f'"{f}"' if (li + fi) % 2 == 0 else f
                for fi, f in enumerate(line.split(","))
            )
            for li, line in enumerate(lines)
        ]
    if header is not None:
        lines.insert(0, header)
    eol = "\r\n" if crlf else "\n"
    return eol.join(lines) + eol
