"""Chaos engine: counter-based fault sampling shared by BOTH execution paths.

The simulator's first nondeterminism-bearing subsystem. Every random draw is
a pure function of a counter tuple — threefry2x32 on
(seed, stream, cluster, object, incarnation/attempt) — so the scalar
event-driven path and the batched array path consume IDENTICAL values with no
stream to keep in sync, batched runs stay order-independent (a dropped or
re-ordered draw cannot shift any other draw), and re-running any prefix of a
simulation replays the same faults. This is the template every future
stochastic workload should follow (see docs/DESIGN.md "Fault model").

Two fault channels:

- Node crashes (MTTF) with recovery (MTTR), sampled HOST-SIDE into concrete
  crash/recover events before either path runs: crash/recover chains depend
  only on the trace's node lifetimes, never on simulation state, so they
  compile exactly. A crash rides the planned node-removal chain (flagged
  `crashed`, carrying its pre-sampled downtime); a recovery is a fresh
  CreateNodeRequest (flagged `recovered`) — the node returns as fresh
  capacity on a NEW slot/pool component in both paths, visible to the
  cluster autoscaler like any other capacity. TTF/TTR draws are clamped
  below at one scheduling interval so every crash->recover->crash transition
  lands in its own batched window (the bulk event application is
  window-granular).

- Pod failures (CrashLoopBackOff), drawn AT ATTEMPT COMMIT TIME in both
  paths from (cluster, global plain pod slot, restart count): a failing
  attempt runs for u_frac x duration then fails; the pod re-enters the
  scheduling queue after min(backoff_base * 2^k, backoff_cap) and is marked
  permanently failed once its restart count exceeds restart_limit. Only
  plain trace pods participate (HPA pod-group ring replicas and
  long-running services are exempt — their identities are runtime-assigned
  and path-specific).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

# Stream ids separating the fault channels in the counter space.
STREAM_NODE = 1
STREAM_GROUP = 2
STREAM_POD = 3


class FaultParams(NamedTuple):
    """Static (hashable) fault constants threaded into the batched step as a
    jit-static argument. None in its place = fault injection off — every
    compiled program is then textually identical to the pre-chaos build
    (the composed-path dispatch formula is untouched)."""

    seed: int
    fail_prob: float
    backoff_base: float
    backoff_cap: float
    restart_limit: int
    node_faults: bool  # slab may carry EV_NODE_CRASH / EV_NODE_RECOVER

    @property
    def pod_faults(self) -> bool:
        return self.fail_prob > 0.0


def has_node_faults(cfg) -> bool:
    """Whether a FaultInjectionConfig configures any node-level fault
    channel — the ONE owner of this predicate (the CLI's native-feeder
    guard, the engine's per-cluster compile decision and the jit-static
    FaultParams must never disagree)."""
    return (
        cfg is not None
        and cfg.enabled
        and (
            (cfg.node is not None and cfg.node.mttf > 0)
            or any(g.mttf > 0 for g in (cfg.failure_groups or []))
        )
    )


def make_fault_params(config) -> Optional[FaultParams]:
    """FaultParams from a SimulationConfig; None when fault injection is
    disabled or configured to do nothing."""
    cfg = getattr(config, "fault_injection", None)
    if cfg is None or not cfg.enabled:
        return None
    node_faults = has_node_faults(cfg)
    pod = cfg.pod
    fail_prob = float(pod.fail_prob) if pod else 0.0
    if not node_faults and fail_prob <= 0:
        return None
    return FaultParams(
        seed=int(cfg.seed if cfg.seed is not None else config.seed),
        fail_prob=fail_prob,
        backoff_base=float(pod.backoff_base) if pod else 10.0,
        backoff_cap=float(pod.backoff_cap) if pod else 300.0,
        restart_limit=int(pod.restart_limit) if pod else 5,
        node_faults=node_faults,
    )

_KS_PARITY = 0x1BD11BDA
_ROT_A = (13, 15, 26, 6)
_ROT_B = (17, 29, 16, 24)


def _threefry2x32(k0, k1, c0, c1, xp):
    """Threefry-2x32 (20 rounds). `xp` is numpy or jax.numpy; every
    intermediate is cast back to uint32 so both backends wrap identically.
    Returns two uint32 blocks."""
    u32 = xp.uint32

    def u(x):
        return xp.asarray(x).astype(u32)

    def rotl(x, r):
        return u(
            (x << u(np.uint32(r))) | (x >> u(np.uint32(32 - r)))
        )

    ks0, ks1 = u(k0), u(k1)
    ks2 = u(ks0 ^ ks1 ^ u(np.uint32(_KS_PARITY)))
    ks = (ks0, ks1, ks2)
    x0 = u(u(c0) + ks0)
    x1 = u(u(c1) + ks1)
    for chunk in range(5):
        rots = _ROT_A if chunk % 2 == 0 else _ROT_B
        for r in rots:
            x0 = u(x0 + x1)
            x1 = rotl(x1, r)
            x1 = u(x1 ^ x0)
        d = chunk + 1
        x0 = u(x0 + ks[d % 3])
        x1 = u(x1 + ks[(d + 1) % 3] + u(np.uint32(d)))
    return x0, x1


def _to_unit(bits, xp):
    """uint32 -> float32 uniform in [0, 1): top 24 bits scaled. (bits >> 8)
    < 2^24 is exactly representable in float32 and the 2^-24 scaling is a
    power of two, so the conversion is bit-identical on every backend."""
    f32 = xp.float32
    return (bits >> xp.uint32(8)).astype(f32) * f32(2.0**-24)


def object_uniforms(seed, stream, cluster, obj, counter, xp=np):
    """Two float32 uniforms for (seed, stream, cluster, obj, counter) via a
    two-level threefry chain: key = H(seed, stream | cluster, obj), then
    block (counter, 0). Vectorized: cluster/obj/counter broadcast. The ONE
    derivation both paths use (numpy host-side, jnp on device)."""
    h0, h1 = _threefry2x32(seed, stream, cluster, obj, xp)
    b0, b1 = _threefry2x32(h0, h1, counter, xp.uint32(0), xp)
    return _to_unit(b0, xp), _to_unit(b1, xp)


def pod_attempt_uniforms(seed, cluster, slot, attempt, xp=np):
    """(u_fail, u_frac) for one pod scheduling attempt; attempt = the pod's
    restart count when the attempt commits."""
    return object_uniforms(seed, STREAM_POD, cluster, slot, attempt, xp)


# --- node-fault compilation (host-side, shared by both paths) ---------------


def _sample_span(u: float, mean: float, distribution: str) -> float:
    if distribution == "fixed":
        return float(mean)
    if distribution != "exponential":
        # Config parsing validates too; this guards direct-API callers.
        raise ValueError(
            f"unknown fault distribution {distribution!r} "
            "(expected 'exponential' or 'fixed')"
        )
    # Exponential inverse CDF; u in [0, 1) so log(1-u) is finite.
    return float(-mean * np.log1p(-np.float64(u)))


def _sample_span_vec(
    u: np.ndarray, mean: float, distribution: str
) -> np.ndarray:
    """Vectorized _sample_span over a float32 uniform array: the SAME f64
    elementwise arithmetic (cast first, then -mean * log1p(-u)), so each
    lane is bit-identical to the scalar call on its element."""
    if distribution == "fixed":
        return np.full(np.shape(u), float(mean), np.float64)
    if distribution != "exponential":
        raise ValueError(
            f"unknown fault distribution {distribution!r} "
            "(expected 'exponential' or 'fixed')"
        )
    return -float(mean) * np.log1p(-np.asarray(u, np.float64))


def fault_horizon(cfg, cluster_events, workload_events) -> float:
    """Sampling horizon: explicit config value, else the latest finite trace
    timestamp (both paths hold the same traces, so both derive the same
    horizon)."""
    if cfg.horizon is not None:
        return float(cfg.horizon)
    last = 0.0
    for events in (cluster_events, workload_events):
        for ts, _ in events:
            if np.isfinite(ts):
                last = max(last, float(ts))
    return last


@dataclass
class _NodeLifetime:
    uid: int  # appearance index among the trace's CreateNode events
    name: str
    node: object  # core.types.Node template (capacity source)
    create_ts: float
    remove_ts: float  # +inf when never removed by the trace


def _node_lifetimes(cluster_events) -> List[_NodeLifetime]:
    from kubernetriks_tpu.core.events import CreateNodeRequest, RemoveNodeRequest

    lifetimes: List[_NodeLifetime] = []
    live: Dict[str, _NodeLifetime] = {}
    for ts, event in cluster_events:
        if isinstance(event, CreateNodeRequest):
            lt = _NodeLifetime(
                uid=len(lifetimes),
                name=event.node.metadata.name,
                node=event.node,
                create_ts=float(ts),
                remove_ts=np.inf,
            )
            lifetimes.append(lt)
            live[lt.name] = lt
        elif isinstance(event, RemoveNodeRequest):
            lt = live.pop(event.node_name, None)
            if lt is not None:
                lt.remove_ts = float(ts)
    return lifetimes


def _chain(
    seed: int,
    stream: int,
    cluster: int,
    uid: int,
    t0: float,
    end: float,
    horizon: float,
    mttf: float,
    mttr: float,
    distribution: str,
    interval: float,
) -> List[Tuple[float, float]]:
    """Crash/recover pairs for one failure process alive on [t0, end).
    Each incarnation k draws (u_ttf, u_ttr) from the counter PRNG; draws are
    clamped below at one scheduling interval so consecutive transitions land
    in distinct batched windows. A pair is emitted only when BOTH times fall
    before the node's planned removal (a crash whose recovery would outlive
    the node is dropped — the node stays up until its planned removal)."""
    pairs: List[Tuple[float, float]] = []
    t = t0
    k = 0
    while True:
        u1, u2 = object_uniforms(
            seed, stream, np.uint32(cluster), np.uint32(uid), np.uint32(k)
        )
        ttf = max(_sample_span(float(u1), mttf, distribution), interval)
        crash = t + ttf
        if crash >= min(horizon, end):
            break
        ttr = max(_sample_span(float(u2), mttr, distribution), interval)
        recover = crash + ttr
        if recover >= end:
            break
        pairs.append((crash, recover))
        t = recover
        k += 1
    return pairs


def _chains_batched(
    seed: int,
    stream: int,
    cluster: int,
    uids: Sequence[int],
    t0s: Sequence[float],
    ends: Sequence[float],
    horizon: float,
    mttf: float,
    mttr: float,
    distribution: str,
    interval: float,
) -> List[List[Tuple[float, float]]]:
    """Crash/recover chains for MANY failure processes at once — the
    vectorized twin of per-uid _chain calls, pinned bit-identical by
    tests/test_chaos.py. The counter PRNG is order-independent, so one
    threefry call per incarnation index draws (u_ttf, u_ttr) for EVERY
    process; only the tiny incarnation loop stays sequential (chain times
    accumulate), and each lane's float arithmetic is the scalar loop's
    exact sequence (elementwise f64 adds in the same association). Draws
    for already-terminated processes are computed and dropped — dropped
    draws desync nothing by construction.

    Replaces the host-side compile bottleneck for node-fault traces: the
    loop version hashed 2 x incarnations x lifetimes blocks one scalar
    threefry at a time through Python."""
    U = len(uids)
    pairs: List[List[Tuple[float, float]]] = [[] for _ in range(U)]
    if U == 0:
        return pairs
    uid_arr = np.asarray(uids, np.uint32)
    t = np.asarray(t0s, np.float64).copy()
    end_arr = np.asarray(ends, np.float64)
    cutoff = np.minimum(np.float64(horizon), end_arr)  # crash must stay below
    active = np.ones(U, bool)
    k = 0
    while active.any():
        u1, u2 = object_uniforms(
            seed, stream, np.uint32(cluster), uid_arr, np.uint32(k)
        )
        ttf = np.maximum(_sample_span_vec(u1, mttf, distribution), interval)
        crash = t + ttf
        active &= crash < cutoff
        ttr = np.maximum(_sample_span_vec(u2, mttr, distribution), interval)
        recover = crash + ttr
        active &= recover < end_arr
        for i in np.nonzero(active)[0]:
            pairs[i].append((float(crash[i]), float(recover[i])))
        t = np.where(active, recover, t)
        k += 1
    return pairs


def inject_node_faults(
    cluster_events,
    cfg,
    seed: int,
    cluster_idx: int,
    horizon: float,
    interval: float,
):
    """Return a NEW cluster-event list: the original events (order
    preserved) plus sampled crash/recover events appended in time order.
    Crash = RemoveNodeRequest(crashed=True, downtime_s=sampled TTR);
    recover = CreateNodeRequest(recovered=True) with the node's original
    capacity (a fresh slot / pool component in both paths). Deterministic in
    (cfg, seed, cluster_idx, trace)."""
    from kubernetriks_tpu.core.events import CreateNodeRequest, RemoveNodeRequest

    lifetimes = _node_lifetimes(cluster_events)
    by_name: Dict[str, List[_NodeLifetime]] = {}
    for lt in lifetimes:
        by_name.setdefault(lt.name, []).append(lt)

    fault_events: List[Tuple[float, object]] = []
    # Emitted downtime spans per lifetime uid. The per-node and group chains
    # are sampled independently, so without mutual exclusion a group crash
    # could land while its member is already down (double-remove -> KeyError
    # at trace compile). Channels are applied in a fixed order (per-node
    # first, then groups in config order) and a pair is dropped for any
    # member already down — or within one scheduling interval of another
    # transition, keeping every slot's create/remove in distinct batched
    # windows. Host-side and order-deterministic, so both paths agree.
    downtime: Dict[int, List[Tuple[float, float]]] = {}

    def clear_of_existing(lt: _NodeLifetime, crash: float, recover: float) -> bool:
        return all(
            recover + interval <= start or crash >= end + interval
            for start, end in downtime.get(lt.uid, [])
        )

    def emit_pair(lt: _NodeLifetime, crash: float, recover: float) -> None:
        downtime.setdefault(lt.uid, []).append((crash, recover))
        ttr = recover - crash
        fault_events.append(
            (
                crash,
                RemoveNodeRequest(
                    node_name=lt.name, crashed=True, downtime_s=float(ttr)
                ),
            )
        )
        fresh = lt.node.copy()
        fresh.status.allocatable = fresh.status.capacity.copy()
        fault_events.append(
            (recover, CreateNodeRequest(node=fresh, recovered=True))
        )

    # Chain sampling is BATCHED across lifetimes (_chains_batched draws one
    # threefry block per incarnation index for every process at once);
    # emission order is unchanged — lifetimes in uid order, each chain in
    # incarnation order — so the event stream is bit-identical to the
    # per-lifetime loop (pinned in tests/test_chaos.py).
    if cfg.node is not None and cfg.node.mttf > 0:
        chains = _chains_batched(
            seed,
            STREAM_NODE,
            cluster_idx,
            [lt.uid for lt in lifetimes],
            [lt.create_ts for lt in lifetimes],
            [lt.remove_ts for lt in lifetimes],
            horizon,
            cfg.node.mttf,
            cfg.node.mttr,
            cfg.node.distribution,
            interval,
        )
        for lt, chain in zip(lifetimes, chains):
            for crash, recover in chain:
                emit_pair(lt, crash, recover)

    # Correlated failure groups: one shared crash process per group; every
    # member whose lifetime covers the full (crash, recover) span goes down
    # and comes back together (blast radius). Groups carry their own
    # mttf/mttr, so each is its own (single-process) batched call.
    for gi, group in enumerate(cfg.failure_groups or []):
        for crash, recover in _chains_batched(
            seed,
            STREAM_GROUP,
            cluster_idx,
            [gi],
            [0.0],
            [np.inf],
            horizon,
            group.mttf,
            group.mttr,
            group.distribution,
            interval,
        )[0]:
            for name in group.members:
                for lt in by_name.get(name, []):
                    if (
                        lt.create_ts <= crash
                        and recover < lt.remove_ts
                        and clear_of_existing(lt, crash, recover)
                    ):
                        emit_pair(lt, crash, recover)

    fault_events.sort(key=lambda item: item[0])
    return list(cluster_events) + fault_events


# --- pod-fault oracle (scalar path) -----------------------------------------


def plain_pod_slot_map(workload_events) -> Dict[str, int]:
    """name -> global plain pod slot, replicating the batched trace
    compiler's numbering: CreatePodRequest events stably sorted by
    timestamp, ranked among plain pods (pod-group ring slots are renumbered
    past every plain pod by segment_pod_slots, so the plain rank IS the
    global slot in both the segmented and unsegmented layouts)."""
    from kubernetriks_tpu.core.events import CreatePodRequest

    creates = [
        (float(ts), i, event.pod.metadata.name)
        for i, (ts, event) in enumerate(workload_events)
        if isinstance(event, CreatePodRequest)
    ]
    creates.sort(key=lambda item: (item[0], item[1]))
    return {name: slot for slot, (_, _, name) in enumerate(creates)}


class PodFaultOracle:
    """Scalar-path pod failure oracle: draws the SAME counter-PRNG values
    the batched commit draws on device, tracks per-pod restart counts, and
    answers the retry/perma/backoff questions the control-plane components
    ask. Pods without a plain trace slot (HPA ring replicas) and
    long-running services are exempt."""

    def __init__(self, cfg, seed: int, cluster_idx: int, workload_events) -> None:
        pod = cfg.pod
        self.fail_prob = np.float32(pod.fail_prob if pod else 0.0)
        self.backoff_base = float(pod.backoff_base) if pod else 10.0
        self.backoff_cap = float(pod.backoff_cap) if pod else 300.0
        self.restart_limit = int(pod.restart_limit) if pod else 5
        self.seed = int(seed)
        self.cluster_idx = int(cluster_idx)
        self.slot_map = plain_pod_slot_map(workload_events)
        self.restarts: Dict[str, int] = {}

    def attempt(
        self, pod_name: str, pod_duration: Optional[float]
    ) -> Optional[float]:
        """Draw for one scheduling attempt at commit: returns fail_after
        seconds (the attempt fails that long after its start) or None (the
        attempt runs to completion)."""
        if self.fail_prob <= 0 or pod_duration is None:
            return None
        slot = self.slot_map.get(pod_name)
        if slot is None:
            return None
        k = self.restarts.get(pod_name, 0)
        u_fail, u_frac = pod_attempt_uniforms(
            self.seed,
            np.uint32(self.cluster_idx),
            np.uint32(slot),
            np.uint32(k),
        )
        if not bool(np.float32(u_fail) < self.fail_prob):
            return None
        # f32 product mirrors the batched path's u_frac * duration_seconds.
        return float(np.float32(u_frac) * np.float32(pod_duration))

    def record_failure(self, pod_name: str) -> int:
        """Increment and return the pod's restart count (called once per
        failure, by the api server — the first component on the failure
        chain)."""
        k = self.restarts.get(pod_name, 0) + 1
        self.restarts[pod_name] = k
        return k

    def is_permanently_failed(self, pod_name: str) -> bool:
        return self.restarts.get(pod_name, 0) > self.restart_limit

    def backoff_after_failure(self, pod_name: str) -> float:
        """Backoff of the pod's LAST recorded failure: min(base * 2^k, cap)
        with k = the restart count before that failure (0-based). float32
        arithmetic so the value matches the batched path bit-for-bit."""
        k = max(self.restarts.get(pod_name, 1) - 1, 0)
        return float(
            np.minimum(
                np.float32(self.backoff_base) * np.exp2(np.float32(k)),
                np.float32(self.backoff_cap),
            )
        )
