"""Simulation configuration: one YAML file -> SimulationConfig.

Mirrors the reference's config surface (reference: src/config.rs:12-69 and the
autoscaler sub-configs at
src/autoscalers/cluster_autoscaler/cluster_autoscaler.rs:57-96,
src/autoscalers/horizontal_pod_autoscaler/horizontal_pod_autoscaler.rs:39-70,
src/autoscalers/cluster_autoscaler/kube_cluster_autoscaler.rs:34-55,
src/autoscalers/horizontal_pod_autoscaler/kube_horizontal_pod_autoscaler.rs:27-46,
src/metrics/printer.rs:7-18).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import yaml

from kubernetriks_tpu.core.types import Node


@dataclass
class NodeGroup:
    """Node-group template for the default cluster and the cluster autoscaler.

    Two uses, two count fields (the reference keeps separate types for them):
    - ``node_count`` sizes default-cluster groups (reference: src/config.rs:61-69).
      Naming rules (applied in the simulator): node_count>1 + named template =>
      name used as prefix; node_count None/1 => name used verbatim; unnamed =>
      default_node(_<idx>)? prefix.
    - ``max_count`` caps how many nodes the cluster autoscaler may scale a group
      up to (reference: src/autoscalers/cluster_autoscaler/interface.rs:7-18);
      None means unbounded (up to the global max_node_count).
    """

    node_count: Optional[int] = None
    max_count: Optional[int] = None
    node_template: Node = field(default_factory=Node)

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "NodeGroup":
        return NodeGroup(
            node_count=d.get("node_count"),
            max_count=d.get("max_count"),
            node_template=Node.from_dict(d.get("node_template") or {}),
        )


@dataclass
class KubeClusterAutoscalerConfig:
    scale_down_utilization_threshold: float = 0.5

    @staticmethod
    def from_dict(d: Optional[Dict[str, Any]]) -> "KubeClusterAutoscalerConfig":
        if not d:
            return KubeClusterAutoscalerConfig()
        return KubeClusterAutoscalerConfig(
            scale_down_utilization_threshold=float(
                d.get("scale_down_utilization_threshold", 0.5)
            )
        )


@dataclass
class ClusterAutoscalerConfig:
    enabled: bool = False
    autoscaler_type: str = "kube_cluster_autoscaler"
    scan_interval: float = 10.0
    max_node_count: int = 0
    node_groups: List[NodeGroup] = field(default_factory=list)
    kube_cluster_autoscaler: Optional[KubeClusterAutoscalerConfig] = None

    @staticmethod
    def from_dict(d: Optional[Dict[str, Any]]) -> "ClusterAutoscalerConfig":
        if not d:
            return ClusterAutoscalerConfig()
        return ClusterAutoscalerConfig(
            enabled=bool(d.get("enabled", False)),
            autoscaler_type=d.get("autoscaler_type", d.get("type", "kube_cluster_autoscaler")),
            scan_interval=float(d.get("scan_interval", 10.0)),
            max_node_count=int(d.get("max_node_count", 0)),
            node_groups=[NodeGroup.from_dict(g) for g in d.get("node_groups") or []],
            kube_cluster_autoscaler=(
                KubeClusterAutoscalerConfig.from_dict(d["kube_cluster_autoscaler"])
                if d.get("kube_cluster_autoscaler") is not None
                else None
            ),
        )


@dataclass
class KubeHorizontalPodAutoscalerConfig:
    target_threshold_tolerance: float = 0.1

    @staticmethod
    def from_dict(d: Optional[Dict[str, Any]]) -> "KubeHorizontalPodAutoscalerConfig":
        if not d:
            return KubeHorizontalPodAutoscalerConfig()
        return KubeHorizontalPodAutoscalerConfig(
            target_threshold_tolerance=float(d.get("target_threshold_tolerance", 0.1))
        )


@dataclass
class HorizontalPodAutoscalerConfig:
    enabled: bool = False
    autoscaler_type: str = "kube_horizontal_pod_autoscaler"
    scan_interval: float = 60.0
    kube_horizontal_pod_autoscaler_config: Optional[KubeHorizontalPodAutoscalerConfig] = None

    @staticmethod
    def from_dict(d: Optional[Dict[str, Any]]) -> "HorizontalPodAutoscalerConfig":
        if not d:
            return HorizontalPodAutoscalerConfig()
        return HorizontalPodAutoscalerConfig(
            enabled=bool(d.get("enabled", False)),
            autoscaler_type=d.get(
                "autoscaler_type", d.get("type", "kube_horizontal_pod_autoscaler")
            ),
            scan_interval=float(d.get("scan_interval", 60.0)),
            kube_horizontal_pod_autoscaler_config=(
                KubeHorizontalPodAutoscalerConfig.from_dict(
                    d["kube_horizontal_pod_autoscaler_config"]
                )
                if d.get("kube_horizontal_pod_autoscaler_config") is not None
                else None
            ),
        )


_FAULT_DISTRIBUTIONS = ("exponential", "fixed")


def _checked_distribution(value: Any) -> str:
    dist = str(value)
    if dist not in _FAULT_DISTRIBUTIONS:
        raise ValueError(
            f"fault_injection distribution must be one of "
            f"{_FAULT_DISTRIBUTIONS}, got {dist!r}"
        )
    return dist


@dataclass
class NodeFaultConfig:
    """Per-node crash/recovery process. mttf <= 0 disables the channel.
    distribution: "exponential" (default) or "fixed" (deterministic spans).
    Draws are clamped below at one scheduling interval (chaos.py)."""

    mttf: float = 0.0  # mean time to failure, seconds
    mttr: float = 60.0  # mean time to recovery, seconds
    distribution: str = "exponential"

    @staticmethod
    def from_dict(d: Optional[Dict[str, Any]]) -> "NodeFaultConfig":
        if not d:
            return NodeFaultConfig()
        return NodeFaultConfig(
            mttf=float(d.get("mttf", 0.0)),
            mttr=float(d.get("mttr", 60.0)),
            distribution=_checked_distribution(
                d.get("distribution", "exponential")
            ),
        )


@dataclass
class PodFaultConfig:
    """Pod-level failure with CrashLoopBackOff retry. fail_prob <= 0
    disables the channel. A failed attempt re-enters the scheduling queue
    after min(backoff_base * 2^k, backoff_cap) seconds (k = restarts so
    far); a pod whose restart count exceeds restart_limit is marked
    permanently failed."""

    fail_prob: float = 0.0
    backoff_base: float = 10.0
    backoff_cap: float = 300.0
    restart_limit: int = 5

    @staticmethod
    def from_dict(d: Optional[Dict[str, Any]]) -> "PodFaultConfig":
        if not d:
            return PodFaultConfig()
        return PodFaultConfig(
            fail_prob=float(d.get("fail_prob", 0.0)),
            backoff_base=float(d.get("backoff_base", 10.0)),
            backoff_cap=float(d.get("backoff_cap", 300.0)),
            restart_limit=int(d.get("restart_limit", 5)),
        )


@dataclass
class FailureGroupConfig:
    """Correlated blast-radius set: one shared crash process takes every
    member down (and back up) together."""

    members: List[str] = field(default_factory=list)
    mttf: float = 0.0
    mttr: float = 60.0
    distribution: str = "exponential"

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "FailureGroupConfig":
        return FailureGroupConfig(
            members=[str(m) for m in d.get("members") or []],
            mttf=float(d.get("mttf", 0.0)),
            mttr=float(d.get("mttr", 60.0)),
            distribution=_checked_distribution(
                d.get("distribution", "exponential")
            ),
        )


@dataclass
class FaultInjectionConfig:
    """Chaos engine (kubernetriks_tpu/chaos.py): stochastic node
    crash/recovery and pod CrashLoopBackOff, bit-identical across the
    scalar and batched paths via a counter-based PRNG on
    (seed, cluster, object, incarnation)."""

    enabled: bool = False
    seed: Optional[int] = None  # defaults to the simulation seed
    horizon: Optional[float] = None  # defaults to the last trace timestamp
    node: NodeFaultConfig = field(default_factory=NodeFaultConfig)
    pod: PodFaultConfig = field(default_factory=PodFaultConfig)
    failure_groups: List[FailureGroupConfig] = field(default_factory=list)

    @staticmethod
    def from_dict(d: Optional[Dict[str, Any]]) -> "FaultInjectionConfig":
        if not d:
            return FaultInjectionConfig()
        return FaultInjectionConfig(
            enabled=bool(d.get("enabled", False)),
            seed=(int(d["seed"]) if d.get("seed") is not None else None),
            horizon=(
                float(d["horizon"]) if d.get("horizon") is not None else None
            ),
            node=NodeFaultConfig.from_dict(d.get("node")),
            pod=PodFaultConfig.from_dict(d.get("pod")),
            failure_groups=[
                FailureGroupConfig.from_dict(g)
                for g in d.get("failure_groups") or []
            ],
        )


@dataclass
class MetricsPrinterConfig:
    format: str = "JSON"  # "JSON" | "PrettyTable"
    output_file: str = ""

    @staticmethod
    def from_dict(d: Optional[Dict[str, Any]]) -> Optional["MetricsPrinterConfig"]:
        if not d:
            return None
        fmt = d.get("format", "JSON")
        # The reference's YAML uses serde enum tags (`format: !PrettyTable`);
        # plain strings are the canonical form here. A tag on an empty mapping
        # arrives as {"__tag__": name}; an untagged serde-style map as
        # {"PrettyTable": None}.
        if isinstance(fmt, dict):
            fmt = fmt.get("__tag__") or (next(iter(fmt)) if fmt else "JSON")
        return MetricsPrinterConfig(format=str(fmt), output_file=str(d.get("output_file", "")))


@dataclass
class AlibabaWorkloadTraceV2017Paths:
    batch_instance_trace_path: str = ""
    batch_task_trace_path: str = ""
    machine_events_trace_path: Optional[str] = None

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "AlibabaWorkloadTraceV2017Paths":
        return AlibabaWorkloadTraceV2017Paths(
            batch_instance_trace_path=d.get("batch_instance_trace_path", ""),
            batch_task_trace_path=d.get("batch_task_trace_path", ""),
            machine_events_trace_path=d.get("machine_events_trace_path"),
        )


@dataclass
class GenericTracePaths:
    workload_trace_path: str = ""
    cluster_trace_path: str = ""

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "GenericTracePaths":
        return GenericTracePaths(
            workload_trace_path=d.get("workload_trace_path", ""),
            cluster_trace_path=d.get("cluster_trace_path", ""),
        )


@dataclass
class TraceConfig:
    """Exactly one of the two may be set (asserted at CLI entry, mirroring
    reference: src/main.rs:62-65)."""

    alibaba_cluster_trace_v2017: Optional[AlibabaWorkloadTraceV2017Paths] = None
    generic_trace: Optional[GenericTracePaths] = None

    @staticmethod
    def from_dict(d: Optional[Dict[str, Any]]) -> Optional["TraceConfig"]:
        if not d:
            return None
        return TraceConfig(
            alibaba_cluster_trace_v2017=(
                AlibabaWorkloadTraceV2017Paths.from_dict(d["alibaba_cluster_trace_v2017"])
                if d.get("alibaba_cluster_trace_v2017")
                else None
            ),
            generic_trace=(
                GenericTracePaths.from_dict(d["generic_trace"])
                if d.get("generic_trace")
                else None
            ),
        )


@dataclass
class SimulationConfig:
    sim_name: str = "kubernetriks-tpu"
    seed: int = 0
    trace_config: Optional[TraceConfig] = None
    logs_filepath: Optional[str] = None
    cluster_autoscaler: ClusterAutoscalerConfig = field(
        default_factory=ClusterAutoscalerConfig
    )
    horizontal_pod_autoscaler: HorizontalPodAutoscalerConfig = field(
        default_factory=HorizontalPodAutoscalerConfig
    )
    fault_injection: FaultInjectionConfig = field(
        default_factory=FaultInjectionConfig
    )
    metrics_printer: Optional[MetricsPrinterConfig] = None
    default_cluster: Optional[List[NodeGroup]] = None
    scheduling_cycle_interval: float = 10.0
    # Scheduler profile spec: a NAMED_PROFILE_SPECS string ("default",
    # "best_fit", "balanced_packing") or an explicit mapping
    # {filters: [...], score: [{name, weight}, ...]}. Parsed by
    # core.scheduler.kube_scheduler.kube_scheduler_config_from_spec — the
    # ONE parser both backends share; the batched engine additionally
    # compiles it into kernel statics (batched/pipeline.py) and raises at
    # construction on a profile it cannot lower. None = reference default
    # (Fit + LeastAllocatedResources).
    scheduler_profile: Optional[Any] = None
    enable_unscheduled_pods_conditional_move: bool = False
    # Simulated control-plane network delays in seconds; as = api server,
    # ps = persistent storage, ca = cluster autoscaler, hpa = horizontal pod
    # autoscaler. All are bidirectional (reference: src/config.rs:28-36).
    as_to_ps_network_delay: float = 0.0
    ps_to_sched_network_delay: float = 0.0
    sched_to_as_network_delay: float = 0.0
    as_to_node_network_delay: float = 0.0
    as_to_ca_network_delay: float = 0.0
    as_to_hpa_network_delay: float = 0.0

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "SimulationConfig":
        default_cluster = d.get("default_cluster")
        return SimulationConfig(
            sim_name=d.get("sim_name", "kubernetriks-tpu"),
            seed=int(d.get("seed", 0)),
            trace_config=TraceConfig.from_dict(d.get("trace_config")),
            logs_filepath=d.get("logs_filepath"),
            cluster_autoscaler=ClusterAutoscalerConfig.from_dict(
                d.get("cluster_autoscaler")
            ),
            horizontal_pod_autoscaler=HorizontalPodAutoscalerConfig.from_dict(
                d.get("horizontal_pod_autoscaler")
            ),
            fault_injection=FaultInjectionConfig.from_dict(
                d.get("fault_injection")
            ),
            metrics_printer=MetricsPrinterConfig.from_dict(d.get("metrics_printer")),
            default_cluster=(
                [NodeGroup.from_dict(g) for g in default_cluster]
                if default_cluster
                else None
            ),
            scheduling_cycle_interval=float(d.get("scheduling_cycle_interval", 10.0)),
            scheduler_profile=d.get("scheduler_profile"),
            enable_unscheduled_pods_conditional_move=bool(
                d.get("enable_unscheduled_pods_conditional_move", False)
            ),
            as_to_ps_network_delay=float(d.get("as_to_ps_network_delay", 0.0)),
            ps_to_sched_network_delay=float(d.get("ps_to_sched_network_delay", 0.0)),
            sched_to_as_network_delay=float(d.get("sched_to_as_network_delay", 0.0)),
            as_to_node_network_delay=float(d.get("as_to_node_network_delay", 0.0)),
            as_to_ca_network_delay=float(d.get("as_to_ca_network_delay", 0.0)),
            as_to_hpa_network_delay=float(d.get("as_to_hpa_network_delay", 0.0)),
        )

    @staticmethod
    def from_yaml(text: str) -> "SimulationConfig":
        return SimulationConfig.from_dict(load_yaml_with_tags(text) or {})

    @staticmethod
    def from_file(path: str) -> "SimulationConfig":
        with open(path) as f:
            return SimulationConfig.from_yaml(f.read())


class _TaggedLoader(yaml.SafeLoader):
    """SafeLoader that flattens serde-style YAML tags.

    The reference's YAML uses serde enum tags like ``event_type: !CreatePod {...}``
    and ``format: !PrettyTable`` (reference: src/data/*.yaml, src/config.yaml:6-8).
    A tag on a mapping becomes {"__tag__": name, **mapping}; a tag on an empty
    scalar becomes the bare tag name string.
    """


def _multi_constructor(loader: _TaggedLoader, tag_suffix: str, node: yaml.Node) -> Any:
    if isinstance(node, yaml.MappingNode):
        value = loader.construct_mapping(node, deep=True)
        value["__tag__"] = tag_suffix
        return value
    if isinstance(node, yaml.SequenceNode):
        return {"__tag__": tag_suffix, "items": loader.construct_sequence(node, deep=True)}
    scalar = loader.construct_scalar(node)
    return tag_suffix if scalar in (None, "") else {"__tag__": tag_suffix, "value": scalar}


_TaggedLoader.add_multi_constructor("!", _multi_constructor)


def load_yaml_with_tags(text: str) -> Any:
    return yaml.load(text, Loader=_TaggedLoader)
