"""Runtime sanitizer (`KTPU_SANITIZE=1`) — the dynamic half of ktpu-lint.

The static passes (kubernetriks_tpu/lint/) prove the SOURCE obeys the
framework invariants; the sanitizer enforces them on a live run:

- **Transfer guard**: the engine's steady-state dispatch region
  (`step_until_time`) runs under
  `jax.transfer_guard_device_to_host("disallow_explicit")`, so ANY
  device-to-host transfer — implicit (`.item()`, `int(arr)`,
  `np.asarray(arr)`) or explicit (`jax.device_get`) — raises unless it
  sits inside an `allow_transfer(reason)` scope. The allow scopes pair
  1:1 with the lint pass's sync-ok waivers: the static budget and the
  runtime budget are the same list.

  The CPU backend never fires jax's transfer guard (host-resident
  buffers make every d2h read zero-copy, measured on jax 0.4.37), so the
  guard alone has no teeth on CPU CI. The sanitizer therefore ALSO keeps
  its own thread-local guard depth, and `to_host` — the framework's d2h
  convention (parallel/multihost.py) — asserts through
  `assert_sync_allowed` that it is inside an allow scope whenever the
  guard is active. Textual sync forms that bypass `to_host`
  (`np.asarray`, `int(arr)`, `.item()`) are the static lint pass's job;
  together the two nets cover both backends.
- **Donation enforcement**: after a donated jit call, donated inputs must
  be dead. On accelerator backends XLA marks them deleted; on CPU
  donation is a no-op, which is exactly why read-after-donate bugs pass
  CPU CI. `consume_donated` force-deletes any surviving donated input so
  a later read raises ("Array has been deleted") on every backend.
- The `KTPU_DEBUG_FINITE` NaN/inf state sweep folds in at every dispatch
  boundary (engine._check_finite runs under sanitize too).

Host-to-device transfers stay unguarded: argument commits at dispatch are
implicit h2d by design (cheap, asynchronous), and staging/refill uploads
are the documented streaming protocol — the sanitizer targets the sync
bug class (d2h), not uploads.
"""

from __future__ import annotations

import contextlib
import threading

import jax

from kubernetriks_tpu.flags import flag_bool

_state = threading.local()


def _depths():
    if not hasattr(_state, "guard"):
        _state.guard = 0
        _state.allow = 0
    return _state


def sanitize_default() -> bool:
    """The build-time default for BatchedSimulation(sanitize_mode=None)."""
    return flag_bool("KTPU_SANITIZE")


@contextlib.contextmanager
def _guard_cm():
    st = _depths()
    st.guard += 1
    try:
        with jax.transfer_guard_device_to_host("disallow_explicit"):
            yield
    finally:
        st.guard -= 1


@contextlib.contextmanager
def _allow_cm():
    st = _depths()
    st.allow += 1
    try:
        with jax.transfer_guard_device_to_host("allow"):
            yield
    finally:
        st.allow -= 1


def guard(active: bool):
    """Context manager for the steady-state dispatch region: disallow ALL
    device-to-host transfers (explicit included) while active — via jax's
    transfer guard on backends that enforce it, and via the
    assert_sync_allowed choke point everywhere."""
    if not active:
        return contextlib.nullcontext()
    return _guard_cm()


def allow_transfer(active: bool, reason: str):
    """Waived-sync scope; `reason` mirrors the lint waiver's reason and is
    kept as a required argument so the runtime budget stays greppable."""
    assert reason, "allow_transfer requires a reason"
    if not active:
        return contextlib.nullcontext()
    return _allow_cm()


def assert_sync_allowed(what: str) -> None:
    """Raise when a device-to-host sync happens inside a sanitized
    dispatch region outside every allow_transfer scope. Called by the
    framework's d2h choke points (to_host); two integer compares when no
    guard is active."""
    st = _depths()
    if st.guard > 0 and st.allow == 0:
        raise RuntimeError(
            f"KTPU_SANITIZE: unwaived device-to-host sync ({what}) inside "
            "the sanitized steady-state dispatch region — wrap a legitimate "
            "sync in sanitize.allow_transfer(reason) and give its line a "
            "sync-ok lint waiver"
        )


def consume_donated(tree) -> int:
    """Enforce donation semantics on `tree` (a pytree that was passed at a
    donated position): every jax.Array leaf must be dead after the call.
    Leaves XLA already consumed are left alone; survivors (CPU, where
    donation is unimplemented and the bug class silently passes) are
    force-deleted so any read-after-donate raises. Returns the number of
    leaves force-deleted."""
    forced = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        if isinstance(leaf, jax.Array):
            try:
                deleted = leaf.is_deleted()
            except AttributeError:  # tracers/ShapeDtypeStructs: nothing to do
                continue
            if not deleted:
                leaf.delete()
                forced += 1
    return forced
