"""Multi-host (DCN) support for the batched simulation.

The cluster batch shards over a mesh with no collectives inside the step
(batched/engine.py), so scaling past one host is purely a placement problem:
build the same compiled trace on every process, materialize each process's
addressable shards of the global arrays, and gather metric reductions across
processes at readout. The step program itself is unchanged — XLA runs it
SPMD per host, and the only DCN traffic is trace upload and metric readout
(the scalar analog of this "network" is the in-process event queue,
reference: src/config.rs:28-36; SURVEY.md §5.8).

Single-process meshes take the plain device_put path; these helpers are the
cross-process generalization (jax.make_array_from_callback for placement,
multihost_utils.process_allgather for readout) and degrade to the local
behavior when jax.process_count() == 1, which is how the test suite
exercises them.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh

from kubernetriks_tpu.sanitize import assert_sync_allowed


def shard_map(f, mesh, in_specs, out_specs, check_vma=None):
    """jax.shard_map across the installed-JAX API drift (the jax.enable_x64
    / pltpu.CompilerParams treatment, PR 3): newer lines expose a top-level
    jax.shard_map with `check_vma`; the 0.4.x line ships it as
    jax.experimental.shard_map.shard_map with the same semantics under
    `check_rep`. ONE shim so every caller (step._shard_rowwise, the RL
    attention policy, tests) stays on one spelling."""
    if hasattr(jax, "shard_map"):
        kwargs = {} if check_vma is None else {"check_vma": check_vma}
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    kwargs = {} if check_vma is None else {"check_rep": check_vma}
    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs
    )


def _distributed_is_initialized() -> bool:
    """jax.distributed.is_initialized across the API drift: absent on the
    installed 0.4.x line, where the client object's existence is the
    equivalent signal."""
    probe = getattr(jax.distributed, "is_initialized", None)
    if probe is not None:
        return bool(probe())
    state = getattr(jax.distributed, "global_state", None)
    return state is not None and getattr(state, "client", None) is not None


def initialize_from_env(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """jax.distributed.initialize with explicit args or the JAX_* /
    cloud-TPU environment autodetection; call once per process before any
    device op. Returns True if a multi-process runtime was initialized.
    Safe to call unconditionally: when no coordinator is configured or
    detectable (a plain single-process run), this is a no-op returning
    False, and a repeated call after the runtime (or backend) already
    started returns whether a multi-process runtime is active instead of
    surfacing jax's RuntimeError."""
    if _distributed_is_initialized():
        return jax.process_count() > 1
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    except ValueError:
        # jax raises when cluster autodetection finds no coordinator; that
        # IS the single-process case this helper promises to tolerate.
        return False
    except RuntimeError as e:
        # Tolerate ONLY the late-init case (XLA backend already started —
        # too late to go distributed, i.e. a plain single-process run).
        # Genuine distributed-init failures (coordinator unreachable, ...)
        # also surface as RuntimeError subclasses and must stay loud.
        if "must be called before" in str(e) or "already initialized" in str(e):
            return False
        raise
    return True


def global_mesh(axis_name: str = "clusters") -> Mesh:
    """1-D mesh over every device of every process (DP over the cluster
    batch; pass to BatchedSimulation(mesh=...))."""
    return Mesh(np.array(jax.devices()), (axis_name,))


def is_cross_process(mesh: Mesh) -> bool:
    me = jax.process_index()
    return any(d.process_index != me for d in mesh.devices.flat)


def put_global(tree, shardings):
    """Place a host-built pytree onto (possibly cross-process) shardings.

    Every process holds the full host copy (the compiled trace is
    deterministic, so all processes build identical arrays) and contributes
    the shards it can address; jax.make_array_from_callback assembles the
    global jax.Arrays. Equivalent to jax.device_put on a single process."""

    def put(leaf, sharding):
        host = np.asarray(leaf)
        return jax.make_array_from_callback(
            host.shape, sharding, lambda idx: host[idx]
        )

    return jax.tree.map(put, tree, shardings)


def to_host(x) -> np.ndarray:
    """Global host copy of a (possibly cross-process sharded) array: plain
    np.asarray when this process addresses all shards, otherwise an
    allgather over DCN.

    THE framework's device-to-host choke point: under KTPU_SANITIZE an
    unwaived call inside the sanitized dispatch region raises (jax's
    transfer guard never fires on the CPU backend, so the sanitizer
    carries its own net here)."""
    assert_sync_allowed("to_host")
    if getattr(x, "is_fully_addressable", True):
        return np.asarray(x)
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(x, tiled=True))
