"""Ring attention: sequence-parallel attention over a sharded axis.

The long-context capability of this framework: when the per-cluster node
count is too large for one device (or simply sharded for throughput), the
attention pass of the scheduler policy runs with the node ("sequence") axis
sharded over a mesh axis. Each device holds its own Q/K/V block; K/V blocks
rotate around the ring via `lax.ppermute` while every device folds each
incoming block into a numerically-stable online softmax (the flash-attention
accumulation), so the full N×N attention is computed with O(N/s) memory per
device and only neighbor-to-neighbor ICI traffic — no all-gather ever
materializes the full sequence.

`full_attention` is the single-device reference implementation with the same
masking semantics; `tests/test_parallel.py` asserts the ring path reproduces
it on a virtual mesh.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

# Finite "minus infinity" for masked scores: keeps exp()/max() NaN-free even
# for fully-masked blocks (exp(-1e30) underflows cleanly to 0.0). A plain
# Python float: materializing a jnp scalar at import time would initialize
# the XLA backend, breaking jax.distributed.initialize-before-first-device-op
# (parallel/multihost.py).
_NEG = -1e30


def _accumulate_block(q, k, v, kv_mask, o, m, l, scale):
    """Fold one K/V block into the online-softmax accumulators.

    q: (..., nq, d), k/v: (..., nk, d), kv_mask: broadcastable to
    (..., 1, nk) over the score tensor (..., nq, nk). Accumulators:
    o (..., nq, dv) unnormalized output, m (..., nq) running max,
    l (..., nq) running denominator.
    """
    s = jnp.einsum("...qd,...kd->...qk", q, k) * scale
    s = jnp.where(kv_mask[..., None, :], s, _NEG)
    m_new = jnp.maximum(m, s.max(axis=-1))
    p = jnp.exp(s - m_new[..., None])
    # A fully-masked block leaves m_new == _NEG and would give exp(0) == 1
    # per masked element; zero them explicitly.
    p = jnp.where(kv_mask[..., None, :], p, 0.0)
    alpha = jnp.exp(m - m_new)
    l_new = l * alpha + p.sum(axis=-1)
    o_new = o * alpha[..., None] + jnp.einsum("...qk,...kd->...qd", p, v)
    return o_new, m_new, l_new


def full_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    kv_mask: jnp.ndarray,
    scale: Optional[float] = None,
) -> jnp.ndarray:
    """Masked softmax(q k^T / sqrt(d)) v over the full (unsharded) axis.

    kv_mask marks valid keys, shape broadcastable to (..., 1, nk); queries
    with zero valid keys return 0 (no NaN).
    """
    if scale is None:
        scale = 1.0 / float(q.shape[-1]) ** 0.5
    s = jnp.einsum("...qd,...kd->...qk", q, k) * jnp.float32(scale)
    s = jnp.where(kv_mask[..., None, :], s, _NEG)
    m = s.max(axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    p = jnp.where(kv_mask[..., None, :], p, 0.0)
    l = p.sum(axis=-1, keepdims=True)
    out = jnp.einsum("...qk,...kd->...qd", p, v)
    return out / jnp.maximum(l, 1e-30)


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    kv_mask: jnp.ndarray,
    axis_name: str,
    scale: Optional[float] = None,
) -> jnp.ndarray:
    """Sequence-parallel attention; call INSIDE shard_map with the sequence
    axis sharded over `axis_name`.

    Per-device shards: q/k/v (..., n_shard, d), kv_mask broadcastable to
    (..., 1, n_shard). Every device computes its local queries' attention
    over ALL keys by rotating the K/V (+mask) shards around the ring once,
    folding each block with the online softmax. Equals `full_attention` on
    the gathered axis up to float32 reassociation (tests pin rtol 1e-5).
    """
    if scale is None:
        scale = 1.0 / float(q.shape[-1]) ** 0.5
    scale = jnp.float32(scale)
    size = jax.lax.psum(1, axis_name)  # static mesh-axis size
    perm = [(j, (j + 1) % size) for j in range(size)]

    # The accumulators are device-varying (each shard computes its own
    # queries' attention), but zeros/full literals trace as unvarying —
    # cast them to q's full varying-axis set (e.g. data AND seq on a 2D+
    # mesh) so the fori_loop carry types match the body's outputs.
    # jax.typeof is the new-API spelling; the installed 0.4.x line has
    # neither typeof nor varying-axis tracking (shard_map there uses
    # check_rep), so vma degrades to () and `varying` is the identity.
    _typeof = getattr(jax, "typeof", None)
    vma = tuple(getattr(_typeof(q), "vma", ())) if _typeof is not None else ()

    def varying(x):
        return jax.lax.pcast(x, vma, to="varying") if vma else x

    # Accumulator dtype must match what the body's arithmetic produces
    # (float64 when inputs are — the batched subsystem enables x64).
    dt = jnp.result_type(q.dtype, k.dtype, v.dtype, jnp.float32)
    o = varying(jnp.zeros(q.shape[:-1] + (v.shape[-1],), dt))
    m = varying(jnp.full(q.shape[:-1], _NEG, dt))
    l = varying(jnp.zeros(q.shape[:-1], dt))

    def body(_, carry):
        o, m, l, k, v, msk = carry
        o, m, l = _accumulate_block(q, k, v, msk, o, m, l, scale)
        k = jax.lax.ppermute(k, axis_name, perm)
        v = jax.lax.ppermute(v, axis_name, perm)
        msk = jax.lax.ppermute(msk, axis_name, perm)
        return (o, m, l, k, v, msk)

    o, m, l, _, _, _ = jax.lax.fori_loop(
        0, size, body, (o, m, l, k, v, kv_mask)
    )
    return o / jnp.maximum(l[..., None], 1e-30)
