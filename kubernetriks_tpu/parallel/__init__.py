"""Explicit model-parallelism primitives for the RL policy head.

The simulator itself needs only data parallelism (the cluster batch axis
shards with no collectives in the step — batched/engine.py). The policy
network is where TP/SP become real: parallel/ring.py provides ring attention
(sequence parallelism over the node axis, K/V blocks rotated over the mesh
via ppermute), and rl/attention_policy.py combines it with megatron-style
tensor parallelism of the FFN hidden dimension on a (data, seq, model) mesh.
"""

from kubernetriks_tpu.parallel.ring import full_attention, ring_attention

__all__ = ["full_attention", "ring_attention"]
