"""Shared orbax checkpoint helpers (SURVEY §5.4: checkpointing is absent in
the reference — a run is seed+config+trace — but every stateful object here
is a pytree of arrays, so persistence is one save/restore pair).

Hardened (chaos-era): saves are ATOMIC — the checkpoint is written to a
temporary sibling directory and renamed into place, so a crash mid-save can
never leave a torn checkpoint at the target path — and restores validate the
saved tree against the caller's template first, raising a ValueError that
names every mismatching leaf instead of surfacing an orbax stack trace.
"""

from __future__ import annotations

import json
import os
import shutil

import jax
import numpy as np

# Structure manifest sidecar (next to the checkpoint directory, not inside
# it — orbax owns the directory's contents); restore validates against it
# before touching orbax.
def _manifest_path(path: str) -> str:
    return path + ".structure.json"


def _manifest_entries(payload) -> dict:
    """keystr -> [shape, dtype] for every array leaf of the payload."""
    flat, _ = jax.tree_util.tree_flatten_with_path(payload)
    return {
        jax.tree_util.keystr(path): [
            list(np.shape(leaf)),
            str(np.asarray(leaf).dtype if not hasattr(leaf, "dtype") else leaf.dtype),
        ]
        for path, leaf in flat
    }


def ckpt_save(path: str, payload) -> None:
    """Save a pytree of arrays to an orbax checkpoint directory
    (overwrites). Atomic: writes to a temp dir on the same filesystem, then
    renames over the target — no torn checkpoints on crash."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    # FIXED suffixes (not pid-tagged): a crash mid-swap must leave the aside
    # at a path a LATER process can find (ckpt_restore falls back to it),
    # and stale temp/aside dirs from crashed runs get cleaned on the next
    # save instead of accumulating.
    tmp = f"{path}.tmp"
    if os.path.exists(tmp):
        shutil.rmtree(tmp)
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(tmp, payload, force=True)
    ckptr.wait_until_finished()
    manifest_tmp = _manifest_path(tmp)
    with open(manifest_tmp, "w") as fh:
        json.dump(_manifest_entries(payload), fh)
    # Never destroy the only complete checkpoint: move the previous save
    # ASIDE (rename, not delete), swing the new one into place, then clean
    # up. A crash at any point leaves a complete checkpoint at `path` or at
    # the .old aside — never a torn or missing one. (The manifest swap is
    # a separate step; a crash between it and the dir swap can only cause a
    # LOUD validation mismatch on restore, never silent acceptance.)
    old = f"{path}.old"
    if os.path.exists(old):
        shutil.rmtree(old)
    if os.path.exists(path):
        os.rename(path, old)
    os.rename(tmp, path)
    os.replace(manifest_tmp, _manifest_path(path))
    if os.path.exists(old):
        shutil.rmtree(old)


def ckpt_restore(path: str, template):
    """Restore a pytree saved by ckpt_save; `template` (a live pytree of the
    same structure) provides the shapes/dtypes. Raises ValueError naming the
    mismatching leaves when the checkpoint's structure/shapes/dtypes don't
    match the template (instead of an orbax stack trace)."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    manifest_path = _manifest_path(path)
    if not os.path.isdir(path):
        # A save that crashed between moving the previous checkpoint aside
        # and swinging the new one into place leaves the only complete
        # checkpoint at the .old aside — recover it. Its manifest is still
        # the one at the MAIN manifest path (the manifest swap comes last).
        aside = f"{path}.old"
        if not os.path.isdir(aside):
            raise ValueError(f"no checkpoint directory at {path!r}")
        path = aside
    if os.path.exists(manifest_path):
        with open(manifest_path) as fh:
            saved = json.load(fh)
        expected = _manifest_entries(template)
        problems = []
        for key, spec in expected.items():
            got = saved.get(key)
            if got is None:
                problems.append(f"missing in checkpoint: {key} {spec}")
            elif got != spec:
                problems.append(
                    f"mismatch at {key}: checkpoint has shape={got[0]} "
                    f"dtype={got[1]}, template expects shape={spec[0]} "
                    f"dtype={spec[1]}"
                )
        for key in saved:
            if key not in expected:
                problems.append(f"unexpected leaf in checkpoint: {key}")
        if problems:
            raise ValueError(
                f"checkpoint at {path!r} does not match the expected state "
                "structure (was it saved from a different config/trace or "
                "an older state layout?):\n  " + "\n  ".join(problems)
            )
    ckptr = ocp.StandardCheckpointer()
    abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, template)
    try:
        return ckptr.restore(path, abstract)
    except Exception as exc:  # orbax raises various internal types
        raise ValueError(
            f"failed to restore checkpoint at {path!r}: structure/shape/"
            f"dtype mismatch against the live template ({exc})"
        ) from exc
