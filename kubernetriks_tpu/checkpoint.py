"""Shared orbax checkpoint helpers (SURVEY §5.4: checkpointing is absent in
the reference — a run is seed+config+trace — but every stateful object here
is a pytree of arrays, so persistence is one save/restore pair)."""

from __future__ import annotations

import os

import jax


def ckpt_save(path: str, payload) -> None:
    """Save a pytree of arrays to an orbax checkpoint directory (overwrites)."""
    import orbax.checkpoint as ocp

    ckptr = ocp.StandardCheckpointer()
    ckptr.save(os.path.abspath(path), payload, force=True)
    ckptr.wait_until_finished()


def ckpt_restore(path: str, template):
    """Restore a pytree saved by ckpt_save; `template` (a live pytree of the
    same structure) provides the shapes/dtypes."""
    import orbax.checkpoint as ocp

    ckptr = ocp.StandardCheckpointer()
    abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, template)
    return ckptr.restore(os.path.abspath(path), abstract)
