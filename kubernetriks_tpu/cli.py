"""CLI entry point (reference: src/main.rs).

Usage: python -m kubernetriks_tpu.cli --config-file <yaml> [--gauge-csv <path>]

Loads the config, selects the trace source (alibaba XOR generic, asserted like
the reference at main.rs:62-65), builds the simulation, runs until all pods
finish, and prints metrics.
"""

from __future__ import annotations

import argparse
import logging
import os
import sys

from kubernetriks_tpu.config import SimulationConfig
from kubernetriks_tpu.metrics.printer import print_metrics
from kubernetriks_tpu.sim.callbacks import RunUntilAllPodsAreFinishedCallbacks
from kubernetriks_tpu.sim.simulator import KubernetriksSimulation
from kubernetriks_tpu.trace.interface import EmptyTrace


def setup_logging(config: SimulationConfig) -> None:
    """Level from KUBERNETRIKS_LOG (RUST_LOG equivalent), optional file sink
    (reference: main.rs:33-50)."""
    level = os.environ.get("KUBERNETRIKS_LOG", "INFO").upper()
    handlers = [logging.StreamHandler()]
    if config.logs_filepath:
        os.makedirs(os.path.dirname(config.logs_filepath) or ".", exist_ok=True)
        handlers.append(logging.FileHandler(config.logs_filepath))
    logging.basicConfig(
        level=getattr(logging, level, logging.INFO),
        format="%(asctime)s %(levelname)s %(name)s: %(message)s",
        handlers=handlers,
        force=True,
    )


def build_traces(config: SimulationConfig):
    trace_config = config.trace_config
    if trace_config is None:
        return EmptyTrace(), EmptyTrace()
    alibaba = trace_config.alibaba_cluster_trace_v2017
    generic = trace_config.generic_trace
    assert (alibaba is None) != (generic is None), (
        "Exactly one of alibaba_cluster_trace_v2017 or generic_trace must be set"
    )
    if generic is not None:
        from kubernetriks_tpu.trace.generic import (
            GenericClusterTrace,
            GenericWorkloadTrace,
        )

        return (
            GenericClusterTrace.from_file(generic.cluster_trace_path),
            GenericWorkloadTrace.from_file(generic.workload_trace_path),
        )
    from kubernetriks_tpu.trace import feeder

    if feeder.native_available():
        cluster_cls = feeder.NativeAlibabaClusterTrace
        workload_cls = feeder.NativeAlibabaWorkloadTrace
    else:
        logging.getLogger(__name__).info(
            "native trace feeder unavailable (%s); using the Python parser",
            feeder.native_build_error(),
        )
        from kubernetriks_tpu.trace.alibaba import (
            AlibabaClusterTraceV2017,
            AlibabaWorkloadTraceV2017,
        )

        cluster_cls = AlibabaClusterTraceV2017
        workload_cls = AlibabaWorkloadTraceV2017

    cluster = (
        cluster_cls.from_file(alibaba.machine_events_trace_path)
        if alibaba.machine_events_trace_path
        else EmptyTrace()
    )
    workload = workload_cls.from_files(
        alibaba.batch_instance_trace_path, alibaba.batch_task_trace_path
    )
    return cluster, workload


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description="kubernetriks-tpu simulator")
    parser.add_argument("--config-file", required=True, help="Path to YAML config")
    parser.add_argument(
        "--gauge-csv",
        default=None,
        help="Path for the 5s gauge-metrics CSV (off by default)",
    )
    args = parser.parse_args(argv)

    config = SimulationConfig.from_file(args.config_file)
    setup_logging(config)

    cluster_trace, workload_trace = build_traces(config)
    sim = KubernetriksSimulation(config, gauge_csv_path=args.gauge_csv)
    sim.initialize(cluster_trace, workload_trace)
    sim.run_with_callbacks(RunUntilAllPodsAreFinishedCallbacks())
    if config.metrics_printer is None:
        print_metrics(sim.metrics_collector, None)
    sim.metrics_collector.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
