"""CLI entry point (reference: src/main.rs).

Usage: python -m kubernetriks_tpu.cli --config-file <yaml>
           [--backend scalar|batched] [--clusters N] [--gauge-csv <path>]

Loads the config, selects the trace source (alibaba XOR generic, asserted like
the reference at main.rs:62-65), builds the simulation, runs until all pods
finish, and prints metrics.

--backend batched runs the vectorized JAX path: N identical clusters stepped
in lockstep on the accelerator. Alibaba traces with the native C++ feeder
available go CSV -> dense arrays -> compile_from_arrays without ever
materializing per-event Python objects (the object-free fast path).
"""

from __future__ import annotations

import argparse
import logging
import os
import sys

from kubernetriks_tpu.config import SimulationConfig
from kubernetriks_tpu.metrics.printer import print_metrics
from kubernetriks_tpu.sim.callbacks import RunUntilAllPodsAreFinishedCallbacks
from kubernetriks_tpu.sim.simulator import KubernetriksSimulation
from kubernetriks_tpu.trace.interface import EmptyTrace


def setup_logging(config: SimulationConfig) -> None:
    """Level from KUBERNETRIKS_LOG (RUST_LOG equivalent), optional rotating
    file sink — 50 files x 100 MiB like the reference's FileRotate
    (reference: main.rs:33-50)."""
    from logging.handlers import RotatingFileHandler

    from kubernetriks_tpu.flags import flag_str

    level = (flag_str("KUBERNETRIKS_LOG") or "INFO").upper()
    if config.logs_filepath:
        # The reference logs EXCLUSIVELY to the rotating file when a path is
        # configured (main.rs:40-47) — no console duplicate.
        os.makedirs(os.path.dirname(config.logs_filepath) or ".", exist_ok=True)
        handlers = [
            RotatingFileHandler(
                config.logs_filepath,
                maxBytes=100 * 1024 * 1024,
                backupCount=50,
            )
        ]
    else:
        handlers = [logging.StreamHandler()]
    logging.basicConfig(
        level=getattr(logging, level, logging.INFO),
        format="%(asctime)s %(levelname)s %(name)s: %(message)s",
        handlers=handlers,
        force=True,
    )


def build_traces(config: SimulationConfig):
    trace_config = config.trace_config
    if trace_config is None:
        return EmptyTrace(), EmptyTrace()
    alibaba = trace_config.alibaba_cluster_trace_v2017
    generic = trace_config.generic_trace
    assert (alibaba is None) != (generic is None), (
        "Exactly one of alibaba_cluster_trace_v2017 or generic_trace must be set"
    )
    if generic is not None:
        from kubernetriks_tpu.trace.generic import (
            GenericClusterTrace,
            GenericWorkloadTrace,
        )

        return (
            GenericClusterTrace.from_file(generic.cluster_trace_path),
            GenericWorkloadTrace.from_file(generic.workload_trace_path),
        )
    from kubernetriks_tpu.trace import feeder

    if feeder.native_available():
        cluster_cls = feeder.NativeAlibabaClusterTrace
        workload_cls = feeder.NativeAlibabaWorkloadTrace
    else:
        logging.getLogger(__name__).info(
            "native trace feeder unavailable (%s); using the Python parser",
            feeder.native_build_error(),
        )
        from kubernetriks_tpu.trace.alibaba import (
            AlibabaClusterTraceV2017,
            AlibabaWorkloadTraceV2017,
        )

        cluster_cls = AlibabaClusterTraceV2017
        workload_cls = AlibabaWorkloadTraceV2017

    cluster = (
        cluster_cls.from_file(alibaba.machine_events_trace_path)
        if alibaba.machine_events_trace_path
        else EmptyTrace()
    )
    workload = workload_cls.from_files(
        alibaba.batch_instance_trace_path, alibaba.batch_task_trace_path
    )
    return cluster, workload


def build_batched_simulation(
    config: SimulationConfig,
    n_clusters: int,
    max_pods_per_cycle: int = 0,
    pod_window: int = 0,
    **engine_kwargs,
):
    """Build a BatchedSimulation from the config's trace source.

    Alibaba + native feeder: CSVs parse natively into dense arrays and
    compile via compile_from_arrays — no per-event Python objects on the
    multi-million-row pod axis. Otherwise: the object-based trace path.
    engine_kwargs pass through to the BatchedSimulation constructor
    (e.g. ca_slot_multiplier, use_pallas, mesh).
    """
    from kubernetriks_tpu.batched.engine import (
        BatchedSimulation,
        build_batched_from_traces,
    )
    from kubernetriks_tpu.batched.trace_compile import compile_from_arrays
    from kubernetriks_tpu.trace import feeder

    # 0 = auto: bound each scheduling cycle's work at 256 pods (the scalar
    # path drains the queue unboundedly, reference scheduler.rs:261; the
    # batched path defers overflow to the next cycle — SURVEY §7 "bounded
    # lax.scan microcycles"). Exact-drain runs pass the pod count explicitly.
    # The bound applies identically on every trace/build path so a config
    # simulates the same regardless of native-feeder availability (the engine
    # clamps the slice to the pod-slot count when it is smaller).
    kwargs = {"max_pods_per_cycle": max_pods_per_cycle or 256}
    if pod_window:
        kwargs["pod_window"] = pod_window
    kwargs.update(engine_kwargs)

    trace_config = config.trace_config
    alibaba = trace_config.alibaba_cluster_trace_v2017 if trace_config else None
    if alibaba is not None and feeder.native_available():
        from kubernetriks_tpu.chaos import has_node_faults

        if has_node_faults(config.fault_injection):
            # Node crash/recover events are injected at trace compile time
            # (chaos.inject_node_faults); the native array fast path skips
            # that stage. Pod-level faults (engine-side draws) still work.
            raise ValueError(
                "node-level fault injection is not supported on the "
                "alibaba native-feeder path — use the generic trace path "
                "or set fault_injection.node.mttf to 0 (pod-level faults "
                "are unaffected)"
            )
        workload_arrays = feeder.load_workload_arrays(
            alibaba.batch_instance_trace_path, alibaba.batch_task_trace_path
        )
        cluster_arrays = (
            feeder.load_cluster_arrays(alibaba.machine_events_trace_path)
            if alibaba.machine_events_trace_path
            else None
        )
        compiled = compile_from_arrays(cluster_arrays, workload_arrays, config)
        return BatchedSimulation(config, [compiled] * n_clusters, **kwargs)
    cluster_trace, workload_trace = build_traces(config)
    return build_batched_from_traces(
        config,
        cluster_trace.convert_to_simulator_events(),
        workload_trace.convert_to_simulator_events(),
        n_clusters=n_clusters,
        **kwargs,
    )


def run_batched(config: SimulationConfig, args) -> int:
    import time

    sim = build_batched_simulation(
        config, args.clusters, args.max_pods_per_cycle, args.pod_window
    )
    logging.getLogger(__name__).info(
        "batched run: %d clusters x %d node slots x %d pod slots (pallas=%s)",
        sim.n_clusters, sim.n_nodes, sim.n_pods, sim.use_pallas,
    )
    if args.metrics_export:
        # Capacity-observatory time-series export: every telemetry-ring
        # drain appends a JSONL record (occupancy gauges, memory
        # watermarks, watchdog verdicts); the final report lands as a
        # Prometheus textfile next to it. Requires the flight recorder
        # (KTPU_TRACE=1) — attach_metrics_exporter raises otherwise.
        from kubernetriks_tpu.telemetry.export import JsonlExporter

        sim.attach_metrics_exporter(JsonlExporter(args.metrics_export + ".jsonl"))
    sim.collect_gauges = bool(args.gauge_csv)
    t0 = time.perf_counter()
    sim.run_to_completion()
    elapsed = time.perf_counter() - t0
    if args.gauge_csv:
        sim.write_gauge_csv(args.gauge_csv)
    summary = sim.metrics_summary()
    decisions = summary["counters"]["scheduling_decisions"]
    logging.getLogger(__name__).info(
        "Processed %d scheduling decisions in %.2fs (%.0f decisions/s)",
        decisions, elapsed, decisions / max(elapsed, 1e-9),
    )
    from kubernetriks_tpu.metrics.render import render_metrics, render_telemetry

    print(render_metrics(summary, args.report or "json"))
    if sim._telemetry:
        # Flight recorder was armed (KTPU_TRACE=1): emit the telemetry
        # report in the same format and write the Perfetto trace. ONE
        # report serves both the render and the Prometheus textfile (a
        # second call would only force a redundant drain).
        telemetry_rep = sim.telemetry_report()
        print(render_telemetry(telemetry_rep, args.report or "json"))
        from kubernetriks_tpu.flags import flag_str

        trace_path = (flag_str("KTPU_TRACE_PATH") or "ktpu_trace") + ".json"
        sim.write_chrome_trace(trace_path)
        logging.getLogger(__name__).info(
            "wrote Chrome trace (Perfetto-loadable) to %s", trace_path
        )
        if args.metrics_export:
            from kubernetriks_tpu.telemetry.export import (
                write_prometheus_textfile,
            )

            prom = write_prometheus_textfile(
                args.metrics_export + ".prom", telemetry_rep
            )
            logging.getLogger(__name__).info(
                "wrote observatory metrics to %s.jsonl and %s",
                args.metrics_export, prom,
            )
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description="kubernetriks-tpu simulator")
    parser.add_argument("--config-file", required=True, help="Path to YAML config")
    parser.add_argument(
        "--backend",
        choices=("scalar", "batched"),
        default="scalar",
        help="scalar event-loop oracle or the vectorized JAX path",
    )
    parser.add_argument(
        "--clusters",
        type=int,
        default=1,
        help="batched backend: number of identical clusters to step in lockstep",
    )
    parser.add_argument(
        "--max-pods-per-cycle",
        type=int,
        default=0,
        help="batched backend: per-cycle scheduling work bound (0 = auto)",
    )
    parser.add_argument(
        "--pod-window",
        type=int,
        default=0,
        help="batched backend: sliding pod-slot window size (0 = whole trace "
        "resident; set to ~2x peak pod concurrency to stream long traces)",
    )
    parser.add_argument(
        "--profile",
        default=None,
        help="Scheduler profile: a named profile (default, best_fit, "
        "balanced_packing) overriding the config's scheduler_profile "
        "block. Both backends honor it; the batched backend compiles it "
        "into the scan/Pallas decision kernels and fails loudly on a "
        "profile it cannot lower.",
    )
    parser.add_argument(
        "--gauge-csv",
        default=None,
        help="Path for the 5s gauge-metrics CSV (off by default)",
    )
    parser.add_argument(
        "--metrics-export",
        default=None,
        help="batched backend: capacity-observatory export stem — drain "
        "records append to <stem>.jsonl (bounded rotation) and the final "
        "telemetry report is written as <stem>.prom (Prometheus "
        "textfile). Requires the flight recorder (KTPU_TRACE=1).",
    )
    parser.add_argument(
        "--report",
        choices=("json", "table"),
        default=None,
        help="End-of-run report format for BOTH backends (one rendering "
        "path, metrics/render.py). Default: the legacy behavior — JSON, "
        "or the config's metrics_printer format on the scalar backend.",
    )
    args = parser.parse_args(argv)

    config = SimulationConfig.from_file(args.config_file)
    setup_logging(config)
    if args.profile is not None:
        # --profile supersedes the config's scheduler_profile block for
        # BOTH backends (the scalar simulator parses it through the same
        # spec parser; the batched engine compiles it).
        import dataclasses

        config = dataclasses.replace(config, scheduler_profile=args.profile)
    if args.report is not None:
        # --report supersedes the config's metrics_printer block; nulling
        # it here keeps the run-loop callbacks from ALSO printing the
        # configured report (one report, in the CLI-chosen format).
        import dataclasses

        config = dataclasses.replace(config, metrics_printer=None)

    if args.backend == "batched":
        return run_batched(config, args)

    cluster_trace, workload_trace = build_traces(config)
    sim = KubernetriksSimulation(config, gauge_csv_path=args.gauge_csv)
    sim.initialize(cluster_trace, workload_trace)
    sim.run_with_callbacks(RunUntilAllPodsAreFinishedCallbacks())
    if args.report is not None:
        # Explicit format: render through the shared path regardless of
        # the config's metrics_printer block (batched runs honor the same
        # flag, so both backends emit the same schema both ways).
        from kubernetriks_tpu.metrics.printer import metrics_as_dict
        from kubernetriks_tpu.metrics.render import render_metrics

        print(render_metrics(metrics_as_dict(sim.metrics_collector), args.report))
    elif config.metrics_printer is None:
        print_metrics(sim.metrics_collector, None)
    sim.metrics_collector.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
