"""kubernetriks-tpu: a TPU-native, batched re-implementation of the Kubernetriks
Kubernetes-cluster simulator (reference: jellythefish/kubernetriks).

Two execution paths share one semantic model:

- ``kubernetriks_tpu.sim`` + ``kubernetriks_tpu.core``: a scalar, single-cluster,
  deterministic discrete-event path that preserves the reference's exact
  event-ordering semantics (reference: src/simulator.rs, src/core/*).
- ``kubernetriks_tpu.batched``: a vectorized JAX path where cluster state lives in
  dense arrays of shape (clusters, nodes, ...) / (clusters, pods, ...) and thousands
  of simulated clusters step in lockstep on a TPU mesh.
"""

__version__ = "0.1.0"
