"""Donation-safety pass: no read-after-donate.

A call to a `donate_argnums` jit entry consumes the buffers passed at the
donated positions — on TPU they are reused for the outputs, and any later
read of the donated variable observes garbage (or raises). On CPU donation
is a no-op, so the bug class silently passes CI. This pass flags, within a
function body, any read of a variable (dotted path: `state`, `self.state`)
passed positionally at a donated index of a known donated entry AFTER the
call, unless the variable was rebound (typically from the call's own
result) first.

The donated-entry table comes from scanning `jax.jit` / `partial(jax.jit,
...)` sites package-wide (lint.build_context), not from a hardcoded list.
Local aliases are tracked (`fn = run_windows_donated if donate else
run_windows` makes `fn(...)` a possibly-donating call).

Analysis is a linear abstract interpretation over statement lists: branch
arms are analyzed with the same entry state and their poison sets union at
the join (conservative: rebinding on only one arm keeps the variable
poisoned); loop bodies run twice so a read before the donating call is
caught on the simulated second iteration when the rebind is missing.

Waive with `# ktpu: donation-ok(<reason>)` on the read's line.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from kubernetriks_tpu.lint import (
    LintContext,
    SourceFile,
    Violation,
    dotted_name,
    local_entry_aliases,
)

PASS_ID = "donation"


class _FunctionChecker:
    def __init__(
        self,
        sf: SourceFile,
        ctx: LintContext,
        fn: ast.FunctionDef,
        violations: List[Violation],
    ):
        self.sf = sf
        self.ctx = ctx
        self.fn = fn
        self.violations = violations
        self.aliases = local_entry_aliases(fn, ctx.donated)
        # poisoned dotted path -> (donating entry name, call line)
        self.poisoned: Dict[str, tuple] = {}

    # -- helpers --------------------------------------------------------------

    def _donated_positions(self, call: ast.Call) -> Optional[tuple]:
        name = dotted_name(call.func)
        if name is None:
            return None
        bare = name.rsplit(".", 1)[-1]
        if bare in self.ctx.donated:
            return self.ctx.donated[bare]
        if bare in self.aliases:
            # union of donated positions across possible targets
            pos: Set[int] = set()
            for entry in self.aliases[bare]:
                pos.update(self.ctx.donated[entry])
            return tuple(sorted(pos))
        return None

    def _check_reads(self, node: ast.AST) -> None:
        """Flag loads of poisoned paths anywhere in an expression tree
        (outermost chain node only: `state.time` is one read, not two)."""
        inner = set()
        for sub in ast.walk(node):
            if isinstance(sub, ast.Attribute):
                v = sub.value
                while isinstance(v, ast.Attribute):
                    inner.add(id(v))
                    v = v.value
                if isinstance(v, ast.Name):
                    inner.add(id(v))
        for sub in ast.walk(node):
            if not isinstance(sub, (ast.Name, ast.Attribute)):
                continue
            if id(sub) in inner:
                continue
            if not isinstance(getattr(sub, "ctx", None), ast.Load):
                continue
            path = dotted_name(sub)
            if path is None:
                continue
            for poisoned, (entry, call_line) in self.poisoned.items():
                if path == poisoned or path.startswith(poisoned + "."):
                    line = sub.lineno
                    if not self.sf.waived(line, PASS_ID):
                        self.violations.append(
                            Violation(
                                self.sf.path,
                                line,
                                PASS_ID,
                                f"read of {path!r} after it was donated to "
                                f"{entry}() on line {call_line}; rebind it "
                                "from the call's result (or waive: "
                                "# ktpu: donation-ok(reason))",
                            )
                        )
                    break

    def _poison_calls(self, node: ast.AST) -> None:
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            positions = self._donated_positions(sub)
            if not positions:
                continue
            name = dotted_name(sub.func)
            bare = name.rsplit(".", 1)[-1] if name else "<call>"
            for idx in positions:
                if idx < len(sub.args):
                    path = dotted_name(sub.args[idx])
                    if path is not None:
                        self.poisoned[path] = (bare, sub.lineno)

    def _unpoison_targets(self, targets) -> None:
        for tgt in targets:
            if isinstance(tgt, (ast.Tuple, ast.List)):
                self._unpoison_targets(tgt.elts)
                continue
            path = dotted_name(tgt)
            if path is None:
                continue
            for poisoned in list(self.poisoned):
                if poisoned == path or poisoned.startswith(path + "."):
                    del self.poisoned[poisoned]

    # -- statement walk -------------------------------------------------------

    def run(self) -> None:
        self.visit_stmts(self.fn.body)

    def visit_stmts(self, stmts) -> None:
        for st in stmts:
            self.visit_stmt(st)

    def _expr_parts(self, st: ast.stmt):
        """Expression children of a statement, EXCLUDING nested bodies."""
        for fld, value in ast.iter_fields(st):
            if fld in ("body", "orelse", "finalbody", "handlers"):
                continue
            if isinstance(value, ast.expr):
                yield value
            elif isinstance(value, list):
                for v in value:
                    if isinstance(v, ast.expr):
                        yield v

    def _simple(self, st: ast.stmt) -> None:
        """Read-check, then poison donating calls, then apply rebinds —
        in that order, so `state = f(state)` is clean (the arg read happens
        at the donation itself, and the target rebind lifts the poison)."""
        for part in self._expr_parts(st):
            self._check_reads(part)
            self._poison_calls(part)
        if isinstance(st, ast.Assign):
            self._unpoison_targets(st.targets)
        elif isinstance(st, (ast.AugAssign, ast.AnnAssign)) and st.target:
            self._unpoison_targets([st.target])
        elif isinstance(st, ast.Delete):
            self._unpoison_targets(st.targets)

    def visit_stmt(self, st: ast.stmt) -> None:
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            return  # nested scopes are analyzed as their own functions
        if isinstance(st, ast.If):
            self._check_reads(st.test)
            self._poison_calls(st.test)
            self._branch([st.body, st.orelse])
            return
        if isinstance(st, (ast.For, ast.AsyncFor)):
            self._check_reads(st.iter)
            self._poison_calls(st.iter)
            self._loop(st.body)
            self.visit_stmts(st.orelse)
            return
        if isinstance(st, ast.While):
            self._check_reads(st.test)
            self._poison_calls(st.test)
            self._loop(st.body, extra_exprs=[st.test])
            self.visit_stmts(st.orelse)
            return
        if isinstance(st, (ast.With, ast.AsyncWith)):
            for item in st.items:
                self._check_reads(item.context_expr)
                self._poison_calls(item.context_expr)
                if item.optional_vars is not None:
                    self._unpoison_targets([item.optional_vars])
            self.visit_stmts(st.body)
            return
        if isinstance(st, ast.Try):
            self.visit_stmts(st.body)
            for handler in st.handlers:
                self.visit_stmts(handler.body)
            self.visit_stmts(st.orelse)
            self.visit_stmts(st.finalbody)
            return
        self._simple(st)

    def _branch(self, arms) -> None:
        entry = dict(self.poisoned)
        merged: Dict[str, tuple] = {}
        for arm in arms:
            self.poisoned = dict(entry)
            self.visit_stmts(arm)
            merged.update(self.poisoned)
        self.poisoned = merged

    def _loop(self, body, extra_exprs=()) -> None:
        # Two iterations: the second catches loop-carried reads of a
        # variable donated (and not rebound) on the first.
        for _ in range(2):
            self.visit_stmts(body)
            for e in extra_exprs:
                self._check_reads(e)
                self._poison_calls(e)


def check(ctx: LintContext) -> List[Violation]:
    violations: List[Violation] = []
    for sf in ctx.files:
        for node in ast.walk(sf.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                _FunctionChecker(sf, ctx, node, violations).run()
    return violations
