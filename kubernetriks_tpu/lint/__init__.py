"""ktpu-lint: framework-invariant static analysis for kubernetriks-tpu.

The framework's correctness rests on invariants no general-purpose tool
checks; this package turns them into machine-checked AST passes
(`python -m kubernetriks_tpu.lint`):

1. donation  — no read of a variable after it was passed at a donated
   position of a `donate_argnums` jit entry, unless rebound first. The bug
   class silently PASSES on CPU CI (donation is a no-op there) and corrupts
   state only on TPU. The donated-entry table is built by scanning
   `jax.jit` / `partial(jax.jit, ...)` sites, not hardcoded.
2. hostsync  — hot-path modules must not grow implicit host syncs:
   `.item()`, `int()`/`float()`/`bool()` on array-valued expressions,
   `np.asarray` / `jax.device_get` / `to_host` / `block_until_ready`, and
   Python branches on traced values. Every legitimate sync carries a
   `# ktpu: sync-ok(<reason>)` waiver, making the sync budget greppable.
3. jitstatic — every `static_argnames` entry names a parameter of the
   wrapped function, and paired donated/undonated entries declare identical
   static sets (drift makes a kwarg traced in one variant only).
4. prng      — simulation-path modules draw no ad-hoc randomness
   (`jax.random.*`, `np.random.*`, stdlib `random`): all draws route
   through the counter-based threefry keying in `chaos.py`, or
   scalar/batched bit-identity breaks.
5. envflags  — every `os.environ` / `os.getenv` read of a KTPU_* /
   KUBERNETRIKS_* name resolves against the central registry
   (`kubernetriks_tpu/flags.py`) and happens inside it.

The contract-prover passes (v2) turn the batched rebuild's CROSS-MODULE
contracts — enforced until now only by whichever test happened to
exercise them — into commit-time checks:

6. stateleaf     — every leaf of the state NamedTuples
   (`ClusterBatchState` / `AutoscaleState` / `TelemetryRing`) is provably
   handled in each registered consumer (fleet lane reset, checkpoint
   meta, compare_states, strip_telemetry, sanitize's donated sweep, the
   DESIGN §12 allocation-index list), by name or by a pytree-generic
   traversal; a new leaf that misses any registry is an error naming the
   leaf and the registry (the PR 14 "reclaim counters must ride the
   pytree" lesson, machine-checked).
7. scenariotrace — per-lane scenario leaves (`fleet.scenario_leaves`'s
   composition targets, `StepConstants.fault_seed`) never flow into
   Python control flow, `int()`/`.item()` casts, jit statics or shape
   expressions: the fleet's compile-once guarantee, statically.
8. shapecontract — per-cluster `(C,)` leaves carry declared axis
   signatures; mixing one with a `(C,G)`/`(C,P)`/node-layout expression
   without an explicit `[:, None]` / transpose / broadcast is flagged
   (the PR 13 `tolerance` broadcast bug class, lane-major aware).
9. feederlock    — in threaded modules (`batched/stream.py`, or a
   `# ktpu: threaded` pragma), attributes mutated off-thread are only
   touched under the ring lock/condvar (or sit in an explicit
   `_LOCK_FREE` handoff list), and blocking waits are forbidden while
   holding the lock.

Waiver syntax (same line as the violation, or on the `def` line to waive a
whole function for hostsync): `# ktpu: <tag>-ok(<reason>)` with a
non-empty reason, e.g. `# ktpu: sync-ok(async 4-byte shift readback)`.
Tags: donation, sync, static, prng, flag, leaf, scenario, shape, lock.
A waiver that no longer suppresses anything is itself reported stale
(`--strict-waivers` promotes that to an error) — the waiver inventory
can only shrink with the violations it excuses.
File pragmas: `# ktpu: hot-path` opts a module into the hostsync pass,
`# ktpu: sim-path` into the prng/scenariotrace/shapecontract passes,
`# ktpu: threaded` into the feederlock pass, and `# ktpu: state-module`
marks a self-contained state-leaf fixture (classes + consumers in one
file). The built-in module lists cover the real package; pragmas serve
the self-test fixtures and future modules.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

PASS_IDS = (
    "donation",
    "hostsync",
    "jitstatic",
    "prng",
    "envflags",
    "stateleaf",
    "scenariotrace",
    "shapecontract",
    "feederlock",
)

# pass id -> waiver tag (`# ktpu: <tag>-ok(reason)`); the reverse map
# drives stale-waiver detection.
WAIVER_TAGS: Dict[str, str] = {
    "donation": "donation",
    "hostsync": "sync",
    "jitstatic": "static",
    "prng": "prng",
    "envflags": "flag",
    "stateleaf": "leaf",
    "scenariotrace": "scenario",
    "shapecontract": "shape",
    "feederlock": "lock",
}
TAG_TO_PASS: Dict[str, str] = {tag: pid for pid, tag in WAIVER_TAGS.items()}

# Modules whose steady-state dispatch regions are hot: a stray host sync
# here undoes the dispatch-overhaul work (ROADMAP item 1 — the composed
# flagship is host-dispatch bound). Relative to the repo root.
HOT_MODULES = (
    "kubernetriks_tpu/batched/step.py",
    "kubernetriks_tpu/batched/engine.py",
    "kubernetriks_tpu/batched/autoscale.py",
    "kubernetriks_tpu/ops/",
)

# Modules on the simulation path, where every random draw must route
# through chaos.py's counter-based threefry keying (scalar/batched
# bit-identity). chaos.py itself is the key constructor and is exempt.
SIM_MODULES = (
    "kubernetriks_tpu/batched/",
    "kubernetriks_tpu/ops/",
    "kubernetriks_tpu/sim/",
    "kubernetriks_tpu/core/",
    "kubernetriks_tpu/autoscalers/",
)

# Self-test fixtures hold seeded violations on purpose; the default scope
# must stay golden-clean without them.
DEFAULT_EXCLUDE = ("tests/lint_fixtures/",)

# Reason is greedy to the LAST ')' on the line, so reasons containing
# parentheses ("(4,)-i32 readback") survive intact; convention is one
# waiver per line.
_WAIVER_RE = re.compile(r"#\s*ktpu:\s*([a-z]+)-ok\((.*)\)")
_PRAGMA_RE = re.compile(
    r"#\s*ktpu:\s*(hot-path|sim-path|threaded|state-module)\b"
)


@dataclass(frozen=True)
class Violation:
    path: str
    line: int
    pass_id: str
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.pass_id}] {self.message}"

    def as_json(self) -> Dict[str, object]:
        return {
            "file": self.path,
            "line": self.line,
            "pass": self.pass_id,
            "message": self.message,
        }


@dataclass(frozen=True)
class StaleWaiver:
    """A `# ktpu: <tag>-ok(reason)` whose line/def no longer triggers its
    pass — dead weight that silently re-licenses a future violation."""

    path: str
    line: int
    tag: str
    reason: str
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: [stale-waiver] {self.message}"

    def as_json(self) -> Dict[str, object]:
        return {
            "file": self.path,
            "line": self.line,
            "pass": "stale-waiver",
            "waiver": f"{self.tag}-ok({self.reason})",
            "message": self.message,
        }


@dataclass
class JitEntry:
    """One jax.jit wrapping site found in the package."""

    name: str  # bound name (decorated def or assignment target)
    path: str
    line: int
    static_argnames: Optional[Tuple[str, ...]]  # None = unresolvable
    static_resolved: bool
    donate_argnums: Tuple[int, ...]
    params: Optional[Tuple[str, ...]]  # wrapped function params, if resolved
    has_varkw: bool = False


@dataclass
class SourceFile:
    path: str  # repo-relative, forward slashes
    abspath: str
    text: str
    lines: List[str]
    tree: ast.AST
    waivers: Dict[int, List[Tuple[str, str]]]  # line -> [(pass tag, reason)]
    pragmas: frozenset
    # (line, tag) pairs that actually suppressed a violation this run —
    # the live half of the waiver inventory; declared-minus-used is the
    # stale set (find_stale_waivers).
    used_waivers: set = field(default_factory=set)

    def has_waiver(self, line: int, pass_id: str) -> bool:
        """Non-recording query: is there a waiver for pass_id on `line`?"""
        tag = WAIVER_TAGS.get(pass_id, pass_id)
        return any(t == tag and r.strip() for t, r in self.waivers.get(line, []))

    def waived(self, line: int, pass_id: str) -> bool:
        """Recording query: like has_waiver, but a True result marks the
        waiver USED (it suppressed a real violation). Passes must call
        this exactly when they are about to flag."""
        tag = WAIVER_TAGS.get(pass_id, pass_id)
        if self.has_waiver(line, pass_id):
            self.used_waivers.add((line, tag))
            return True
        return False


@dataclass
class LintContext:
    """Package-wide tables built in phase 1, shared by every pass."""

    files: List[SourceFile] = field(default_factory=list)
    jit_entries: List[JitEntry] = field(default_factory=list)
    # bare entry name -> donated positional indices (non-empty only)
    donated: Dict[str, Tuple[int, ...]] = field(default_factory=dict)
    # bare names of ALL jit entries (hostsync taint sources)
    jit_names: frozenset = frozenset()


def dotted_name(node: ast.AST) -> Optional[str]:
    """'a.b.c' for a Name/Attribute chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def local_entry_aliases(scope: ast.AST, entries) -> Dict[str, set]:
    """Local names that may hold one of `entries` (bare names of jit/donated
    entry points): `f = entry`, `f = entry if c else other`, `f = a or b`.
    Returns alias name -> set of matched entry names. Shared by the donation
    pass (poisons alias-call arguments) and the hostsync pass (alias calls
    seed taint) so the recognized alias shapes can't drift apart."""
    aliases: Dict[str, set] = {}

    def entry_names(node: ast.AST) -> set:
        out: set = set()
        if isinstance(node, ast.IfExp):
            out |= entry_names(node.body) | entry_names(node.orelse)
        elif isinstance(node, ast.BoolOp):
            for v in node.values:
                out |= entry_names(v)
        else:
            name = dotted_name(node)
            if name is not None:
                bare = name.rsplit(".", 1)[-1]
                if bare in entries:
                    out.add(bare)
        return out

    for node in ast.walk(scope):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            tgt = node.targets[0]
            if isinstance(tgt, ast.Name):
                found = entry_names(node.value)
                if found:
                    aliases[tgt.id] = found
    return aliases


def _comment_tokens(text: str) -> List[Tuple[int, str]]:
    """(line, comment text) for every REAL comment token — waiver/pragma
    syntax quoted inside docstrings or message strings must not count as
    a declaration (the stale-waiver detector would otherwise chase its
    own documentation)."""
    import io
    import tokenize

    out: List[Tuple[int, str]] = []
    try:
        for tok in tokenize.generate_tokens(io.StringIO(text).readline):
            if tok.type == tokenize.COMMENT:
                out.append((tok.start[0], tok.string))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        # Unterminated constructs: ast.parse will report the real error.
        pass
    return out


def _scan_waivers(text: str) -> Dict[int, List[Tuple[str, str]]]:
    out: Dict[int, List[Tuple[str, str]]] = {}
    for line_no, comment in _comment_tokens(text):
        for m in _WAIVER_RE.finditer(comment):
            out.setdefault(line_no, []).append((m.group(1), m.group(2)))
    return out


def _scan_pragmas(text: str) -> frozenset:
    found = set()
    for _, comment in _comment_tokens(text):
        for m in _PRAGMA_RE.finditer(comment):
            found.add(m.group(1))
    return frozenset(found)


def load_file(abspath: str, root: str) -> SourceFile:
    with open(abspath, encoding="utf-8") as fh:
        text = fh.read()
    rel = os.path.relpath(abspath, root).replace(os.sep, "/")
    lines = text.splitlines()
    return SourceFile(
        path=rel,
        abspath=abspath,
        text=text,
        lines=lines,
        tree=ast.parse(text, filename=rel),
        waivers=_scan_waivers(text),
        pragmas=_scan_pragmas(text),
    )


def collect_files(
    paths: Sequence[str], root: str, exclude: Sequence[str] = DEFAULT_EXCLUDE
) -> List[SourceFile]:
    out: List[Tuple[str, bool]] = []  # (abspath, from directory walk)
    seen = set()
    for p in paths:
        ap = os.path.abspath(os.path.join(root, p) if not os.path.isabs(p) else p)
        if os.path.isdir(ap):
            for dirpath, dirnames, filenames in os.walk(ap):
                dirnames[:] = [d for d in dirnames if d != "__pycache__"]
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        out.append((os.path.join(dirpath, fn), True))
        elif ap.endswith(".py"):
            # explicitly-named files always lint (that's how the self-test
            # fixtures are invoked); excludes only prune directory walks
            out.append((ap, False))
    files: List[SourceFile] = []
    for ap, walked in out:
        rel = os.path.relpath(ap, root).replace(os.sep, "/")
        if ap in seen or (walked and any(rel.startswith(e) for e in exclude)):
            continue
        seen.add(ap)
        files.append(load_file(ap, root))
    return files


def is_hot(sf: SourceFile) -> bool:
    return "hot-path" in sf.pragmas or any(
        sf.path.startswith(m) if m.endswith("/") else sf.path == m
        for m in HOT_MODULES
    )


def is_sim_path(sf: SourceFile) -> bool:
    return "sim-path" in sf.pragmas or any(
        sf.path.startswith(m) for m in SIM_MODULES
    )


# Modules owning threads that share mutable attributes with the engine
# thread — the feederlock pass patrols them.
THREADED_MODULES = ("kubernetriks_tpu/batched/stream.py",)


def is_threaded(sf: SourceFile) -> bool:
    return "threaded" in sf.pragmas or sf.path in THREADED_MODULES


# --- phase 1: jit-entry and module-constant tables ---------------------------


def _const_str_tuple(node: ast.AST, consts: Dict[str, Tuple[str, ...]]):
    """Resolve an expression to a tuple of strings: literal tuples, names of
    module-level string-tuple constants, and + concatenations of those."""
    if isinstance(node, ast.Tuple):
        vals = []
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                vals.append(elt.value)
            else:
                return None
        return tuple(vals)
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    if isinstance(node, ast.Name):
        return consts.get(node.id)
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        left = _const_str_tuple(node.left, consts)
        right = _const_str_tuple(node.right, consts)
        if left is not None and right is not None:
            return left + right
    return None


def _const_int_tuple(node: ast.AST) -> Tuple[int, ...]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, ast.Tuple):
        out = []
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, int):
                out.append(elt.value)
            else:
                return ()
        return tuple(out)
    return ()


def _is_jax_jit(node: ast.AST) -> bool:
    return dotted_name(node) in ("jax.jit", "jit")


def _is_partial(node: ast.AST) -> bool:
    return dotted_name(node) in ("partial", "functools.partial")


def _jit_kwargs(call: ast.Call) -> Optional[Dict[str, ast.AST]]:
    """kwargs of a jax.jit(...) or partial(jax.jit, ...) call, else None."""
    if _is_jax_jit(call.func):
        return {kw.arg: kw.value for kw in call.keywords if kw.arg}
    if (
        _is_partial(call.func)
        and call.args
        and _is_jax_jit(call.args[0])
    ):
        return {kw.arg: kw.value for kw in call.keywords if kw.arg}
    return None


def _module_functions(tree: ast.AST) -> Dict[str, ast.FunctionDef]:
    return {
        n.name: n
        for n in ast.walk(tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    }


def _func_params(fn: ast.FunctionDef) -> Tuple[Tuple[str, ...], bool]:
    a = fn.args
    names = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
    return tuple(names), a.kwarg is not None


def build_context(files: List[SourceFile]) -> LintContext:
    ctx = LintContext(files=files)
    # Pass A: module-level string-tuple constants, per file AND pooled
    # package-wide so imported constants resolve (`from ..step import
    # _STEP_STATICS`); a name defined differently in two modules is
    # ambiguous and dropped from the pool.
    per_file_consts: Dict[str, Dict[str, Tuple[str, ...]]] = {}
    global_consts: Dict[str, Tuple[str, ...]] = {}
    # Two rounds so a constant built from an IMPORTED constant
    # (`_FUSED_STATICS = _STEP_STATICS + ("W",)`) resolves once the import's
    # definition entered the pool in round one.
    for _ in range(2):
        ambiguous: set = set()
        for sf in files:
            consts: Dict[str, Tuple[str, ...]] = dict(global_consts)
            local: Dict[str, Tuple[str, ...]] = {}
            for node in sf.tree.body if isinstance(sf.tree, ast.Module) else []:
                if (
                    isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                ):
                    val = _const_str_tuple(node.value, consts)
                    if val is not None:
                        name = node.targets[0].id
                        consts[name] = val
                        local[name] = val
                        if name in global_consts and global_consts[name] != val:
                            ambiguous.add(name)
                        else:
                            global_consts[name] = val
            per_file_consts[sf.path] = local
        for name in ambiguous:
            global_consts.pop(name, None)
    for sf in files:
        consts = dict(global_consts)
        consts.update(per_file_consts[sf.path])
        funcs = _module_functions(sf.tree)

        def add_entry(name, line, kwargs, wrapped_name):
            static_node = kwargs.get("static_argnames")
            statics = (
                _const_str_tuple(static_node, consts)
                if static_node is not None
                else ()
            )
            donate = _const_int_tuple(kwargs["donate_argnums"]) if (
                "donate_argnums" in kwargs
            ) else ()
            params = None
            has_varkw = False
            fn = funcs.get(wrapped_name) if wrapped_name else None
            if fn is not None:
                params, has_varkw = _func_params(fn)
            ctx.jit_entries.append(
                JitEntry(
                    name=name,
                    path=sf.path,
                    line=line,
                    static_argnames=statics,
                    static_resolved=statics is not None,
                    donate_argnums=donate,
                    params=params,
                    has_varkw=has_varkw,
                )
            )
            if donate:
                ctx.donated[name] = donate

        for node in ast.walk(sf.tree):
            # @jax.jit / @partial(jax.jit, ...) decorators
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    if isinstance(dec, ast.Call):
                        kwargs = _jit_kwargs(dec)
                        if kwargs is not None:
                            add_entry(node.name, node.lineno, kwargs, node.name)
                    elif _is_jax_jit(dec):
                        add_entry(node.name, node.lineno, {}, node.name)
            # name = jax.jit(fn, ...) / name = partial(jax.jit, ...)(fn)
            elif isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call
            ):
                if len(node.targets) != 1 or not isinstance(
                    node.targets[0], ast.Name
                ):
                    continue
                tgt = node.targets[0].id
                call = node.value
                kwargs = _jit_kwargs(call)
                if kwargs is not None and not _is_partial(call.func):
                    # jax.jit(fn, ...)
                    wrapped = (
                        call.args[0].id
                        if call.args and isinstance(call.args[0], ast.Name)
                        else None
                    )
                    add_entry(tgt, node.lineno, kwargs, wrapped)
                elif isinstance(call.func, ast.Call):
                    # partial(jax.jit, ...)(fn)
                    inner_kwargs = _jit_kwargs(call.func)
                    if inner_kwargs is not None:
                        wrapped = (
                            call.args[0].id
                            if call.args
                            and isinstance(call.args[0], ast.Name)
                            else None
                        )
                        add_entry(tgt, node.lineno, inner_kwargs, wrapped)
    ctx.jit_names = frozenset(e.name for e in ctx.jit_entries)
    return ctx


# --- driver ------------------------------------------------------------------


@dataclass
class LintReport:
    """run_lint_report's full result: violations plus the stale-waiver
    inventory (only meaningful when every pass ran — a waiver for an
    unselected pass is never stale)."""

    violations: List[Violation]
    stale_waivers: List[StaleWaiver]
    root: str = ""


def _run_passes(
    paths: Sequence[str],
    root: str,
    passes: Optional[Sequence[str]],
    exclude: Sequence[str],
) -> Tuple[List[Violation], LintContext, Tuple[str, ...]]:
    from kubernetriks_tpu.lint import (
        donation,
        envflags,
        feederlock,
        hostsync,
        jitstatic,
        prng,
        scenariotrace,
        shapecontract,
        stateleaf,
    )

    selected = tuple(passes) if passes else PASS_IDS
    unknown = set(selected) - set(PASS_IDS)
    if unknown:
        raise ValueError(f"unknown lint pass(es): {sorted(unknown)}")
    files = collect_files(paths, root, exclude=exclude)
    ctx = build_context(files)
    checkers = {
        "donation": donation.check,
        "hostsync": hostsync.check,
        "jitstatic": jitstatic.check,
        "prng": prng.check,
        "envflags": envflags.check,
        "stateleaf": stateleaf.check,
        "scenariotrace": scenariotrace.check,
        "shapecontract": shapecontract.check,
        "feederlock": feederlock.check,
    }
    violations: List[Violation] = []
    seen = set()
    for pass_id in selected:
        for v in checkers[pass_id](ctx):
            # loop bodies are walked twice (donation) — dedupe exact repeats
            if v not in seen:
                seen.add(v)
                violations.append(v)
    violations.sort(key=lambda v: (v.path, v.line, v.pass_id))
    return violations, ctx, selected


def run_lint(
    paths: Sequence[str],
    root: str,
    passes: Optional[Sequence[str]] = None,
    exclude: Sequence[str] = DEFAULT_EXCLUDE,
) -> List[Violation]:
    return _run_passes(paths, root, passes, exclude)[0]


def find_stale_waivers(
    ctx: LintContext, selected: Sequence[str]
) -> List[StaleWaiver]:
    """Declared waivers that suppressed nothing in this run. Only waivers
    whose tag maps to a SELECTED pass are judged (a tag for a pass that
    did not run cannot be proven stale); unknown tags are always
    reported — a typo'd tag (`synk-ok`) suppresses nothing anywhere."""
    selected_tags = {WAIVER_TAGS[p] for p in selected}
    out: List[StaleWaiver] = []
    for sf in ctx.files:
        for line, entries in sorted(sf.waivers.items()):
            for tag, reason in entries:
                if tag not in TAG_TO_PASS:
                    out.append(
                        StaleWaiver(
                            sf.path,
                            line,
                            tag,
                            reason,
                            f"unknown waiver tag {tag!r} — known tags: "
                            f"{', '.join(sorted(TAG_TO_PASS))}",
                        )
                    )
                    continue
                if tag not in selected_tags:
                    continue
                if (line, tag) not in sf.used_waivers:
                    out.append(
                        StaleWaiver(
                            sf.path,
                            line,
                            tag,
                            reason,
                            f"stale waiver: {tag}-ok({reason}) suppresses "
                            f"nothing — the line/def no longer triggers the "
                            f"{TAG_TO_PASS[tag]} pass; remove the waiver",
                        )
                    )
    return out


def run_lint_report(
    paths: Sequence[str],
    root: str,
    passes: Optional[Sequence[str]] = None,
    exclude: Sequence[str] = DEFAULT_EXCLUDE,
) -> LintReport:
    """run_lint plus the stale-waiver inventory (the --json/CI entry)."""
    violations, ctx, selected = _run_passes(paths, root, passes, exclude)
    return LintReport(
        violations=violations,
        stale_waivers=find_stale_waivers(ctx, selected),
        root=root,
    )


def list_waivers(paths: Sequence[str], root: str) -> List[str]:
    """Greppable sync-budget listing: every waiver in scope with its reason."""
    out = []
    for sf in collect_files(paths, root):
        for line, entries in sorted(sf.waivers.items()):
            for tag, reason in entries:
                out.append(f"{sf.path}:{line}: {tag}-ok({reason})")
    return out
