"""PRNG-hygiene pass: simulation-path randomness routes through chaos.py.

Scalar/batched bit-identity (the framework's core exactness promise, pinned
by the equivalence suites) holds because every random draw on the
simulation path flows through the counter-based threefry keying in
`chaos.py` — keys are pure functions of (seed, stream, cluster, object,
counter), so the scalar oracle and the batched engine draw identical
numbers in any order. An ad-hoc `jax.random.PRNGKey` / `np.random` /
stdlib-`random` draw in a simulation-path module breaks that silently.

Within simulation-path modules (lint.SIM_MODULES, or a
`# ktpu: sim-path` pragma), flags:

- any `jax.random.*` attribute use (PRNGKey, split, uniform, ...);
- any `np.random.*` / `numpy.random.*` use;
- stdlib `random` usage (`import random`, `random.*`, `from random
  import ...`);
- `from jax import random` / `from jax.random import ...` and
  `from numpy.random import ...` imports.

chaos.py itself (the key constructor) lives at the package root, outside
the simulation-path module set. Waive deliberate uses with
`# ktpu: prng-ok(<reason>)` — e.g. the scalar kernel's seeded
reference-port RNG.
"""

from __future__ import annotations

import ast
from typing import List

from kubernetriks_tpu.lint import (
    LintContext,
    SourceFile,
    Violation,
    dotted_name,
    is_sim_path,
)

PASS_ID = "prng"

_FORBIDDEN_PREFIXES = ("jax.random.", "np.random.", "numpy.random.", "random.")
_FORBIDDEN_IMPORT_MODULES = ("jax.random", "numpy.random", "random")


def _flag(sf: SourceFile, node: ast.AST, what: str, out: List[Violation]):
    if sf.waived(node.lineno, PASS_ID):
        return
    out.append(
        Violation(
            sf.path,
            node.lineno,
            PASS_ID,
            f"{what} in a simulation-path module: route all draws through "
            "the counter-based key constructors in chaos.py "
            "(object_uniforms / pod_attempt_uniforms) or scalar/batched "
            "bit-identity breaks; waive with # ktpu: prng-ok(reason)",
        )
    )


def check(ctx: LintContext) -> List[Violation]:
    violations: List[Violation] = []
    for sf in ctx.files:
        if not is_sim_path(sf):
            continue
        # `import random` presence makes bare `random.` stdlib usage — track
        # whether the name is bound to something else (e.g. a local module).
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.name in _FORBIDDEN_IMPORT_MODULES:
                        _flag(sf, node, f"import of {alias.name!r}", violations)
            elif isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                if mod in _FORBIDDEN_IMPORT_MODULES:
                    _flag(
                        sf,
                        node,
                        f"import from {mod!r} "
                        f"({', '.join(a.name for a in node.names)})",
                        violations,
                    )
                elif mod == "jax" and any(
                    a.name == "random" for a in node.names
                ):
                    _flag(sf, node, "import of jax.random", violations)
                elif mod == "numpy" and any(
                    a.name == "random" for a in node.names
                ):
                    _flag(sf, node, "import of numpy.random", violations)
            elif isinstance(node, ast.Attribute):
                path = dotted_name(node)
                if path is not None and any(
                    path.startswith(p) or path == p.rstrip(".")
                    for p in _FORBIDDEN_PREFIXES
                ):
                    _flag(sf, node, f"use of {path}", violations)
    return violations
